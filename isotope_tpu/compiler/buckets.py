"""Level-bucket planning for the scan executor.

The engine's original data plane Python-unrolls one tensor-program body
per depth level, so trace/HLO size grows with depth (and with every
retry-widened level).  The bucketed executor instead packs *consecutive*
depth levels whose shapes are close into one **bucket**: each level's
tensors are padded up to the bucket's bounds and the per-level sweep
body is traced ONCE as a ``lax.scan`` over the stacked constants — the
GSPMD move (one small reusable program over padded static shapes,
arxiv 2105.04663) applied to the depth axis.

Planning is a pure host-side function over light per-level shape
metadata.  A level is *scan-eligible* when it has calls and children and
would not use the sparse call-slot encoding (sparse levels keep their
specialized unrolled path — it exists precisely because the dense grid
is pathological there).  Consecutive eligible levels are grouped
greedily while the padded element count stays within ``waste`` times the
real element count, so chains and plateau-shaped multitier graphs
collapse into a handful of buckets while geometric trees (3x size per
level) naturally stay unrolled.
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple, Union

import numpy as np

from isotope_tpu import telemetry

#: padded-elements / real-elements budget for one bucket (see plan_segments)
DEFAULT_WASTE = 1.6

#: a bucket shorter than this runs unrolled (no padding, no scan overhead)
MIN_SCAN_LEVELS = 2

#: per-segment dispatch/trace overhead in element units for the
#: critical-path schedule (see plan_segments).  The executor's segments
#: run strictly sequentially (level d+1 feeds level d), so the schedule's
#: critical path is the SUM over segments of (dispatch overhead +
#: element work); the overhead constant is set high enough that merging
#: consecutive levels is preferred whenever the waste budget allows it —
#: the cost model's "one dispatch saved beats moderate padding" regime
#: (analysis/costmodel.py consumes the same cost via segment_cp_cost).
#: Calibrating it against a real-TPU capture is a ROADMAP follow-up.
SEGMENT_OVERHEAD_ELEMS = 1 << 24

#: default bound on a dense tile's step width (plan_tiles): hops whose
#: script is wider stay on the residual sparse encoding.  32 * 8-row
#: fan-out bins keep tiles VPU-shaped; retune on a real capture.
DEFAULT_TILE_PMAX = 64

#: critical-path DP lookback cap: buckets longer than this are not
#: considered (keeps planning O(levels * cap); a >64-level scan body
#: already amortizes its dispatch overhead to nothing)
MAX_BUCKET_LEVELS = 64


@dataclasses.dataclass(frozen=True)
class LevelShape:
    """Shape metadata of one depth level (host-side planning input)."""

    size: int       # hops at this level
    pmax: int       # widest script among the level's services
    children: int   # hops at the next level spawned here
    calls: int      # call sites (retry fans share one site)
    attempts: int   # max retry attempts of any call
    sparse: bool    # the engine would use a non-dense (sparse/tiled)
    offset: int     # start of the level's slice in BFS hop order
    # dense-blocked tiling of a sparse level: ((size, width), ...) per
    # tile plus the residual sparse slot count — reporting/cost only
    # (tiled levels execute as one unrolled segment)
    tiles: Optional[Tuple[Tuple[int, int], ...]] = None
    residual_slots: int = 0

    @property
    def leaf(self) -> bool:
        return self.calls == 0 or self.children == 0


@dataclasses.dataclass(frozen=True)
class ScanBucketPlan:
    """One scan segment: levels ``d0..d1`` padded to common bounds.

    ``bound_hops`` covers every level size in ``d0..d1`` AND the size of
    level ``d1+1`` — the scan carry holds the *child* level's outputs,
    so the deepest child must fit the carry width too.
    """

    d0: int
    d1: int
    bound_hops: int      # B — hop/children axis bound
    bound_steps: int     # P — step axis bound
    bound_calls: int     # K
    bound_attempts: int  # A

    @property
    def num_levels(self) -> int:
        return self.d1 - self.d0 + 1

    def signature(self) -> tuple:
        return ("scan", self.d0, self.d1, self.bound_hops,
                self.bound_steps, self.bound_calls, self.bound_attempts)


@dataclasses.dataclass(frozen=True)
class UnrolledLevelPlan:
    """One unrolled segment: a single level traced with static shapes."""

    d: int

    def signature(self) -> tuple:
        return ("unrolled", self.d)


Segment = Union[ScanBucketPlan, UnrolledLevelPlan]


def _bucket_cost(shapes: Sequence[LevelShape], bounds: Tuple[int, int, int,
                                                             int]) -> int:
    b, p, k, a = bounds
    return len(shapes) * (b * p + 3 * b + 2 * k * a)


def _real_cost(shapes: Sequence[LevelShape]) -> int:
    return sum(
        s.size * s.pmax + 3 * s.children + 2 * s.calls * s.attempts
        for s in shapes
    )


def _bounds(levels: Sequence[LevelShape], child_size: int
            ) -> Tuple[int, int, int, int]:
    return (
        max([child_size] + [s.size for s in levels]),
        max(s.pmax for s in levels),
        max(s.calls for s in levels),
        max(s.attempts for s in levels),
    )


def segment_cp_cost(shapes: Sequence[LevelShape], seg: Segment) -> int:
    """Critical-path cost (element units) of one schedule segment.

    Segments execute strictly sequentially — level d+1's outputs feed
    level d's sweep — so the schedule's critical path is the SUM of
    per-segment costs: a fixed dispatch/trace overhead plus the padded
    element work the segment touches.  This is the cost function BOTH
    the planner's critical-path schedule (plan_segments) and the vet
    cost model's schedule report (analysis/costmodel.py) use.
    """
    if isinstance(seg, ScanBucketPlan):
        members = shapes[seg.d0:seg.d1 + 1]
        bounds = (seg.bound_hops, seg.bound_steps, seg.bound_calls,
                  seg.bound_attempts)
        return SEGMENT_OVERHEAD_ELEMS + _bucket_cost(members, bounds)
    s = shapes[seg.d]
    if s.tiles is not None:
        elems = sum(t_size * t_w for t_size, t_w in s.tiles)
        elems += s.residual_slots + 3 * s.children + 2 * s.calls * s.attempts
        return SEGMENT_OVERHEAD_ELEMS + elems
    return SEGMENT_OVERHEAD_ELEMS + _real_cost([s])


def plan_cp_cost(shapes: Sequence[LevelShape],
                 segs: Sequence[Segment]) -> int:
    """Total critical-path cost of one plan (element units)."""
    return sum(segment_cp_cost(shapes, s) for s in segs)


def _partition_run(
    shapes: Sequence[LevelShape],
    i: int,
    j: int,
    waste: float,
    schedule: str,
) -> List[Segment]:
    """Partition one maximal scan-eligible run ``[i..j]`` into segments.

    ``critical-path`` solves the optimal partition by DP over the run,
    minimizing the summed per-segment critical-path cost
    (:func:`segment_cp_cost`); the waste budget stays a HARD constraint
    on every bucket, so the knob keeps its meaning.  ``greedy`` is the
    historical left-to-right maximal extension (kept for comparison /
    fallback).
    """
    n = len(shapes)

    def bucket_of(a: int, b: int) -> Optional[ScanBucketPlan]:
        run = shapes[a:b + 1]
        child_size = shapes[b + 1].size if b + 1 < n else 0
        bounds = _bounds(run, child_size)
        if _bucket_cost(run, bounds) > waste * _real_cost(run):
            return None
        bb, p, k, a_ = bounds
        return ScanBucketPlan(a, b, bb, p, k, a_)

    if schedule == "greedy":
        segs: List[Segment] = []
        a = i
        while a <= j:
            b = a
            while b + 1 <= j and bucket_of(a, b + 1) is not None:
                b += 1
            if b - a + 1 >= MIN_SCAN_LEVELS:
                segs.append(bucket_of(a, b))
                a = b + 1
            else:
                segs.append(UnrolledLevelPlan(a))
                a += 1
        return segs

    # critical-path DP: best[e] = (cost, segments) covering run[i..e].
    # Bucket bounds are maintained INCREMENTALLY while the candidate
    # start walks left (they are running maxima), so each (a, e) pair
    # costs O(1); the lookback is capped at MAX_BUCKET_LEVELS.
    INF = float("inf")
    best_cost = [INF] * (j - i + 2)
    best_prev: List[Optional[Tuple[int, Segment]]] = [None] * (j - i + 2)
    best_cost[0] = 0.0
    for e in range(i, j + 1):
        idx = e - i + 1
        # unrolled single level
        seg: Segment = UnrolledLevelPlan(e)
        c = best_cost[idx - 1] + segment_cp_cost(shapes, seg)
        if c < best_cost[idx]:
            best_cost[idx] = c
            best_prev[idx] = (idx - 1, seg)
        # buckets ending at e (length >= MIN_SCAN_LEVELS)
        child_size = shapes[e + 1].size if e + 1 < n else 0
        bb, bp, bk, ba = child_size, 1, 0, 1
        real = 0
        for a in range(e, max(i, e - MAX_BUCKET_LEVELS + 1) - 1, -1):
            s = shapes[a]
            bb = max(bb, s.size)
            bp = max(bp, s.pmax)
            bk = max(bk, s.calls)
            ba = max(ba, s.attempts)
            real += (
                s.size * s.pmax + 3 * s.children
                + 2 * s.calls * s.attempts
            )
            length = e - a + 1
            if length < MIN_SCAN_LEVELS:
                continue
            padded = length * (bb * bp + 3 * bb + 2 * bk * ba)
            if padded > waste * real:
                # infeasible at THIS span; wider spans can re-enter
                # feasibility (bounds are maxima), so keep walking
                continue
            c = best_cost[a - i] + SEGMENT_OVERHEAD_ELEMS + padded
            if c < best_cost[idx]:
                best_cost[idx] = c
                best_prev[idx] = (
                    a - i, ScanBucketPlan(a, e, bb, bp, bk, ba)
                )
    # walk back
    out: List[Segment] = []
    idx = j - i + 1
    while idx > 0:
        prev, seg = best_prev[idx]
        out.append(seg)
        idx = prev
    out.reverse()
    return out


def plan_segments(
    shapes: Sequence[LevelShape],
    waste: float = DEFAULT_WASTE,
    enabled: bool = True,
    schedule: str = "critical-path",
) -> List[Segment]:
    """Partition the depth levels into scan buckets and unrolled islands.

    Levels are first split at the ineligible islands (leaves, sparse /
    tiled levels); each maximal eligible run is then partitioned by the
    selected ``schedule``:

    - ``"critical-path"`` (default): optimal DP over the run minimizing
      the summed per-segment critical-path cost
      (:func:`segment_cp_cost` — dispatch overhead + padded elements),
      the ordering/merging discipline of the static-schedule literature
      applied to the depth axis.  The ``waste`` budget stays a hard
      per-bucket constraint.
    - ``"greedy"``: the historical left-to-right maximal extension.

    Runs shorter than ``MIN_SCAN_LEVELS`` fall back to unrolled
    segments either way, and results are bit-identical across plans
    (the executor contract — only wall-clock changes).

    ``enabled`` carries only ``SimParams.bucketed_scan``: protected
    (policies/rollouts) Simulators plan buckets like any other since
    the retry-budget gate reached the scan body
    (sim/levelscan.SweepCtx.retry_coin) — the old
    ``and policies is None`` restriction is gone.
    """
    segs: List[Segment] = []
    n = len(shapes)
    i = 0
    while i < n:
        s = shapes[i]
        eligible = enabled and not s.leaf and not s.sparse
        if not eligible:
            segs.append(UnrolledLevelPlan(i))
            i += 1
            continue
        j = i
        while j + 1 < n and not (shapes[j + 1].leaf or shapes[j + 1].sparse):
            j += 1
        segs.extend(_partition_run(shapes, i, j, waste, schedule))
        i = j + 1
    _record_plan(shapes, segs)
    return segs


def plan_signature(segs: Sequence[Segment]) -> tuple:
    """Hashable shape signature of a plan — part of the AOT cache key."""
    return tuple(s.signature() for s in segs)


def schedule_table(shapes: Sequence[LevelShape],
                   segs: Sequence[Segment]) -> List[dict]:
    """The chosen schedule as cost-ranked rows (vet ``--json`` surface).

    One row per executor segment with its critical-path cost
    (:func:`segment_cp_cost`) and share of the plan's total; rows are
    ordered by DESCENDING cost — the segments that own the critical
    path come first — while ``position`` records the execution order.
    """
    total = max(plan_cp_cost(shapes, segs), 1)
    rows = []
    for pos, seg in enumerate(segs):
        if isinstance(seg, ScanBucketPlan):
            kind = "scan"
            d0, d1 = seg.d0, seg.d1
        else:
            s = shapes[seg.d]
            if s.tiles is not None:
                kind = "tiled"
            elif s.sparse:
                kind = "sparse"
            elif s.leaf:
                kind = "leaf"
            else:
                kind = "unrolled"
            d0 = d1 = seg.d
        cost = segment_cp_cost(shapes, seg)
        rows.append({
            "position": pos,
            "kind": kind,
            "d0": d0,
            "d1": d1,
            "cp_cost_elems": int(cost),
            "cp_share": cost / total,
        })
    rows.sort(key=lambda r: (-r["cp_cost_elems"], r["position"]))
    return rows


# ---------------------------------------------------------------------------
# dense-blocked tiling of sparse levels


@dataclasses.dataclass(frozen=True)
class TilePlan:
    """Dense-blocked partition of one skewed level's hops.

    ``tiles`` holds (width, hop-index-array) bins — each becomes a
    dense (size x width) sub-grid padded to the bin's widest script —
    and ``residual`` the hop indices that stay on the true sparse
    call-slot encoding (scripts wider than the tile cap).
    """

    tiles: Tuple[Tuple[int, np.ndarray], ...]
    residual: np.ndarray

    @property
    def tiled_elems(self) -> int:
        return int(sum(w * len(idx) for w, idx in self.tiles))

    def shapes(self) -> Tuple[Tuple[int, int], ...]:
        return tuple((len(idx), w) for w, idx in self.tiles)


def plan_tiles(
    widths: np.ndarray,
    cap: int = DEFAULT_TILE_PMAX,
    waste: float = DEFAULT_WASTE,
) -> TilePlan:
    """Bin one level's hops into fixed-width dense tiles.

    ``widths`` is the per-hop real script width (number of occupied
    step columns).  Hops wider than ``cap`` go to the residual sparse
    encoding.  The rest are sorted by width and greedily grouped into
    tiles: a bin grows while padding every member to the running widest
    script stays within ``waste`` x the real element count — the same
    budget discipline the level-bucket planner applies on the depth
    axis, here applied within one level's fan-out classes.
    """
    widths = np.asarray(widths, np.int64)
    idx = np.arange(len(widths))
    residual = idx[widths > cap]
    tileable = idx[widths <= cap]
    order = tileable[np.argsort(widths[tileable], kind="stable")]
    tiles: List[Tuple[int, np.ndarray]] = []
    start = 0
    while start < len(order):
        end = start + 1
        real = max(int(widths[order[start]]), 1)
        wmax = max(int(widths[order[start]]), 1)
        while end < len(order):
            w = max(int(widths[order[end]]), 1)
            cand_w = max(wmax, w)
            cand_real = real + w
            if cand_w * (end - start + 1) > waste * cand_real:
                break
            wmax, real = cand_w, cand_real
            end += 1
        tiles.append((wmax, np.sort(order[start:end])))
        start = end
    return TilePlan(tiles=tuple(tiles), residual=np.sort(residual))


def level_encoding(
    size: int,
    pmax: int,
    n_slots: int,
    widths: np.ndarray,
    *,
    sparse_level_elems: int,
    tiling: bool = True,
    tile_pmax: int = DEFAULT_TILE_PMAX,
    waste: float = DEFAULT_WASTE,
) -> Tuple[str, Optional[TilePlan]]:
    """Decide one call-bearing level's step encoding.

    Returns ``("dense" | "tiled" | "sparse", tile_plan)`` — the single
    decision point shared by the engine's lowering and the vet linter,
    so the static analysis always reports the executor's real choice.
    A level leaves the dense grid when the grid is > 4x its real call
    slots (or past ``sparse_level_elems``); it then tiles when the
    dense-blocked plan halves the grid, else keeps the true sparse
    encoding (tiny fully-skewed levels, e.g. one hub hop).
    """
    dense_elems = size * pmax
    if dense_elems <= max(4 * n_slots, sparse_level_elems):
        return "dense", None
    if not tiling:
        return "sparse", None
    plan = plan_tiles(widths, cap=tile_pmax, waste=waste)
    # residual hops keep one slot per call-bearing step; approximate
    # with their width sum for the decision (exact slots need call
    # tables the caller may not have at hand)
    res_elems = int(np.asarray(widths)[plan.residual].sum())
    if plan.tiled_elems + res_elems <= dense_elems // 2 and plan.tiles:
        return "tiled", plan
    return "sparse", None


def plan_stats(shapes: Sequence[LevelShape],
               segs: Sequence[Segment]) -> dict:
    """Padding/coverage accounting of one plan (telemetry + tests).

    ``padded_elems`` / ``real_elems`` count only the SCAN buckets —
    unrolled islands pay no padding — so ``padding_waste_fraction`` is
    the fraction of bucket element-slots that are pure padding.
    """
    buckets_list = [s for s in segs if isinstance(s, ScanBucketPlan)]
    padded = real = 0
    per_bucket = []
    for b in buckets_list:
        members = shapes[b.d0:b.d1 + 1]
        bounds = (b.bound_hops, b.bound_steps, b.bound_calls,
                  b.bound_attempts)
        p = _bucket_cost(members, bounds)
        r = _real_cost(members)
        padded += p
        real += r
        per_bucket.append(
            {"d0": b.d0, "d1": b.d1, "levels": b.num_levels,
             "padded_elems": p, "real_elems": r,
             "padded_rows": b.num_levels * b.bound_hops
             - sum(s.size for s in members)}
        )
    return {
        "num_segments": len(segs),
        "num_buckets": len(buckets_list),
        "levels_bucketed": sum(b.num_levels for b in buckets_list),
        "levels_unrolled": len(segs) - len(buckets_list),
        "padded_elems": padded,
        "real_elems": real,
        "padding_waste_fraction": (
            (padded - real) / padded if padded else 0.0
        ),
        "buckets": per_bucket,
    }


def _record_plan(shapes: Sequence[LevelShape],
                 segs: Sequence[Segment]) -> None:
    """Fold one plan's stats into the engine telemetry registry."""
    st = plan_stats(shapes, segs)
    telemetry.counter_inc("bucket_plans")
    telemetry.counter_inc("buckets_formed", st["num_buckets"])
    telemetry.counter_inc("levels_bucketed", st["levels_bucketed"])
    telemetry.counter_inc("levels_unrolled", st["levels_unrolled"])
    telemetry.counter_inc("bucket_padded_elems", st["padded_elems"])
    telemetry.counter_inc("bucket_real_elems", st["real_elems"])
    telemetry.counter_inc(
        "bucket_padded_rows",
        sum(b["padded_rows"] for b in st["buckets"]),
    )
    telemetry.gauge_set(
        "bucket_padding_waste_fraction", st["padding_waste_fraction"]
    )
