"""AOT executable cache + persistent XLA compilation cache wiring.

Two layers keep repeated runs of the same topology family from paying
XLA again:

- **In-process executable cache** (:data:`executable_cache`): jitted
  entry points are stored process-wide, keyed by the engine's *shape
  signature* — the bucket plan bounds, request-block shape, load kind,
  feature flags, and a content digest of every constant the traced
  program closes over.  Re-instantiating a ``Simulator`` for the same
  compiled topology (same signature) reuses the already-traced — and,
  after first execution, already-compiled — function instead of
  retracing.  The digest makes sharing *sound*: two engines share an
  executable only when every baked constant is byte-identical.
- **Persistent on-disk cache** (:func:`enable_persistent_cache`): JAX's
  compilation cache, keyed by XLA on the optimized HLO, so separate
  *processes* (bench.py's per-case subprocesses, repeated CLI runs of
  one suite) skip the XLA backend compile entirely.  The directory
  comes from the ``ISOTOPE_COMPILE_CACHE`` env knob or an explicit
  path; unset means disabled.
"""
from __future__ import annotations

import hashlib
import logging
import os
from collections import OrderedDict
from typing import Callable, List, Optional

from isotope_tpu import telemetry

logger = logging.getLogger(__name__)

#: env knob for the persistent compilation cache directory; the values
#: "", "0", "off" and "none" (case-insensitive) disable it explicitly.
ENV_CACHE_DIR = "ISOTOPE_COMPILE_CACHE"

#: sidecar recording each cache entry's content digest (scan_cache_dir)
DIGEST_SIDECAR = ".isotope-digests.json"
#: subdirectory corrupted entries are moved into (never deleted: a
#: quarantined entry is evidence, and XLA just retraces without it)
QUARANTINE_DIR = "quarantine"

_persistent_dir: Optional[str] = None


def scan_cache_dir(path: str) -> dict:
    """Integrity-scan a persistent cache dir, quarantining bad entries.

    A corrupted entry (truncated write on a killed run, bit rot, a
    concurrent writer) used to surface as an unpickle/deserialize crash
    *inside* XLA's cache read — killing the run that was supposed to be
    saved compile time.  This scan runs at :func:`enable_persistent_cache`
    time: every entry file is digested; an EMPTY file, an unreadable
    file, or one whose digest no longer matches the recorded sidecar
    digest is moved to ``<dir>/quarantine/`` (counter
    ``compile_cache_quarantined``) so XLA simply misses and retraces.
    Fresh entries get their digest recorded for the next scan.  Never
    raises — a broken cache must degrade to "no cache", not crash.
    """
    stats = {"checked": 0, "quarantined": [], "recorded": 0}
    try:
        import json
        import shutil

        sidecar = os.path.join(path, DIGEST_SIDECAR)
        digests = {}
        try:
            with open(sidecar) as f:
                digests = json.load(f)
            if not isinstance(digests, dict):
                digests = {}
        except (OSError, ValueError):
            digests = {}  # missing/corrupt sidecar: rebuild from scratch
        qdir = os.path.join(path, QUARANTINE_DIR)
        fresh = {}
        for name in sorted(os.listdir(path)):
            fpath = os.path.join(path, name)
            if (
                name == DIGEST_SIDECAR
                or name.startswith(".")
                or not os.path.isfile(fpath)
            ):
                continue
            stats["checked"] += 1
            digest = None
            try:
                with open(fpath, "rb") as f:
                    data = f.read()
                if data:
                    digest = hashlib.sha256(data).hexdigest()
            except OSError:
                digest = None
            bad = digest is None or (
                name in digests and digests[name] != digest
            )
            if bad:
                os.makedirs(qdir, exist_ok=True)
                try:
                    shutil.move(fpath, os.path.join(qdir, name))
                except OSError:  # pragma: no cover - best effort
                    try:
                        os.unlink(fpath)
                    except OSError:
                        continue
                stats["quarantined"].append(name)
                telemetry.counter_inc("compile_cache_quarantined")
                logger.warning(
                    "quarantined corrupted compile-cache entry %s "
                    "(%s) — it will be retraced", name,
                    "unreadable/empty" if digest is None
                    else "digest mismatch",
                )
            else:
                fresh[name] = digest
        stats["recorded"] = len(fresh)
        tmp = sidecar + ".tmp"
        with open(tmp, "w") as f:
            json.dump(fresh, f)
        os.replace(tmp, sidecar)
    except Exception:  # pragma: no cover - scan must never kill a run
        logger.warning("compile-cache scan failed", exc_info=True)
    return stats


def persistent_cache_dir() -> Optional[str]:
    """The currently wired persistent cache dir (None when disabled)."""
    return _persistent_dir


def enable_persistent_cache(path: Optional[str] = None) -> Optional[str]:
    """Point JAX's persistent compilation cache at ``path``.

    ``path=None`` reads ``$ISOTOPE_COMPILE_CACHE``; when that is unset
    (or explicitly off) this is a no-op returning ``None``.  Idempotent
    — safe to call from every entry point (bench, CLI, sharded runner).
    """
    global _persistent_dir
    if path is None:
        path = os.environ.get(ENV_CACHE_DIR)
    if not path or str(path).strip().lower() in ("0", "off", "none"):
        return None
    path = os.path.abspath(os.path.expanduser(str(path)))
    if _persistent_dir == path:
        return path
    # persistent-cache hit/miss counts come from jax's own monitoring
    # events — subscribe before anything compiles through the cache
    telemetry.install_jax_hooks()
    telemetry.counter_inc("persistent_cache_enables")
    import jax

    os.makedirs(path, exist_ok=True)
    # evict corrupted entries BEFORE jax reads any (a bad entry then
    # costs a retrace, never a crash)
    scan_cache_dir(path)
    jax.config.update("jax_compilation_cache_dir", path)
    # jax initializes its cache object lazily ONCE; re-pointing the dir
    # after something already compiled needs an explicit reset
    try:
        from jax.experimental.compilation_cache import (
            compilation_cache as _cc,
        )

        _cc.reset_cache()
    except Exception:  # pragma: no cover - cache not initialized yet
        pass
    # cache every entry: the sweep programs are exactly the long-compile
    # artifacts the cache exists for, and tiny entries are harmless
    for knob, val in (
        ("jax_persistent_cache_min_compile_time_secs", 0.0),
        ("jax_persistent_cache_min_entry_size_bytes", -1),
    ):
        try:
            jax.config.update(knob, val)
        except AttributeError:  # pragma: no cover - newer/older jax
            pass
    _persistent_dir = path
    return path


def array_digest(*chunks) -> str:
    """SHA-256 over a heterogeneous sequence of arrays / reprs.

    Used to fingerprint every constant a traced program bakes in:
    NumPy (or JAX) arrays hash their raw bytes + shape + dtype, and
    anything else hashes its ``repr``.  ``None`` entries are skipped.
    """
    import numpy as np

    h = hashlib.sha256()
    for c in chunks:
        if c is None:
            continue
        a = None
        if isinstance(c, np.ndarray):
            a = c
        elif hasattr(c, "__array__") and not isinstance(c, (str, bytes)):
            a = np.asarray(c)
        if a is not None:
            h.update(str(a.dtype).encode())
            h.update(str(a.shape).encode())
            h.update(np.ascontiguousarray(a).tobytes())
        else:
            h.update(repr(c).encode())
    return h.hexdigest()


class ExecutableCache:
    """Process-wide LRU of jitted entry points, keyed by shape signature.

    The stored value is the ``jax.jit``-wrapped callable; JAX's own jit
    cache then holds the compiled executable behind it, so a signature
    hit skips both retracing AND recompiling.

    Retention caveat: each entry's closure pins its builder Simulator's
    device constants until eviction, so ``max_entries`` bounds how many
    otherwise-dead engines a long multi-topology sweep keeps resident —
    sized for a sweep's load-shape grid over a few topologies, not a
    museum of every graph ever built.  Call :meth:`clear` to release
    everything (e.g. between unrelated experiments in one process).
    """

    def __init__(self, max_entries: int = 32):
        self.max_entries = max_entries
        self._fns: "OrderedDict[tuple, object]" = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    @staticmethod
    def key_digest(key: tuple) -> str:
        """Short stable digest of a cache key (log/stats identity)."""
        return hashlib.sha256(repr(key).encode()).hexdigest()[:12]

    def get_or_build(self, key: tuple, build: Callable[[], object]):
        if key in self._fns:
            self.hits += 1
            telemetry.counter_inc("executable_cache_hits")
            self._fns.move_to_end(key)
            return self._fns[key]
        self.misses += 1
        telemetry.counter_inc("executable_cache_misses")
        fn = self._build_quarantining(build)
        self._fns[key] = fn
        while len(self._fns) > self.max_entries:
            self._fns.popitem(last=False)
            self.evictions += 1
            telemetry.counter_inc("executable_cache_evictions")
        telemetry.gauge_set("executable_cache_entries", len(self._fns))
        logger.debug(
            "executable-cache miss #%d key=%s (hits=%d entries=%d)",
            self.misses, self.key_digest(key), self.hits, len(self._fns),
        )
        return fn

    @staticmethod
    def _build_quarantining(build: Callable[[], object]):
        """Build an entry, absorbing corrupted persistent-cache reads.

        A digest-mismatch / unpickle failure surfacing from the
        persistent cache is the one DETERMINISTIC error with a better
        move than failing: quarantine the bad entries (scan_cache_dir)
        and retrace once.  Everything else propagates untouched.
        """
        from isotope_tpu.resilience import faults, taxonomy

        try:
            faults.check("cache.load")
            return build()
        except Exception as e:
            if not taxonomy.is_cache_corruption(e):
                raise
            telemetry.counter_inc("compile_cache_quarantine_retries")
            logger.warning(
                "corrupted persistent-cache entry (%s) — quarantining "
                "and retracing", e,
            )
            if _persistent_dir is not None:
                scan_cache_dir(_persistent_dir)
            return build()

    def cache_stats(self) -> dict:
        """Introspection: counts plus the resident keys' digests."""
        keys: List[str] = [self.key_digest(k) for k in self._fns]
        return {
            "hits": self.hits,
            "misses": self.misses,
            "evictions": self.evictions,
            "entries": len(self._fns),
            "max_entries": self.max_entries,
            "keys": keys,
        }

    def reset_stats(self) -> None:
        """Zero the counters WITHOUT dropping entries (test hook)."""
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def __contains__(self, key: tuple) -> bool:
        return key in self._fns

    def __len__(self) -> int:
        return len(self._fns)

    def clear(self) -> None:
        self._fns.clear()
        self.hits = 0
        self.misses = 0
        self.evictions = 0


#: the process-wide instance every Simulator / ShardedSimulator consults
executable_cache = ExecutableCache()


def cache_stats() -> dict:
    """Stats of the process-wide executable cache (see ExecutableCache)."""
    return executable_cache.cache_stats()
