"""Compiled-program dataclasses (host-side, NumPy).

The compiled form has two parts:

- ``ServiceTable``: per-service parameter arrays (the analogue of the
  per-service Deployment fields the reference renders,
  isotope/convert/pkg/kubernetes/kubernetes.go:189-270).
- the unrolled **hop tree**: every request entering the entrypoint walks a
  statically known call tree (the recursion of
  isotope/service/pkg/srv/handler.go:66-76 + executable.go:94-179 over a
  fixed topology).  Each node of that tree is a *hop* — one service
  invocation.  Hops are laid out level-by-level (BFS order) so the engine
  can sweep depth levels with static shapes.

Everything here is plain NumPy; the engine moves it on-device once.
"""
from __future__ import annotations

import dataclasses
from typing import Tuple

import numpy as np


@dataclasses.dataclass(frozen=True)
class ServiceTable:
    """Per-service parameters, indexed by a dense service id.

    Mirrors ``svc.Service`` (isotope/convert/pkg/graph/svc/service.go:25-51)
    minus the deployment-only fields (RBAC policy counts live in the k8s
    converter, not the simulator).
    """

    names: Tuple[str, ...]
    replicas: np.ndarray       # (S,) int32  — NumReplicas => queueing servers
    error_rate: np.ndarray     # (S,) f32    — P(injected 500) in [0, 1]
    response_size: np.ndarray  # (S,) f32    — bytes
    is_entrypoint: np.ndarray  # (S,) bool
    # multicluster placement (perf/load/templates/service-graph.gen.yaml
    # :1-3): dense cluster id per service; edges between different ids
    # pay the NetworkModel's cross-cluster class.  A single-cluster
    # topology has all-zero ids.
    cluster: np.ndarray = None          # (S,) int32
    cluster_names: Tuple[str, ...] = ("",)

    def __post_init__(self):
        if self.cluster is None:
            object.__setattr__(
                self, "cluster", np.zeros(len(self.names), np.int32)
            )

    @property
    def num_services(self) -> int:
        return len(self.names)

    @property
    def num_clusters(self) -> int:
        return len(self.cluster_names)

    def index_of(self, name: str) -> int:
        return self.names.index(name)

    def replicas_by_name(self) -> "dict[str, int]":
        """``{service name: replica count}`` — the host-side view the
        chaos-schedule jitter clamps magnitudes against."""
        return {
            n: int(r) for n, r in zip(self.names, self.replicas)
        }


@dataclasses.dataclass(frozen=True)
class HopLevel:
    """All hops at one depth of the unrolled call tree.

    ``Pmax`` is the graph-wide maximum script length; every hop's script is
    padded to it.  Step slots hold either a fixed base duration (sleep
    commands — including the max over sleeps inside a concurrent group,
    which run in parallel with the group's calls,
    srv/executable.go:148-179) or a join over child hops.

    Child hops (depth+1, in that level's local order) are grouped two
    ways:

    - per **call**: a call site in a parent's script owns ``retries+1``
      consecutive attempt hops; ``att_child[a, k]`` is the local child
      index of call k's attempt a (``att_valid`` masks shorter chains).
      Attempt durations sum serially; the call's outcome is the last
      attempt's.
    - per **step**: ``call_seg`` maps each call to the flat
      ``parent_local * Pmax + step`` slot so a scatter-max computes the
      per-step join — the vectorized form of the reference's WaitGroup
      (srv/executable.go:171-175); sequential steps have one call each.
    """

    hop_ids: np.ndarray        # (L,) int32 — global hop ids, level-local order
    service: np.ndarray        # (L,) int32
    step_is_real: np.ndarray   # (L, Pmax) bool — slot holds an actual step
    step_base: np.ndarray      # (L, Pmax) f32 — sleep seconds (0 for calls)
    child_ids: np.ndarray      # (C,) int32 — global hop ids at depth+1
    child_seg: np.ndarray      # (C,) int32 — parent_local * Pmax + step
    # -- call tables (K = number of call sites at this level) -------------
    call_seg: np.ndarray       # (K,) int32 — parent_local * Pmax + step
    call_step: np.ndarray      # (K,) int32
    call_timeout: np.ndarray   # (K,) f32 — +inf when none
    att_child: np.ndarray      # (maxA, K) int32 — local child idx (or C)
    att_valid: np.ndarray      # (maxA, K) bool

    @property
    def num_hops(self) -> int:
        return len(self.hop_ids)

    @property
    def num_children(self) -> int:
        return len(self.child_ids)

    @property
    def num_calls(self) -> int:
        return len(self.call_seg)

    @property
    def max_attempts(self) -> int:
        return self.att_child.shape[0]


@dataclasses.dataclass(frozen=True)
class CompiledGraph:
    """A ServiceGraph lowered for vectorized simulation."""

    services: ServiceTable
    entry_service: int

    # -- flat hop arrays (H hops, BFS order; hop 0 is the root) ------------
    hop_service: np.ndarray    # (H,) int32
    hop_parent: np.ndarray     # (H,) int32 — -1 for the root
    hop_depth: np.ndarray      # (H,) int32
    hop_step: np.ndarray       # (H,) int32 — step index in parent's script
    hop_attempt: np.ndarray    # (H,) int32 — retry attempt index (0 first)
    hop_send_prob: np.ndarray  # (H,) f32 — this hop's own coin, [0, 1]
    hop_request_size: np.ndarray  # (H,) f32 — bytes sent to the hop
    # P(hop is reached) = prod over path of send_prob * (1 - parent error
    # rate); drives offered-load estimates for the queueing model.
    hop_reach: np.ndarray      # (H,) f64

    levels: Tuple[HopLevel, ...]
    max_steps: int             # Pmax

    @property
    def num_hops(self) -> int:
        return len(self.hop_service)

    @property
    def num_services(self) -> int:
        return self.services.num_services

    @property
    def depth(self) -> int:
        return len(self.levels)

    def shape_signature(self) -> tuple:
        """Hashable shape-only fingerprint of the lowered program.

        Two compiled graphs with equal signatures produce identically
        *shaped* tensor programs (same level sizes, call/attempt
        tables, step width) — the coarse half of the AOT executable
        cache key (compiler/cache.py); value equality is established
        separately by the engine's constant digest.
        """
        return (
            self.num_hops,
            self.num_services,
            self.max_steps,
            self.depth,
            tuple(
                (
                    lvl.num_hops,
                    lvl.num_children,
                    lvl.num_calls,
                    lvl.max_attempts,
                )
                for lvl in self.levels
            ),
        )

    def expected_visits(self, hop_multiplier=None) -> np.ndarray:
        """Expected hops per root request, per service (f64, shape (S,)).

        Offered load at service s under root rate R is ``R *
        expected_visits()[s]`` — the simulator's replacement for measuring
        per-service request rates off live Prometheus counters
        (service/pkg/srv/prometheus/handler.go:37-49).  ``hop_multiplier``
        (shape (H,)) scales each hop's static reach — e.g. time-averaged
        traffic-split weights.
        """
        weights = self.hop_reach
        if hop_multiplier is not None:
            weights = weights * hop_multiplier
        return np.bincount(
            self.hop_service,
            weights=weights,
            minlength=self.num_services,
        )


def hop_wire_times(compiled: "CompiledGraph", net) -> Tuple[np.ndarray,
                                                            np.ndarray]:
    """Per-hop one-way (request, response) wire times, cluster-aware.

    Intra-cluster edges pay ``base_latency_s`` + bytes/bandwidth; edges
    whose caller and callee sit in different clusters additionally pay
    ``cross_cluster_latency_s`` per direction (the egress+ingress
    gateway traversal of the reference's multicluster split,
    perf/load/common.sh:36-42) and ride
    ``cross_cluster_bytes_per_second`` when set.  The client is
    co-located with the entrypoint (the reference deploys one
    loadclient per namespace), so hop 0 is never cross-cluster; the
    entry edge's ingress-gateway tax (``entry_extra_latency_s``) is
    applied here as before.
    """
    hs = compiled.hop_service
    resp = compiled.services.response_size.astype(np.float64)
    req = compiled.hop_request_size.astype(np.float64)
    cl = compiled.services.cluster
    cross = np.zeros(compiled.num_hops, bool)
    if compiled.services.num_clusters > 1:
        parent = compiled.hop_parent
        cross[1:] = cl[hs[parent[1:]]] != cl[hs[1:]]
    extra = float(getattr(net, "cross_cluster_latency_s", 0.0))
    cross_bps = getattr(net, "cross_cluster_bytes_per_second", None)
    bps = np.where(
        cross, cross_bps if cross_bps else net.bytes_per_second,
        net.bytes_per_second,
    )
    lat = net.base_latency_s + np.where(cross, extra, 0.0)
    net_out = lat + req / bps
    net_back = lat + resp[hs] / bps
    net_out[0] += net.entry_extra_latency_s
    net_back[0] += net.entry_extra_latency_s
    return net_out, net_back
