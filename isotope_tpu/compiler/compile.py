"""Lower a validated ServiceGraph into a CompiledGraph.

The reference executes the topology by recursion at request time
(isotope/service/pkg/srv/handler.go:66-76 calling executable.go:43-179,
which issues real HTTP requests downstream).  Over a fixed topology that
recursion traces a statically known call tree, so we unroll it once at
compile time: every service invocation a root request can cause becomes a
*hop* with a parent pointer, and the engine evaluates all requests × all
hops as one tensor program.

Unrolling terminates iff the call graph reachable from the entrypoint is
acyclic — the reference has no cycle guard at all (a cyclic topology would
recurse until sockets run out), so rejecting cycles at compile time is
strictly safer.
"""
from __future__ import annotations

import dataclasses
from typing import List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from isotope_tpu import telemetry
from isotope_tpu.compiler.program import CompiledGraph, HopLevel, ServiceTable
from isotope_tpu.models.graph import ServiceGraph
from isotope_tpu.models.script import (
    ConcurrentCommand,
    RequestCommand,
    SleepCommand,
)


class NoEntrypointError(ValueError):
    def __init__(self):
        super().__init__(
            "service graph has no entrypoint (set isEntrypoint: true)"
        )


class CycleError(ValueError):
    def __init__(self, path: Sequence[str]):
        self.path = list(path)
        super().__init__(
            "call graph contains a cycle reachable from the entrypoint: "
            + " -> ".join(self.path)
        )


class HopBudgetExceededError(ValueError):
    def __init__(self, budget: int):
        self.budget = budget
        super().__init__(
            f"unrolled call tree exceeds {budget} hops; raise max_hops or "
            "simplify the topology"
        )


@dataclasses.dataclass(frozen=True)
class _Call:
    target: int
    size: float
    send_prob: float
    timeout: float = float("inf")
    attempts: int = 1  # retries + 1


@dataclasses.dataclass(frozen=True)
class _Step:
    base: float               # sleep seconds (max over a concurrent group's
    calls: Tuple[_Call, ...]  # sleeps — they run in parallel with its calls)


def _lower_script(script, name_to_idx) -> Tuple[_Step, ...]:
    """One _Step per script command (handler.go:66-76 runs them in order)."""
    steps: List[_Step] = []
    for cmd in script:
        if isinstance(cmd, SleepCommand):
            steps.append(_Step(base=cmd.seconds, calls=()))
        elif isinstance(cmd, RequestCommand):
            steps.append(_Step(base=0.0, calls=(_lower_call(cmd, name_to_idx),)))
        elif isinstance(cmd, ConcurrentCommand):
            sleeps = [c.seconds for c in cmd if isinstance(c, SleepCommand)]
            calls = tuple(
                _lower_call(c, name_to_idx)
                for c in cmd
                if isinstance(c, RequestCommand)
            )
            steps.append(_Step(base=max(sleeps, default=0.0), calls=calls))
        else:  # pragma: no cover - grammar is closed
            raise TypeError(f"unknown command: {cmd!r}")
    return tuple(steps)


def _lower_call(cmd: RequestCommand, name_to_idx) -> _Call:
    return _Call(
        target=name_to_idx[cmd.service_name],
        size=float(int(cmd.size)),
        send_prob=cmd.send_probability,
        timeout=float("inf") if cmd.timeout is None else cmd.timeout,
        attempts=cmd.retries + 1,
    )


def _check_acyclic(entry: int, programs, names) -> None:
    """DFS over the static call graph; raise CycleError on a back edge."""
    WHITE, GRAY, BLACK = 0, 1, 2
    color = [WHITE] * len(programs)
    stack_names: List[str] = []

    def visit(s: int) -> None:
        color[s] = GRAY
        stack_names.append(names[s])
        for step in programs[s]:
            for call in step.calls:
                t = call.target
                if color[t] == GRAY:
                    raise CycleError(stack_names + [names[t]])
                if color[t] == WHITE:
                    visit(t)
        stack_names.pop()
        color[s] = BLACK

    visit(entry)


def compile_policies(graph: ServiceGraph, compiled: CompiledGraph):
    """Lower a topology's ``policies:`` block to dense per-service
    tables in COMPILED service order (sim/policies.PolicyTables) — the
    device-constant form the engine's in-scan control loop consumes.

    Returns ``None`` when the graph declares no policies (the engine's
    byte-identical default path).  Decode errors carry key paths
    (``policies.worker.breaker.max_pending: ...``).
    """
    if not graph.policies:
        return None
    from isotope_tpu.sim import policies as policies_mod

    pols = policies_mod.PolicySet.decode(
        graph.policies, compiled.services.names
    )
    if pols.empty:
        return None
    tables = policies_mod.build_tables(pols, compiled.services)
    telemetry.counter_inc("policies_compiled")
    return tables


def compile_lb(graph: ServiceGraph, compiled: CompiledGraph):
    """Lower a topology's per-service ``lb:`` entries (inside the
    ``policies:`` block) to dense per-service tables in COMPILED
    service order (sim/lb.LbTables) — the device-constant form the
    engine's per-station wait-law selection consumes.

    Returns ``None`` when no service declares an ``lb:`` law (the
    engine's byte-identical default path).  Decode errors carry key
    paths (``policies.worker.lb.choices_d: ...``).
    """
    if not graph.policies:
        return None
    from isotope_tpu.sim import lb as lb_mod

    lbs = lb_mod.LbSet.decode(graph.policies, compiled.services.names)
    if lbs.empty:
        return None
    tables = lb_mod.build_tables(lbs, compiled.services)
    telemetry.counter_inc("lb_compiled")
    return tables


def compile_rollouts(graph: ServiceGraph, compiled: CompiledGraph):
    """Lower a topology's ``rollouts:`` block to dense per-service
    tables in COMPILED service order (sim/rollout.RolloutTables) — the
    device-constant form the engine's in-scan rollout controller
    consumes.

    Returns ``None`` when the graph declares no active rollout (the
    engine's byte-identical default path).  Decode errors carry key
    paths (``rollouts.worker.steps[2]: ...``).
    """
    if not getattr(graph, "rollouts", None):
        return None
    from isotope_tpu.sim import rollout as rollout_mod

    rset = rollout_mod.RolloutSet.decode(
        graph.rollouts, compiled.services.names
    )
    if rset.empty:
        return None
    tables = rollout_mod.build_tables(rset, compiled.services)
    telemetry.counter_inc("rollouts_compiled")
    return tables


class EnsembleTables(NamedTuple):
    """Stacked device tables of one Monte Carlo fleet (sim/ensemble.py)
    — the ``(N,)``-leading leaves the engine's vmapped summary program
    consumes.

    ``qps_scale`` stays host-side (it reshapes the per-member offered
    rate, visit tables and trim windows BEFORE tracing); ``cpu_scale``
    / ``err_scale`` are the traced per-member physics arguments (all
    ones when the spec leaves that axis off — the vmapped program is
    specialized on ``jittered``, not on the values).  The trace facts
    the executable cache keys on are the chunk WIDTH (not the total
    fleet size), ``jittered``, and ``mode`` — see
    ``Simulator._get_ensemble``.
    """

    members: int
    seeds: Tuple[int, ...]
    qps_scale: "object"   # (N,) np.float64, all-ones when off
    cpu_scale: "object"   # (N,) jnp.float32
    err_scale: "object"   # (N,) jnp.float32
    jittered: bool
    mode: str             # "vmap" | "map" (auto already resolved)


def compile_ensemble(spec) -> EnsembleTables:
    """Lower an :class:`~isotope_tpu.sim.ensemble.EnsembleSpec` to the
    stacked tables the engine's vmapped fleet program consumes.  The
    scale VALUES ride as traced arguments, so re-drawn jitters reuse
    the compiled fleet program.
    """
    import jax.numpy as jnp
    import numpy as np

    n = spec.members
    ones = np.ones(max(n, 1), np.float64)
    qps = ones if spec.qps_scale is None else spec.qps_scale
    cpu = ones if spec.cpu_scale is None else spec.cpu_scale
    err = ones if spec.error_scale is None else spec.error_scale
    telemetry.counter_inc("ensembles_compiled")
    return EnsembleTables(
        members=n,
        seeds=tuple(spec.seeds),
        qps_scale=np.asarray(qps, np.float64),
        cpu_scale=jnp.asarray(cpu, jnp.float32),
        err_scale=jnp.asarray(err, jnp.float32),
        jittered=spec.jittered,
        mode=spec.resolved_mode(),
    )


def rung_bucket(width: int) -> int:
    """Pad a rung's member width up to the next power of two.

    Successive-halving brackets (sim/search.py) dispatch one fleet
    program per rung *shape*; padding widths to a small bucket family
    means a whole bracket compiles once per distinct (bucket, horizon)
    pair and later brackets of any nearby population size reuse the
    same executables — the VET-J004 retrace audit sees powers of two,
    never raw survivor counts.
    """
    return 1 << max(int(width) - 1, 0).bit_length()


def ensemble_take(stacked, idx):
    """Gather survivor rows from member-stacked fleet inputs/outputs.

    ``stacked`` is any pytree whose array leaves carry a leading
    member axis (the stacked argument tuple of the vmapped fleet
    program, its stacked RunSummary output, or a carry tuple); ``idx``
    is a device array of member indices.  This is the rung-advancement
    primitive of sim/search.py: a plain ``jnp.take`` per leaf, so
    survivors move between rungs without a host round-trip and the
    gathered rows stay bit-identical to the source rows.
    """
    import jax
    import jax.numpy as jnp

    return jax.tree.map(
        lambda x: jnp.take(x, idx, axis=0), stacked
    )


class ChaosFx(NamedTuple):
    """Per-member stacked chaos tables (chaos fleets).

    The engine's chaos tables — effective replicas, outage flags, the
    policy layer's chaos-downed deltas, the rollout canary-first
    kill-split tables, the LB panic healthy pools, the ungraceful-kill
    reset rows, and the saturated finite-population tables — are all
    trace-time CONSTANTS on solo runs.  A fleet whose members each
    survive a *different* bad day needs them per member; this tuple
    carries the ``(N,)``-leading stacked versions as TRACED arguments
    into ``Simulator._simulate_core(chaos_fx=...)`` so one compiled
    fleet program serves every member's schedule.  Every field past
    the first two is an OPTIONAL leaf: ``None`` means the composition
    does not arm that layer and the leaf vanishes from the jaxpr —
    :func:`chaos_fx_layout` names the armed fields for a given
    composition, and the positional packing on both sides of the
    jitted boundary follows that layout.  Shape alignment (same P,
    same window count W) is guaranteed by
    ``resilience/faults.jitter_chaos_events`` preserving the solo
    schedule's cut structure and asserted at build time.
    """

    eff_replicas_pc: "object"   # (N, P*Cc, S) i32
    svc_down_pc: "object"       # (N, P*Cc, S) bool
    downed_pc: "object" = None  # (N, P*Cc, S) f32 (policies)
    # rollout canary-first kill-split tables (rollouts x chaos)
    eff_base_roll_pc: "object" = None       # (N, P*Cc, S) i32
    svc_down_base_roll_pc: "object" = None  # (N, P*Cc, S) bool
    can_reps_pc: "object" = None            # (N, P*Cc, S) f32
    svc_down_can_pc: "object" = None        # (N, P*Cc, S) bool
    downed_base_pc: "object" = None         # (N, P*Cc, S) f32
    # LB panic healthy pools (lb x chaos)
    lb_alive_pc: "object" = None            # (N, P*Cc, S) f32
    # ungraceful-kill (drain=False) resident-request reset rows
    kill_t: "object" = None                 # (N, E) f32
    kill_frac: "object" = None              # (N, E, H) f32
    # saturated -qps max finite-population tables + nominal-time warp
    sat_p0: "object" = None                 # (N, R, H) f32
    sat_coef: "object" = None               # (N, R, D+1, H) f32
    sat_e: "object" = None                  # (N, R, H) f32
    sat_c: "object" = None                  # (N, R) f32
    sat_scale: "object" = None              # (N, R, H) f32
    sat_cuts: "object" = None               # (N, P) f32
    sat_lam: "object" = None                # (N, P) f32
    sat_breaks: "object" = None             # (N, P) f32


def chaos_fx_layout(sim, with_pol: bool, roll: bool,
                    sat: bool) -> Tuple[str, ...]:
    """The armed :class:`ChaosFx` fields for one fleet composition.

    Both sides of the jitted boundary — the argument packer
    (``Simulator._chaos_fx_args``) and the in-trace unpacker
    (``Simulator._member_chaos_fx``) — derive the positional row
    layout from THIS function, so a composition flag flip changes the
    wire format coherently (and the executable cache key already
    carries the same flags).
    """
    fields = ["eff_replicas_pc", "svc_down_pc"]
    pol = with_pol and sim._policies is not None
    if pol:
        fields.append("downed_pc")
    if roll and sim._rollouts is not None:
        fields += [
            "eff_base_roll_pc", "svc_down_base_roll_pc",
            "can_reps_pc", "svc_down_can_pc",
        ]
        if pol:
            fields.append("downed_base_pc")
    if (sim._lb is not None and sim._lb.any_panic and not sat):
        fields.append("lb_alive_pc")
    if sim._num_kill_events:
        fields += ["kill_t", "kill_frac"]
    if sat:
        fields += [
            "sat_p0", "sat_coef", "sat_e", "sat_c", "sat_scale",
            "sat_cuts", "sat_lam", "sat_breaks",
        ]
    return tuple(fields)


def compile_chaos_members(sim, member_events, with_pol: bool = False,
                          roll: bool = False, sat_conns: int = 0):
    """Build each member's host-side planner Simulator (its own phase
    reach multipliers, retry-feedback fixed point, and drain windows)
    plus the stacked :class:`ChaosFx` device tables.

    ``member_events`` is one jittered ``ChaosEvent`` tuple per member
    (``resilience/faults.jitter_chaos_events``); ``with_pol`` also
    stacks the policy chaos-down tables, ``roll`` the rollout
    canary-first split tables, and a nonzero ``sat_conns`` the
    saturated finite-population tables at that connection count
    (fleets read exactly the :func:`chaos_fx_layout` fields — absent
    layers skip the transfer).  Returns ``(planners, ChaosFx)``.
    Raises when a member's schedule breaks the shape-aligned contract
    (different cut count than the base schedule) — the loud version of
    the structural invariant the stacked tables rely on.
    """
    import jax.numpy as jnp
    import numpy as np

    planners = [sim._member_planner(evts) for evts in member_events]
    P = int(np.asarray(sim._phase_starts).shape[0])
    W = sim._num_windows
    for m, pl in enumerate(planners):
        if (int(np.asarray(pl._phase_starts).shape[0]) != P
                or pl._num_windows != W
                or pl._num_combos != sim._num_combos):
            raise ValueError(
                f"member {m}'s jittered chaos schedule has a "
                "different phase-cut structure than the base schedule "
                f"({np.asarray(pl._phase_starts).shape[0]} cuts vs "
                f"{P}); per-member chaos requires shape-aligned "
                "schedules (same event count, distinct solo cuts)"
            )
    telemetry.counter_inc("chaos_fleets_compiled")
    kw: dict = {}
    pol = with_pol and sim._policies is not None
    if pol:
        kw["downed_pc"] = jnp.stack([pl._downed_pc for pl in planners])
    if roll and sim._rollouts is not None:
        kw["eff_base_roll_pc"] = jnp.stack(
            [pl._eff_base_roll_pc for pl in planners]
        )
        kw["svc_down_base_roll_pc"] = jnp.stack(
            [pl._svc_down_base_roll_pc for pl in planners]
        )
        kw["can_reps_pc"] = jnp.stack(
            [pl._can_reps_pc for pl in planners]
        )
        kw["svc_down_can_pc"] = jnp.stack(
            [pl._svc_down_can_pc for pl in planners]
        )
        if pol:
            kw["downed_base_pc"] = jnp.stack(
                [pl._downed_base_pc for pl in planners]
            )
    if sim._lb is not None and sim._lb.any_panic and not sat_conns:
        kw["lb_alive_pc"] = jnp.stack(
            [pl._lb_alive_pc for pl in planners]
        )
    if sim._num_kill_events:
        kw["kill_t"] = jnp.asarray(
            np.stack([pl._kill_t_np for pl in planners]), jnp.float32
        )
        kw["kill_frac"] = jnp.asarray(
            np.stack([pl._kill_frac_np for pl in planners]),
            jnp.float32,
        )
    if sat_conns:
        rows = [pl._closed_tables(int(sat_conns)) for pl in planners]
        kw["sat_p0"] = jnp.stack([r[1] for r in rows])
        kw["sat_coef"] = jnp.stack([r[2] for r in rows])
        kw["sat_e"] = jnp.stack([r[3] for r in rows])
        kw["sat_c"] = jnp.asarray(
            np.stack([r[4] for r in rows]), jnp.float32
        )
        kw["sat_scale"] = jnp.stack([r[5] for r in rows])
        # the phased nominal-time warp constants, f64 host math
        # mirroring the solo branch exactly so the f32-cast traced
        # rows carry identical bits
        cuts_l, lam_l, breaks_l = [], [], []
        for pl, r in zip(planners, rows):
            lam_p = np.maximum(
                r[0].reshape(P, pl._num_combos).mean(1), 1e-9
            )
            cuts_np = np.asarray(pl._phase_starts, np.float64)
            breaks = np.concatenate(
                [[0.0], np.cumsum(lam_p[:-1] * np.diff(cuts_np))]
            )
            cuts_l.append(cuts_np)
            lam_l.append(lam_p)
            breaks_l.append(breaks)
        kw["sat_cuts"] = jnp.asarray(np.stack(cuts_l), jnp.float32)
        kw["sat_lam"] = jnp.asarray(np.stack(lam_l), jnp.float32)
        kw["sat_breaks"] = jnp.asarray(np.stack(breaks_l), jnp.float32)
    fx = ChaosFx(
        eff_replicas_pc=jnp.stack(
            [pl._eff_replicas_pc for pl in planners]
        ),
        svc_down_pc=jnp.stack([pl._svc_down_pc for pl in planners]),
        **kw,
    )
    return planners, fx


def compile_graph(
    graph: ServiceGraph,
    entry: Optional[str] = None,
    max_hops: int = 2_000_000,
) -> CompiledGraph:
    """Compile ``graph`` for simulation, unrolling from ``entry``.

    ``entry`` defaults to the graph's first entrypoint service — the service
    the reference's Fortio client is pointed at
    (isotope/convert/pkg/kubernetes/fortio_client.go:28-78).
    """
    with telemetry.phase("compile.unroll"):
        compiled = _compile_graph(graph, entry, max_hops)
    telemetry.counter_inc("graphs_compiled")
    telemetry.gauge_set("last_graph_hops", compiled.num_hops)
    telemetry.gauge_set("last_graph_levels", len(compiled.levels))
    # step-grid skew: the widest level's dense (hops x pmax) element
    # count and its width skew (level pmax / mean script width) — the
    # shape signal that drives the sparse/tiled encoding decision
    # (compiler/buckets.level_encoding); a skew near 1 means dense
    # grids are tight, a large skew predicts tiling
    grid_elems = 0
    skew = 1.0
    for lvl in compiled.levels:
        widths = lvl.step_is_real.sum(1)
        pmax = int(widths.max(initial=0))
        if pmax <= 0:
            continue
        grid_elems = max(grid_elems, lvl.num_hops * pmax)
        mean_w = float(widths.mean()) if lvl.num_hops else 1.0
        skew = max(skew, pmax / max(mean_w, 1e-9))
    telemetry.gauge_set("last_graph_max_step_grid_elems", grid_elems)
    telemetry.gauge_set("last_graph_step_width_skew", skew)
    return compiled


def _compile_graph(
    graph: ServiceGraph,
    entry: Optional[str],
    max_hops: int,
) -> CompiledGraph:
    if not graph.services:
        raise NoEntrypointError()
    names = tuple(s.name for s in graph.services)
    name_to_idx = {n: i for i, n in enumerate(names)}

    if entry is None:
        entrypoints = graph.entrypoints()
        if not entrypoints:
            raise NoEntrypointError()
        entry_idx = name_to_idx[entrypoints[0].name]
    else:
        if entry not in name_to_idx:
            raise ValueError(f"unknown entry service: {entry!r}")
        entry_idx = name_to_idx[entry]

    cluster_names = tuple(
        sorted({getattr(s, "cluster", "") for s in graph.services})
    )
    cluster_idx = {c: i for i, c in enumerate(cluster_names)}
    table = ServiceTable(
        names=names,
        replicas=np.asarray(
            [max(1, s.num_replicas) for s in graph.services], np.int32
        ),
        error_rate=np.asarray(
            [float(s.error_rate) for s in graph.services], np.float32
        ),
        response_size=np.asarray(
            [float(int(s.response_size)) for s in graph.services], np.float32
        ),
        is_entrypoint=np.asarray(
            [s.is_entrypoint for s in graph.services], bool
        ),
        cluster=np.asarray(
            [cluster_idx[getattr(s, "cluster", "")] for s in graph.services],
            np.int32,
        ),
        cluster_names=cluster_names,
    )

    programs = [_lower_script(s.script, name_to_idx) for s in graph.services]
    _check_acyclic(entry_idx, programs, names)
    max_steps = max([len(p) for p in programs] + [1])

    # -- BFS unroll --------------------------------------------------------
    hop_service: List[int] = [entry_idx]
    hop_parent: List[int] = [-1]
    hop_depth: List[int] = [0]
    hop_step: List[int] = [-1]
    hop_attempt: List[int] = [0]
    hop_send_prob: List[float] = [1.0]
    hop_request_size: List[float] = [0.0]
    hop_reach: List[float] = [1.0]

    levels: List[HopLevel] = []
    frontier = [0]  # global hop ids at the current depth
    while frontier:
        level_services = [hop_service[h] for h in frontier]
        step_is_real = np.zeros((len(frontier), max_steps), bool)
        step_base = np.zeros((len(frontier), max_steps), np.float32)
        child_ids: List[int] = []
        child_seg: List[int] = []
        call_seg: List[int] = []
        call_step: List[int] = []
        call_timeout: List[float] = []
        call_attempt_children: List[List[int]] = []  # local child indices
        next_frontier: List[int] = []
        for local, h in enumerate(frontier):
            prog = programs[hop_service[h]]
            parent_err = float(table.error_rate[hop_service[h]])
            for step_idx, step in enumerate(prog):
                step_is_real[local, step_idx] = True
                step_base[local, step_idx] = step.base
                for call in step.calls:
                    # Each retry attempt is its own hop (with its own
                    # subtree); its static reach discounts by the target's
                    # error rate — the statically-known part of "previous
                    # attempt failed" — for offered-load estimation.
                    target_err = float(table.error_rate[call.target])
                    call_seg.append(local * max_steps + step_idx)
                    call_step.append(step_idx)
                    call_timeout.append(call.timeout)
                    att_locals: List[int] = []
                    for a in range(call.attempts):
                        child = len(hop_service)
                        if child >= max_hops:
                            raise HopBudgetExceededError(max_hops)
                        hop_service.append(call.target)
                        hop_parent.append(h)
                        hop_depth.append(hop_depth[h] + 1)
                        hop_step.append(step_idx)
                        hop_attempt.append(a)
                        hop_send_prob.append(call.send_prob)
                        hop_request_size.append(call.size)
                        hop_reach.append(
                            hop_reach[h]
                            * call.send_prob
                            * (1.0 - parent_err)
                            * target_err**a
                        )
                        att_locals.append(len(child_ids))
                        child_ids.append(child)
                        child_seg.append(local * max_steps + step_idx)
                        next_frontier.append(child)
                    call_attempt_children.append(att_locals)
        max_a = max((len(c) for c in call_attempt_children), default=1)
        n_calls = len(call_seg)
        att_child = np.full((max_a, n_calls), len(child_ids), np.int32)
        att_valid = np.zeros((max_a, n_calls), bool)
        for k, att_locals in enumerate(call_attempt_children):
            for a, local_idx in enumerate(att_locals):
                att_child[a, k] = local_idx
                att_valid[a, k] = True
        levels.append(
            HopLevel(
                hop_ids=np.asarray(frontier, np.int32),
                service=np.asarray(level_services, np.int32),
                step_is_real=step_is_real,
                step_base=step_base,
                child_ids=np.asarray(child_ids, np.int32),
                child_seg=np.asarray(child_seg, np.int32),
                call_seg=np.asarray(call_seg, np.int32),
                call_step=np.asarray(call_step, np.int32),
                call_timeout=np.asarray(call_timeout, np.float32),
                att_child=att_child,
                att_valid=att_valid,
            )
        )
        frontier = next_frontier

    return CompiledGraph(
        services=table,
        entry_service=entry_idx,
        hop_service=np.asarray(hop_service, np.int32),
        hop_parent=np.asarray(hop_parent, np.int32),
        hop_depth=np.asarray(hop_depth, np.int32),
        hop_step=np.asarray(hop_step, np.int32),
        hop_attempt=np.asarray(hop_attempt, np.int32),
        hop_send_prob=np.asarray(hop_send_prob, np.float32),
        hop_request_size=np.asarray(hop_request_size, np.float32),
        hop_reach=np.asarray(hop_reach, np.float64),
        levels=tuple(levels),
        max_steps=max_steps,
    )
