"""Graph compiler: lower the ServiceGraph IR to dense tensors.

This is the TPU-native analogue of the reference's
``kubernetes.ServiceGraphToKubernetesManifests``
(isotope/convert/pkg/kubernetes/kubernetes.go:56-137): same input — a
validated ``ServiceGraph`` — different target.  Instead of k8s manifests
that *deploy* the topology, we emit static arrays that *simulate* it: a
per-service parameter table plus the entrypoint's call tree unrolled into a
level-ordered hop program that the vectorized engine evaluates with pure
tensor ops.
"""
from isotope_tpu.compiler.program import (
    CompiledGraph,
    HopLevel,
    ServiceTable,
)
from isotope_tpu.compiler.buckets import (
    LevelShape,
    ScanBucketPlan,
    UnrolledLevelPlan,
    plan_segments,
)
from isotope_tpu.compiler.cache import (
    enable_persistent_cache,
    executable_cache,
    persistent_cache_dir,
)
from isotope_tpu.compiler.compile import (
    ChaosFx,
    CycleError,
    EnsembleTables,
    HopBudgetExceededError,
    NoEntrypointError,
    compile_chaos_members,
    compile_ensemble,
    compile_graph,
    compile_lb,
    compile_policies,
    compile_rollouts,
)

__all__ = [
    "CompiledGraph",
    "HopLevel",
    "LevelShape",
    "ScanBucketPlan",
    "ServiceTable",
    "UnrolledLevelPlan",
    "ChaosFx",
    "CycleError",
    "EnsembleTables",
    "HopBudgetExceededError",
    "NoEntrypointError",
    "compile_chaos_members",
    "compile_ensemble",
    "compile_graph",
    "compile_lb",
    "compile_policies",
    "compile_rollouts",
    "enable_persistent_cache",
    "executable_cache",
    "persistent_cache_dir",
    "plan_segments",
]
