"""``isotope-tpu search`` — on-device config-search brackets.

Runs one successive-halving bracket (sim/search.py) over a jittered
candidate population of the given topology: every candidate simulates
as one member of a stacked fleet, rungs rank on device and advance
the best ``1/eta`` by gathers over the stacked tables AND the scan
carries, so a 64-candidate screen costs ~3 engine traces and a few
dispatches instead of 64 solo runs.  Prints the per-rung survivor
lineage and the winning candidate's exact config (the ``optimize``
warm start); ``--out`` writes the isotope-search/v1 artifact.
"""
from __future__ import annotations

import json
import sys

from isotope_tpu.utils import duration as dur


def register(sub) -> None:
    s = sub.add_parser(
        "search",
        help="screen a jittered config population with a "
             "successive-halving bracket (single-dispatch rungs)",
    )
    s.add_argument("topology", help="path to the service graph YAML")
    s.add_argument("--qps", default="1000",
                   help="target QPS (the population's base rate)")
    s.add_argument("--connections", "-c", type=int, default=64)
    s.add_argument("--duration", "-t", default="240s",
                   help='full-horizon duration, e.g. "240s" or "5m"')
    s.add_argument("--load-kind", choices=["open", "closed"],
                   default="open")
    s.add_argument("--max-requests", type=int, default=200_000)
    s.add_argument("--candidates", "-n", type=int, default=64,
                   help="population size (the rung-0 width)")
    s.add_argument("--eta", type=int, default=4,
                   help="halving rate: each rung keeps the best "
                        "ceil(width/eta)")
    s.add_argument("--rungs", type=int, default=3,
                   help="screening levels incl. the full-horizon rung")
    s.add_argument("--growth", type=int, default=None,
                   help="horizon growth between rungs (default: eta)")
    s.add_argument("--rank", default="err_share",
                   help="severity channel candidates rank by "
                        "(err_share | p99 | err_peak)")
    s.add_argument("--slo", default=None,
                   help='p99 rank SLO latency, e.g. "250ms" '
                        "(required for --rank p99)")
    s.add_argument("--jitter", default=None,
                   help='population perturbations, e.g. '
                        '"qps=0.2,cpu=0.1,error=0.3,seed=1"')
    s.add_argument("--chunk", type=int, default=None,
                   help="members per rung dispatch (default: "
                        "carry-aware cost model)")
    s.add_argument("--seed", type=int, default=0)
    s.add_argument("--entry", default=None,
                   help="entrypoint service override")
    s.add_argument("--out", metavar="FILE", default=None,
                   help="write the isotope-search/v1 JSON artifact")
    s.add_argument("--json", action="store_true",
                   help="print the search doc as JSON instead of the "
                        "lineage table")
    s.set_defaults(func=run_search_cmd)


def run_search_cmd(args) -> int:
    try:
        import jax
    except ModuleNotFoundError as e:
        raise ValueError(
            "the search command needs jax, which is not installed "
            "in this environment"
        ) from e

    from isotope_tpu.compiler import compile_graph
    from isotope_tpu.models.graph import ServiceGraph
    from isotope_tpu.sim.config import LoadModel, SimParams
    from isotope_tpu.sim.engine import Simulator
    from isotope_tpu.sim.ensemble import EnsembleSpec, parse_jitter_spec
    from isotope_tpu.sim.search import SearchSpec

    sim = Simulator(
        compile_graph(
            ServiceGraph.from_yaml_file(args.topology),
            entry=args.entry,
        ),
        SimParams(),
    )
    jitter = parse_jitter_spec(args.jitter)
    if not any(jitter.get(k) for k in
               ("qps_jitter", "cpu_jitter", "error_jitter")):
        # an unjittered population is N copies of one config — the
        # bracket would rank pure RNG noise; default to a broad screen
        jitter = dict(jitter, qps_jitter=0.2, cpu_jitter=0.1,
                      error_jitter=0.3)
        print(
            "search: no --jitter given; screening the default "
            "qps=0.2,cpu=0.1,error=0.3 population",
            file=sys.stderr,
        )
    spec = SearchSpec(
        candidates=EnsembleSpec.from_jitter(args.candidates, **jitter),
        eta=args.eta,
        rungs=args.rungs,
        growth=args.growth,
        rank=args.rank,
        slo_s=(
            dur.parse_duration_seconds(args.slo) if args.slo else None
        ),
        seed=args.seed,
        chunk=args.chunk,
    )
    spec.check()
    load = LoadModel(
        kind=args.load_kind,
        qps=float(args.qps),
        connections=args.connections,
        duration_s=dur.parse_duration_seconds(args.duration),
    )
    n = max(
        1, min(int(load.qps * load.duration_s), args.max_requests)
    )
    # the rung schedule needs growth^(rungs-1) blocks to be strictly
    # increasing; the HBM-sized default block often swallows the whole
    # horizon on small topologies, so shrink it to fit the bracket
    need = spec.resolved_growth() ** (spec.rungs - 1)
    block = max(1, min(sim.default_block_size(), n // need))
    srch = sim.run_search(
        load, n, jax.random.PRNGKey(args.seed), spec,
        block_size=block,
    )

    import pathlib

    doc = srch.to_doc(pathlib.Path(args.topology).stem)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(doc, f, indent=2)
        print(f"search -> {args.out}", file=sys.stderr)
    if args.json:
        json.dump(doc, sys.stdout, indent=2)
        sys.stdout.write("\n")
        return 0
    print(
        f"search: {spec.members} candidates, {spec.rungs} rungs "
        f"(eta={spec.eta}, growth={spec.resolved_growth()}), "
        f"rank={doc['rank_effective']}, {srch.traces} engine "
        f"trace(s), mode={srch.mode}"
    )
    for r in srch.rungs:
        surv = ", ".join(str(int(x)) for x in r.survivors[:8])
        more = len(r.survivors) - 8
        print(
            f"  rung {r.rung}: {r.width} candidate(s) x "
            f"{r.cum_requests} req (+{r.num_blocks} block(s), "
            f"chunk {r.chunk}) -> "
            f"{'winner' if r.rung == spec.rungs - 1 else 'survivors'}"
            f" [{surv}{f', +{more} more' if more > 0 else ''}]"
        )
    win = srch.winner_config()
    parts = [
        f"{k}={win[k]:.4f}" for k in
        ("qps_scale", "cpu_scale", "error_scale")
        if win[k] is not None
    ]
    print(
        f"winner: candidate {win['candidate']} (seed {win['seed']}) "
        f"severity={win['severity']:.6f} "
        f"offered={win['offered_qps']:.1f}qps "
        + " ".join(parts)
    )
    return 0
