"""``isotope-tpu telemetry`` — engine self-telemetry probe.

Runs a short, labeled simulation with engine telemetry armed and
reports what the ENGINE did (compile-phase seconds, bucket plan and
padding waste, executable/persistent cache traffic, device-memory
high-water) — the introspection counterpart of ``simulate``, which
reports what the simulated *workload* did.  ``--xla-trace DIR``
additionally captures a ``jax.profiler`` trace of warmed steps via
:mod:`isotope_tpu.telemetry.profile` (the promoted
``tools/capture_profile.py`` backend).
"""
from __future__ import annotations

import json
import sys


def register(sub) -> None:
    t = sub.add_parser(
        "telemetry",
        help="probe the engine's self-telemetry on one topology",
    )
    t.add_argument("topology", nargs="?", default=None,
                   help="service-graph YAML (default: the flagship "
                        "~120-service tree)")
    t.add_argument("--qps", type=float, default=1000.0)
    t.add_argument("--requests", type=int, default=4096)
    t.add_argument("--seed", type=int, default=0)
    t.add_argument("--detail", action="store_true",
                   help="fence at segment granularity (eager execution "
                        "— per-segment wall times; diagnosis, not "
                        "benchmarking)")
    t.add_argument("--json", action="store_true",
                   help="print the RunTelemetry record as JSON instead "
                        "of the Prometheus exposition")
    t.add_argument("--out", metavar="FILE", default=None,
                   help="also append the record to this JSONL file")
    t.add_argument("--compile-cache", metavar="DIR", default=None,
                   help="persistent XLA compilation cache directory "
                        "(default: $ISOTOPE_COMPILE_CACHE)")
    t.add_argument("--xla-trace", metavar="DIR", default=None,
                   help="capture a jax.profiler trace of warmed steps "
                        "into DIR (TensorBoard/XProf-readable)")
    t.set_defaults(func=run_telemetry)


def run_telemetry(args) -> int:
    try:
        import jax
    except ModuleNotFoundError as e:
        raise ValueError(
            "the telemetry command needs jax, which is not installed in "
            "this environment"
        ) from e

    from isotope_tpu import telemetry
    from isotope_tpu.commands.common import arm_telemetry
    from isotope_tpu.compiler.cache import enable_persistent_cache
    from isotope_tpu.sim.config import LoadModel
    from isotope_tpu.telemetry import profile

    # shared detail plumbing (commands/common.py): --detail composes
    # with any --telemetry=detail armed earlier in this process
    arm_telemetry("on", detail=args.detail)
    enable_persistent_cache(args.compile_cache)

    sim = profile.build_simulator(args.topology)
    label = args.topology or "flagship-tree121"
    load = LoadModel(kind="open", qps=args.qps)
    summary = sim.run_summary(
        load, args.requests, jax.random.PRNGKey(args.seed),
        block_size=min(sim.default_block_size(), args.requests),
    )
    jax.block_until_ready(summary.count)

    if args.xla_trace:
        with telemetry.phase("xla_trace_capture"):
            xplanes = profile.capture_xla_trace(
                args.xla_trace, sim=sim,
                num_requests=args.requests, qps=args.qps, seed=args.seed,
            )
        print(f"xla trace: {len(xplanes)} xplane file(s) -> "
              f"{args.xla_trace}", file=sys.stderr)

    rec = telemetry.snapshot(label=label)
    if args.out:
        rec.append_jsonl(args.out)
        print(f"telemetry record -> {args.out}", file=sys.stderr)
    if args.json:
        json.dump(rec.to_dict(), sys.stdout, indent=2, sort_keys=True)
        sys.stdout.write("\n")
    else:
        sys.stdout.write(rec.prometheus_text())
    print(telemetry.summary_line(), file=sys.stderr)
    return 0
