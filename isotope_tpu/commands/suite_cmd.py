"""``isotope-tpu suite`` — the CI benchmark-job pipeline.

The run_benchmark_job.sh analogue: run every given experiment config,
collect artifacts under one ``<date>_<loadgen>_<branch>_<ver>`` publish
id, evaluate the stability alarms on every run into a monitor-status
sink, and render per-config reports plus a manifest.
"""
from __future__ import annotations

import sys


def register(sub) -> None:
    s = sub.add_parser(
        "suite",
        help="run a set of experiment configs as one published "
             "benchmark job",
    )
    s.add_argument("configs", nargs="+",
                   help="experiment TOML files to run, in order")
    s.add_argument("--out", "-o", default="publish",
                   help="publish root (default: ./publish)")
    s.add_argument("--id", default=None,
                   help="publish id (default: <date>_sim_<labels>_dev)")
    s.add_argument("--labels", default="master")
    s.add_argument("--cpu-limit", type=float, default=50.0,
                   help="alarm threshold, milli-cores")
    s.add_argument("--mem-limit", type=float, default=64.0,
                   help="alarm threshold, MiB")
    s.add_argument("--fresh", action="store_true",
                   help="ignore existing per-config checkpoints")
    s.add_argument("--compile-cache", metavar="DIR", default=None,
                   help="persistent XLA compilation cache directory "
                        "(default: $ISOTOPE_COMPILE_CACHE); a suite "
                        "re-run of the same topology set skips XLA")
    s.add_argument("--telemetry", nargs="?", const="on",
                   choices=("on", "detail"), default=None,
                   help="emit engine self-telemetry per run: "
                        "isotope_engine_* series in each .prom artifact "
                        "plus a telemetry.jsonl per config ('detail' "
                        "adds segment fences — diagnosis, not "
                        "benchmarking)")
    from isotope_tpu.commands.simulate_cmd import (
        _add_resilience_args,
        _add_vet_arg,
    )

    _add_resilience_args(s)
    _add_vet_arg(s)
    s.set_defaults(func=run_suite_cmd)


def run_suite_cmd(args) -> int:
    from isotope_tpu.commands.common import arm_telemetry
    from isotope_tpu.compiler.cache import enable_persistent_cache

    arm_telemetry(args.telemetry)
    enable_persistent_cache(args.compile_cache)
    from isotope_tpu.commands.simulate_cmd import _policy
    from isotope_tpu.runner.suite import run_suite

    result = run_suite(
        args.configs,
        args.out,
        id=args.id,
        labels=args.labels,
        cpu_limit_mcores=args.cpu_limit,
        mem_limit_mib=args.mem_limit,
        progress=lambda label: print(f"running {label}", file=sys.stderr),
        resume=not args.fresh,
        policy=_policy(args),
        vet=args.vet,
    )
    m = result.manifest
    print(
        f"suite {m['id']}: {m['total_runs']} runs across "
        f"{len(m['configs'])} configs, {m['total_alarms']} alarms, "
        f"{m['total_failed']} failed, {m['total_degraded']} degraded -> "
        f"{result.publish_dir}",
        file=sys.stderr,
    )
    return 1 if (m["total_alarms"] or m["total_failed"]) else 0
