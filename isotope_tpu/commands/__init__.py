"""CLI subcommand registry."""
from __future__ import annotations


def register_all(sub) -> None:
    from isotope_tpu.commands import convert_cmd, generate_cmd

    convert_cmd.register(sub)
    generate_cmd.register(sub)
