"""CLI subcommand registry."""
from __future__ import annotations


def register_all(sub) -> None:
    from isotope_tpu.commands import (
        convert_cmd,
        generate_cmd,
        ingest_cmd,
        report_cmd,
    )

    convert_cmd.register(sub)
    generate_cmd.register(sub)
    generate_cmd.register_pilot(sub)
    ingest_cmd.register(sub)
    report_cmd.register(sub)
    # simulate_cmd/suite_cmd defer their jax-dependent imports into the
    # handlers (so --help stays instant); a jax-less environment gets a
    # clean error at run time from _require_jax, not a hidden subcommand.
    from isotope_tpu.commands import (
        explain_cmd,
        fidelity_cmd,
        search_cmd,
        simulate_cmd,
        suite_cmd,
        telemetry_cmd,
        timeline_cmd,
        vet_cmd,
    )

    simulate_cmd.register(sub)
    suite_cmd.register(sub)
    fidelity_cmd.register(sub)
    telemetry_cmd.register(sub)
    timeline_cmd.register(sub)
    search_cmd.register(sub)
    explain_cmd.register(sub)
    vet_cmd.register(sub)
