"""``isotope-tpu timeline`` — the simulation flight recorder probe.

Runs one labeled simulation with the windowed recorder armed
(metrics/timeline.py) and reports the run as a TIME SERIES instead of
an end-of-run aggregate: per-window client throughput/error/latency
rows, per-service utilization / queue-depth sparklines, the convoy
detector's entry-wait-vs-leaf-busy correlation, and (per window) the
standard stability alarms so an SLO breach gets a sim-time ONSET.

``--controlplane N:M:P`` co-simulates an istiod config push (N
ServiceEntries x M endpoints to P proxies, sim/controlplane.py) and
projects its convergence events onto the same window axis, so
config-push and data-plane timelines compose.
"""
from __future__ import annotations

import json
import sys

from isotope_tpu.utils import duration as dur


def register(sub) -> None:
    t = sub.add_parser(
        "timeline",
        help="record a run as windowed per-service time series",
    )
    t.add_argument("topology", help="path to the service graph YAML")
    t.add_argument("--qps", default="1000",
                   help='target QPS, or "max" (fortio -qps max)')
    t.add_argument("--connections", "-c", type=int, default=64)
    t.add_argument("--duration", "-t", default="240s",
                   help='run duration, e.g. "240s" or "5m"')
    t.add_argument("--load-kind", choices=["open", "closed"],
                   default="open")
    t.add_argument("--max-requests", type=int, default=200_000)
    t.add_argument("--window", "-w", default="10s",
                   help="sim-time window width (the scrape interval)")
    t.add_argument("--seed", type=int, default=0)
    t.add_argument("--entry", default=None,
                   help="entrypoint service override")
    t.add_argument("--out", metavar="FILE", default=None,
                   help="write the isotope-timeline/v1 JSON artifact")
    t.add_argument("--perfetto", metavar="FILE", default=None,
                   help="write Perfetto/Chrome counter tracks over "
                        "real sim time")
    t.add_argument("--prometheus", metavar="FILE", default=None,
                   help="write the timestamped Prometheus exposition "
                        "(one sample per window)")
    t.add_argument("--json", action="store_true",
                   help="print the timeline doc as JSON instead of "
                        "the table")
    t.add_argument("--alarms", action="store_true",
                   help="evaluate the standard stability alarms per "
                        "window and report the first breach's sim-time "
                        "onset")
    t.add_argument("--alarm-sink", metavar="FILE", default=None,
                   help="append per-window MonitorStatus rows to this "
                        "JSONL sink (with --alarms)")
    # NOTE the semantics: the per-window CPU series is the BUSY
    # OCCUPANCY integral (server-side time incl. script sleeps and
    # downstream blocking), an upper bound on CPU burn — so the
    # defaults are sized in occupancy terms (one full core per
    # service), NOT the reference's 50-milli-core vCPU budget, which
    # would fire on any service >5% busy
    t.add_argument("--cpu-limit", type=float, default=1000.0,
                   help="per-service CPU-OCCUPANCY alarm threshold, "
                        "milli-cores (busy time incl. sleeps and "
                        "downstream blocking; default = 1 core)")
    t.add_argument("--mem-limit", type=float, default=1024.0,
                   help="per-service working-set alarm threshold, MiB")
    t.add_argument("--controlplane", metavar="N:M:P", default=None,
                   help="co-simulate a config push (N entries x M "
                        "endpoints to P proxies) onto the same window "
                        "axis")
    t.set_defaults(func=run_timeline_cmd)


def run_timeline_cmd(args) -> int:
    try:
        import jax
    except ModuleNotFoundError as e:
        raise ValueError(
            "the timeline command needs jax, which is not installed "
            "in this environment"
        ) from e

    import dataclasses

    from isotope_tpu.compiler import compile_graph
    from isotope_tpu.metrics import timeline as timeline_mod
    from isotope_tpu.models.graph import ServiceGraph
    from isotope_tpu.sim.config import LoadModel, SimParams
    from isotope_tpu.sim.engine import Simulator

    window_s = dur.parse_duration_seconds(args.window)
    compiled = compile_graph(
        ServiceGraph.from_yaml_file(args.topology), entry=args.entry
    )
    sim = Simulator(
        compiled,
        dataclasses.replace(
            SimParams(), timeline=True, timeline_window_s=window_s
        ),
    )
    qps = None if args.qps == "max" else float(args.qps)
    load = LoadModel(
        kind=args.load_kind,
        qps=qps,
        connections=args.connections,
        duration_s=dur.parse_duration_seconds(args.duration),
    )
    rate = qps if qps is not None else sim.capacity_qps()
    n = max(1, min(int(rate * load.duration_s), args.max_requests))
    _, tl = sim.run_timeline(
        load, n, jax.random.PRNGKey(args.seed),
        block_size=sim.default_block_size(),
    )
    jax.block_until_ready(tl.count)

    controlplane = None
    if args.controlplane:
        from isotope_tpu.sim.controlplane import (
            PilotModel,
            push_convergence,
        )

        try:
            n_e, m_e, p_e = (
                int(x) for x in args.controlplane.split(":")
            )
        except ValueError:
            raise ValueError(
                f"--controlplane wants N:M:P integers, got "
                f"{args.controlplane!r}"
            )
        conv = push_convergence(
            PilotModel(), n_e, m_e, p_e,
            key=jax.random.PRNGKey(args.seed),
        )
        controlplane = conv.window_series(
            float(tl.window_s), tl.num_windows
        )

    doc = timeline_mod.to_doc(
        compiled, tl, controlplane=controlplane
    )
    if args.out:
        with open(args.out, "w") as f:
            json.dump(doc, f, indent=2)
        print(f"timeline -> {args.out}", file=sys.stderr)
    if args.perfetto:
        from isotope_tpu.metrics.export import write_timeline_perfetto

        ev = write_timeline_perfetto(args.perfetto, compiled, tl)
        print(f"timeline counters ({ev} events) -> {args.perfetto}",
              file=sys.stderr)
    if args.prometheus:
        with open(args.prometheus, "w") as f:
            f.write(timeline_mod.prometheus_text(compiled, tl))
        print(f"timestamped exposition -> {args.prometheus}",
              file=sys.stderr)

    if args.json:
        json.dump(doc, sys.stdout, indent=2)
        sys.stdout.write("\n")
    else:
        print(timeline_mod.format_table(doc))
        if controlplane is not None:
            frac = controlplane["converged_fraction"]
            print(
                f"controlplane: push converged in window "
                f"{controlplane['converged_window']} "
                f"({controlplane['proxies']} proxies) "
                f"{timeline_mod.sparkline(frac)}"
            )

    rc = 0
    if args.alarms:
        import pathlib

        from isotope_tpu.metrics import monitor
        from isotope_tpu.metrics.alarms import standard_queries

        label = pathlib.Path(args.topology).stem
        queries = standard_queries(
            label, cpu_lim=args.cpu_limit, mem_lim=args.mem_limit
        )
        rows = monitor.evaluate_windows(
            queries, timeline_mod.window_stores(compiled, tl),
            run_label=label,
        )
        if args.alarm_sink:
            monitor.MonitorSink(args.alarm_sink).write(rows)
            print(f"{len(rows)} monitor rows -> {args.alarm_sink}",
                  file=sys.stderr)
        onset = monitor.first_alarm_onset(rows)
        n_alarms = sum(
            1 for r in rows if r.status == monitor.STATUS_ALARM
        )
        if onset is not None:
            print(
                f"ALARM onset at window {onset.window_index} "
                f"(t={onset.sim_time_s:g}s): {onset.monitor} = "
                f"{onset.value:g} ({n_alarms} alarming "
                f"window-checks total)",
                file=sys.stderr,
            )
            rc = 1
        else:
            print("alarms: all windows clean", file=sys.stderr)
    return rc
