"""Shared flag plumbing for run-executing subcommands.

Detail-mode (per-segment fence) arming used to be duplicated across
``simulate``/``sweep`` (``--telemetry=detail``) and ``telemetry``
(``--detail``), so two callers in one process could CONFLICT — the
second ``enable(detail=False)`` silently stripped fences the first had
armed.  :func:`arm_telemetry` is the single composition point: detail
requests OR together (a later caller can add detail, never remove it),
which is what lets ``--telemetry=detail`` and the attribution pass (or
the ``telemetry`` probe's ``--detail``) compose.
"""
from __future__ import annotations

import os
from typing import Optional


def arm_telemetry(mode: Optional[str] = None,
                  detail: bool = False) -> bool:
    """Arm engine telemetry emission/detail from command flags.

    ``mode`` is a ``--telemetry`` value (``None`` / ``"on"`` /
    ``"detail"``); ``detail`` is an independent detail request (the
    ``telemetry`` subcommand's ``--detail``).  Returns whether detail
    fencing is armed after this call.
    """
    from isotope_tpu import telemetry

    want_detail = bool(detail) or mode == "detail"
    if mode or want_detail:
        # compose, never strip: an earlier caller's detail request
        # survives a later plain --telemetry
        telemetry.enable(
            detail=want_detail or telemetry.detail_enabled()
        )
    return telemetry.detail_enabled()


def default_compile_cache(compile_cache: Optional[str],
                          mode: Optional[str]) -> Optional[str]:
    """The telemetry-run compile-cache default (bench's ``.xla-cache``
    convention): plain ``--telemetry`` runs measure cache
    effectiveness, so they default the persistent cache ON unless the
    user or environment said otherwise.  Detail mode is excluded —
    eager execution would fill the cache with per-primitive noise."""
    from isotope_tpu.compiler.cache import ENV_CACHE_DIR

    if (mode == "on" and compile_cache is None
            and ENV_CACHE_DIR not in os.environ):
        return ".xla-cache"
    return compile_cache
