"""``isotope-tpu kubernetes`` and ``isotope-tpu graphviz`` subcommands.

Mirror the reference converter CLI (isotope/convert/cmd/kubernetes.go:30-90,
cmd/graphviz.go:28-48).
"""
from __future__ import annotations

import sys

from isotope_tpu.convert import graphviz as graphviz_mod
from isotope_tpu.convert import kubernetes as k8s_mod
from isotope_tpu.models.graph import ServiceGraph


def register(sub) -> None:
    k8s = sub.add_parser(
        "kubernetes",
        help="convert a topology YAML to Kubernetes manifests (stdout)",
    )
    k8s.add_argument("topology", help="path to the service graph YAML")
    k8s.add_argument(
        "--service-image", default=k8s_mod.DEFAULT_SERVICE_IMAGE
    )
    k8s.add_argument("--client-image", default=k8s_mod.DEFAULT_CLIENT_IMAGE)
    k8s.add_argument(
        "--environment-name",
        default="NONE",
        choices=["NONE", "ISTIO"],
        help="mesh environment (cmd/kubernetes.go:78)",
    )
    k8s.add_argument(
        "--max-idle-connections-per-host", type=int, default=0
    )
    k8s.add_argument(
        "--cluster", default=None,
        help="emit only this cluster's Deployments/Services (the "
             "per-context apply of the reference's multicluster split, "
             "perf/load/common.sh:36-42); the ConfigMap always embeds "
             "the full topology",
    )
    k8s.set_defaults(func=run_kubernetes)

    gv = sub.add_parser(
        "graphviz", help="convert a topology YAML to Graphviz DOT"
    )
    gv.add_argument("topology")
    gv.add_argument(
        "output", nargs="?", help="output file (default: stdout)"
    )
    gv.set_defaults(func=run_graphviz)

    sec = sub.add_parser(
        "security-policies",
        help="generate large-scale AuthorizationPolicy / PeerAuthentication"
             " / RequestAuthentication manifests from a JSON config "
             "(perf/benchmark/security/generate_policies parity)",
    )
    sec.add_argument(
        "config", nargs="?",
        help="JSON config (README 'Config file' schema); default: "
             "empty config",
    )
    sec.add_argument("-o", "--output",
                     help="manifest output file (default: stdout)")
    sec.add_argument("--token-out", metavar="FILE",
                     help="write the signed bearer token here")
    sec.set_defaults(func=run_security)


def run_kubernetes(args) -> int:
    with open(args.topology) as f:
        topology_yaml = f.read()
    graph = ServiceGraph.from_yaml(topology_yaml)
    k8s_mod.validate_service_types(graph)
    opts = k8s_mod.ConvertOptions(
        service_image=args.service_image,
        client_image=args.client_image,
        environment_name=args.environment_name,
        max_idle_connections_per_host=args.max_idle_connections_per_host,
        cluster=args.cluster,
    )
    manifests = k8s_mod.service_graph_to_manifests(graph, topology_yaml, opts)
    sys.stdout.write(k8s_mod.manifests_to_yaml(manifests))
    return 0


def run_graphviz(args) -> int:
    graph = ServiceGraph.from_yaml_file(args.topology)
    dot = graphviz_mod.to_dot(graph)
    if args.output:
        with open(args.output, "w") as f:
            f.write(dot)
    else:
        sys.stdout.write(dot)
    return 0


def run_security(args) -> int:
    from isotope_tpu.convert.security import (
        SecurityPolicyConfig,
        generate_policies,
    )

    if args.config:
        with open(args.config) as f:
            cfg = SecurityPolicyConfig.from_json(f.read())
    else:
        cfg = SecurityPolicyConfig()
    manifests, token = generate_policies(cfg)
    if args.output:
        with open(args.output, "w") as f:
            f.write(manifests)
    else:
        sys.stdout.write(manifests)
    if args.token_out:
        if token is None:
            print("no RequestAuthentication policies: no token generated",
                  file=sys.stderr)
        else:
            with open(args.token_out, "w") as f:
                f.write(token)
    return 0
