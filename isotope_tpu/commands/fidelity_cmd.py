"""``isotope-tpu fidelity`` — diff the sim against a real Fortio run.

The ground-truth workflow for the north star's "p99 within 5% of a
real Fortio run" clause (BASELINE.json): take an actual ``fortio load
-json`` artifact from the cluster (the schema
perf/benchmark/runner/fortio.py:38-75 flattens), point this command at
it plus the topology the cluster ran, and it reconstructs the load
(closed-loop workers at the artifact's NumThreads/RequestedQPS),
simulates, and reports per-percentile deltas against the clause.
"""
from __future__ import annotations

import json
import sys

from isotope_tpu.utils import duration as dur


def register(sub) -> None:
    f = sub.add_parser(
        "fidelity",
        help="diff simulated percentiles against a real fortio "
             "load -json artifact",
    )
    f.add_argument("topology", help="path to the service graph YAML "
                                    "the cluster ran")
    f.add_argument("--fortio", required=True,
                   help="path to the fortio load -json result")
    f.add_argument("--tolerance", type=float, default=0.05,
                   help="relative per-percentile tolerance "
                        "(default 0.05 — the north-star clause)")
    f.add_argument("--max-requests", type=int, default=1_000_000)
    f.add_argument("--service-time",
                   choices=["exponential", "deterministic", "lognormal",
                            "pareto"],
                   default="exponential")
    f.add_argument("--service-time-param", type=float, default=None)
    f.add_argument("--cpu-time", default=None,
                   help='per-request CPU demand, e.g. "77us"')
    f.add_argument("--entry", default=None)
    f.add_argument("--seed", type=int, default=0)
    f.add_argument("--json", action="store_true", dest="as_json",
                   help="print a machine-readable report instead")
    f.set_defaults(func=run_fidelity)


def run_fidelity(args) -> int:
    from isotope_tpu.metrics.fidelity import check_fidelity
    from isotope_tpu.sim.config import SimParams

    with open(args.fortio) as fh:
        doc = json.load(fh)
    with open(args.topology) as fh:
        topology_yaml = fh.read()

    extra = {}
    if args.cpu_time is not None:
        extra["cpu_time_s"] = dur.parse_duration_seconds(args.cpu_time)
    if args.service_time_param is not None:
        extra["service_time_param"] = args.service_time_param
    elif args.service_time == "pareto":
        extra["service_time_param"] = 1.5
    params = SimParams(service_time=args.service_time, **extra)

    report = check_fidelity(
        doc,
        topology_yaml,
        params=params,
        tolerance=args.tolerance,
        max_requests=args.max_requests,
        entry=args.entry,
        seed=args.seed,
    )
    if args.as_json:
        print(json.dumps({
            "ok": report.ok,
            "tolerance": report.tolerance,
            "actual_qps": {"fortio": report.actual_qps_fortio,
                           "sim": report.actual_qps_sim},
            "error_percent": {"fortio": report.error_percent_fortio,
                              "sim": report.error_percent_sim},
            "percentiles": [
                {"percentile": d.percentile, "fortio_s": d.fortio_s,
                 "sim_s": d.sim_s, "rel_err": d.rel_err}
                for d in report.deltas
            ],
        }))
    else:
        for line in report.lines():
            print(line)
    return 0 if report.ok else 1


def main(argv=None) -> int:  # pragma: no cover - thin wrapper
    import argparse

    parser = argparse.ArgumentParser()
    sub = parser.add_subparsers(dest="command")
    register(sub)
    args = parser.parse_args(argv)
    return args.func(args)


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
