"""``isotope-tpu simulate`` and ``isotope-tpu sweep`` subcommands.

``simulate`` is one labeled run — the counterpart of a single ``fortio
load`` invocation against a deployed graph (perf/benchmark/runner/
runner.py:255-268) — printing the Fortio-style JSON (or the flattened
single-line record) and optionally the Prometheus exposition.

``sweep`` is the full experiment driver: a TOML config (the shape of
isotope/example-config.toml) crossed over topologies x environments x
connections x qps, writing results.jsonl / benchmark.csv / per-run JSON
like the reference's collection pipeline.
"""
from __future__ import annotations

import json
import sys

from isotope_tpu.utils import duration as dur


def _add_resilience_args(parser) -> None:
    """The run supervisor's knobs (resilience/supervisor.py), shared by
    every run-executing subcommand."""
    parser.add_argument(
        "--max-retries", type=int, default=None, metavar="N",
        help="transient-failure retries per phase before the case "
             "fails (default: $ISOTOPE_MAX_RETRIES or 3; backoff is "
             "exponential with deterministic jitter)")
    parser.add_argument(
        "--no-degrade", action="store_true",
        help="disable the OOM degradation ladder (halve request "
             "chunk, sharded -> single-device -> CPU eager); an OOM "
             "then fails the case immediately")


def _policy(args):
    from isotope_tpu.resilience import ResiliencePolicy

    return ResiliencePolicy.from_env(
        max_retries=args.max_retries,
        degrade=False if args.no_degrade else None,
    )


def _add_attribution_args(parser) -> None:
    """The tail-latency attribution knobs (metrics/attribution.py),
    shared by simulate and sweep."""
    parser.add_argument(
        "--attribution", nargs="?", const="on", choices=("on", "tail"),
        default=None,
        help="critical-path blame attribution: after the main run, an "
             "attributed pass (identical request streams) reduces "
             "per-service/per-edge blame on device and prints the "
             "blame table.  'tail' also accumulates conditional-tail "
             "blame past an estimated p99 cut and mines top-K slow "
             "exemplars")


def _add_timeline_args(parser) -> None:
    """The flight-recorder knobs (metrics/timeline.py), shared by
    simulate and sweep."""
    parser.add_argument(
        "--timeline", nargs="?", const="10s", default=None,
        metavar="WINDOW",
        help="simulation flight recorder: after the main run, a "
             "timeline pass (identical request streams) bins every "
             "hop event into fixed sim-time windows on device and "
             "reports per-service x per-window series (throughput, "
             "errors, in-flight, queue depth, utilization) plus the "
             "convoy detector.  Optional value = window width "
             "(default 10s)")


def _timeline_window(args):
    """The ``--timeline`` window in seconds, or None when off."""
    if args.timeline is None:
        return None
    return dur.parse_duration_seconds(args.timeline)


def _add_policies_args(parser) -> None:
    """The resilience-policy co-sim knobs (sim/policies.py), shared by
    simulate and sweep."""
    parser.add_argument(
        "--policies", action="store_true",
        help="co-simulate the topology's `policies:` block (circuit "
             "breakers, retry budgets, outlier ejection, HPA "
             "autoscalers) inside the block scan: the MAIN run becomes "
             "the PROTECTED system, reacting window-by-window to the "
             "flight-recorder signals (implies --timeline; the policy "
             "actuation series lands next to the windowed series)")


def _add_rollouts_args(parser) -> None:
    """The progressive-delivery co-sim knobs (sim/rollout.py), shared
    by simulate and sweep."""
    parser.add_argument(
        "--rollouts", action="store_true",
        help="co-simulate the topology's `rollouts:` block (reactive "
             "canary rollouts: per-service baseline/canary traffic "
             "splits advanced window-by-window — PROMOTE on passing "
             "SLO gates, HOLD while samples are short, ROLL BACK on a "
             "gate trip) inside the block scan: the MAIN run becomes "
             "the progressively-delivered system (implies --timeline; "
             "composes with --policies in the same carry)")


def _add_ensemble_args(parser) -> None:
    """The scenario-ensemble knobs (sim/ensemble.py), shared by
    simulate and sweep."""
    parser.add_argument(
        "--ensemble", type=int, default=None, metavar="N",
        help="Monte Carlo fleet: run every case as N seed members in "
             "ONE jitted program per device (member k bit-equals a "
             "solo run with fold_in(run_key, k)); the reported row "
             "pools the members and <label>.ensemble.json carries "
             "per-member quantiles, quantile bands, and the "
             "SLO-violation probability with a Wilson CI")
    parser.add_argument(
        "--ensemble-jitter", default=None, metavar="SPEC",
        help="per-member perturbations as axis=sigma pairs, e.g. "
             "'qps=0.1,cpu=0.05,error=0.2[,seed=K]': mean-preserving "
             "lognormal factors on the offered qps, per-request CPU "
             "demand, and per-hop error rates (deterministic per "
             "seed K)")
    parser.add_argument(
        "--ensemble-slo", default=None, metavar="LATENCY",
        help="SLO latency (e.g. '250ms') the ensemble artifact's "
             "P(p99 > SLO) estimate targets")
    parser.add_argument(
        "--ensemble-chaos-jitter", default=None, metavar="SPEC",
        help="per-member chaos schedules (chaos fleets): jitter each "
             "member's kill timing / target / magnitude as key=value "
             "pairs, e.g. 'time=0.2,magnitude=0.5,target=0.3[,seed"
             "=K]' — every fleet member survives a DIFFERENT bad "
             "day (needs a [chaos] schedule; composes with "
             "--policies AND --rollouts)")
    parser.add_argument(
        "--ensemble-split", default=None, metavar="SPEC",
        help="importance splitting (multilevel/RESTART) over the "
             "chaos+workload RNG for rare-outage tails plain Monte "
             "Carlo cannot resolve, e.g. 'levels=4,members=64,keep="
             "0.25,threshold=0.5,sev=err_peak[,horizon=0.25]'; the "
             "estimate lands behind <label>.ensemble.json's "
             "'splitting' key")
    parser.add_argument(
        "--split-horizon", default=None, type=float, metavar="FRAC",
        help="splitting screening-horizon fraction in (0, 1] "
             "(default 0.25): each splitting level simulates FRAC of "
             "the case's request count — overrides the 'horizon=' "
             "key of --ensemble-split and is recorded in the "
             "artifact's splitting block")


def _ensemble_config_kwargs(args) -> dict:
    """ExperimentConfig overrides from the --ensemble* flags."""
    out: dict = {}
    if args.ensemble is not None:
        out["ensemble"] = int(args.ensemble)
    if args.ensemble_jitter is not None:
        from isotope_tpu.sim.ensemble import parse_jitter_spec

        j = parse_jitter_spec(args.ensemble_jitter)
        out["ensemble_qps_jitter"] = j["qps_jitter"]
        out["ensemble_cpu_jitter"] = j["cpu_jitter"]
        out["ensemble_error_jitter"] = j["error_jitter"]
        out["ensemble_jitter_seed"] = j.get("jitter_seed", 0)
    if args.ensemble_slo is not None:
        out["ensemble_slo_s"] = dur.parse_duration_seconds(
            args.ensemble_slo
        )
    if getattr(args, "ensemble_chaos_jitter", None) is not None:
        from isotope_tpu.resilience.faults import parse_chaos_jitter

        parse_chaos_jitter(args.ensemble_chaos_jitter)  # fail fast
        out["ensemble_chaos_jitter"] = args.ensemble_chaos_jitter
    if getattr(args, "ensemble_split", None) is not None:
        from isotope_tpu.sim.splitting import parse_split_spec

        parse_split_spec(args.ensemble_split)  # fail fast
        out["ensemble_split"] = args.ensemble_split
    if getattr(args, "split_horizon", None) is not None:
        h = float(args.split_horizon)
        if not 0.0 < h <= 1.0:
            raise SystemExit(
                "--split-horizon must lie in (0, 1]"
            )
        out["ensemble_split_horizon"] = h
    return out


def _add_mesh_args(parser) -> None:
    """The mesh-layout knobs (parallel/mesh.py + parallel/layout.py),
    shared by simulate and sweep."""
    parser.add_argument(
        "--mesh", default=None, metavar="SPEC",
        help="device-mesh factorization for sharded runs: 'auto' "
             "(cost-model layout search over {data, svc, slice}), "
             "'DATAxSVC[xSLICE]' (e.g. 4x2 or 2x2x2 — the slice axis "
             "crosses DCN), or 'data=4,svc=2,slice=1'.  Also env "
             "$ISOTOPE_MESH; default: the TOML mesh_data/mesh_svc "
             "keys, else all devices on the data axis")
    parser.add_argument(
        "--overlap", action="store_true",
        help="overlap the sharded metric-merge collectives with the "
             "next request block's compute (double-buffered carry; "
             "hides DCN merge latency).  Identical results up to f32 "
             "reduction order; off by default (byte-identical "
             "single-merge path).  Applies to the main summary run — "
             "the --attribution/--timeline diagnostic passes keep "
             "their single post-scan merge")


def _add_vet_arg(parser) -> None:
    """The static pre-flight gate (analysis/), shared by every
    run-executing subcommand."""
    parser.add_argument(
        "--vet", nargs="?", const="on", choices=("on", "strict"),
        default=None,
        help="pre-flight static analysis before each case (also env "
             "ISOTOPE_VET=1|strict): lint the topology/config, audit "
             "the traced jaxpr, and let the pre-flight memory verdict "
             "pick the resilience ladder's starting rung.  Blocking "
             "findings fail the case; 'strict' promotes warnings")


def register(sub) -> None:
    s = sub.add_parser(
        "simulate", help="simulate one topology under one load"
    )
    s.add_argument("topology", help="path to the service graph YAML")
    s.add_argument("--qps", default="1000",
                   help='target QPS, or "max" (fortio -qps max)')
    s.add_argument("--connections", "-c", type=int, default=64)
    s.add_argument("--duration", "-t", default="240s",
                   help='run duration, e.g. "240s" or "5m"')
    s.add_argument("--load-kind", choices=["open", "closed"],
                   default="closed",
                   help="closed = fortio workers; open = Poisson arrivals")
    s.add_argument("--environment", default="NONE",
                   help="NONE or ISTIO (adds the sidecar latency tax)")
    s.add_argument("--max-requests", type=int, default=1_000_000)
    s.add_argument("--service-time",
                   choices=["exponential", "deterministic", "lognormal",
                            "pareto"],
                   default="exponential",
                   help="per-request CPU-time distribution")
    s.add_argument("--service-time-param", type=float, default=None,
                   help="lognormal sigma / pareto alpha")
    s.add_argument("--cpu-time", default=None,
                   help='per-request CPU demand, e.g. "77us"')
    s.add_argument("--seed", type=int, default=0)
    s.add_argument("--compile-cache", metavar="DIR", default=None,
                   help="persistent XLA compilation cache directory "
                        "(default: $ISOTOPE_COMPILE_CACHE; repeated "
                        "runs of one topology family skip XLA)")
    s.add_argument("--labels", default="")
    s.add_argument("--entry", default=None,
                   help="entrypoint service (for multi-instance "
                        "topologies; default: the first entrypoint)")
    s.add_argument("--flat", action="store_true",
                   help="print the flattened single-line record instead "
                        "of the full Fortio JSON")
    s.add_argument("--prometheus", metavar="FILE",
                   help="also write the Prometheus text exposition here")
    s.add_argument("--trace", metavar="FILE",
                   help="write sampled per-request spans here (the "
                        "reference's OTel->Jaeger tracing, "
                        "service/main.go:76-109)")
    s.add_argument("--trace-format", choices=["chrome", "jaeger"],
                   default="chrome")
    s.add_argument("--trace-requests", type=int, default=32,
                   help="how many requests to trace (sampled dense run)")
    s.add_argument("--telemetry", nargs="?", const="on",
                   choices=("on", "detail"), default=None,
                   help="emit engine self-telemetry: isotope_engine_* "
                        "series appended to --prometheus output, a "
                        "telemetry.jsonl record, and a summary block on "
                        "stderr.  'detail' additionally fences at "
                        "segment granularity (eager execution — for "
                        "diagnosis, not benchmarking).  Defaults the "
                        "persistent compile cache to .xla-cache so "
                        "repeated runs show cache hits")
    s.add_argument("--telemetry-out", metavar="FILE",
                   default="telemetry.jsonl",
                   help="where --telemetry appends its JSONL record")
    _add_attribution_args(s)
    s.add_argument("--blame-out", metavar="FILE", default=None,
                   help="write the blame tables as JSON "
                        "(isotope-blame/v1) instead of only printing "
                        "the table to stderr")
    s.add_argument("--flamegraph", metavar="FILE", default=None,
                   help="write the critical-path blame as a "
                        "collapsed-stack flamegraph file "
                        "(flamegraph.pl / speedscope input)")
    s.add_argument("--perfetto-blame", metavar="FILE", default=None,
                   help="write per-service blame-distribution counter "
                        "tracks as Perfetto/Chrome trace JSON")
    s.add_argument("--exemplar-trace", metavar="FILE", default=None,
                   help="write the mined top-K slowest requests as a "
                        "distributed trace (tail_rank/tail_cut "
                        "annotated spans; no dense re-run)")
    s.add_argument("--exemplar-format", choices=["chrome", "jaeger"],
                   default="jaeger")
    _add_timeline_args(s)
    _add_policies_args(s)
    s.add_argument("--policies-out", metavar="FILE", default=None,
                   help="write the policy actuation series as JSON "
                        "(isotope-policies/v1)")
    _add_rollouts_args(s)
    s.add_argument("--rollouts-out", metavar="FILE", default=None,
                   help="write the rollout trajectory (weight/step "
                        "series, promote/hold/rollback sim-time "
                        "onsets, per-arm error shares) as JSON "
                        "(isotope-rollout/v1)")
    s.add_argument("--lb-out", metavar="FILE", default=None,
                   help="write the load-balancing laws + per-window "
                        "per-backend load split as JSON "
                        "(isotope-lb/v1); laws come from the "
                        "topology's per-service `lb:` entries and "
                        "apply to EVERY run kind (no flag needed)")
    s.add_argument("--timeline-out", metavar="FILE", default=None,
                   help="write the windowed series as JSON "
                        "(isotope-timeline/v1)")
    s.add_argument("--timeline-perfetto", metavar="FILE", default=None,
                   help="write the windowed series as Perfetto/Chrome "
                        "counter tracks over real sim time")
    s.add_argument("--timeline-prometheus", metavar="FILE",
                   default=None,
                   help="write the timestamped Prometheus exposition "
                        "(one sample per window, like a scrape "
                        "sequence)")
    _add_ensemble_args(s)
    s.add_argument("--ensemble-out", metavar="FILE", default=None,
                   help="write the ensemble's distributional summary "
                        "as JSON (isotope-ensemble/v2)")
    _add_mesh_args(s)
    _add_resilience_args(s)
    _add_vet_arg(s)
    s.set_defaults(func=run_simulate)

    k = sub.add_parser(
        "check",
        help="simulate a topology and evaluate the stability alarm suite",
    )
    k.add_argument("topology")
    k.add_argument("--qps", default="1000")
    k.add_argument("--connections", "-c", type=int, default=64)
    k.add_argument("--duration", "-t", default="240s")
    k.add_argument("--load-kind", choices=["open", "closed"], default="open")
    k.add_argument("--max-requests", type=int, default=200_000)
    k.add_argument("--seed", type=int, default=0)
    k.add_argument("--cpu-limit", type=float, default=50.0,
                   help="per-service CPU alarm threshold, milli-cores "
                        "(the reference's load-test override is 250)")
    k.add_argument("--mem-limit", type=float, default=64.0,
                   help="per-service memory alarm threshold, MiB")
    k.add_argument("--debug", action="store_true",
                   help="print every query result")
    k.set_defaults(func=run_check)

    w = sub.add_parser("sweep", help="run a TOML-configured experiment")
    w.add_argument("config", help="experiment TOML (example-config.toml shape)")
    w.add_argument("--out", "-o", default="results",
                   help="output directory (default: ./results)")
    w.add_argument("--fresh", action="store_true",
                   help="ignore an existing checkpoint and rerun "
                        "everything (default: resume a killed sweep)")
    w.add_argument("--compile-cache", metavar="DIR", default=None,
                   help="persistent XLA compilation cache directory "
                        "(default: $ISOTOPE_COMPILE_CACHE)")
    w.add_argument("--profile", metavar="DIR",
                   help="capture a jax.profiler trace per run into "
                        "DIR/<label>/ (the reference's per-run flame "
                        "capture, runner.py:405-417)")
    w.add_argument("--export", action="append", default=[],
                   metavar="SPEC",
                   help="post-run exporter(s), e.g. "
                        "bigquery:project.dataset.table or "
                        "gcs:gs://bucket/path (the collector's upload "
                        "hook, fortio.py:235-242); repeatable")
    w.add_argument("--telemetry", nargs="?", const="on",
                   choices=("on", "detail"), default=None,
                   help="emit engine self-telemetry per run: "
                        "isotope_engine_* series in each .prom artifact "
                        "plus <out>/telemetry.jsonl ('detail' adds "
                        "segment fences — diagnosis, not benchmarking)")
    _add_attribution_args(w)
    _add_timeline_args(w)
    _add_policies_args(w)
    _add_rollouts_args(w)
    _add_ensemble_args(w)
    _add_mesh_args(w)
    _add_resilience_args(w)
    _add_vet_arg(w)
    w.set_defaults(func=run_sweep)

    p = sub.add_parser(
        "plot", help="plot latency/CPU curves from a sweep's benchmark.csv"
    )
    p.add_argument("csv", help="benchmark.csv from a sweep")
    p.add_argument("--x", choices=["conn", "qps"], default="conn")
    p.add_argument("--metrics", default="p50,p90,p99",
                   help="comma-separated columns (latency in us, or e.g. "
                        "cpu_cores_<service>)")
    p.add_argument("--series", default=None,
                   help="comma-separated series (default: all)")
    p.add_argument("--title", default=None)
    p.add_argument("-o", "--output", default="benchmark.png")
    p.set_defaults(func=run_plot)


def _require_jax() -> None:
    try:
        import jax  # noqa: F401
    except ModuleNotFoundError as e:
        raise ValueError(
            "the simulate/sweep commands need jax, which is not installed "
            "in this environment (the converter commands still work)"
        ) from e


def run_simulate(args) -> int:
    # jax-dependent imports stay inside the handler so `--help` is instant
    _require_jax()
    from isotope_tpu import telemetry
    from isotope_tpu.commands.common import (
        arm_telemetry,
        default_compile_cache,
    )
    from isotope_tpu.compiler.cache import enable_persistent_cache

    arm_telemetry(args.telemetry)
    # any explicit env setting — including the disable values "", "0",
    # "off", "none" — wins over the telemetry-run cache default
    args.compile_cache = default_compile_cache(
        args.compile_cache, args.telemetry
    )
    enable_persistent_cache(args.compile_cache)
    from isotope_tpu.runner.config import (
        DEFAULT_ENVIRONMENTS,
        ExperimentConfig,
    )
    from isotope_tpu.runner.run import run_experiment

    if args.environment not in DEFAULT_ENVIRONMENTS:
        raise ValueError(
            f"unknown environment {args.environment!r} "
            f"(expected one of {sorted(DEFAULT_ENVIRONMENTS)})"
        )
    qps = None if args.qps == "max" else float(args.qps)
    extra = {}
    if args.cpu_time is not None:
        extra["cpu_time_s"] = dur.parse_duration_seconds(args.cpu_time)
    if args.service_time_param is not None:
        extra["service_time_param"] = args.service_time_param
    elif args.service_time == "pareto":
        extra["service_time_param"] = 1.5  # a sane heavy-tail default
    tl_window = _timeline_window(args)
    config = ExperimentConfig(
        topology_paths=(args.topology,),
        environments=(DEFAULT_ENVIRONMENTS[args.environment],),
        qps=(qps,),
        connections=(args.connections,),
        duration_s=dur.parse_duration_seconds(args.duration),
        load_kind=args.load_kind,
        num_requests=args.max_requests,
        seed=args.seed,
        labels=args.labels,
        service_time=args.service_time,
        entry=args.entry,
        attribution=args.attribution is not None,
        timeline=tl_window is not None,
        policies=args.policies,
        rollouts=args.rollouts,
        mesh_spec=args.mesh,
        overlap=args.overlap,
        **_ensemble_config_kwargs(args),
        **extra,
    )
    (result,) = run_experiment(config, policy=_policy(args),
                               vet=args.vet,
                               attribution=args.attribution,
                               timeline=tl_window)
    if result.failed:
        print(f"error: run failed: {result.error}", file=sys.stderr)
        return 1
    if args.attribution and result.blame is not None:
        from isotope_tpu.metrics import attribution as attr_mod

        print(attr_mod.format_table(result.blame), file=sys.stderr)
        if args.blame_out:
            with open(args.blame_out, "w") as f:
                json.dump(result.blame, f, indent=2)
            print(f"blame tables -> {args.blame_out}", file=sys.stderr)
        if result.attribution is not None:
            _write_attribution_artifacts(args, result)
    elif args.attribution:
        print(
            "warning: attribution pass produced no blame document",
            file=sys.stderr,
        )
    if args.policies and result.policies is not None:
        from isotope_tpu.sim import policies as policies_mod

        print(policies_mod.format_table(result.policies),
              file=sys.stderr)
        if args.policies_out:
            with open(args.policies_out, "w") as f:
                json.dump(result.policies, f, indent=2)
            print(f"policies -> {args.policies_out}", file=sys.stderr)
    elif args.policies:
        print(
            "warning: --policies set but the topology declares no "
            "policies block (unprotected run)",
            file=sys.stderr,
        )
    if args.rollouts and result.rollouts is not None:
        from isotope_tpu.sim import rollout as rollout_mod

        print(rollout_mod.format_table(result.rollouts),
              file=sys.stderr)
        if args.rollouts_out:
            with open(args.rollouts_out, "w") as f:
                json.dump(result.rollouts, f, indent=2)
            print(f"rollouts -> {args.rollouts_out}", file=sys.stderr)
    elif args.rollouts:
        print(
            "warning: --rollouts set but the topology declares no "
            "active rollouts block (open-loop run)",
            file=sys.stderr,
        )
    if result.ensemble is not None:
        d = result.ensemble
        band = d["quantile_band_p99"]
        line = (
            f"ensemble: {d['members']} members (chunk {d['chunk']}): "
            f"p99 band [{band['lo_s'] * 1e3:.2f}, "
            f"{band['mid_s'] * 1e3:.2f}, {band['hi_s'] * 1e3:.2f}] ms"
        )
        if "slo" in d:
            s = d["slo"]
            line += (
                f"; P(p{s['quantile'] * 100:g} > "
                f"{s['slo_s'] * 1e3:g}ms) = {s['p_violation']:.3f} "
                f"[{s['ci_lo']:.3f}, {s['ci_hi']:.3f}] "
                f"@{s['confidence']:.0%}"
            )
        print(line, file=sys.stderr)
        if args.ensemble_out:
            with open(args.ensemble_out, "w") as f:
                json.dump(d, f, indent=2)
            print(f"ensemble -> {args.ensemble_out}", file=sys.stderr)
    elif args.ensemble:
        print(
            "warning: --ensemble set but the run was not served by a "
            "fleet dispatch (protected co-sim runs and fleet "
            "failures fall back to the solo path)",
            file=sys.stderr,
        )
    if result.lb is not None:
        from isotope_tpu.sim import lb as lb_mod

        print(lb_mod.format_table(result.lb), file=sys.stderr)
        if args.lb_out:
            with open(args.lb_out, "w") as f:
                json.dump(result.lb, f, indent=2)
            print(f"lb -> {args.lb_out}", file=sys.stderr)
    elif args.lb_out:
        print(
            "warning: --lb-out set but the topology declares no "
            "lb entries (fifo everywhere)",
            file=sys.stderr,
        )
    if (tl_window is not None or args.policies or args.rollouts) \
            and result.timeline is not None:
        _write_timeline_artifacts(args, result)
    elif tl_window is not None:
        print(
            "warning: timeline pass produced no windowed series",
            file=sys.stderr,
        )
    doc = result.flat if args.flat else result.fortio_json
    json.dump(doc, sys.stdout, indent=None if args.flat else 2)
    sys.stdout.write("\n")
    if args.prometheus:
        with open(args.prometheus, "w") as f:
            f.write(result.prometheus_text)
    if args.telemetry and result.telemetry is not None:
        rec = telemetry.RunTelemetry.from_dict(result.telemetry)
        rec.append_jsonl(args.telemetry_out)
        print(f"{telemetry.summary_line()} -> {args.telemetry_out}",
              file=sys.stderr)
    if args.trace:
        # traces are sampled: re-run a small dense batch (the load path
        # keeps only histograms, like the reference's samplers)
        import jax

        from isotope_tpu.compiler import compile_graph
        from isotope_tpu.metrics.trace import write_trace
        from isotope_tpu.models.graph import ServiceGraph
        from isotope_tpu.sim.engine import Simulator

        # identical model to the main run: same compiled graph shape
        # (including the entrypoint override), same env-applied params,
        # same load grid (of one), same chaos
        compiled = compile_graph(
            ServiceGraph.from_yaml_file(args.topology), entry=config.entry
        )
        sim = Simulator(
            compiled,
            config.environments[0].apply(config.sim_params()),
            config.chaos,
            config.churn,
            mtls=config.mtls,
        )
        (load,) = config.load_models()
        res = sim.run(load, args.trace_requests,
                      jax.random.PRNGKey(args.seed))
        traced = write_trace(args.trace, compiled, res,
                             fmt=args.trace_format)
        print(f"traced {traced} requests -> {args.trace}",
              file=sys.stderr)
    if result.window.discarded:
        print(
            f"warning: run would be discarded by the collector: "
            f"{result.window.discard_reason}",
            file=sys.stderr,
        )
    return 0


def _write_attribution_artifacts(args, result) -> None:
    """The attributed run's visual artifacts (simulate-only flags)."""
    from isotope_tpu.metrics.export import (
        write_flamegraph,
        write_perfetto_counters,
    )

    attr = result.attribution
    if not (args.flamegraph or args.perfetto_blame
            or args.exemplar_trace):
        return
    # the runner carries the exact CompiledGraph the blame vectors are
    # indexed by; recompile only as a fallback
    compiled = result.compiled
    if compiled is None:
        from isotope_tpu.compiler import compile_graph
        from isotope_tpu.models.graph import ServiceGraph

        compiled = compile_graph(
            ServiceGraph.from_yaml_file(args.topology),
            entry=args.entry,
        )
    if args.flamegraph:
        lines = write_flamegraph(args.flamegraph, compiled, attr)
        print(f"flamegraph ({lines} stacks) -> {args.flamegraph}",
              file=sys.stderr)
    if args.perfetto_blame:
        n = write_perfetto_counters(args.perfetto_blame, compiled, attr)
        print(f"perfetto counters ({n} events) -> "
              f"{args.perfetto_blame}", file=sys.stderr)
    if args.exemplar_trace:
        if attr.exemplars is None:
            print("warning: no exemplars mined "
                  "(attribution_top_k == 0)", file=sys.stderr)
            return
        from isotope_tpu.metrics.trace import write_trace

        traced = write_trace(
            args.exemplar_trace, compiled,
            fmt=args.exemplar_format, exemplars=attr,
        )
        print(f"traced {traced} tail exemplars -> "
              f"{args.exemplar_trace}", file=sys.stderr)


def _write_timeline_artifacts(args, result) -> None:
    """The flight recorder's artifacts (simulate-only flags): the
    per-window table on stderr, plus the JSON / Perfetto / timestamped
    Prometheus files when requested."""
    from isotope_tpu.metrics import timeline as timeline_mod

    print(timeline_mod.format_table(result.timeline), file=sys.stderr)
    if args.timeline_out:
        with open(args.timeline_out, "w") as f:
            json.dump(result.timeline, f, indent=2)
        print(f"timeline -> {args.timeline_out}", file=sys.stderr)
    needs_summary = args.timeline_perfetto or args.timeline_prometheus
    if not needs_summary:
        return
    tl = result.timeline_summary
    compiled = result.compiled
    if tl is None or compiled is None:
        print(
            "warning: timeline summary unavailable; perfetto/"
            "prometheus artifacts skipped",
            file=sys.stderr,
        )
        return
    if args.timeline_perfetto:
        from isotope_tpu.metrics.export import write_timeline_perfetto

        n = write_timeline_perfetto(args.timeline_perfetto, compiled, tl)
        print(f"timeline counters ({n} events) -> "
              f"{args.timeline_perfetto}", file=sys.stderr)
    if args.timeline_prometheus:
        with open(args.timeline_prometheus, "w") as f:
            f.write(timeline_mod.prometheus_text(compiled, tl))
        print(f"timestamped exposition -> {args.timeline_prometheus}",
              file=sys.stderr)


def run_check(args) -> int:
    _require_jax()
    import pathlib

    import jax

    from isotope_tpu.compiler import compile_graph
    from isotope_tpu.metrics.alarms import (
        requests_sanity,
        run_queries,
        standard_queries,
        store_from_summary,
    )
    from isotope_tpu.metrics.prometheus import MetricsCollector
    from isotope_tpu.models.graph import ServiceGraph
    from isotope_tpu.sim.config import LoadModel
    from isotope_tpu.sim.engine import Simulator

    compiled = compile_graph(ServiceGraph.from_yaml_file(args.topology))
    qps = None if args.qps == "max" else float(args.qps)
    load = LoadModel(
        kind=args.load_kind,
        qps=qps,
        connections=args.connections,
        duration_s=dur.parse_duration_seconds(args.duration),
    )
    sim = Simulator(compiled)
    collector = MetricsCollector(compiled)
    rate = qps if qps is not None else sim.capacity_qps()
    n = max(1, min(int(rate * load.duration_s), args.max_requests))
    summary = sim.run_summary(
        load, n, jax.random.PRNGKey(args.seed),
        block_size=sim.default_block_size(), collector=collector,
    )
    label = pathlib.Path(args.topology).stem
    queries = standard_queries(
        label, cpu_lim=args.cpu_limit, mem_lim=args.mem_limit
    ) + [requests_sanity(label)]
    errors = run_queries(
        queries, store_from_summary(collector, summary), debug=args.debug,
        log=lambda m: print(m, file=sys.stderr),
    )
    for e in errors:
        print(f"ALARM: {e}", file=sys.stderr)
    print(
        f"{len(queries) - len(errors)}/{len(queries)} checks passed",
        file=sys.stderr,
    )
    return 1 if errors else 0


def run_plot(args) -> int:
    from isotope_tpu.plotting import plot_benchmark

    plotted = plot_benchmark(
        args.csv,
        args.output,
        x_axis=args.x,
        metrics=[m.strip() for m in args.metrics.split(",") if m.strip()],
        series=(
            [s.strip() for s in args.series.split(",")]
            if args.series
            else None
        ),
        title=args.title,
    )
    print(f"plotted {len(plotted)} series -> {args.output}", file=sys.stderr)
    return 0


def run_sweep(args) -> int:
    _require_jax()
    import dataclasses

    from isotope_tpu.commands.common import arm_telemetry
    from isotope_tpu.compiler.cache import enable_persistent_cache

    arm_telemetry(args.telemetry)
    enable_persistent_cache(args.compile_cache)
    from isotope_tpu.runner.config import load_toml
    from isotope_tpu.runner.run import run_experiment

    config = load_toml(args.config)
    if args.attribution and not config.attribution:
        config = dataclasses.replace(config, attribution=True)
    if args.mesh:
        config = dataclasses.replace(config, mesh_spec=args.mesh)
    if args.overlap and not config.overlap:
        config = dataclasses.replace(config, overlap=True)
    if args.policies and not config.policies:
        config = dataclasses.replace(config, policies=True)
    if args.rollouts and not config.rollouts:
        config = dataclasses.replace(config, rollouts=True)
    ens_kw = _ensemble_config_kwargs(args)
    if ens_kw:
        config = dataclasses.replace(config, **ens_kw)
    tl_window = _timeline_window(args)
    if tl_window is None and config.timeline:
        # [sim] timeline = true in the TOML arms the pass without a
        # CLI flag
        tl_window = config.timeline_window_s
    if tl_window is not None and not config.timeline:
        config = dataclasses.replace(
            config, timeline=True, timeline_window_s=tl_window
        )
    results = run_experiment(
        config,
        out_dir=args.out,
        progress=lambda label: print(f"running {label}", file=sys.stderr),
        resume=not args.fresh,
        profile_dir=args.profile,
        export=args.export,
        policy=_policy(args),
        vet=args.vet,
        attribution=args.attribution,
        timeline=tl_window,
    )
    discarded = [r.label for r in results if r.window.discarded]
    failed = [r.label for r in results if r.failed]
    degraded = [r.label for r in results if r.degraded_to is not None]
    print(
        f"{len(results)} runs -> {args.out}/ "
        f"({len(discarded)} would be discarded by the collector)",
        file=sys.stderr,
    )
    if degraded:
        print(
            f"{len(degraded)} run(s) completed DEGRADED: "
            f"{', '.join(degraded)}",
            file=sys.stderr,
        )
    if failed:
        # the failed cases are checkpointed: the same invocation
        # retries exactly them
        print(
            f"{len(failed)} run(s) FAILED (recorded in the checkpoint; "
            f"re-run to retry): {', '.join(failed)}",
            file=sys.stderr,
        )
        return 1
    return 0
