"""``isotope-tpu generate`` subcommand: synthetic topologies.

Mirrors isotope/create_tree_topology.py and create_realistic_topology.py,
with the constants promoted to flags (the reference Makefile passes --type
flags the scripts never parsed — isotope/Makefile:30-72 vs
create_realistic_topology.py:159-165; here they work).
"""
from __future__ import annotations

import sys

import yaml

from isotope_tpu.models import generators


def register(sub) -> None:
    gen = sub.add_parser("generate", help="generate a topology YAML")
    kind = gen.add_subparsers(dest="kind", required=True)

    tree = kind.add_parser("tree", help="BFS-complete tree topology")
    tree.add_argument("--levels", type=int, default=3)
    tree.add_argument("--branches", type=int, default=3)
    tree.add_argument("--request-size", type=int, default=128)
    tree.add_argument("--response-size", type=int, default=128)
    tree.add_argument("--num-replicas", type=int, default=1)
    tree.add_argument(
        "--sleep", default=None, help='per-service sleep, e.g. "10ms"'
    )
    tree.add_argument(
        "--num-services", type=int, default=None,
        help="cap the tree at exactly this many services",
    )
    tree.add_argument(
        "--instances", type=int, default=1,
        help="replicate the topology N times with namespaced service "
             "names (perf/load/common.sh's N-namespace fan-out)",
    )
    tree.add_argument("-o", "--output", default=None)
    tree.set_defaults(func=run_tree)

    real = kind.add_parser(
        "realistic", help="scale-free Barabasi-Albert topology"
    )
    real.add_argument("--services", type=int, default=10)
    real.add_argument(
        "--type",
        dest="archetype",
        default="multitier",
        choices=sorted(generators.ARCHETYPES),
    )
    real.add_argument("--request-size", type=int, default=128)
    real.add_argument("--response-size", type=int, default=128)
    real.add_argument("--num-replicas", type=int, default=1)
    real.add_argument("--seed", type=int, default=0)
    real.add_argument(
        "--instances", type=int, default=1,
        help="replicate the topology N times with namespaced service "
             "names (perf/load/common.sh's N-namespace fan-out)",
    )
    real.add_argument("-o", "--output", default=None)
    real.set_defaults(func=run_realistic)

    pl = kind.add_parser(
        "powerlaw",
        help="Zipf out-degree topology (production-shaped fan-out "
             "skew; the ingest self-closure fixture family)",
    )
    pl.add_argument("--services", type=int, default=100)
    pl.add_argument("--exponent", type=float, default=2.0)
    pl.add_argument("--max-degree", type=int, default=None)
    pl.add_argument("--request-size", type=int, default=128)
    pl.add_argument("--response-size", type=int, default=128)
    pl.add_argument("--num-replicas", type=int, default=1)
    pl.add_argument("--seed", type=int, default=0)
    pl.add_argument(
        "--sleep-choices", default=None,
        help='comma-separated per-service sleep pool, e.g. "1ms,4ms"',
    )
    pl.add_argument(
        "--error-rate-choices", default=None,
        help='comma-separated errorRate pool, e.g. "0%%,1%%,2%%"',
    )
    pl.add_argument("-o", "--output", default=None)
    pl.set_defaults(func=run_powerlaw)


def _emit(doc: dict, output) -> int:
    text = yaml.safe_dump(doc, default_flow_style=False, sort_keys=False)
    if output:
        with open(output, "w") as f:
            f.write(text)
    else:
        sys.stdout.write(text)
    return 0


def run_tree(args) -> int:
    doc = generators.tree_topology(
        num_levels=args.levels,
        num_branches=args.branches,
        request_size=args.request_size,
        response_size=args.response_size,
        num_replicas=args.num_replicas,
        sleep=args.sleep,
        num_services=args.num_services,
    )
    return _emit(
        generators.replicate_topology(doc, args.instances), args.output
    )


def run_realistic(args) -> int:
    doc = generators.realistic_topology(
        num_services=args.services,
        archetype=args.archetype,
        request_size=args.request_size,
        response_size=args.response_size,
        num_replicas=args.num_replicas,
        seed=args.seed,
    )
    return _emit(
        generators.replicate_topology(doc, args.instances), args.output
    )


def run_powerlaw(args) -> int:
    doc = generators.powerlaw_topology(
        num_services=args.services,
        exponent=args.exponent,
        max_degree=args.max_degree,
        request_size=args.request_size,
        response_size=args.response_size,
        num_replicas=args.num_replicas,
        seed=args.seed,
        sleep_choices=(
            args.sleep_choices.split(",") if args.sleep_choices else None
        ),
        error_rate_choices=(
            args.error_rate_choices.split(",")
            if args.error_rate_choices else None
        ),
    )
    return _emit(doc, args.output)


def register_pilot(sub) -> None:
    p = sub.add_parser(
        "pilot-load",
        help="model config-push convergence vs ServiceEntry count "
             "(perf/load/pilot/load_test.py analogue)",
    )
    p.add_argument("--entries", default="10,100,1000",
                   help="comma-separated ServiceEntry counts")
    p.add_argument("--endpoints", type=int, default=10,
                   help="endpoints per entry")
    p.add_argument("--proxies", type=int, default=100,
                   help="number of sidecars receiving pushes")
    p.add_argument("--push-throttle", type=int, default=100)
    p.add_argument("--seed", type=int, default=0)
    p.set_defaults(func=run_pilot_load)


def run_pilot_load(args) -> int:
    import json as _json
    import sys as _sys

    from isotope_tpu.sim.controlplane import (
        PilotModel,
        convergence_sweep,
    )

    model = PilotModel(push_throttle=args.push_throttle)
    rows = convergence_sweep(
        model,
        [int(x) for x in args.entries.split(",") if x.strip()],
        args.endpoints,
        args.proxies,
        seed=args.seed,
    )
    for row in rows:
        _sys.stdout.write(_json.dumps(row) + "\n")
    return 0
