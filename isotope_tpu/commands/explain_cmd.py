"""``isotope-tpu explain`` — narrate WHY from a fleet's artifacts.

Fleet runs leave evidence on disk (runner/run.py): the
``isotope-fleet-blame/v1`` divergence doc (``<label>.fleet-blame.json``
— per-hop blame-share bands across members, control deltas, onset
windows), the worst member's stamped postmortem docs
(``<label>.blame.json`` / ``.timeline.json`` with the member's RNG
replay recipe), and the ``isotope-search/v1`` bracket lineage
(``<label>.search.json`` with per-rung cut lines and cost evidence).
This command turns those artifacts into a ranked "why" report —
WITHOUT re-running anything:

- fleet-blame docs render the worst members' narratives: which hop's
  blame share departed the member band, by how much vs the control
  member, and WHEN the divergence started (the recorder onset);
- search docs narrate the bracket: per rung, who was cut at what
  rank-channel value, how close the cut was, what the rung cost
  (engine traces, compile wall), and why the winner beat the
  runner-up;
- ``isotope-ingest/v1`` docs (``<label>.ingest.json``, the ``ingest``
  subcommand's fit-fidelity report) render coverage accounting,
  per-service fitted-vs-observed values, everything dropped with its
  reason, and the self-closure verdict when present.

Point it at a runner ``--out`` directory to explain every fleet in
it, or at one artifact file.
"""
from __future__ import annotations

import json
import pathlib
import sys


def register(sub) -> None:
    e = sub.add_parser(
        "explain",
        help="narrate why fleet members diverged / a search winner "
             "won, from run artifacts alone",
    )
    e.add_argument(
        "path",
        help="a runner --out directory, a <label>.fleet-blame.json, "
             "a <label>.search.json, or a <label>.ingest.json",
    )
    e.add_argument("--label", default=None,
                   help="only runs whose label contains this "
                        "substring (directory mode)")
    e.add_argument("--top", type=int, default=3,
                   help="worst members to narrate per fleet")
    e.add_argument("--hops", type=int, default=3,
                   help="hops to show per member narrative")
    e.add_argument("--json", action="store_true",
                   help="emit the collected explanation docs as JSON "
                        "instead of the report")
    e.set_defaults(func=run_explain_cmd)


def _load(path: pathlib.Path) -> dict:
    with open(path) as f:
        return json.load(f)


def _replay_stamp(doc: dict) -> str:
    """The worst member's RNG replay recipe off a stamped postmortem
    doc (runner/run.py stamps member/member_seed/member_key)."""
    parts = [f"member {doc.get('member')}"]
    if doc.get("member_seed") is not None:
        parts.append(f"seed {doc['member_seed']}")
    if doc.get("member_key"):
        parts.append(f"key = {doc['member_key']}")
    elif doc.get("member_seed") is not None:
        parts.append("key = fold_in(cell_key, seed)")
    return ", ".join(parts)


def _fleet_section(fb_path: pathlib.Path, top: int, hops: int,
                   fleetblame) -> str:
    doc = _load(fb_path)
    label = doc.get("label") or fb_path.name.replace(
        ".fleet-blame.json", ""
    )
    lines = [f"== {label} =="]
    lines.append(fleetblame.format_report(doc, top=top, hops=hops))
    # the stamped worst-member postmortems sitting next to the fleet
    # doc carry the replay recipe
    stem = fb_path.name[: -len(".fleet-blame.json")]
    for suffix, what in (
        (".blame.json", "blame postmortem"),
        (".timeline.json", "timeline postmortem"),
        (".policies.json", "policy postmortem"),
    ):
        p = fb_path.with_name(stem + suffix)
        if not p.exists():
            continue
        d = _load(p)
        if d.get("worst_member"):
            lines.append(
                f"  replay: {p.name} pins the worst member "
                f"({_replay_stamp(d)})"
            )
    return "\n".join(lines)


def _bracket_report(doc: dict) -> str:
    """Narrate an isotope-search/v1 bracket from its lineage."""
    winner = doc["winner"]
    wid = int(winner["candidate"])
    lineage = doc.get("lineage", [])
    lines = [
        f"search bracket ({doc.get('label') or 'unlabeled'}): "
        f"{doc['candidates']} candidates -> winner {wid} "
        f"({doc['rank_effective']} severity "
        f"{winner['severity']:.6g}) in {len(lineage)} rungs, "
        f"{doc.get('traces', '?')} engine traces, mode {doc['mode']}"
    ]
    for r in lineage:
        sev = r["severity"]
        cands = r["candidates"]
        ev = r.get("evidence") or {}
        cost = ""
        if ev:
            cost = (
                f"  [traces {ev.get('traces', 0)}, compile "
                f"{ev.get('compile_s', 0.0):.2f}s]"
            )
        lines.append(
            f"rung {r['rung']}: width {r['width']} (chunk "
            f"{r['chunk']}), blocks {r['start_block']}-"
            f"{r['start_block'] + r['num_blocks']}, "
            f"{r['cum_requests']} cumulative requests{cost}"
        )
        cut = r.get("cut")
        if cut is not None:
            kept = cut["last_kept"]
            line = (
                f"  kept {cut['kept']} of {r['width']}; cut line: "
                f"candidate {kept['candidate']} "
                f"(sev {kept['severity']:.6g}) kept"
            )
            fc = cut.get("first_cut")
            if fc is not None:
                line += (
                    f" vs candidate {fc['candidate']} "
                    f"(sev {fc['severity']:.6g}) cut — margin "
                    f"{cut['margin']:.6g}"
                )
            lines.append(line)
        if wid in cands:
            row = cands.index(wid)
            rank = None
            ro = ev.get("rank_order")
            if ro is not None and wid in ro:
                rank = ro.index(wid)
            where = (
                f"ranked #{rank + 1}" if rank is not None
                else "present"
            )
            lines.append(
                f"  winner {wid} {where} (sev {sev[row]:.6g})"
            )
    # the final-rung "why": winner vs runner-up on the rank channel
    if lineage:
        last = lineage[-1]
        ro = (last.get("evidence") or {}).get("rank_order")
        if ro and len(ro) > 1:
            ru = ro[1]
            cands = last["candidates"]
            sev = last["severity"]
            try:
                gap = sev[cands.index(ru)] - sev[cands.index(wid)]
                lines.append(
                    f"why: winner {wid} beat runner-up {ru} by "
                    f"{gap:.6g} on {doc['rank_effective']} at the "
                    f"final horizon ({last['cum_requests']} requests)"
                )
            except ValueError:
                pass
    return "\n".join(lines)


def _search_section(path: pathlib.Path) -> str:
    doc = _load(path)
    if doc.get("schema") != "isotope-search/v1":
        raise ValueError(
            f"{path}: not an isotope-search/v1 document "
            f"({doc.get('schema')!r})"
        )
    label = doc.get("label") or path.name.replace(".search.json", "")
    return f"== {label} ==\n" + _bracket_report(doc)


def _ingest_section(path: pathlib.Path, top: int) -> str:
    from isotope_tpu.ingest import report as ingest_report

    doc = ingest_report.load_doc(str(path))
    label = doc.get("label") or path.name.replace(".ingest.json", "")
    return f"== {label} ==\n" + ingest_report.format_report(
        doc, top=top
    )


def run_explain_cmd(args) -> int:
    # fleet-blame rendering lives with the explainer math; the import
    # is deferred so --help stays instant (commands/__init__ idiom)
    from isotope_tpu.metrics import fleetblame

    root = pathlib.Path(args.path)
    fleet_docs, search_docs, ingest_docs = [], [], []
    if root.is_dir():
        match = (args.label or "")
        fleet_docs = sorted(
            p for p in root.glob("*.fleet-blame.json")
            if match in p.name
        )
        search_docs = sorted(
            p for p in root.glob("*.search.json") if match in p.name
        )
        ingest_docs = sorted(
            p for p in root.glob("*.ingest.json") if match in p.name
        )
    elif root.name.endswith(".search.json"):
        search_docs = [root]
    elif root.name.endswith(".ingest.json"):
        ingest_docs = [root]
    else:
        fleet_docs = [root]
    if not fleet_docs and not search_docs and not ingest_docs:
        print(
            f"explain: no *.fleet-blame.json, *.search.json, or "
            f"*.ingest.json under {root} — run with --attribution "
            f"over an --ensemble (or --search / ingest) first",
            file=sys.stderr,
        )
        return 1

    if args.json:
        out = {
            "fleets": [_load(p) for p in fleet_docs],
            "searches": [_load(p) for p in search_docs],
            "ingests": [_load(p) for p in ingest_docs],
        }
        json.dump(out, sys.stdout, indent=2)
        sys.stdout.write("\n")
        return 0

    sections = [
        _fleet_section(p, args.top, args.hops, fleetblame)
        for p in fleet_docs
    ]
    sections += [_search_section(p) for p in search_docs]
    sections += [_ingest_section(p, args.top) for p in ingest_docs]
    print("\n\n".join(sections))
    return 0
