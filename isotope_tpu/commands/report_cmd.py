"""``isotope-tpu report`` — dashboard-lite over sweep artifacts.

The one-file replacement for the reference's Django dashboard
(perf_dashboard/benchmarks/views.py): latency/CPU/error charts per
series, the full results table, and a run-vs-run regression view when a
baseline sweep directory is given.
"""
from __future__ import annotations

import sys


def register(sub) -> None:
    r = sub.add_parser(
        "report",
        help="render a sweep's results.jsonl as a static HTML report",
    )
    r.add_argument("results", help="sweep output directory (or, with "
                                   "--history, a directory of publish "
                                   "trees)")
    r.add_argument("--baseline", metavar="DIR",
                   help="another sweep to diff against (regression view)")
    r.add_argument("--history", action="store_true",
                   help="treat RESULTS as a directory of "
                        "<date>_<loadgen>_<branch>_<ver> publish trees "
                        "and render metric-over-time series (the "
                        "reference dashboard's day-over-day view)")
    r.add_argument("--lineage", default=None, metavar="SUBSTR",
                   help="with --history: select one publish lineage "
                        "(substring of the id suffix after the date) "
                        "when the directory holds several")
    r.add_argument("--title", default=None)
    r.add_argument("-o", "--output", default="report.html")
    r.set_defaults(func=run_report)


def run_report(args) -> int:
    from isotope_tpu.report import write_history_report, write_report

    if args.history:
        if args.baseline:
            print("--baseline is ignored with --history", file=sys.stderr)
        count = write_history_report(
            args.results, args.output, title=args.title,
            lineage=args.lineage,
        )
        print(f"{count} publishes -> {args.output}", file=sys.stderr)
        return 0
    if args.lineage:
        print("--lineage is ignored without --history", file=sys.stderr)
    count = write_report(
        args.results, args.output,
        baseline_dir=args.baseline, title=args.title,
    )
    print(f"{count} runs -> {args.output}", file=sys.stderr)
    return 0
