"""``isotope-tpu vet`` — static program & config analysis.

Lints topology YAMLs / sweep TOMLs, audits the jaxpr the engine would
jit (trace-only; nothing executes on a device), and runs the
pre-flight cost model.  Exit status: 0 clean, 1 when any error-severity
finding survives suppression (``--strict`` promotes warnings), 2 on
usage errors — the shape of ``go vet``.
"""
from __future__ import annotations

import sys

from isotope_tpu.utils import duration as dur


def register(sub) -> None:
    s = sub.add_parser(
        "vet",
        help="static analysis: lint topologies/configs, audit the "
             "traced program, model pre-flight cost",
    )
    s.add_argument("paths", nargs="+", metavar="PATH",
                   help="topology YAMLs and/or experiment TOMLs "
                        "(.toml runs the config linter over the whole "
                        "sweep grid first)")
    s.add_argument("--strict", action="store_true",
                   help="promote warnings to blocking (exit 1)")
    s.add_argument("--suppress", default=None, metavar="RULES",
                   help="comma-separated rule ids/globs to suppress, "
                        "e.g. VET-J003,VET-T00* (also "
                        "$ISOTOPE_VET_SUPPRESS)")
    s.add_argument("--json", action="store_true",
                   help="machine-readable report on stdout")
    s.add_argument("--no-trace", action="store_true",
                   help="skip the jaxpr audit / traced cost model "
                        "(lint + plan-table estimates only)")
    s.add_argument("--grad", action="store_true",
                   help="run the gradient audit (VET-G rules): "
                        "classify every registered design knob as "
                        "differentiable / gradient-dead / "
                        "trace-constant (off by default: traces the "
                        "knob-armed engine body)")
    s.add_argument("--grad-json", default=None, metavar="PATH",
                   help="write the isotope-gradaudit/v1 artifact "
                        "(the optimize relaxation worklist) to PATH; "
                        "implies --grad")
    s.add_argument("--entry", default=None,
                   help="entrypoint override for multi-entry "
                        "topologies")
    s.add_argument("--qps", default="1000",
                   help='planned load for the audit/cost model, or '
                        '"max"')
    s.add_argument("--connections", "-c", type=int, default=64)
    s.add_argument("--load-kind", choices=["open", "closed"],
                   default="open")
    s.add_argument("--duration", "-t", default="240s")
    s.add_argument("--device-bytes", type=float, default=None,
                   metavar="N",
                   help="device memory capacity for the OOM verdict "
                        "(default: $ISOTOPE_VET_DEVICE_BYTES, then the "
                        "backend's memory_stats; unknown on CPU)")
    s.set_defaults(func=run_vet)


def _collect_grad_docs(path, meta, out) -> None:
    """Pull per-topology gradient-audit documents out of a report's
    meta (a topology vet puts the doc at ``meta['grad']``; a sweep
    TOML nests one per referenced topology path)."""
    if "grad" in meta:
        out.append(dict(meta["grad"], topology=str(path)))
    for k, v in meta.items():
        if k != "grad" and isinstance(v, dict) and "grad" in v:
            out.append(dict(v["grad"], topology=str(k)))


def run_vet(args) -> int:
    from isotope_tpu.analysis import (
        Report,
        default_suppressions,
        suppression_patterns,
        vet_config_path,
        vet_topology_path,
    )
    from isotope_tpu.sim.config import LoadModel

    suppress = default_suppressions()
    if args.suppress:
        suppress += suppression_patterns(args.suppress)
    load = LoadModel(
        kind=args.load_kind,
        qps=None if args.qps == "max" else float(args.qps),
        connections=args.connections,
        duration_s=dur.parse_duration_seconds(args.duration),
    )

    grad = bool(args.grad or args.grad_json)
    merged = Report(suppress=())
    grad_docs = []
    for path in args.paths:
        if str(path).endswith(".toml"):
            rep = vet_config_path(
                path, trace=not args.no_trace,
                device_bytes=args.device_bytes, suppress=suppress,
                grad=grad,
            )
        else:
            rep = vet_topology_path(
                path, load=load, entry=args.entry,
                trace=not args.no_trace,
                device_bytes=args.device_bytes, suppress=suppress,
                grad=grad,
            )
        merged.findings.extend(rep.findings)
        merged.suppressed.extend(rep.suppressed)
        if rep.meta:
            merged.meta[str(path)] = rep.meta
        _collect_grad_docs(path, rep.meta, grad_docs)

    if args.grad_json:
        import json

        from isotope_tpu.analysis.grad_audit import SCHEMA

        with open(args.grad_json, "w") as f:
            json.dump(
                {"schema": SCHEMA, "audits": grad_docs},
                f, indent=2, sort_keys=True,
            )
            f.write("\n")

    if args.json:
        print(merged.to_json())
    else:
        for f in merged.sorted():
            print(f.render())
        print(merged.summary_line(), file=sys.stderr)

    blocking = merged.blocking(strict=args.strict)
    return 1 if blocking else 0
