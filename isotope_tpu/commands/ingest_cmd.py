"""``isotope-tpu ingest``: telemetry in, runnable topology out.

Host-only (no jax): reads Prometheus/OpenMetrics expositions, Envoy
``/stats`` cluster JSON, and CSV span traces (see README "Trace-driven
ingest" for the schema), fits a topology + load schedule, and writes

- ``<label>.yaml``   — the fitted topology (validated through
  ServiceGraph.decode before it is written);
- ``<label>.toml``   — a runnable ``[client]``/``[sim]`` experiment
  config (validated through runner.config.load_toml);
- ``<label>.ingest.json`` — the isotope-ingest/v1 fit-fidelity report
  (coverage, residuals, per-service fitted-vs-observed), rendered by
  ``isotope-tpu explain``.

The fitted topology is linted on the way out (topology rules plus the
ingest-specific VET-T027/VET-T028); findings print to stderr but do
not fail the command — the artifacts carry the evidence either way.
"""
from __future__ import annotations

import os
import sys

import yaml

from isotope_tpu.utils import duration as dur


def register(sub) -> None:
    p = sub.add_parser(
        "ingest",
        help="fit observed telemetry into a topology + load schedule",
    )
    p.add_argument(
        "inputs", nargs="+",
        help="telemetry files: Prometheus/OpenMetrics text, Envoy "
             "/stats JSON (.json), or CSV span traces (.csv)",
    )
    p.add_argument(
        "--format", default="auto",
        choices=["auto", "prometheus", "envoy", "csv"],
        help="pin the input format (default: sniff per file extension)",
    )
    p.add_argument("--label", default="ingested")
    p.add_argument("-o", "--out-dir", default=".")
    p.add_argument(
        "--entry", default=None,
        help="entrypoint service (default: inferred from client edges)",
    )
    p.add_argument(
        "--duration", default=None,
        help="observation duration (Go duration) for inputs without "
             "timestamps (Envoy stats)",
    )
    p.add_argument(
        "--window", default="1s",
        help="qps schedule window for CSV timestamp bucketing",
    )
    p.add_argument(
        "--cpu-time", default=None,
        help="override the fitted station cpu_time (Go duration)",
    )
    p.add_argument("--connections", type=int, default=64)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument(
        "--json", action="store_true",
        help="print the isotope-ingest/v1 report to stdout",
    )
    p.set_defaults(func=run_ingest)


def run_ingest(args) -> int:
    from isotope_tpu.analysis.topo_lint import lint_graph, lint_ingest
    from isotope_tpu.ingest import fitters, readers, report
    from isotope_tpu.runner.config import load_toml

    window_s = dur.parse_duration_seconds(args.window)
    obs = None
    for path in args.inputs:
        fmt = None if args.format == "auto" else args.format
        obs = readers.read_path(
            path, obs=obs, fmt=fmt, window_s=window_s
        )
    opts = fitters.FitOptions(
        label=args.label,
        entry=args.entry,
        duration_s=(
            dur.parse_duration_seconds(args.duration)
            if args.duration else None
        ),
        window_s=window_s,
        cpu_time_s=(
            dur.parse_duration_seconds(args.cpu_time)
            if args.cpu_time else None
        ),
        connections=args.connections,
        seed=args.seed,
    )
    fr = fitters.fit(obs, opts)

    os.makedirs(args.out_dir, exist_ok=True)
    topo_path = os.path.join(args.out_dir, f"{args.label}.yaml")
    toml_path = os.path.join(args.out_dir, f"{args.label}.toml")
    json_path = os.path.join(args.out_dir, f"{args.label}.ingest.json")
    with open(topo_path, "w") as f:
        f.write(yaml.safe_dump(
            fr.topology_doc, default_flow_style=False, sort_keys=False
        ))
    with open(toml_path, "w") as f:
        f.write(fr.toml_text)
    # the emitted TOML must decode through the real config loader
    load_toml(toml_path)

    doc = report.to_doc(fr, obs)
    findings = lint_graph(fr.graph, entry=fr.entry)
    findings += lint_ingest(fr.graph, doc)
    if findings:
        doc["findings"] = [f.to_dict() for f in findings]
        for f in findings:
            print(f.render(), file=sys.stderr)
    report.save_doc(doc, json_path)

    if args.json:
        import json as json_mod

        json_mod.dump(doc, sys.stdout, indent=1)
        sys.stdout.write("\n")
    else:
        print(report.format_report(doc))
        print(
            f"wrote {topo_path}, {toml_path}, {json_path}"
        )
    return 0
