"""shard_map'd simulation with collective-merged metrics.

Every device simulates a disjoint slice of the request stream (the event
tensor's leading axis is the ``data`` x ``svc`` mesh) in HBM-bounded
blocks under ``lax.scan`` (see sim/summary.py), then block summaries
merge with XLA collectives riding ICI:

- scalar counters / the fine latency histogram: ``psum`` over both axes;
- per-service duration histograms: ``psum`` over ``data``, then
  ``psum_scatter`` over ``svc`` so the (service, code, bucket) state ends
  up sharded across the ``svc`` axis — cross-partition edges become
  collectives, not RPCs (SURVEY.md §5.8).

There is deliberately no cross-device traffic *during* the event sweeps:
the hop program is replicated (topology tensors are tiny next to the event
tensor) and requests are independent given the analytic queue model, so
the only communication is the metric reduction — the design that makes
>1e9 hop-events/s reachable on a v5e-8.

Multi-host (DCN) awareness: a mesh with a ``slice`` axis reduces the
ICI axes first and crosses DCN last, on already-scattered per-service
tiles; ``SimParams.overlap=True`` additionally pipelines the merge
collectives one block behind the compute (``_overlap_body``) so DCN
latency hides behind the next block's event sweep.  An
:class:`~isotope_tpu.parallel.mesh.EmulatedMesh` runs the whole thing
shard-by-shard on one device — any host count, no pod required.
"""
from __future__ import annotations

from functools import partial
from typing import Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from isotope_tpu import telemetry
from isotope_tpu.compiler.cache import (
    enable_persistent_cache,
    executable_cache,
)
from isotope_tpu.resilience import faults
from isotope_tpu.compiler.program import CompiledGraph
from isotope_tpu.metrics.prometheus import MetricsCollector, ServiceMetrics
from isotope_tpu.parallel.mesh import SLICE_AXIS, SVC_AXIS, EmulatedMesh
from isotope_tpu.sim.config import OPEN_LOOP, LoadModel, SimParams
from isotope_tpu.sim.engine import Simulator
from isotope_tpu.sim.summary import (
    RunSummary,
    reduce_stacked,
    summarize,
    summary_accumulate,
    zeros_summary,
)

# back-compat alias: the sharded path now returns the same summary type
# the single-device scan path produces
ShardedSummary = RunSummary


class _RunPlan(NamedTuple):
    """Everything a run's physical execution shape depends on — shared
    between the shard_map path and the single-device emulation so the
    degradation ladder reproduces the exact same request streams."""

    offered: float
    gap: float
    nominal_gap: float
    conns_local: int
    block: int
    num_blocks: int
    window: Tuple[float, float]
    sat_conns: int
    kind: str
    trim: bool


def _shard_map(body, mesh, in_specs, out_specs):
    """``jax.shard_map`` across jax versions.

    Newer jax exposes it top-level with ``check_vma``; older releases
    (<= 0.4.x) ship ``jax.experimental.shard_map`` whose equivalent
    knob is ``check_rep``.
    """
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            body, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
    from jax.experimental.shard_map import shard_map

    return shard_map(
        body, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=False,
    )


class ShardedSimulator:
    """Runs a compiled graph data-parallel over a mesh."""

    def __init__(
        self,
        compiled: CompiledGraph,
        mesh,  # jax.sharding.Mesh | EmulatedMesh
        params: SimParams = SimParams(),
        chaos=(),
        churn=(),
        mtls=None,
        policies=None,  # Optional[sim.policies.PolicyTables]
        rollouts=None,  # Optional[sim.rollout.RolloutTables]
        lb=None,  # Optional[sim.lb.LbTables]
    ):
        self.compiled = compiled
        self.mesh = mesh
        # an EmulatedMesh carries a mesh SHAPE with no devices: every
        # run_*_emulated twin replays it shard-by-shard on one device
        # (any host count on a laptop); the shard_map entry points
        # raise a clear error instead of tracing
        self.emulated = isinstance(mesh, EmulatedMesh)
        # persistent XLA cache (no-op unless $ISOTOPE_COMPILE_CACHE is
        # set): the sharded sweep programs are the most expensive
        # compiles in the system, so wire the disk cache here too
        enable_persistent_cache()
        # lb laws ride _simulate_core's per-station wait selection, so
        # the device path and the emulated twin stay bit-equal with no
        # extra collectives: the per-backend census the laws consume is
        # derived from the ALREADY psum-merged recorder windows (the
        # control-state advance sees global signals), and the profile /
        # panic tables are replicated trace constants
        self.sim = Simulator(compiled, params, chaos, churn, mtls=mtls,
                             policies=policies, rollouts=rollouts,
                             lb=lb)
        self.collector = MetricsCollector(compiled)
        if SVC_AXIS not in mesh.axis_names:
            raise ValueError(
                f"mesh must carry a {SVC_AXIS!r} axis; got "
                f"{mesh.axis_names}"
            )
        # every non-svc axis shards the request stream: (data,) on one
        # slice, (slice, data) across slices — only the O(buckets)
        # summary reduction ever crosses the slice (DCN) axis
        self.request_axes = tuple(
            a for a in mesh.axis_names if a != SVC_AXIS
        )
        # DCN-aware merge order: ICI axes reduce first (inside every
        # slice/host), the slice axis last — and on the per-service
        # state only AFTER the svc reduce-scatter, so DCN carries
        # 1/svc of the histogram payload once per merge
        self.dcn_axes = tuple(
            a for a in mesh.axis_names if a == SLICE_AXIS
        )
        self.ici_axes = tuple(
            a for a in mesh.axis_names if a != SLICE_AXIS
        )
        self.ici_request_axes = tuple(
            a for a in self.request_axes if a != SLICE_AXIS
        )
        self.n_svc = mesh.shape[SVC_AXIS]
        self.n_shards = mesh.size
        # services padded so psum_scatter can tile over the svc axis
        s = compiled.num_services
        self.s_pad = -(-s // self.n_svc) * self.n_svc
        self._fns: Dict[Tuple[int, int, str, int], object] = {}

    def run(
        self,
        load: LoadModel,
        num_requests: int,
        key: jax.Array,
        offered_qps=None,
        block_size: int = 65_536,
        trim: bool = False,
    ) -> RunSummary:
        """Simulate >= ``num_requests`` (rounded up to fill all shards),
        scanning blocks of at most ``block_size`` requests per device.

        For closed-loop load the offered rate is latency-dependent; pass
        ``offered_qps`` (e.g. ``SimResults.offered_qps`` from a prior
        single-device run of the same load) to skip the pilot fixed point.
        ``trim=True`` accumulates the collector's steady-state window
        into the summary's ``win_*`` fields (see Simulator.run_summary).
        """
        self._require_mesh("run")
        plan = self._plan_run(load, num_requests, key, offered_qps,
                              block_size, trim)
        # shard balance: the rows actually simulated are num_blocks *
        # block per shard (shard fill + connection rounding + block
        # rounding), so the gauge is the fraction simulated beyond the
        # request count asked for — the parallel path's padding waste
        telemetry.counter_inc("sharded_runs")
        telemetry.gauge_set("shard_count", self.n_shards)
        telemetry.gauge_set(
            "shard_rows_imbalance_fraction",
            (plan.num_blocks * plan.block * self.n_shards - num_requests)
            / max(num_requests, 1),
        )
        fn = self._get(plan.block, plan.num_blocks, plan.kind,
                       plan.conns_local, plan.trim, plan.sat_conns)
        vis, windows = self._args_put(plan)
        faults.check("sharded.compute")
        if self.dcn_axes:
            # the dropped-DCN-collective chaos site: a mesh with a
            # slice axis is about to issue cross-host collectives;
            # injected transients here exercise the supervisor's retry
            # path without real hosts (resilience/faults.py)
            faults.check("sharded.dcn_collective")
        out = fn(
            key, jnp.float32(plan.offered), jnp.float32(plan.gap),
            jnp.float32(plan.nominal_gap),
            jnp.float32(plan.window[0]), jnp.float32(plan.window[1]),
            vis, windows,
        )
        if telemetry.detail_enabled():
            with telemetry.phase("sharded.gather"):
                jax.block_until_ready(out.count)
            telemetry.record_device_memory()
        faults.check("sharded.gather")
        return out

    def _plan_run(self, load, num_requests: int, key,
                  offered_qps=None, block_size: int = 65_536,
                  trim: bool = False) -> _RunPlan:
        """Resolve the physical run shape (see :class:`_RunPlan`)."""
        # every sharded entry point plans here: lb preconditions (no
        # saturated loads) + the lb.degraded_backend fault site
        self.sim._check_lb_load(load)
        n_local = -(-num_requests // self.n_shards)
        if load.kind == OPEN_LOOP:
            offered = float(load.qps)
            gap = 0.0
            nominal_gap = 0.0
            conns_local = 0
            block = max(1, min(block_size, n_local))
        else:
            if load.connections % self.n_shards:
                raise ValueError(
                    f"closed-loop connections ({load.connections}) must "
                    f"divide evenly over {self.n_shards} shards"
                )
            if offered_qps is None:
                # saturated phased runs time-average per-phase rates
                # over the REQUEST COUNT, so pass the real total (no
                # pilot runs happen on that path); the pilot-based
                # solver for paced loads keeps the small cap
                n_solve = (
                    num_requests
                    if self.sim._saturated(load)
                    else min(num_requests, 2048)
                )
                offered_qps = self.sim.solve_closed_rate(
                    load, n_solve, key
                )
            offered = float(offered_qps)
            gap = (
                load.connections / load.qps
                if load.qps is not None
                else 0.0
            )
            nominal_gap = load.connections / offered
            conns_local = max(load.connections // self.n_shards, 1)
            # block_size is a soft HBM bound: when per-shard connections
            # exceed it the block grows to ``conns_local`` requests
            per = max(1, min(block_size, n_local) // conns_local)
            block = per * conns_local
        num_blocks = max(1, -(-n_local // block))
        if trim:
            from isotope_tpu.metrics.fortio import trim_window_bounds

            window = trim_window_bounds(
                num_blocks * block * self.n_shards, offered
            )
        else:
            window = (0.0, float("inf"))
        # saturated (-qps max): the finite-population wait law uses the
        # TOTAL connection count — every shard's requests share the same
        # service stations
        sat_conns = (
            load.connections if self.sim._saturated(load) else 0
        )
        return _RunPlan(
            offered=offered, gap=gap, nominal_gap=nominal_gap,
            conns_local=conns_local, block=block, num_blocks=num_blocks,
            window=window, sat_conns=sat_conns, kind=load.kind,
            trim=trim,
        )

    def _args_put(self, plan: _RunPlan):
        """Per-run argument tables (visit fixed points, phase windows).

        args_put covers building + transferring them to the devices;
        the explicit put + block is DETAIL-ONLY so the default path
        keeps its async dispatch (no added sync points).
        """
        with telemetry.phase("sharded.args_put"):
            faults.check("sharded.args_put")
            vis = self.sim._vis_arg(plan.offered)
            windows = self.sim._windows_arg(
                plan.offered, plan.sat_conns > 0
            )
            if telemetry.detail_enabled():
                vis = jax.device_put(vis)
                windows = jax.device_put(windows)
                jax.block_until_ready((vis, windows))
        return vis, windows

    # ------------------------------------------------------------------

    def _require_mesh(self, what: str) -> None:
        """The shard_map entry points need real devices behind the mesh."""
        if self.emulated:
            raise ValueError(
                f"{what} needs a device mesh; this ShardedSimulator "
                f"was built over {self.mesh!r} (no devices) — use the "
                f"*_emulated twin, which replays any host count on "
                f"one device"
            )

    def _get(self, block: int, num_blocks: int, kind: str,
             conns_local: int, trim: bool = False, sat_conns: int = 0):
        cache_key = (block, num_blocks, kind, conns_local, trim, sat_conns)
        if cache_key not in self._fns:
            main = (
                self._overlap_body
                if self.sim.params.overlap
                else self._body
            )
            body = partial(main, block, num_blocks, kind, conns_local,
                           trim, sat_conns)
            mapped = _shard_map(
                body,
                mesh=self.mesh,
                in_specs=tuple(P() for _ in range(8)),
                out_specs=self._summary_out_specs(),
            )
            mesh_sig = (
                tuple(self.mesh.axis_names),
                tuple(int(self.mesh.shape[a]) for a in self.mesh.axis_names),
                tuple(d.id for d in self.mesh.devices.flat),
            )
            self._fns[cache_key] = executable_cache.get_or_build(
                ("sharded", self.sim.signature, mesh_sig) + cache_key,
                lambda: jax.jit(mapped),
            )
        return self._fns[cache_key]

    def _summary_out_specs(self) -> RunSummary:
        """Partition specs of the collective-merged RunSummary: scalars
        and the fine histogram replicate; the per-service duration /
        response-size histograms stay sharded over the svc axis."""
        return RunSummary(
            count=P(),
            error_count=P(),
            hop_events=P(),
            latency_sum=P(),
            latency_m2=P(),
            latency_min=P(),
            latency_max=P(),
            latency_hist=P(),
            end_max=P(),
            win_lo=P(),
            win_hi=P(),
            win_count=P(),
            win_error_count=P(),
            win_latency_hist=P(),
            metrics=ServiceMetrics(
                incoming_total=P(),
                outgoing_total=P(),
                outgoing_size_hist=P(),
                outgoing_size_sum=P(),
                duration_hist=P(SVC_AXIS),
                duration_sum=P(),
                response_size_hist=P(SVC_AXIS),
                response_size_sum=P(),
            ),
            utilization=P(),
            unstable=P(),
        )

    def _local_scan(
        self,
        block: int,
        num_blocks: int,
        kind: str,
        conns_local: int,
        trim: bool,
        sat_conns: int,
        shard: jax.Array,
        key: jax.Array,
        offered_qps: jax.Array,
        pace_gap: jax.Array,
        nominal_gap: jax.Array,
        win_lo: jax.Array,
        win_hi: jax.Array,
        visits_pc: jax.Array,
        phase_windows: jax.Array,
    ) -> RunSummary:
        """One shard's pre-collective block scan.

        Shared verbatim between the shard_map body and the single-device
        emulation (``run_emulated``): the shard's RNG streams depend only
        on ``shard``/``key``, so the degraded path replays bit-identical
        per-shard computations.
        """
        # disjoint fold domains: the rate solver's pilots consumed
        # fold_in(key, 0..iters) on the same base key
        local_key = jax.random.fold_in(key, 500_000 + shard)
        c = max(conns_local, 1)
        per = block // c

        def block_body(carry, b):
            t0, conn_t0, req_off = carry
            kb = jax.random.fold_in(local_key, 1_000_000 + b)
            res, t_end, conn_end = self.sim._simulate_core(
                block,
                kind,
                conns_local,
                kb,
                offered_qps,
                pace_gap,
                # each shard generates 1/shards of the open-loop stream
                offered_qps / self.n_shards,
                nominal_gap,
                t0,
                conn_t0,
                req_off,
                sat_conns=sat_conns,
                visits_pc=visits_pc,
                phase_windows=phase_windows,
            )
            return (t_end, conn_end, req_off + per), summarize(
                res, self.collector,
                window=(win_lo, win_hi) if trim else None,
            )

        carry0 = (
            jnp.float32(0.0),
            jnp.zeros((c,), jnp.float32),
            jnp.float32(0.0),
        )
        _, parts = jax.lax.scan(block_body, carry0, jnp.arange(num_blocks))
        return reduce_stacked(parts)

    def _body(
        self,
        block: int,
        num_blocks: int,
        kind: str,
        conns_local: int,
        trim: bool,
        sat_conns: int,
        key: jax.Array,
        offered_qps: jax.Array,
        pace_gap: jax.Array,
        nominal_gap: jax.Array,
        win_lo: jax.Array,
        win_hi: jax.Array,
        visits_pc: jax.Array,
        phase_windows: jax.Array,
    ) -> RunSummary:
        both = tuple(self.mesh.axis_names)
        shard = jnp.int32(0)
        for a in self.mesh.axis_names:
            shard = shard * self.mesh.shape[a] + jax.lax.axis_index(a)
        local = self._local_scan(
            block, num_blocks, kind, conns_local, trim, sat_conns,
            shard, key, offered_qps, pace_gap, nominal_gap,
            win_lo, win_hi, visits_pc, phase_windows,
        )
        return self._merge_summary_collective(local, both)

    def _overlap_body(
        self,
        block: int,
        num_blocks: int,
        kind: str,
        conns_local: int,
        trim: bool,
        sat_conns: int,
        key: jax.Array,
        offered_qps: jax.Array,
        pace_gap: jax.Array,
        nominal_gap: jax.Array,
        win_lo: jax.Array,
        win_hi: jax.Array,
        visits_pc: jax.Array,
        phase_windows: jax.Array,
    ) -> RunSummary:
        """``_body`` with the merge collectives pipelined into the scan.

        Double-buffered carry: block *k*'s summary rides the carry as
        ``pending`` and its psum/psum_scatter merge is issued at the
        TOP of step *k+1*, before that step's event sweep — the
        collective's result is only consumed by the cheap
        ``summary_accumulate`` fold, so the scheduler has a full
        block's compute to hide the (DCN) merge latency behind.  Step 0
        merges a zero primer (one extra tiny collective round per run);
        the last block's merge happens after the scan, un-overlapped.

        Identical RNG streams and per-block summaries to ``_body`` —
        only the reduction ORDER differs (per-block cross-shard merge,
        then across blocks, instead of blocks-then-shards), so
        integer-valued fields match exactly and float sums to
        reduction-order f32 noise (pinned by tests/test_multihost.py).
        """
        both = tuple(self.mesh.axis_names)
        shard = jnp.int32(0)
        for a in self.mesh.axis_names:
            shard = shard * self.mesh.shape[a] + jax.lax.axis_index(a)
        local_key = jax.random.fold_in(key, 500_000 + shard)
        c = max(conns_local, 1)
        per = block // c
        S = self.compiled.num_services

        def block_body(carry, b):
            (t0, conn_t0, req_off), pending, acc = carry
            acc = summary_accumulate(
                acc, self._merge_summary_collective(pending, both)
            )
            kb = jax.random.fold_in(local_key, 1_000_000 + b)
            res, t_end, conn_end = self.sim._simulate_core(
                block, kind, conns_local, kb, offered_qps, pace_gap,
                offered_qps / self.n_shards, nominal_gap, t0, conn_t0,
                req_off,
                sat_conns=sat_conns,
                visits_pc=visits_pc,
                phase_windows=phase_windows,
            )
            s = summarize(
                res, self.collector,
                window=(win_lo, win_hi) if trim else None,
            )
            return ((t_end, conn_end, req_off + per), s, acc), None

        carry0 = (
            (
                jnp.float32(0.0),
                jnp.zeros((c,), jnp.float32),
                jnp.float32(0.0),
            ),
            # the pre-merge primer carries full-S metric shapes; the
            # accumulator holds the post-scatter 1/svc tiles
            zeros_summary(self.collector, S),
            zeros_summary(self.collector, S,
                          svc_rows=self.s_pad // self.n_svc),
        )
        (_, pending, acc), _ = jax.lax.scan(
            block_body, carry0, jnp.arange(num_blocks)
        )
        return summary_accumulate(
            acc, self._merge_summary_collective(pending, both)
        )

    def _merge_summary_collective(self, local: RunSummary,
                                  both) -> RunSummary:
        """The mesh metric reduction over one shard's RunSummary
        (shared by the plain, overlap, and attributed bodies).

        DCN-aware ordering: the ICI axes (``data``/``svc`` — inside one
        slice/host) reduce first, the ``slice`` axis last; the
        per-service histograms reduce-scatter over ``svc`` BEFORE the
        cross-slice psum, so DCN carries a 1/svc tile of the
        per-service state instead of the full (S, ...) tensors.
        Without a slice axis this lowers to the exact same collectives
        as before (single-host default stays byte-identical).
        """
        dcn = self.dcn_axes

        def allsum(x):
            x = jax.lax.psum(x, self.ici_axes)
            return jax.lax.psum(x, dcn) if dcn else x

        def pextreme(op, x):
            x = op(x, self.ici_axes)
            return op(x, dcn) if dcn else x

        # per-service hists: reduce over the ICI request axes, scatter
        # over svc, THEN cross the DCN axis on the scattered tiles
        def scatter_svc(x):
            x = jax.lax.psum(x, self.ici_request_axes)
            pad = self.s_pad - x.shape[0]
            if pad:
                x = jnp.pad(x, [(0, pad)] + [(0, 0)] * (x.ndim - 1))
            x = jax.lax.psum_scatter(
                x, SVC_AXIS, scatter_dimension=0, tiled=True
            )
            return jax.lax.psum(x, dcn) if dcn else x

        m = local.metrics
        metrics = ServiceMetrics(
            incoming_total=allsum(m.incoming_total),
            outgoing_total=allsum(m.outgoing_total),
            outgoing_size_hist=allsum(m.outgoing_size_hist),
            outgoing_size_sum=allsum(m.outgoing_size_sum),
            duration_hist=scatter_svc(m.duration_hist),
            duration_sum=allsum(m.duration_sum),
            response_size_hist=scatter_svc(m.response_size_hist),
            response_size_sum=allsum(m.response_size_sum),
        )
        # Chan/Welford merge of per-shard centered second moments
        n_tot = allsum(local.count)
        s_tot = allsum(local.latency_sum)
        mean_local = local.latency_sum / jnp.maximum(local.count, 1.0)
        mean_tot = s_tot / jnp.maximum(n_tot, 1.0)
        m2_tot = allsum(
            local.latency_m2
            + local.count * (mean_local - mean_tot) ** 2
        )
        return RunSummary(
            count=n_tot,
            error_count=allsum(local.error_count),
            hop_events=allsum(local.hop_events),
            latency_sum=s_tot,
            latency_m2=m2_tot,
            latency_min=pextreme(jax.lax.pmin, local.latency_min),
            latency_max=pextreme(jax.lax.pmax, local.latency_max),
            latency_hist=allsum(local.latency_hist),
            end_max=pextreme(jax.lax.pmax, local.end_max),
            win_lo=local.win_lo,   # identical on every shard
            win_hi=local.win_hi,
            win_count=allsum(local.win_count),
            win_error_count=allsum(local.win_error_count),
            win_latency_hist=allsum(local.win_latency_hist),
            metrics=metrics,
            utilization=local.utilization,
            unstable=local.unstable,
        )

    # -- scenario ensembles (sim/ensemble.py) ---------------------------

    def _plan_ensemble(self, load, num_requests: int, key, spec,
                       block_size: int, trim: bool, member_keys,
                       member_qps=None, member_chaos=None,
                       attribution: bool = False, tail: bool = False,
                       tail_cut=None, timeline: bool = False,
                       window_s=None):
        """Resolve (spec, tables, stacked args, members-per-shard) for
        one fleet dispatch.  Each member is a FULL run of
        ``num_requests`` — the mesh parallelizes the member axis, not
        the request stream, so a member's physics (and bits) are the
        single-device member program's.  Attribution / timeline arm
        the fleet observability pass (PR 17): the stacked tail-cut
        argument rides between the 10 standard member args and the
        chaos rows, exactly the engine's calling convention."""
        from isotope_tpu.compiler.compile import compile_ensemble
        from isotope_tpu.sim import ensemble as ens_mod

        sim = self.sim
        if attribution and not sim.params.attribution:
            raise ValueError(
                "attributed fleets need SimParams(attribution=True)"
            )
        if timeline and not sim.params.timeline:
            raise ValueError(
                "timeline fleets need SimParams(timeline=True)"
            )
        if attribution and tail and tail_cut is None:
            # ONE pilot (on the fleet key) serves every member — and
            # both the mesh path and the emulated twin, so their cut
            # (and bits) agree
            tail_cut = sim.estimate_tail_cut(
                load, num_requests, key, block_size=block_size
            )
        if spec is None:
            if sim.params.ensemble <= 0:
                raise ValueError(
                    "run_ensemble needs an EnsembleSpec (or "
                    "SimParams.ensemble > 0 for the seeds-only "
                    "default fleet)"
                )
            spec = ens_mod.EnsembleSpec.of(sim.params.ensemble)
        spec.check(allow_duplicate_seeds=member_keys is not None)
        sim._check_lb_load(load)
        tables = compile_ensemble(spec)
        sat_load = sim._saturated(load)
        member_events, planners, chaos_fx = (
            sim._resolve_member_chaos(
                member_chaos, spec.seeds,
                sat_conns=load.connections if sat_load else 0,
            )
        )
        chaos_args = sim._chaos_fx_args(
            chaos_fx, with_pol=False, sat=sat_load
        )
        args = sim._ensemble_args(
            load, num_requests, key, spec, tables,
            member_keys=member_keys, block_size=block_size, trim=trim,
            member_qps=member_qps, planners=planners,
        )
        attr_mode = (
            ("tail" if tail else "mean") if attribution else None
        )
        tl_plan = (
            sim.plan_timeline_windows(
                args["num_blocks"] * args["block"],
                float(args["offered"][0]), window_s,
            )
            if timeline else None
        )
        cut_arg = ()
        if attribution:
            cut_arg = (jnp.full(
                (spec.members,),
                tail_cut if (tail and tail_cut is not None)
                else np.inf,
                jnp.float32,
            ),)
        per_shard = -(-spec.members // self.n_shards)
        # member chunking, mesh edition: per_shard members ride EACH
        # device, so the solo path's capacity pre-check applies to the
        # per-shard width — an over-wide fleet splits into sequential
        # ROUNDS of narrower dispatches (the planned split VET-M004
        # promises, not an OOM)
        width = spec.chunk
        if width is None:
            width = sim.ensemble_chunk_size(
                per_shard, args["block"], attr=attribution,
                timeline_windows=(
                    tl_plan[0] if tl_plan is not None else None
                ),
            )
        width = max(1, min(int(width), per_shard))
        rounds = -(-per_shard // width)
        width = -(-per_shard // rounds)  # balanced rounds
        return (spec, tables, args, width, rounds, cut_arg,
                chaos_args, member_events, attr_mode, tl_plan)

    def _ensemble_padded(self, args, n_mem: int, width: int,
                         rounds: int, chaos_args=()):
        """The member-stacked fleet arguments padded (the engine's
        shared pad law) so every (round, shard) slot holds ``width``
        members — round r dispatches the contiguous member slice
        ``[r * n_shards * width, (r + 1) * n_shards * width)``, which
        is exactly the order the emulated twin's flat chunk loop
        walks."""
        return self.sim._ensemble_pad_args(
            self.sim._ensemble_stacked_args(args) + tuple(chaos_args),
            n_mem, rounds * width * self.n_shards,
        )

    def _ensemble_out_specs(self, axes) -> RunSummary:
        """Every summary leaf carries a leading member axis sharded
        over the flattened mesh (``metrics`` stays None — the
        per-service collector series stay out of the fleet program)."""
        member = P(axes)
        return RunSummary(
            count=member, error_count=member, hop_events=member,
            latency_sum=member, latency_m2=member, latency_min=member,
            latency_max=member, latency_hist=member, end_max=member,
            win_lo=member, win_hi=member, win_count=member,
            win_error_count=member, win_latency_hist=member,
            metrics=None, utilization=member, unstable=member,
        )

    def run_ensemble(
        self,
        load: LoadModel,
        num_requests: int,
        key: jax.Array,
        spec=None,  # Optional[ensemble.EnsembleSpec]
        *,
        block_size: int = 65_536,
        trim: bool = False,
        member_keys=None,
        member_qps=None,
        member_chaos=None,
        attribution: bool = False,
        tail: bool = False,
        tail_cut=None,
        timeline: bool = False,
        window_s=None,
    ):
        """The Monte Carlo fleet sharded over the mesh: the member
        axis distributes over the FLATTENED device list (every mesh
        axis, ``data`` included) and each device ``vmap``s its local
        member slice — one jitted program for the whole fleet, with
        per-member physics identical to ``Simulator.run_ensemble``
        (no cross-member collectives exist to reorder float sums).

        Over-wide fleets split into sequential ROUNDS of narrower
        dispatches (the per-shard width is pre-computed from the vet
        cost model like the solo path's member chunk); every round
        reuses ONE compiled program.  Bit-equal to
        :meth:`run_ensemble_emulated`, which replays the same
        per-shard vmapped program serially on one device
        (tests/test_ensemble.py) — the OOM-degradation rung and the
        laptop twin of a pod-scale fleet.

        ``attribution``/``timeline`` arm the fleet observability pass
        (PR 17): each member accumulates its own critical-path blame
        and window series INSIDE the sharded member body, stacked
        along the member axis like the summaries — member k's blame
        is bit-identical to its solo ``run_attributed`` (and to the
        emulated twin's).  ``tail=True`` blames only requests above
        ``tail_cut`` seconds (one pilot run on the fleet key estimates
        it when unset).
        """
        self._require_mesh("run_ensemble")
        (spec, tables, args, width, rounds, cut_arg, chaos_args,
         member_events, attr_mode, tl_plan) = self._plan_ensemble(
            load, num_requests, key, spec, block_size, trim,
            member_keys, member_qps, member_chaos,
            attribution=attribution, tail=tail, tail_cut=tail_cut,
            timeline=timeline, window_s=window_s,
        )
        n_mem = spec.members
        observed = attribution or timeline
        telemetry.counter_inc("sharded_ensemble_runs")
        telemetry.gauge_set("ensemble_members", n_mem)
        telemetry.gauge_set("ensemble_members_per_shard", width)
        telemetry.gauge_set("ensemble_rounds", rounds)
        fn = self._get_ensemble_fn(
            args, width, tables, trim,
            member_chaos=len(chaos_args) > 0,
            n_extra=len(cut_arg) + len(chaos_args),
            attr=attr_mode, tl_plan=tl_plan,
        )
        padded = self._ensemble_padded(
            args, n_mem, width, rounds, cut_arg + chaos_args
        )
        faults.check("sharded.compute")
        if self.dcn_axes:
            faults.check("sharded.dcn_collective")
        per_round = width * self.n_shards
        parts = []
        for r in range(rounds):
            sl = slice(r * per_round, (r + 1) * per_round)
            parts.append(fn(*(x[sl] for x in padded)))
            if rounds > 1:
                # serialize rounds: live memory stays bounded by one
                # round's event tensors (the point of the split)
                head = parts[-1][0] if observed else parts[-1]
                jax.block_until_ready(head.count)
        out = self.sim._ensemble_concat(parts, n_mem)
        if observed:
            summaries = out[0]
            rest = list(out[1:])
            tl_stack = rest.pop(0) if timeline else None
            attr_stack = rest.pop(0) if attribution else None
        else:
            summaries, tl_stack, attr_stack = out, None, None
        from isotope_tpu.sim import ensemble as ens_mod

        return ens_mod.EnsembleSummary(
            spec=spec,
            summaries=summaries,
            offered_qps=args["offered"],
            chunk=width,
            member_chaos=member_events,
            timelines=tl_stack,
            attributions=attr_stack,
        )

    def _attr_out_specs(self, member):
        """AttributionSummary out-specs with every leaf member-sharded
        — the exemplar heap rides only when the params reserve slots
        (matching the member program's ``exemplars=None`` otherwise)."""
        from isotope_tpu.metrics.attribution import (
            AttributionSummary, ExemplarBatch,
        )

        ex = (
            ExemplarBatch(*([member] * len(ExemplarBatch._fields)))
            if self.sim.params.attribution_top_k > 0 else None
        )
        n = len(AttributionSummary._fields) - 1
        return AttributionSummary(*([member] * n), exemplars=ex)

    def _get_ensemble_fn(self, args, width: int, tables,
                         trim: bool, member_chaos: bool = False,
                         n_extra: int = 0, attr=None, tl_plan=None):
        """Jitted shard_map of the vmapped member program; the member
        axis (per-shard round width), jitter arming, per-member chaos
        arming, and the observability plan (attr mode + timeline grid)
        key the cache."""
        from isotope_tpu.metrics.timeline import TimelineSummary

        axes = tuple(self.mesh.axis_names)
        cache_key = (args["block"], args["num_blocks"], args["kind"],
                     args["conns"], trim,
                     args["sat"], width, tables.jittered,
                     tables.mode, member_chaos, attr, tl_plan)
        full_key = (
            ("sharded-ensemble", self.sim.signature,
             (axes,
              tuple(int(self.mesh.shape[a]) for a in axes),
              tuple(d.id for d in self.mesh.devices.flat)))
            + cache_key
        )
        member = self.sim._member_fn(
            args["block"], args["num_blocks"], args["kind"],
            args["conns"], trim, args["sat"], tables.jittered,
            member_chaos=member_chaos, attr=attr, tl_plan=tl_plan,
        )
        if tables.mode == "map":
            def local(*xs):
                return jax.lax.map(lambda t: member(*t), xs)
        else:
            local = jax.vmap(member)
        out_specs = self._ensemble_out_specs(axes)
        if attr is not None or tl_plan is not None:
            # observed member output: (summary[, timeline][, attr]) —
            # attribution LAST, the engine member ordering
            out_specs = (out_specs,)
            if tl_plan is not None:
                out_specs += (self._filled_specs(
                    TimelineSummary, P(axes)
                ),)
            if attr is not None:
                out_specs += (self._attr_out_specs(P(axes)),)
        mapped = _shard_map(
            local,
            mesh=self.mesh,
            in_specs=tuple(P(axes) for _ in range(10 + n_extra)),
            out_specs=out_specs,
        )
        return executable_cache.get_or_build(
            full_key,
            lambda: telemetry.time_first_call(
                jax.jit(mapped), "compile.jit_first_call",
            ),
        )

    def run_ensemble_emulated(
        self,
        load: LoadModel,
        num_requests: int,
        key: jax.Array,
        spec=None,
        *,
        block_size: int = 65_536,
        trim: bool = False,
        member_keys=None,
        member_qps=None,
        member_chaos=None,
        attribution: bool = False,
        tail: bool = False,
        tail_cut=None,
        timeline: bool = False,
        window_s=None,
    ):
        """The fleet's single-device twin: each shard's member slice
        runs through the SAME vmapped member program (the engine's
        ``_get_ensemble`` at ``per_shard`` width), serially, then the
        slices concatenate on host.  No collectives exist in the fleet
        program, so this is bit-equal to :meth:`run_ensemble` — works
        over an :class:`~isotope_tpu.parallel.mesh.EmulatedMesh` (any
        host count on one CPU) and serves as the fleet's OOM
        degradation rung.  ``attribution``/``timeline`` arm the same
        fleet observability pass as the mesh path (same member trace,
        same bits)."""
        (spec, tables, args, width, rounds, cut_arg, chaos_args,
         member_events, attr_mode, tl_plan) = self._plan_ensemble(
            load, num_requests, key, spec, block_size, trim,
            member_keys, member_qps, member_chaos,
            attribution=attribution, tail=tail, tail_cut=tail_cut,
            timeline=timeline, window_s=window_s,
        )
        n_mem = spec.members
        observed = attribution or timeline
        telemetry.counter_inc("sharded_ensemble_emulated_runs")
        fn = self.sim._get_ensemble(
            args["block"], args["num_blocks"], args["kind"],
            args["conns"], trim, args["sat"], width,
            tables.jittered, tables.mode,
            member_chaos=len(chaos_args) > 0,
            attr=attr_mode, tl_plan=tl_plan,
        )
        padded = self._ensemble_padded(
            args, n_mem, width, rounds, cut_arg + chaos_args
        )
        parts = []
        with telemetry.phase("sharded.emulated"):
            # the flat width-chunk walk visits members in exactly the
            # device path's (round, shard) order — contiguous slices
            for c in range(rounds * self.n_shards):
                sl = slice(c * width, (c + 1) * width)
                out = fn(*(x[sl] for x in padded))
                # serialize: live memory stays bounded by ONE shard
                head = out[0] if observed else out
                jax.block_until_ready(head.count)
                parts.append(out)
        out = self.sim._ensemble_concat(parts, n_mem)
        if observed:
            summaries = out[0]
            rest = list(out[1:])
            tl_stack = rest.pop(0) if timeline else None
            attr_stack = rest.pop(0) if attribution else None
        else:
            summaries, tl_stack, attr_stack = out, None, None
        from isotope_tpu.sim import ensemble as ens_mod

        return ens_mod.EnsembleSummary(
            spec=spec,
            summaries=summaries,
            offered_qps=args["offered"],
            chunk=width,
            member_chaos=member_events,
            timelines=tl_stack,
            attributions=attr_stack,
        )

    # -- search brackets (sim/search.py) --------------------------------

    def _get_search_fn(self, block: int, num_blocks: int, kind: str,
                       conns: int, sat: bool, width: int, tables):
        """Jitted shard_map of the carry-I/O member program (the
        search-bracket twin of :meth:`_get_ensemble_fn`): 14 member-
        sharded inputs (10 standard + b0 + the 3 carries), summary +
        carry outputs sharded the same way.  No donation on the mesh
        path — rounds already bound live memory and shard_map aliasing
        is backend-dependent."""
        axes = tuple(self.mesh.axis_names)
        cache_key = (block, num_blocks, kind, conns, sat, width,
                     tables.jittered, tables.mode)
        full_key = (
            ("sharded-search", self.sim.signature,
             (axes,
              tuple(int(self.mesh.shape[a]) for a in axes),
              tuple(d.id for d in self.mesh.devices.flat)))
            + cache_key
        )
        member = self.sim._member_fn(
            block, num_blocks, kind, conns, False, sat,
            tables.jittered, carry_io=True,
        )
        if tables.mode == "map":
            def local(*xs):
                return jax.lax.map(lambda t: member(*t), xs)
        else:
            local = jax.vmap(member)
        mapped = _shard_map(
            local,
            mesh=self.mesh,
            in_specs=tuple(P(axes) for _ in range(14)),
            out_specs=(
                self._ensemble_out_specs(axes),
                (P(axes), P(axes), P(axes)),
            ),
        )
        return executable_cache.get_or_build(
            full_key,
            lambda: telemetry.time_first_call(
                jax.jit(mapped), "compile.jit_first_call",
            ),
        )

    def run_search(self, load, num_requests: int, key, spec, *,
                   block_size: int = 65_536, chunk=None):
        """The successive-halving bracket sharded over the mesh
        (sim/search.py :func:`run_search_sharded`): rung fleets
        distribute the member axis over the flattened device list;
        ranking and survivor gathers are the solo path's jnp ops, so
        the lineage is bit-identical to the solo bracket and to
        :meth:`run_search_emulated`."""
        from isotope_tpu.sim import search as search_mod

        faults.check("sharded.compute")
        return search_mod.run_search_sharded(
            self, load, num_requests, key, spec,
            block_size=block_size, chunk=chunk,
        )

    def run_search_emulated(self, load, num_requests: int, key, spec,
                            *, block_size: int = 65_536, chunk=None):
        """The sharded bracket's single-device twin (EmulatedMesh-
        friendly): the same rung geometry walked serially through the
        solo carry-I/O program."""
        from isotope_tpu.sim import search as search_mod

        return search_mod.run_search_emulated(
            self, load, num_requests, key, spec,
            block_size=block_size, chunk=chunk,
        )

    # -- protected ensembles: chaos fleets (sim/ensemble.py) ------------

    @staticmethod
    def _filled_specs(cls, spec, none_fields=()):
        """A NamedTuple out-spec with ``spec`` on every leaf (None
        fields stay None — e.g. RunSummary.metrics stays out of fleet
        programs)."""
        return cls(**{
            f: (None if f in none_fields else spec)
            for f in cls._fields
        })

    def _protected_ens_out_specs(self, axes, roll: bool,
                                 attr: bool = False):
        """The protected fleet's output pytree: every leaf carries a
        leading member axis sharded over the flattened mesh
        (attribution rides LAST, the engine member ordering)."""
        from isotope_tpu.metrics.timeline import TimelineSummary

        member = P(axes)
        out = (
            self._filled_specs(RunSummary, member, ("metrics",)),
            self._filled_specs(TimelineSummary, member),
        )
        if roll:
            from isotope_tpu.sim.rollout import RolloutSummary

            out = out + (
                self._filled_specs(RolloutSummary, member),
            )
        if self.sim._policies is not None:
            from isotope_tpu.sim.policies import PolicySummary

            out = out + (
                self._filled_specs(PolicySummary, member),
            )
        if attr:
            out = out + (self._attr_out_specs(member),)
        return out

    def _plan_protected_ensemble(self, load, num_requests, key, spec,
                                 block_size, trim, window_s,
                                 member_keys, member_qps,
                                 member_chaos, roll: bool,
                                 attribution: bool = False,
                                 tail: bool = False, tail_cut=None):
        """Resolve one protected fleet dispatch: spec/tables/args plus
        the timeline plan and the stacked chaos rows — shared by the
        mesh path and the emulated twin so their member programs are
        the identical trace.  ``attribution`` arms the per-member
        blame pass: the stacked tail-cut argument rides between the
        10 standard member args and the chaos rows (the engine's
        calling convention)."""
        from isotope_tpu.compiler.compile import compile_ensemble
        from isotope_tpu.metrics import timeline as timeline_mod
        from isotope_tpu.sim import ensemble as ens_mod

        sim = self.sim
        if attribution and not sim.params.attribution:
            raise ValueError(
                "attributed fleets need SimParams(attribution=True)"
            )
        if attribution and tail and tail_cut is None:
            # ONE pilot (on the fleet key) serves every member — and
            # both the mesh path and the emulated twin
            tail_cut = sim.estimate_tail_cut(
                load, num_requests, key, block_size=block_size
            )
        if spec is None:
            if sim.params.ensemble <= 0:
                raise ValueError(
                    "protected fleets need an EnsembleSpec (or "
                    "SimParams.ensemble > 0)"
                )
            spec = ens_mod.EnsembleSpec.of(sim.params.ensemble)
        spec.check(allow_duplicate_seeds=member_keys is not None)
        if sim._saturated(load):
            raise ValueError(
                "protected fleets do not support saturated -qps max "
                "loads (static finite-population tables)"
            )
        sim._check_lb_load(load)
        tables = compile_ensemble(spec)
        member_events, planners, chaos_fx = sim._resolve_member_chaos(
            member_chaos, spec.seeds, with_pol=True, roll=roll,
        )
        args = sim._ensemble_args(
            load, num_requests, key, spec, tables,
            member_keys=member_keys, block_size=block_size,
            trim=trim, member_qps=member_qps, planners=planners,
        )
        tl_plan = sim.plan_timeline_windows(
            args["num_blocks"] * args["block"],
            float(args["offered"][0]), window_s,
        )
        chaos_args = sim._chaos_fx_args(
            chaos_fx, with_pol=True, roll=roll
        )
        if chaos_fx is not None and sim._policies is not None:
            tspec = timeline_mod.build_spec(
                self.compiled, tl_plan[0], tl_plan[1]
            )
            chaos_args = chaos_args + (jnp.stack([
                pl._policy_downed_windows(tspec, base_split=roll)
                for pl in planners
            ]),)
        attr_mode = (
            ("tail" if tail else "mean") if attribution else None
        )
        cut_arg = ()
        if attribution:
            cut_arg = (jnp.full(
                (spec.members,),
                tail_cut if (tail and tail_cut is not None)
                else np.inf,
                jnp.float32,
            ),)
        per_shard = -(-spec.members // self.n_shards)
        width = spec.chunk
        if width is None:
            width = sim.protected_ensemble_chunk(
                per_shard, args["block"], tl_plan, roll,
                attr=attribution,
            )
        width = max(1, min(int(width), per_shard))
        rounds = -(-per_shard // width)
        width = -(-per_shard // rounds)  # balanced rounds
        return (spec, tables, args, tl_plan, cut_arg, chaos_args,
                member_events, width, rounds, attr_mode)

    def _protected_ens_summary(self, spec, args, out, width,
                               member_events, roll: bool,
                               attribution: bool = False):
        """Assemble the EnsembleSummary from the concatenated
        protected fleet output tuple (the engine's unpack order —
        attribution LAST)."""
        from isotope_tpu.sim import ensemble as ens_mod

        summary, tl = out[0], out[1]
        rest = list(out[2:])
        roll_stack = rest.pop(0) if roll else None
        pol_stack = (
            rest.pop(0) if self.sim._policies is not None else None
        )
        attr_stack = rest.pop(0) if attribution else None
        return ens_mod.EnsembleSummary(
            spec=spec,
            summaries=summary,
            offered_qps=args["offered"],
            chunk=width,
            member_chaos=member_events,
            timelines=tl,
            policies=pol_stack,
            rollouts=roll_stack,
            attributions=attr_stack,
        )

    def run_policies_ensemble(
        self, load, num_requests, key, spec=None, *,
        block_size: int = 65_536, trim: bool = False,
        window_s=None, member_keys=None, member_qps=None,
        member_chaos=None, attribution: bool = False,
        tail: bool = False, tail_cut=None,
    ):
        """The protected policy fleet sharded over the mesh: the
        member axis distributes over the FLATTENED device list and
        each device maps its local member slice through the
        single-device protected member program — no cross-member (or
        cross-shard) collectives exist, so per-member physics and
        bits are :meth:`Simulator.run_policies_ensemble`'s, and the
        whole fleet is bit-equal to
        :meth:`run_policies_ensemble_emulated` (pinned).  Unlike the
        request-sharded :meth:`run_policies` there is NO svc=1 mesh
        restriction: members are whole worlds.  ``attribution`` arms
        the per-member critical-path blame pass (PR 17) — stacked
        like the summaries, bit-identical to each member's solo
        ``run_policies(attribution=True)``."""
        self._require_mesh("run_policies_ensemble")
        if self.sim._policies is None:
            raise ValueError(
                "policy fleets need compiled policy tables "
                "(ShardedSimulator(..., policies=...))"
            )
        if not self.sim.params.timeline:
            raise ValueError(
                "policy fleets need SimParams(timeline=True)"
            )
        faults.check("policies.stuck_breaker")
        faults.check("policies.autoscaler_lag")
        return self._run_protected_ensemble_device(
            load, num_requests, key, spec, block_size, trim,
            window_s, member_keys, member_qps, member_chaos,
            roll=False, attribution=attribution, tail=tail,
            tail_cut=tail_cut,
        )

    def run_rollouts_ensemble(
        self, load, num_requests, key, spec=None, *,
        block_size: int = 65_536, trim: bool = False,
        window_s=None, member_keys=None, member_qps=None,
        member_chaos=None, attribution: bool = False,
        tail: bool = False, tail_cut=None,
    ):
        """The progressive-delivery fleet sharded over the mesh (see
        :meth:`run_policies_ensemble` — member-axis sharding, zero
        collectives, bit-equal emulated twin, optional per-member
        blame via ``attribution``)."""
        self._require_mesh("run_rollouts_ensemble")
        if self.sim._rollouts is None:
            raise ValueError(
                "rollout fleets need compiled rollout tables "
                "(ShardedSimulator(..., rollouts=...))"
            )
        if not self.sim.params.timeline:
            raise ValueError(
                "rollout fleets need SimParams(timeline=True)"
            )
        if self.sim._policies is not None:
            faults.check("policies.stuck_breaker")
            faults.check("policies.autoscaler_lag")
        return self._run_protected_ensemble_device(
            load, num_requests, key, spec, block_size, trim,
            window_s, member_keys, member_qps, member_chaos,
            roll=True, attribution=attribution, tail=tail,
            tail_cut=tail_cut,
        )

    def _run_protected_ensemble_device(self, load, num_requests, key,
                                       spec, block_size, trim,
                                       window_s, member_keys,
                                       member_qps, member_chaos,
                                       roll: bool,
                                       attribution: bool = False,
                                       tail: bool = False,
                                       tail_cut=None):
        (spec, tables, args, tl_plan, cut_arg, chaos_args,
         member_events, width, rounds, attr_mode) = (
            self._plan_protected_ensemble(
                load, num_requests, key, spec, block_size, trim,
                window_s, member_keys, member_qps, member_chaos,
                roll, attribution=attribution, tail=tail,
                tail_cut=tail_cut,
            )
        )
        n_mem = spec.members
        telemetry.counter_inc(
            "sharded_rollout_fleet_runs" if roll
            else "sharded_policy_fleet_runs"
        )
        telemetry.gauge_set("ensemble_members", n_mem)
        telemetry.gauge_set("ensemble_members_per_shard", width)
        telemetry.gauge_set("ensemble_rounds", rounds)
        member_chaos_on = len(chaos_args) > 0
        axes = tuple(self.mesh.axis_names)
        cache_key = ("prot-ens", args["block"], args["num_blocks"],
                     args["kind"], args["conns"], trim, tl_plan,
                     roll, width, tables.jittered, tables.mode,
                     member_chaos_on, attr_mode)
        full_key = (
            ("sharded-ensemble", self.sim.signature,
             (axes,
              tuple(int(self.mesh.shape[a]) for a in axes),
              tuple(d.id for d in self.mesh.devices.flat)))
            + cache_key
        )
        member = self.sim._member_fn(
            args["block"], args["num_blocks"], args["kind"],
            args["conns"], trim, False, tables.jittered,
            member_chaos=member_chaos_on, attr=attr_mode,
            tl_plan=tl_plan,
            prot="rollouts" if roll else "policies",
        )
        if tables.mode == "map":
            def local(*xs):
                return jax.lax.map(lambda t: member(*t), xs)
        else:
            local = jax.vmap(member)
        n_args = 10 + len(cut_arg) + len(chaos_args)
        mapped = _shard_map(
            local,
            mesh=self.mesh,
            in_specs=tuple(P(axes) for _ in range(n_args)),
            out_specs=self._protected_ens_out_specs(
                axes, roll, attr=attribution
            ),
        )
        fn = executable_cache.get_or_build(
            full_key,
            lambda: telemetry.time_first_call(
                jax.jit(mapped), "compile.jit_first_call",
            ),
        )
        padded = self.sim._ensemble_pad_args(
            self.sim._ensemble_stacked_args(args) + cut_arg
            + chaos_args,
            n_mem, rounds * width * self.n_shards,
        )
        faults.check("sharded.compute")
        if self.dcn_axes:
            faults.check("sharded.dcn_collective")
        per_round = width * self.n_shards
        parts = []
        for r in range(rounds):
            sl = slice(r * per_round, (r + 1) * per_round)
            parts.append(fn(*(x[sl] for x in padded)))
            if rounds > 1:
                jax.block_until_ready(parts[-1][0].count)
        out = self.sim._ensemble_concat(parts, n_mem)
        return self._protected_ens_summary(
            spec, args, out, width, member_events, roll,
            attribution=attribution,
        )

    def run_policies_ensemble_emulated(
        self, load, num_requests, key, spec=None, *,
        block_size: int = 65_536, trim: bool = False,
        window_s=None, member_keys=None, member_qps=None,
        member_chaos=None, attribution: bool = False,
        tail: bool = False, tail_cut=None,
    ):
        """The protected fleet's single-device twin: each shard's
        member slice runs through the engine's own protected fleet
        program serially, then concatenates on host — bit-equal to
        :meth:`run_policies_ensemble` (no collectives exist in the
        fleet program), works over an
        :class:`~isotope_tpu.parallel.mesh.EmulatedMesh`, and serves
        as the fleet's OOM degradation rung."""
        if self.sim._policies is None:
            raise ValueError(
                "policy fleets need compiled policy tables "
                "(ShardedSimulator(..., policies=...))"
            )
        return self._run_protected_ensemble_emulated(
            load, num_requests, key, spec, block_size, trim,
            window_s, member_keys, member_qps, member_chaos,
            roll=False, attribution=attribution, tail=tail,
            tail_cut=tail_cut,
        )

    def run_rollouts_ensemble_emulated(
        self, load, num_requests, key, spec=None, *,
        block_size: int = 65_536, trim: bool = False,
        window_s=None, member_keys=None, member_qps=None,
        member_chaos=None, attribution: bool = False,
        tail: bool = False, tail_cut=None,
    ):
        """The rollout fleet's single-device twin (see
        :meth:`run_policies_ensemble_emulated`)."""
        if self.sim._rollouts is None:
            raise ValueError(
                "rollout fleets need compiled rollout tables "
                "(ShardedSimulator(..., rollouts=...))"
            )
        return self._run_protected_ensemble_emulated(
            load, num_requests, key, spec, block_size, trim,
            window_s, member_keys, member_qps, member_chaos,
            roll=True, attribution=attribution, tail=tail,
            tail_cut=tail_cut,
        )

    def _run_protected_ensemble_emulated(self, load, num_requests,
                                         key, spec, block_size, trim,
                                         window_s, member_keys,
                                         member_qps, member_chaos,
                                         roll: bool,
                                         attribution: bool = False,
                                         tail: bool = False,
                                         tail_cut=None):
        (spec, tables, args, tl_plan, cut_arg, chaos_args,
         member_events, width, rounds, attr_mode) = (
            self._plan_protected_ensemble(
                load, num_requests, key, spec, block_size, trim,
                window_s, member_keys, member_qps, member_chaos,
                roll, attribution=attribution, tail=tail,
                tail_cut=tail_cut,
            )
        )
        n_mem = spec.members
        telemetry.counter_inc(
            "sharded_rollout_fleet_emulated_runs" if roll
            else "sharded_policy_fleet_emulated_runs"
        )
        fn = self.sim._get_protected_ensemble(
            args["block"], args["num_blocks"], args["kind"],
            args["conns"], trim, tl_plan, roll, width,
            tables.jittered, tables.mode, len(chaos_args) > 0,
            attr=attr_mode,
        )
        padded = self.sim._ensemble_pad_args(
            self.sim._ensemble_stacked_args(args) + cut_arg
            + chaos_args,
            n_mem, rounds * width * self.n_shards,
        )
        parts = []
        with telemetry.phase("sharded.emulated"):
            # the flat width-chunk walk visits members in exactly the
            # device path's (round, shard) order — contiguous slices
            for c in range(rounds * self.n_shards):
                sl = slice(c * width, (c + 1) * width)
                out = fn(*(x[sl] for x in padded))
                jax.block_until_ready(out[0].count)
                parts.append(out)
        out = self.sim._ensemble_concat(parts, n_mem)
        return self._protected_ens_summary(
            spec, args, out, width, member_events, roll,
            attribution=attribution,
        )

    # -- attributed runs (metrics/attribution.py) -----------------------

    def run_attributed(
        self,
        load: LoadModel,
        num_requests: int,
        key: jax.Array,
        offered_qps=None,
        block_size: int = 65_536,
        trim: bool = False,
        tail: bool = False,
        tail_cut=None,
    ):
        """Sharded twin of :meth:`Simulator.run_attributed`: every
        shard reduces its block scan to (RunSummary, AttributionSummary)
        and the attribution leaves merge with the same collectives the
        summary takes — ``psum`` for the O(H)/O(S * buckets) blame
        accumulators, ``all_gather`` + ``top_k`` for the O(K * H)
        exemplar batch (so every shard returns the same global top-K).
        Returns ``(RunSummary, AttributionSummary)``."""
        if not self.sim.params.attribution:
            raise ValueError(
                "attributed runs need SimParams(attribution=True)"
            )
        self._require_mesh("run_attributed")
        if tail and tail_cut is None:
            tail_cut = self.sim.estimate_tail_cut(
                load, num_requests, key, block_size=block_size
            )
        plan = self._plan_run(load, num_requests, key, offered_qps,
                              block_size, trim)
        telemetry.counter_inc("sharded_attributed_runs")
        # build the blame tables EAGERLY: constants created inside the
        # shard_map trace would be cached as tracers and leak
        self.sim._attribution_tables()
        fn = self._get_attr(plan, tail)
        vis, windows = self._args_put(plan)
        faults.check("sharded.compute")
        out = fn(
            key, jnp.float32(plan.offered), jnp.float32(plan.gap),
            jnp.float32(plan.nominal_gap),
            jnp.float32(plan.window[0]), jnp.float32(plan.window[1]),
            jnp.float32(tail_cut if tail else np.inf),
            vis, windows,
        )
        faults.check("sharded.gather")
        return out

    def run_attributed_emulated(
        self,
        load: LoadModel,
        num_requests: int,
        key: jax.Array,
        offered_qps=None,
        block_size: int = 65_536,
        trim: bool = False,
        tail: bool = False,
        tail_cut=None,
    ):
        """The attributed mesh program replayed shard-by-shard on one
        device with the collectives merged on host (sequential psum
        order, host top-K exemplar merge) — the degradation rung /
        equivalence reference for :meth:`run_attributed`."""
        if not self.sim.params.attribution:
            raise ValueError(
                "attributed runs need SimParams(attribution=True)"
            )
        if tail and tail_cut is None:
            tail_cut = self.sim.estimate_tail_cut(
                load, num_requests, key, block_size=block_size
            )
        from isotope_tpu.metrics import attribution

        plan = self._plan_run(load, num_requests, key, offered_qps,
                              block_size, trim)
        self.sim._attribution_tables()  # eager — see run_attributed
        fn = self._get_local_attr_fn(plan, tail)
        vis, windows = self._args_put(plan)
        shards = []
        with telemetry.phase("sharded.emulated"):
            for s in range(self.n_shards):
                out = fn(
                    jnp.int32(s), key,
                    jnp.float32(plan.offered), jnp.float32(plan.gap),
                    jnp.float32(plan.nominal_gap),
                    jnp.float32(plan.window[0]),
                    jnp.float32(plan.window[1]),
                    jnp.float32(tail_cut if tail else np.inf),
                    vis, windows,
                )
                jax.block_until_ready(out[0].count)
                shards.append(out)
        summary = self._merge_shard_summaries([s for s, _ in shards])
        return summary, attribution.merge_host([a for _, a in shards])

    def _local_scan_attr(
        self,
        block: int,
        num_blocks: int,
        kind: str,
        conns_local: int,
        trim: bool,
        sat_conns: int,
        tail: bool,
        shard: jax.Array,
        key: jax.Array,
        offered_qps: jax.Array,
        pace_gap: jax.Array,
        nominal_gap: jax.Array,
        win_lo: jax.Array,
        win_hi: jax.Array,
        tail_cut: jax.Array,
        visits_pc: jax.Array,
        phase_windows: jax.Array,
    ) -> Tuple[RunSummary, attribution.AttributionSummary]:
        """One shard's pre-collective attributed block scan (the
        ``_local_scan`` twin; identical RNG stream layout, so the
        RunSummary half matches the unattributed path bit-for-bit)."""
        # lazy: attribution-off paths never import the blame module
        from isotope_tpu.metrics import attribution

        tables = self.sim._attribution_tables()
        top_k = self.sim.params.attribution_top_k
        local_key = jax.random.fold_in(key, 500_000 + shard)
        c = max(conns_local, 1)
        per = block // c

        def block_body(carry, b):
            (t0, conn_t0, req_off), ex = carry
            kb = jax.random.fold_in(local_key, 1_000_000 + b)
            res, t_end, conn_end = self.sim._simulate_core(
                block, kind, conns_local, kb, offered_qps, pace_gap,
                offered_qps / self.n_shards, nominal_gap, t0, conn_t0,
                req_off,
                sat_conns=sat_conns,
                visits_pc=visits_pc,
                phase_windows=phase_windows,
            )
            s = summarize(
                res, self.collector,
                window=(win_lo, win_hi) if trim else None,
            )
            a, ex = attribution.attribute_block(
                res, tables,
                tail_cut=tail_cut if tail else None,
                top_k=top_k, ex_state=ex,
                packed=self.sim.params.packed_carries,
            )
            return ((t_end, conn_end, req_off + per), ex), (s, a)

        k0 = min(top_k, block) if top_k > 0 else 0
        H = self.compiled.num_hops
        ex0 = (
            attribution.empty_exemplars(k0, H)
            if k0 > 0
            else None
        )
        carry0 = (
            (
                jnp.float32(0.0),
                jnp.zeros((c,), jnp.float32),
                jnp.float32(0.0),
            ),
            ex0,
        )
        (_, ex_final), (parts, aparts) = jax.lax.scan(
            block_body, carry0, jnp.arange(num_blocks)
        )
        return (
            reduce_stacked(parts),
            attribution.reduce_stacked(aparts, ex_final),
        )

    def _attr_body(
        self,
        block: int,
        num_blocks: int,
        kind: str,
        conns_local: int,
        trim: bool,
        sat_conns: int,
        tail: bool,
        key: jax.Array,
        offered_qps: jax.Array,
        pace_gap: jax.Array,
        nominal_gap: jax.Array,
        win_lo: jax.Array,
        win_hi: jax.Array,
        tail_cut: jax.Array,
        visits_pc: jax.Array,
        phase_windows: jax.Array,
    ):
        both = tuple(self.mesh.axis_names)
        shard = jnp.int32(0)
        for a in self.mesh.axis_names:
            shard = shard * self.mesh.shape[a] + jax.lax.axis_index(a)
        summary, attr = self._local_scan_attr(
            block, num_blocks, kind, conns_local, trim, sat_conns,
            tail, shard, key, offered_qps, pace_gap, nominal_gap,
            win_lo, win_hi, tail_cut, visits_pc, phase_windows,
        )
        merged_summary = self._merge_summary_collective(summary, both)
        ex = attr.exemplars
        psummed = jax.tree.map(
            lambda x: jax.lax.psum(x, both),
            attr._replace(tail_cut=jnp.float32(0.0), exemplars=None),
        )
        merged_attr = psummed._replace(tail_cut=attr.tail_cut)
        if ex is not None:
            k = ex.latency.shape[0]

            def gather(x):
                # one new leading axis of size mesh.size; fold it into
                # the K axis so top_k sees every shard's candidates
                y = jax.lax.all_gather(x, both)
                return y.reshape((-1,) + x.shape[1:])

            cat = jax.tree.map(gather, ex)
            _, keep = jax.lax.top_k(cat.latency, k)
            merged_attr = merged_attr._replace(
                exemplars=jax.tree.map(lambda a: a[keep], cat)
            )
        return merged_summary, merged_attr

    def _get_attr(self, plan: _RunPlan, tail: bool):
        cache_key = (plan.block, plan.num_blocks, plan.kind,
                     plan.conns_local, plan.trim, plan.sat_conns, tail)
        key = ("sharded-attr",) + cache_key
        if key not in self._fns:
            from isotope_tpu.metrics import attribution

            body = partial(self._attr_body, *cache_key)
            ex_spec = (
                attribution.ExemplarBatch(*([P()] * 7))
                if self.sim.params.attribution_top_k > 0
                else None
            )
            attr_spec = attribution.AttributionSummary(
                *([P()] * 18), exemplars=ex_spec
            )
            mapped = _shard_map(
                body,
                mesh=self.mesh,
                in_specs=tuple(P() for _ in range(9)),
                out_specs=(self._summary_out_specs(), attr_spec),
            )
            mesh_sig = (
                tuple(self.mesh.axis_names),
                tuple(int(self.mesh.shape[a])
                      for a in self.mesh.axis_names),
                tuple(d.id for d in self.mesh.devices.flat),
            )
            self._fns[key] = executable_cache.get_or_build(
                ("sharded-attr", self.sim.signature, mesh_sig)
                + cache_key,
                lambda: telemetry.time_first_call(
                    jax.jit(mapped), "compile.jit_first_call"
                ),
            )
        return self._fns[key]

    def _get_local_attr_fn(self, plan: _RunPlan, tail: bool):
        cache_key = (plan.block, plan.num_blocks, plan.kind,
                     plan.conns_local, plan.trim, plan.sat_conns, tail)
        full_key = ("sharded-attr-local", self.sim.signature,
                    self.n_shards) + cache_key
        return executable_cache.get_or_build(
            full_key,
            lambda: telemetry.time_first_call(
                jax.jit(partial(self._local_scan_attr, *cache_key)),
                "compile.jit_first_call",
            ),
        )

    # -- timeline runs (metrics/timeline.py) ----------------------------

    def _timeline_plan(self, plan: _RunPlan, window_s):
        """The static window grid for a sharded run: every shard bins
        into the SAME absolute sim-time grid (shard clocks all start at
        t=0), sized from the TOTAL request count and offered rate."""
        total = plan.num_blocks * plan.block * self.n_shards
        return self.sim.plan_timeline_windows(
            total, plan.offered, window_s
        )

    def run_timeline(
        self,
        load: LoadModel,
        num_requests: int,
        key: jax.Array,
        offered_qps=None,
        block_size: int = 65_536,
        trim: bool = False,
        window_s=None,
    ):
        """Sharded twin of :meth:`Simulator.run_timeline`: every shard
        reduces its block scan to (RunSummary, TimelineSummary) and the
        timeline leaves merge with ``psum`` — windows align because all
        shards share the absolute sim-time axis.  Returns
        ``(RunSummary, TimelineSummary)``."""
        if not self.sim.params.timeline:
            raise ValueError(
                "timeline runs need SimParams(timeline=True)"
            )
        self._require_mesh("run_timeline")
        plan = self._plan_run(load, num_requests, key, offered_qps,
                              block_size, trim)
        tl_plan = self._timeline_plan(plan, window_s)
        telemetry.counter_inc("sharded_timeline_runs")
        fn = self._get_tl(plan, tl_plan)
        vis, windows = self._args_put(plan)
        faults.check("sharded.compute")
        out = fn(
            key, jnp.float32(plan.offered), jnp.float32(plan.gap),
            jnp.float32(plan.nominal_gap),
            jnp.float32(plan.window[0]), jnp.float32(plan.window[1]),
            vis, windows,
        )
        faults.check("sharded.gather")
        return out

    def run_timeline_emulated(
        self,
        load: LoadModel,
        num_requests: int,
        key: jax.Array,
        offered_qps=None,
        block_size: int = 65_536,
        trim: bool = False,
        window_s=None,
    ):
        """The timeline mesh program replayed shard-by-shard on one
        device with the psum merged on host (sequential shard-order
        sums) — the degradation rung / equivalence reference for
        :meth:`run_timeline`."""
        if not self.sim.params.timeline:
            raise ValueError(
                "timeline runs need SimParams(timeline=True)"
            )
        from isotope_tpu.metrics import timeline as timeline_mod

        plan = self._plan_run(load, num_requests, key, offered_qps,
                              block_size, trim)
        tl_plan = self._timeline_plan(plan, window_s)
        fn = self._get_local_tl_fn(plan, tl_plan)
        vis, windows = self._args_put(plan)
        shards = []
        with telemetry.phase("sharded.emulated"):
            for s in range(self.n_shards):
                out = fn(
                    jnp.int32(s), key,
                    jnp.float32(plan.offered), jnp.float32(plan.gap),
                    jnp.float32(plan.nominal_gap),
                    jnp.float32(plan.window[0]),
                    jnp.float32(plan.window[1]),
                    vis, windows,
                )
                jax.block_until_ready(out[0].count)
                shards.append(out)
        summary = self._merge_shard_summaries([s for s, _ in shards])
        return summary, timeline_mod.merge_host(
            [t for _, t in shards]
        )

    def _local_scan_tl(
        self,
        block: int,
        num_blocks: int,
        kind: str,
        conns_local: int,
        trim: bool,
        sat_conns: int,
        tl_plan: Tuple[int, float],
        shard: jax.Array,
        key: jax.Array,
        offered_qps: jax.Array,
        pace_gap: jax.Array,
        nominal_gap: jax.Array,
        win_lo: jax.Array,
        win_hi: jax.Array,
        visits_pc: jax.Array,
        phase_windows: jax.Array,
    ):
        """One shard's pre-collective timeline block scan (the
        ``_local_scan`` twin; identical RNG stream layout, so the
        RunSummary half matches the unrecorded path bit-for-bit)."""
        from isotope_tpu.metrics import timeline as timeline_mod

        spec = timeline_mod.build_spec(
            self.compiled, tl_plan[0], tl_plan[1]
        )
        local_key = jax.random.fold_in(key, 500_000 + shard)
        c = max(conns_local, 1)
        per = block // c

        def block_body(carry, b):
            (t0, conn_t0, req_off), tl_acc = carry
            kb = jax.random.fold_in(local_key, 1_000_000 + b)
            res, t_end, conn_end = self.sim._simulate_core(
                block, kind, conns_local, kb, offered_qps, pace_gap,
                offered_qps / self.n_shards, nominal_gap, t0, conn_t0,
                req_off,
                sat_conns=sat_conns,
                visits_pc=visits_pc,
                phase_windows=phase_windows,
            )
            s = summarize(
                res, self.collector,
                window=(win_lo, win_hi) if trim else None,
            )
            # carry accumulation (not stacked ys): one O(S * W)
            # recorder state per shard, independent of num_blocks
            tl_acc = timeline_mod.accumulate(
                tl_acc,
                timeline_mod.timeline_block(
                    res, spec, packed=self.sim.params.packed_carries
                ),
            )
            return ((t_end, conn_end, req_off + per), tl_acc), s

        carry0 = (
            (
                jnp.float32(0.0),
                jnp.zeros((c,), jnp.float32),
                jnp.float32(0.0),
            ),
            timeline_mod.zeros_summary(
                spec, packed=self.sim.params.packed_carries
            ),
        )
        (_, tl_final), parts = jax.lax.scan(
            block_body, carry0, jnp.arange(num_blocks)
        )
        return reduce_stacked(parts), tl_final

    def _tl_body(
        self,
        block: int,
        num_blocks: int,
        kind: str,
        conns_local: int,
        trim: bool,
        sat_conns: int,
        tl_plan: Tuple[int, float],
        key: jax.Array,
        offered_qps: jax.Array,
        pace_gap: jax.Array,
        nominal_gap: jax.Array,
        win_lo: jax.Array,
        win_hi: jax.Array,
        visits_pc: jax.Array,
        phase_windows: jax.Array,
    ):
        both = tuple(self.mesh.axis_names)
        shard = jnp.int32(0)
        for a in self.mesh.axis_names:
            shard = shard * self.mesh.shape[a] + jax.lax.axis_index(a)
        summary, tl = self._local_scan_tl(
            block, num_blocks, kind, conns_local, trim, sat_conns,
            tl_plan, shard, key, offered_qps, pace_gap, nominal_gap,
            win_lo, win_hi, visits_pc, phase_windows,
        )
        merged_summary = self._merge_summary_collective(summary, both)
        # window_s is identical on every shard — exclude it from the
        # psum (the attribution tail_cut idiom)
        psummed = jax.tree.map(
            lambda x: jax.lax.psum(x, both),
            tl._replace(window_s=jnp.float32(0.0)),
        )
        return merged_summary, psummed._replace(window_s=tl.window_s)

    def _get_tl(self, plan: _RunPlan, tl_plan: Tuple[int, float]):
        cache_key = (plan.block, plan.num_blocks, plan.kind,
                     plan.conns_local, plan.trim, plan.sat_conns,
                     tl_plan)
        key = ("sharded-tl",) + cache_key
        if key not in self._fns:
            from isotope_tpu.metrics import timeline as timeline_mod

            body = partial(self._tl_body, *cache_key)
            n_fields = len(timeline_mod.TimelineSummary._fields)
            tl_spec = timeline_mod.TimelineSummary(
                *([P()] * n_fields)
            )
            mapped = _shard_map(
                body,
                mesh=self.mesh,
                in_specs=tuple(P() for _ in range(8)),
                out_specs=(self._summary_out_specs(), tl_spec),
            )
            mesh_sig = (
                tuple(self.mesh.axis_names),
                tuple(int(self.mesh.shape[a])
                      for a in self.mesh.axis_names),
                tuple(d.id for d in self.mesh.devices.flat),
            )
            self._fns[key] = executable_cache.get_or_build(
                ("sharded-tl", self.sim.signature, mesh_sig)
                + cache_key,
                lambda: telemetry.time_first_call(
                    jax.jit(mapped), "compile.jit_first_call"
                ),
            )
        return self._fns[key]

    def _get_local_tl_fn(self, plan: _RunPlan,
                         tl_plan: Tuple[int, float]):
        cache_key = (plan.block, plan.num_blocks, plan.kind,
                     plan.conns_local, plan.trim, plan.sat_conns,
                     tl_plan)
        full_key = ("sharded-tl-local", self.sim.signature,
                    self.n_shards) + cache_key
        return executable_cache.get_or_build(
            full_key,
            lambda: telemetry.time_first_call(
                jax.jit(partial(self._local_scan_tl, *cache_key)),
                "compile.jit_first_call",
            ),
        )

    # -- protected co-sim runs (sim/policies.py + sim/rollout.py) -------

    def _require_policies(self, load: LoadModel) -> None:
        if self.sim._policies is None:
            raise ValueError(
                "policy runs need compiled policy tables "
                "(ShardedSimulator(..., policies=...))"
            )
        self._require_protected(load, "policy", "run_policies")

    def _require_rollouts(self, load: LoadModel) -> None:
        if self.sim._rollouts is None:
            raise ValueError(
                "rollout runs need compiled rollout tables "
                "(ShardedSimulator(..., rollouts=...))"
            )
        self._require_protected(load, "rollout", "run_rollouts")

    def _require_protected(self, load: LoadModel, what: str,
                           method: str) -> None:
        if not self.sim.params.timeline:
            raise ValueError(
                f"{what} runs need SimParams(timeline=True)"
            )
        if self.sim._saturated(load):
            raise ValueError(
                f"{what} runs do not support saturated -qps max loads "
                "(static finite-population tables; see "
                f"Simulator.{method})"
            )
        if self.n_svc != 1:
            raise ValueError(
                f"{what} runs need a mesh with svc=1: the per-service "
                "control state is replicated across shards (every "
                "shard advances the identical trajectory from the "
                "psum-merged window signals), which a svc-sharded "
                "metric layout would split"
            )

    def run_policies(
        self,
        load: LoadModel,
        num_requests: int,
        key: jax.Array,
        offered_qps=None,
        block_size: int = 65_536,
        trim: bool = False,
        window_s=None,
        attribution: bool = False,
        tail: bool = False,
        tail_cut=None,
    ):
        """Sharded twin of :meth:`Simulator.run_policies`: every shard
        scans its blocks under the SHARED policy state — each block's
        flight-recorder contribution (and the retry-observation
        channel) is psum-merged ACROSS the mesh inside the scan, so
        the control law advances from global window signals and every
        shard actuates the identical trajectory.  Returns
        ``(RunSummary, TimelineSummary, PolicySummary)``; the
        timeline/policy outputs are replicated (already globally
        merged) and bit-equal to :meth:`run_policies_emulated`.

        ``attribution=True`` ALSO reduces the PR-5 critical-path blame
        over the protected physics inside the same scan: the O(H) /
        O(S x buckets) blame accumulators merge with ``psum`` and the
        top-K exemplar batch with ``all_gather`` + ``top_k`` (the
        :meth:`run_attributed` collectives), appending an
        ``AttributionSummary`` to the return."""
        self._require_policies(load)
        self._require_mesh("run_policies")
        return self._protected_run(
            "policy", False, load, num_requests, key, offered_qps,
            block_size, trim, window_s, attribution, tail, tail_cut,
        )

    def run_rollouts(
        self,
        load: LoadModel,
        num_requests: int,
        key: jax.Array,
        offered_qps=None,
        block_size: int = 65_536,
        trim: bool = False,
        window_s=None,
        attribution: bool = False,
        tail: bool = False,
        tail_cut=None,
    ):
        """Sharded twin of :meth:`Simulator.run_rollouts`: every shard
        routes its hops through the SHARED rollout state's canary
        weights, the per-version (S, 2, W, 4) observation channel
        psum-merges across the mesh inside the scan, and every shard
        advances the identical promote/hold/rollback trajectory —
        bit-equal to :meth:`run_rollouts_emulated` (pinned).  Returns
        ``(RunSummary, TimelineSummary, RolloutSummary)``, appending a
        ``PolicySummary`` when policy tables are also compiled (the
        PR 9 loops ride the same carry) and an ``AttributionSummary``
        under ``attribution=True``."""
        self._require_rollouts(load)
        self._require_mesh("run_rollouts")
        return self._protected_run(
            "rollout", True, load, num_requests, key, offered_qps,
            block_size, trim, window_s, attribution, tail, tail_cut,
        )

    def run_policies_emulated(
        self,
        load: LoadModel,
        num_requests: int,
        key: jax.Array,
        offered_qps=None,
        block_size: int = 65_536,
        trim: bool = False,
        window_s=None,
        attribution: bool = False,
        tail: bool = False,
        tail_cut=None,
    ):
        """The policy mesh program replayed on one device: unlike the
        other ``*_emulated`` twins (whole-scan per shard), the policy
        control loop couples shards PER BLOCK — every shard's block
        feeds the psum the state advance consumes — so the twin runs
        one scan whose body sweeps ALL shards' blocks in shard order,
        merges their recorder contributions sequentially (the CPU
        psum's association order — ICI shards within a slice first,
        slices last), and advances the shared state once.  Bit-equal
        to :meth:`run_policies` on CPU (pinned); with
        ``attribution=True`` the per-shard blame stacks merge on host
        (``attribution.merge_host``) after the scan."""
        self._require_policies(load)
        return self._protected_emulated(
            "policy", False, load, num_requests, key, offered_qps,
            block_size, trim, window_s, attribution, tail, tail_cut,
        )

    def run_rollouts_emulated(
        self,
        load: LoadModel,
        num_requests: int,
        key: jax.Array,
        offered_qps=None,
        block_size: int = 65_536,
        trim: bool = False,
        window_s=None,
        attribution: bool = False,
        tail: bool = False,
        tail_cut=None,
    ):
        """The rollout mesh program replayed on one device (the
        :meth:`run_policies_emulated` per-block coupling, extended
        with the per-version observation channel) — the equivalence
        reference / degradation rung for :meth:`run_rollouts`."""
        self._require_rollouts(load)
        return self._protected_emulated(
            "rollout", True, load, num_requests, key, offered_qps,
            block_size, trim, window_s, attribution, tail, tail_cut,
        )

    def _protected_prologue(self, what, load, num_requests, key,
                            offered_qps, block_size, trim, window_s,
                            attribution, tail, tail_cut, counter):
        """Shared device/emulated-twin setup for a protected run:
        validates the attribution precondition, estimates the tail
        cut, plans the run/timeline, and arms the policy fault sites.
        Returns ``(plan, tl_plan, attr, tail_cut)``.  One body for
        both paths so the pinned bit-equality contract cannot be
        diverged by a fix applied to only one of them."""
        if attribution and not self.sim.params.attribution:
            raise ValueError(
                f"attributed {what} runs need SimParams("
                "attribution=True)"
            )
        if attribution and tail and tail_cut is None:
            tail_cut = self.sim.estimate_tail_cut(
                load, num_requests, key, block_size=block_size
            )
        plan = self._plan_run(load, num_requests, key, offered_qps,
                              block_size, trim)
        tl_plan = self._timeline_plan(plan, window_s)
        telemetry.counter_inc(counter)
        if self.sim._policies is not None:
            faults.check("policies.stuck_breaker")
            faults.check("policies.autoscaler_lag")
        if attribution:
            # eager: constants created inside the shard_map trace
            # would be cached as tracers and leak
            self.sim._attribution_tables()
        attr = ("tail" if tail else "mean") if attribution else None
        return plan, tl_plan, attr, tail_cut

    def _protected_run(self, what: str, roll: bool, load, num_requests,
                       key, offered_qps, block_size, trim, window_s,
                       attribution, tail, tail_cut):
        plan, tl_plan, attr, tail_cut = self._protected_prologue(
            what, load, num_requests, key, offered_qps, block_size,
            trim, window_s, attribution, tail, tail_cut,
            f"sharded_{what}_runs",
        )
        fn = self._get_prot(plan, tl_plan, attr, roll)
        vis, windows = self._args_put(plan)
        faults.check("sharded.compute")
        out = fn(
            key, jnp.float32(plan.offered), jnp.float32(plan.gap),
            jnp.float32(plan.nominal_gap),
            jnp.float32(plan.window[0]), jnp.float32(plan.window[1]),
            jnp.float32(
                tail_cut
                if (attribution and tail_cut is not None)
                else np.inf
            ),
            vis, windows,
        )
        faults.check("sharded.gather")
        return out

    def _protected_emulated(self, what: str, roll: bool, load,
                            num_requests, key, offered_qps, block_size,
                            trim, window_s, attribution, tail,
                            tail_cut):
        plan, tl_plan, attr, tail_cut = self._protected_prologue(
            what, load, num_requests, key, offered_qps, block_size,
            trim, window_s, attribution, tail, tail_cut,
            f"sharded_{what}_emulated_runs",
        )
        fn = self._get_local_prot_fn(plan, tl_plan, attr, roll)
        vis, windows = self._args_put(plan)
        with telemetry.phase("sharded.emulated"):
            out = fn(
                key, jnp.float32(plan.offered), jnp.float32(plan.gap),
                jnp.float32(plan.nominal_gap),
                jnp.float32(plan.window[0]),
                jnp.float32(plan.window[1]),
                jnp.float32(
                    tail_cut
                    if (attribution and tail_cut is not None)
                    else np.inf
                ),
                vis, windows,
            )
            jax.block_until_ready(out[1].count)
        shard_summaries, rest = out[0], list(out[1:])
        merged = [self._merge_shard_summaries(list(shard_summaries))]
        merged.append(rest.pop(0))  # timeline (host-side global)
        if roll:
            merged.append(rest.pop(0))
        if self.sim._policies is not None:
            merged.append(rest.pop(0))
        if attr is not None:
            from isotope_tpu.metrics import attribution as attr_mod

            merged.append(attr_mod.merge_host(list(rest.pop(0))))
        return tuple(merged)

    def _prot_block_ctx(self, tl_plan: Tuple[int, float], roll: bool):
        """Static protected-scan context shared by the shard_map body
        and the emulated twin (identical traced control program)."""
        from isotope_tpu.metrics import timeline as timeline_mod

        spec = timeline_mod.build_spec(
            self.compiled, tl_plan[0], tl_plan[1]
        )
        ctx = dict(
            spec=spec,
            packed=self.sim.params.packed_carries,
            tl_mod=timeline_mod,
            with_pol=self.sim._policies is not None,
            pol_mod=None,
            roll_mod=None,
        )
        if ctx["with_pol"]:
            from isotope_tpu.sim import policies as policies_mod

            ctx.update(
                pol_mod=policies_mod,
                dtab=policies_mod.device_tables(self.sim._policies),
                downed_w=self.sim._policy_downed_windows(
                    spec, base_split=roll
                ),
                stuck=faults.stuck_breaker(),
                lag=faults.autoscaler_lag(),
                retry_mask=jnp.asarray(self.compiled.hop_attempt > 0),
            )
        if roll:
            from isotope_tpu.sim import rollout as rollout_mod

            ctx.update(
                roll_mod=rollout_mod,
                rdtab=rollout_mod.device_tables(self.sim._rollouts),
            )
        return ctx

    def _prot_body(
        self,
        block: int,
        num_blocks: int,
        kind: str,
        conns_local: int,
        trim: bool,
        tl_plan: Tuple[int, float],
        attr,
        roll: bool,
        key: jax.Array,
        offered_qps: jax.Array,
        pace_gap: jax.Array,
        nominal_gap: jax.Array,
        win_lo: jax.Array,
        win_hi: jax.Array,
        tail_cut: jax.Array,
        visits_pc: jax.Array,
        phase_windows: jax.Array,
    ):
        ctx = self._prot_block_ctx(tl_plan, roll)
        spec, tl_mod = ctx["spec"], ctx["tl_mod"]
        pol_mod, roll_mod = ctx["pol_mod"], ctx["roll_mod"]
        with_pol = ctx["with_pol"]
        both = tuple(self.mesh.axis_names)
        shard = jnp.int32(0)
        for a in self.mesh.axis_names:
            shard = shard * self.mesh.shape[a] + jax.lax.axis_index(a)
        local_key = jax.random.fold_in(key, 500_000 + shard)
        c = max(conns_local, 1)
        per = block // c
        S = self.compiled.num_services
        W = spec.num_windows
        if attr is not None:
            from isotope_tpu.metrics import attribution

            atables = self.sim._attribution_tables()
            top_k = self.sim.params.attribution_top_k

        def block_body(carry, b):
            ((t0, conn_t0, req_off), tl_acc, pobs_acc, pstate,
             pol_acc, robs_acc, rstate, roll_acc, ex) = carry
            pfx = pol_mod.effects(pstate) if with_pol else None
            rfx = roll_mod.effects(rstate) if roll else None
            kb = jax.random.fold_in(local_key, 1_000_000 + b)
            res, t_end, conn_end = self.sim._simulate_core(
                block, kind, conns_local, kb, offered_qps, pace_gap,
                offered_qps / self.n_shards, nominal_gap, t0, conn_t0,
                req_off,
                visits_pc=visits_pc,
                phase_windows=phase_windows,
                policy_fx=pfx,
                rollout_fx=rfx,
            )
            s = summarize(
                res, self.collector,
                window=(win_lo, win_hi) if trim else None,
            )
            # the control loops consume GLOBAL window signals: each
            # block's recorder contribution (and the policy/rollout
            # observation channels) psums across the mesh before the
            # (replicated) state advances — the collectives the
            # emulated twin replays in shard order
            tl_blk = tl_mod.timeline_block(res, spec,
                                           packed=ctx["packed"])
            tl_blk = jax.tree.map(
                lambda x: jax.lax.psum(x, both),
                tl_blk._replace(window_s=jnp.float32(0.0)),
            )._replace(window_s=jnp.float32(spec.window_s))
            tl_acc = tl_mod.accumulate(tl_acc, tl_blk)
            if with_pol:
                pobs_acc = pobs_acc + jax.lax.psum(
                    pol_mod.observe_block(res, spec,
                                          ctx["retry_mask"]),
                    both,
                )
            if roll:
                robs_acc = robs_acc + jax.lax.psum(
                    roll_mod.observe_block(res, spec), both
                )
            # a window is final once EVERY shard's SLOWEST clock
            # passed it (closed loop: the slowest connection, not
            # conn_end.max() — faster connections' later blocks still
            # write into earlier windows)
            t_local = (
                jnp.min(conn_end)
                if kind != OPEN_LOOP
                else t_end
            )
            t_done = jax.lax.pmin(t_local, both)
            if roll:
                rstate, rdelta = roll_mod.advance(
                    rstate, ctx["rdtab"], robs_acc, t_done, spec
                )
                roll_acc = roll_mod.accumulate_summary(
                    roll_acc, rdelta
                )
            if with_pol:
                pstate, delta = pol_mod.advance(
                    pstate, ctx["dtab"], tl_acc, pobs_acc, t_done,
                    spec, stuck_breaker=ctx["stuck"],
                    downed_w=ctx["downed_w"],
                )
                pol_acc = pol_mod.accumulate_summary(pol_acc, delta)
            ys = s
            if attr is not None:
                a_blk, ex = attribution.attribute_block(
                    res, atables,
                    tail_cut=tail_cut if attr == "tail" else None,
                    top_k=top_k, ex_state=ex,
                    packed=ctx["packed"],
                )
                ys = (s, a_blk)
            return (
                (t_end, conn_end, req_off + per),
                tl_acc, pobs_acc, pstate, pol_acc,
                robs_acc, rstate, roll_acc, ex,
            ), ys

        ex0 = None
        if attr is not None:
            k0 = (
                min(top_k, block) if top_k > 0 else 0
            )
            H = self.compiled.num_hops
            ex0 = (
                attribution.empty_exemplars(k0, H)
                if k0 > 0
                else None
            )
        carry0 = (
            (
                jnp.float32(0.0),
                jnp.zeros((c,), jnp.float32),
                jnp.float32(0.0),
            ),
            tl_mod.zeros_summary(spec, packed=ctx["packed"]),
            jnp.zeros((S, W)) if with_pol else None,
            (
                pol_mod.init_state(ctx["dtab"],
                                   lag_periods=ctx["lag"])
                if with_pol else None
            ),
            pol_mod.zeros_summary(spec, S) if with_pol else None,
            jnp.zeros((S, 2, W, 4)) if roll else None,
            roll_mod.init_state(ctx["rdtab"]) if roll else None,
            roll_mod.zeros_summary(spec, S) if roll else None,
            ex0,
        )
        (
            (_, tl_final, _, _, pol_final, robs_final, _, roll_final,
             ex_final),
            ys,
        ) = jax.lax.scan(block_body, carry0, jnp.arange(num_blocks))
        if attr is not None:
            parts, aparts = ys
        else:
            parts = ys
        merged_summary = self._merge_summary_collective(
            reduce_stacked(parts), both
        )
        # tl/pol/roll finals are already global (per-block psums) and
        # replicated across shards
        out = (merged_summary, tl_final)
        if roll:
            out = out + (
                roll_mod.attach_observations(roll_final, robs_final),
            )
        if with_pol:
            out = out + (pol_final,)
        if attr is not None:
            # blame accumulators merge exactly like run_attributed:
            # psum for the dense vectors, all_gather + top_k for the
            # exemplar batch (every shard returns the global top-K)
            local_attr = attribution.reduce_stacked(aparts, ex_final)
            ex = local_attr.exemplars
            psummed = jax.tree.map(
                lambda x: jax.lax.psum(x, both),
                local_attr._replace(
                    tail_cut=jnp.float32(0.0), exemplars=None
                ),
            )
            merged_attr = psummed._replace(
                tail_cut=local_attr.tail_cut
            )
            if ex is not None:
                k = ex.latency.shape[0]

                def gather(x):
                    y = jax.lax.all_gather(x, both)
                    return y.reshape((-1,) + x.shape[1:])

                cat = jax.tree.map(gather, ex)
                _, keep = jax.lax.top_k(cat.latency, k)
                merged_attr = merged_attr._replace(
                    exemplars=jax.tree.map(lambda a: a[keep], cat)
                )
            out = out + (merged_attr,)
        return out

    def _local_prot_scan_all(
        self,
        block: int,
        num_blocks: int,
        kind: str,
        conns_local: int,
        trim: bool,
        tl_plan: Tuple[int, float],
        attr,
        roll: bool,
        key: jax.Array,
        offered_qps: jax.Array,
        pace_gap: jax.Array,
        nominal_gap: jax.Array,
        win_lo: jax.Array,
        win_hi: jax.Array,
        tail_cut: jax.Array,
        visits_pc: jax.Array,
        phase_windows: jax.Array,
    ):
        """The emulated twin's whole-mesh scan: one traced program
        whose block body sweeps every shard (unrolled, shard order)
        and replays the per-block psums as sequential sums in the
        device merge's association order (ICI shards within each
        slice first, slice partials last).  Per-shard blame stacks
        (``attr``) come back un-merged; the caller host-merges them."""
        ctx = self._prot_block_ctx(tl_plan, roll)
        spec, tl_mod = ctx["spec"], ctx["tl_mod"]
        pol_mod, roll_mod = ctx["pol_mod"], ctx["roll_mod"]
        with_pol = ctx["with_pol"]
        R = self.n_shards
        c = max(conns_local, 1)
        per = block // c
        S = self.compiled.num_services
        W = spec.num_windows
        n_slices = dict(self.mesh.shape).get(SLICE_AXIS, 1)
        per_slice = R // max(n_slices, 1)
        if attr is not None:
            from isotope_tpu.metrics import attribution

            atables = self.sim._attribution_tables()
            top_k = self.sim.params.attribution_top_k

        def _hier_sum(vals):
            def _seq(vs):
                acc = vs[0]
                for v in vs[1:]:
                    acc = jax.tree.map(jnp.add, acc, v)
                return acc

            return _seq([
                _seq(vals[i * per_slice:(i + 1) * per_slice])
                for i in range(max(n_slices, 1))
            ])

        def block_body(carry, b):
            (t0s, conn_t0s, req_offs), tl_acc, pobs_acc, pstate, \
                pol_acc, robs_acc, rstate, roll_acc, exs = carry
            pfx = pol_mod.effects(pstate) if with_pol else None
            rfx = roll_mod.effects(rstate) if roll else None
            sums = []
            ablks = []
            exs_out = []
            tl_parts = []
            pobs_parts = []
            robs_parts = []
            t_ends = []
            conn_ends = []
            for s_i in range(R):
                kb = jax.random.fold_in(
                    jax.random.fold_in(key, 500_000 + s_i),
                    1_000_000 + b,
                )
                res, t_end, conn_end = self.sim._simulate_core(
                    block, kind, conns_local, kb, offered_qps,
                    pace_gap, offered_qps / R, nominal_gap,
                    t0s[s_i], conn_t0s[s_i], req_offs[s_i],
                    visits_pc=visits_pc,
                    phase_windows=phase_windows,
                    policy_fx=pfx,
                    rollout_fx=rfx,
                )
                sums.append(summarize(
                    res, self.collector,
                    window=(win_lo, win_hi) if trim else None,
                ))
                tl_parts.append(
                    tl_mod.timeline_block(res, spec,
                                          packed=ctx["packed"])
                )
                if with_pol:
                    pobs_parts.append(
                        pol_mod.observe_block(res, spec,
                                              ctx["retry_mask"])
                    )
                if roll:
                    robs_parts.append(
                        roll_mod.observe_block(res, spec)
                    )
                if attr is not None:
                    a_blk, ex_i = attribution.attribute_block(
                        res, atables,
                        tail_cut=(
                            tail_cut if attr == "tail" else None
                        ),
                        top_k=top_k, ex_state=exs[s_i],
                        packed=ctx["packed"],
                    )
                    ablks.append(a_blk)
                    exs_out.append(ex_i)
                t_ends.append(t_end)
                conn_ends.append(conn_end)
            tl_blk = _hier_sum([
                p._replace(window_s=jnp.float32(0.0))
                for p in tl_parts
            ])._replace(window_s=jnp.float32(spec.window_s))
            tl_acc = tl_mod.accumulate(tl_acc, tl_blk)
            if with_pol:
                pobs_acc = pobs_acc + _hier_sum(pobs_parts)
            if roll:
                robs_acc = robs_acc + _hier_sum(robs_parts)
            locals_ = [
                jnp.min(ce) if kind != OPEN_LOOP else te
                for te, ce in zip(t_ends, conn_ends)
            ]
            t_done = locals_[0]
            for t in locals_[1:]:
                t_done = jnp.minimum(t_done, t)
            if roll:
                rstate, rdelta = roll_mod.advance(
                    rstate, ctx["rdtab"], robs_acc, t_done, spec
                )
                roll_acc = roll_mod.accumulate_summary(
                    roll_acc, rdelta
                )
            if with_pol:
                pstate, delta = pol_mod.advance(
                    pstate, ctx["dtab"], tl_acc, pobs_acc, t_done,
                    spec, stuck_breaker=ctx["stuck"],
                    downed_w=ctx["downed_w"],
                )
                pol_acc = pol_mod.accumulate_summary(pol_acc, delta)
            carry_out = (
                (
                    jnp.stack(t_ends),
                    jnp.stack(conn_ends),
                    req_offs + per,
                ),
                tl_acc, pobs_acc, pstate, pol_acc,
                robs_acc, rstate, roll_acc,
                tuple(exs_out) if attr is not None else None,
            )
            ys = tuple(sums)
            if attr is not None:
                ys = (ys, tuple(ablks))
            return carry_out, ys

        carry0 = (
            (
                jnp.zeros((R,), jnp.float32),
                jnp.zeros((R, c), jnp.float32),
                jnp.zeros((R,), jnp.float32),
            ),
            tl_mod.zeros_summary(spec, packed=ctx["packed"]),
            jnp.zeros((S, W)) if with_pol else None,
            (
                pol_mod.init_state(ctx["dtab"],
                                   lag_periods=ctx["lag"])
                if with_pol else None
            ),
            pol_mod.zeros_summary(spec, S) if with_pol else None,
            jnp.zeros((S, 2, W, 4)) if roll else None,
            roll_mod.init_state(ctx["rdtab"]) if roll else None,
            roll_mod.zeros_summary(spec, S) if roll else None,
            None,
        )
        if attr is not None:
            k0 = min(top_k, block) if top_k > 0 else 0
            H = self.compiled.num_hops
            ex0 = (
                attribution.empty_exemplars(k0, H)
                if k0 > 0
                else None
            )
            carry0 = carry0[:-1] + (tuple(ex0 for _ in range(R)),)
        (
            (_, tl_final, _, _, pol_final, robs_final, _, roll_final,
             exs_final),
            ys,
        ) = jax.lax.scan(block_body, carry0, jnp.arange(num_blocks))
        if attr is not None:
            parts, aparts = ys
        else:
            parts = ys
        out = (
            tuple(reduce_stacked(p) for p in parts),
            tl_final,
        )
        if roll:
            out = out + (
                roll_mod.attach_observations(roll_final, robs_final),
            )
        if with_pol:
            out = out + (pol_final,)
        if attr is not None:
            out = out + (tuple(
                attribution.reduce_stacked(ap, ex)
                for ap, ex in zip(aparts, exs_final)
            ),)
        return out

    def _prot_cache_key(self, plan: _RunPlan, tl_plan, attr,
                        roll: bool):
        return (plan.block, plan.num_blocks, plan.kind,
                plan.conns_local, plan.trim, tl_plan, attr, roll)

    def _get_prot(self, plan: _RunPlan, tl_plan: Tuple[int, float],
                  attr, roll: bool):
        cache_key = self._prot_cache_key(plan, tl_plan, attr, roll)
        key = ("sharded-prot",) + cache_key
        if key not in self._fns:
            from isotope_tpu.metrics import timeline as timeline_mod

            body = partial(self._prot_body, *cache_key)
            tl_spec = timeline_mod.TimelineSummary(
                *([P()] * len(timeline_mod.TimelineSummary._fields))
            )
            out_specs = [self._summary_out_specs(), tl_spec]
            if roll:
                from isotope_tpu.sim import rollout as rollout_mod

                out_specs.append(rollout_mod.RolloutSummary(
                    *([P()] * len(rollout_mod.RolloutSummary._fields))
                ))
            if self.sim._policies is not None:
                from isotope_tpu.sim import policies as policies_mod

                out_specs.append(policies_mod.PolicySummary(
                    *([P()] * len(policies_mod.PolicySummary._fields))
                ))
            if attr is not None:
                from isotope_tpu.metrics import attribution

                ex_spec = (
                    attribution.ExemplarBatch(*([P()] * 7))
                    if self.sim.params.attribution_top_k > 0
                    else None
                )
                out_specs.append(attribution.AttributionSummary(
                    *([P()] * 18), exemplars=ex_spec
                ))
            mapped = _shard_map(
                body,
                mesh=self.mesh,
                in_specs=tuple(P() for _ in range(9)),
                out_specs=tuple(out_specs),
            )
            mesh_sig = (
                tuple(self.mesh.axis_names),
                tuple(int(self.mesh.shape[a])
                      for a in self.mesh.axis_names),
                tuple(d.id for d in self.mesh.devices.flat),
            )
            self._fns[key] = executable_cache.get_or_build(
                ("sharded-prot", self.sim.signature, mesh_sig)
                + cache_key,
                lambda: telemetry.time_first_call(
                    jax.jit(mapped), "compile.jit_first_call"
                ),
            )
        return self._fns[key]

    def _get_local_prot_fn(self, plan: _RunPlan,
                           tl_plan: Tuple[int, float], attr,
                           roll: bool):
        cache_key = self._prot_cache_key(plan, tl_plan, attr, roll)
        full_key = ("sharded-prot-local", self.sim.signature,
                    self.n_shards) + cache_key
        return executable_cache.get_or_build(
            full_key,
            lambda: telemetry.time_first_call(
                jax.jit(partial(self._local_prot_scan_all,
                                *cache_key)),
                "compile.jit_first_call",
            ),
        )

    # -- single-device degradation rung --------------------------------

    def run_emulated(
        self,
        load: LoadModel,
        num_requests: int,
        key: jax.Array,
        offered_qps=None,
        block_size: int = 65_536,
        trim: bool = False,
    ) -> RunSummary:
        """The sharded program replayed shard-by-shard on one device.

        Two jobs share this path:

        - the OOM degradation ladder's ``single-device`` rung: when
          the full mesh program exhausts HBM (or devices are lost),
          each shard's block scan — bit-identical RNG streams,
          identical blocking, via the shared ``_local_scan`` body —
          executes serially on the default device, and the metric
          collectives are replayed on host.  Peak live memory is one
          shard's event tensors instead of the whole mesh's;
        - the **emulated multi-host twin**: built over an
          :class:`~isotope_tpu.parallel.mesh.EmulatedMesh`, the same
          loop replays ANY host count (2 hosts x 8 devices, 64 x 4,
          ...) on one CPU — the CI pin for multi-host programs before
          a pod exists.

        Results match the shard_map path to f32 reduction-order
        precision (<= 1 ULP on every field, measured bit-equal on CPU;
        pinned by tests/test_resilience.py and tests/test_multihost.py).
        The host merge always replays the overlap=off reduction order
        (blocks within a shard, then shards): with ``overlap=True`` the
        device path's per-block collective order differs by f32
        reduction order only.
        """
        plan = self._plan_run(load, num_requests, key, offered_qps,
                              block_size, trim)
        telemetry.counter_inc("sharded_emulated_runs")
        telemetry.gauge_set("shard_count", self.n_shards)
        fn = self._get_local_fn(plan)
        vis, windows = self._args_put(plan)
        shards = []
        with telemetry.phase("sharded.emulated"):
            for s in range(self.n_shards):
                out = fn(
                    jnp.int32(s), key,
                    jnp.float32(plan.offered), jnp.float32(plan.gap),
                    jnp.float32(plan.nominal_gap),
                    jnp.float32(plan.window[0]),
                    jnp.float32(plan.window[1]),
                    vis, windows,
                )
                # serialize: live memory stays bounded by ONE shard
                jax.block_until_ready(out.count)
                shards.append(out)
        return self._merge_shard_summaries(shards)

    def _get_local_fn(self, plan: _RunPlan):
        cache_key = (plan.block, plan.num_blocks, plan.kind,
                     plan.conns_local, plan.trim, plan.sat_conns)
        full_key = ("sharded-local", self.sim.signature,
                    self.n_shards) + cache_key
        return executable_cache.get_or_build(
            full_key,
            lambda: telemetry.time_first_call(
                jax.jit(partial(self._local_scan, *cache_key)),
                "compile.jit_first_call",
            ),
        )

    def _merge_shard_summaries(self, shards) -> RunSummary:
        """Host replay of the mesh collectives over per-shard summaries.

        Cross-shard sums accumulate SEQUENTIALLY in shard order at the
        summaries' own dtype — the reduction order XLA's CPU psum uses
        (measured: 200/200 random draws bit-equal; a tree-order backend
        would still land within ~log2(shards) ULP) — and the Welford
        cross-shard term repeats the exact f32 steps of the device
        merge, so the degraded path's results are indistinguishable
        from the mesh path's.
        """
        # DCN-aware association replay: the device merge reduces the
        # ICI axes first (one psum per slice) and the slice axis last,
        # so the host sums each slice's shards sequentially, then the
        # slice partials — the order XLA's CPU collectives take
        # (measured bit-equal; a flat sum differs by 1 ULP on float
        # sums once a slice axis exists)
        n_slices = dict(self.mesh.shape).get(SLICE_AXIS, 1)
        per_slice = len(shards) // max(n_slices, 1)

        def stack(get):
            return np.stack([np.asarray(get(s)) for s in shards])

        def _seq(vals):
            acc = vals[0]
            for v in vals[1:]:
                acc = acc + v  # elementwise, own dtype
            return acc

        def _hier(vals):
            return _seq([
                _seq(vals[i * per_slice:(i + 1) * per_slice])
                for i in range(max(n_slices, 1))
            ])

        def allsum(get):
            return _hier([np.asarray(get(s)) for s in shards])

        def scatter_svc(get):
            # psum over request axes + tiled psum_scatter over svc ==
            # the zero-padded total sum laid out over the svc axis
            # (histogram counts: integer-valued, order-insensitive)
            x = allsum(get)
            pad = self.s_pad - x.shape[0]
            if pad:
                x = np.pad(x, [(0, pad)] + [(0, 0)] * (x.ndim - 1))
            return x

        counts = stack(lambda s: s.count)          # (R,) f32
        sums = stack(lambda s: s.latency_sum)
        m2s = stack(lambda s: s.latency_m2)
        n_tot = allsum(lambda s: s.count)
        s_tot = allsum(lambda s: s.latency_sum)
        mean_local = sums / np.maximum(counts, counts.dtype.type(1.0))
        mean_tot = s_tot / np.maximum(n_tot, n_tot.dtype.type(1.0))
        terms = m2s + counts * (mean_local - mean_tot) ** 2
        m2_tot = _hier(list(terms))
        m = shards[0].metrics
        metrics = None
        if m is not None:
            metrics = ServiceMetrics(
                incoming_total=allsum(lambda s: s.metrics.incoming_total),
                outgoing_total=allsum(lambda s: s.metrics.outgoing_total),
                outgoing_size_hist=allsum(
                    lambda s: s.metrics.outgoing_size_hist
                ),
                outgoing_size_sum=allsum(
                    lambda s: s.metrics.outgoing_size_sum
                ),
                duration_hist=scatter_svc(
                    lambda s: s.metrics.duration_hist
                ),
                duration_sum=allsum(lambda s: s.metrics.duration_sum),
                response_size_hist=scatter_svc(
                    lambda s: s.metrics.response_size_hist
                ),
                response_size_sum=allsum(
                    lambda s: s.metrics.response_size_sum
                ),
            )
        return RunSummary(
            count=n_tot,
            error_count=allsum(lambda s: s.error_count),
            hop_events=allsum(lambda s: s.hop_events),
            latency_sum=s_tot,
            latency_m2=m2_tot,
            latency_min=stack(lambda s: s.latency_min).min(axis=0),
            latency_max=stack(lambda s: s.latency_max).max(axis=0),
            latency_hist=allsum(lambda s: s.latency_hist),
            end_max=stack(lambda s: s.end_max).max(axis=0),
            win_lo=np.asarray(shards[0].win_lo),
            win_hi=np.asarray(shards[0].win_hi),
            win_count=allsum(lambda s: s.win_count),
            win_error_count=allsum(lambda s: s.win_error_count),
            win_latency_hist=allsum(lambda s: s.win_latency_hist),
            metrics=metrics,
            utilization=np.asarray(shards[0].utilization),
            unstable=np.asarray(shards[0].unstable),
        )
