"""shard_map'd simulation with collective-merged metrics.

Every device simulates a disjoint slice of the request stream (the event
tensor's leading axis is the ``data`` x ``svc`` mesh), then results merge
with XLA collectives riding ICI:

- scalar counters / the fine latency histogram: ``psum`` over both axes;
- per-service duration histograms: ``psum`` over ``data``, then
  ``psum_scatter`` over ``svc`` so the (service, code, bucket) state ends
  up sharded across the ``svc`` axis — cross-partition edges become
  collectives, not RPCs (SURVEY.md §5.8).

There is deliberately no cross-device traffic *during* the event sweeps:
the hop program is replicated (topology tensors are tiny next to the event
tensor) and requests are independent given the analytic queue model, so
the only communication is the metric reduction — the design that makes
>1e9 hop-events/s reachable on a v5e-8.
"""
from __future__ import annotations

from functools import partial
from typing import Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from isotope_tpu.compiler.program import CompiledGraph
from isotope_tpu.metrics.histogram import (
    NUM_BUCKETS,
    latency_histogram,
    quantile_from_histogram,
)
from isotope_tpu.metrics.prometheus import MetricsCollector, ServiceMetrics
from isotope_tpu.parallel.mesh import DATA_AXIS, SVC_AXIS
from isotope_tpu.sim.config import CLOSED_LOOP, OPEN_LOOP, LoadModel, SimParams
from isotope_tpu.sim.engine import Simulator


class ShardedSummary(NamedTuple):
    """Globally-reduced run summary (small; per-request tensors stay
    device-local and are never materialized on host)."""

    count: jax.Array          # scalar — requests simulated
    error_count: jax.Array    # scalar — client-visible 500s
    hop_events: jax.Array     # scalar — executed hops (the benchmark unit)
    latency_sum: jax.Array    # scalar
    latency_min: jax.Array
    latency_max: jax.Array
    latency_hist: jax.Array   # (NUM_BUCKETS,) fine log-spaced
    metrics: ServiceMetrics   # duration/response hists sharded over svc
    utilization: jax.Array    # (S,)
    unstable: jax.Array       # (S,) bool

    def quantiles_s(self, qs=(0.5, 0.75, 0.9, 0.99, 0.999)) -> np.ndarray:
        return quantile_from_histogram(np.asarray(self.latency_hist), qs)

    @property
    def mean_latency_s(self) -> float:
        return float(self.latency_sum) / max(float(self.count), 1.0)


class ShardedSimulator:
    """Runs a compiled graph data-parallel over a mesh."""

    def __init__(
        self,
        compiled: CompiledGraph,
        mesh: Mesh,
        params: SimParams = SimParams(),
        chaos=(),
    ):
        self.compiled = compiled
        self.mesh = mesh
        self.sim = Simulator(compiled, params, chaos)
        self.collector = MetricsCollector(compiled)
        self.n_data = mesh.shape[DATA_AXIS]
        self.n_svc = mesh.shape[SVC_AXIS]
        self.n_shards = self.n_data * self.n_svc
        # services padded so psum_scatter can tile over the svc axis
        s = compiled.num_services
        self.s_pad = -(-s // self.n_svc) * self.n_svc
        self._fns: Dict[Tuple[int, str, int], object] = {}

    def run(
        self,
        load: LoadModel,
        num_requests: int,
        key: jax.Array,
        offered_qps=None,
    ) -> ShardedSummary:
        """Simulate >= ``num_requests`` (rounded up to fill all shards).

        For closed-loop load the offered rate is latency-dependent; pass
        ``offered_qps`` (e.g. ``SimResults.offered_qps`` from a prior
        single-device run of the same load) to skip the pilot fixed point.
        """
        n_local = -(-num_requests // self.n_shards)
        if load.kind == OPEN_LOOP:
            offered = jnp.float32(load.qps)
            gap = jnp.float32(0.0)
        else:
            if load.connections % self.n_shards:
                raise ValueError(
                    f"closed-loop connections ({load.connections}) must "
                    f"divide evenly over {self.n_shards} shards"
                )
            if offered_qps is None:
                # fixed point on a single-device pilot, then fan out
                offered_qps = self.sim.run(
                    load, min(num_requests, 2048), key
                ).offered_qps
            offered = jnp.float32(offered_qps)
            gap = (
                jnp.float32(load.connections / load.qps)
                if load.qps is not None
                else jnp.float32(0.0)
            )
        return self._get(n_local, load.kind, load.connections)(
            key, offered, gap
        )

    # ------------------------------------------------------------------

    def _get(self, n_local: int, kind: str, connections: int):
        cache_key = (n_local, kind, connections)
        if cache_key not in self._fns:
            body = partial(self._body, n_local, kind, connections)
            mapped = jax.shard_map(
                body,
                mesh=self.mesh,
                in_specs=(P(), P(), P()),
                out_specs=ShardedSummary(
                    count=P(),
                    error_count=P(),
                    hop_events=P(),
                    latency_sum=P(),
                    latency_min=P(),
                    latency_max=P(),
                    latency_hist=P(),
                    metrics=ServiceMetrics(
                        incoming_total=P(),
                        outgoing_total=P(),
                        outgoing_size_hist=P(),
                        outgoing_size_sum=P(),
                        duration_hist=P(SVC_AXIS),
                        duration_sum=P(),
                        response_size_hist=P(SVC_AXIS),
                        response_size_sum=P(),
                    ),
                    utilization=P(),
                    unstable=P(),
                ),
                check_vma=False,
            )
            self._fns[cache_key] = jax.jit(mapped)
        return self._fns[cache_key]

    def _body(
        self,
        n_local: int,
        kind: str,
        connections: int,
        key: jax.Array,
        offered_qps: jax.Array,
        pace_gap: jax.Array,
    ) -> ShardedSummary:
        both = (DATA_AXIS, SVC_AXIS)
        shard = (
            jax.lax.axis_index(DATA_AXIS) * self.n_svc
            + jax.lax.axis_index(SVC_AXIS)
        )
        local_key = jax.random.fold_in(key, shard)
        conns_local = max(connections // self.n_shards, 1)
        res = self.sim._simulate(
            n_local,
            kind,
            conns_local,
            local_key,
            offered_qps,
            pace_gap,
            # each shard generates 1/shards of the open-loop stream
            offered_qps / self.n_shards,
        )
        m = self.collector.collect(res)

        def allsum(x):
            return jax.lax.psum(x, both)

        # per-service hists: reduce over data, stay sharded over svc
        def scatter_svc(x):
            x = jax.lax.psum(x, DATA_AXIS)
            pad = self.s_pad - x.shape[0]
            if pad:
                x = jnp.pad(x, [(0, pad)] + [(0, 0)] * (x.ndim - 1))
            return jax.lax.psum_scatter(
                x, SVC_AXIS, scatter_dimension=0, tiled=True
            )

        metrics = ServiceMetrics(
            incoming_total=allsum(m.incoming_total),
            outgoing_total=allsum(m.outgoing_total),
            outgoing_size_hist=allsum(m.outgoing_size_hist),
            outgoing_size_sum=allsum(m.outgoing_size_sum),
            duration_hist=scatter_svc(m.duration_hist),
            duration_sum=allsum(m.duration_sum),
            response_size_hist=scatter_svc(m.response_size_hist),
            response_size_sum=allsum(m.response_size_sum),
        )
        return ShardedSummary(
            count=allsum(jnp.float32(n_local)),
            error_count=allsum(res.client_error.sum().astype(jnp.float32)),
            hop_events=allsum(res.hop_events.astype(jnp.float32)),
            latency_sum=allsum(res.client_latency.sum()),
            latency_min=jax.lax.pmin(res.client_latency.min(), both),
            latency_max=jax.lax.pmax(res.client_latency.max(), both),
            latency_hist=allsum(latency_histogram(res.client_latency)),
            metrics=metrics,
            utilization=res.utilization,
            unstable=res.unstable,
        )
