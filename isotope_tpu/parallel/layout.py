"""Automap-style mesh-layout search (``--mesh auto``).

The PR 4 vet cost model estimates per-segment FLOPs/bytes but the mesh
factorization itself was hardcoded (``{'slice': 2, 'data': 2,
'svc': 2}`` in the multichip dryrun, ``mesh_data x mesh_svc`` in sweep
configs).  Automap (PAPERS.md) argues the factorization should be
*searched* from a cost model instead; this module does exactly that
over the engine's tiny decision space:

- every shard simulates a disjoint request slice, so COMPUTE is
  embarrassingly parallel across the whole mesh regardless of the
  factorization — what distinguishes layouts is the metric-merge
  COMMUNICATION (costmodel.comm_table prices each collective with
  ICI/DCN bandwidth constants) plus the ``svc``-padding waste;
- a wider ``svc`` axis turns the big per-service histogram all-reduce
  into a cheaper reduce-scatter and shrinks the payload any DCN axis
  must carry (the DCN psum runs LAST, on already-scattered tiles), but
  pads ``S`` up to a multiple of ``svc``;
- a ``slice`` (DCN) axis is pure cost on a single host — the search
  only proposes one when the caller says hosts exist (``max_slices``),
  and then pins it to the host count (each host is one slice; any
  other factor would put ICI axes across DCN).

The search is exhaustive — the space is divisor-triples of the device
count, a few dozen candidates — and deterministic (ties break toward
fewer slices, then narrower ``svc``).
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional

from isotope_tpu.parallel.mesh import MeshSpec


@dataclasses.dataclass(frozen=True)
class LayoutScore:
    """One scored candidate factorization."""

    spec: MeshSpec
    score_s: float            # modeled merge time per run (lower = better)
    comm_rows: tuple          # the costmodel.comm_table rows
    pad_fraction: float       # svc-padding waste, (s_pad - S) / S

    def to_dict(self) -> dict:
        return {
            "mesh": self.spec.describe(),
            "score_s": self.score_s,
            "pad_fraction": self.pad_fraction,
            "comm": [dict(r) for r in self.comm_rows],
        }


def _divisors(n: int) -> List[int]:
    return [d for d in range(1, n + 1) if n % d == 0]


def enumerate_specs(
    n_devices: int,
    num_services: int,
    max_slices: int = 1,
) -> List[MeshSpec]:
    """All valid ``{slice, data, svc}`` factorizations of the devices.

    Constraints: the product must equal ``n_devices`` (the search
    respects the device count — it never over- or under-subscribes),
    the ``svc`` axis is never wider than the service count (a shard
    owning only padding does no useful metric work), and with
    ``max_slices > 1`` EVERY candidate uses exactly ``max_slices``
    slices: hosts ARE slices, so a flat mesh spanning several hosts
    would run its ``data``/``svc`` collectives across DCN while the
    model priced them as ICI — the one mispricing the search must
    never offer.
    """
    if n_devices < 1:
        raise ValueError(f"n_devices must be >= 1, got {n_devices}")
    if max_slices > 1 and n_devices % max_slices:
        raise ValueError(
            f"{n_devices} devices do not divide over {max_slices} "
            f"hosts/slices (a slice must own whole hosts)"
        )
    specs = []
    slice_options = [max_slices] if max_slices > 1 else [1]
    for slices in slice_options:
        per_slice = n_devices // slices
        for svc in _divisors(per_slice):
            if svc > max(num_services, 1):
                continue
            specs.append(
                MeshSpec(data=per_slice // svc, svc=svc, slices=slices)
            )
    return specs


def score_layout(
    spec: MeshSpec,
    num_services: int,
    num_edges: Optional[int] = None,
    num_merges: int = 1,
) -> LayoutScore:
    """Price one candidate with the comm-augmented vet cost model."""
    from isotope_tpu.analysis import costmodel

    rows = costmodel.comm_table(
        num_services,
        data=spec.data,
        svc=spec.svc,
        slices=spec.slices,
        num_edges=num_edges,
        num_merges=num_merges,
    )
    s = max(num_services, 1)
    s_pad = -(-s // spec.svc) * spec.svc
    pad = (s_pad - s) / s
    # padding inflates every per-service device-side accumulation a
    # run performs, not just the merge wire time: charge it as a
    # fraction of the scattered payload at ICI speed per merge
    pad_s = (
        pad
        * costmodel.summary_bytes(num_services, num_edges)["scattered"]
        / costmodel.ICI_BANDWIDTH_BYTES_S
        * max(num_merges, 1)
    )
    return LayoutScore(
        spec=spec,
        score_s=sum(r["time_s"] for r in rows) + pad_s,
        comm_rows=tuple(tuple(r.items()) for r in rows),
        pad_fraction=pad,
    )


def choose_layout(
    n_devices: int,
    num_services: int,
    num_edges: Optional[int] = None,
    max_slices: int = 1,
    num_merges: int = 1,
) -> LayoutScore:
    """The best-scoring factorization for one topology.

    Deterministic: among equal scores the tie breaks toward fewer
    slices, then a narrower ``svc`` axis (closest to the historic
    all-data default).
    """
    candidates = [
        score_layout(spec, num_services, num_edges, num_merges)
        for spec in enumerate_specs(n_devices, num_services, max_slices)
    ]
    return min(
        candidates,
        key=lambda c: (c.score_s, c.spec.slices, c.spec.svc),
    )
