"""Sharded execution over a TPU mesh.

The reference scales horizontally by deploying more namespaces x replicas
onto more nodes (perf/load/common.sh:68-90); the simulator scales by
sharding the (request x hop) event tensor over a ``jax.sharding.Mesh`` and
merging metrics with XLA collectives over ICI — psum for counters and
histograms, psum_scatter to leave per-service histogram state sharded over
the ``svc`` axis (SURVEY.md §2.5, §5.8).
"""
from isotope_tpu.parallel.mesh import (
    default_mesh,
    make_mesh,
    make_multislice_mesh,
)
from isotope_tpu.parallel.sharded import ShardedSimulator, ShardedSummary

__all__ = [
    "default_mesh",
    "make_mesh",
    "make_multislice_mesh",
    "ShardedSimulator",
    "ShardedSummary",
]
