"""Sharded execution over a TPU mesh.

The reference scales horizontally by deploying more namespaces x replicas
onto more nodes (perf/load/common.sh:68-90); the simulator scales by
sharding the (request x hop) event tensor over a ``jax.sharding.Mesh`` and
merging metrics with XLA collectives — psum for counters and histograms
over ICI, psum_scatter to leave per-service histogram state sharded over
the ``svc`` axis, and a final cross-``slice`` psum over DCN on multi-host
meshes (SURVEY.md §2.5, §5.8).

The mesh itself can be an explicit spec (``--mesh`` / ``$ISOTOPE_MESH``),
an Automap-style cost-model search (``--mesh auto``, parallel/layout.py),
or an :class:`EmulatedMesh` that replays any host count on one device.
"""
from isotope_tpu.parallel.mesh import (
    EmulatedMesh,
    MeshSpec,
    build_mesh,
    default_mesh,
    make_mesh,
    make_multislice_mesh,
    mesh_spec_from_env,
    parse_mesh_spec,
)
from isotope_tpu.parallel.sharded import ShardedSimulator, ShardedSummary

__all__ = [
    "EmulatedMesh",
    "MeshSpec",
    "build_mesh",
    "default_mesh",
    "make_mesh",
    "make_multislice_mesh",
    "mesh_spec_from_env",
    "parse_mesh_spec",
    "ShardedSimulator",
    "ShardedSummary",
]
