"""Mesh construction helpers.

Axis conventions:

- ``slice`` (optional, outermost): multi-slice / multi-host scale-out —
  collectives crossing it ride DCN.  This is pure request-parallelism
  (more load per wall-second); per-request work never crosses it and
  only the O(buckets) summary reduction does, so the DCN traffic per
  run is a few KB regardless of request count.  NOTE: it does NOT model
  the reference's cluster1/cluster2 *topology* split — that is a
  property of the simulated system, modeled by per-service ``cluster``
  placement plus the cross-cluster NetworkModel edge class
  (perf/load/templates/service-graph.gen.yaml:1-3; see
  tests/test_multicluster.py), independent of how the simulation
  itself is sharded;
- ``data``: shards the request batch within a slice over ICI (every
  device simulates a disjoint slice of the arrival stream — the
  analogue of running more Fortio clients, perf/load/common.sh:68-90);
- ``svc``: shards per-service metric state (the analogue of services
  living on different nodes/namespaces).  Compute for all hops is still
  data-parallel; cross-``svc`` traffic is the metrics reduce-scatter.

DCN-awareness is purely positional: the ``slice`` axis is OUTERMOST, so
on real multi-slice hardware (devices ordered slice-major, the order
``jax.devices()`` already uses) the ``data``/``svc`` collectives stay
on ICI and only the ``slice`` reduction crosses DCN.

A mesh can come from three places, in priority order (runner/run.py):

1. an explicit spec — CLI ``--mesh`` or env ``$ISOTOPE_MESH`` —
   ``"auto"`` (cost-model search, parallel/layout.py), positional
   ``"DATAxSVC[xSLICE]"`` (e.g. ``4x2`` or ``2x2x2``), or named
   ``"data=4,svc=2,slice=1"``;
2. the legacy ``[sim] mesh_data`` / ``mesh_svc`` TOML keys;
3. the built-in all-devices-on-data factorization.

:class:`EmulatedMesh` carries a mesh *shape* with no devices behind it:
``ShardedSimulator`` accepts one and replays the full shard program
shard-by-shard on a single device (``run_emulated``), so any host
count — 2 hosts x 8 devices, 64 x 4, ... — is testable bit-for-bit on
one CPU in CI before a pod exists.
"""
from __future__ import annotations

import dataclasses
import os
from typing import Optional, Sequence, Union

import jax
import numpy as np
from jax.sharding import Mesh

from isotope_tpu.models.errors import config_path

SLICE_AXIS = "slice"
DATA_AXIS = "data"
SVC_AXIS = "svc"

#: valid axis names for named ``--mesh`` specs, in mesh (outer->inner)
#: order
AXIS_ORDER = (SLICE_AXIS, DATA_AXIS, SVC_AXIS)

ENV_MESH = "ISOTOPE_MESH"


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """An axis factorization — the logical mesh before devices exist.

    ``slices`` is the DCN (multi-host / multi-slice) axis; ``data`` and
    ``svc`` stay on ICI.  ``slices == 1`` collapses to the plain
    ``(data, svc)`` mesh (no DCN axis is materialized).
    """

    data: int
    svc: int = 1
    slices: int = 1

    def __post_init__(self):
        for name, v in (("data", self.data), ("svc", self.svc),
                        ("slice", self.slices)):
            if int(v) < 1:
                with config_path(f"mesh.{name}"):
                    raise ValueError(
                        f"axis size must be >= 1, got {v}"
                    )

    @property
    def size(self) -> int:
        return self.slices * self.data * self.svc

    @property
    def axis_names(self):
        if self.slices > 1:
            return (SLICE_AXIS, DATA_AXIS, SVC_AXIS)
        return (DATA_AXIS, SVC_AXIS)

    @property
    def shape(self) -> dict:
        if self.slices > 1:
            return {SLICE_AXIS: self.slices, DATA_AXIS: self.data,
                    SVC_AXIS: self.svc}
        return {DATA_AXIS: self.data, SVC_AXIS: self.svc}

    def describe(self) -> str:
        """Canonical named form (``data=4,svc=2`` / ``+,slice=2``)."""
        s = f"data={self.data},svc={self.svc}"
        if self.slices > 1:
            s += f",slice={self.slices}"
        return s


class EmulatedMesh:
    """A mesh SHAPE with no devices — the multi-host emulation handle.

    Mimics the slice of the ``jax.sharding.Mesh`` API the sharded
    runner reads (``axis_names`` / ``shape`` / ``size``) so
    ``ShardedSimulator`` can plan and replay an N-host program
    shard-by-shard on one device (``run_emulated`` and friends); the
    ``shard_map`` entry points raise — there is nothing to map over.
    """

    def __init__(self, spec: MeshSpec):
        self.spec = spec
        self.axis_names = spec.axis_names
        self.shape = spec.shape
        self.size = spec.size
        self.devices = None

    def __repr__(self) -> str:
        return f"EmulatedMesh({self.spec.describe()})"


MeshLike = Union[Mesh, EmulatedMesh]


def parse_mesh_spec(text: str) -> Union[str, MeshSpec]:
    """Parse a ``--mesh`` / ``$ISOTOPE_MESH`` value.

    Returns the string ``"auto"`` (layout search, parallel/layout.py)
    or a :class:`MeshSpec`.  Accepted forms::

        auto
        4x2          # data x svc
        2x2x2        # data x svc x slice
        data=4,svc=2,slice=1   # named, any subset, any order

    Errors are key-pathed (``mesh.svc: ...``) like every other config
    decode in the tree (models/errors.py).
    """
    text = text.strip()
    if not text:
        with config_path("mesh"):
            raise ValueError("empty mesh spec")
    if text.lower() == "auto":
        return "auto"
    if "=" in text:
        sizes = {}
        for part in text.split(","):
            part = part.strip()
            if not part:
                continue
            key, _, val = part.partition("=")
            key = key.strip()
            if key not in AXIS_ORDER:
                with config_path("mesh"):
                    raise ValueError(
                        f"unknown mesh axis {key!r} (valid axes: "
                        f"{', '.join(AXIS_ORDER)})"
                    )
            if key in sizes:
                with config_path(f"mesh.{key}"):
                    raise ValueError("axis given twice")
            with config_path(f"mesh.{key}"):
                try:
                    sizes[key] = int(val.strip())
                except ValueError:
                    raise ValueError(
                        f"axis size must be an integer, got "
                        f"{val.strip()!r}"
                    ) from None
        if not sizes:
            with config_path("mesh"):
                raise ValueError("empty mesh spec")
        return MeshSpec(
            data=sizes.get(DATA_AXIS, 1),
            svc=sizes.get(SVC_AXIS, 1),
            slices=sizes.get(SLICE_AXIS, 1),
        )
    parts = [p.strip() for p in text.lower().split("x")]
    if len(parts) not in (1, 2, 3):
        with config_path("mesh"):
            raise ValueError(
                f"bad mesh spec {text!r} (want 'auto', 'DATAxSVC', "
                f"'DATAxSVCxSLICE', or 'data=4,svc=2,slice=1')"
            )
    dims = []
    for name, part in zip(("data", "svc", "slice"), parts):
        with config_path(f"mesh.{name}"):
            try:
                dims.append(int(part))
            except ValueError:
                raise ValueError(
                    f"axis size must be an integer, got {part!r}"
                ) from None
    while len(dims) < 3:
        dims.append(1)
    return MeshSpec(data=dims[0], svc=dims[1], slices=dims[2])


def mesh_spec_from_env() -> Optional[Union[str, MeshSpec]]:
    """The ``$ISOTOPE_MESH`` spec, or None when unset/empty."""
    raw = os.environ.get(ENV_MESH, "").strip()
    if not raw:
        return None
    with config_path(ENV_MESH):
        return parse_mesh_spec(raw)


def build_mesh(
    spec: MeshSpec,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Materialize a spec over real devices (DCN axis outermost).

    Raises a key-pathed config error when the spec wants more devices
    than exist — the same failure text whether the spec came from the
    CLI, the env, or a TOML.
    """
    devices = list(devices) if devices is not None else jax.devices()
    if spec.size > len(devices):
        with config_path("mesh"):
            raise ValueError(
                f"mesh {spec.describe()} needs {spec.size} devices, "
                f"have {len(devices)} (use an EmulatedMesh / "
                f"run_emulated to replay more hosts than exist)"
            )
    if spec.slices > 1:
        return make_multislice_mesh(
            spec.slices, spec.data, spec.svc, devices
        )
    return make_mesh(spec.data, spec.svc, devices)


def make_mesh(
    data: int,
    svc: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    devices = list(devices) if devices is not None else jax.devices()
    if data * svc > len(devices):
        raise ValueError(
            f"mesh {data}x{svc} needs {data * svc} devices, have "
            f"{len(devices)}"
        )
    grid = np.asarray(devices[: data * svc]).reshape(data, svc)
    return Mesh(grid, (DATA_AXIS, SVC_AXIS))


def make_multislice_mesh(
    slices: int,
    data: int,
    svc: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """(slice, data, svc) mesh for multi-slice runs.

    On real multi-slice hardware, pass ``devices`` ordered so that each
    contiguous ``data * svc`` block lives on one slice (the order
    ``jax.devices()`` already uses) — then ``data``/``svc`` collectives
    stay on ICI and only the ``slice`` axis crosses DCN.
    """
    devices = list(devices) if devices is not None else jax.devices()
    need = slices * data * svc
    if need > len(devices):
        raise ValueError(
            f"mesh {slices}x{data}x{svc} needs {need} devices, have "
            f"{len(devices)}"
        )
    grid = np.asarray(devices[:need]).reshape(slices, data, svc)
    return Mesh(grid, (SLICE_AXIS, DATA_AXIS, SVC_AXIS))


def default_mesh(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """All available devices on the data axis."""
    devices = list(devices) if devices is not None else jax.devices()
    return make_mesh(len(devices), 1, devices)
