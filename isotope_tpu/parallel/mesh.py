"""Mesh construction helpers.

Axis conventions:

- ``data``: shards the request batch (every device simulates a disjoint
  slice of the arrival stream — the analogue of running more Fortio
  clients, perf/load/common.sh:68-90);
- ``svc``: shards per-service metric state (the analogue of services
  living on different nodes/namespaces).  Compute for all hops is still
  data-parallel; cross-``svc`` traffic is the metrics reduce-scatter.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

DATA_AXIS = "data"
SVC_AXIS = "svc"


def make_mesh(
    data: int,
    svc: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    devices = list(devices) if devices is not None else jax.devices()
    if data * svc > len(devices):
        raise ValueError(
            f"mesh {data}x{svc} needs {data * svc} devices, have "
            f"{len(devices)}"
        )
    grid = np.asarray(devices[: data * svc]).reshape(data, svc)
    return Mesh(grid, (DATA_AXIS, SVC_AXIS))


def default_mesh(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """All available devices on the data axis."""
    devices = list(devices) if devices is not None else jax.devices()
    return make_mesh(len(devices), 1, devices)
