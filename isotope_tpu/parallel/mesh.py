"""Mesh construction helpers.

Axis conventions:

- ``slice`` (optional, outermost): multi-slice scale-out — collectives
  crossing it ride DCN.  This is pure request-parallelism (more load
  per wall-second); per-request work never crosses it and only the
  O(buckets) summary reduction does, so the DCN traffic per run is a
  few KB regardless of request count.  NOTE: it does NOT model the
  reference's cluster1/cluster2 *topology* split — that is a property
  of the simulated system, modeled by per-service ``cluster``
  placement plus the cross-cluster NetworkModel edge class
  (perf/load/templates/service-graph.gen.yaml:1-3; see
  tests/test_multicluster.py), independent of how the simulation
  itself is sharded;
- ``data``: shards the request batch within a slice over ICI (every
  device simulates a disjoint slice of the arrival stream — the
  analogue of running more Fortio clients, perf/load/common.sh:68-90);
- ``svc``: shards per-service metric state (the analogue of services
  living on different nodes/namespaces).  Compute for all hops is still
  data-parallel; cross-``svc`` traffic is the metrics reduce-scatter.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

SLICE_AXIS = "slice"
DATA_AXIS = "data"
SVC_AXIS = "svc"


def make_mesh(
    data: int,
    svc: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    devices = list(devices) if devices is not None else jax.devices()
    if data * svc > len(devices):
        raise ValueError(
            f"mesh {data}x{svc} needs {data * svc} devices, have "
            f"{len(devices)}"
        )
    grid = np.asarray(devices[: data * svc]).reshape(data, svc)
    return Mesh(grid, (DATA_AXIS, SVC_AXIS))


def make_multislice_mesh(
    slices: int,
    data: int,
    svc: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """(slice, data, svc) mesh for multi-slice runs.

    On real multi-slice hardware, pass ``devices`` ordered so that each
    contiguous ``data * svc`` block lives on one slice (the order
    ``jax.devices()`` already uses) — then ``data``/``svc`` collectives
    stay on ICI and only the ``slice`` axis crosses DCN.
    """
    devices = list(devices) if devices is not None else jax.devices()
    need = slices * data * svc
    if need > len(devices):
        raise ValueError(
            f"mesh {slices}x{data}x{svc} needs {need} devices, have "
            f"{len(devices)}"
        )
    grid = np.asarray(devices[:need]).reshape(slices, data, svc)
    return Mesh(grid, (SLICE_AXIS, DATA_AXIS, SVC_AXIS))


def default_mesh(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """All available devices on the data axis."""
    devices = list(devices) if devices is not None else jax.devices()
    return make_mesh(len(devices), 1, devices)
