"""Benchmark CSV plotting.

Capability parity with the reference's graph plotter
(perf/benchmark/graph_plotter/graph_plotter.py): latency percentiles or
CPU vs connections or QPS, one line per series label, from the
``benchmark.csv`` the sweep driver writes.  Matplotlib with the Agg
backend — output is a PNG, no display needed.
"""
from __future__ import annotations

import re
from typing import List, Optional, Sequence

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt  # noqa: E402
import pandas as pd  # noqa: E402

LATENCY_METRICS = ("p50", "p75", "p90", "p99", "p999")
X_AXES = ("conn", "qps")

# our sweep labels: <topology>_<env>_<qps>qps_<conn>c[_extra]; the qps is
# rendered with {:g}, which switches to exponent form above 1e6 ("1e+06")
_LABEL_RE = re.compile(
    r"^(?P<series>.+?)_(?P<qps>[0-9.]+(?:e[+-]?[0-9]+)?|max)qps_\d+c"
)


def _series_of(label: str) -> str:
    m = _LABEL_RE.match(str(label))
    return m.group("series") if m else str(label)


def plot_benchmark(
    csv_path,
    out_path,
    x_axis: str = "conn",
    metrics: Sequence[str] = ("p50", "p90", "p99"),
    series: Optional[Sequence[str]] = None,
    title: Optional[str] = None,
) -> List[str]:
    """Plot ``metrics`` vs ``x_axis`` per series; returns series plotted.

    Latency columns are integer microseconds (the flattened fortio
    schema); they are shown in milliseconds.  Any other numeric column
    (e.g. the per-service ``cpu_cores_*`` columns) plots raw.
    """
    if x_axis not in X_AXES:
        raise ValueError(f"x_axis must be one of {X_AXES}")
    df = pd.read_csv(csv_path)
    if df.empty:
        raise ValueError(f"no rows in {csv_path}")
    df["series"] = df["Labels"].map(_series_of)
    xcol = "NumThreads" if x_axis == "conn" else "ActualQPS"

    wanted = list(series) if series else sorted(df["series"].unique())
    plotted: List[str] = []
    dpi = 100
    plt.figure(figsize=(1138 / dpi, 871 / dpi), dpi=dpi)
    for s in wanted:
        rows = df[df["series"] == s].sort_values(xcol)
        if rows.empty:
            continue
        drew = False
        for metric in metrics:
            if metric not in rows.columns:
                raise ValueError(f"no column {metric!r} in {csv_path}")
            # record-dependent columns (cpu_cores_<svc>) are "-"-padded on
            # rows from topologies without that service — skip those rows
            y = pd.to_numeric(rows[metric], errors="coerce")
            keep = y.notna()
            if not keep.any():
                continue
            label = f"{s} {metric}"
            if metric in LATENCY_METRICS:
                y = y / 1000.0  # us -> ms
            plt.plot(rows[xcol][keep], y[keep], marker="o", label=label)
            drew = True
        if drew:
            plotted.append(s)
    if not plotted:
        raise ValueError(f"no matching series in {csv_path}")
    plt.xlabel(
        "Connections" if x_axis == "conn" else "QPS"
    )
    unit = (
        "Latency (ms)"
        if all(m in LATENCY_METRICS for m in metrics)
        else ", ".join(metrics)
    )
    plt.ylabel(unit)
    if title:
        plt.title(title)
    plt.legend()
    plt.grid(True)
    plt.savefig(out_path, dpi=dpi, bbox_inches="tight")
    plt.close()
    return plotted
