"""Fitters: Observation IR -> topology YAML + experiment TOML.

The estimators invert the engine's forward model
(PAPER.md service semantics; sim/engine.py):

- **station CPU**: ``service_cpu_usage_seconds_total`` counts station
  CPU only (utilization x replicas x duration), so
  ``cpu_seconds / incoming`` is the per-request ``cpu_time`` exactly;
  the global ``[sim] cpu_time`` is the median across services.
- **self-time / sleep**: with the timeline's occupancy gauges,
  per-request busy time (occupancy over [start+wait, end)) decomposes
  as ``busy = cpu_time + sleeps + sum_children r * (sojourn(child) +
  wire)`` — the fitted sleep is the residual after subtracting station
  CPU and downstream segments, wire estimated from NetworkModel's
  defaults (2 x 250us + bytes / 1.25 GB/s).  CSV traces with span ids
  skip the inversion: self-time is measured directly as rt minus the
  union of child span intervals.
- **fan-out**: the engine skips a service's calls when its own error
  coin fires, so the observed edge ratio under-counts by the caller's
  error share; the corrected ratio is ``edges / incoming / (1 - p)``.
  Integer part -> repeated calls, fractional part -> one
  ``probability`` call (the script grammar's int-percent knob).
- **errorRate**: without timeouts/retries a service's 500s are its own
  error coin only, so the observed per-service 500 share IS the
  intrinsic rate — no deconvolution needed.
- **qps schedule**: first differences of the cumulative
  ``timeline_client_requests_total`` counter (or CSV arrival
  bucketing); ``[client] qps`` is the mean (a TOML list would decode
  as a sweep grid, not a schedule), the full windowed schedule rides
  in the ``isotope-ingest/v1`` report and an informational
  ``[ingest]`` TOML table (load_toml ignores unknown tables).

Everything dropped — unreachable services, cycle-closing edges,
zero-ratio edges, empty lead/tail windows — lands in
``FitResult.dropped`` with a reason, never on the floor.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Set, Tuple

from isotope_tpu.ingest.readers import CLIENT_ALIASES, Observation
from isotope_tpu.models.graph import ServiceGraph
from isotope_tpu.sim.config import DEFAULT_CPU_TIME_S, NetworkModel

# log-bucket histogram bounds for the fitted self-time distribution:
# powers of ~2 from 10us to ~10s (engine sleep model is a point sleep;
# the histogram records the observed spread the point estimate loses)
_LOG_BUCKETS_S: Tuple[float, ...] = tuple(
    1e-5 * (2.0 ** k) for k in range(21)
)


def _median(xs: List[float]) -> Optional[float]:
    if not xs:
        return None
    xs = sorted(xs)
    n = len(xs)
    if n % 2:
        return xs[n // 2]
    return 0.5 * (xs[n // 2 - 1] + xs[n // 2])


def _fmt_us(seconds: float) -> str:
    return f"{int(round(seconds * 1e6))}us"


def log_bucket_hist(samples: List[float]) -> List[List[float]]:
    """[[upper_bound_s, count], ...] over the fixed log-bucket grid;
    only non-empty buckets are emitted (+Inf bound as the last catch-
    all when needed)."""
    counts = [0] * (len(_LOG_BUCKETS_S) + 1)
    for x in samples:
        for i, b in enumerate(_LOG_BUCKETS_S):
            if x <= b:
                counts[i] += 1
                break
        else:
            counts[-1] += 1
    out: List[List[float]] = []
    for i, c in enumerate(counts[:-1]):
        if c:
            out.append([_LOG_BUCKETS_S[i], c])
    if counts[-1]:
        out.append([math.inf, counts[-1]])
    return out


@dataclasses.dataclass
class FitOptions:
    label: str = "ingested"
    entry: Optional[str] = None
    # fallback observation duration (needed for rate fits when the
    # inputs carry no timestamps, e.g. Envoy stats)
    duration_s: Optional[float] = None
    window_s: float = 1.0
    cpu_time_s: Optional[float] = None  # override the station estimate
    connections: int = 64
    seed: int = 0
    max_calls_per_edge: int = 64
    # sleeps below this floor are measurement noise, not structure
    min_sleep_s: float = 1e-5


@dataclasses.dataclass
class FittedService:
    name: str
    incoming: float = 0.0
    error_rate: float = 0.0
    station_cpu_s: Optional[float] = None
    self_time_s: float = 0.0       # cpu + sleep point estimate
    sleep_s: float = 0.0
    sojourn_s: Optional[float] = None
    response_size: Optional[int] = None
    replicas: int = 1
    out_degree: int = 0
    concurrent: bool = False
    samples: float = 0.0           # observations backing the fit
    self_hist: List[List[float]] = dataclasses.field(
        default_factory=list
    )
    flags: List[str] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class FitResult:
    label: str
    entry: str
    topology_doc: dict
    graph: ServiceGraph
    toml_text: str
    services: Dict[str, FittedService]
    # (caller, callee) -> corrected call ratio actually emitted
    edges: Dict[Tuple[str, str], float]
    qps_schedule: List[float]
    qps_mean: float
    window_s: float
    duration_s: float
    cpu_time_s: float
    dropped: Dict[str, List[dict]]
    notes: List[str]


def fit(obs: Observation, opts: Optional[FitOptions] = None) -> FitResult:
    opts = opts or FitOptions()
    notes: List[str] = list(obs.notes)
    dropped: Dict[str, List[dict]] = {
        "services": [], "edges": [], "windows": [],
    }
    clients = set(CLIENT_ALIASES) | set(obs.clients_seen)

    # -- per-service totals (with fallbacks) --
    incoming: Dict[str, float] = {}
    for name, s in obs.services.items():
        if name in clients:
            continue
        inc = s.incoming or s.latency_count or sum(
            c for (src, dst), c in obs.edges.items() if dst == name
        )
        incoming[name] = inc
    # callers that only ever appear as edge sources still need a node
    for (src, dst) in obs.edges:
        if src not in clients and src not in incoming:
            incoming[src] = 0.0
        if dst not in clients and dst not in incoming:
            incoming[dst] = obs.edges[(src, dst)]

    err_rate = {
        n: (obs.services[n].errors / incoming[n]
            if n in obs.services and incoming[n] > 0 else 0.0)
        for n in incoming
    }

    # -- edges: split client vs service, correct for error skipping --
    entry_votes: Dict[str, float] = {}
    svc_edges: Dict[Tuple[str, str], float] = {}
    for (src, dst), c in obs.edges.items():
        if dst in clients:
            dropped["edges"].append({
                "edge": [src, dst], "count": c,
                "reason": "destination is an external client",
            })
            continue
        if src in clients:
            entry_votes[dst] = entry_votes.get(dst, 0.0) + c
        elif src == dst:
            dropped["edges"].append({
                "edge": [src, dst], "count": c,
                "reason": "self-call not expressible in the script grammar",
            })
        else:
            svc_edges[(src, dst)] = c

    ratios: Dict[Tuple[str, str], float] = {}
    for (src, dst), c in svc_edges.items():
        inc = incoming.get(src, 0.0)
        if inc <= 0:
            dropped["edges"].append({
                "edge": [src, dst], "count": c,
                "reason": f"caller {src!r} has zero observed arrivals",
            })
            continue
        p = min(err_rate.get(src, 0.0), 0.95)
        ratios[(src, dst)] = (c / inc) / (1.0 - p)

    # -- entrypoint --
    if opts.entry:
        entry = opts.entry
        if entry not in incoming:
            raise ValueError(
                f"--entry {entry!r} not among observed services"
            )
    elif entry_votes:
        entry = max(sorted(entry_votes), key=lambda k: entry_votes[k])
    else:
        called = {dst for (_, dst) in ratios}
        roots = [n for n in incoming if n not in called]
        if not roots:
            roots = list(incoming)
        if not roots:
            raise ValueError("no services observed: nothing to fit")
        entry = max(sorted(roots), key=lambda n: incoming[n])
        notes.append(
            f"no external-client edges: entrypoint inferred as {entry!r}"
            " (max-arrival root)"
        )

    # -- reachability + cycle breaking (DFS from entry) --
    out_adj: Dict[str, List[str]] = {}
    for (src, dst) in sorted(ratios):
        out_adj.setdefault(src, []).append(dst)
    kept_edges: Dict[Tuple[str, str], float] = {}
    state: Dict[str, int] = {}  # 1=on stack, 2=done
    # iterative DFS (CSV chains can exceed the recursion limit): each
    # stack frame is (node, iterator over its sorted out-neighbors)
    state[entry] = 1
    stack: List[Tuple[str, int]] = [(entry, 0)]
    while stack:
        node, i = stack.pop()
        kids = out_adj.get(node, [])
        advanced = False
        while i < len(kids):
            dst = kids[i]
            i += 1
            if state.get(dst) == 1:
                dropped["edges"].append({
                    "edge": [node, dst], "count": svc_edges[(node, dst)],
                    "reason": "breaks a call-graph cycle "
                              "(engine unrolls acyclic graphs only)",
                })
                continue
            kept_edges[(node, dst)] = ratios[(node, dst)]
            if state.get(dst) != 2:
                stack.append((node, i))
                state[dst] = 1
                stack.append((dst, 0))
                advanced = True
                break
        if not advanced:
            state[node] = 2
    reachable = set(state)
    for n in sorted(incoming):
        if n not in reachable:
            dropped["services"].append({
                "service": n, "incoming": incoming[n],
                "reason": "unreachable from fitted entrypoint",
            })
    for (src, dst), c in sorted(svc_edges.items()):
        if (src, dst) in ratios and (src, dst) not in kept_edges and (
            src not in reachable or dst not in reachable
        ):
            dropped["edges"].append({
                "edge": [src, dst], "count": c,
                "reason": "endpoint unreachable from fitted entrypoint",
            })

    # -- global station cpu_time --
    cpu_samples = [
        obs.services[n].cpu_seconds / incoming[n]
        for n in sorted(reachable)
        if n in obs.services
        and obs.services[n].cpu_seconds is not None
        and incoming[n] > 0
    ]
    if opts.cpu_time_s is not None:
        cpu_time = opts.cpu_time_s
    else:
        cpu_time = _median(cpu_samples) or DEFAULT_CPU_TIME_S
        if not cpu_samples:
            notes.append(
                "no service_cpu_usage_seconds_total observed: "
                f"[sim] cpu_time defaulted to {_fmt_us(DEFAULT_CPU_TIME_S)}"
            )

    # -- per-service timing decomposition --
    net = NetworkModel()
    fitted: Dict[str, FittedService] = {}

    def sojourn_mean(n: str) -> Optional[float]:
        s = obs.services.get(n)
        if s is None:
            return None
        if s.sojourn_seconds is not None and incoming[n] > 0:
            return s.sojourn_seconds / incoming[n]
        if s.latency_count > 0:
            return s.latency_sum_s / s.latency_count
        return None

    def edge_req_size(src: str, dst: str) -> Optional[int]:
        cnt = obs.edge_size_count.get((src, dst), 0.0)
        if cnt > 0:
            return int(round(obs.edge_size_sum[(src, dst)] / cnt))
        return None

    for n in sorted(reachable):
        s = obs.services.get(n)
        f = FittedService(name=n, incoming=incoming[n])
        f.error_rate = round(err_rate.get(n, 0.0), 6)
        f.samples = incoming[n]
        if s is not None and s.cpu_seconds is not None and incoming[n] > 0:
            f.station_cpu_s = s.cpu_seconds / incoming[n]
        f.sojourn_s = sojourn_mean(n)
        if s is not None and s.response_size_count > 0:
            f.response_size = int(
                round(s.response_size_sum / s.response_size_count)
            )
        if s is not None and s.replicas_hint is not None:
            f.replicas = max(1, int(round(s.replicas_hint)))

        children = [
            (dst, r) for (src, dst), r in kept_edges.items() if src == n
        ]
        downstream = 0.0
        for dst, r in children:
            child_sojourn = sojourn_mean(dst) or 0.0
            req = edge_req_size(n, dst) or 0
            resp = (
                obs.services[dst].response_size_sum
                / obs.services[dst].response_size_count
                if dst in obs.services
                and obs.services[dst].response_size_count > 0
                else 0.0
            )
            wire = 2.0 * net.base_latency_s + (req + resp) / (
                net.bytes_per_second
            )
            downstream += r * (child_sojourn + wire)

        if s is not None and s.self_time_count > 0:
            # CSV span decomposition: direct measurement
            f.self_time_s = s.self_time_sum_s / s.self_time_count
            f.self_hist = log_bucket_hist(s.self_time_samples)
        elif s is not None and s.busy_seconds is not None and (
            incoming[n] > 0
        ):
            busy = s.busy_seconds / incoming[n]
            f.self_time_s = max(busy - downstream, 0.0)
        elif f.sojourn_s is not None:
            f.self_time_s = max(f.sojourn_s - downstream, 0.0)
            f.flags.append(
                "self-time from sojourn (no busy/occupancy data): "
                "queueing wait folds into the fitted sleep"
            )
        else:
            f.self_time_s = 0.0
        station = f.station_cpu_s if f.station_cpu_s is not None else (
            cpu_time
        )
        f.sleep_s = max(f.self_time_s - station, 0.0)
        if f.sleep_s < opts.min_sleep_s:
            f.sleep_s = 0.0
        # provisional: re-set to the emitted call-command count below
        # (repeated calls count once per command, matching a source
        # script's flattened degree)
        f.out_degree = len(children)
        f.concurrent = n in obs.concurrent_callers and len(children) > 1
        if f.samples <= 0:
            f.flags.append("zero observed samples (degenerate fit)")
        fitted[n] = f

    # -- qps schedule --
    window_s = obs.window_s or opts.window_s
    schedule: List[float] = []
    if obs.client_windows:
        arr = list(obs.client_windows)
        lead = 0
        while arr and arr[0] == 0.0:
            dropped["windows"].append({
                "index": lead, "reason": "empty leading window",
            })
            arr.pop(0)
            lead += 1
        tail_idx = lead + len(arr) - 1
        while arr and arr[-1] == 0.0:
            dropped["windows"].append({
                "index": tail_idx, "reason": "empty trailing window",
            })
            arr.pop()
            tail_idx -= 1
        schedule = [a / window_s for a in arr]
    if schedule:
        qps_mean = sum(schedule) / len(schedule)
        duration_s = opts.duration_s or len(schedule) * window_s
    else:
        entry_total = sum(entry_votes.values()) or incoming.get(
            entry, 0.0
        )
        if opts.duration_s:
            duration_s = opts.duration_s
            qps_mean = entry_total / duration_s
            schedule = [qps_mean]
            window_s = duration_s
            notes.append(
                "no timestamped windows: flat schedule from totals "
                "over --duration"
            )
        else:
            duration_s = 60.0
            qps_mean = 100.0
            schedule = [qps_mean]
            window_s = duration_s
            notes.append(
                "no timestamps and no --duration: qps defaulted to "
                "100 over 60s (UNCALIBRATED — pass --duration)"
            )

    # -- topology YAML doc --
    resp_sizes = [
        f.response_size for f in fitted.values()
        if f.response_size is not None
    ]
    req_sizes = [
        edge_req_size(src, dst) for (src, dst) in kept_edges
        if edge_req_size(src, dst) is not None
    ]
    default_resp = _median([float(x) for x in resp_sizes])
    default_req = _median([float(x) for x in req_sizes])
    defaults: dict = {"type": "http"}
    if default_resp is not None:
        defaults["responseSize"] = int(default_resp)
    if default_req is not None:
        defaults["requestSize"] = int(default_req)

    services_out: List[dict] = []
    zero_edges: Set[Tuple[str, str]] = set()
    for n in sorted(reachable, key=lambda x: (x != entry, x)):
        f = fitted[n]
        doc: dict = {"name": n}
        if n == entry:
            doc["isEntrypoint"] = True
        if f.error_rate >= 1e-6:
            doc["errorRate"] = f.error_rate
        if f.response_size is not None and (
            default_resp is None or f.response_size != int(default_resp)
        ):
            doc["responseSize"] = f.response_size
        if f.replicas > 1:
            doc["numReplicas"] = f.replicas
        script: List = []
        if f.sleep_s > 0:
            script.append({"sleep": _fmt_us(f.sleep_s)})
        calls: List[dict] = []
        for (src, dst), r in sorted(kept_edges.items()):
            if src != n:
                continue
            k = int(math.floor(r + 1e-9))
            frac = r - k
            if frac >= 0.95:
                k, frac = k + 1, 0.0
            elif frac <= 0.05:
                frac = 0.0
            if k > opts.max_calls_per_edge:
                f.flags.append(
                    f"call ratio to {dst!r} capped at "
                    f"{opts.max_calls_per_edge} (fitted {r:.1f})"
                )
                k = opts.max_calls_per_edge
                frac = 0.0
            if k == 0 and frac == 0.0:
                dropped["edges"].append({
                    "edge": [src, dst],
                    "count": svc_edges.get((src, dst), 0.0),
                    "reason": f"fitted ratio {r:.4f} rounds to zero",
                })
                zero_edges.add((src, dst))
                continue
            size = edge_req_size(src, dst)
            base: dict = {"service": dst}
            if size is not None and (
                default_req is None or size != int(default_req)
            ):
                base["size"] = size
            for _i in range(k):
                calls.append({"call": dict(base) if len(base) > 1 else dst})
            if frac > 0.0:
                prob = min(max(int(round(frac * 100)), 1), 99)
                calls.append({"call": {**base, "probability": prob}})
        f.out_degree = len(calls)
        if calls:
            if f.concurrent:
                script.append([dict(c) for c in calls])
            else:
                script.extend(calls)
        if script:
            doc["script"] = script
        services_out.append(doc)

    for e in zero_edges:
        kept_edges.pop(e, None)

    topo_doc = {"defaults": defaults, "services": services_out}
    graph = ServiceGraph.decode(topo_doc)  # validation gate

    # -- experiment TOML --
    toml_text = _emit_toml(
        opts, entry, cpu_time, qps_mean, duration_s, window_s, schedule,
    )

    return FitResult(
        label=opts.label,
        entry=entry,
        topology_doc=topo_doc,
        graph=graph,
        toml_text=toml_text,
        services=fitted,
        edges=dict(kept_edges),
        qps_schedule=schedule,
        qps_mean=qps_mean,
        window_s=window_s,
        duration_s=duration_s,
        cpu_time_s=cpu_time,
        dropped=dropped,
        notes=notes,
    )


def _fmt_duration(seconds: float) -> str:
    if seconds >= 1.0 and abs(seconds - round(seconds)) < 1e-9:
        return f"{int(round(seconds))}s"
    return _fmt_us(seconds)


def _emit_toml(
    opts: FitOptions,
    entry: str,
    cpu_time: float,
    qps_mean: float,
    duration_s: float,
    window_s: float,
    schedule: List[float],
) -> str:
    """The runnable `[client]`/`[sim]` schedule.  `qps` is the schedule
    MEAN — a TOML list would decode as a sweep grid, not a schedule —
    and the full windowed schedule rides in the `[ingest]` table
    (ignored by load_toml) plus the .ingest.json report."""
    lines = [
        f"# generated by `isotope-tpu ingest` — label {opts.label!r}",
        f'topology_paths = ["{opts.label}.yaml"]',
        'environments = ["NONE"]',
        "",
        "[client]",
        f"qps = {qps_mean:.6g}",
        f'duration = "{_fmt_duration(duration_s)}"',
        f"num_concurrent_connections = {opts.connections}",
        'load_kind = "open"',
        "",
        "[sim]",
        f"seed = {opts.seed}",
        f'cpu_time = "{_fmt_us(cpu_time)}"',
        "timeline = true",
        f'timeline_window = "{_fmt_duration(window_s)}"',
        "",
        "# informational: full fitted qps schedule (load_toml ignores",
        "# unknown tables; machine-readable copy in <label>.ingest.json)",
        "[ingest]",
        f'label = "{opts.label}"',
        f'entry = "{entry}"',
        f"windows = {len(schedule)}",
        f"window_s = {window_s:.6g}",
        f"qps_min = {min(schedule):.6g}",
        f"qps_max = {max(schedule):.6g}",
    ]
    return "\n".join(lines) + "\n"
