"""Trace-driven ingest: compile real mesh telemetry into topologies.

The reference repo's upper layers exist to *measure* real meshes —
Fortio drives load, Prometheus scrapes the proxies, the benchmark
runner aggregates (perf/benchmark/runner/prom.py).  The simulator so
far only *emitted* that telemetry (metrics/prometheus.py exposition,
metrics/timeline.py timestamped windows); this package closes the loop
by consuming it:

- :mod:`readers` parse Prometheus/OpenMetrics expositions (including
  our own timestamped timeline series), Envoy ``/stats``-style cluster
  JSON, and a documented CSV trace schema (caller, callee, timestamp,
  rt, status) into one :class:`~isotope_tpu.ingest.readers.Observation`
  IR with per-input coverage accounting — nothing is dropped silently.
- :mod:`fit` estimates per-service self-time (→ script ``sleep``),
  ``errorRate``, fan-out call graphs (with concurrent-group inference
  from overlapping spans), payload sizes, replica counts, and a
  windowed qps schedule, emitted as standard topology YAML + ``[sim]``
  TOML through the existing ``models/`` decoders.
- :mod:`report` records the fit-fidelity evidence as an
  ``isotope-ingest/v1`` artifact (``<label>.ingest.json``) and checks
  the self-closure loop: simulate a known topology, export its
  exposition, ingest it back, and pin the reconstruction against the
  source within stated tolerances.

Host-only: no jax imports anywhere in this package.
"""
from isotope_tpu.ingest.readers import (  # noqa: F401
    Observation,
    InputCoverage,
    read_prometheus,
    read_envoy,
    read_csv_trace,
    read_path,
)
from isotope_tpu.ingest.fitters import FitOptions, FitResult, fit  # noqa: F401
from isotope_tpu.ingest.report import (  # noqa: F401
    DOC_SCHEMA,
    check_doc,
    load_doc,
    format_report,
    closure_check,
    CLOSURE_TOLERANCES,
)
