"""Telemetry readers: three formats, one Observation IR.

Every reader appends into a shared :class:`Observation` and records an
:class:`InputCoverage` row whose counters PARTITION the input — for the
text formats ``lines_total == blank + comment + parsed + malformed``
and ``parsed == used + ignored`` (for Envoy JSON the unit is stats
*entries* instead of physical lines).  The fidelity report surfaces
these rows verbatim, so a scrape with vendor series we don't model
shows up as ``ignored`` counts, never as silent truncation.

Formats:

- **Prometheus / OpenMetrics text** (:func:`read_prometheus`): the
  simulator's own exposition family (``service_*`` from
  metrics/prometheus.py, timestamped ``timeline_*`` from
  metrics/timeline.py).  Counter families are matched with and without
  the ``_total`` suffix; timestamped cumulative counters become
  per-window first differences.
- **Envoy cluster stats JSON** (:func:`read_envoy`): the
  ``/stats?format=json`` subset the reference's proxy dashboards read —
  ``cluster.<callee>.upstream_rq_total`` / ``upstream_rq_5xx`` /
  ``upstream_rq_time`` / ``upstream_cx_active``.  No timestamps, so the
  caller must supply an observation duration to turn counts into rates.
- **CSV span traces** (:func:`read_csv_trace`): the Alibaba
  cluster-trace / DeathStarBench shape — one row per call span with
  columns ``traceid`` (optional), ``caller``, ``callee``, ``timestamp``
  (s), ``rt`` (s), ``status``.  With trace ids the reader reconstructs
  parent/child span nesting: per-span self-time = rt minus the union of
  child span intervals (concurrency-safe), and sibling spans that
  overlap in time mark the caller for a concurrent call group.
"""
from __future__ import annotations

import csv
import dataclasses
import io
import json
import math
from typing import Dict, List, Optional, Sequence, Set, Tuple

from isotope_tpu.metrics.query import Sample, parse_exposition_tolerant

# Caller names treated as the external load generator (entry traffic),
# not as mesh services.  "fortio-client" is our own exposition's client
# label (metrics/prometheus.py CLIENT_NAME); the rest are the aliases
# public trace dumps actually use.
CLIENT_ALIASES: Tuple[str, ...] = (
    "fortio-client", "client", "user", "USER", "(user)", "loadgen",
    "ingress", "",
)


@dataclasses.dataclass
class InputCoverage:
    """Accounting for one ingested input. Counters partition the input:
    ``lines_total == lines_blank + lines_comment + lines_parsed +
    lines_malformed`` and ``lines_parsed == samples_used +
    samples_ignored`` (Envoy JSON counts stats entries as 'lines')."""

    path: str
    format: str
    lines_total: int = 0
    lines_blank: int = 0
    lines_comment: int = 0
    lines_parsed: int = 0
    lines_malformed: int = 0
    samples_used: int = 0
    samples_ignored: int = 0
    # up to 5 (line_number, text) examples of malformed input
    malformed_examples: List[Tuple[int, str]] = dataclasses.field(
        default_factory=list
    )
    notes: List[str] = dataclasses.field(default_factory=list)

    def note(self, msg: str) -> None:
        if msg not in self.notes:
            self.notes.append(msg)

    def to_dict(self) -> dict:
        return {
            "path": self.path,
            "format": self.format,
            "lines_total": self.lines_total,
            "lines_blank": self.lines_blank,
            "lines_comment": self.lines_comment,
            "lines_parsed": self.lines_parsed,
            "lines_malformed": self.lines_malformed,
            "samples_used": self.samples_used,
            "samples_ignored": self.samples_ignored,
            "malformed_examples": [
                [n, t] for n, t in self.malformed_examples
            ],
            "notes": list(self.notes),
        }


@dataclasses.dataclass
class ObservedService:
    """Everything the inputs told us about one service."""

    name: str
    incoming: float = 0.0          # total hops arriving
    errors: float = 0.0            # hops answered 500
    latency_sum_s: float = 0.0     # per-hop sojourn sum (duration hist)
    latency_count: float = 0.0
    # merged _bucket counts: upper bound (s) -> cumulative count
    latency_buckets: Dict[float, float] = dataclasses.field(
        default_factory=dict
    )
    cpu_seconds: Optional[float] = None    # station CPU (excl. sleeps)
    busy_seconds: Optional[float] = None   # occupancy [start+wait, end)
    wait_seconds: Optional[float] = None   # queue occupancy integral
    sojourn_seconds: Optional[float] = None  # occupancy [start, end)
    response_size_sum: float = 0.0
    response_size_count: float = 0.0
    replicas_hint: Optional[float] = None  # busy / (dt * utilization)
    # direct self-time observations (CSV span decomposition)
    self_time_sum_s: float = 0.0
    self_time_count: float = 0.0
    self_time_samples: List[float] = dataclasses.field(
        default_factory=list
    )


@dataclasses.dataclass
class Observation:
    """The merged IR all readers write into and the fitter reads."""

    services: Dict[str, ObservedService] = dataclasses.field(
        default_factory=dict
    )
    # (caller, callee) -> outgoing request count
    edges: Dict[Tuple[str, str], float] = dataclasses.field(
        default_factory=dict
    )
    edge_size_sum: Dict[Tuple[str, str], float] = dataclasses.field(
        default_factory=dict
    )
    edge_size_count: Dict[Tuple[str, str], float] = dataclasses.field(
        default_factory=dict
    )
    # callers whose sibling spans overlap in time (CSV inference)
    concurrent_callers: Set[str] = dataclasses.field(default_factory=set)
    # external caller names seen in the inputs
    clients_seen: Set[str] = dataclasses.field(default_factory=set)
    # entry arrivals per window (first differences of the cumulative
    # timeline counter, or CSV timestamp bucketing)
    client_windows: Optional[List[float]] = None
    window_s: Optional[float] = None
    duration_s: Optional[float] = None
    inputs: List[InputCoverage] = dataclasses.field(default_factory=list)
    notes: List[str] = dataclasses.field(default_factory=list)

    def svc(self, name: str) -> ObservedService:
        s = self.services.get(name)
        if s is None:
            s = self.services[name] = ObservedService(name)
        return s

    def add_edge(self, src: str, dst: str, count: float) -> None:
        key = (src, dst)
        self.edges[key] = self.edges.get(key, 0.0) + count

    def note(self, msg: str) -> None:
        if msg not in self.notes:
            self.notes.append(msg)


# -- Prometheus / OpenMetrics ------------------------------------------


def _latest(samples: Sequence[Sample]) -> float:
    """Instant value of a (possibly timestamped) counter series: the
    sample with the greatest timestamp wins, matching
    query.MetricStore._select."""
    best = samples[0]
    for s in samples[1:]:
        a = -1 if best.timestamp_ms is None else best.timestamp_ms
        b = -1 if s.timestamp_ms is None else s.timestamp_ms
        if b >= a:
            best = s
    return best.value


def _window_diffs(samples: Sequence[Sample]) -> Tuple[List[int], List[float]]:
    """Cumulative timestamped counter -> (sorted ts_ms, per-window
    first differences). Non-monotone steps clamp at zero (counter
    resets in real scrapes)."""
    pts = sorted(
        ((s.timestamp_ms, s.value) for s in samples if s.timestamp_ms
         is not None),
        key=lambda p: p[0],
    )
    ts = [p[0] for p in pts]
    diffs: List[float] = []
    prev = 0.0
    for _, v in pts:
        diffs.append(max(v - prev, 0.0))
        prev = v
    return ts, diffs


# series the prometheus reader consumes; anything else parsed but not
# listed here counts as ignored (vendor series, engine telemetry, ...)
_PROM_HANDLED_PREFIXES = (
    "service_incoming_requests",
    "service_outgoing_requests",
    "service_request_duration_seconds",
    "service_response_size",
    "service_outgoing_request_size",
    "service_cpu_usage_seconds",
    "timeline_client_requests",
    "timeline_client_errors",
    "timeline_service_requests",
    "timeline_service_errors",
    "timeline_service_inflight",
    "timeline_service_queue_depth",
    "timeline_service_utilization",
)


def _family(name: str) -> str:
    """Base family name: strip counter/histogram sample suffixes so
    ``foo``, ``foo_total``, ``foo_bucket``, ``foo_sum``, ``foo_count``
    land in one family (OpenMetrics suffix tolerance)."""
    for suf in ("_total", "_bucket", "_sum", "_count"):
        if name.endswith(suf):
            return name[: -len(suf)]
    return name


def read_prometheus(
    text: str,
    path: str = "<prometheus>",
    obs: Optional[Observation] = None,
) -> Observation:
    """Parse one exposition (the simulator's full + timeline families,
    or any scrape containing them) into the Observation IR."""
    if obs is None:
        obs = Observation()
    parse = parse_exposition_tolerant(text)
    cov = InputCoverage(path=path, format="prometheus")
    cov.lines_total = parse.lines_total
    cov.lines_blank = parse.lines_blank
    cov.lines_comment = parse.lines_comment
    cov.lines_parsed = parse.lines_parsed
    cov.lines_malformed = parse.lines_malformed
    cov.malformed_examples = list(parse.malformed[:5])

    by_name: Dict[str, List[Sample]] = {}
    for s in parse.samples:
        by_name.setdefault(s.name, []).append(s)

    used = 0

    def take(name: str) -> List[Sample]:
        nonlocal used
        got = by_name.pop(name, [])
        used += len(got)
        return got

    def by_label(
        samples: Sequence[Sample], *keys: str
    ) -> Dict[Tuple[str, ...], List[Sample]]:
        out: Dict[Tuple[str, ...], List[Sample]] = {}
        for s in samples:
            out.setdefault(
                tuple(s.labels.get(k, "") for k in keys), []
            ).append(s)
        return out

    # ---- full exposition (untimestamped totals) ----
    incoming = take("service_incoming_requests_total") + take(
        "service_incoming_requests"
    )
    for (svc,), group in by_label(incoming, "service").items():
        obs.svc(svc).incoming += _latest(group)

    outgoing = take("service_outgoing_requests_total") + take(
        "service_outgoing_requests"
    )
    for (src, dst), group in by_label(
        outgoing, "service", "destination_service"
    ).items():
        if src in CLIENT_ALIASES:
            obs.clients_seen.add(src)
        obs.add_edge(src, dst, _latest(group))

    for (svc, _code), group in by_label(
        take("service_request_duration_seconds_sum"), "service", "code"
    ).items():
        obs.svc(svc).latency_sum_s += _latest(group)
    for (svc, code), group in by_label(
        take("service_request_duration_seconds_count"), "service", "code"
    ).items():
        v = _latest(group)
        obs.svc(svc).latency_count += v
        if code.startswith("5"):
            obs.svc(svc).errors += v
    for (svc, le), groups in by_label(
        take("service_request_duration_seconds_bucket"), "service", "le"
    ).items():
        try:
            bound = float(le)
        except ValueError:
            cov.note(f"unparseable le={le!r} bucket bound dropped")
            continue
        # codes merged: per-(svc, le) groups may span code labels
        per_code = by_label(groups, "code")
        b = obs.svc(svc).latency_buckets
        b[bound] = b.get(bound, 0.0) + sum(
            _latest(g) for g in per_code.values()
        )

    for (svc, _code), group in by_label(
        take("service_response_size_sum"), "service", "code"
    ).items():
        obs.svc(svc).response_size_sum += _latest(group)
    for (svc, _code), group in by_label(
        take("service_response_size_count"), "service", "code"
    ).items():
        obs.svc(svc).response_size_count += _latest(group)
    used += len(take("service_response_size_bucket"))

    for (src, dst), group in by_label(
        take("service_outgoing_request_size_sum"),
        "service", "destination_service",
    ).items():
        k = (src, dst)
        obs.edge_size_sum[k] = obs.edge_size_sum.get(k, 0.0) + _latest(
            group
        )
    for (src, dst), group in by_label(
        take("service_outgoing_request_size_count"),
        "service", "destination_service",
    ).items():
        k = (src, dst)
        obs.edge_size_count[k] = obs.edge_size_count.get(
            k, 0.0
        ) + _latest(group)
    used += len(take("service_outgoing_request_size_bucket"))

    cpu = take("service_cpu_usage_seconds_total") + take(
        "service_cpu_usage_seconds"
    )
    for (svc,), group in by_label(cpu, "service").items():
        s = obs.svc(svc)
        s.cpu_seconds = (s.cpu_seconds or 0.0) + _latest(group)

    # ---- timestamped timeline exposition ----
    cli_req = take("timeline_client_requests_total") + take(
        "timeline_client_requests"
    )
    if cli_req:
        ts, diffs = _window_diffs(cli_req)
        if len(ts) >= 2:
            steps = [(b - a) / 1e3 for a, b in zip(ts, ts[1:])]
            steps = [s for s in steps if s > 0]
            window_s = sorted(steps)[len(steps) // 2] if steps else None
        else:
            window_s = None
        if window_s is None and len(ts) == 1:
            window_s = ts[0] / 1e3
        if obs.client_windows is None:
            obs.client_windows = diffs
            obs.window_s = window_s
            obs.duration_s = ts[-1] / 1e3 if ts else None
        else:
            obs.note(
                f"{path}: second client window series ignored "
                "(schedule already set)"
            )
    used += len(take("timeline_client_errors_total"))

    # per-service timeline: totals fall back to / cross-check the full
    # exposition; occupancy gauges feed the self-time decomposition
    tl_req = by_label(
        take("timeline_service_requests_total"), "service"
    )
    tl_err = by_label(take("timeline_service_errors_total"), "service")
    tl_inf = by_label(take("timeline_service_inflight"), "service")
    tl_q = by_label(take("timeline_service_queue_depth"), "service")
    tl_util = by_label(take("timeline_service_utilization"), "service")

    for (svc,), group in tl_req.items():
        s = obs.svc(svc)
        if s.incoming == 0.0:
            s.incoming = _latest(group)
    for (svc,), group in tl_err.items():
        s = obs.svc(svc)
        if s.errors == 0.0 and s.latency_count == 0.0:
            s.errors = _latest(group)

    dt = obs.window_s
    if dt:
        for (svc,), group in tl_inf.items():
            s = obs.svc(svc)
            inf_pts = sorted(
                (g.timestamp_ms, g.value) for g in group
                if g.timestamp_ms is not None
            )
            q_pts = dict(
                (g.timestamp_ms, g.value)
                for g in tl_q.get((svc,), [])
                if g.timestamp_ms is not None
            )
            u_pts = dict(
                (g.timestamp_ms, g.value)
                for g in tl_util.get((svc,), [])
                if g.timestamp_ms is not None
            )
            sojourn = busy = wait = 0.0
            rep_samples: List[float] = []
            for t, inflight in inf_pts:
                queue = q_pts.get(t, 0.0)
                util = u_pts.get(t, 0.0)
                busy_n = max(inflight - queue, 0.0)
                sojourn += inflight * dt
                busy += busy_n * dt
                wait += queue * dt
                if util > 1e-9 and busy_n > 1e-9:
                    rep_samples.append(busy_n / util)
            s.sojourn_seconds = (s.sojourn_seconds or 0.0) + sojourn
            s.busy_seconds = (s.busy_seconds or 0.0) + busy
            s.wait_seconds = (s.wait_seconds or 0.0) + wait
            if rep_samples:
                rep_samples.sort()
                s.replicas_hint = rep_samples[len(rep_samples) // 2]
    elif tl_inf:
        cov.note(
            "timeline gauges present but window length unknown "
            "(no timeline_client_requests_total): occupancy ignored"
        )

    ignored = sum(len(v) for v in by_name.values())
    families = sorted({_family(n) for n in by_name})
    if families:
        cov.note(
            "ignored series families: " + ", ".join(families[:8])
            + ("..." if len(families) > 8 else "")
        )
    cov.samples_used = used
    cov.samples_ignored = ignored
    assert cov.samples_used + cov.samples_ignored == cov.lines_parsed, (
        cov.samples_used, cov.samples_ignored, cov.lines_parsed,
    )
    obs.inputs.append(cov)
    return obs


# -- Envoy /stats cluster JSON -----------------------------------------

# the stat suffixes we model; matched from the END of the stat name so
# callee cluster names may themselves contain dots
_ENVOY_SUFFIXES = (
    "upstream_rq_total",
    "upstream_rq_5xx",
    "upstream_rq_time",
    "upstream_cx_active",
    "upstream_rq_active",
)


def read_envoy(
    text: str,
    path: str = "<envoy>",
    obs: Optional[Observation] = None,
    default_caller: str = "ingress",
) -> Observation:
    """Parse Envoy ``/stats?format=json`` cluster stats.

    Accepted shapes::

        {"services": {"<caller>": {"stats": [{"name":..., "value":...}]}}}
        {"stats": [{"name":..., "value":...}]}          # one caller

    Consumed stats: ``cluster.<callee>.upstream_rq_total`` (edge +
    callee arrivals), ``...upstream_rq_5xx`` (callee errors),
    ``...upstream_rq_time`` (mean ms -> latency sum),
    ``...upstream_cx_active`` / ``upstream_rq_active`` (concurrency
    hint).  Coverage counts stats ENTRIES (not physical lines); there
    are no timestamps, so rates require an externally supplied
    observation duration.
    """
    if obs is None:
        obs = Observation()
    cov = InputCoverage(path=path, format="envoy")
    try:
        doc = json.loads(text)
    except ValueError as e:
        cov.lines_total = 1
        cov.lines_malformed = 1
        cov.malformed_examples = [(1, f"invalid JSON: {e}")]
        obs.inputs.append(cov)
        return obs

    if isinstance(doc, dict) and isinstance(doc.get("services"), dict):
        callers = doc["services"]
    elif isinstance(doc, dict) and "stats" in doc:
        callers = {default_caller: doc}
    else:
        cov.lines_total = 1
        cov.lines_malformed = 1
        cov.malformed_examples = [
            (1, "unrecognized Envoy stats document shape")
        ]
        obs.inputs.append(cov)
        return obs

    rq_time: Dict[Tuple[str, str], float] = {}
    for caller, body in callers.items():
        stats = body.get("stats") if isinstance(body, dict) else None
        if not isinstance(stats, list):
            cov.lines_total += 1
            cov.lines_malformed += 1
            if len(cov.malformed_examples) < 5:
                cov.malformed_examples.append(
                    (cov.lines_total, f"service {caller!r}: no stats list")
                )
            continue
        if caller in CLIENT_ALIASES:
            obs.clients_seen.add(caller)
        for entry in stats:
            cov.lines_total += 1
            name = entry.get("name") if isinstance(entry, dict) else None
            value = entry.get("value") if isinstance(entry, dict) else None
            if not isinstance(name, str) or not isinstance(
                value, (int, float)
            ):
                cov.lines_malformed += 1
                if len(cov.malformed_examples) < 5:
                    cov.malformed_examples.append(
                        (cov.lines_total, repr(entry)[:120])
                    )
                continue
            cov.lines_parsed += 1
            if not name.startswith("cluster."):
                cov.samples_ignored += 1
                continue
            rest = name[len("cluster."):]
            matched = None
            for suf in _ENVOY_SUFFIXES:
                if rest.endswith("." + suf):
                    matched = suf
                    callee = rest[: -(len(suf) + 1)]
                    break
            if matched is None:
                cov.samples_ignored += 1
                continue
            cov.samples_used += 1
            v = float(value)
            if matched == "upstream_rq_total":
                obs.add_edge(caller, callee, v)
                obs.svc(callee).incoming += v
            elif matched == "upstream_rq_5xx":
                obs.svc(callee).errors += v
            elif matched == "upstream_rq_time":
                # Envoy renders this histogram as a mean in ms in the
                # JSON stats dump; defer to rq_total for the weight
                rq_time[(caller, callee)] = v / 1e3
            else:  # *_active gauges: replica/concurrency hint
                s = obs.svc(callee)
                s.replicas_hint = max(s.replicas_hint or 0.0, v)
    for (caller, callee), mean_s in rq_time.items():
        n = obs.edges.get((caller, callee), 0.0)
        if n > 0:
            s = obs.svc(callee)
            s.latency_sum_s += mean_s * n
            s.latency_count += n
    cov.note(
        "no timestamps in Envoy stats: qps schedule requires "
        "--duration; latency from upstream_rq_time means"
    )
    assert (
        cov.lines_total
        == cov.lines_parsed + cov.lines_malformed + cov.lines_blank
        + cov.lines_comment
    )
    assert cov.samples_used + cov.samples_ignored == cov.lines_parsed
    obs.inputs.append(cov)
    return obs


# -- CSV span traces ---------------------------------------------------

_CSV_COLUMNS = ("caller", "callee", "timestamp", "rt", "status")


def _union_length(intervals: List[Tuple[float, float]]) -> float:
    """Total length covered by a set of [start, end) intervals —
    concurrency-safe child-time subtraction."""
    if not intervals:
        return 0.0
    intervals.sort()
    total = 0.0
    cur_s, cur_e = intervals[0]
    for s, e in intervals[1:]:
        if s > cur_e:
            total += cur_e - cur_s
            cur_s, cur_e = s, e
        else:
            cur_e = max(cur_e, e)
    total += cur_e - cur_s
    return total


def read_csv_trace(
    text: str,
    path: str = "<csv>",
    obs: Optional[Observation] = None,
    window_s: float = 1.0,
) -> Observation:
    """Parse a span-per-row CSV trace (see README "Trace-driven ingest"
    for the schema).  Required columns: caller, callee, timestamp (s),
    rt (s), status (HTTP code, or ok/error).  Optional: traceid —
    enables self-time decomposition and concurrent-group inference.
    Callers never observed as callees are treated as external clients.
    """
    if obs is None:
        obs = Observation()
    cov = InputCoverage(path=path, format="csv")
    reader = csv.reader(io.StringIO(text))
    header: Optional[List[str]] = None
    col: Dict[str, int] = {}
    rows: List[tuple] = []  # (traceid, caller, callee, ts, rt, err)
    lineno = 0
    for raw in reader:
        lineno += 1
        cov.lines_total += 1
        if not raw or all(not c.strip() for c in raw):
            cov.lines_blank += 1
            continue
        if raw[0].lstrip().startswith("#"):
            cov.lines_comment += 1
            continue
        if header is None:
            header = [c.strip().lower() for c in raw]
            col = {name: i for i, name in enumerate(header)}
            missing = [c for c in _CSV_COLUMNS if c not in col]
            if missing:
                cov.lines_malformed += 1
                cov.malformed_examples.append(
                    (lineno, f"header missing columns: {missing}")
                )
                header = None
                col = {}
            else:
                cov.lines_comment += 1  # header is schema, not data
            continue
        try:
            caller = raw[col["caller"]].strip()
            callee = raw[col["callee"]].strip()
            ts = float(raw[col["timestamp"]])
            rt = float(raw[col["rt"]])
            status = raw[col["status"]].strip().lower()
        except (IndexError, ValueError):
            cov.lines_malformed += 1
            if len(cov.malformed_examples) < 5:
                cov.malformed_examples.append(
                    (lineno, ",".join(raw)[:120])
                )
            continue
        if not callee or rt < 0 or not math.isfinite(ts):
            cov.lines_malformed += 1
            if len(cov.malformed_examples) < 5:
                cov.malformed_examples.append(
                    (lineno, ",".join(raw)[:120])
                )
            continue
        cov.lines_parsed += 1
        cov.samples_used += 1
        err = status.startswith("5") or status in ("error", "err", "fail")
        tid = raw[col["traceid"]].strip() if "traceid" in col else ""
        rows.append((tid, caller, callee, ts, rt, err))

    if header is None and cov.lines_parsed == 0:
        cov.note("no valid header row: expected columns "
                 + ", ".join(_CSV_COLUMNS))
        obs.inputs.append(cov)
        return obs

    callees = {r[2] for r in rows}
    for tid, caller, callee, ts, rt, err in rows:
        if caller in CLIENT_ALIASES or caller not in callees:
            obs.clients_seen.add(caller)
        obs.add_edge(caller, callee, 1.0)
        s = obs.svc(callee)
        s.incoming += 1.0
        s.latency_sum_s += rt
        s.latency_count += 1.0
        if err:
            s.errors += 1.0

    # entry arrival windows from external-caller spans
    entry_ts = [
        r[3] for r in rows
        if r[1] in CLIENT_ALIASES or r[1] not in callees
    ]
    if entry_ts:
        t0, t1 = min(entry_ts), max(entry_ts)
        n_windows = max(1, int(math.ceil((t1 - t0) / window_s + 1e-9)))
        n_windows = max(n_windows, 1)
        windows = [0.0] * n_windows
        for t in entry_ts:
            w = min(int((t - t0) / window_s), n_windows - 1)
            windows[w] += 1.0
        if obs.client_windows is None:
            obs.client_windows = windows
            obs.window_s = window_s
            obs.duration_s = max(n_windows * window_s, t1 - t0)
    else:
        cov.note("no external-caller rows: qps schedule not inferred")

    # span nesting: self-time + concurrent-group inference (traceid)
    with_tid = [r for r in rows if r[0]]
    if with_tid:
        by_trace: Dict[str, Dict[str, List[tuple]]] = {}
        for r in with_tid:
            by_trace.setdefault(r[0], {}).setdefault(r[1], []).append(r)
        overlap_pairs: Dict[str, List[int]] = {}
        for callers_in_trace in by_trace.values():
            for spans in callers_in_trace.values():
                for _tid, _caller, callee, ts, rt, _err in spans:
                    # children: spans whose caller == this callee,
                    # starting inside this span's interval
                    kids = [
                        k for k in callers_in_trace.get(callee, [])
                        if ts - 1e-9 <= k[3] <= ts + rt + 1e-9
                    ]
                    child_iv = [
                        (k[3], min(k[3] + k[4], ts + rt)) for k in kids
                    ]
                    self_t = max(rt - _union_length(child_iv), 0.0)
                    s = obs.svc(callee)
                    s.self_time_sum_s += self_t
                    s.self_time_count += 1.0
                    if len(s.self_time_samples) < 10_000:
                        s.self_time_samples.append(self_t)
                    # sibling overlap among this span's children
                    if len(kids) >= 2:
                        kids.sort(key=lambda k: k[3])
                        tally = overlap_pairs.setdefault(callee, [0, 0])
                        for a, b in zip(kids, kids[1:]):
                            tally[1] += 1
                            if b[3] < a[3] + a[4] - 1e-9:
                                tally[0] += 1
        for svc, (hits, pairs) in overlap_pairs.items():
            if pairs > 0 and hits / pairs > 0.5:
                obs.concurrent_callers.add(svc)
    elif rows:
        cov.note(
            "no traceid column: self-time and concurrency not "
            "inferred; sojourn used as self-time upper bound"
        )

    assert (
        cov.lines_total
        == cov.lines_blank + cov.lines_comment + cov.lines_parsed
        + cov.lines_malformed
    )
    assert cov.samples_used + cov.samples_ignored == cov.lines_parsed
    obs.inputs.append(cov)
    return obs


# -- dispatch ----------------------------------------------------------


def read_path(
    path: str,
    obs: Optional[Observation] = None,
    fmt: Optional[str] = None,
    window_s: float = 1.0,
) -> Observation:
    """Read one input file, sniffing the format from the extension
    (``.json`` -> envoy, ``.csv`` -> csv, else prometheus) unless
    ``fmt`` pins it."""
    with open(path) as f:
        text = f.read()
    if fmt is None:
        low = path.lower()
        if low.endswith(".json"):
            fmt = "envoy"
        elif low.endswith(".csv"):
            fmt = "csv"
        else:
            fmt = "prometheus"
    if fmt == "envoy":
        return read_envoy(text, path=path, obs=obs)
    if fmt == "csv":
        return read_csv_trace(text, path=path, obs=obs, window_s=window_s)
    if fmt == "prometheus":
        return read_prometheus(text, path=path, obs=obs)
    raise ValueError(f"unknown ingest format: {fmt!r}")
