"""The fit-fidelity artifact: ``<label>.ingest.json``.

Schema ``isotope-ingest/v1`` (to_doc / check_doc / load_doc, the same
round-trip idiom as isotope-timeline/v1 and isotope-search/v1):

- ``inputs``: per-input coverage rows whose counters PARTITION each
  input (lines_total == blank + comment + parsed + malformed;
  parsed == used + ignored) — the no-silent-truncation pin;
- ``fit``: the global knobs the TOML carries (entry, cpu_time, qps
  schedule) plus per-service observed/fitted values and residuals;
- ``coverage``: everything dropped, each with a reason (services
  unreachable from the entrypoint, cycle-closing edges, zero-ratio
  edges, empty lead/tail windows);
- ``closure`` (optional): the self-closure comparison appended when
  the source topology is known (tools/ingest_smoke.py), reconstructed
  vs source error share / mean self-time / degree sequence / qps
  schedule with the tolerances stated next to each check.

``format_report`` renders the human view for ``isotope-tpu explain``.
"""
from __future__ import annotations

import json
import math
from typing import Dict, List, Optional

from isotope_tpu.ingest.fitters import FitResult
from isotope_tpu.ingest.readers import Observation

DOC_SCHEMA = "isotope-ingest/v1"

# Self-closure tolerances (documented in README "Trace-driven ingest").
# The fit is statistical — Poisson arrival noise ~ 1/sqrt(qps * dt) per
# window, self-time residuals from the wire-time model — so the pins
# are bands, not equalities; the degree sequence alone is exact.
CLOSURE_TOLERANCES = {
    # |fitted - source| per-service intrinsic error share
    "error_share_abs": 0.02,
    # relative error of the FLEET MEAN self-time (cpu + sleep)
    "self_time_mean_rel": 0.15,
    # per-service self-time relative error (services with >= the
    # sample floor below); the residual estimator subtracts a
    # wire+sojourn term PER CALL, so high-fan-out hubs accumulate
    # noise linearly in degree — the pin is a band SHARE, not
    # all-or-nothing
    "self_time_each_rel": 0.35,
    "self_time_min_samples": 30,
    "self_time_band_share": 0.90,
    # sorted out-degree sequences must match exactly
    "degree_sequence": "exact",
    # fitted windowed qps: mean within this relative band ...
    "qps_mean_rel": 0.10,
    # ... and this share of windows within qps_window_rel of source
    "qps_window_rel": 0.25,
    "qps_window_share": 0.80,
}


def _finite(x: Optional[float]) -> Optional[float]:
    if x is None:
        return None
    x = float(x)
    return x if math.isfinite(x) else None


def to_doc(fr: FitResult, obs: Observation) -> dict:
    services = []
    for name in sorted(fr.services):
        f = fr.services[name]
        o = obs.services.get(name)
        row = {
            "name": name,
            "observed": {
                "incoming": f.incoming,
                "errors": (o.errors if o else 0.0),
                "sojourn_s": _finite(f.sojourn_s),
                "station_cpu_s": _finite(f.station_cpu_s),
                "samples": f.samples,
            },
            "fitted": {
                "error_rate": f.error_rate,
                "self_time_s": round(f.self_time_s, 9),
                "sleep_s": round(f.sleep_s, 9),
                "replicas": f.replicas,
                "out_degree": f.out_degree,
                "concurrent": f.concurrent,
                "response_size": f.response_size,
            },
            "residuals": _residuals(f),
        }
        if f.self_hist:
            row["observed"]["self_time_log_hist"] = [
                ["+Inf" if math.isinf(b) else b, c]
                for b, c in f.self_hist
            ]
        if f.flags:
            row["flags"] = list(f.flags)
        services.append(row)
    doc = {
        "schema": DOC_SCHEMA,
        "label": fr.label,
        "entry": fr.entry,
        "inputs": [c.to_dict() for c in obs.inputs],
        "fit": {
            "cpu_time_s": fr.cpu_time_s,
            "qps_mean": fr.qps_mean,
            "qps_schedule": [round(q, 6) for q in fr.qps_schedule],
            "window_s": fr.window_s,
            "duration_s": fr.duration_s,
            "num_services": len(fr.services),
            "num_edges": len(fr.edges),
            "degree_sequence": degree_sequence(fr),
            "services": services,
            "edges": [
                {"caller": src, "callee": dst, "ratio": round(r, 6)}
                for (src, dst), r in sorted(fr.edges.items())
            ],
        },
        "coverage": {
            "services_dropped": fr.dropped["services"],
            "edges_dropped": fr.dropped["edges"],
            "windows_dropped": fr.dropped["windows"],
        },
        "notes": list(fr.notes),
    }
    return doc


def _residuals(f) -> dict:
    """Self-consistency residuals: how far the fitted point estimates
    sit from their own observations (not from ground truth — that is
    the closure block's job)."""
    out: dict = {}
    if f.sojourn_s is not None and f.sojourn_s > 0:
        # the sleep+cpu point estimate can never exceed the sojourn
        out["self_over_sojourn"] = round(
            f.self_time_s / f.sojourn_s, 6
        )
    if f.station_cpu_s is not None and f.self_time_s > 0:
        out["station_share_of_self"] = round(
            min(f.station_cpu_s / f.self_time_s, 1.0), 6
        )
    return out


def degree_sequence(fr: FitResult) -> List[int]:
    return sorted(
        (f.out_degree for f in fr.services.values()), reverse=True
    )


def check_doc(doc: dict) -> dict:
    """Validate an isotope-ingest/v1 document (round-trip guard)."""
    if doc.get("schema") != DOC_SCHEMA:
        raise ValueError(
            f"not an {DOC_SCHEMA} document: {doc.get('schema')!r}"
        )
    for key in ("label", "inputs", "fit", "coverage"):
        if key not in doc:
            raise ValueError(f"{DOC_SCHEMA} document missing {key!r}")
    cov = doc["coverage"]
    for key in ("services_dropped", "edges_dropped", "windows_dropped"):
        if not isinstance(cov.get(key), list):
            raise ValueError(
                f"{DOC_SCHEMA} coverage.{key} must be a list"
            )
    for row in doc["inputs"]:
        total = row["lines_total"]
        parts = (
            row["lines_blank"] + row["lines_comment"]
            + row["lines_parsed"] + row["lines_malformed"]
        )
        if total != parts:
            raise ValueError(
                f"coverage accounting broken for {row.get('path')!r}: "
                f"lines_total={total} != partition sum {parts}"
            )
        if row["samples_used"] + row["samples_ignored"] != (
            row["lines_parsed"]
        ):
            raise ValueError(
                f"sample accounting broken for {row.get('path')!r}"
            )
    return doc


def load_doc(path: str) -> dict:
    with open(path) as f:
        return check_doc(json.load(f))


def save_doc(doc: dict, path: str) -> None:
    with open(path, "w") as f:
        json.dump(check_doc(doc), f, indent=1, sort_keys=False)
        f.write("\n")


# -- self-closure ------------------------------------------------------


def closure_check(
    source_graph,
    source_cpu_time_s: float,
    source_qps: List[float],
    fr: FitResult,
    tolerances: Optional[dict] = None,
) -> dict:
    """Compare a fit against its known source topology: the self-closure
    pin.  Returns a dict with per-check pass/fail detail and an overall
    ``ok``; appended to the artifact under ``closure`` by the smoke.

    ``source_qps`` is the per-window source schedule (a constant-rate
    run passes ``[qps] * windows`` or just ``[qps]``).
    """
    tol = dict(CLOSURE_TOLERANCES)
    if tolerances:
        tol.update(tolerances)
    checks: List[dict] = []

    # source per-service truth
    src_err: Dict[str, float] = {}
    src_self: Dict[str, float] = {}
    src_deg: List[int] = []
    for svc in source_graph.services:
        src_err[svc.name] = float(svc.error_rate)
        sleep = 0.0
        deg = 0
        for cmd in svc.script:
            for c in _flatten(cmd):
                if hasattr(c, "seconds"):
                    sleep += c.seconds
                elif hasattr(c, "service_name"):
                    deg += 1
        src_self[svc.name] = source_cpu_time_s + sleep
        src_deg.append(deg)

    # error share
    worst_err = (None, 0.0)
    for name, f in fr.services.items():
        if name not in src_err:
            continue
        d = abs(f.error_rate - src_err[name])
        if d > worst_err[1]:
            worst_err = (name, d)
    checks.append({
        "check": "error_share",
        "tolerance_abs": tol["error_share_abs"],
        "worst_service": worst_err[0],
        "worst_abs_error": round(worst_err[1], 6),
        "ok": worst_err[1] <= tol["error_share_abs"],
    })

    # self-time (cpu + sleep): fleet mean + per-service band
    pairs = [
        (name, src_self[name],
         f.self_time_s if f.self_time_s > 0 else (
             f.station_cpu_s or fr.cpu_time_s
         ))
        for name, f in fr.services.items() if name in src_self
    ]
    if pairs:
        src_mean = sum(p[1] for p in pairs) / len(pairs)
        fit_mean = sum(p[2] for p in pairs) / len(pairs)
        mean_rel = abs(fit_mean - src_mean) / max(src_mean, 1e-12)
        per_svc_bad = []
        eligible = 0
        for name, s, v in pairs:
            f = fr.services[name]
            if f.samples < tol["self_time_min_samples"]:
                continue
            eligible += 1
            rel = abs(v - s) / max(s, 1e-12)
            if rel > tol["self_time_each_rel"]:
                per_svc_bad.append(
                    {"service": name, "rel_error": round(rel, 4),
                     "source_s": s, "fitted_s": v}
                )
        in_band_share = (
            (eligible - len(per_svc_bad)) / eligible
            if eligible else 1.0
        )
        checks.append({
            "check": "self_time",
            "tolerance_mean_rel": tol["self_time_mean_rel"],
            "tolerance_each_rel": tol["self_time_each_rel"],
            "tolerance_band_share": tol["self_time_band_share"],
            "source_mean_s": src_mean,
            "fitted_mean_s": fit_mean,
            "mean_rel_error": round(mean_rel, 6),
            "services_eligible": eligible,
            "services_in_band_share": round(in_band_share, 4),
            "services_out_of_band": per_svc_bad[:10],
            "ok": (
                mean_rel <= tol["self_time_mean_rel"]
                and in_band_share >= tol["self_time_band_share"]
            ),
        })

    # fan-out degree sequence (exact)
    fit_deg = degree_sequence(fr)
    src_deg_sorted = sorted(src_deg, reverse=True)
    checks.append({
        "check": "degree_sequence",
        "tolerance": "exact",
        "source": src_deg_sorted,
        "fitted": fit_deg,
        "ok": fit_deg == src_deg_sorted,
    })

    # qps schedule
    if source_qps:
        src_sched = list(source_qps)
        if len(src_sched) == 1:
            src_sched = src_sched * len(fr.qps_schedule)
        n = min(len(src_sched), len(fr.qps_schedule))
        src_mean_q = sum(src_sched) / max(len(src_sched), 1)
        fit_mean_q = fr.qps_mean
        mean_rel = abs(fit_mean_q - src_mean_q) / max(src_mean_q, 1e-12)
        in_band = sum(
            1 for i in range(n)
            if abs(fr.qps_schedule[i] - src_sched[i])
            <= tol["qps_window_rel"] * max(src_sched[i], 1e-12)
        )
        share = in_band / n if n else 0.0
        checks.append({
            "check": "qps_schedule",
            "tolerance_mean_rel": tol["qps_mean_rel"],
            "tolerance_window_rel": tol["qps_window_rel"],
            "tolerance_window_share": tol["qps_window_share"],
            "source_mean": src_mean_q,
            "fitted_mean": fit_mean_q,
            "mean_rel_error": round(mean_rel, 6),
            "windows_compared": n,
            "windows_in_band_share": round(share, 4),
            "ok": (
                mean_rel <= tol["qps_mean_rel"]
                and share >= tol["qps_window_share"]
            ),
        })

    return {
        "tolerances": tol,
        "checks": checks,
        "ok": all(c["ok"] for c in checks),
    }


def _flatten(cmd):
    # ConcurrentCommand subclasses list
    if isinstance(cmd, (list, tuple)):
        for c in cmd:
            yield from _flatten(c)
    else:
        yield cmd


# -- rendering (explain) -----------------------------------------------


def format_report(doc: dict, top: int = 10) -> str:
    check_doc(doc)
    fit = doc["fit"]
    cov = doc["coverage"]
    out: List[str] = []
    out.append(
        f"ingest {doc['label']!r}: {fit['num_services']} services, "
        f"{fit['num_edges']} edges, entry {doc.get('entry')!r}"
    )
    out.append(
        f"  schedule: {len(fit['qps_schedule'])} windows x "
        f"{fit['window_s']:g}s, mean {fit['qps_mean']:.1f} qps; "
        f"[sim] cpu_time {fit['cpu_time_s'] * 1e6:.0f}us"
    )
    for row in doc["inputs"]:
        out.append(
            f"  input {row['path']} ({row['format']}): "
            f"{row['lines_parsed']} parsed / {row['lines_malformed']} "
            f"malformed of {row['lines_total']}; "
            f"{row['samples_used']} used, "
            f"{row['samples_ignored']} ignored"
        )
        for n, t in row.get("malformed_examples", [])[:3]:
            out.append(f"    line {n}: {t}")
    dropped = (
        len(cov["services_dropped"]), len(cov["edges_dropped"]),
        len(cov["windows_dropped"]),
    )
    if any(dropped):
        out.append(
            f"  dropped: {dropped[0]} services, {dropped[1]} edges, "
            f"{dropped[2]} windows (reasons in coverage block)"
        )
        for row in cov["services_dropped"][:3]:
            out.append(
                f"    service {row['service']!r}: {row['reason']}"
            )
        for row in cov["edges_dropped"][:3]:
            out.append(
                f"    edge {row['edge'][0]}->{row['edge'][1]}: "
                f"{row['reason']}"
            )
    else:
        out.append("  dropped: nothing")
    rows = sorted(
        fit["services"],
        key=lambda r: -(r["observed"]["incoming"] or 0),
    )[:top]
    out.append(
        f"  top services by arrivals (of {fit['num_services']}):"
    )
    for r in rows:
        fitted = r["fitted"]
        line = (
            f"    {r['name']}: {r['observed']['incoming']:.0f} req, "
            f"err {fitted['error_rate']:.3f}, "
            f"self {fitted['self_time_s'] * 1e3:.2f}ms "
            f"(sleep {fitted['sleep_s'] * 1e3:.2f}ms), "
            f"fan-out {fitted['out_degree']}"
        )
        if fitted.get("concurrent"):
            line += " (concurrent)"
        out.append(line)
        for flag in r.get("flags", [])[:2]:
            out.append(f"      ! {flag}")
    closure = doc.get("closure")
    if closure:
        verdict = "PASS" if closure.get("ok") else "FAIL"
        out.append(f"  self-closure: {verdict}")
        for c in closure.get("checks", []):
            mark = "ok" if c.get("ok") else "FAIL"
            detail = ""
            if "mean_rel_error" in c:
                detail = f" mean_rel={c['mean_rel_error']:.3f}"
            elif "worst_abs_error" in c:
                detail = f" worst_abs={c['worst_abs_error']:.4f}"
            out.append(f"    {c['check']}: {mark}{detail}")
    if doc.get("notes"):
        for n in doc["notes"][:5]:
            out.append(f"  note: {n}")
    return "\n".join(out)
