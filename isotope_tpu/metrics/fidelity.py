"""Ground-truth fidelity check against a real Fortio artifact.

The north star's fidelity clause is "p99 within 5% of a real Fortio
run" (BASELINE.json).  The reference's evidence chain starts from real
``fortio load -json`` output — the artifact whose schema
``perf/benchmark/runner/fortio.py:38-75`` flattens (and which
``metrics/fortio.py`` emits for simulated runs).  This module closes
the loop for the day real ground truth exists: ingest an actual Fortio
result JSON, reconstruct the matching load (closed-loop workers at the
artifact's NumThreads / RequestedQPS, or ``-qps max`` saturation),
simulate the topology, and diff the sim's percentiles against the
artifact's, percentile by percentile.

Simulation knobs that the artifact cannot carry (service-time
distribution, CPU demand, the environment's sidecar tax) are passed by
the caller — the workflow is: measure once on the cluster, then tune
``SimParams`` until the report is inside the clause.
"""
from __future__ import annotations

import dataclasses
import math
from typing import List, Optional, Sequence

import numpy as np


@dataclasses.dataclass(frozen=True)
class PercentileDelta:
    """One artifact-vs-sim percentile comparison (seconds)."""

    percentile: float
    fortio_s: float
    sim_s: float

    @property
    def rel_err(self) -> float:
        if self.fortio_s <= 0:
            return math.inf if self.sim_s > 0 else 0.0
        return self.sim_s / self.fortio_s - 1.0


@dataclasses.dataclass(frozen=True)
class FidelityReport:
    deltas: List[PercentileDelta]
    actual_qps_fortio: float
    actual_qps_sim: float
    error_percent_fortio: float
    error_percent_sim: float
    tolerance: float

    @property
    def ok(self) -> bool:
        # vacuous truth is failure: a gate that compared nothing must
        # not report PASS (empty Percentiles happens on tiny runs)
        return bool(self.deltas) and all(
            abs(d.rel_err) <= self.tolerance for d in self.deltas
        )

    def lines(self) -> List[str]:
        out = [
            f"{'pctile':>8} {'fortio':>12} {'sim':>12} {'delta':>8}",
        ]
        for d in self.deltas:
            mark = "" if abs(d.rel_err) <= self.tolerance else "  OUT"
            out.append(
                f"{d.percentile:>8g} {d.fortio_s * 1e3:>10.3f}ms "
                f"{d.sim_s * 1e3:>10.3f}ms {d.rel_err:>+7.2%}{mark}"
            )
        out.append(
            f"   qps: fortio {self.actual_qps_fortio:.1f} vs sim "
            f"{self.actual_qps_sim:.1f}; errors: "
            f"{self.error_percent_fortio:.2f}% vs "
            f"{self.error_percent_sim:.2f}%"
        )
        if self.ok:
            out.append(
                f"   PASS: all percentiles within "
                f"{self.tolerance:.0%} of the Fortio artifact"
            )
        elif not self.deltas:
            out.append(
                "   FAIL: the artifact carried no comparable "
                "percentiles — nothing was checked"
            )
        else:
            out.append(
                f"   FAIL: at least one percentile beyond "
                f"{self.tolerance:.0%}"
            )
        return out


def load_from_artifact(doc: dict, connections_default: int = 64):
    """(LoadModel, duration_s) reconstructed from a Fortio result JSON.

    ``RequestedQPS`` is a number or the string "max" (runner.py's
    ``-qps max``); ``NumThreads`` is ``-c``; ``ActualDuration`` is in
    nanoseconds (the Go time.Duration encoding the reference divides
    by 1e9, fortio.py:58).
    """
    from isotope_tpu.sim.config import LoadModel

    req = doc.get("RequestedQPS", "max")
    conns = int(doc.get("NumThreads", connections_default))
    if isinstance(req, str) and req == "max":
        load = LoadModel(kind="closed", qps=None, connections=conns)
    else:
        load = LoadModel(
            kind="closed", qps=float(req), connections=conns
        )
    duration_s = float(doc.get("ActualDuration", 0)) / 1e9
    return load, duration_s


def check_fidelity(
    doc: dict,
    topology_yaml: str,
    params=None,
    tolerance: float = 0.05,
    max_requests: int = 1_000_000,
    percentiles: Optional[Sequence[float]] = None,
    entry: Optional[str] = None,
    seed: int = 0,
) -> FidelityReport:
    """Simulate the artifact's run and diff percentiles.

    ``doc`` is a parsed ``fortio load -json`` result; ``topology_yaml``
    the service-graph YAML text the cluster ran.  The request count is
    the artifact's own census (ActualQPS x duration) capped at
    ``max_requests``.
    """
    import jax

    from isotope_tpu.compiler import compile_graph
    from isotope_tpu.models.graph import ServiceGraph
    from isotope_tpu.sim.config import SimParams
    from isotope_tpu.sim.engine import Simulator

    params = params or SimParams()
    load, duration_s = load_from_artifact(doc)
    actual_qps = float(doc.get("ActualQPS", 0.0))
    n = int(min(max(actual_qps * duration_s, 10_000), max_requests))

    graph = ServiceGraph.from_yaml(topology_yaml)
    sim = Simulator(compile_graph(graph, entry=entry), params)
    summary = sim.run_summary(load, n, jax.random.PRNGKey(seed))

    h = doc["DurationHistogram"]
    wanted = (
        [float(p["Percentile"]) for p in h["Percentiles"]]
        if percentiles is None
        else list(percentiles)
    )
    ref_vals = {float(p["Percentile"]): float(p["Value"])
                for p in h["Percentiles"]}
    qs = [p / 100.0 for p in wanted]
    sim_vals = summary.quantiles_s(tuple(qs))
    deltas = [
        PercentileDelta(p, ref_vals.get(p, float("nan")), float(sv))
        for p, sv in zip(wanted, np.asarray(sim_vals))
        if p in ref_vals
    ]

    count = float(doc.get("Sizes", {}).get("Count", 0.0)) or float(
        sum(doc.get("RetCodes", {}).values())
    )
    ok_ref = float(doc.get("RetCodes", {}).get("200", 0))
    err_ref = 100.0 * (count - ok_ref) / count if count else 0.0
    sim_count = float(summary.count)
    err_sim = (
        100.0 * float(summary.error_count) / sim_count
        if sim_count else 0.0
    )
    sim_qps = (
        sim_count / float(summary.end_max)
        if float(summary.end_max) > 0 else 0.0
    )
    return FidelityReport(
        deltas=deltas,
        actual_qps_fortio=actual_qps,
        actual_qps_sim=sim_qps,
        error_percent_fortio=err_ref,
        error_percent_sim=err_sim,
        tolerance=tolerance,
    )
