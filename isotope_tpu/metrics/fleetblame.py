"""Fleet divergence explainer: WHY a Monte Carlo member had a bad day.

PR 17's fleet observability threads the PR 5 attribution pass and the
PR 7 flight recorder through the member axis of every fleet entry
point (``Simulator.run_ensemble(attribution=..., timeline=...)`` and
the protected fleet runners), so an ``EnsembleSummary`` now carries a
stacked :class:`~isotope_tpu.metrics.attribution.AttributionSummary`
(``attributions``, ``(N,)``-leading leaves) and the per-member window
series (``timelines``).  This module turns those stacks into an
explanation — the fleet dimension is what upgrades blame from a single
anecdote to a distribution (the Ising-on-TPU statistical-power idiom
from PAPERS.md):

- **blame-share bands**: per-hop across-member quantile bands of the
  blame share — "a healthy member spends 55–60% of its latency in
  ``worker`` queueing" — the DrJAX-style population reduction (a
  per-member map, a quantile reduce over the member axis);
- **control deltas**: member k's per-request blame-seconds minus the
  control member's, per hop, ranked descending — the hops whose excess
  blame adds up to (mean-decomposes) member k's latency gap;
- **onset localization**: for each member and recorder channel
  (per-service in-flight occupancy, per-service errors), the first
  window
  where the member departs the across-member per-window median by
  more than ``margin`` robust sigmas (median + MAD — one divergent
  member cannot contaminate its own reference band) — WHEN the
  divergence started, not just that it existed.

Everything reduces on device inside one jitted program; the caller
pays exactly ONE ``jax.device_get`` per fleet (:func:`explain_fleet`).
The ``isotope-fleet-blame/v1`` document (:func:`to_doc`) is what the
runner writes as ``<label>.fleet-blame.json`` and what the
``isotope-tpu explain`` subcommand renders (:func:`format_report`).
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

#: artifact schema tag (runner/run.py writes ``<label>.fleet-blame.json``)
DOC_SCHEMA = "isotope-fleet-blame/v1"

#: across-member quantile band reported per hop (lo, mid, hi)
BAND = (0.1, 0.5, 0.9)

#: how many robust sigmas past the member median counts as a departure
ONSET_MARGIN = 4.0

#: recorder channels the onset localizer scans, in report priority
#: order (an error burst outranks an occupancy ramp at the same
#: window).  ``inflight`` is the [start, end) occupancy integral —
#: unlike ``svc_busy_s`` it INCLUDES queueing wait, which is where a
#: capacity loss first shows
ONSET_CHANNELS = ("errors", "inflight")


def _hop_blame(attr) -> jax.Array:
    """(N, H) total blame seconds per hop (wait + self + net + timeout)."""
    return (
        jnp.asarray(attr.wait_blame)
        + jnp.asarray(attr.self_blame)
        + jnp.asarray(attr.net_blame)
        + jnp.asarray(attr.timeout_blame)
    )


def _onset_windows(series: jax.Array,
                   margin: float) -> Tuple[jax.Array, jax.Array]:
    """First departing window per (member, service).

    ``series`` is (N, S, W).  A member departs at window w when its
    value exceeds the across-member per-window median by more than
    ``margin`` robust sigmas (1.4826 * MAD — breakdown point 50%, so
    ONE divergent member cannot contaminate its own reference band the
    way a small-N quantile would), with an absolute scale floor so a
    near-constant channel's noise never "departs".  Returns
    ``(onset, depth)`` — onset is (N, S) i32 (-1 when the member never
    departs), depth the departure magnitude in robust sigmas."""
    med = jnp.median(series, axis=0)                     # (S, W)
    mad = jnp.median(jnp.abs(series - med[None]), axis=0)
    # per-SERVICE scale floor: a busy entry tier must not flatten a
    # small service's departure signal
    floor = 0.02 * jnp.max(med, axis=1, keepdims=True) + 1e-9
    scale = jnp.maximum(1.4826 * mad, floor)
    excess = (series - med[None]) / scale[None]          # (N, S, W)
    departed = excess > margin
    W = series.shape[-1]
    idx = jnp.arange(W)[None, None, :]
    first = jnp.min(jnp.where(departed, idx, W), axis=-1)  # (N, S)
    # departure magnitude at the onset window (0 when never departed)
    at = jnp.clip(first, 0, W - 1)
    depth = jnp.take_along_axis(
        excess, at[..., None], axis=-1
    )[..., 0]
    depth = jnp.where(first < W, depth, 0.0)
    onset = jnp.where(first < W, first, -1).astype(jnp.int32)
    return onset, depth


def _device_reduce(attributions, timelines, control: int,
                   band: Tuple[float, float, float], margin: float):
    """The one-dispatch device program behind :func:`explain_fleet`."""
    blame = _hop_blame(attributions)                     # (N, H)
    count = jnp.maximum(
        jnp.asarray(attributions.count, jnp.float32), 1.0
    )                                                    # (N,)
    per_req = blame / count[:, None]                     # (N, H)
    total = jnp.maximum(blame.sum(axis=1), 1e-12)        # (N,)
    share = blame / total[:, None]                       # (N, H)
    out = {
        "blame_s": blame,
        "per_request_s": per_req,
        "share": share,
        "share_band": jnp.quantile(
            share, jnp.asarray(band), axis=0
        ),                                               # (3, H)
        "delta_per_request_s": per_req - per_req[control][None],
        "mean_latency_gap_s": (
            blame.sum(axis=1) / count
            - blame[control].sum() / count[control]
        ),                                               # (N,)
        "error_count": jnp.asarray(
            attributions.error_count, jnp.float32
        ),                                               # (N, H)
    }
    if timelines is not None:
        channels = {
            "inflight": jnp.asarray(
                timelines.svc_inflight_s, jnp.float32
            ),
            "errors": jnp.asarray(
                timelines.svc_errors, jnp.float32
            ),
        }
        for name in ONSET_CHANNELS:
            onset, depth = _onset_windows(channels[name], margin)
            out[f"onset_{name}"] = onset                 # (N, S)
            out[f"onset_{name}_depth"] = depth           # (N, S)
    return out


def explain_fleet(attributions, timelines=None, *, control: int = 0,
                  band: Tuple[float, float, float] = BAND,
                  margin: float = ONSET_MARGIN) -> dict:
    """Run the fleet divergence reductions on device and read the
    result back in ONE ``jax.device_get`` — the module's only
    readback, matching the fleet dispatch's one-readback contract.

    ``attributions`` is the stacked ``(N,)``-leading
    ``AttributionSummary`` off an observed fleet; ``timelines`` the
    stacked ``TimelineSummary`` (or None — onsets are then absent).
    Returns a dict of host numpy arrays (see :func:`_device_reduce`).
    """
    reduced = jax.jit(
        _device_reduce, static_argnums=(2, 3, 4)
    )(attributions, timelines, int(control), tuple(band),
      float(margin))
    return jax.device_get(reduced)


def to_doc(compiled, attributions, timelines=None, *, label: str = "",
           control: int = 0, severity=None, seeds=None,
           window_s: Optional[float] = None, top_hops: int = 5,
           band: Tuple[float, float, float] = BAND,
           margin: float = ONSET_MARGIN) -> dict:
    """The ``isotope-fleet-blame/v1`` artifact document.

    ``severity`` attaches the fleet's (N,) ranking statistic
    (``EnsembleSummary.severity()``) so the report orders members by
    the same channel the chaos-fleet postmortem uses; without it,
    members rank by their positive blame excess vs the control.
    ``seeds`` stamps each member's RNG identity; ``window_s`` converts
    onset window indices to sim seconds."""
    host = explain_fleet(
        attributions, timelines, control=control, band=band,
        margin=margin,
    )
    share = np.asarray(host["share"], np.float64)        # (N, H)
    delta = np.asarray(host["delta_per_request_s"], np.float64)
    per_req = np.asarray(host["per_request_s"], np.float64)
    blame = np.asarray(host["blame_s"], np.float64)
    errs = np.asarray(host["error_count"], np.float64)
    n_mem, n_hops = share.shape
    hs = np.asarray(compiled.hop_service)
    names = compiled.services.names
    excess = np.clip(delta, 0.0, None).sum(axis=1)       # (N,)
    sev = (
        np.asarray(severity, np.float64)
        if severity is not None else excess
    )
    order = np.argsort(-sev)

    def hop_row(k: int, h: int) -> dict:
        row = {
            "hop": int(h),
            "service": names[int(hs[h])],
            "share": float(share[k, h]),
            "blame_s": float(blame[k, h]),
            "per_request_s": float(per_req[k, h]),
            "delta_vs_control_s": float(delta[k, h]),
            "errors": float(errs[k, h]),
        }
        if timelines is not None:
            onset = _member_onset(host, k, int(hs[h]))
            if onset is not None:
                row["onset"] = _onset_entry(onset, window_s)
        return row

    members = []
    for k in range(n_mem):
        top = np.argsort(-share[k])[: max(int(top_hops), 1)]
        top = [int(h) for h in top if share[k, h] > 0]
        entry = {
            "member": int(k),
            "seed": (
                int(seeds[k]) if seeds is not None else None
            ),
            "control": bool(k == control),
            "severity": float(sev[k]),
            "blame_excess_vs_control_s": float(excess[k]),
            "mean_latency_gap_s": float(
                host["mean_latency_gap_s"][k]
            ),
            "top_hops": [hop_row(k, h) for h in top],
            # the "why" ranking: hops by their contribution to the
            # member's latency gap over the control member
            "gap_ranking": [
                hop_row(k, int(h))
                for h in np.argsort(-delta[k])[:max(int(top_hops), 1)]
                if delta[k, int(h)] > 0
            ],
        }
        if timelines is not None:
            onset = _member_onset(host, k)
            entry["onset"] = (
                _onset_entry(onset, window_s, names)
                if onset is not None else None
            )
        members.append(entry)

    # bands only for hops that surface in any member's table — O(top
    # * N), never O(H), so svc100k artifacts stay bounded
    surfaced = sorted({
        h["hop"]
        for m in members
        for h in (m["top_hops"] + m["gap_ranking"])
    })
    sb = np.asarray(host["share_band"], np.float64)      # (3, H)
    return {
        "schema": DOC_SCHEMA,
        "label": label,
        "members": int(n_mem),
        "control_member": int(control),
        "band": [float(b) for b in band],
        "onset_margin": float(margin),
        "window_s": (
            float(window_s) if window_s is not None else None
        ),
        "ranking": [int(k) for k in order],
        "hop_bands": [
            {
                "hop": int(h),
                "service": names[int(hs[h])],
                "share_lo": float(sb[0, h]),
                "share_mid": float(sb[1, h]),
                "share_hi": float(sb[2, h]),
            }
            for h in surfaced
        ],
        "member_blame": members,
    }


def _member_onset(host: dict, k: int, service: Optional[int] = None
                  ) -> Optional[dict]:
    """Member k's earliest band departure — over every service (the
    member narrative) or pinned to one service (a hop row).  Onset
    values are window indices, -1 = the member never left its band;
    ties between channels keep the ONSET_CHANNELS priority order."""
    best = None
    for name in ONSET_CHANNELS:
        key = f"onset_{name}"
        if key not in host:
            continue
        onset = np.asarray(host[key])                    # (N, S)
        depth = np.asarray(host[f"{key}_depth"])
        row = onset[k]
        svcs = (
            [int(service)] if service is not None
            else list(range(row.shape[0]))
        )
        hits = [(int(row[s]), int(s)) for s in svcs if row[s] >= 0]
        if not hits:
            continue
        w, s = min(hits)
        if best is None or w < best["window"]:
            best = {
                "window": w,
                "service_id": s,
                "channel": name,
                "depth": float(depth[k, s]),
            }
    return best


def _onset_entry(onset: dict, window_s: Optional[float],
                 names: Optional[Sequence[str]] = None) -> dict:
    out = dict(onset)
    if window_s is not None:
        out["time_s"] = onset["window"] * float(window_s)
    if names is not None:
        out["service"] = names[onset["service_id"]]
    return out


def worst_members(doc: dict, top: int = 3) -> list:
    """The ``top`` most-severe member entries of a fleet-blame doc."""
    by_id = {m["member"]: m for m in doc["member_blame"]}
    return [
        by_id[k]
        for k in doc["ranking"][: max(int(top), 1)]
        if k in by_id and not by_id[k]["control"]
    ] or [by_id[k] for k in doc["ranking"][: max(int(top), 1)]]


def format_report(doc: dict, top: int = 3, hops: int = 3) -> str:
    """Human-readable "why" narrative (the ``explain`` subcommand)."""
    lines = [
        f"fleet blame over {doc['members']} members "
        f"(control member {doc['control_member']}; band "
        f"p{int(doc['band'][0] * 100)}-p{int(doc['band'][2] * 100)})"
    ]
    bands = {b["hop"]: b for b in doc["hop_bands"]}
    for m in worst_members(doc, top):
        head = f"member {m['member']}"
        if m.get("seed") is not None:
            head += f" (seed {m['seed']})"
        head += (
            f": +{m['blame_excess_vs_control_s'] * 1e3:.3f} ms/req "
            "blame excess vs control"
        )
        lines.append(head)
        for r in (m["gap_ranking"] or m["top_hops"])[:hops]:
            b = bands.get(r["hop"])
            line = (
                f"  {r['service']:<20} +{r['delta_vs_control_s'] * 1e6:8.1f}"
                f" us/req  share {r['share'] * 100:5.1f}%"
            )
            if b is not None:
                line += (
                    f"  (band {b['share_lo'] * 100:.1f}-"
                    f"{b['share_hi'] * 100:.1f}%)"
                )
            if r.get("errors"):
                line += f"  errors {r['errors']:.0f}"
            lines.append(line)
        onset = m.get("onset")
        if onset:
            where = onset.get("service", f"svc{onset['service_id']}")
            when = (
                f"{onset['time_s']:.2f}s"
                if "time_s" in onset
                else f"window {onset['window']}"
            )
            lines.append(
                f"  onset: {where} departs the member band at {when} "
                f"({onset['channel']} channel, "
                f"{onset['depth']:.1f} robust sigmas out)"
            )
    return "\n".join(lines)
