"""Fortio-compatible result formatting and summarization.

Produces the same artifacts the reference's collection pipeline scrapes and
flattens (perf/benchmark/runner/fortio.py):

- ``fortio_result`` / ``fortio_result_from_summary``: a Fortio-style
  result JSON (the schema ``fortio load -json`` writes and
  ``convert_data`` consumes: DurationHistogram with Min/Max/Avg/StdDev/
  Percentiles, RetCodes, Sizes, ActualQPS...) — from dense per-request
  SimResults resp. from the scan path's O(buckets) RunSummary;
- ``convert_data``: the reference's single-line flattening
  (fortio.py:38-75) — integer microsecond percentiles, errorPercent,
  Payload — reimplemented so downstream CSV/BigQuery consumers are
  drop-in;
- ``trim_window_summary`` / ``window_summary_from_summary``: the
  reference's Prometheus-join window semantics (fortio.py:116-121,
  175-186): skip the first 62s and last 30s, summarize at most 180s, and
  flag runs with >10% errors as discarded;
- ``write_csv``: fortio.py:215-232's key-list CSV writer.
"""
from __future__ import annotations

import dataclasses
from datetime import datetime, timezone
from typing import Dict, List, Optional

import numpy as np

from isotope_tpu.sim.config import LoadModel
from isotope_tpu.sim.engine import SimResults

# fortio.py:116-121
METRICS_START_SKIP_DURATION = 62
METRICS_END_SKIP_DURATION = 30
METRICS_SUMMARY_DURATION = 180
# fortio.py:175-177
MAX_ERROR_PERCENT = 10.0

# ints for the round percentiles: the reference's flattener builds keys
# with str(Percentile) (fortio.py:60-62), so 50 must print as "50" -> p50.
PERCENTILES = (50, 75, 90, 99, 99.9)

# fortio histogram resolution: runner.py:136-137 passes -r 0.001 (1ms).
HISTOGRAM_RESOLUTION_S = 0.001


def _percentile_list(lat: np.ndarray) -> List[dict]:
    qs = np.quantile(lat, [p / 100.0 for p in PERCENTILES]) if len(lat) else (
        np.zeros(len(PERCENTILES))
    )
    return [
        {"Percentile": p, "Value": float(v)} for p, v in zip(PERCENTILES, qs)
    ]


def _histogram_data(lat: np.ndarray) -> List[dict]:
    """Fortio-style bucket records at 1ms resolution (capped at 1000 rows)."""
    if len(lat) == 0:
        return []
    res = HISTOGRAM_RESOLUTION_S
    hi = min(int(np.ceil(lat.max() / res)), 1000)
    edges = np.arange(hi + 1) * res
    counts, _ = np.histogram(np.minimum(lat, edges[-1] - 1e-12), bins=edges)
    total = len(lat)
    data = []
    for i, c in enumerate(counts):
        if c == 0:
            continue
        data.append(
            {
                "Start": float(edges[i]),
                "End": float(edges[i + 1]),
                "Percent": float(100.0 * c / total),
                "Count": int(c),
            }
        )
    return data


def _fortio_doc(
    load: LoadModel,
    labels: str,
    start_time: Optional[datetime],
    response_size_bytes: float,
    *,
    n: int,
    errors: int,
    actual_duration_s: float,
    lat_min: float,
    lat_max: float,
    lat_sum: float,
    lat_avg: float,
    lat_std: float,
    data: List[dict],
    percentiles: List[dict],
) -> dict:
    """The shared Fortio result-JSON scaffolding for both derivations."""
    start_time = start_time or datetime.now(timezone.utc)
    ret_codes: Dict[str, int] = {}
    if n - errors:
        ret_codes["200"] = n - errors
    if errors:
        ret_codes["500"] = errors
    return {
        "RunType": "HTTP",
        "Labels": labels,
        "StartTime": start_time.isoformat(),
        "RequestedQPS": "max" if load.qps is None else str(load.qps),
        "RequestedDuration": f"{load.duration_s}s",
        "ActualQPS": (n / actual_duration_s) if actual_duration_s > 0 else 0.0,
        "ActualDuration": int(actual_duration_s * 1e9),  # nanoseconds
        "NumThreads": load.connections,
        "DurationHistogram": {
            "Count": n,
            "Min": lat_min if n else 0.0,
            "Max": lat_max if n else 0.0,
            "Sum": lat_sum,
            "Avg": lat_avg if n else 0.0,
            "StdDev": lat_std if n else 0.0,
            "Data": data,
            "Percentiles": percentiles,
        },
        "RetCodes": ret_codes,
        # the payload the client receives: the entrypoint's responseSize
        "Sizes": {"Count": n, "Avg": float(response_size_bytes)},
    }


def fortio_result(
    res: SimResults,
    load: LoadModel,
    labels: str = "",
    start_time: Optional[datetime] = None,
    response_size_bytes: float = 0.0,
) -> dict:
    """Render a dense per-request run as a Fortio result JSON document."""
    lat = np.asarray(res.client_latency, np.float64)
    err = np.asarray(res.client_error)
    n = len(lat)
    end = np.asarray(res.client_end, np.float64)
    return _fortio_doc(
        load, labels, start_time, response_size_bytes,
        n=n,
        errors=int(err.sum()),
        actual_duration_s=float(end.max()) if n else 0.0,
        lat_min=float(lat.min()) if n else 0.0,
        lat_max=float(lat.max()) if n else 0.0,
        lat_sum=float(lat.sum()),
        lat_avg=float(lat.mean()) if n else 0.0,
        lat_std=float(lat.std()) if n else 0.0,
        data=_histogram_data(lat),
        percentiles=_percentile_list(lat),
    )


def fortio_result_from_summary(
    summary,
    load: LoadModel,
    labels: str = "",
    start_time: Optional[datetime] = None,
    response_size_bytes: float = 0.0,
) -> dict:
    """Render a :class:`~isotope_tpu.sim.summary.RunSummary` as a Fortio
    result JSON — the scan-path counterpart of :func:`fortio_result`.

    Exact where Fortio is exact (Count, Min, Max, Sum, Avg, StdDev,
    RetCodes, ActualQPS); Percentiles and the bucket rows come from the
    fine log-spaced device histogram (~0.6% relative bucket width), the
    same reduction Fortio itself applies at 1ms resolution
    (runner.py:136-137).
    """
    from isotope_tpu.metrics.histogram import (
        bucket_centers,
        quantile_from_histogram,
    )

    n = int(summary.count)
    hist = np.asarray(summary.latency_hist, np.float64)
    qs = quantile_from_histogram(hist, [p / 100.0 for p in PERCENTILES])
    percentiles = [
        {"Percentile": p, "Value": float(v)} for p, v in zip(PERCENTILES, qs)
    ]

    # re-bucket the fine histogram into Fortio's 1ms rows
    data: List[dict] = []
    if n:
        res_s = HISTOGRAM_RESOLUTION_S
        lat_max = float(summary.latency_max)
        hi = max(min(int(np.ceil(lat_max / res_s)), 1000), 1)
        bins = np.minimum(
            (bucket_centers() / res_s).astype(np.int64), hi - 1
        )
        counts = np.zeros(hi)
        np.add.at(counts, bins, hist)
        for i, c in enumerate(counts):
            if c == 0:
                continue
            data.append(
                {
                    "Start": float(i * res_s),
                    "End": float((i + 1) * res_s),
                    "Percent": float(100.0 * c / n),
                    "Count": int(round(c)),
                }
            )

    return _fortio_doc(
        load, labels, start_time, response_size_bytes,
        n=n,
        errors=int(summary.error_count),
        actual_duration_s=float(summary.end_max) if n else 0.0,
        lat_min=float(summary.latency_min),
        lat_max=float(summary.latency_max),
        lat_sum=float(summary.latency_sum),
        lat_avg=summary.mean_latency_s,
        lat_std=summary.stddev_latency_s,
        data=data,
        percentiles=percentiles,
    )


def convert_data(data: dict) -> Optional[dict]:
    """Flatten a Fortio result JSON exactly like fortio.py:38-75."""
    obj: dict = {}
    for key in (
        "Labels",
        "StartTime",
        "RequestedQPS",
        "ActualQPS",
        "NumThreads",
        "RunType",
        "ActualDuration",
    ):
        if key == "RequestedQPS" and data[key] == "max":
            obj[key] = 99999999
            continue
        if key in ("RequestedQPS", "ActualQPS"):
            obj[key] = int(round(float(data[key])))
            continue
        if key == "ActualDuration":
            obj[key] = int(data[key] / 10 ** 9)
            continue
        obj[key] = data[key]

    h = data["DurationHistogram"]
    obj["min"] = int(h["Min"] * 10 ** 6)
    obj["max"] = int(h["Max"] * 10 ** 6)
    for pp in h["Percentiles"]:
        obj["p" + str(pp["Percentile"]).replace(".", "")] = int(
            pp["Value"] * 10 ** 6
        )
    success = int(data["RetCodes"].get("200", 0))
    if data["RunType"] == "HTTP":
        count = int(data["Sizes"]["Count"])
        obj["errorPercent"] = 100 * (count - success) / count if count else 0.0
        obj["Payload"] = int(data["Sizes"]["Avg"])
    return obj


def trim_window_bounds(
    num_requests: int, offered_qps: float
) -> "tuple[float, float]":
    """The ``[lo, hi)`` client-start interval of the collector's trim
    window, placed from the run's expected duration (fortio.py:116-121)."""
    d_exp = num_requests / max(float(offered_qps), 1e-12)
    min_dur = METRICS_START_SKIP_DURATION + METRICS_END_SKIP_DURATION
    w_len = min(max(d_exp - min_dur, 0.0), METRICS_SUMMARY_DURATION)
    lo = float(METRICS_START_SKIP_DURATION)
    return lo, lo + w_len


@dataclasses.dataclass(frozen=True)
class WindowSummary:
    """Steady-state window statistics (the sim's stand-in for the
    Prometheus CPU/mem join of fortio.py:178-195)."""

    start_s: float
    duration_s: float
    count: int
    qps: float
    error_percent: float
    discarded: bool           # >10% errors or run shorter than 92s
    discard_reason: str
    percentiles_us: Dict[str, int]
    # simulated per-service CPU (cores): utilization x replicas — what the
    # reference measures off cadvisor (prom.py:116-120)
    cpu_cores: Dict[str, float]


def _window_summary(
    *,
    count: int,
    error_count: float,
    actual_duration: float,
    w_start: float,
    w_len: float,
    wcount: int,
    werr: float,
    percentiles: Dict[str, int],
    utilization: np.ndarray,
    service_names,
    replicas,
) -> WindowSummary:
    """Shared discard logic + shaping for both window derivations."""
    min_duration = METRICS_START_SKIP_DURATION + METRICS_END_SKIP_DURATION
    error_percent = 100.0 * float(error_count) / count if count else 0.0

    discarded, reason = False, ""
    if error_percent > MAX_ERROR_PERCENT:
        discarded, reason = True, f"{error_percent:.1f}% errors"
    elif actual_duration < min_duration:
        discarded, reason = (
            True,
            f"duration={actual_duration:.0f}s is less than minimum "
            f"{min_duration}s",
        )

    util = np.asarray(utilization, np.float64)
    reps = (
        np.asarray(replicas, np.float64)
        if replicas is not None
        else np.ones_like(util)
    )
    cpu = {
        name: float(util[i] * reps[i])
        for i, name in enumerate(service_names)
    }
    return WindowSummary(
        start_s=w_start,
        duration_s=w_len,
        count=wcount,
        qps=(wcount / w_len) if w_len > 0 else 0.0,
        error_percent=(
            100.0 * float(werr) / wcount if wcount else error_percent
        ),
        discarded=discarded,
        discard_reason=reason,
        percentiles_us=percentiles,
        cpu_cores=cpu,
    )


def trim_window_summary(
    res: SimResults,
    load: LoadModel,
    service_names=(),
    replicas=None,
) -> WindowSummary:
    lat = np.asarray(res.client_latency, np.float64)
    starts = np.asarray(res.client_start, np.float64)
    err = np.asarray(res.client_error)
    actual_duration = (
        float(np.asarray(res.client_end).max()) if len(lat) else 0.0
    )

    w_start = float(METRICS_START_SKIP_DURATION)
    min_duration = METRICS_START_SKIP_DURATION + METRICS_END_SKIP_DURATION
    w_len = min(
        max(actual_duration - min_duration, 0.0), METRICS_SUMMARY_DURATION
    )
    mask = (starts >= w_start) & (starts < w_start + w_len)
    wlat = lat[mask]
    wcount = int(mask.sum())
    percentiles = {}
    if wcount:
        qs = np.quantile(wlat, [p / 100.0 for p in PERCENTILES])
        percentiles = {
            "p" + str(p).replace(".", ""): int(v * 1e6)
            for p, v in zip(PERCENTILES, qs)
        }
    return _window_summary(
        count=len(lat),
        error_count=float(err.sum()),
        actual_duration=actual_duration,
        w_start=w_start,
        w_len=w_len,
        wcount=wcount,
        werr=float(err[mask].sum()),
        percentiles=percentiles,
        utilization=res.utilization,
        service_names=service_names,
        replicas=replicas,
    )


def window_summary_from_summary(
    summary,
    service_names=(),
    replicas=None,
) -> WindowSummary:
    """Trim-window statistics from a RunSummary's on-device ``win_*``
    accumulators (the scan-path counterpart of
    :func:`trim_window_summary`).

    The reported window is the one the device actually accumulated
    (``summary.win_lo``/``win_hi``, placed from the expected duration) —
    never a recomputed one, so windowed QPS stays consistent with
    ``win_count``.  Produced with ``trim=False`` the window covers the
    whole run and the length falls back to the actual duration.
    """
    from isotope_tpu.metrics.histogram import quantile_from_histogram

    count = int(summary.count)
    actual_duration = float(summary.end_max) if count else 0.0
    win_lo = float(summary.win_lo)
    win_hi = float(summary.win_hi)
    if np.isfinite(win_hi):
        w_start, w_len = win_lo, win_hi - win_lo
    else:  # trim was off: the "window" is the whole run
        w_start, w_len = 0.0, actual_duration
    wcount = int(summary.win_count)
    percentiles = {}
    if wcount:
        qs = quantile_from_histogram(
            np.asarray(summary.win_latency_hist),
            [p / 100.0 for p in PERCENTILES],
        )
        percentiles = {
            "p" + str(p).replace(".", ""): int(v * 1e6)
            for p, v in zip(PERCENTILES, qs)
        }
    return _window_summary(
        count=count,
        error_count=float(summary.error_count),
        actual_duration=actual_duration,
        w_start=w_start,
        w_len=w_len,
        wcount=wcount,
        werr=float(summary.win_error_count),
        percentiles=percentiles,
        utilization=summary.utilization,
        service_names=service_names,
        replicas=replicas,
    )


DEFAULT_CSV_KEYS = (
    "Labels,StartTime,RequestedQPS,ActualQPS,NumThreads,min,max,"
    "p50,p75,p90,p99,p999,errorPercent"
)


def write_csv(keys: str, data: List[dict], path) -> None:
    """fortio.py:215-232: header then one row per record, '-' for gaps."""
    lst = keys.split(",")
    with open(path, "w") as out:
        out.write(keys + "\n")
        for gd in data:
            out.write(",".join(str(gd.get(k, "-")) for k in lst) + "\n")
