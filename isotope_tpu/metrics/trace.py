"""Distributed-trace export from simulated runs.

The reference's mock service wraps every request handler and script
command in OpenTelemetry spans exported to Jaeger
(isotope/service/main.go:76-109: JAEGERADDR/JAEGERPORT/NOTRACING config;
srv/executable.go:49-74: per-command spans with error recording), with
B3 header forwarding stitching the per-pod spans into one distributed
trace per client request (srv/header.go:21-48).

The simulator holds the same span data densely — per-hop start times,
server-side durations, statuses, and the static parent pointers of the
unrolled call tree — so a trace is a formatting pass over SimResults:

- ``chrome_trace``: the Chrome/Perfetto trace-event format (one
  process per request, one thread per call depth, "X" complete events);
- ``jaeger_trace``: Jaeger's JSON wire shape (one traceID per request,
  CHILD_OF references along hop parents) as its UI's upload accepts.

Like the reference's samplers, traces are for *sampled* requests — the
product load path reduces to histograms; tracing re-runs a small dense
batch (``simulate --trace``).
"""
from __future__ import annotations

import json
from typing import Dict, List, Optional

import numpy as np

from isotope_tpu.compiler.program import CompiledGraph
from isotope_tpu.sim.engine import SimResults


def _as_host(res: SimResults):
    return (
        np.asarray(res.hop_sent),
        np.asarray(res.hop_start, np.float64),
        np.asarray(res.hop_latency, np.float64),
        np.asarray(res.hop_error),
    )


def chrome_trace(
    compiled: CompiledGraph,
    res: SimResults,
    max_requests: Optional[int] = None,
    annotations: Optional[List[dict]] = None,
) -> dict:
    """Render sampled requests as Chrome trace-event JSON.

    Layout: pid = request index, tid = call depth, one complete ("X")
    event per executed hop; timestamps in microseconds.
    ``annotations`` (one dict per request, e.g. the tail-exemplar
    ``tail_rank``/``tail_cut_s`` of metrics/attribution.py) merge into
    every event's ``args``.
    """
    sent, start, lat, err = _as_host(res)
    names = compiled.services.names
    depth = compiled.hop_depth
    parent = compiled.hop_parent
    n = sent.shape[0] if max_requests is None else min(
        max_requests, sent.shape[0]
    )
    events: List[dict] = []
    for r in range(n):
        extra = annotations[r] if annotations else {}
        for h in np.nonzero(sent[r])[0]:
            events.append(
                {
                    "name": names[compiled.hop_service[h]],
                    "cat": "hop",
                    "ph": "X",
                    "ts": start[r, h] * 1e6,
                    "dur": lat[r, h] * 1e6,
                    "pid": int(r),
                    "tid": int(depth[h]),
                    "args": {
                        "hop": int(h),
                        "parent_hop": int(parent[h]),
                        "status": 500 if err[r, h] else 200,
                        **extra,
                    },
                }
            )
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {"source": "isotope-tpu simulate --trace"},
    }


def _jaeger_tag(key: str, value):
    if isinstance(value, bool):
        return {"key": key, "type": "bool", "value": value}
    if isinstance(value, int):
        return {"key": key, "type": "int64", "value": value}
    if isinstance(value, float):
        return {"key": key, "type": "float64", "value": value}
    return {"key": key, "type": "string", "value": str(value)}


def jaeger_trace(
    compiled: CompiledGraph,
    res: SimResults,
    max_requests: Optional[int] = None,
    annotations: Optional[List[dict]] = None,
) -> dict:
    """Render sampled requests in Jaeger's JSON shape (one trace per
    request; spans reference their caller hop with CHILD_OF, the
    simulated B3 propagation of srv/header.go:21-48).  ``annotations``
    (one dict per request) become extra tags on every span."""
    sent, start, lat, err = _as_host(res)
    names = compiled.services.names
    parent = compiled.hop_parent
    H = compiled.num_hops
    data = []
    n = sent.shape[0] if max_requests is None else min(
        max_requests, sent.shape[0]
    )
    for r in range(n):
        trace_id = f"{r + 1:032x}"
        spans = []
        procs: Dict[str, dict] = {}
        extra_tags = [
            _jaeger_tag(k, v)
            for k, v in (annotations[r] if annotations else {}).items()
        ]
        for h in np.nonzero(sent[r])[0]:
            svc = names[compiled.hop_service[h]]
            pkey = f"p{compiled.hop_service[h]}"
            procs[pkey] = {"serviceName": svc}
            span = {
                "traceID": trace_id,
                "spanID": f"{r * H + int(h) + 1:016x}",
                "operationName": "execute-request-command",
                "references": [],
                "startTime": int(start[r, h] * 1e6),
                "duration": int(lat[r, h] * 1e6),
                "processID": pkey,
                "tags": [
                    {
                        "key": "http.status_code",
                        "type": "int64",
                        "value": 500 if err[r, h] else 200,
                    },
                    {"key": "hop", "type": "int64", "value": int(h)},
                ] + extra_tags,
            }
            if parent[h] >= 0 and sent[r, parent[h]]:
                span["references"].append(
                    {
                        "refType": "CHILD_OF",
                        "traceID": trace_id,
                        "spanID": f"{r * H + int(parent[h]) + 1:016x}",
                    }
                )
            spans.append(span)
        data.append(
            {"traceID": trace_id, "spans": spans, "processes": procs}
        )
    return {"data": data}


def exemplar_annotations(attr) -> List[dict]:
    """Per-request tail annotations for an exemplar batch: the rank
    among the mined slowest requests (0 = slowest) plus the tail cut
    the run used, carried in Chrome ``args`` / Jaeger ``tags``."""
    ex = attr.exemplars
    if ex is None:
        raise ValueError(
            "attribution summary carries no exemplars (run with "
            "attribution_top_k > 0)"
        )
    cut = float(np.asarray(attr.tail_cut))
    k = int(np.asarray(ex.latency).shape[0])
    out = []
    for r in range(k):
        ann = {"tail_rank": r}
        if np.isfinite(cut):
            ann["tail_cut_s"] = cut
        out.append(ann)
    return out


def write_trace(
    path: str,
    compiled: CompiledGraph,
    res: Optional[SimResults] = None,
    fmt: str = "chrome",
    max_requests: Optional[int] = None,
    exemplars=None,
) -> int:
    """Write a trace file; returns the number of requests traced.

    ``exemplars`` accepts an
    :class:`~isotope_tpu.metrics.attribution.AttributionSummary` whose
    mined top-K batch is traced directly — no dense re-run — with
    ``tail_rank`` / ``tail_cut_s`` annotations on every span.
    """
    annotations = None
    if exemplars is not None:
        from isotope_tpu.metrics import attribution

        res = attribution.exemplar_results(exemplars)
        annotations = exemplar_annotations(exemplars)
    if res is None:
        raise ValueError("write_trace needs res or exemplars")
    if fmt == "chrome":
        doc = chrome_trace(compiled, res, max_requests, annotations)
        count = len({e["pid"] for e in doc["traceEvents"]})
    elif fmt == "jaeger":
        doc = jaeger_trace(compiled, res, max_requests, annotations)
        count = len(doc["data"])
    else:
        raise ValueError(f"unknown trace format: {fmt!r}")
    with open(path, "w") as f:
        json.dump(doc, f, indent=1)
    return count
