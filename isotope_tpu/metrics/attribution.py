"""On-device tail-latency attribution: critical-path blame.

The reference answers "the mesh got slower" with Fortio histograms;
answering "*which service* made p99 worse" requires stitching Jaeger
traces by hand.  The simulator holds every hop of every request on
device — this module decomposes each request's client latency along the
critical path of its unrolled call tree *inside* the existing
``lax.scan`` block reduction (and the sharded ``psum`` merge), so the
per-request tensors are reduced to O(H) blame vectors + O(S * buckets)
blame histograms before they ever leave the device.  Nothing O(N * H)
reaches the host.

Decomposition (exact, telescoping):

- the client edge contributes its wire round trip (a refused connection
  under chaos contributes exactly the refused-connect cost);
- a hop on the critical path contributes its queueing **wait** and its
  **self** time (CPU draw + sleeps + any step time the concurrent calls
  did not cover);
- at each executed call-bearing step, the *winning* call (the per-step
  ``max`` the engine's WaitGroup join takes) passes the path to its
  attempts: every attempt that actually ran is serially on the path —
  an uncapped attempt charges its request+response **wire** time to the
  caller->callee edge and recurses into the callee, a timeout-capped
  attempt charges the full **timeout** to the edge and stops (the
  subtree past the timeout is off the caller's clock).

Summing every charge reproduces the client latency exactly (up to f32
accumulation order); the per-request difference is accumulated as
``residual`` — nonzero only for ungraceful-kill resets, whose
client-observed latency is a connection reset, not the tree walk.

Tail attribution re-weights every accumulator by ``latency >= cut``
(the streaming-threshold mode: the cut is a p99/p99.9 estimate from a
pilot histogram), so the report can show p99 blame shares next to mean
shares.  Exemplar mining keeps the top-K slowest requests' per-hop
vectors (O(K * H)) in the scan carry; they feed the Chrome/Jaeger trace
exporters (metrics/trace.py) so the worst requests come back as
inspectable spans.
"""
from __future__ import annotations

import dataclasses
from typing import List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from isotope_tpu.compiler.program import CompiledGraph, hop_wire_times

# Coarse log-spaced blame buckets: per-service blame histograms are
# (S, NUM_BLAME_BUCKETS), so svc100k stays ~25 MB where the fine
# 2048-bucket layout of metrics/histogram.py would be ~800 MB.
NUM_BLAME_BUCKETS = 64
_BLO, _BHI = 1e-6, 10.0  # seconds
_B_LOG_LO = float(np.log(_BLO))
_B_INV_LOG_R = float((NUM_BLAME_BUCKETS - 2) / np.log(_BHI / _BLO))

BLAME_EDGES = np.concatenate(
    [[0.0], np.geomspace(_BLO, _BHI, NUM_BLAME_BUCKETS - 1), [np.inf]]
)


def blame_bucket_index(v: jax.Array) -> jax.Array:
    """Bucket index per blame value (same arithmetic-index trick as
    metrics/histogram.bucket_index, at the coarse width)."""
    t = (jnp.log(v) - _B_LOG_LO) * _B_INV_LOG_R
    t = jnp.clip(t, -1.0, NUM_BLAME_BUCKETS - 2)
    idx = jnp.floor(t).astype(jnp.int32) + 1
    return jnp.where(jnp.isnan(t), NUM_BLAME_BUCKETS - 1, idx)


def blame_bucket_centers() -> np.ndarray:
    centers = np.empty(NUM_BLAME_BUCKETS)
    centers[0] = BLAME_EDGES[1] / 2
    centers[1:-1] = np.sqrt(BLAME_EDGES[1:-2] * BLAME_EDGES[2:-1])
    centers[-1] = BLAME_EDGES[-2]
    return centers


class ExemplarBatch(NamedTuple):
    """Top-K slowest requests' per-hop vectors — O(K * H), the only
    per-request data attribution ever materializes.  Rows are sorted
    slowest-first (``tail_rank`` = row index)."""

    latency: jax.Array     # (K,)
    start: jax.Array       # (K,)
    error: jax.Array       # (K,) bool
    hop_sent: jax.Array    # (K, H) bool
    hop_error: jax.Array   # (K, H) bool
    hop_latency: jax.Array  # (K, H)
    hop_start: jax.Array   # (K, H)


def empty_exemplars(k: int, num_hops: int) -> "ExemplarBatch":
    """The scan-carry seed batch every attributed entry point starts
    from: latency = -inf so any real request displaces a seed row."""
    return ExemplarBatch(
        latency=jnp.full((k,), -jnp.inf),
        start=jnp.zeros((k,)),
        error=jnp.zeros((k,), bool),
        hop_sent=jnp.zeros((k, num_hops), bool),
        hop_error=jnp.zeros((k, num_hops), bool),
        hop_latency=jnp.zeros((k, num_hops)),
        hop_start=jnp.zeros((k, num_hops)),
    )


class AttributionSummary(NamedTuple):
    """Device-reduced critical-path blame for one run.

    Every array is O(H), O(S * blame buckets), or O(K * H); block
    summaries sum under ``lax.scan`` and shards merge with ``psum``
    exactly like :class:`~isotope_tpu.sim.summary.RunSummary`.

    Blame vectors are indexed by HOP (BFS order); per-service and
    per-edge tables are host-side groupbys over the static hop->service
    map (:func:`service_blame` / :func:`edge_blame`).  ``*_tail``
    fields restrict to requests with client latency >= ``tail_cut``
    (identically zero when the run had no tail cut).
    """

    count: jax.Array          # scalar — requests attributed
    tail_count: jax.Array     # scalar — requests past the tail cut
    tail_cut: jax.Array       # scalar — the cut used (+inf = mean only)
    residual: jax.Array       # scalar — sum(client latency - attributed)
    residual_abs: jax.Array   # scalar — sum |client latency - attributed|
    crit_count: jax.Array     # (H,) times the hop was on the crit path
    wait_blame: jax.Array     # (H,) queueing wait on the crit path
    self_blame: jax.Array     # (H,) CPU + sleeps + uncovered step time
    net_blame: jax.Array      # (H,) wire time of the edge INTO the hop
    timeout_blame: jax.Array  # (H,) timeout charges on the edge into it
    error_count: jax.Array    # (H,) executed hops that returned 500
    tail_crit_count: jax.Array
    tail_wait_blame: jax.Array
    tail_self_blame: jax.Array
    tail_net_blame: jax.Array
    tail_timeout_blame: jax.Array
    hist: jax.Array           # (S, NUM_BLAME_BUCKETS) per-service blame
    tail_hist: jax.Array      # (S, NUM_BLAME_BUCKETS)
    exemplars: Optional[ExemplarBatch]

    @property
    def total_blame_s(self) -> float:
        return float(
            np.asarray(self.wait_blame).sum()
            + np.asarray(self.self_blame).sum()
            + np.asarray(self.net_blame).sum()
            + np.asarray(self.timeout_blame).sum()
        )

    @property
    def tail_total_blame_s(self) -> float:
        return float(
            np.asarray(self.tail_wait_blame).sum()
            + np.asarray(self.tail_self_blame).sum()
            + np.asarray(self.tail_net_blame).sum()
            + np.asarray(self.tail_timeout_blame).sum()
        )


# -- static tables ----------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class _LevelTables:
    """Static index tables for one depth level's blame sweep."""

    offset: int                 # hop slice of this level in BFS order
    size: int
    child_offset: int           # hop slice of the children (level d+1)
    child_size: int
    parent_local: Optional[jax.Array]   # (C,) i32
    call_of_child: Optional[jax.Array]  # (C,) i32 in [0, K)
    slot_of_call: Optional[jax.Array]   # (K,) i32 in [0, n_slots)
    n_slots: int
    num_calls: int
    slot_base: Optional[jax.Array]      # (n_slots,) sleep floor per step
    child_rtt: Optional[jax.Array]      # (C,) request+response wire time
    child_timeout: Optional[jax.Array]  # (C,) +inf when none
    has_timeout: bool
    svc: np.ndarray             # (L,) static service id per hop


@dataclasses.dataclass(frozen=True)
class AttrTables:
    """Everything :func:`attribute_block` needs, built once per
    Simulator from the compiled graph + network model (host-side)."""

    levels: Tuple[_LevelTables, ...]
    num_hops: int
    num_services: int
    root_net: float        # client->entry wire round trip
    refused_net: float     # refused-connect cost (down entry)
    svc_flat: Tuple[np.ndarray, ...]  # per-level (L,) service ids


def build_tables(compiled: CompiledGraph, net) -> AttrTables:
    """Lower the compiled graph's call structure into blame-sweep index
    tables.  Only uses the assembled program's *static* shape — the
    sweep itself reads nothing but the engine's (N, H) outputs, so it
    is oblivious to which executor (unrolled / scan-bucketed / sparse)
    produced them."""
    net_out, net_back = hop_wire_times(compiled, net)
    rtt = net_out + net_back
    levels: List[_LevelTables] = []
    for d, lvl in enumerate(compiled.levels):
        svc = np.asarray(lvl.service, np.int32)
        if lvl.num_children == 0:
            levels.append(
                _LevelTables(
                    offset=int(lvl.hop_ids[0]), size=lvl.num_hops,
                    child_offset=0, child_size=0,
                    parent_local=None, call_of_child=None,
                    slot_of_call=None, n_slots=0, num_calls=0,
                    slot_base=None, child_rtt=None, child_timeout=None,
                    has_timeout=False, svc=svc,
                )
            )
            continue
        C = lvl.num_children
        K = lvl.num_calls
        parent_local = (lvl.child_seg // compiled.max_steps).astype(
            np.int32
        )
        # child -> owning call site (every child is exactly one call's
        # attempt; attempt order within a call is serial)
        call_of_child = np.zeros(C, np.int32)
        for a in range(lvl.max_attempts):
            valid = lvl.att_valid[a]
            call_of_child[lvl.att_child[a][valid]] = np.arange(
                K, dtype=np.int32
            )[valid]
        # call-bearing steps only — the sparse-level fix applied
        # globally: no (L x Pmax) dense step grid is ever materialized
        slot_segs = np.unique(lvl.call_seg)
        slot_of_call = np.searchsorted(slot_segs, lvl.call_seg).astype(
            np.int32
        )
        slot_base = lvl.step_base[
            slot_segs // compiled.max_steps,
            slot_segs % compiled.max_steps,
        ].astype(np.float32)
        timeout = lvl.call_timeout[call_of_child].astype(np.float32)
        nxt = compiled.levels[d + 1]
        levels.append(
            _LevelTables(
                offset=int(lvl.hop_ids[0]), size=lvl.num_hops,
                child_offset=int(nxt.hop_ids[0]), child_size=C,
                parent_local=jnp.asarray(parent_local),
                call_of_child=jnp.asarray(call_of_child),
                slot_of_call=jnp.asarray(slot_of_call),
                n_slots=len(slot_segs), num_calls=K,
                slot_base=jnp.asarray(slot_base),
                child_rtt=jnp.asarray(rtt[lvl.child_ids], jnp.float32),
                child_timeout=jnp.asarray(timeout),
                has_timeout=bool(np.isfinite(timeout).any()),
                svc=svc,
            )
        )
    return AttrTables(
        levels=tuple(levels),
        num_hops=compiled.num_hops,
        num_services=compiled.num_services,
        root_net=float(rtt[0]),
        refused_net=float(2.0 * net.entry_one_way(0.0)),
        svc_flat=tuple(lvl.svc for lvl in levels),
    )


# -- the on-device blame sweep ----------------------------------------------


def _winner_charges(lvl: _LevelTables, w, sent_c, lat_c):
    """Per-child critical-path charges at one level.

    ``w`` is the level's (N, L) crit weights; returns
    ``(D, on_crit, att_dur, capped)``: the per-parent charged duration
    and the per-child path weights/durations.
    """
    n = sent_c.shape[0]
    K, S = lvl.num_calls, lvl.n_slots
    # attempt duration exactly as the engine's call outcome: capped by
    # the call's timeout; an unsent / refused attempt costs 0
    raw = lvl.child_rtt + lat_c
    att_dur = sent_c * (
        jnp.minimum(raw, lvl.child_timeout) if lvl.has_timeout else raw
    )
    # serial attempts of one call sum; concurrent calls at one step join
    # via max — the winner is the engine's WaitGroup argmax (first max)
    dur_call = (
        jnp.zeros((n, K)).at[:, lvl.call_of_child].add(att_dur)
    )
    slot_max = (
        jnp.zeros((n, S)).at[:, lvl.slot_of_call].max(dur_call)
    )
    beats_sleep = slot_max >= lvl.slot_base          # (N, S)
    win_idx = (
        jnp.full((n, S), K, jnp.int32)
        .at[:, lvl.slot_of_call]
        .min(
            jnp.where(
                dur_call == slot_max[:, lvl.slot_of_call],
                jnp.arange(K, dtype=jnp.int32),
                K,
            )
        )
    )
    is_win = (
        jnp.arange(K, dtype=jnp.int32) == win_idx[:, lvl.slot_of_call]
    ) & beats_sleep[:, lvl.slot_of_call]             # (N, K)
    on_crit = (
        w[:, lvl.parent_local]
        * is_win[:, lvl.call_of_child]
        * sent_c
    )                                                # (N, C) f32
    capped = (raw > lvl.child_timeout) if lvl.has_timeout else None
    D = (
        jnp.zeros((n, lvl.size))
        .at[:, lvl.parent_local]
        .add(on_crit * att_dur)
    )
    return D, on_crit, att_dur, capped


def attribute_block(
    res,
    tables: AttrTables,
    *,
    tail_cut: Optional[jax.Array] = None,
    top_k: int = 0,
    ex_state: Optional[ExemplarBatch] = None,
    packed: bool = False,
) -> Tuple[AttributionSummary, Optional[ExemplarBatch]]:
    """Reduce one block's SimResults to an AttributionSummary
    (jit-friendly; called inside the engine's block scan).

    ``tail_cut`` arms the conditional-tail accumulators; ``top_k`` > 0
    maintains the exemplar state across blocks via ``ex_state`` (ride
    the scan carry — the stacked per-block summaries carry
    ``exemplars=None``).

    ``packed`` (SimParams.packed_carries) accumulates the COUNT-valued
    carries — request/tail counts, per-hop crit/error counters, and the
    blame-histogram censuses — as int32 instead of f32.  Crit weights
    are exact 0/1 products, so the packing is exact (and strictly more
    exact than f32 past 2^24 events) UP TO the int32 bound: a single
    run's per-counter total must stay under 2^31 events or the sum
    wraps, where f32 only lost precision — int64 would need the
    globally-disabled x64 mode, so longer soaks should run
    ``packed=False`` (see SimParams.packed_carries).  Every
    seconds-valued blame accumulator stays f32 — the <= 1 ULP pin
    forbids narrowing them.
    """
    lat_all = res.hop_latency
    wait_all = res.hop_wait
    if wait_all is None:
        raise ValueError(
            "attribution needs SimResults.hop_wait (produced by "
            "Simulator runs; synthetic SimResults must fill it)"
        )
    n = lat_all.shape[0]
    sent_f = res.hop_sent.astype(jnp.float32)
    tail_w = (
        (res.client_latency >= tail_cut).astype(jnp.float32)
        if tail_cut is not None
        else None
    )

    root_sent = sent_f[:, 0]
    net0 = jnp.where(
        res.hop_sent[:, 0], tables.root_net, tables.refused_net
    )
    per_req = net0
    w = root_sent[:, None]  # (N, 1) — level 0 crit weights

    count_dtype = jnp.int32 if packed else jnp.float32
    crit_l: List[jax.Array] = []
    wait_l: List[jax.Array] = []
    self_l: List[jax.Array] = []
    net_l: List[jax.Array] = [net0.sum()[None]]
    tmo_l: List[jax.Array] = [jnp.zeros(1)]
    t_crit_l: List[jax.Array] = []
    t_wait_l: List[jax.Array] = []
    t_self_l: List[jax.Array] = []
    t_net_l: List[jax.Array] = [
        (net0 * tail_w).sum()[None] if tail_w is not None
        else jnp.zeros(1)
    ]
    t_tmo_l: List[jax.Array] = [jnp.zeros(1)]
    hist = jnp.zeros(
        tables.num_services * NUM_BLAME_BUCKETS, count_dtype
    )
    t_hist = jnp.zeros(
        tables.num_services * NUM_BLAME_BUCKETS, count_dtype
    )

    for li, lvl in enumerate(tables.levels):
        sl = slice(lvl.offset, lvl.offset + lvl.size)
        lat = lat_all[:, sl]
        wait = wait_all[:, sl]
        if lvl.child_size:
            csl = slice(
                lvl.child_offset, lvl.child_offset + lvl.child_size
            )
            D, on_crit, att_dur, capped = _winner_charges(
                lvl, w, sent_f[:, csl], lat_all[:, csl]
            )
            if capped is not None:
                w_next = on_crit * ~capped
                net_c = w_next * lvl.child_rtt
                tmo_c = on_crit * capped * att_dur
            else:
                w_next = on_crit
                net_c = on_crit * lvl.child_rtt
                tmo_c = None
            net_l.append(net_c.sum(0))
            tmo_l.append(
                tmo_c.sum(0) if tmo_c is not None
                else jnp.zeros(lvl.child_size)
            )
            per_req = per_req + net_c.sum(1)
            if tmo_c is not None:
                per_req = per_req + tmo_c.sum(1)
            if tail_w is not None:
                t_net_l.append((net_c * tail_w[:, None]).sum(0))
                t_tmo_l.append(
                    (tmo_c * tail_w[:, None]).sum(0)
                    if tmo_c is not None
                    else jnp.zeros(lvl.child_size)
                )
            else:
                t_net_l.append(jnp.zeros(lvl.child_size))
                t_tmo_l.append(jnp.zeros(lvl.child_size))
        else:
            D = 0.0
            w_next = None

        hop_wait = w * wait
        hop_self = w * (lat - wait) - D
        contrib = hop_wait + hop_self  # == w * lat - D
        per_req = per_req + contrib.sum(1)
        crit_l.append(
            w.astype(count_dtype).sum(0) if packed else w.sum(0)
        )
        wait_l.append(hop_wait.sum(0))
        self_l.append(hop_self.sum(0))
        # clamp before bucketing: f32 accumulation can leave an
        # off-path hop's contribution a hair below zero, and log(<0)
        # would scatter its weight into the overflow bucket
        flat_idx = (
            jnp.asarray(lvl.svc)[None, :] * NUM_BLAME_BUCKETS
            + blame_bucket_index(jnp.maximum(contrib, 0.0))
        )
        hist = hist.at[flat_idx].add(w.astype(count_dtype))
        if tail_w is not None:
            wt = w * tail_w[:, None]
            t_crit_l.append(
                wt.astype(count_dtype).sum(0) if packed else wt.sum(0)
            )
            t_wait_l.append((hop_wait * tail_w[:, None]).sum(0))
            t_self_l.append((hop_self * tail_w[:, None]).sum(0))
            t_hist = t_hist.at[flat_idx].add(wt.astype(count_dtype))
        else:
            t_crit_l.append(jnp.zeros(lvl.size, count_dtype))
            t_wait_l.append(jnp.zeros(lvl.size))
            t_self_l.append(jnp.zeros(lvl.size))
        w = w_next

    resid = res.client_latency - per_req
    err_count = (res.hop_sent & res.hop_error).sum(0).astype(count_dtype)

    if top_k > 0:
        ex_state = _update_exemplars(res, ex_state, top_k)

    summary = AttributionSummary(
        count=count_dtype(n),
        tail_count=(
            (
                tail_w.astype(count_dtype).sum()
                if packed
                else tail_w.sum()
            )
            if tail_w is not None
            else count_dtype(0)
        ),
        tail_cut=(
            jnp.asarray(tail_cut, jnp.float32)
            if tail_cut is not None
            else jnp.float32(np.inf)
        ),
        residual=resid.sum(),
        residual_abs=jnp.abs(resid).sum(),
        crit_count=jnp.concatenate(crit_l),
        wait_blame=jnp.concatenate(wait_l),
        self_blame=jnp.concatenate(self_l),
        net_blame=jnp.concatenate(net_l),
        timeout_blame=jnp.concatenate(tmo_l),
        error_count=err_count,
        tail_crit_count=jnp.concatenate(t_crit_l),
        tail_wait_blame=jnp.concatenate(t_wait_l),
        tail_self_blame=jnp.concatenate(t_self_l),
        tail_net_blame=jnp.concatenate(t_net_l),
        tail_timeout_blame=jnp.concatenate(t_tmo_l),
        hist=hist.reshape(tables.num_services, NUM_BLAME_BUCKETS),
        tail_hist=t_hist.reshape(
            tables.num_services, NUM_BLAME_BUCKETS
        ),
        exemplars=None,
    )
    return summary, ex_state


def _update_exemplars(
    res, ex: Optional[ExemplarBatch], k: int
) -> ExemplarBatch:
    """Merge this block's top-K slowest requests into the carry."""
    k = min(k, res.client_latency.shape[0])
    _, idx = jax.lax.top_k(res.client_latency, k)
    batch = ExemplarBatch(
        latency=res.client_latency[idx],
        start=res.client_start[idx],
        error=res.client_error[idx],
        hop_sent=res.hop_sent[idx],
        hop_error=res.hop_error[idx],
        hop_latency=res.hop_latency[idx],
        hop_start=res.hop_start[idx],
    )
    if ex is None:
        return batch
    merged = jax.tree.map(
        lambda a, b: jnp.concatenate([a, b]), ex, batch
    )
    _, keep = jax.lax.top_k(merged.latency, k)
    return jax.tree.map(lambda a: a[keep], merged)


def merge_exemplars_host(
    batches: Sequence[ExemplarBatch], k: Optional[int] = None
) -> ExemplarBatch:
    """Top-K merge of per-shard exemplar batches on host (the
    single-device emulation's replay of the mesh ``all_gather``)."""
    cat = jax.tree.map(
        lambda *xs: np.concatenate([np.asarray(x) for x in xs]),
        *batches,
    )
    k = k if k is not None else len(np.asarray(batches[0].latency))
    order = np.argsort(-np.asarray(cat.latency), kind="stable")[:k]
    return jax.tree.map(lambda a: a[order], cat)


def reduce_stacked(
    parts: AttributionSummary,
    exemplars: Optional[ExemplarBatch] = None,
) -> AttributionSummary:
    """Reduce block-stacked summaries (the scan's ys) to one summary;
    ``exemplars`` is the scan carry's final top-K state."""
    out = jax.tree.map(lambda x: x.sum(0), parts._replace(
        tail_cut=jnp.zeros_like(parts.tail_cut), exemplars=None,
    ))
    return out._replace(
        tail_cut=parts.tail_cut.max(0), exemplars=exemplars
    )


def merge_host(shards: Sequence[AttributionSummary]) -> AttributionSummary:
    """Host replay of the mesh collectives over per-shard summaries
    (sequential shard-order sums — the degraded single-device path)."""
    acc = jax.tree.map(
        np.asarray, shards[0]._replace(exemplars=None)
    )
    for s in shards[1:]:
        nxt = jax.tree.map(np.asarray, s._replace(exemplars=None))
        acc = jax.tree.map(lambda a, b: a + b, acc, nxt)
    acc = acc._replace(tail_cut=np.asarray(shards[0].tail_cut))
    ex = [s.exemplars for s in shards if s.exemplars is not None]
    if ex:
        acc = acc._replace(exemplars=merge_exemplars_host(ex))
    return acc


# -- host-side tables -------------------------------------------------------


def service_blame(compiled: CompiledGraph, attr: AttributionSummary,
                  tail: bool = False) -> List[dict]:
    """Per-service blame rows (seconds + share of total blame), sorted
    by descending share."""
    hs = compiled.hop_service
    S = compiled.num_services

    def by_svc(v):
        return np.bincount(hs, weights=np.asarray(v, np.float64),
                           minlength=S)

    wait = by_svc(attr.tail_wait_blame if tail else attr.wait_blame)
    self_ = by_svc(attr.tail_self_blame if tail else attr.self_blame)
    net = by_svc(attr.tail_net_blame if tail else attr.net_blame)
    tmo = by_svc(
        attr.tail_timeout_blame if tail else attr.timeout_blame
    )
    crit = by_svc(attr.tail_crit_count if tail else attr.crit_count)
    errs = by_svc(attr.error_count)
    total = float(wait.sum() + self_.sum() + net.sum() + tmo.sum())
    count = float(attr.tail_count if tail else attr.count)
    rows = []
    for s in range(S):
        blame = wait[s] + self_[s] + net[s] + tmo[s]
        if blame <= 0 and crit[s] <= 0 and errs[s] <= 0:
            continue
        rows.append(
            {
                "service": compiled.services.names[s],
                "share": blame / total if total > 0 else 0.0,
                "blame_s": blame,
                "wait_s": float(wait[s]),
                "self_s": float(self_[s]),
                "net_s": float(net[s]),
                "timeout_s": float(tmo[s]),
                "crit_per_request": (
                    float(crit[s]) / count if count else 0.0
                ),
                "errors": float(errs[s]),
            }
        )
    rows.sort(key=lambda r: -r["share"])
    return rows


def edge_blame(compiled: CompiledGraph, attr: AttributionSummary,
               tail: bool = False) -> List[dict]:
    """Per caller->callee edge wire/timeout blame (the client edge is
    ``client -> <entry>``), sorted by descending blame."""
    names = compiled.services.names
    hs = compiled.hop_service
    parent = compiled.hop_parent
    net = np.asarray(
        attr.tail_net_blame if tail else attr.net_blame, np.float64
    )
    tmo = np.asarray(
        attr.tail_timeout_blame if tail else attr.timeout_blame,
        np.float64,
    )
    crit = np.asarray(
        attr.tail_crit_count if tail else attr.crit_count, np.float64
    )
    errs = np.asarray(attr.error_count, np.float64)
    agg: dict = {}
    for h in range(compiled.num_hops):
        caller = "client" if parent[h] < 0 else names[hs[parent[h]]]
        key = (caller, names[hs[h]])
        row = agg.setdefault(
            key, {"net_s": 0.0, "timeout_s": 0.0, "crit": 0.0,
                  "errors": 0.0}
        )
        row["net_s"] += net[h]
        row["timeout_s"] += tmo[h]
        row["crit"] += crit[h]
        row["errors"] += errs[h]
    out = [
        {"caller": c, "callee": e, **v}
        for (c, e), v in agg.items()
        if v["net_s"] or v["timeout_s"] or v["crit"] or v["errors"]
    ]
    out.sort(key=lambda r: -(r["net_s"] + r["timeout_s"]))
    return out


def to_doc(compiled: CompiledGraph, attr: AttributionSummary,
           top: int = 0) -> dict:
    """The ``<label>.blame.json`` artifact: mean + tail service/edge
    tables plus the invariant evidence (residual, counts)."""
    count = max(float(attr.count), 1.0)
    tail_on = bool(np.isfinite(float(attr.tail_cut)))
    doc = {
        "schema": "isotope-blame/v1",
        "count": float(attr.count),
        "tail_cut_s": (
            float(attr.tail_cut) if tail_on else None
        ),
        "tail_count": float(attr.tail_count),
        "mean_attributed_s": attr.total_blame_s / count,
        "residual_s_per_request": float(attr.residual) / count,
        "residual_abs_s_per_request": float(attr.residual_abs) / count,
        "services": service_blame(compiled, attr)[: top or None],
        "edges": edge_blame(compiled, attr)[: top or None],
    }
    if tail_on:
        doc["tail_services"] = service_blame(
            compiled, attr, tail=True
        )[: top or None]
        doc["tail_edges"] = edge_blame(compiled, attr, tail=True)[
            : top or None
        ]
    return doc


def format_table(doc: dict, top: int = 12) -> str:
    """Human-readable blame table (the ``report``/``simulate`` CLI)."""
    tail_rows = {
        r["service"]: r for r in doc.get("tail_services") or []
    }
    lines = [
        f"critical-path blame over {doc['count']:.0f} requests "
        f"(mean attributed {doc['mean_attributed_s'] * 1e3:.3f} ms, "
        f"residual {doc['residual_abs_s_per_request'] * 1e6:.3f} us/req)"
    ]
    if doc.get("tail_cut_s") is not None:
        lines.append(
            f"tail cut: {doc['tail_cut_s'] * 1e3:.3f} ms "
            f"({doc['tail_count']:.0f} requests past it)"
        )
    hdr = (
        f"{'service':<24} {'share':>7} {'wait':>9} {'self':>9} "
        f"{'net':>9} {'timeout':>9}"
    )
    if tail_rows:
        hdr += f" {'tail share':>10}"
    lines.append(hdr)
    for r in doc["services"][:top]:
        line = (
            f"{r['service']:<24} {r['share'] * 100:>6.1f}% "
            f"{r['wait_s']:>9.4f} {r['self_s']:>9.4f} "
            f"{r['net_s']:>9.4f} {r['timeout_s']:>9.4f}"
        )
        t = tail_rows.get(r["service"])
        if tail_rows:
            line += (
                f" {t['share'] * 100:>9.1f}%" if t else f" {'-':>10}"
            )
        lines.append(line)
    return "\n".join(lines)


def exemplar_results(attr: AttributionSummary):
    """Rebuild a :class:`~isotope_tpu.sim.engine.SimResults`-shaped view
    of the mined exemplars so the trace exporters accept them without a
    dense re-run (rows stay slowest-first; utilization fields are
    zeroed — they are run-level, not per-request)."""
    from isotope_tpu.sim.engine import SimResults

    ex = attr.exemplars
    if ex is None:
        raise ValueError(
            "attribution summary carries no exemplars (run with "
            "attribution_top_k > 0)"
        )
    k = np.asarray(ex.latency).shape[0]
    h = np.asarray(ex.hop_latency).shape[1]
    return SimResults(
        client_start=np.asarray(ex.start),
        client_latency=np.asarray(ex.latency),
        client_error=np.asarray(ex.error),
        hop_sent=np.asarray(ex.hop_sent),
        hop_error=np.asarray(ex.hop_error),
        hop_latency=np.asarray(ex.hop_latency),
        hop_start=np.asarray(ex.hop_start),
        utilization=np.zeros(1, np.float32),
        unstable=np.zeros(1, bool),
        offered_qps=np.float32(0.0),
        hop_wait=np.zeros((k, h), np.float32),
    )
