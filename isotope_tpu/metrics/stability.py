"""Background stability-scenario metrics: the shared series contract.

The reference's long-running stability scenarios (redis, rabbitmq,
mysql, http10, gateway-bouncer, graceful-shutdown, ...) all report
through ONE metric surface, ``perf/docker/prom_client.py:1-40``: a
``stability_outgoing_requests`` counter labeled
``{source, destination, succeeded}`` incremented per attempted request
(``attempt_request``), plus a ``stability_test_instances{test}`` gauge
pinned to 1 while the scenario runs.  The alarm layer then asserts on
those series for every deployed scenario.

The backing services themselves (a real redis cluster, a rabbitmq
broker) are out of simulation scope — they exercise third-party
software, not the mesh.  What IS in scope is the metric contract: a
:class:`StabilityScenario` models the client loop (request cadence,
success probability, optional failure windows matching a
gateway-bouncer schedule), and :func:`stability_text` emits the exact
text exposition ``prom_client.py`` would serve, so
``metrics.alarms``/``metrics.query`` can assert reference-style
stability alarms (e.g. "zero failed scenario requests") against
simulated background scenarios.
"""
from __future__ import annotations

import dataclasses
import zlib
from typing import List, Sequence, Tuple

import numpy as np

from isotope_tpu.metrics.alarms import Alarm, Query


@dataclasses.dataclass(frozen=True)
class StabilityScenario:
    """One background client loop (prom_client.py's attempt_request).

    ``period_s`` is the request cadence (the reference clients loop
    with a sleep); ``success_prob`` the per-request success chance
    outside failure windows; ``fail_windows`` are [start, end) spans of
    run time where every request fails — the shape of the
    gateway-bouncer coupling, where requests through a bouncing
    gateway fail while the gateway is down.
    """

    name: str                     # the {test} label / metric source
    destination: str              # e.g. "redis-master", "rabbitmq"
    period_s: float = 1.0
    success_prob: float = 1.0
    fail_windows: Tuple[Tuple[float, float], ...] = ()

    def __post_init__(self):
        if self.period_s <= 0:
            raise ValueError("period_s must be positive")
        if not 0.0 <= self.success_prob <= 1.0:
            raise ValueError("success_prob must be in [0, 1]")
        for lo, hi in self.fail_windows:
            if hi <= lo:
                raise ValueError("fail window must have end > start")

    def counts(self, duration_s: float, seed: int = 0) -> Tuple[int, int]:
        """(succeeded, failed) requests over ``duration_s`` seconds."""
        times = np.arange(0.0, duration_s, self.period_s)
        n = len(times)
        if n == 0:
            return 0, 0
        in_window = np.zeros(n, bool)
        for lo, hi in self.fail_windows:
            in_window |= (times >= lo) & (times < hi)
        # zlib.crc32 is process-stable; builtin hash() is salted per
        # interpreter, which would make (seed, name) irreproducible
        rng = np.random.default_rng(
            np.random.SeedSequence(
                [seed, zlib.crc32(self.name.encode())]
            )
        )
        ok = (rng.random(n) < self.success_prob) & ~in_window
        return int(ok.sum()), int(n - ok.sum())


def stability_text(
    scenarios: Sequence[StabilityScenario],
    duration_s: float,
    seed: int = 0,
) -> str:
    """Text exposition of the shared stability series
    (prom_client.py's Counter + Gauge as a Prometheus scraper sees
    them; the client library appends ``_total`` to counters)."""
    out: List[str] = [
        "# HELP stability_outgoing_requests_total Number of requests "
        "from this service.",
        "# TYPE stability_outgoing_requests_total counter",
    ]
    for sc in scenarios:
        ok, fail = sc.counts(duration_s, seed)
        for succeeded, count in (("True", ok), ("False", fail)):
            out.append(
                "stability_outgoing_requests_total{"
                f'source="{sc.name}",destination="{sc.destination}",'
                f'succeeded="{succeeded}"}} {count}'
            )
    out.append(
        "# HELP stability_test_instances Is this test running"
    )
    out.append("# TYPE stability_test_instances gauge")
    for sc in scenarios:
        out.append(
            f'stability_test_instances{{test="{sc.name}"}} 1'
        )
    return "\n".join(out) + "\n"


def stability_queries(
    scenarios: Sequence[StabilityScenario],
    max_failed: float = 0.0,
) -> List[Query]:
    """Reference-style per-scenario alarms: no failed requests (beyond
    ``max_failed``) while the scenario's instance gauge is up — the
    ``running_query`` gate mirrors check_metrics.py:196-206 (a check is
    skipped when its scenario isn't deployed)."""
    queries = []
    for sc in scenarios:
        queries.append(
            Query(
                f"stability: {sc.name} failed requests",
                'sum(rate(stability_outgoing_requests_total{'
                f'source="{sc.name}",succeeded="False"}}[1m]))',
                Alarm(
                    (lambda lim: lambda r: r > lim)(max_failed),
                    f"{sc.name}: background scenario requests failed.",
                ),
                f'sum(stability_test_instances{{test="{sc.name}"}})',
            )
        )
    return queries


def scenario_from_bounce(
    name: str,
    destination: str,
    bounce_schedule: Sequence[Tuple[float, float]],
    period_s: float = 1.0,
    success_prob: float = 1.0,
) -> StabilityScenario:
    """Couple a scenario's failure windows to a gateway-bouncer
    schedule (sim.config ChaosEvent bounce windows): requests issued
    while the gateway is down fail, exactly like the reference's
    istio-gateway-bouncer scenario observed through prom_client."""
    return StabilityScenario(
        name=name,
        destination=destination,
        period_s=period_s,
        success_prob=success_prob,
        fail_windows=tuple((float(lo), float(hi))
                           for lo, hi in bounce_schedule),
    )
