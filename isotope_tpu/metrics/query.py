"""A Prometheus query layer over the simulator's exposition.

The reference's analysis path speaks PromQL at a live Prometheus:
canned proxy CPU/memory aggregations
(perf/benchmark/runner/prom.py:116-126), latency quantiles via
``histogram_quantile(p, sum(rate(m[Ns])) by (g, le))``
(prom.py:216-232), and the stability alarms of metrics/check_metrics.py.
The simulator renders the same text exposition a scraper would see
(metrics/prometheus.py); this module closes the loop by parsing that
text back into samples and evaluating the PromQL subset those consumers
actually use:

- instant vector selectors with label matchers: ``m{a="x",b!="y"}``
  (and ``=~``/``!~`` anchored regexes);
- range selectors ``m[1m]`` — the simulator is a single scrape of a
  complete run, so ``rate()`` divides by the *run duration* regardless
  of the bracketed window (each counter accumulated over exactly that
  window); the bracket is accepted for query-string parity;
- ``rate(v)``, aggregations ``sum/max/min/avg/count (v) by (l1, ...)``
  (also ``without (...)``), ``histogram_quantile(q, v)``,
  ``max_over_time``/``avg_over_time`` (identity on a single scrape),
  and scalar arithmetic ``expr * 1000`` / ``expr / 60``.

``histogram_quantile`` implements Prometheus's algorithm: group
``_bucket`` series by all labels but ``le``, cumulative counts, linear
interpolation within the winning bucket (upper bound for +Inf).
"""
from __future__ import annotations

import dataclasses
import math
import re
from typing import Callable, Dict, List, Optional, Sequence, Tuple

LabelSet = Tuple[Tuple[str, str], ...]


@dataclasses.dataclass(frozen=True)
class Sample:
    name: str
    labels: Dict[str, str]
    value: float
    # optional exposition timestamp (ms) — one series may carry several
    # timestamped samples (the timeline's per-window scrape sequence);
    # instant queries read the LATEST one (see MetricStore._select)
    timestamp_ms: Optional[int] = None

    def key(self, drop: Sequence[str] = ()) -> LabelSet:
        return tuple(
            sorted((k, v) for k, v in self.labels.items() if k not in drop)
        )


_LINE_RE = re.compile(
    # the label body matches quoted strings as units, so a '}' INSIDE a
    # label value (cluster="outbound|8080|{tag}") does not end the set;
    # the timestamp accepts OpenMetrics float/exponent notation
    # (1.7e12), not just the Prometheus text format's integer ms
    r'^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)'
    r'(?:\{(?P<labels>(?:[^"{}]|"(?:[^"\\]|\\.)*")*)\})?'
    r'\s+(?P<value>[^\s]+)'
    r'(?:\s+(?P<ts>[-+0-9.eE]+))?\s*$'
)
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')
# the whole label body must be well-formed pairs, not just contain some
_LABELS_BODY_RE = re.compile(
    r'^\s*(?:[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*"\s*'
    r'(?:,\s*[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*"\s*)*,?\s*)?$'
)


# single-pass unescape: sequential str.replace passes corrupt values
# like '\\' + 'n' (escaped backslash followed by a literal n)
_UNESCAPE_RE = re.compile(r'\\(.)')
_UNESCAPE_MAP = {'"': '"', "\\": "\\", "n": "\n"}


def _unescape_label(v: str) -> str:
    return _UNESCAPE_RE.sub(
        lambda m: _UNESCAPE_MAP.get(m.group(1), m.group(0)), v
    )


def _parse_sample_line(line: str) -> Sample:
    """One non-comment exposition line -> Sample; raises ValueError with
    the offending line on any malformation (shape, labels, value, ts)."""
    m = _LINE_RE.match(line)
    if not m:
        raise ValueError(f"unparseable exposition line: {line!r}")
    body = m.group("labels") or ""
    if not _LABELS_BODY_RE.match(body):
        raise ValueError(f"malformed labels in line: {line!r}")
    labels = {
        k: _unescape_label(v) for k, v in _LABEL_RE.findall(body)
    }
    try:
        # float() accepts the OpenMetrics specials verbatim: NaN,
        # +Inf/-Inf, and exponent notation
        value = float(m.group("value"))
    except ValueError:
        raise ValueError(
            f"unparseable sample value in line: {line!r}"
        ) from None
    ts = m.group("ts")
    ts_ms: Optional[int] = None
    if ts is not None:
        try:
            ts_ms = int(round(float(ts)))
        except (ValueError, OverflowError):
            raise ValueError(
                f"unparseable timestamp in line: {line!r}"
            ) from None
    return Sample(m.group("name"), labels, value, timestamp_ms=ts_ms)


@dataclasses.dataclass
class ExpositionParse:
    """A tolerant parse of one exposition: samples plus line accounting.

    The counters partition the input exactly —
    ``lines_total == lines_blank + lines_comment + lines_parsed +
    len(malformed)`` — so consumers (the ingest coverage block) can
    prove nothing was dropped silently.  Comment lines cover all ``#``
    families: HELP/TYPE and the OpenMetrics UNIT/EOF markers.
    """

    samples: List[Sample]
    lines_total: int = 0
    lines_blank: int = 0
    lines_comment: int = 0
    lines_parsed: int = 0
    malformed: List[Tuple[int, str]] = dataclasses.field(
        default_factory=list
    )

    @property
    def lines_malformed(self) -> int:
        return len(self.malformed)


def parse_exposition_tolerant(text: str) -> ExpositionParse:
    """Parse a real-world scrape: malformed lines are COUNTED and
    carried (1-based line numbers), never raised mid-file, so one bad
    line cannot abort the ingest of an otherwise-usable exposition.
    Tolerates OpenMetrics ``# EOF`` / ``# TYPE`` / ``# UNIT`` comment
    families, ``NaN``/``+Inf`` values, and exponent-notation
    timestamps."""
    out = ExpositionParse(samples=[])
    for lineno, raw in enumerate(text.splitlines(), 1):
        out.lines_total += 1
        line = raw.strip()
        if not line:
            out.lines_blank += 1
            continue
        if line.startswith("#"):
            out.lines_comment += 1
            continue
        try:
            out.samples.append(_parse_sample_line(line))
        except ValueError:
            out.malformed.append((lineno, raw))
            continue
        out.lines_parsed += 1
    return out


def parse_exposition(text: str) -> List[Sample]:
    """Parse the Prometheus text format into flat samples.

    Strict: the first malformed line raises ValueError (the simulator's
    own expositions must be pristine).  Scrape ingestion uses
    :func:`parse_exposition_tolerant`, which counts instead."""
    out: List[Sample] = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        out.append(_parse_sample_line(line))
    return out


# -- the PromQL-subset evaluator -------------------------------------------


class QueryError(ValueError):
    pass


@dataclasses.dataclass
class _Matcher:
    label: str
    op: str          # = != =~ !~
    value: str

    def ok(self, labels: Dict[str, str]) -> bool:
        got = labels.get(self.label, "")
        if self.op == "=":
            return got == self.value
        if self.op == "!=":
            return got != self.value
        # Prometheus fully anchors regex matchers
        hit = re.fullmatch(self.value, got) is not None
        return hit if self.op == "=~" else not hit


_AGGS: Dict[str, Callable] = {
    "sum": sum,
    "max": max,
    "min": min,
    "avg": lambda vs: sum(vs) / len(vs),
    "count": len,
}
# single-scrape identities: the run IS the whole time range
_OVER_TIME = {"max_over_time", "avg_over_time", "min_over_time"}


class _Parser:
    """Recursive descent over the supported grammar."""

    def __init__(self, text: str):
        self.text = text
        self.pos = 0

    def peek(self) -> str:
        self._ws()
        return self.text[self.pos:self.pos + 1]

    def _ws(self):
        while self.pos < len(self.text) and self.text[self.pos].isspace():
            self.pos += 1

    def expect(self, ch: str):
        self._ws()
        if not self.text.startswith(ch, self.pos):
            raise QueryError(
                f"expected {ch!r} at {self.pos} in {self.text!r}"
            )
        self.pos += len(ch)

    def ident(self) -> str:
        self._ws()
        m = re.match(r"[a-zA-Z_:][a-zA-Z0-9_:]*", self.text[self.pos:])
        if not m:
            raise QueryError(
                f"expected identifier at {self.pos} in {self.text!r}"
            )
        self.pos += m.end()
        return m.group(0)

    def number(self) -> float:
        self._ws()
        m = re.match(r"[0-9]*\.?[0-9]+(?:[eE][+-]?[0-9]+)?",
                     self.text[self.pos:])
        if not m:
            raise QueryError(f"expected number at {self.pos}")
        self.pos += m.end()
        return float(m.group(0))

    # grammar -----------------------------------------------------------

    def parse(self):
        node = self.expr()
        self._ws()
        if self.pos != len(self.text):
            raise QueryError(
                f"trailing input at {self.pos}: {self.text[self.pos:]!r}"
            )
        return node

    def expr(self):
        node = self.term()
        while True:
            self._ws()
            ch = self.peek()
            if ch in ("*", "/"):
                self.pos += 1
                rhs = self.term()
                node = ("binop", ch, node, rhs)
            else:
                return node

    def term(self):
        self._ws()
        ch = self.peek()
        if ch == "(":
            self.expect("(")
            node = self.expr()
            self.expect(")")
            return node
        if ch.isdigit() or ch == ".":
            return ("number", self.number())
        ident = self.ident()
        self._ws()
        if self.peek() == "(":
            return self.call(ident)
        return self.selector(ident)

    def call(self, fn: str):
        self.expect("(")
        args = [self.expr()]
        while self.peek() == ",":
            self.expect(",")
            args.append(self.expr())
        self.expect(")")
        by: Optional[Tuple[str, bool]] = None
        self._ws()
        m = re.match(r"(by|without)\s*\(", self.text[self.pos:])
        if fn in _AGGS and m:
            self.pos += m.end()
            labels = []
            while self.peek() != ")":
                labels.append(self.ident())
                if self.peek() == ",":
                    self.expect(",")
            self.expect(")")
            by = (tuple(labels), m.group(1) == "by")
        return ("call", fn, args, by)

    def selector(self, name: str):
        matchers: List[_Matcher] = []
        self._ws()
        if self.peek() == "{":
            self.expect("{")
            while self.peek() != "}":
                label = self.ident()
                self._ws()
                for op in ("!~", "=~", "!=", "="):
                    if self.text.startswith(op, self.pos):
                        self.pos += len(op)
                        break
                else:
                    raise QueryError(f"bad matcher op at {self.pos}")
                self._ws()
                m = re.match(r'"((?:[^"\\]|\\.)*)"', self.text[self.pos:])
                if not m:
                    raise QueryError(f"expected quoted value at {self.pos}")
                self.pos += m.end()
                matchers.append(_Matcher(label, op, m.group(1)))
                if self.peek() == ",":
                    self.expect(",")
            self.expect("}")
        self._ws()
        if self.peek() == "[":
            self.expect("[")
            m = re.match(r"[0-9]+[smhd]?", self.text[self.pos:])
            if not m:
                raise QueryError(f"expected range duration at {self.pos}")
            self.pos += m.end()
            self.expect("]")
            return ("range", name, tuple(matchers))
        return ("instant", name, tuple(matchers))


Vector = Dict[LabelSet, float]


class MetricStore:
    """Instant-query evaluation over one scrape of samples.

    ``duration_s`` is the wall span the counters accumulated over — the
    simulated run's duration — used by ``rate()``.
    """

    def __init__(self, samples: Sequence[Sample], duration_s: float):
        self.samples = list(samples)
        self.duration_s = float(duration_s)
        self._by_name: Dict[str, List[Sample]] = {}
        for s in self.samples:
            self._by_name.setdefault(s.name, []).append(s)

    @classmethod
    def from_text(cls, text: str, duration_s: float) -> "MetricStore":
        return cls(parse_exposition(text), duration_s)

    # -- public API -----------------------------------------------------

    def query(self, expr: str) -> Vector:
        """Evaluate; returns {sorted-label-tuple: value}."""
        node = _Parser(expr).parse()
        val = self._eval(node)
        if isinstance(val, float):
            return {(): val}
        return val

    def query_value(self, expr: str, default: float = 0.0) -> float:
        """Evaluate to one number (prometheus.py:43-61's fetch_value:
        an empty result is 0)."""
        vec = self.query(expr)
        if not vec:
            return default
        if len(vec) > 1:
            raise QueryError(
                f"query returned {len(vec)} series, expected 1: {expr!r}"
            )
        return next(iter(vec.values()))

    # -- evaluation -----------------------------------------------------

    def _select(self, name: str, matchers) -> Vector:
        out: Vector = {}
        # instant-query semantics for TIMESTAMPED series: the latest
        # sample of each label set wins (a timeline exposition carries
        # one sample per window); untimestamped duplicates keep the
        # historical summing behavior
        latest_ts: Dict[LabelSet, int] = {}
        for s in self._by_name.get(name, ()):
            if not all(m.ok(s.labels) for m in matchers):
                continue
            k = s.key()
            if s.timestamp_ms is not None:
                if k not in latest_ts or s.timestamp_ms >= latest_ts[k]:
                    latest_ts[k] = s.timestamp_ms
                    out[k] = s.value
            else:
                out[k] = out.get(k, 0.0) + s.value
        return out

    def _eval(self, node):
        kind = node[0]
        if kind == "number":
            return node[1]
        if kind in ("instant", "range"):
            return self._select(node[1], node[2])
        if kind == "binop":
            _, op, lhs, rhs = node
            lv, rv = self._eval(lhs), self._eval(rhs)
            f = (lambda a, b: a * b) if op == "*" else (lambda a, b: a / b)
            if isinstance(lv, float) and isinstance(rv, float):
                return f(lv, rv)
            if isinstance(rv, float):
                return {k: f(v, rv) for k, v in lv.items()}
            if isinstance(lv, float):
                return {k: f(lv, v) for k, v in rv.items()}
            raise QueryError("vector-vector arithmetic is not supported")
        if kind == "call":
            _, fn, args, by = node
            if fn == "rate" or fn == "irate":
                v = self._eval(args[0])
                if not isinstance(v, dict):
                    raise QueryError("rate() needs a selector")
                if self.duration_s <= 0:
                    return {k: 0.0 for k in v}
                return {k: val / self.duration_s for k, val in v.items()}
            if fn in _OVER_TIME:
                return self._eval(args[0])
            if fn == "histogram_quantile":
                q = self._eval(args[0])
                v = self._eval(args[1])
                if not isinstance(q, float) or not isinstance(v, dict):
                    raise QueryError(
                        "histogram_quantile(scalar, vector) expected"
                    )
                return _histogram_quantile(q, v)
            if fn in _AGGS:
                v = self._eval(args[0])
                if not isinstance(v, dict):
                    raise QueryError(f"{fn}() needs a vector")
                groups: Dict[LabelSet, List[float]] = {}
                for key, val in v.items():
                    labels = dict(key)
                    if by is None:
                        gkey: LabelSet = ()
                    else:
                        names, is_by = by
                        if is_by:
                            kept = {
                                k: x for k, x in labels.items()
                                if k in names
                            }
                        else:
                            kept = {
                                k: x for k, x in labels.items()
                                if k not in names
                            }
                        gkey = tuple(sorted(kept.items()))
                    groups.setdefault(gkey, []).append(val)
                return {
                    k: float(_AGGS[fn](vs)) for k, vs in groups.items()
                }
            raise QueryError(f"unsupported function: {fn!r}")
        raise QueryError(f"bad node {node!r}")  # pragma: no cover


def _histogram_quantile(q: float, vec: Vector) -> Vector:
    """Prometheus's histogram_quantile over ``_bucket`` series."""
    groups: Dict[LabelSet, List[Tuple[float, float]]] = {}
    for key, val in vec.items():
        labels = dict(key)
        le = labels.pop("le", None)
        if le is None:
            raise QueryError("histogram_quantile input lacks 'le' labels")
        bound = math.inf if le in ("+Inf", "Inf", "inf") else float(le)
        groups.setdefault(tuple(sorted(labels.items())), []).append(
            (bound, val)
        )
    out: Vector = {}
    for gkey, buckets in groups.items():
        buckets.sort()
        total = buckets[-1][1] if buckets else 0.0
        # Prometheus: NaN without at least two buckets (one finite + +Inf)
        if (
            len(buckets) < 2
            or total <= 0
            or not math.isinf(buckets[-1][0])
        ):
            out[gkey] = math.nan
            continue
        rank = q * total
        prev_bound, prev_count = 0.0, 0.0
        val = buckets[-2][0] if len(buckets) > 1 else math.nan
        for bound, count in buckets:
            if count >= rank:
                if math.isinf(bound):
                    # quantile falls in +Inf: report the last finite bound
                    val = prev_bound
                else:
                    width = bound - prev_bound
                    frac = (
                        (rank - prev_count) / (count - prev_count)
                        if count > prev_count
                        else 0.0
                    )
                    val = prev_bound + width * frac
                break
            prev_bound, prev_count = bound, count
        out[gkey] = val
    return out
