"""Simulation flight recorder: on-device windowed time series.

The reference system's observability was *time-resolved*: Prometheus
scraped each mock service's ``/metrics`` on an interval while Fortio
drove load, so every analysis query had a time axis (rates ramping,
error bursts, queues draining).  isotope-tpu's summaries so far are
end-of-run aggregates — one number per run.  This module restores the
time axis **on device**: inside the existing block ``lax.scan`` (the
same reduction attribution rides), every hop event is binned into fixed
sim-time windows and accumulated into per-service x per-window series:

- client arrival / completion / error counts and latency sums per
  window, plus a coarse per-window latency histogram (the PR-5
  log-bucket scheme, ``attribution.blame_bucket_index``);
- per-service hop arrivals / completions / errors per window;
- per-service **in-flight** and **busy** occupancy integrals per
  window (exact interval-overlap seconds via a prefix-sum identity —
  no O(N x H x W) tensor ever materializes), from which utilization,
  mean queue depth, and mean concurrency derive.

Everything is O(S x W x small): block summaries sum under the scan,
shards merge with ``psum`` bit-equal to the emulated host merge, and
``timeline=off`` leaves every existing program byte-identical (pinned,
like attribution).

The occupancy integral: for events ``[s_i, e_i)`` truncated to the
horizon ``T = W * dt``, the cumulative busy-seconds before time ``t``
is ``F(t) = sum_i min(t, e_i) - min(t, s_i)``.  With per-window scatter
sums of start/end counts and clamped start/end times, ``F`` at every
window boundary is a cumulative sum —

    F(t) = Esum(<t) - Ssum(<t) + t * (A(<t) - B(<t))

(``A``/``B`` = starts/ends before ``t``) — and the per-window busy
seconds are first differences of ``F``.  Exact, linear in events, and
additive across blocks and shards.
"""
from __future__ import annotations

import dataclasses
import sys
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from isotope_tpu.compiler.program import CompiledGraph
from isotope_tpu.metrics.attribution import (
    NUM_BLAME_BUCKETS,
    blame_bucket_centers,
    blame_bucket_index,
)

#: soft cap on S x W elements per (S, W) series — the recorder carries
#: ~5 such fields, stacked once per scan block, so this bounds device
#: cost at a few tens of MB before the window planner clamps
ELEM_BUDGET = 2_097_152


class TimelineSummary(NamedTuple):
    """Device-reduced windowed series for one run.

    Every leaf is O(W) or O(S x W); block summaries sum under
    ``lax.scan`` and shards merge with ``psum`` exactly like
    :class:`~isotope_tpu.sim.summary.RunSummary`.  ``window_s`` rides
    as a scalar (identical everywhere; excluded from the psum like
    attribution's ``tail_cut``).

    Window ``w`` covers sim time ``[w * window_s, (w+1) * window_s)``;
    the final window also absorbs any overflow past the planned
    horizon (clamped index), so count reconciliation is exact:
    ``arrivals.sum() == count``.
    """

    window_s: jax.Array        # scalar f32 — the window width used
    count: jax.Array           # scalar — requests recorded
    arrivals: jax.Array        # (W,) client requests by start window
    completions: jax.Array     # (W,) client requests by end window
    errors: jax.Array          # (W,) client 500s by start window
    latency_sum: jax.Array     # (W,) client latency sum by start window
    latency_hist: jax.Array    # (W, NUM_BLAME_BUCKETS) coarse log-bucket
    svc_arrivals: jax.Array    # (S, W) executed hops by hop start
    svc_completions: jax.Array  # (S, W) executed hops by hop end
    svc_errors: jax.Array      # (S, W) hop 500s by hop start
    svc_inflight_s: jax.Array  # (S, W) occupancy integral [start, end)
    svc_busy_s: jax.Array      # (S, W) occupancy integral [start+wait, end)

    @property
    def num_windows(self) -> int:
        return int(np.asarray(self.arrivals).shape[0])


@dataclasses.dataclass(frozen=True)
class TimelineSpec:
    """Static recorder tables: the window grid + the hop -> service map."""

    num_windows: int
    window_s: float
    num_services: int
    hop_service: jax.Array     # (H,) i32


def plan_windows(
    expected_duration_s: float,
    window_s: float,
    max_windows: int,
    num_services: int,
    elem_budget: int = ELEM_BUDGET,
    log=None,
) -> Tuple[int, float, bool]:
    """Resolve the static window grid for a run.

    Returns ``(num_windows, effective_window_s, clamped)``.  The window
    count is ``ceil(duration / window_s)`` clamped by ``max_windows``
    AND by the per-series element budget (``S * W <= elem_budget``) —
    when clamped, ``window_s`` widens so the grid still covers the
    expected duration (a warning instead of an OOM; the vet cost model
    reports the same bound as VET-M003)."""
    if window_s <= 0:
        raise ValueError("timeline window_s must be positive")
    duration = max(float(expected_duration_s), window_s)
    want = max(1, int(np.ceil(duration / window_s)))
    cap = max(1, min(int(max_windows), elem_budget // max(num_services, 1)))
    if want <= cap:
        return want, float(window_s), False
    eff = duration / cap
    msg = (
        f"timeline: {want} windows of {window_s:g}s exceed the cap "
        f"({cap}); widening to {cap} windows of {eff:g}s"
    )
    (log or (lambda m: print(m, file=sys.stderr)))(msg)
    return cap, float(eff), True


def build_spec(
    compiled: CompiledGraph, num_windows: int, window_s: float
) -> TimelineSpec:
    return TimelineSpec(
        num_windows=int(num_windows),
        window_s=float(window_s),
        num_services=compiled.num_services,
        hop_service=jnp.asarray(compiled.hop_service, jnp.int32),
    )


# -- the on-device recorder --------------------------------------------------


def _window_index(spec: TimelineSpec, t: jax.Array) -> jax.Array:
    """Clamped window index (the final window absorbs overflow)."""
    idx = jnp.floor(t * (1.0 / spec.window_s)).astype(jnp.int32)
    return jnp.clip(idx, 0, spec.num_windows - 1)


#: window counts up to this bound take the DENSE boundary-compare path
#: (per-boundary masked contractions — no O(N x H) scatter, which XLA
#: lowers to near-serial code on CPU and ~element-gather speed on TPU);
#: beyond it, per-channel scatters keep the work O(N x H) independent
#: of W.  Measured crossover on CPU: ~2.8 ms/boundary (einsum) vs
#: ~36 ms/scatter at (2048 x 121) — dense wins up to ~90 windows.
DENSE_WINDOWS_MAX = 64


def _service_boundary_prefixes(
    spec: TimelineSpec,
    t: jax.Array,          # (N, H) f32 — clamped event times, [0, T]
    vals: Sequence[jax.Array],  # V arrays (N, H) f32 to prefix-sum
) -> jax.Array:
    """(S, W+1, V) per-service boundary prefixes of one time family:
    ``out[s, j, v]`` sums ``vals[v]`` over service-``s`` events with
    time STRICTLY before the boundary ``j * window_s``; column ``W``
    holds the family total (the overflow-clamped "before the horizon
    end" prefix, matching the clamped final window).

    Everything the recorder reports is a first difference of these
    prefixes, so both lowering regimes (dense compare vs scatter) are
    interchangeable per run — selection is static in W.
    """
    W = spec.num_windows
    S = spec.num_services
    H = t.shape[1]
    V = len(vals)
    if W <= DENSE_WINDOWS_MAX:
        stacked = jnp.stack(vals, axis=-1)  # (N, H, V)
        # per-hop totals at each interior boundary via a masked
        # contraction over the request axis (one compare + one
        # einsum per boundary — bounded (N, H) intermediates), then
        # one H-row scatter folds hops into services
        cols = [jnp.zeros((H, V))]
        for j in range(1, W):
            m = (t < j * spec.window_s).astype(jnp.float32)
            cols.append(
                jnp.einsum(
                    "nh,nhv->hv", m, stacked,
                    precision=jax.lax.Precision.HIGHEST,
                )
            )
        cols.append(stacked.sum(0))
        per_hop = jnp.stack(cols, axis=1)  # (H, W+1, V)
        return (
            jnp.zeros((S, W + 1, V))
            .at[spec.hop_service]
            .add(per_hop)
        )
    # wide grids: one scatter per channel (XLA lowers a multi-channel
    # scatter row catastrophically worse than V independent ones —
    # measured 1.2 s vs 3 x 36 ms on CPU), cumsum over the window axis
    # recovers the prefixes
    idx = (
        jnp.broadcast_to(spec.hop_service[None, :], t.shape) * W
        + _window_index(spec, t)
    ).reshape(-1)
    bins = jnp.stack(
        [
            jnp.zeros(S * W).at[idx].add(v.reshape(-1))
            for v in vals
        ],
        axis=-1,
    ).reshape(S, W, V)
    return jnp.pad(jnp.cumsum(bins, axis=1), ((0, 0), (1, 0), (0, 0)))


def versioned_service_windows(
    spec: TimelineSpec,
    t: jax.Array,            # (N, H) f32 — clamped event times, [0, T]
    version: jax.Array,      # (N, H) bool — per-hop version coin
    vals: Sequence[jax.Array],  # V arrays (N, H) f32 to window-sum
) -> jax.Array:
    """(S, 2, W, V) per-service, per-VERSION window sums of one time
    family — the recorder's (S, W) observation channel extended along a
    two-arm deployment axis (axis 1: 0 = baseline, 1 = canary).

    The per-version split rides the SAME boundary-prefix machinery as
    every other series (one `_service_boundary_prefixes` call over 2V
    masked channels), so both lowering regimes apply unchanged and the
    result is additive across blocks and shards exactly like the
    recorder's series — the property the rollout controller's psum
    merge (sim/rollout.py) relies on.
    """
    ver = version.astype(jnp.float32)
    base = 1.0 - ver
    masked = [v * base for v in vals] + [v * ver for v in vals]
    pref = _service_boundary_prefixes(spec, t, masked)  # (S, W+1, 2V)
    diff = pref[:, 1:, :] - pref[:, :-1, :]             # (S, W, 2V)
    V = len(vals)
    return jnp.stack([diff[..., :V], diff[..., V:]], axis=1)


def timeline_block(
    res, spec: TimelineSpec, packed: bool = False
) -> TimelineSummary:
    """Reduce one block's SimResults to a TimelineSummary (jit-friendly;
    called inside the engine's block scan — the block's clocks are
    absolute sim time, so windows align across blocks and shards).

    ``packed`` (SimParams.packed_carries) accumulates the pure COUNT
    series as int32 (exact past 2^24 where f32 loses integers, same
    bound caveats as attribution); the occupancy integrals stay f32.
    """
    if res.hop_wait is None:
        raise ValueError(
            "timeline needs SimResults.hop_wait (produced by Simulator "
            "runs with SimParams.timeline=True; synthetic SimResults "
            "must fill it)"
        )
    n = res.client_latency.shape[0]
    W = spec.num_windows
    count_dtype = jnp.int32 if packed else jnp.float32

    # -- client-level series --------------------------------------------
    start_w = _window_index(spec, res.client_start)
    end_w = _window_index(spec, res.client_end)
    ones = jnp.ones(n, count_dtype)
    arrivals = jnp.zeros(W, count_dtype).at[start_w].add(ones)
    completions = jnp.zeros(W, count_dtype).at[end_w].add(ones)
    errors = (
        jnp.zeros(W, count_dtype)
        .at[start_w]
        .add(res.client_error.astype(count_dtype))
    )
    latency_sum = jnp.zeros(W).at[start_w].add(res.client_latency)
    hist = (
        jnp.zeros(W * NUM_BLAME_BUCKETS, count_dtype)
        .at[
            start_w * NUM_BLAME_BUCKETS
            + blame_bucket_index(jnp.maximum(res.client_latency, 0.0))
        ]
        .add(ones)
    ).reshape(W, NUM_BLAME_BUCKETS)

    # -- per-service series ---------------------------------------------
    # Three time families (hop start, hop end, busy start = start +
    # queueing wait), each reduced to per-service boundary prefixes;
    # every reported series is a first difference of those.  The
    # occupancy identity (module docstring):
    #   F(t) = Esum(<t) - Ssum(<t) + t * (A(<t) - B(<t))
    # gives exact per-window busy-seconds of the event intervals
    # truncated to the horizon.
    dt = spec.window_s
    T = W * dt
    sent_f = res.hop_sent.astype(jnp.float32)
    err_f = (res.hop_sent & res.hop_error).astype(jnp.float32)
    s_c = jnp.clip(res.hop_start, 0.0, T)
    e_c = jnp.clip(res.hop_start + res.hop_latency, s_c, T)
    b_c = jnp.clip(res.hop_start + res.hop_wait, s_c, e_c)

    p_start = _service_boundary_prefixes(
        spec, s_c, (sent_f, sent_f * s_c, err_f)
    )
    p_end = _service_boundary_prefixes(
        spec, e_c, (sent_f, sent_f * e_c)
    )
    p_busy = _service_boundary_prefixes(
        spec, b_c, (sent_f, sent_f * b_c)
    )
    a_pref, ssum = p_start[..., 0], p_start[..., 1]
    err_pref = p_start[..., 2]
    b_pref, esum = p_end[..., 0], p_end[..., 1]
    ab_pref, bsum = p_busy[..., 0], p_busy[..., 1]

    def diff(x):
        return x[:, 1:] - x[:, :-1]

    bounds = jnp.arange(W + 1, dtype=jnp.float32) * dt
    inflight = diff(esum - ssum + bounds[None, :] * (a_pref - b_pref))
    busy = diff(esum - bsum + bounds[None, :] * (ab_pref - b_pref))

    return TimelineSummary(
        window_s=jnp.float32(spec.window_s),
        count=count_dtype(n),
        arrivals=arrivals,
        completions=completions,
        errors=errors,
        latency_sum=latency_sum,
        latency_hist=hist,
        svc_arrivals=diff(a_pref).astype(count_dtype),
        svc_completions=diff(b_pref).astype(count_dtype),
        svc_errors=diff(err_pref).astype(count_dtype),
        svc_inflight_s=inflight,
        svc_busy_s=busy,
    )


def zeros_summary(spec: TimelineSpec, packed: bool = False
                  ) -> TimelineSummary:
    """An all-zero TimelineSummary shaped for ``spec`` — the scan
    CARRY's initial value.  The recorder accumulates into the carry
    (``accumulate``) rather than stacking per-block ys, so device
    footprint stays O(S x W) regardless of the block count — the
    bound the window planner and the vet cost model enforce."""
    W = spec.num_windows
    S = spec.num_services
    cd = jnp.int32 if packed else jnp.float32
    return TimelineSummary(
        window_s=jnp.float32(spec.window_s),
        count=cd(0),
        arrivals=jnp.zeros(W, cd),
        completions=jnp.zeros(W, cd),
        errors=jnp.zeros(W, cd),
        latency_sum=jnp.zeros(W),
        latency_hist=jnp.zeros((W, NUM_BLAME_BUCKETS), cd),
        svc_arrivals=jnp.zeros((S, W), cd),
        svc_completions=jnp.zeros((S, W), cd),
        svc_errors=jnp.zeros((S, W), cd),
        svc_inflight_s=jnp.zeros((S, W)),
        svc_busy_s=jnp.zeros((S, W)),
    )


def accumulate(
    acc: TimelineSummary, block: TimelineSummary
) -> TimelineSummary:
    """Fold one block's summary into the scan-carry accumulator
    (element sums; ``window_s`` is an identical constant, kept)."""
    out = jax.tree.map(
        jnp.add,
        acc._replace(window_s=jnp.float32(0.0)),
        block._replace(window_s=jnp.float32(0.0)),
    )
    return out._replace(window_s=acc.window_s)


def merge_host(shards: Sequence[TimelineSummary]) -> TimelineSummary:
    """Host replay of the mesh psum over per-shard summaries
    (sequential shard-order sums — the single-device emulation)."""
    acc = jax.tree.map(np.asarray, shards[0])
    for s in shards[1:]:
        nxt = jax.tree.map(np.asarray, s)
        acc = jax.tree.map(lambda a, b: a + b, acc, nxt)
    return acc._replace(window_s=np.asarray(shards[0].window_s))


# -- host-side derivations ---------------------------------------------------


def _np(x) -> np.ndarray:
    return np.asarray(x, np.float64)


def window_quantile(tl: TimelineSummary, w: int, q: float) -> float:
    """One window's client-latency quantile off the coarse log-bucket
    histogram (PR-5 bucket centers)."""
    hist = _np(tl.latency_hist)[w]
    total = hist.sum()
    if total <= 0:
        return 0.0
    idx = int(np.searchsorted(np.cumsum(hist), q * total, side="left"))
    return float(blame_bucket_centers()[min(idx, NUM_BLAME_BUCKETS - 1)])


def leaf_services(compiled: CompiledGraph) -> List[int]:
    """Service ids that never call anyone (no hop of theirs is a
    parent) — the ``star9`` spokes whose joint busy windows a convoy
    correlates with the entry's wait."""
    callers = set()
    parent = compiled.hop_parent
    hs = compiled.hop_service
    for h in range(1, compiled.num_hops):
        callers.add(int(hs[parent[h]]))
    return [s for s in range(compiled.num_services) if s not in callers]


def convoy(compiled: CompiledGraph, tl: TimelineSummary) -> dict:
    """Convoy detector: cross-correlation of the entry's wait share vs
    the leaves' busy share, per window.

    A convoy (the star9 saturated fidelity gap, ROADMAP) shows up as
    time-correlated entry-idle-waiting / leaf-busy windows: when the
    leaves' joint busy share rises, the entry's wait share of its own
    occupancy rises with it.  The independent per-station census cannot
    carry that coupling; this detector localizes it on the window axis
    so the fidelity fix has a measurable target.
    """
    entry = int(compiled.entry_service)
    leaves = leaf_services(compiled)
    dt = float(tl.window_s)
    inflight = _np(tl.svc_inflight_s)
    busy = _np(tl.svc_busy_s)
    queue = np.maximum(inflight - busy, 0.0)
    reps = np.asarray(compiled.services.replicas, np.float64)

    entry_occ = inflight[entry]
    wait_share = np.where(
        entry_occ > 1e-12, queue[entry] / np.maximum(entry_occ, 1e-12), 0.0
    )
    leaf_cap = max(float(reps[leaves].sum()), 1.0) * dt
    leaf_busy_share = busy[leaves].sum(0) / leaf_cap

    active = entry_occ > 1e-12
    r = 0.0
    if active.sum() >= 3:
        a = wait_share[active]
        b = leaf_busy_share[active]
        if a.std() > 1e-12 and b.std() > 1e-12:
            r = float(np.corrcoef(a, b)[0, 1])
    return {
        "entry": compiled.services.names[entry],
        "num_leaf_services": len(leaves),
        "windows_active": int(active.sum()),
        "entry_wait_share": [round(float(v), 6) for v in wait_share],
        "leaf_busy_share": [
            round(float(v), 6) for v in leaf_busy_share
        ],
        "correlation": round(r, 4),
        "convoy_suspected": bool(r > 0.5 and active.sum() >= 3),
    }


def controlplane_windows(
    ack_times_s: np.ndarray, window_s: float, num_windows: int
) -> dict:
    """Project control-plane convergence events (per-proxy push-ACK
    times, sim/controlplane.py) onto the data-plane window axis, so a
    config-push timeline composes with the recorder's series."""
    acks = np.asarray(ack_times_s, np.float64)
    W = int(num_windows)
    idx = np.clip(
        np.floor(acks / float(window_s)).astype(np.int64), 0, W - 1
    )
    per = np.bincount(idx, minlength=W).astype(np.float64)
    frac = np.cumsum(per) / max(len(acks), 1)
    return {
        "proxies": int(len(acks)),
        "acks": [int(v) for v in per],
        "converged_fraction": [round(float(v), 6) for v in frac],
        "converged_window": (
            int(np.argmax(frac >= 1.0)) if len(acks) else 0
        ),
    }


def to_doc(
    compiled: CompiledGraph,
    tl: TimelineSummary,
    top_services: int = 64,
    controlplane: Optional[dict] = None,
) -> dict:
    """The ``timeline.json`` artifact (``isotope-timeline/v1``):
    per-window client rows, the most-active services' series, and the
    convoy verdict."""
    W = tl.num_windows
    dt = float(tl.window_s)
    arr = _np(tl.arrivals)
    comp = _np(tl.completions)
    errs = _np(tl.errors)
    lat = _np(tl.latency_sum)
    windows = []
    for w in range(W):
        a = arr[w]
        windows.append(
            {
                "index": w,
                "t_start_s": round(w * dt, 6),
                "t_end_s": round((w + 1) * dt, 6),
                "arrivals": float(a),
                "completions": float(comp[w]),
                "errors": float(errs[w]),
                "qps": round(a / dt, 4),
                "mean_latency_s": (
                    round(lat[w] / a, 9) if a > 0 else 0.0
                ),
                "p99_s": round(window_quantile(tl, w, 0.99), 9),
            }
        )

    names = compiled.services.names
    reps = np.asarray(compiled.services.replicas, np.float64)
    inflight = _np(tl.svc_inflight_s)
    busy = _np(tl.svc_busy_s)
    queue = np.maximum(inflight - busy, 0.0)
    svc_arr = _np(tl.svc_arrivals)
    svc_err = _np(tl.svc_errors)
    order = np.argsort(-busy.sum(1), kind="stable")
    services: Dict[str, dict] = {}
    for s in order[: top_services or None]:
        s = int(s)
        if svc_arr[s].sum() <= 0 and busy[s].sum() <= 0:
            continue
        util = busy[s] / (dt * max(float(reps[s]), 1.0))
        peak_w = int(np.argmax(util))
        services[names[s]] = {
            "requests": float(svc_arr[s].sum()),
            "errors": float(svc_err[s].sum()),
            "utilization": [round(float(v), 6) for v in util],
            "queue_depth": [
                round(float(v) / dt, 6) for v in queue[s]
            ],
            "in_flight": [
                round(float(v) / dt, 6) for v in inflight[s]
            ],
            "peak_utilization": round(float(util[peak_w]), 6),
            "peak_window": peak_w,
        }
    doc = {
        "schema": "isotope-timeline/v1",
        "window_s": dt,
        "num_windows": W,
        "count": float(tl.count),
        "windows": windows,
        "services": services,
        "services_truncated": max(
            0, compiled.num_services - len(services)
        ),
        "convoy": convoy(compiled, tl),
    }
    if controlplane is not None:
        doc["controlplane"] = controlplane
    return doc


def format_table(doc: dict, top: int = 24) -> str:
    """Human-readable per-window table with a per-service sparkline
    block (the ``timeline`` CLI / ``simulate --timeline`` rendering)."""
    lines = [
        f"timeline: {doc['num_windows']} windows x "
        f"{doc['window_s']:g}s ({doc['count']:.0f} requests)"
    ]
    lines.append(
        f"{'win':>4} {'t (s)':>9} {'qps':>9} {'errors':>7} "
        f"{'mean (ms)':>10} {'p99 (ms)':>9}"
    )
    for row in doc["windows"][:top]:
        lines.append(
            f"{row['index']:>4} {row['t_start_s']:>9.1f} "
            f"{row['qps']:>9.1f} {row['errors']:>7.0f} "
            f"{row['mean_latency_s'] * 1e3:>10.3f} "
            f"{row['p99_s'] * 1e3:>9.3f}"
        )
    if len(doc["windows"]) > top:
        lines.append(f"... {len(doc['windows']) - top} more window(s)")
    for name, svc in list(doc.get("services", {}).items())[:8]:
        lines.append(
            f"{name:<24} util {sparkline(svc['utilization'])} "
            f"peak {svc['peak_utilization']:.2f} "
            f"@w{svc['peak_window']}"
        )
    cv = doc.get("convoy") or {}
    if cv:
        lines.append(
            f"convoy: entry-wait vs leaf-busy correlation "
            f"{cv['correlation']:+.3f}"
            + (" (convoy suspected)" if cv.get("convoy_suspected")
               else "")
        )
    return "\n".join(lines)


_SPARK = "▁▂▃▄▅▆▇█"


def sparkline(values: Sequence[float]) -> str:
    """A unicode sparkline of one windowed series."""
    vs = [float(v) for v in values]
    if not vs:
        return ""
    hi = max(vs)
    if hi <= 0:
        return _SPARK[0] * len(vs)
    return "".join(
        _SPARK[min(int(v / hi * (len(_SPARK) - 1) + 1e-9),
                   len(_SPARK) - 1)]
        for v in vs
    )


# -- Prometheus / monitor surfaces -------------------------------------------


def prometheus_text(compiled: CompiledGraph, tl: TimelineSummary) -> str:
    """Timestamped Prometheus exposition: each window renders as one
    scrape-interval sample (value + ``<timestamp_ms>``), matching the
    reference's collection semantics — counters are cumulative across
    windows, gauges are per-window levels."""
    from isotope_tpu.metrics.prometheus import timestamped_series

    names = compiled.services.names
    dt = float(tl.window_s)
    W = tl.num_windows
    ts = [int(round((w + 1) * dt * 1e3)) for w in range(W)]
    out: List[str] = []

    def counter_rows(series_by_label):
        rows = []
        for label, series in series_by_label:
            cum = np.cumsum(_np(series))
            rows.extend(
                (label, float(cum[w]), ts[w]) for w in range(W)
            )
        return rows

    def gauge_rows(series_by_label):
        rows = []
        for label, series in series_by_label:
            rows.extend(
                (label, float(series[w]), ts[w]) for w in range(W)
            )
        return rows

    timestamped_series(
        out, "timeline_client_requests_total",
        "Client requests arriving, cumulative per sim-time window.",
        "counter", counter_rows([({}, tl.arrivals)]),
    )
    timestamped_series(
        out, "timeline_client_errors_total",
        "Client-visible 500s, cumulative per sim-time window.",
        "counter", counter_rows([({}, tl.errors)]),
    )
    svc_arr = _np(tl.svc_arrivals)
    svc_err = _np(tl.svc_errors)
    inflight = _np(tl.svc_inflight_s) / dt
    busy = _np(tl.svc_busy_s)
    queue = np.maximum(_np(tl.svc_inflight_s) - busy, 0.0) / dt
    reps = np.asarray(compiled.services.replicas, np.float64)
    active = [
        s for s in range(compiled.num_services)
        if svc_arr[s].sum() > 0 or busy[s].sum() > 0
    ]
    timestamped_series(
        out, "timeline_service_requests_total",
        "Hops arriving at this service, cumulative per window.",
        "counter",
        counter_rows(
            [({"service": names[s]}, svc_arr[s]) for s in active]
        ),
    )
    timestamped_series(
        out, "timeline_service_errors_total",
        "Hop 500s at this service, cumulative per window.",
        "counter",
        counter_rows(
            [({"service": names[s]}, svc_err[s]) for s in active]
        ),
    )
    timestamped_series(
        out, "timeline_service_inflight",
        "Mean in-flight requests at this service per window.",
        "gauge",
        gauge_rows(
            [({"service": names[s]}, inflight[s]) for s in active]
        ),
    )
    timestamped_series(
        out, "timeline_service_queue_depth",
        "Mean queued (waiting) requests at this service per window.",
        "gauge",
        gauge_rows(
            [({"service": names[s]}, queue[s]) for s in active]
        ),
    )
    timestamped_series(
        out, "timeline_service_utilization",
        "Busy-time utilization of this service per window.",
        "gauge",
        gauge_rows(
            [
                (
                    {"service": names[s]},
                    busy[s] / (dt * max(float(reps[s]), 1.0)),
                )
                for s in active
            ]
        ),
    )
    return "\n".join(out) + ("\n" if out else "")


def window_stores(compiled: CompiledGraph, tl: TimelineSummary):
    """Per-window :class:`~isotope_tpu.metrics.query.MetricStore`s —
    each window rendered as the service series a scraper would have
    seen over that interval, so the alarm queries (metrics/alarms.py)
    evaluate per window and an SLO breach gets a sim-time ONSET.

    Yields ``(window_index, sim_time_s, store)``; ``sim_time_s`` is the
    window's end (the scrape instant).

    ``service_cpu_usage_seconds_total`` is the BUSY-OCCUPANCY integral
    (server-side time excluding the queueing wait), which includes
    script sleeps and downstream blocking — an upper bound on CPU
    burn, so size CPU alarm limits against occupancy, not raw vCPU.
    """
    from isotope_tpu.metrics.query import MetricStore, Sample

    names = compiled.services.names
    dt = float(tl.window_s)
    svc_arr = _np(tl.svc_arrivals)
    svc_err = _np(tl.svc_errors)
    busy = _np(tl.svc_busy_s)
    inflight = _np(tl.svc_inflight_s)

    # resident payload estimate per in-flight request (the
    # resource_text working-set model, metrics/prometheus.py)
    req_sum = np.zeros(len(names))
    req_cnt = np.zeros(len(names))
    np.add.at(req_sum, compiled.hop_service, compiled.hop_request_size)
    np.add.at(req_cnt, compiled.hop_service, 1.0)
    payload = (
        compiled.services.response_size.astype(np.float64)
        + req_sum / np.maximum(req_cnt, 1.0)
    )

    for w in range(tl.num_windows):
        samples: List[Sample] = []
        for s, name in enumerate(names):
            lbl = {"service": name}
            samples.append(Sample(
                "service_incoming_requests_total", dict(lbl),
                float(svc_arr[s, w]),
            ))
            err = float(svc_err[s, w])
            samples.append(Sample(
                "service_request_duration_seconds_count",
                {"service": name, "code": "500"}, err,
            ))
            samples.append(Sample(
                "service_request_duration_seconds_count",
                {"service": name, "code": "200"},
                max(float(svc_arr[s, w]) - err, 0.0),
            ))
            samples.append(Sample(
                "service_cpu_usage_seconds_total", dict(lbl),
                float(busy[s, w]),
            ))
            samples.append(Sample(
                "service_memory_working_set_bytes", dict(lbl),
                float(inflight[s, w] / dt * payload[s]),
            ))
        yield w, (w + 1) * dt, MetricStore(samples, duration_s=dt)
