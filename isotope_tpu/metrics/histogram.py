"""Device-friendly latency histograms with quantile recovery.

Fortio derives its reported percentiles from a bucketed histogram rather
than a full sort (runner.py:136-137 sets 1ms resolution).  We keep the
same idea but with log-spaced buckets — 1us..10s at ~0.6% relative width —
so a single psum-merged (B,) vector supports p50..p999 recovery within a
fraction of a percent at any scale, which is what the sharded path reduces
across devices instead of gathering per-request latencies.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

NUM_BUCKETS = 2048
_LO, _HI = 1e-6, 10.0  # seconds

# bucket i covers [EDGES[i], EDGES[i+1]); underflow in 0, overflow in last.
# NOTE: bucket_index computes membership via float32 log arithmetic, so a
# value lying exactly on an edge may land in the adjacent bucket — EDGES is
# the nominal layout for quantile recovery, not an exact membership oracle.
EDGES = np.concatenate(
    [[0.0], np.geomspace(_LO, _HI, NUM_BUCKETS - 1), [np.inf]]
)
# The edges are geometric, so the bucket index is arithmetic:
# idx = floor(log(x / LO) / log(r)) + 1 — a searchsorted would binary-search
# with log2(B) rounds of element gathers, which run at ~2 GiB/s on TPU.
_LOG_LO = float(np.log(_LO))
_INV_LOG_R = float((NUM_BUCKETS - 2) / np.log(_HI / _LO))


def bucket_index(latencies: jax.Array) -> jax.Array:
    """Bucket index per latency — pure elementwise math, no gathers."""
    t = (jnp.log(latencies) - _LOG_LO) * _INV_LOG_R
    t = jnp.clip(t, -1.0, NUM_BUCKETS - 2)  # catches 0 / -inf
    idx = jnp.floor(t).astype(jnp.int32) + 1
    # NaN survives clip; keep searchsorted's behavior (overflow bucket)
    return jnp.where(jnp.isnan(t), NUM_BUCKETS - 1, idx)


def latency_histogram(latencies: jax.Array, weights=None) -> jax.Array:
    """Scatter-add latencies (seconds) into the fine log-spaced buckets."""
    idx = bucket_index(latencies)
    w = weights if weights is not None else jnp.ones_like(latencies)
    return jnp.zeros(NUM_BUCKETS, jnp.float32).at[idx].add(w)


def bucket_centers() -> np.ndarray:
    """Representative value per bucket (geometric mean of its edges)."""
    centers = np.empty(NUM_BUCKETS)
    centers[0] = EDGES[1] / 2
    centers[1:-1] = np.sqrt(EDGES[1:-2] * EDGES[2:-1])
    centers[-1] = EDGES[-2]
    return centers


def quantile_from_histogram(hist: np.ndarray, qs) -> np.ndarray:
    """Recover quantiles from bucket counts (geometric-mean bucket value)."""
    hist = np.asarray(hist, np.float64)
    total = hist.sum()
    if total == 0:
        return np.zeros(len(qs))
    cum = np.cumsum(hist)
    idx = np.searchsorted(cum, np.asarray(qs) * total, side="left")
    return bucket_centers()[np.minimum(idx, NUM_BUCKETS - 1)]


def quantile_from_histogram_device(hist: jax.Array, q: float) -> jax.Array:
    """On-device twin of :func:`quantile_from_histogram` for ONE
    quantile over a stack of histograms ``(..., NUM_BUCKETS)``.

    ``searchsorted(cum, q*total, side="left")`` is the count of cumsum
    entries strictly below the target, so the index is a comparison
    reduction — no per-element binary-search gathers (the same reason
    :func:`bucket_index` avoids searchsorted).  The cumsum runs in f32
    on device vs the host's f64, so exact bucket-edge ties may resolve
    one bucket apart from the host answer — every device consumer
    (sim/search.py rank channels) compares members through THIS twin,
    so rankings stay internally consistent.  Empty histograms yield 0
    like the host function.
    """
    hist = jnp.asarray(hist, jnp.float32)
    total = hist.sum(axis=-1, keepdims=True)
    cum = jnp.cumsum(hist, axis=-1)
    idx = jnp.sum((cum < q * total).astype(jnp.int32), axis=-1)
    idx = jnp.minimum(idx, NUM_BUCKETS - 1)
    val = jnp.asarray(bucket_centers(), jnp.float32)[idx]
    return jnp.where(total[..., 0] > 0, val, jnp.float32(0.0))
