"""Metrics / observability layer.

The simulator emits the same series the reference's data plane does, so
existing analysis keeps working (SURVEY.md §5.5):

- the mock service's five Prometheus series with the reference's exact
  bucket layouts (isotope/service/pkg/srv/prometheus/handler.go:27-69) —
  see :mod:`isotope_tpu.metrics.prometheus`;
- Fortio-style result JSON + the benchmark runner's flattened single-line
  schema and CSV (perf/benchmark/runner/fortio.py:38-75,215-232) with its
  trim-window and error-discard semantics — see
  :mod:`isotope_tpu.metrics.fortio`;
- a PromQL-subset query layer over the text exposition
  (perf/benchmark/runner/prom.py:92-126,216-232) — see
  :mod:`isotope_tpu.metrics.query`;
- on-device critical-path blame attribution (per-service wait/self/
  wire/timeout decomposition, conditional tail histograms, top-K
  exemplar mining) — see :mod:`isotope_tpu.metrics.attribution`
  (imported lazily; attribution-off paths never touch it);
- the simulation flight recorder (per-service x per-window throughput
  / occupancy series binned on device, timestamped expositions,
  convoy detection) — see :mod:`isotope_tpu.metrics.timeline`
  (imported lazily; timeline-off paths never touch it).
"""
from isotope_tpu.metrics.prometheus import (
    DURATION_BUCKETS,
    SIZE_BUCKETS,
    MetricsCollector,
    ServiceMetrics,
)
from isotope_tpu.metrics.fortio import (
    METRICS_END_SKIP_DURATION,
    METRICS_START_SKIP_DURATION,
    METRICS_SUMMARY_DURATION,
    convert_data,
    fortio_result,
    fortio_result_from_summary,
    trim_window_summary,
    window_summary_from_summary,
    write_csv,
)
from isotope_tpu.metrics.query import MetricStore, parse_exposition

__all__ = [
    "DURATION_BUCKETS",
    "SIZE_BUCKETS",
    "MetricsCollector",
    "MetricStore",
    "ServiceMetrics",
    "METRICS_START_SKIP_DURATION",
    "METRICS_END_SKIP_DURATION",
    "METRICS_SUMMARY_DURATION",
    "convert_data",
    "fortio_result",
    "fortio_result_from_summary",
    "parse_exposition",
    "trim_window_summary",
    "window_summary_from_summary",
    "write_csv",
]
