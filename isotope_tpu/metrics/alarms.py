"""Alarm assertions over simulated runs — evaluated as Prometheus queries.

The reference's stability gate is ``metrics/check_metrics.py``: a unittest
suite where each check is a PromQL ``Query`` paired with an ``Alarm``
predicate (metrics/prometheus.py:21-29), with standard checks — zero 5xx,
proxy CPU below 50 milli-cores (250 for the service-graph load test,
check_metrics.py:61-102,170-174), memory below limits — run against a
long-lived cluster's Prometheus.

Here the same Query/Alarm shape carries a real query *string*, evaluated
by :class:`~isotope_tpu.metrics.query.MetricStore` against the run's own
text exposition (the five service series plus the sim-side resource
series of ``MetricsCollector.resource_text``) — the alarm layer consumes
exactly what a Prometheus scraper would see, instead of bypassing the
metrics with Python callables.
"""
from __future__ import annotations

import collections
from typing import Callable, List, Sequence

from isotope_tpu.metrics.query import MetricStore

# Same tuple shapes as the reference (metrics/prometheus.py:21-29).
Query = collections.namedtuple(
    "Query", ["description", "query", "alarm", "running_query"]
)
Alarm = collections.namedtuple("Alarm", ["in_alarm", "error_message"])

# check_metrics.py's unit conversions, applied inside the query string
# exactly like the reference's ``... * %f`` formatting (:73-84)
CPU_MILLI = 1000.0
MEM_MB = 1.0 / 2**20


def store_from_summary(collector, summary) -> MetricStore:
    """Build the queryable store for a run: the five service series plus
    the resource series, parsed back from the text exposition."""
    if summary.metrics is None:
        raise ValueError(
            "summary has no metrics; run with a MetricsCollector"
        )
    return MetricStore.from_text(
        collector.full_text(summary), float(summary.end_max)
    )


def standard_queries(
    label: str = "sim",
    cpu_lim: float = 50,
    mem_lim: float = 64,
) -> List[Query]:
    """The reference's standard checks (check_metrics.py:61-102), phrased
    against the sim's series the way the reference phrases them against
    istio/cadvisor series.

    ``cpu_lim`` is in milli-cores, ``mem_lim`` in MiB; the service-graph
    load test overrides them to 250/100 (check_metrics.py:170-174).
    """
    return [
        Query(
            f"{label}: 5xx Requests/s",
            # ≙ sum(rate(istio_requests_total{response_code=~"5.."}[1m]))
            'sum(rate(service_request_duration_seconds_count'
            '{code=~"5.."}[1m]))',
            Alarm(lambda r: r > 0, "There were 5xx errors."),
            None,
        ),
        Query(
            f"{label}: Service CPU",
            # ≙ rate(container_cpu_usage_seconds_total{...}[1m]) * 1000
            "max(sum(rate(service_cpu_usage_seconds_total[1m])) "
            f"by (service)) * {CPU_MILLI!r}",
            Alarm(lambda c: c > cpu_lim, "Service CPU is unexpectedly high."),
            None,
        ),
        Query(
            f"{label}: Service Memory",
            # ≙ max(max_over_time(container_memory_usage_bytes[1m])) * MB
            "max(max_over_time(service_memory_working_set_bytes[1m])) "
            f"* {MEM_MB!r}",
            Alarm(
                lambda m: m > mem_lim, "Service memory is unexpectedly high."
            ),
            None,
        ),
    ]


def requests_sanity(label: str = "sim") -> Query:
    """There must be *some* traffic (check_metrics.py istio_requests_sanity)."""
    return Query(
        f"{label}: Total Requests/s (sanity check)",
        "sum(rate(service_incoming_requests_total[1m]))",
        Alarm(lambda r: r <= 0, "No requests were recorded."),
        None,
    )


def run_queries(
    queries: Sequence[Query],
    store: MetricStore,
    debug: bool = False,
    log: Callable[[str], None] = print,
) -> List[str]:
    """Evaluate queries; return alarm messages (prometheus.py:63-71).

    A ``running_query`` gates the check: evaluate it first and skip the
    check when it returns <= 0 — the scenario isn't deployed
    (check_metrics.py:196-206).
    """
    errors: List[str] = []
    for q in queries:
        if q.running_query is not None and (
            store.query_value(q.running_query) <= 0
        ):
            continue
        value = store.query_value(q.query)
        if q.alarm.in_alarm(value):
            errors.append(f"{q.alarm.error_message} Response: {value}")
        if debug:
            log(f"Testing: {q.description}. Result: {value:f}.")
    return errors
