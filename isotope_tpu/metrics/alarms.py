"""Alarm assertions over simulated runs.

The reference's stability gate is ``metrics/check_metrics.py``: a unittest
suite where each check is a Prometheus ``Query`` paired with an ``Alarm``
predicate (metrics/prometheus.py:21-29), with standard checks — zero 5xx,
proxy CPU below 50 milli-cores (250 for the service-graph load test,
check_metrics.py:61-102,170-174), memory below limits — run against a
long-lived cluster.

Here the same Query/Alarm shape evaluates against a simulated run: the
``query`` field is a callable on a :class:`RunSource` instead of a PromQL
string, and the standard suite derives its values from the event tensors
(5xx counts from the metric scatter, CPU from utilization, memory from a
Little's-law estimate of resident payload buffers).
"""
from __future__ import annotations

import collections
from typing import Callable, List, Sequence

import numpy as np

from isotope_tpu.compiler.program import CompiledGraph
from isotope_tpu.sim.engine import SimResults

# Same tuple shapes as the reference (metrics/prometheus.py:21-29).
Query = collections.namedtuple(
    "Query", ["description", "query", "alarm", "running_query"]
)
Alarm = collections.namedtuple("Alarm", ["in_alarm", "error_message"])

CPU_MILLI = 1000.0
MEM_MB = 1.0 / 2**20


class RunSource:
    """Derived per-run values the standard queries read."""

    def __init__(self, compiled: CompiledGraph, res: SimResults):
        self.compiled = compiled
        self.res = res
        self._sent = np.asarray(res.hop_sent)
        self._err = np.asarray(res.hop_error)
        self._lat = np.asarray(res.hop_latency)
        end = np.asarray(res.client_end)
        self.duration_s = float(end.max()) if len(end) else 0.0

    # -- canned values -----------------------------------------------------

    def rate_5xx(self) -> float:
        """Service-level 5xx per second (client-visible or internal)."""
        if self.duration_s <= 0:
            return 0.0
        return float(self._err.sum()) / self.duration_s

    def total_request_rate(self) -> float:
        if self.duration_s <= 0:
            return 0.0
        return float(self._sent.sum()) / self.duration_s

    def max_cpu_cores(self) -> float:
        """Worst per-service CPU in cores: utilization x replicas."""
        util = np.asarray(self.res.utilization)
        reps = self.compiled.services.replicas
        return float((util * reps).max())

    def max_memory_bytes(self) -> float:
        """Little's-law resident-buffer estimate, worst service.

        In-flight requests at service s = arrival rate x mean sojourn;
        each holds its request + response payload.
        """
        hop_svc = self.compiled.hop_service
        S = self.compiled.num_services
        counts = np.zeros(S)
        np.add.at(counts, hop_svc, self._sent.sum(0))
        lat_sum = np.zeros(S)
        np.add.at(lat_sum, hop_svc, (self._lat * self._sent).sum(0))
        if self.duration_s <= 0:
            return 0.0
        rate = counts / self.duration_s
        mean_lat = np.where(counts > 0, lat_sum / np.maximum(counts, 1), 0.0)
        payload = (
            self.compiled.services.response_size.astype(np.float64)
            + _mean_request_size(self.compiled)
        )
        in_flight = rate * mean_lat
        return float((in_flight * payload).max())


def _mean_request_size(compiled: CompiledGraph) -> np.ndarray:
    sizes = np.zeros(compiled.num_services)
    counts = np.zeros(compiled.num_services)
    np.add.at(sizes, compiled.hop_service, compiled.hop_request_size)
    np.add.at(counts, compiled.hop_service, 1.0)
    return sizes / np.maximum(counts, 1.0)


def standard_queries(
    label: str = "sim",
    cpu_lim: float = 50,
    mem_lim: float = 64,
) -> List[Query]:
    """The reference's standard checks (check_metrics.py:61-102).

    ``cpu_lim`` is in milli-cores, ``mem_lim`` in MiB; the service-graph
    load test overrides them to 250/100 (check_metrics.py:170-174).
    """
    return [
        Query(
            f"{label}: 5xx Requests/s",
            lambda s: s.rate_5xx(),
            Alarm(lambda r: r > 0, "There were 5xx errors."),
            None,
        ),
        Query(
            f"{label}: Service CPU",
            lambda s: s.max_cpu_cores() * CPU_MILLI,
            Alarm(lambda c: c > cpu_lim, "Service CPU is unexpectedly high."),
            None,
        ),
        Query(
            f"{label}: Service Memory",
            lambda s: s.max_memory_bytes() * MEM_MB,
            Alarm(
                lambda m: m > mem_lim, "Service memory is unexpectedly high."
            ),
            None,
        ),
    ]


def requests_sanity(label: str = "sim") -> Query:
    """There must be *some* traffic (check_metrics.py istio_requests_sanity)."""
    return Query(
        f"{label}: Total Requests/s (sanity check)",
        lambda s: s.total_request_rate(),
        Alarm(lambda r: r <= 0, "No requests were recorded."),
        None,
    )


def run_queries(
    queries: Sequence[Query],
    source: RunSource,
    debug: bool = False,
    log: Callable[[str], None] = print,
) -> List[str]:
    """Evaluate queries; return alarm messages (prometheus.py:63-71)."""
    errors: List[str] = []
    for q in queries:
        if q.running_query is not None and not q.running_query(source):
            continue  # scenario not deployed (check_metrics.py:196-206)
        value = q.query(source)
        if q.alarm.in_alarm(value):
            errors.append(f"{q.alarm.error_message} Response: {value}")
        if debug:
            log(f"Testing: {q.description}. Result: {value:f}.")
    return errors
