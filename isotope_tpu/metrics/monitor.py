"""Monitor-status sink: the alert-webhook analogue.

The reference's release-qual path wires Alertmanager to a webhook
(perf/stability/alertmanager/webhook.go:26-56) that re-queries
Prometheus to confirm each alert and writes MonitorStatus rows to Cloud
Spanner for the eng.istio.io dashboard.  The simulation analogue:
evaluate the alarm queries against a run's metric store and append one
MonitorStatus row per check — confirmed by re-evaluating the query the
way the webhook re-queries before writing (a flapping source read
between evaluations is recorded as INCONCLUSIVE, not ALARM) — to a
JSONL sink any dashboard can ingest.
"""
from __future__ import annotations

import dataclasses
import json
import pathlib
from typing import Iterable, List, Optional, Sequence, Tuple

from isotope_tpu.metrics.alarms import Query
from isotope_tpu.metrics.query import MetricStore

STATUS_OK = "OK"
STATUS_ALARM = "ALARM"
STATUS_INCONCLUSIVE = "INCONCLUSIVE"


@dataclasses.dataclass(frozen=True)
class MonitorStatus:
    """One check outcome (webhook.go's Spanner row shape: monitor name,
    status, detail, and the observed value).

    ``window_index`` / ``sim_time_s`` localize a per-window evaluation
    (the timeline recorder's scrape sequence) on the sim-time axis —
    an SLO breach gets an ONSET, not just a run-level verdict.  Legacy
    run-level rows leave both ``None``; JSONL rows written before the
    fields existed read back with the same defaults.
    """

    monitor: str
    status: str
    value: float
    detail: str
    run_label: str = ""
    window_index: Optional[int] = None
    sim_time_s: Optional[float] = None

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self))


def evaluate(
    queries: Sequence[Query],
    store: MetricStore,
    run_label: str = "",
    window_index: Optional[int] = None,
    sim_time_s: Optional[float] = None,
) -> List[MonitorStatus]:
    """Evaluate every check, re-querying to confirm alarms.

    ``window_index`` / ``sim_time_s`` stamp every produced row when the
    store covers one timeline window instead of a whole run."""
    rows: List[MonitorStatus] = []
    for q in queries:
        if q.running_query is not None and (
            store.query_value(q.running_query) <= 0
        ):
            continue
        value = store.query_value(q.query)
        if not q.alarm.in_alarm(value):
            rows.append(
                MonitorStatus(q.description, STATUS_OK, float(value), "",
                              run_label, window_index, sim_time_s)
            )
            continue
        # the webhook re-queries before writing an alarm row; a source
        # that stopped alarming between reads is flapping, not firing
        confirm = store.query_value(q.query)
        if q.alarm.in_alarm(confirm):
            rows.append(
                MonitorStatus(
                    q.description, STATUS_ALARM, float(confirm),
                    q.alarm.error_message, run_label,
                    window_index, sim_time_s,
                )
            )
        else:
            rows.append(
                MonitorStatus(
                    q.description, STATUS_INCONCLUSIVE, float(confirm),
                    "alarm did not confirm on re-query", run_label,
                    window_index, sim_time_s,
                )
            )
    return rows


def evaluate_windows(
    queries: Sequence[Query],
    window_stores: Iterable[Tuple[int, float, MetricStore]],
    run_label: str = "",
) -> List[MonitorStatus]:
    """Evaluate the checks once per timeline window.

    ``window_stores`` yields ``(window_index, sim_time_s, store)``
    (the shape :func:`isotope_tpu.metrics.timeline.window_stores`
    produces); every returned row carries its window's sim-time stamp,
    so ``first_alarm_onset`` can report when a breach STARTED."""
    rows: List[MonitorStatus] = []
    for w, t, store in window_stores:
        rows.extend(
            evaluate(queries, store, run_label,
                     window_index=int(w), sim_time_s=float(t))
        )
    return rows


def first_alarm_onset(
    rows: Sequence[MonitorStatus],
) -> Optional[MonitorStatus]:
    """The earliest-window ALARM row, or None — the sim-time onset of
    the first SLO breach."""
    alarms = [
        r for r in rows
        if r.status == STATUS_ALARM and r.window_index is not None
    ]
    return min(alarms, key=lambda r: r.window_index) if alarms else None


class MonitorSink:
    """Append-only JSONL sink (the Spanner-table stand-in)."""

    def __init__(self, path):
        self.path = pathlib.Path(path)

    def write(self, rows: Sequence[MonitorStatus]) -> None:
        with open(self.path, "a") as f:
            for row in rows:
                f.write(row.to_json() + "\n")

    def read(self) -> List[MonitorStatus]:
        if not self.path.exists():
            return []
        out = []
        for line in self.path.read_text().splitlines():
            if line.strip():
                out.append(MonitorStatus(**json.loads(line)))
        return out

    def alarms(self) -> List[MonitorStatus]:
        return [r for r in self.read() if r.status == STATUS_ALARM]


def monitor_run(
    store: MetricStore,
    sink: MonitorSink,
    queries: Sequence[Query],
    run_label: str = "",
) -> List[MonitorStatus]:
    """Evaluate + persist; returns the rows written."""
    rows = evaluate(queries, store, run_label)
    sink.write(rows)
    return rows
