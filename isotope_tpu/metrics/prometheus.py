"""Prometheus-compatible service metrics.

Replicates the reference mock service's five series with identical names,
labels, and bucket layouts (isotope/service/pkg/srv/prometheus/handler.go:
27-69):

- ``service_incoming_requests_total``            counter
- ``service_outgoing_requests_total``            counter, by destination
- ``service_outgoing_request_size``              histogram, by destination
- ``service_request_duration_seconds``           histogram, by code
- ``service_response_size``                      histogram, by code

In the reference each pod exposes its own ``/metrics`` and Prometheus adds
pod identity at scrape time (kubernetes.go:49-52); the simulator has no
pods, so every series carries an explicit ``service`` label instead.

Collection is a jit-friendly scatter-add over the (request x hop) event
tensor; exposition renders the standard text format so any Prometheus
parser/scraper tooling keeps working.
"""
from __future__ import annotations

from typing import Dict, List, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from isotope_tpu.compiler.program import CompiledGraph
from isotope_tpu.sim.engine import SimResults

# srv/prometheus/handler.go:27-31 — 32 buckets, 7ms..500ms.
DURATION_BUCKETS = np.asarray(
    [
        0.007, 0.008, 0.009, 0.01, 0.011, 0.012, 0.014, 0.016, 0.018, 0.02,
        0.025, 0.03, 0.035, 0.04, 0.045, 0.05, 0.06, 0.07, 0.08, 0.09, 0.1,
        0.12, 0.14, 0.16, 0.18, 0.2, 0.25, 0.3, 0.35, 0.4, 0.45, 0.5,
    ],
    np.float64,
)

# srv/prometheus/handler.go:32-35 — decade buckets 1B..1GB.
SIZE_BUCKETS = np.asarray([10.0 ** e for e in range(10)], np.float64)

# The client that drives the entrypoint (fortio_client.go:28-78).
CLIENT_NAME = "fortio-client"

_NB = len(DURATION_BUCKETS) + 1  # +overflow (+Inf)


def escape_label_value(value: str) -> str:
    """Prometheus text-format label-value escaping (backslash, quote,
    newline — the exposition-format spec's three escapes)."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def render_labels(labels: Dict[str, str]) -> str:
    """``{a="x",b="y"}`` with escaped values; empty dict renders
    nothing."""
    if not labels:
        return ""
    body = ",".join(
        f'{k}="{escape_label_value(v)}"' for k, v in labels.items()
    )
    return "{" + body + "}"


def timestamped_series(
    out: List[str],
    name: str,
    help_text: str,
    type_: str,
    rows,
) -> None:
    """Append one metric family of TIMESTAMPED samples to ``out``.

    ``rows`` is an iterable of ``(labels: dict, value, timestamp_ms)``
    — the exposition-format's optional trailing timestamp, which lets
    one scrape carry a whole time series (each sim-time window renders
    as the sample a scrape at that instant would have returned).  Rows
    render in the given order; keep them (labels, then window) sorted
    so the exposition is deterministic.
    """
    out.append(f"# HELP {name} {help_text}")
    out.append(f"# TYPE {name} {type_}")
    for labels, value, ts_ms in rows:
        out.append(
            f"{name}{render_labels(labels)} {value:.10g} {int(ts_ms)}"
        )


class ServiceMetrics(NamedTuple):
    """Device-side accumulators (all counts are float32 for scatter-adds)."""

    incoming_total: jax.Array        # (S,)
    outgoing_total: jax.Array        # (E,) per static call edge
    outgoing_size_hist: jax.Array    # (E, len(SIZE_BUCKETS)+1)
    outgoing_size_sum: jax.Array     # (E,)
    duration_hist: jax.Array         # (S, 2, _NB) code axis: 0=200, 1=500
    duration_sum: jax.Array          # (S, 2)
    response_size_hist: jax.Array    # (S, 2, len(SIZE_BUCKETS)+1)
    response_size_sum: jax.Array     # (S, 2)

    def __add__(self, other: "ServiceMetrics") -> "ServiceMetrics":
        return jax.tree.map(jnp.add, self, other)


class MetricsCollector:
    """Compiled-topology-specific metric reduction.

    The hop -> (source, destination) edge map is static, so outgoing
    counters aggregate with one segment-sum.  Edge 0 is always the client
    -> entrypoint edge.
    """

    def __init__(self, compiled: CompiledGraph):
        self.compiled = compiled
        src = np.where(
            compiled.hop_parent >= 0,
            compiled.hop_service[np.maximum(compiled.hop_parent, 0)],
            -1,  # client
        )
        dst = compiled.hop_service
        pairs: List[Tuple[int, int]] = []
        pair_idx: Dict[Tuple[int, int], int] = {}
        hop_edge = np.zeros(compiled.num_hops, np.int32)
        for h in range(compiled.num_hops):
            p = (int(src[h]), int(dst[h]))
            if p not in pair_idx:
                pair_idx[p] = len(pairs)
                pairs.append(p)
            hop_edge[h] = pair_idx[p]
        self.edges: List[Tuple[int, int]] = pairs
        self._hop_edge = jnp.asarray(hop_edge)
        # static per-hop byte sizes -> static size-bucket index
        self._hop_size_bucket = jnp.asarray(
            np.searchsorted(SIZE_BUCKETS, compiled.hop_request_size, "left"),
            jnp.int32,
        )
        self._hop_service = jnp.asarray(compiled.hop_service)
        resp = compiled.services.response_size.astype(np.float64)
        self._svc_resp_bucket = jnp.asarray(
            np.searchsorted(SIZE_BUCKETS, resp, "left"), jnp.int32
        )
        self._svc_resp_size = jnp.asarray(resp, jnp.float32)

    # -- device-side collection (jittable) --------------------------------

    def zeros(self) -> ServiceMetrics:
        """An all-zero ServiceMetrics with this topology's shapes — the
        identity of the ``+`` merge (the overlap pipeline's primer,
        parallel/sharded.py)."""
        S, E = self.compiled.num_services, len(self.edges)
        nsb = len(SIZE_BUCKETS) + 1
        return ServiceMetrics(
            incoming_total=jnp.zeros(S),
            outgoing_total=jnp.zeros(E),
            outgoing_size_hist=jnp.zeros((E, nsb)),
            outgoing_size_sum=jnp.zeros(E),
            duration_hist=jnp.zeros((S, 2, _NB)),
            duration_sum=jnp.zeros((S, 2)),
            response_size_hist=jnp.zeros((S, 2, nsb)),
            response_size_sum=jnp.zeros((S, 2)),
        )

    def collect(self, res: SimResults) -> ServiceMetrics:
        c = self.compiled
        S, E = c.num_services, len(self.edges)
        sent = res.hop_sent
        sent_f = sent.astype(jnp.float32)
        code = res.hop_error.astype(jnp.int32)  # 0 => 200, 1 => 500

        incoming = jnp.zeros(S).at[self._hop_service].add(sent_f.sum(0))
        outgoing = jnp.zeros(E).at[self._hop_edge].add(sent_f.sum(0))

        out_size = (
            jnp.zeros((E, len(SIZE_BUCKETS) + 1))
            .at[self._hop_edge, self._hop_size_bucket]
            .add(sent_f.sum(0))
        )
        out_size_sum = (
            jnp.zeros(E)
            .at[self._hop_edge]
            .add(sent_f.sum(0) * jnp.asarray(
                self.compiled.hop_request_size, jnp.float32))
        )

        # duration histogram: scatter every sent hop into (svc, code, bucket)
        # bucket index by counting edges below x — 32 fused compares beat a
        # binary-search gather (element gathers run ~2 GiB/s on TPU)
        edges = jnp.asarray(DURATION_BUCKETS, jnp.float32)
        dbuckets = (
            (res.hop_latency[..., None] > edges)
            .sum(-1)
            .astype(jnp.int32)
        )
        svc = jnp.broadcast_to(self._hop_service, sent.shape)
        dur_hist = (
            jnp.zeros((S, 2, _NB))
            .at[svc, code, dbuckets]
            .add(sent_f)
        )
        dur_sum = (
            jnp.zeros((S, 2))
            .at[svc, code]
            .add(jnp.where(sent, res.hop_latency, 0.0))
        )

        rbucket = jnp.broadcast_to(self._svc_resp_bucket[c.hop_service], sent.shape)
        resp_hist = (
            jnp.zeros((S, 2, len(SIZE_BUCKETS) + 1))
            .at[svc, code, rbucket]
            .add(sent_f)
        )
        resp_sum = (
            jnp.zeros((S, 2))
            .at[svc, code]
            .add(jnp.where(sent, self._svc_resp_size[c.hop_service], 0.0))
        )
        return ServiceMetrics(
            incoming_total=incoming,
            outgoing_total=outgoing,
            outgoing_size_hist=out_size,
            outgoing_size_sum=out_size_sum,
            duration_hist=dur_hist,
            duration_sum=dur_sum,
            response_size_hist=resp_hist,
            response_size_sum=resp_sum,
        )

    # -- host-side exposition ----------------------------------------------

    def full_text(self, summary) -> str:
        """The complete exposition for a run summary: the five service
        series plus the sim-side resource series — what a scraper (and
        the alarm queries) should see.  A summary without collector
        metrics (ensemble fleet runs keep the per-service series out
        of the vmapped program) renders the resource series only."""
        if summary.metrics is None:
            return self.resource_text(
                None, summary.utilization, float(summary.end_max)
            )
        return self.to_text(summary.metrics) + self.resource_text(
            summary.metrics, summary.utilization, float(summary.end_max)
        )

    def resource_text(self, m: ServiceMetrics, utilization,
                      duration_s: float) -> str:
        """Render the sim-side resource series — the counterpart of the
        cadvisor metrics the reference's analysis queries
        (prom.py:116-126: ``container_cpu_usage_seconds_total``,
        ``container_memory_usage_bytes``):

        - ``service_cpu_usage_seconds_total``: CPU-seconds consumed per
          service over the run = utilization x replicas x duration;
        - ``service_memory_working_set_bytes``: Little's-law resident
          payload estimate — in-flight requests (arrival rate x mean
          sojourn) each holding request + response buffers.
        """
        names = self.compiled.services.names
        reps = np.asarray(self.compiled.services.replicas, np.float64)
        util = np.asarray(utilization, np.float64)
        cpu_s = util * reps * float(duration_s)

        if m is None:
            # no collector series (ensemble fleet summaries): the
            # memory estimate's rate/latency inputs are unavailable
            inc = np.zeros(len(names))
            rate = np.zeros(len(names))
            mean_lat = np.zeros(len(names))
        else:
            inc = np.asarray(m.incoming_total, np.float64)
            lat_sum = np.asarray(m.duration_sum, np.float64).sum(1)
            rate = (
                inc / duration_s if duration_s > 0
                else np.zeros_like(inc)
            )
            mean_lat = np.where(
                inc > 0, lat_sum / np.maximum(inc, 1.0), 0.0
            )
        # mean request payload arriving at each service (static per hop)
        req_sum = np.zeros(len(names))
        req_cnt = np.zeros(len(names))
        np.add.at(req_sum, self.compiled.hop_service,
                  self.compiled.hop_request_size)
        np.add.at(req_cnt, self.compiled.hop_service, 1.0)
        payload = (
            self.compiled.services.response_size.astype(np.float64)
            + req_sum / np.maximum(req_cnt, 1.0)
        )
        mem = rate * mean_lat * payload

        out: List[str] = []
        out.append(
            "# HELP service_cpu_usage_seconds_total Simulated CPU seconds"
            " consumed by this service."
        )
        out.append("# TYPE service_cpu_usage_seconds_total counter")
        for s, name in enumerate(names):
            out.append(
                f'service_cpu_usage_seconds_total{{service="{name}"}}'
                f" {cpu_s[s]:.10g}"
            )
        out.append(
            "# HELP service_memory_working_set_bytes Estimated resident"
            " payload bytes held by in-flight requests."
        )
        out.append("# TYPE service_memory_working_set_bytes gauge")
        for s, name in enumerate(names):
            out.append(
                f'service_memory_working_set_bytes{{service="{name}"}}'
                f" {mem[s]:.10g}"
            )
        return "\n".join(out) + "\n"

    def to_text(self, m: ServiceMetrics) -> str:
        """Render the Prometheus text exposition format."""
        names = self.compiled.services.names

        def ename(i: int) -> str:
            return CLIENT_NAME if i < 0 else names[i]

        out: List[str] = []

        out.append(
            "# HELP service_incoming_requests_total Number of requests sent"
            " to this service."
        )
        out.append("# TYPE service_incoming_requests_total counter")
        inc = np.asarray(m.incoming_total)
        for s, name in enumerate(names):
            out.append(
                f'service_incoming_requests_total{{service="{name}"}}'
                f" {inc[s]:.10g}"
            )

        out.append(
            "# HELP service_outgoing_requests_total Number of requests sent"
            " from this service."
        )
        out.append("# TYPE service_outgoing_requests_total counter")
        outc = np.asarray(m.outgoing_total)
        for e, (src, dst) in enumerate(self.edges):
            out.append(
                "service_outgoing_requests_total{"
                f'service="{ename(src)}",destination_service="{ename(dst)}"'
                f"}} {outc[e]:.10g}"
            )

        self._histogram(
            out,
            "service_outgoing_request_size",
            "Size in bytes of requests sent from this service.",
            SIZE_BUCKETS,
            np.asarray(m.outgoing_size_hist),
            np.asarray(m.outgoing_size_sum),
            [
                (
                    f'service="{ename(src)}",'
                    f'destination_service="{ename(dst)}"'
                )
                for src, dst in self.edges
            ],
        )

        dur = np.asarray(m.duration_hist)
        dur_sum = np.asarray(m.duration_sum)
        labels, rows, sums = self._by_code(names, dur, dur_sum)
        self._histogram(
            out,
            "service_request_duration_seconds",
            "Duration in seconds it took to serve requests to this service.",
            DURATION_BUCKETS,
            rows,
            sums,
            labels,
        )

        resp = np.asarray(m.response_size_hist)
        resp_sum = np.asarray(m.response_size_sum)
        labels, rows, sums = self._by_code(names, resp, resp_sum)
        self._histogram(
            out,
            "service_response_size",
            "Size in bytes of responses sent from this service.",
            SIZE_BUCKETS,
            rows,
            sums,
            labels,
        )
        return "\n".join(out) + "\n"

    @staticmethod
    def _by_code(names, hist, sums):
        labels, rows, row_sums = [], [], []
        for s, name in enumerate(names):
            for ci, code in enumerate(("200", "500")):
                labels.append(f'service="{name}",code="{code}"')
                rows.append(hist[s, ci])
                row_sums.append(sums[s, ci])
        return labels, np.asarray(rows), np.asarray(row_sums)

    @staticmethod
    def _histogram(out, name, help_text, buckets, rows, sums, labels):
        out.append(f"# HELP {name} {help_text}")
        out.append(f"# TYPE {name} histogram")
        rows = np.asarray(rows)
        for row, s, label in zip(rows, np.asarray(sums), labels):
            cum = np.cumsum(row)
            for le, c in zip(buckets, cum[:-1]):
                out.append(f'{name}_bucket{{{label},le="{le:g}"}} {c:.10g}')
            out.append(f'{name}_bucket{{{label},le="+Inf"}} {cum[-1]:.10g}')
            out.append(f"{name}_sum{{{label}}} {s:.10g}")
            out.append(f"{name}_count{{{label}}} {cum[-1]:.10g}")
