"""Experiment orchestration.

The TPU-native replacement for the reference's two drivers:

- ``isotope/run_tests.py``: topology x environment pipeline configured by
  TOML (example-config.toml schema) — here the "cluster" is the local
  device mesh and "deploying" a topology is compiling it;
- ``perf/benchmark/runner/runner.py``: the conn x qps sweep grid with
  labeled runs and CSV/JSONL output.
"""
from isotope_tpu.runner.config import (
    EnvironmentModel,
    ExperimentConfig,
    load_toml,
)
from isotope_tpu.runner.run import RunResult, run_experiment

__all__ = [
    "EnvironmentModel",
    "ExperimentConfig",
    "load_toml",
    "RunResult",
    "run_experiment",
]
