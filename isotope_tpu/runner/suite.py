"""The benchmark-suite pipeline: run configs -> collect -> publish.

The reference's CI entry (perf/benchmark/run_benchmark_job.sh) stands a
cluster up, runs every enabled config (run_perf_test.conf toggles),
collects CSVs and flame graphs, and uploads the artifact tree to
``gs://istio-build/perf/<date>_<loadgen>_<branch>_<ver>/`` — the id
format the dashboard scrapes (perf_dashboard/helpers/download.py:56-62).

The simulation suite keeps the same pipeline shape without the cluster:
each experiment TOML runs (checkpointed, resumable) into its own
subdirectory of one publish id, every run's metrics are evaluated
against the standard alarm suite into a monitor-status sink, and a
per-config HTML report plus a manifest round out the artifact tree.
"""
from __future__ import annotations

import dataclasses
import json
import pathlib
from datetime import datetime, timezone
from typing import List, Optional, Sequence

from isotope_tpu.metrics.alarms import (
    requests_sanity,
    standard_queries,
)
from isotope_tpu.metrics.monitor import MonitorSink, monitor_run
from isotope_tpu.metrics.query import MetricStore
from isotope_tpu.runner.config import load_toml
from isotope_tpu.runner.run import run_experiment


def suite_id(
    labels: str = "master",
    loadgen: str = "sim",
    version: str = "dev",
    date: Optional[datetime] = None,
) -> str:
    """``<date>_<loadgen>_<branch>_<ver>`` (download.py:56-62 format)."""
    date = date or datetime.now(timezone.utc)
    return f"{date:%Y%m%d}_{loadgen}_{labels}_{version}"


@dataclasses.dataclass
class SuiteResult:
    publish_dir: pathlib.Path
    manifest: dict


def run_suite(
    config_paths: Sequence[str],
    out_root,
    id: Optional[str] = None,
    labels: str = "master",
    cpu_limit_mcores: float = 50.0,
    mem_limit_mib: float = 64.0,
    progress=None,
    resume: bool = True,
    policy=None,
    vet=None,
) -> SuiteResult:
    """Run every config, publish one artifact tree, monitor every run."""
    cfgs = [(p, load_toml(p)) for p in config_paths]
    # the publish id carries the loadgen name (download.py:56-62:
    # `<date>_<loadgen>_<branch>_<ver>`); a mixed suite is labeled as such
    loadgens = {c.loadgen for _, c in cfgs} or {"sim"}
    loadgen = loadgens.pop() if len(loadgens) == 1 else "mixed"
    sid = id or suite_id(labels=labels, loadgen=loadgen)
    publish = pathlib.Path(out_root) / sid
    publish.mkdir(parents=True, exist_ok=True)
    # the sink is append-only and every invocation re-evaluates all runs
    # (checkpoint-restored included), so a re-run with the same publish
    # id must start from a fresh file or rows duplicate
    sink_path = publish / "monitor_status.jsonl"
    sink_path.unlink(missing_ok=True)
    sink = MonitorSink(sink_path)

    configs_out: List[dict] = []
    total_runs = 0
    for cfg_path, cfg in cfgs:
        stem = pathlib.Path(cfg_path).stem
        out_dir = publish / stem
        results = run_experiment(
            cfg, out_dir=str(out_dir), progress=progress, resume=resume,
            policy=policy, vet=vet,
        )
        queries = standard_queries(
            stem, cpu_lim=cpu_limit_mcores, mem_lim=mem_limit_mib
        ) + [requests_sanity(stem)]
        alarm_count = 0
        for r in results:
            if not r.prometheus_text:
                continue
            # the fortio JSON carries nanoseconds; the flat CSV field is
            # truncated to integer seconds, which zeroes every rate()
            # for sub-second runs (and with it the CPU/mem alarms)
            duration = (
                float(r.fortio_json.get("ActualDuration", 0) or 0) / 1e9
            )
            store = MetricStore.from_text(r.prometheus_text, duration)
            rows = monitor_run(store, sink, queries, run_label=r.label)
            alarm_count += sum(1 for row in rows if row.status == "ALARM")

        # per-config dashboard page
        from isotope_tpu.report import write_report

        write_report(
            out_dir, out_dir / "report.html",
            title=f"{sid} — {stem}",
        )
        configs_out.append(
            {
                "config": str(cfg_path),
                "name": stem,
                "runs": len(results),
                "discarded": sum(
                    1 for r in results if r.window.discarded
                ),
                "alarms": alarm_count,
                # engine-level resilience outcomes: cases the supervisor
                # could not recover (retried on the next resume) and
                # cases served degraded (counted, never silent)
                "failed": sum(1 for r in results if r.failed),
                "degraded": sum(
                    1 for r in results if r.degraded_to is not None
                ),
            }
        )
        total_runs += len(results)

    manifest = {
        "id": sid,
        "loadgen": loadgen,
        "configs": configs_out,
        "total_runs": total_runs,
        "total_alarms": sum(c["alarms"] for c in configs_out),
        "total_failed": sum(c["failed"] for c in configs_out),
        "total_degraded": sum(c["degraded"] for c in configs_out),
    }
    with open(publish / "manifest.json", "w") as f:
        json.dump(manifest, f, indent=2)
    return SuiteResult(publish_dir=publish, manifest=manifest)
