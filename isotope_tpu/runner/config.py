"""Experiment configuration.

TOML schema follows the reference's ``isotope/example-config.toml`` where
it maps onto simulation (topology_paths, environments, client
qps/duration/num_concurrent_connections); the cluster/istio/image blocks —
GKE deployment detail — are replaced by a ``[sim]`` block (model
parameters, seed, mesh shape) and per-environment overlays.

Environments: the reference runs each topology twice, bare ("NONE") and
meshed ("ISTIO", Envoy sidecars injected around every pod,
kubernetes.go:150-157).  The simulator models the mesh as extra per-edge
latency and per-hop proxy CPU — both explicit, overridable knobs.
"""
from __future__ import annotations

import dataclasses
import pathlib

try:
    import tomllib
except ModuleNotFoundError:  # Python < 3.11: the tomli backport is the
    import tomli as tomllib  # same parser under its pre-stdlib name
from typing import Dict, List, Optional, Tuple

from isotope_tpu.models.errors import config_path
from isotope_tpu.sim.config import (
    ChaosEvent,
    MtlsSchedule,
    bounce_schedule,
    LoadModel,
    SimParams,
    TrafficSplit,
)
from isotope_tpu.utils import duration as dur


# One Envoy traversal, one way — the per-pass tax underlying the
# baseline-vs-sidecar deltas of the twopods benchmarks
# (perf/benchmark/README.md's mode comparisons).
DEFAULT_PROXY_LATENCY_S = 250e-6


@dataclasses.dataclass(frozen=True)
class EnvironmentModel:
    """How an environment (service mesh flavor) perturbs the data plane.

    Models the reference's 5-way sidecar-mode matrix
    (perf/benchmark/runner/runner.py:93-99 port table, :178-197
    mode -> URI) as direction-aware per-edge proxy passes:

    - ``client_proxy``: the *caller's* outbound Envoy on every edge
      (fortio client included) — the "clientsidecar" mode;
    - ``server_proxy``: the *callee's* inbound Envoy on every edge —
      "serversidecar";
    - both flags -> "both"; neither -> "baseline";
    - ``gateway``: entry traffic traverses the ingress gateway (an
      extra Envoy on the client -> entrypoint edge only) — "ingress".

    Each pass adds ``proxy_latency_s`` to the edge's one-way latency in
    both directions (Envoy sits on the request and response path).
    ``extra_hop_latency_s`` is a free-form additional per-edge tax for
    custom environments.
    """

    name: str
    client_proxy: bool = False
    server_proxy: bool = False
    gateway: bool = False
    proxy_latency_s: float = DEFAULT_PROXY_LATENCY_S
    # extra one-way per-edge latency on top of the proxy passes
    extra_hop_latency_s: float = 0.0

    def apply(self, params: SimParams) -> SimParams:
        passes = int(self.client_proxy) + int(self.server_proxy)
        extra = self.extra_hop_latency_s + passes * self.proxy_latency_s
        entry_extra = self.proxy_latency_s if self.gateway else 0.0
        if not extra and not entry_extra:
            return params
        net = params.network
        return dataclasses.replace(
            params,
            network=dataclasses.replace(
                net,
                base_latency_s=net.base_latency_s + extra,
                entry_extra_latency_s=(
                    net.entry_extra_latency_s + entry_extra
                ),
            ),
        )


# The reference's sidecar-mode matrix (runner.py:93-99), plus the
# NONE/ISTIO pair of isotope's run_tests.py (aliases of baseline/both).
DEFAULT_ENVIRONMENTS = {
    "NONE": EnvironmentModel(name="NONE"),
    "ISTIO": EnvironmentModel(
        name="ISTIO", client_proxy=True, server_proxy=True
    ),
    "baseline": EnvironmentModel(name="baseline"),
    "clientsidecar": EnvironmentModel(
        name="clientsidecar", client_proxy=True
    ),
    "serversidecar": EnvironmentModel(
        name="serversidecar", server_proxy=True
    ),
    "both": EnvironmentModel(
        name="both", client_proxy=True, server_proxy=True
    ),
    "ingress": EnvironmentModel(
        name="ingress", server_proxy=True, gateway=True
    ),
}


@dataclasses.dataclass(frozen=True)
class ExperimentConfig:
    topology_paths: Tuple[str, ...]
    environments: Tuple[EnvironmentModel, ...]
    qps: Tuple[Optional[float], ...]     # None == "max"
    connections: Tuple[int, ...]
    duration_s: float
    load_kind: str = "closed"            # fortio's default mode
    # the load-generator identity axis of the reference's benchmark
    # matrix: "fortio" (closed-loop workers, runner.py:255-268) or
    # "nighthawk" (open-loop, runner.py:270-316); flows into the suite
    # publish id `<date>_<loadgen>_<branch>_<ver>`
    loadgen: str = "fortio"
    num_requests: int = 100_000
    seed: int = 0
    cpu_time_s: float = SimParams().cpu_time_s
    service_time: str = SimParams().service_time
    service_time_param: float = SimParams().service_time_param
    mesh_data: int = 0                   # 0 => all devices
    mesh_svc: int = 1
    # explicit mesh spec (CLI --mesh / TOML [sim] mesh / $ISOTOPE_MESH):
    # "auto" (cost-model layout search, parallel/layout.py),
    # "DATAxSVC[xSLICE]", or "data=4,svc=2,slice=1".  Overrides the
    # legacy mesh_data/mesh_svc pair when set.
    mesh_spec: Optional[str] = None
    # collective/compute overlap on sharded runs (SimParams.overlap):
    # merge collectives pipeline one block behind the event sweeps
    overlap: bool = False
    labels: str = ""
    chaos: Tuple[ChaosEvent, ...] = ()
    churn: Tuple[TrafficSplit, ...] = ()
    mtls: Optional[MtlsSchedule] = None
    # entrypoint override: pick one instance of a multi-entry topology
    # (replicate_topology); None = the graph's first entrypoint
    entry: Optional[str] = None
    # critical-path blame attribution (metrics/attribution.py): arms
    # SimParams.attribution so the runner's attributed pass can reduce
    # per-service blame on device (--attribution[=tail])
    attribution: bool = False
    # simulation flight recorder (metrics/timeline.py): arms
    # SimParams.timeline so the runner's timeline pass can accumulate
    # windowed series on device (--timeline[=<window>])
    timeline: bool = False
    timeline_window_s: float = SimParams().timeline_window_s
    # in-graph resilience policies (sim/policies.py): when True, the
    # topology's `policies:` block compiles to per-service tables and
    # the MAIN run co-simulates the breaker / retry-budget /
    # autoscaler control loop inside the block scan (--policies /
    # TOML [sim] policies = true).  Implies the timeline recorder (the
    # control loop's observation side).
    policies: bool = False
    # reactive canary rollouts (sim/rollout.py): when True, the
    # topology's `rollouts:` block compiles to per-service step
    # schedules and the MAIN run co-simulates the progressive-delivery
    # controller (canary traffic splits as scan-carry state, PROMOTE /
    # HOLD / ROLLBACK from the per-version window signals) inside the
    # block scan (--rollouts / TOML [sim] rollouts = true).  Implies
    # the timeline recorder, like policies.
    rollouts: bool = False
    # scenario ensembles (sim/ensemble.py): N > 0 runs every
    # unprotected case as a Monte Carlo fleet of N seed members in ONE
    # jitted program per device (--ensemble N / TOML [sim] ensemble),
    # reporting the pooled summary plus a `<label>.ensemble.json`
    # artifact with quantile bands and SLO-violation probabilities.
    # 0 (the default) leaves every run byte-identical to the solo path.
    ensemble: int = 0
    # per-member lognormal jitters (log-space sigma; see
    # EnsembleSpec.from_jitter) — the seed-jitter spec of
    # `--ensemble-jitter qps=0.1,cpu=0.05,error=0.2`
    ensemble_qps_jitter: float = 0.0
    ensemble_cpu_jitter: float = 0.0
    ensemble_error_jitter: float = 0.0
    ensemble_jitter_seed: int = 0
    # the SLO latency (seconds) the ensemble artifact's P(violation)
    # estimate is computed against; None omits the estimate
    ensemble_slo_s: Optional[float] = None
    # per-member chaos schedules (chaos fleets, PR 15): a
    # resilience/faults.ChaosJitterSpec spec string
    # ("time=0.2,magnitude=0.5,target=0.3,seed=K") jittering each
    # fleet member's kill timing / target / magnitude; None keeps the
    # base schedule on every member (--ensemble-chaos-jitter /
    # TOML [sim] ensemble_chaos_jitter)
    ensemble_chaos_jitter: Optional[str] = None
    # importance splitting (sim/splitting.py): a SplitSpec string
    # ("levels=4,members=64,keep=0.25,threshold=0.5,sev=err_peak")
    # arming the rare-outage estimator per ensemble case; the result
    # lands behind `<label>.ensemble.json`'s schema-versioned
    # "splitting" key (--ensemble-split / TOML [sim] ensemble_split)
    ensemble_split: Optional[str] = None
    # the splitting screening-horizon fraction (PR 18): overrides the
    # spec string's ``horizon=`` key so sweeps can tune how much of
    # the case's request count each splitting level simulates
    # (--split-horizon / TOML [sim] ensemble_split_horizon); None
    # defers to the spec string (default 0.25)
    ensemble_split_horizon: Optional[float] = None
    # config search (sim/search.py): candidates > 0 arms a
    # successive-halving bracket per case (TOML [search] block),
    # writing a `<label>.search.json` isotope-search/v1 artifact with
    # the per-rung survivor lineage and the winning candidate
    search_candidates: int = 0
    search_eta: int = 4
    search_rungs: int = 3
    search_growth: Optional[int] = None
    search_rank: str = "err_share"
    search_slo_s: Optional[float] = None
    # the population's jitter spec ("qps=0.2,cpu=0.1,error=0.3[,seed=K]")
    search_jitter: Optional[str] = None
    search_seed: int = 0
    # trace-driven provenance (ingest/): the raw informational
    # ``[ingest]`` table an `isotope-tpu ingest` run wrote into the
    # TOML (label, entry, window count, qps band).  None for
    # hand-written configs; when set, the runner stamps the rows so
    # fitted-replay measurements are never compared against
    # hand-written twins (run.py `_ingest` marker).
    ingest: Optional[dict] = None

    def sim_params(self) -> SimParams:
        return SimParams(
            cpu_time_s=self.cpu_time_s,
            service_time=self.service_time,
            service_time_param=self.service_time_param,
            attribution=self.attribution,
            # the policy/rollout co-sims observe through the recorder
            timeline=self.timeline or self.policies or self.rollouts,
            timeline_window_s=self.timeline_window_s,
            overlap=self.overlap,
            ensemble=max(int(self.ensemble), 0),
        )

    def ensemble_spec(self):
        """The sweep's :class:`~isotope_tpu.sim.ensemble.EnsembleSpec`
        (None when the ensemble axis is off)."""
        if self.ensemble <= 0:
            return None
        from isotope_tpu.sim.ensemble import EnsembleSpec

        return EnsembleSpec.from_jitter(
            self.ensemble,
            qps_jitter=self.ensemble_qps_jitter,
            cpu_jitter=self.ensemble_cpu_jitter,
            error_jitter=self.ensemble_error_jitter,
            jitter_seed=self.ensemble_jitter_seed,
        )

    def chaos_jitter_spec(self):
        """The sweep's per-member chaos jitter
        (:class:`~isotope_tpu.resilience.faults.ChaosJitterSpec`), or
        None when off or no chaos schedule exists to jitter."""
        if not self.ensemble_chaos_jitter or not self.chaos:
            return None
        from isotope_tpu.resilience.faults import parse_chaos_jitter

        with config_path("sim.ensemble_chaos_jitter"):
            return parse_chaos_jitter(self.ensemble_chaos_jitter)

    def split_spec(self):
        """The sweep's importance-splitting config
        (:class:`~isotope_tpu.sim.splitting.SplitSpec`), or None.
        ``ensemble_split_horizon`` overrides the spec string's
        ``horizon=`` key; the resolved value lands in the artifact's
        splitting block via ``SplitSpec.to_dict``."""
        if not self.ensemble_split:
            return None
        import dataclasses as _dc

        from isotope_tpu.sim.splitting import parse_split_spec

        with config_path("sim.ensemble_split"):
            spec = parse_split_spec(self.ensemble_split)
        if spec is not None and self.ensemble_split_horizon is not None:
            with config_path("sim.ensemble_split_horizon"):
                spec = _dc.replace(
                    spec, horizon=float(self.ensemble_split_horizon)
                )
        return spec

    def search_spec(self):
        """The sweep's :class:`~isotope_tpu.sim.search.SearchSpec`
        (None when the search axis is off)."""
        if self.search_candidates <= 0:
            return None
        from isotope_tpu.sim.ensemble import (
            EnsembleSpec,
            parse_jitter_spec,
        )
        from isotope_tpu.sim.search import SearchSpec

        with config_path("search"):
            jitter = parse_jitter_spec(self.search_jitter)
            pop = EnsembleSpec.from_jitter(
                self.search_candidates, **jitter
            )
            return SearchSpec(
                candidates=pop,
                eta=self.search_eta,
                rungs=self.search_rungs,
                growth=self.search_growth,
                rank=self.search_rank,
                slo_s=self.search_slo_s,
                seed=self.search_seed,
            )

    def load_models(self):
        for conn in self.connections:
            for qps in self.qps:
                yield LoadModel(
                    kind=self.load_kind,
                    qps=qps,
                    connections=conn,
                    duration_s=self.duration_s,
                )


def _parse_qps(value) -> Optional[float]:
    if value == "max":
        return None
    return float(value)


def load_toml(path) -> ExperimentConfig:
    path = pathlib.Path(path)
    with open(path, "rb") as f:
        doc = tomllib.load(f)
    # topology paths resolve relative to the config file, not the cwd
    base = path.parent
    doc["topology_paths"] = [
        str(p if (p := pathlib.Path(raw)).is_absolute() else base / p)
        for raw in doc.get("topology_paths", ())
    ]

    envs: List[EnvironmentModel] = []
    env_overrides: Dict[str, dict] = doc.get("environment", {})
    for name in doc.get("environments", ["NONE"]):
        if name in env_overrides:
            o = env_overrides[name]
            if set(o) == {"extra_hop_latency"}:
                # legacy knob alone: REPLACES the whole tax (the
                # pre-matrix semantics), so existing configs that tuned
                # e.g. ISTIO via extra_hop_latency keep their numbers
                # instead of silently stacking on the proxy passes
                envs.append(
                    EnvironmentModel(
                        name=name,
                        extra_hop_latency_s=dur.parse_duration_seconds(
                            o["extra_hop_latency"]
                        ),
                    )
                )
                continue
            default_env = DEFAULT_ENVIRONMENTS.get(
                name, EnvironmentModel(name=name)
            )
            envs.append(
                dataclasses.replace(
                    default_env,
                    name=name,
                    client_proxy=bool(
                        o.get("client_proxy", default_env.client_proxy)
                    ),
                    server_proxy=bool(
                        o.get("server_proxy", default_env.server_proxy)
                    ),
                    gateway=bool(o.get("gateway", default_env.gateway)),
                    proxy_latency_s=(
                        dur.parse_duration_seconds(o["proxy_latency"])
                        if "proxy_latency" in o
                        else default_env.proxy_latency_s
                    ),
                    extra_hop_latency_s=(
                        dur.parse_duration_seconds(o["extra_hop_latency"])
                        if "extra_hop_latency" in o
                        else default_env.extra_hop_latency_s
                    ),
                )
            )
        elif name in DEFAULT_ENVIRONMENTS:
            envs.append(DEFAULT_ENVIRONMENTS[name])
        else:
            raise ValueError(
                f"unknown environment {name!r}: define an [environment."
                f"{name}] block"
            )

    client = doc.get("client", {})
    with config_path("client.qps"):
        qps_raw = client.get("qps", "max")
        qps_list = (
            [_parse_qps(q) for q in qps_raw]
            if isinstance(qps_raw, list)
            else [_parse_qps(qps_raw)]
        )
    with config_path("client.num_concurrent_connections"):
        conns_raw = client.get("num_concurrent_connections", 64)
        conns = (
            [int(c) for c in conns_raw]
            if isinstance(conns_raw, list)
            else [int(conns_raw)]
        )

    chaos: List[ChaosEvent] = []
    for i, ev in enumerate(doc.get("chaos", [])):
        with config_path(f"chaos[{i}]"):
            down = ev.get("replicas_down", "all")
            down_n = None if down == "all" else int(down)
            drain = bool(ev.get("drain", True))
            with config_path("start"):
                start = dur.parse_duration_seconds(ev["start"])
            with config_path("end"):
                end = dur.parse_duration_seconds(ev["end"])
            if "period" in ev or "repeat" in ev:
                # rolling-restart shorthand (gateway-bouncer): repeat
                # the [start, end) window every `period` for `repeat`
                # cycles
                if "period" not in ev:
                    raise ValueError(
                        f"[[chaos]] block for {ev['service']!r} sets "
                        "'repeat' without 'period'"
                    )
                chaos.extend(
                    bounce_schedule(
                        service=ev["service"],
                        period_s=dur.parse_duration_seconds(
                            ev["period"]
                        ),
                        down_s=end - start,
                        count=int(ev.get("repeat", 1)),
                        start_s=start,
                        replicas_down=down_n,
                        drain=drain,
                    )
                )
            else:
                chaos.append(
                    ChaosEvent(
                        service=ev["service"],
                        start_s=start,
                        end_s=end,
                        replicas_down=down_n,
                        drain=drain,
                    )
                )

    # [[churn]]: the config-churner analogue (rotating traffic weights)
    churn: List[TrafficSplit] = []
    for i, ts in enumerate(doc.get("churn", [])):
        with config_path(f"churn[{i}]"):
            churn.append(
                TrafficSplit(
                    service=ts["service"],
                    period_s=dur.parse_duration_seconds(ts["period"]),
                    weights=tuple(float(w) for w in ts["weights"]),
                )
            )

    # [mtls]: the auto-mTLS switching analogue — a schedule of per-edge
    # one-way taxes cycled every `period` (perf/load/auto-mtls/scale.py)
    mtls = None
    if "mtls" in doc:
        m = doc["mtls"]
        with config_path("mtls"):
            mtls = MtlsSchedule(
                period_s=dur.parse_duration_seconds(m["period"]),
                taxes_s=tuple(
                    dur.parse_duration_seconds(x) if isinstance(x, str)
                    else float(x)
                    for x in m["taxes"]
                ),
            )

    # loadgen axis: fortio is closed-loop by default, nighthawk is the
    # open-loop generator (runner.py:270-316 builds a distinct
    # invocation; it has no closed-loop mode)
    loadgen = client.get("loadgen", "fortio")
    if loadgen not in ("fortio", "nighthawk"):
        raise ValueError(
            f"unknown loadgen {loadgen!r} (choose fortio or nighthawk)"
        )
    default_kind = "open" if loadgen == "nighthawk" else "closed"
    load_kind = client.get("load_kind", default_kind)
    if loadgen == "nighthawk" and load_kind != "open":
        raise ValueError(
            "nighthawk is an open-loop generator; drop load_kind or "
            "set it to \"open\" (runner.py:270-316)"
        )

    sim = doc.get("sim", {})
    defaults = SimParams()
    return ExperimentConfig(
        topology_paths=tuple(doc.get("topology_paths", ())),
        environments=tuple(envs),
        qps=tuple(qps_list),
        connections=tuple(conns),
        duration_s=dur.parse_duration_seconds(client.get("duration", "5m")),
        load_kind=load_kind,
        loadgen=loadgen,
        num_requests=int(sim.get("num_requests", 100_000)),
        seed=int(sim.get("seed", 0)),
        cpu_time_s=(
            dur.parse_duration_seconds(sim["cpu_time"])
            if "cpu_time" in sim
            else defaults.cpu_time_s
        ),
        service_time=sim.get("service_time", defaults.service_time),
        service_time_param=float(
            sim.get("service_time_param", defaults.service_time_param)
        ),
        mesh_data=int(sim.get("mesh_data", 0)),
        mesh_svc=int(sim.get("mesh_svc", 1)),
        mesh_spec=sim.get("mesh"),
        overlap=bool(sim.get("overlap", False)),
        labels=doc.get("labels", ""),
        chaos=tuple(chaos),
        churn=tuple(churn),
        mtls=mtls,
        entry=sim.get("entry"),
        timeline=bool(sim.get("timeline", False)),
        timeline_window_s=(
            dur.parse_duration_seconds(sim["timeline_window"])
            if "timeline_window" in sim
            else SimParams().timeline_window_s
        ),
        policies=bool(sim.get("policies", False)),
        rollouts=bool(sim.get("rollouts", False)),
        **_ensemble_kwargs(sim),
        **_search_kwargs(doc.get("search", {})),
        ingest=(
            dict(doc["ingest"])
            if isinstance(doc.get("ingest"), dict) else None
        ),
    )


def _ensemble_kwargs(sim: dict) -> dict:
    """The ``[sim]`` ensemble keys: ``ensemble = N`` (member count),
    ``ensemble_jitter = "qps=0.1,cpu=0.05,error=0.2[,seed=K]"`` (the
    per-member perturbation spec), ``ensemble_slo = "250ms"`` (the SLO
    the artifact's P(violation) estimate targets)."""
    out: dict = {"ensemble": int(sim.get("ensemble", 0))}
    if "ensemble_jitter" in sim:
        from isotope_tpu.sim.ensemble import parse_jitter_spec

        with config_path("sim.ensemble_jitter"):
            j = parse_jitter_spec(str(sim["ensemble_jitter"]))
        out["ensemble_qps_jitter"] = j["qps_jitter"]
        out["ensemble_cpu_jitter"] = j["cpu_jitter"]
        out["ensemble_error_jitter"] = j["error_jitter"]
        out["ensemble_jitter_seed"] = j.get("jitter_seed", 0)
    if "ensemble_slo" in sim:
        with config_path("sim.ensemble_slo"):
            out["ensemble_slo_s"] = dur.parse_duration_seconds(
                sim["ensemble_slo"]
            )
    if "ensemble_chaos_jitter" in sim:
        # parse eagerly: a typo'd spec must fail at config load
        from isotope_tpu.resilience.faults import parse_chaos_jitter

        with config_path("sim.ensemble_chaos_jitter"):
            parse_chaos_jitter(str(sim["ensemble_chaos_jitter"]))
        out["ensemble_chaos_jitter"] = str(
            sim["ensemble_chaos_jitter"]
        )
    if "ensemble_split" in sim:
        from isotope_tpu.sim.splitting import parse_split_spec

        with config_path("sim.ensemble_split"):
            parse_split_spec(str(sim["ensemble_split"]))
        out["ensemble_split"] = str(sim["ensemble_split"])
    if "ensemble_split_horizon" in sim:
        with config_path("sim.ensemble_split_horizon"):
            h = float(sim["ensemble_split_horizon"])
            if not 0.0 < h <= 1.0:
                raise ValueError(
                    "ensemble_split_horizon must lie in (0, 1]"
                )
        out["ensemble_split_horizon"] = h
    return out


def _search_kwargs(search: dict) -> dict:
    """The ``[search]`` block: ``candidates = N`` arms a
    successive-halving bracket per case; ``eta``/``rungs``/``growth``
    shape the bracket, ``rank`` picks the severity channel
    (``err_share`` | ``err_peak`` | ``p99``; ``slo = "250ms"``
    anchors p99), ``jitter`` draws the population and ``seed``
    derives the rank tie-breaks.  Specs validate eagerly — a typo'd
    block must fail at config load, not mid-sweep."""
    if not search:
        return {}
    known = {"candidates", "eta", "rungs", "growth", "rank", "slo",
             "jitter", "seed"}
    unknown = sorted(set(search) - known)
    if unknown:
        with config_path("search"):
            raise ValueError(
                f"unknown [search] keys {unknown} (expected "
                f"{sorted(known)})"
            )
    out: dict = {
        "search_candidates": int(search.get("candidates", 0)),
        "search_eta": int(search.get("eta", 4)),
        "search_rungs": int(search.get("rungs", 3)),
        "search_rank": str(search.get("rank", "err_share")),
        "search_seed": int(search.get("seed", 0)),
    }
    if "growth" in search:
        out["search_growth"] = int(search["growth"])
    if "slo" in search:
        with config_path("search.slo"):
            out["search_slo_s"] = dur.parse_duration_seconds(
                search["slo"]
            )
    if "jitter" in search:
        from isotope_tpu.sim.ensemble import parse_jitter_spec

        with config_path("search.jitter"):
            parse_jitter_spec(str(search["jitter"]))
        out["search_jitter"] = str(search["jitter"])
    if out["search_candidates"] > 0:
        from isotope_tpu.sim.ensemble import EnsembleSpec
        from isotope_tpu.sim.search import SearchSpec

        with config_path("search"):
            SearchSpec(
                candidates=EnsembleSpec.of(out["search_candidates"]),
                eta=out["search_eta"],
                rungs=out["search_rungs"],
                growth=out.get("search_growth"),
                rank=out["search_rank"],
                slo_s=out.get("search_slo_s"),
                seed=out["search_seed"],
            ).check()
    return out
