"""The sweep driver: topology x environment x connections x qps.

Mirrors the shape of the reference's drivers (run_tests.py:35-44 outer
product; runner.py:522-525 conn x qps grid; fortio.py artifact formats)
with compilation replacing deployment and simulation replacing ``kubectl
exec fortio load``.

Checkpoint/resume: every completed run appends one line to
``<out>/checkpoint.jsonl`` (after a header binding the config), and its
per-run artifacts are written immediately.  A killed sweep re-invoked
with the same config skips the completed prefix — the run key is
``fold_in(seed_key, run_index)``, so the resumed tail draws the exact
streams the uninterrupted sweep would have, and the final benchmark.csv
is identical except the wall-clock StartTime column.  The reference's durability analogue: Prometheus on a
persistent disk + raw Fortio JSONs copied off-pod
(isotope/README.md:313-323; run_benchmark_job.sh exit handler).
"""
from __future__ import annotations

import contextlib
import dataclasses
import hashlib
import json
import os
import pathlib
import sys
from typing import List, Optional, Sequence

import jax

from isotope_tpu import telemetry
from isotope_tpu.compiler import compile_graph
from isotope_tpu.metrics.fortio import (
    DEFAULT_CSV_KEYS,
    WindowSummary,
    convert_data,
    fortio_result_from_summary,
    window_summary_from_summary,
    write_csv,
)
from isotope_tpu.metrics.prometheus import MetricsCollector
from isotope_tpu.models.graph import ServiceGraph
from isotope_tpu.parallel import (
    MeshSpec,
    ShardedSimulator,
    build_mesh,
    mesh_spec_from_env,
    parse_mesh_spec,
)
from isotope_tpu.resilience import (
    ResiliencePolicy,
    call_with_retries,
    classify,
    execution_rungs,
    run_ladder,
)
from isotope_tpu.runner.config import ExperimentConfig
from isotope_tpu.sim.config import OPEN_LOOP, LoadModel
from isotope_tpu.sim.engine import Simulator


@dataclasses.dataclass
class RunResult:
    label: str
    topology: str
    environment: str
    flat: dict                    # the reference's single-line schema
    window: WindowSummary
    fortio_json: dict
    prometheus_text: str
    # engine self-telemetry snapshot (RunTelemetry.to_dict()); None when
    # telemetry emission is off or the run was restored from checkpoint
    telemetry: Optional[dict] = None
    # which degradation-ladder rung served the run (None = undegraded)
    degraded_to: Optional[str] = None
    # unrecoverable failure: the case is recorded, the sweep continued
    failed: bool = False
    error: Optional[str] = None
    # critical-path blame (metrics/attribution.py): the blame.json doc,
    # the raw AttributionSummary, and the CompiledGraph its hop vectors
    # are indexed by (exporters reuse it instead of recompiling); all
    # None when the attribution pass was off or failed
    blame: Optional[dict] = None
    attribution: Optional[object] = None
    compiled: Optional[object] = None
    # flight-recorder windowed series (metrics/timeline.py): the
    # timeline.json doc and the raw TimelineSummary; None when the
    # timeline pass was off or failed
    timeline: Optional[dict] = None
    timeline_summary: Optional[object] = None
    # in-graph resilience policies (sim/policies.py): the
    # policies.json doc and the raw PolicySummary of the PROTECTED
    # main run; None when the policy co-sim was off
    policies: Optional[dict] = None
    policies_summary: Optional[object] = None
    # reactive canary rollouts (sim/rollout.py): the rollout.json doc
    # and the raw RolloutSummary of the PROTECTED main run; None when
    # the rollout co-sim was off
    rollouts: Optional[dict] = None
    rollouts_summary: Optional[object] = None
    # pluggable load-balancing laws (sim/lb.py): the lb.json doc
    # (per-service law + per-window per-backend load split); None when
    # the topology declares no lb entries
    lb: Optional[dict] = None
    # scenario ensembles (sim/ensemble.py): the ensemble.json doc
    # (isotope-ensemble/v2: per-member quantiles, quantile bands,
    # SLO-violation probability with Wilson CI, and — for chaos
    # fleets — severity ranking, worst-member pointer, and the
    # importance-splitting block) and the raw EnsembleSummary; None
    # when the ensemble axis was off or the fleet dispatch fell back
    # to the solo path
    ensemble: Optional[dict] = None
    ensemble_summary: Optional[object] = None
    # fleet divergence explainer (metrics/fleetblame.py): the
    # fleet-blame.json doc (isotope-fleet-blame/v1: per-hop blame
    # bands, per-member top-K blamed hops, divergence onsets); None
    # when the fleet carried no attribution
    fleet_blame: Optional[dict] = None
    # on-device config search (sim/search.py): the search.json doc
    # (isotope-search/v1: winner config + per-rung lineage of the
    # successive-halving bracket) ; None when the [search] block was
    # off or the bracket dispatch fell back
    search: Optional[dict] = None


def _failed_window(reason: str) -> WindowSummary:
    return WindowSummary(
        start_s=0.0, duration_s=0.0, count=0, qps=0.0,
        error_percent=100.0, discarded=True,
        discard_reason=f"run failed: {reason}",
        percentiles_us={}, cpu_cores={},
    )


def _label(topo_path: str, env: str, load: LoadModel, extra: str) -> str:
    stem = pathlib.Path(topo_path).stem
    qps = "max" if load.qps is None else f"{load.qps:g}"
    base = f"{stem}_{env.lower()}_{qps}qps_{load.connections}c"
    return f"{base}_{extra}" if extra else base


def _num_requests(load: LoadModel, capacity: float, cap: int) -> int:
    """Size the batch so the simulated run spans ``load.duration_s``."""
    rate = capacity if load.qps is None else min(load.qps, capacity)
    return max(1, min(int(rate * load.duration_s), cap))


def resolve_mesh_request(config: ExperimentConfig):
    """The mesh request for a sweep: ``"auto"``, a :class:`MeshSpec`,
    or ``None`` (legacy ``mesh_data``/``mesh_svc`` sizing).

    Priority: explicit config spec (CLI ``--mesh`` / TOML ``[sim]
    mesh``) > ``$ISOTOPE_MESH`` > legacy keys.  Spec errors are
    key-pathed config errors raised here, before any simulation.
    """
    if config.mesh_spec:
        return parse_mesh_spec(str(config.mesh_spec))
    env = mesh_spec_from_env()
    if env is not None:
        return env
    return None


class _LazyTopology:
    """Compile a topology (and build its simulators) only if some run of
    it actually executes — a fully-resumed topology costs nothing."""

    def __init__(self, topo_path: str, config: ExperimentConfig,
                 mesh_req):
        self.path = topo_path
        self.config = config
        self.mesh_req = mesh_req          # "auto" | MeshSpec | None
        self.mesh_layout: Optional[str] = None   # describe() once built
        self.mesh_layout_score: Optional[float] = None
        self._spec = None
        self._compiled = None
        self._collector = None
        self._entry_resp = 0.0
        self._graph = None
        self._sims = {}
        self._policy_tables = None
        self._policy_tables_built = False
        self._rollout_tables = None
        self._rollout_tables_built = False
        self._lb_tables = None
        self._lb_tables_built = False

    @property
    def compiled(self):
        if self._compiled is None:
            graph = ServiceGraph.from_yaml_file(self.path)
            self._graph = graph
            self._compiled = compile_graph(graph, entry=self.config.entry)
            self._entry_resp = float(
                self._compiled.services.response_size[
                    self._compiled.entry_service
                ]
            )
            self._collector = MetricsCollector(self._compiled)
        return self._compiled

    @property
    def graph(self):
        self.compiled
        return self._graph

    @property
    def collector(self):
        self.compiled
        return self._collector

    @property
    def entry_response_size(self) -> float:
        self.compiled
        return self._entry_resp

    @property
    def policy_tables(self):
        """Compiled resilience-policy tables (sim/policies.py), or
        None when the topology declares none or the config leaves the
        co-sim off."""
        if not self._policy_tables_built:
            self._policy_tables_built = True
            if self.config.policies:
                from isotope_tpu.compiler import compile_policies

                self._policy_tables = compile_policies(
                    self.graph, self.compiled
                )
        return self._policy_tables

    @property
    def rollout_tables(self):
        """Compiled progressive-delivery tables (sim/rollout.py), or
        None when the topology declares no active rollout or the
        config leaves the co-sim off."""
        if not self._rollout_tables_built:
            self._rollout_tables_built = True
            if self.config.rollouts:
                from isotope_tpu.compiler import compile_rollouts

                self._rollout_tables = compile_rollouts(
                    self.graph, self.compiled
                )
        return self._rollout_tables

    @property
    def lb_tables(self):
        """Compiled load-balancing tables (sim/lb.py), or None when
        the topology declares no ``lb:`` entries.  Unlike the policy /
        rollout co-sims there is no config gate: a declared lb law IS
        the data plane being measured, on every run kind."""
        if not self._lb_tables_built:
            self._lb_tables_built = True
            from isotope_tpu.compiler import compile_lb

            self._lb_tables = compile_lb(self.graph, self.compiled)
        return self._lb_tables

    def mesh_spec(self) -> MeshSpec:
        """The resolved factorization for this topology (``"auto"``
        runs the layout search against the compiled service count)."""
        if self._spec is None:
            if self.mesh_req == "auto":
                from isotope_tpu.parallel import layout

                n_hosts = getattr(jax, "process_count", lambda: 1)()
                chosen = layout.choose_layout(
                    jax.device_count(),
                    self.compiled.num_services,
                    max_slices=max(n_hosts, 1),
                )
                self._spec = chosen.spec
                self.mesh_layout_score = chosen.score_s
                print(
                    f"mesh auto: {self.path} -> "
                    f"{chosen.spec.describe()} "
                    f"(score {chosen.score_s:.3g}s/merge)",
                    file=sys.stderr,
                )
            elif isinstance(self.mesh_req, MeshSpec):
                self._spec = self.mesh_req
            else:
                # legacy sizing: mesh_data x mesh_svc (0 => all devices)
                svc = max(self.config.mesh_svc, 1)
                data = (
                    self.config.mesh_data
                    if self.config.mesh_data > 0
                    else max(jax.device_count() // svc, 1)
                )
                self._spec = MeshSpec(data=data, svc=svc)
            self.mesh_layout = self._spec.describe()
        return self._spec

    def sims(self, env):
        """(Simulator, ShardedSimulator | None) for an environment."""
        if env.name not in self._sims:
            params = env.apply(self.config.sim_params())
            policies = self.policy_tables
            rollouts = self.rollout_tables
            lb = self.lb_tables
            sim = Simulator(self.compiled, params, self.config.chaos,
                            self.config.churn, mtls=self.config.mtls,
                            policies=policies, rollouts=rollouts, lb=lb)
            spec = self.mesh_spec()
            sharded = (
                ShardedSimulator(
                    self.compiled,
                    build_mesh(spec),
                    params,
                    self.config.chaos,
                    self.config.churn,
                    mtls=self.config.mtls,
                    policies=policies,
                    rollouts=rollouts,
                    lb=lb,
                )
                if spec.size > 1
                else None
            )
            self._sims[env.name] = (sim, sharded)
        return self._sims[env.name]


class _EnsembleGroups:
    """Same-shape case collapse for ensemble sweeps (sim/ensemble.py).

    Grid cells of one (topology, environment) that share the load
    KIND, connection count, and computed run shape (request count +
    block) compile to the same fleet program — so their fleets pack
    into ONE dispatch: members of cell i are keyed
    ``fold_in(fold_in(seed_key, run_index_i), seed)`` (the
    checkpoint-resume fold law, so a collapsed cell's members are
    bit-identical to its uncollapsed dispatch) with each cell's exact
    target qps riding the stacked ``member_qps`` argument.  Typical
    win: a qps grid capped by ``num_requests`` — every cell past the
    cap has the same shape and the whole loop collapses.

    Results are cached per label; cells reached later in the sweep
    loop read their slice instead of re-dispatching.
    """

    def __init__(self, config: ExperimentConfig, spec, key, cells,
                 completed):
        self.config = config
        self.spec = spec          # the per-cell EnsembleSpec
        self.key = key
        self.cells = cells        # [{"topo","env","label","load","idx"}]
        self.completed = set(completed)
        self.results: dict = {}   # label -> per-cell EnsembleSummary

    def _group_for(self, label, topo_path, env_name, load, sim, n):
        """The cells that can ride this dispatch (self included)."""
        from isotope_tpu.sim.config import OPEN_LOOP as _OPEN

        me = [c for c in self.cells if c["label"] == label]
        if load.kind != _OPEN or load.qps is None:
            # closed-loop rate solves are per-cell host pilots; keep
            # those cells on their own (still one fleet per cell)
            return me
        cap = sim.capacity_qps()
        group = [
            c for c in self.cells
            if c["topo"] == topo_path
            and c["env"] == env_name
            and c["label"] not in self.completed
            and c["load"].kind == load.kind
            and c["load"].connections == load.connections
            and c["load"].qps is not None
            and _num_requests(
                c["load"], cap, self.config.num_requests
            ) == n
        ]
        return group if any(c["label"] == label for c in group) else me

    def run(self, label, topo_path, env_name, load, sim, sharded,
            use_sharded, n, block, attribution=None, timeline=None):
        """This cell's EnsembleSummary (dispatching its whole
        same-shape group on first touch).  ``attribution`` (``"on"`` /
        ``"tail"``) and ``timeline`` (a window width) thread the fleet
        observability pass (PR 17) through the SAME dispatch — blame
        and window series accumulate per member inside the fleet
        program instead of a separate solo pass."""
        import numpy as np

        from isotope_tpu.sim.ensemble import (
            EnsembleSpec,
            EnsembleSummary,
        )

        if label in self.results:
            telemetry.counter_inc("ensemble_collapsed_cases")
            return self.results.pop(label)
        spec = self.spec
        n_seeds = spec.members
        group = self._group_for(label, topo_path, env_name, load,
                                sim, n)
        member_keys = []
        member_qps = []
        seed_scale = (
            spec.qps_scale
            if spec.qps_scale is not None
            else np.ones(n_seeds)
        )
        for c in group:
            cell_key = jax.random.fold_in(self.key, c["idx"])
            for s in spec.seeds:
                member_keys.append(jax.random.fold_in(cell_key, s))
            if c["load"].qps is not None:
                member_qps.extend(
                    float(c["load"].qps) * seed_scale
                )
        if len(group) == 1:
            group_spec = spec
            qps_arg = None if load.qps is None else np.asarray(
                member_qps
            )
        else:
            # qps jitter folds into the exact per-member rates; the
            # physics jitters tile per cell
            group_spec = EnsembleSpec(
                seeds=tuple(range(len(member_keys))),
                cpu_scale=(
                    np.tile(spec.cpu_scale, len(group))
                    if spec.cpu_scale is not None else None
                ),
                error_scale=(
                    np.tile(spec.error_scale, len(group))
                    if spec.error_scale is not None else None
                ),
            )
            qps_arg = np.asarray(member_qps)
        runner = sharded if (use_sharded and sharded is not None) \
            else sim
        obs_kw = {}
        if attribution is not None:
            obs_kw.update(
                attribution=True, tail=attribution == "tail",
            )
        if timeline is not None:
            obs_kw.update(timeline=True, window_s=float(timeline))
        ens = runner.run_ensemble(
            load, n, jax.random.fold_in(self.key, group[0]["idx"]),
            group_spec, block_size=block, trim=True,
            member_keys=member_keys, member_qps=qps_arg, **obs_kw,
        )
        # served cells leave the grouping pool: a later cell's group
        # must never re-dispatch members whose results already landed
        self.completed.update(c["label"] for c in group)
        for i, c in enumerate(group):
            sl = slice(i * n_seeds, (i + 1) * n_seeds)

            def cell(stacked, sl=sl):
                if stacked is None:
                    return None
                return jax.tree.map(
                    lambda x: np.asarray(x)[sl], stacked
                )

            self.results[c["label"]] = EnsembleSummary(
                spec=spec,
                summaries=cell(ens.summaries),
                offered_qps=np.asarray(ens.offered_qps)[sl],
                chunk=ens.chunk,
                timelines=cell(ens.timelines),
                attributions=cell(ens.attributions),
            )
        if len(group) > 1:
            telemetry.counter_inc("ensemble_group_dispatches")
            telemetry.gauge_set("ensemble_group_cells", len(group))
            print(
                f"ensemble: collapsed {len(group)} same-shape case(s) "
                f"({len(member_keys)} members) into one dispatch",
                file=sys.stderr,
            )
        return self.results.pop(label)

    def run_protected(self, label, topo_path, env_name, load, sim,
                      sharded, use_sharded, n, block, tables_roll,
                      chaos_jitter, attribution=None, timeline=None):
        """The same-shape collapse extended to PROTECTED fleets
        (PR 18): grid cells whose policy/rollout fleet programs share
        a shape ride ONE ``run_policies_ensemble`` /
        ``run_rollouts_ensemble`` dispatch.  Each cell keeps its
        control member on the cell's own run key (and, under
        ``chaos_jitter``, the solo chaos schedule) so a collapsed
        cell's members stay bit-identical to its uncollapsed
        dispatch — the universal member program made the chaos
        tables traced per-member arguments, which is exactly what
        lets cells with different jittered schedules share the
        executable."""
        import numpy as np

        from isotope_tpu.sim.ensemble import (
            EnsembleSpec,
            EnsembleSummary,
        )

        if label in self.results:
            telemetry.counter_inc("ensemble_collapsed_cases")
            return self.results.pop(label)
        spec = self.spec
        n_seeds = spec.members
        group = self._group_for(label, topo_path, env_name, load,
                                sim, n)
        roll = tables_roll is not None
        win, blk = _protected_window_block(
            sim, load, block, self.config, timeline
        )
        member_keys = []
        member_qps = []
        seed_scale = (
            spec.qps_scale
            if spec.qps_scale is not None
            else np.ones(n_seeds)
        )
        for c in group:
            cell_key = jax.random.fold_in(self.key, c["idx"])
            member_keys.append(cell_key)
            member_keys.extend(
                jax.random.fold_in(cell_key, s)
                for s in spec.seeds[1:]
            )
            if c["load"].qps is not None:
                member_qps.extend(
                    float(c["load"].qps) * seed_scale
                )
        member_chaos = None
        if chaos_jitter is not None \
                and getattr(sim, "_chaos_events", ()):
            from isotope_tpu.resilience import faults as faults_mod

            base_events = tuple(sim._chaos_events)
            reps = sim.compiled.services.replicas_by_name()
            cell_chaos = [base_events] + [
                faults_mod.jitter_chaos_events(
                    base_events, chaos_jitter,
                    faults_mod.member_event_seeds(
                        chaos_jitter, s, len(base_events)
                    ),
                    reps,
                )
                for s in spec.seeds[1:]
            ]
            member_chaos = cell_chaos * len(group)
        if len(group) == 1:
            group_spec = spec
            qps_arg = None if load.qps is None else np.asarray(
                member_qps
            )
        else:
            group_spec = EnsembleSpec(
                seeds=tuple(range(len(member_keys))),
                cpu_scale=(
                    np.tile(spec.cpu_scale, len(group))
                    if spec.cpu_scale is not None else None
                ),
                error_scale=(
                    np.tile(spec.error_scale, len(group))
                    if spec.error_scale is not None else None
                ),
            )
            qps_arg = np.asarray(member_qps)
        runner = sharded if (use_sharded and sharded is not None) \
            else sim
        method = getattr(
            runner,
            "run_rollouts_ensemble" if roll
            else "run_policies_ensemble",
        )
        obs_kw = {}
        if attribution is not None:
            obs_kw = dict(attribution=True, tail=attribution == "tail")
        with telemetry.phase("ensemble.run"):
            ens = method(
                load, n,
                jax.random.fold_in(self.key, group[0]["idx"]),
                group_spec, block_size=blk, trim=True, window_s=win,
                member_keys=member_keys, member_qps=qps_arg,
                member_chaos=member_chaos, **obs_kw,
            )
            jax.block_until_ready(ens.summaries.count)
        telemetry.counter_inc("protected_fleet_cases")
        self.completed.update(c["label"] for c in group)
        for i, c in enumerate(group):
            sl = slice(i * n_seeds, (i + 1) * n_seeds)

            def cell(stacked, sl=sl):
                if stacked is None:
                    return None
                return jax.tree.map(
                    lambda x: np.asarray(x)[sl], stacked
                )

            self.results[c["label"]] = EnsembleSummary(
                spec=spec,
                summaries=cell(ens.summaries),
                offered_qps=np.asarray(ens.offered_qps)[sl],
                chunk=ens.chunk,
                member_chaos=(
                    None if member_chaos is None
                    else member_chaos[sl]
                ),
                timelines=cell(ens.timelines),
                policies=cell(ens.policies),
                rollouts=cell(ens.rollouts),
                attributions=cell(ens.attributions),
            )
        if len(group) > 1:
            telemetry.counter_inc("ensemble_group_dispatches")
            telemetry.gauge_set("ensemble_group_cells", len(group))
            print(
                f"ensemble: collapsed {len(group)} same-shape "
                f"protected case(s) ({len(member_keys)} members) "
                "into one dispatch",
                file=sys.stderr,
            )
        return self.results.pop(label)


def _vet_gate(mode: str, sim, topo, config, load, block, rungs,
              policy, ensemble=None, protected: bool = False,
              split_spec=None, search_spec=None) -> int:
    """The ``--vet`` pre-flight: lint + audit + cost model for one case.

    Returns the ladder rung index the case should START on (the memory
    verdict's recommendation, 0 when everything fits).  Blocking
    findings raise :class:`~isotope_tpu.analysis.VetError` — a
    deterministic failure the sweep records like any other.  The
    VET-M* memory rules never block while the degradation ladder is
    armed: for them the rung pre-selection IS the recovery.
    ``ensemble`` (the sweep's EnsembleSpec, when armed) adds the
    fleet verdicts: VET-T023 spec lint + the VET-M004 member-capacity
    check reporting the pre-computed chunk.
    """
    from isotope_tpu.analysis import (
        MEMORY_RULES,
        VetError,
        default_suppressions,
        vet_simulator,
    )

    report = vet_simulator(
        sim, load, block_requests=block,
        graph=topo.graph, entry=config.entry,
        suppress=default_suppressions(),
        rung_names=tuple(name for name, _ in rungs),
        ensemble=ensemble,
        protected=protected,
        split_spec=split_spec,
        search_spec=search_spec,
    )
    for f in report.sorted():
        print(f"vet: {f.render()}", file=sys.stderr)
    nonblocking = MEMORY_RULES if policy.degrade else ()
    if report.blocking(strict=(mode == "strict"),
                       nonblocking_rules=nonblocking):
        raise VetError(report, mode == "strict", nonblocking)
    est = report.meta.get("cost", {}).get("peak_bytes_at_block")
    if est:
        # published so the post-run measured/estimate ratio gauge can
        # calibrate CAPACITY_FILL from real runs (ROADMAP follow-up)
        telemetry.gauge_set("vet_peak_bytes_estimate", float(est))
    start = int(report.meta.get("start_rung", 0))
    if start:
        telemetry.counter_inc("vet_rung_preselections")
        telemetry.set_meta("vet_start_rung", rungs[start][0])
        print(
            f"vet: memory verdict pre-selects ladder rung "
            f"{rungs[start][0]!r}",
            file=sys.stderr,
        )
    return start


def _config_fingerprint(config: ExperimentConfig) -> str:
    """Config identity for resume: the dataclass repr plus a hash of
    each topology file's bytes — editing a topology YAML must
    invalidate the checkpoint, not silently replay stale results."""
    h = hashlib.sha256()
    for p in config.topology_paths:
        try:
            h.update(pathlib.Path(p).read_bytes())
        except OSError:
            h.update(b"<missing>")
    return f"{config!r}#topos={h.hexdigest()[:16]}"


def _load_checkpoint(path: pathlib.Path, fingerprint: str) -> List[dict]:
    """Trustworthy records, or [] when absent/config-mismatched.

    A corrupted or truncated line (SIGKILL mid-append, disk trouble) is
    QUARANTINED — skipped and counted — instead of invalidating
    everything after it: records are self-contained and matched by
    label, so one bad line costs exactly one re-run.  Failure records
    (``"failed": true``) are loaded too; the resume loop re-executes
    those cases.
    """
    if not path.exists():
        return []
    lines = path.read_text().splitlines()
    if not lines:
        return []
    try:
        header = json.loads(lines[0])
    except json.JSONDecodeError:
        return []
    if header.get("config") != fingerprint:
        return []
    records = []
    for i, line in enumerate(lines[1:], 2):
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            telemetry.counter_inc("checkpoint_quarantined_records")
            print(
                f"warning: quarantined corrupt checkpoint record "
                f"{path}:{i} (its run will re-execute)",
                file=sys.stderr,
            )
            continue
        if not isinstance(rec, dict) or "label" not in rec:
            telemetry.counter_inc("checkpoint_quarantined_records")
            continue
        records.append(rec)
    return records


def _restore_result(rec: dict, out: pathlib.Path) -> RunResult:
    prom_path = out / f"{rec['label']}.prom"
    return RunResult(
        label=rec["label"],
        topology=rec["topology"],
        environment=rec["environment"],
        flat=rec["flat"],
        window=WindowSummary(**rec["window"]),
        fortio_json=rec["fortio_json"],
        prometheus_text=(
            prom_path.read_text() if prom_path.exists() else ""
        ),
        degraded_to=rec.get("degraded_to"),
    )


def _attribution_pass(sim, sharded, use_sharded, topo, load, n, key,
                      block, tail: bool):
    """The post-ladder attributed pass for one case: identical request
    streams to the main scan run (same executor, key, and blocking —
    the sharded twin when the mesh served the case), reduced to blame
    on device.  Blame covers EVERY simulated request; the collector's
    trim window applies to the reported percentiles only (``trim`` is
    passed for stream parity, it does not restrict the blame
    accumulators).  Best-effort — a blame failure must never fail a
    case whose metrics already landed."""
    from isotope_tpu.metrics import attribution as attr_mod

    runner = sharded if (use_sharded and sharded is not None) else sim
    try:
        with telemetry.phase("attribution.pass"):
            _, attr = runner.run_attributed(
                load, n, key, block_size=block, tail=tail, trim=True,
            )
            jax.block_until_ready(attr.count)
        doc = attr_mod.to_doc(topo.compiled, attr)
        telemetry.counter_inc("attribution_passes")
        return doc, attr
    except Exception as e:  # pragma: no cover - best-effort surface
        telemetry.counter_inc("attribution_pass_failures")
        print(f"warning: attribution pass failed: {e}",
              file=sys.stderr)
        return None, None


def _timeline_pass(sim, sharded, use_sharded, topo, load, n, key,
                   block, window_s):
    """The post-ladder timeline pass for one case: identical request
    streams to the main scan run (same executor, key, and blocking —
    the sharded twin when the mesh served the case), reduced to the
    windowed series on device.  Best-effort — a recorder failure must
    never fail a case whose metrics already landed."""
    from isotope_tpu.metrics import timeline as timeline_mod

    runner = sharded if (use_sharded and sharded is not None) else sim
    try:
        with telemetry.phase("timeline.pass"):
            _, tl = runner.run_timeline(
                load, n, key, block_size=block, trim=True,
                window_s=window_s,
            )
            jax.block_until_ready(tl.count)
        doc = timeline_mod.to_doc(topo.compiled, tl)
        telemetry.counter_inc("timeline_passes")
        return doc, tl
    except Exception as e:  # pragma: no cover - best-effort surface
        telemetry.counter_inc("timeline_pass_failures")
        print(f"warning: timeline pass failed: {e}", file=sys.stderr)
        return None, None


def _protected_rung_specs(is_sharded: bool, block: int):
    """Rung specs for a PROTECTED (policy/rollout) main run — the
    PR 3 supervisor rungs adapted to the co-sim entry points.  Each
    spec is ``(name, block_size, mode)`` with mode ``"dev"`` (the
    normal entry point), ``"emu"`` (the ``*_emulated`` twin —
    bit-equal trajectory by construction), or ``"eager"``
    (``jax.disable_jit``, the rung of last resort).

    NOTE a half-block protected run is a DIFFERENT measurement: the
    control loops actuate at block boundaries, so halving the block
    halves the actuation lag.  That is exactly why ``degraded_to`` is
    recorded on the result (and why bench_regress fails a capture
    that degrades a previously-clean case)."""
    half = max(256, block // 2)
    if is_sharded:
        return [
            ("sharded", block, "dev"),
            ("sharded-half-block", half, "dev"),
            ("single-device", block, "emu"),
        ]
    return [
        ("scan", block, "dev"),
        ("half-block", half, "dev"),
        ("cpu-eager", half, "eager"),
    ]


def _protected_call(runner, method: str, spec, load, n, key, kwargs,
                    **extra):
    """Invoke one protected rung: the co-sim entry point named by
    ``spec``'s mode, blocking on the summary with the numeric
    sentinels armed (deferred device errors must surface inside the
    supervised scope)."""
    import contextlib

    from isotope_tpu.resilience import sentinels

    _, b, mode = spec
    fn = getattr(runner, f"{method}_emulated" if mode == "emu"
                 else method)
    ctx = jax.disable_jit() if mode == "eager" \
        else contextlib.nullcontext()
    with ctx:
        out = fn(load, n, key, block_size=b, **kwargs, **extra)
        jax.block_until_ready(out[0].count)
    sentinels.check_summary(out[0])
    return out


def _protected_run(sim, sharded, use_sharded, load, n, key, block,
                   config, collector, policy, timeline, tables_pol,
                   tables_roll, attribution=None):
    """The protected co-sim main run for one case (sim/policies.py
    and/or sim/rollout.py): the PROTECTED physics is the measurement,
    so this replaces the plain ladder run.  Failures walk the PR 3
    supervisor ladder (:func:`_protected_rungs`: half-block →
    single-device emulation) with ``degraded_to`` recorded, exactly
    like unprotected cases.

    The block size is capped near ONE recorder window of requests:
    the control loops actuate at block boundaries, so the default
    HBM-sized block would give a whole-run actuation lag.

    ``attribution`` additionally runs the blame pass OVER THE
    PROTECTED physics (identical streams/blocking/trajectory to the
    main run): single-device reduces in the same scan; mesh-served
    cases reduce with the ``run_attributed`` collectives (per-block
    psum + top-K all_gather), bit-equal to the emulated twin.

    Returns ``(summary, timeline, roll_summary | None,
    pol_summary | None, blame_doc | None, attr_summary | None,
    degraded_to | None)``."""
    roll = tables_roll is not None
    method = "run_rollouts" if roll else "run_policies"
    # svc-sharded meshes split the per-service metric layout the
    # replicated control state needs; fall back to the single-device
    # scan for those rather than failing the case
    runner = (
        sharded
        if use_sharded and sharded is not None and sharded.n_svc == 1
        else sim
    )
    if use_sharded and sharded is not None and runner is sim:
        # the fallback is a different execution shape — say so
        # instead of silently serving a mesh-sized case on one device
        print(
            "warning: the protected co-sim falls back to the "
            "single-device scan (the svc-sharded mesh splits the "
            "per-service metric layout the replicated control state "
            "needs; use svc=1)",
            file=sys.stderr,
        )
    # a window that never completes is a control loop that never
    # observes: without an explicit --timeline width the shared law
    # sizes the default so a run spans >= ~8 windows
    win, block = _protected_window_block(
        sim, load, block, config, timeline,
        shards=getattr(runner, "n_shards", 1),
    )
    kwargs = dict(trim=True, window_s=win)
    is_sharded = runner is not sim
    if not is_sharded:
        # the sharded runner summarizes with its own collector
        kwargs["collector"] = collector
    specs = _protected_rung_specs(is_sharded, block)
    rungs = [
        (spec[0],
         (lambda s: lambda: _protected_call(
             runner, method, s, load, n, key, kwargs))(spec))
        for spec in specs
    ]
    with telemetry.phase(f"{'rollouts' if roll else 'policies'}.run"):
        out, degraded_to = run_ladder(
            rungs, policy, site_prefix="engine"
        )
    telemetry.counter_inc(f"{'rollout' if roll else 'policy'}_main_runs")
    # unpack by construction: run_rollouts -> (summary, tl, roll
    # [, pol][, attr]); run_policies -> (summary, tl, pol[, attr])
    summary, tl_main = out[0], out[1]
    rest = list(out[2:])
    roll_main = rest.pop(0) if roll else None
    pol_main = rest.pop(0) if tables_pol is not None else None
    blame_doc = attr_summary = None
    if attribution is not None:
        from isotope_tpu.metrics import attribution as attr_mod

        # replay the RUNG THAT SERVED the main run (identical streams,
        # blocking, and control trajectory), reduced to blame in the
        # same scan; mesh-served cases use the run_attributed
        # collectives (per-block psum + top-K all_gather)
        served = next(
            s for s in specs
            if s[0] == (degraded_to or specs[0][0])
        )
        try:
            with telemetry.phase("attribution.pass"):
                attr_out = _protected_call(
                    runner, method, served, load, n, key, kwargs,
                    attribution=True, tail=attribution == "tail",
                )
                attr_summary = attr_out[-1]
                jax.block_until_ready(attr_summary.count)
            blame_doc = attr_mod.to_doc(sim.compiled, attr_summary)
            telemetry.counter_inc("attribution_passes")
        except Exception as e:  # pragma: no cover - best effort
            telemetry.counter_inc("attribution_pass_failures")
            print(
                f"warning: protected attribution pass failed: {e}",
                file=sys.stderr,
            )
            attr_summary = None
    return (summary, tl_main, roll_main, pol_main, blame_doc,
            attr_summary, degraded_to)


def _protected_window_block(sim, load, block, config, timeline,
                            shards: int = 1):
    """The protected runners' shared window/block sizing: cap the
    block near ONE recorder window of requests (the control loops
    actuate at block boundaries).  ONE copy serves `_protected_run`
    (which passes the request-sharded executor's shard count) and the
    fleet path (shards=1 — the member program is the solo program),
    so fleet member 0 reproduces the solo protected run's shape on
    one device by construction."""
    if timeline is not None:
        win = float(timeline)
    else:
        win = min(
            config.timeline_window_s,
            max(load.duration_s / 8.0, 1e-3),
        )
    rate = load.qps if load.qps is not None else sim.capacity_qps()
    return win, max(
        256, min(block, int(max(rate * win / max(shards, 1), 1.0)))
    )


def _splitting_pass(sim, sharded, use_sharded, topo, load, n,
                    run_key, block, config, timeline, protected,
                    tables_roll, split, chaos_jitter):
    """Best-effort importance-splitting estimate for one case
    (sim/splitting.py): one SHORT-HORIZON fleet dispatch per level,
    members ranked by the severity statistic, the worst quantile
    cloned-and-continued with re-folded keys.  The estimate lands
    behind the ensemble artifact's schema-versioned ``splitting``
    key; a splitting failure never fails a case whose metrics
    already landed."""
    import numpy as np

    from isotope_tpu.sim import splitting as split_mod
    from isotope_tpu.sim.ensemble import EnsembleSpec

    runner = sharded if (use_sharded and sharded is not None) else sim
    n_short = max(256, int(n * split.horizon))
    roll = tables_roll is not None
    chaos = tuple(config.chaos)
    jitter = chaos_jitter if chaos else None
    # a distinct key lane: splitting fleets must not replay the
    # measurement members' streams
    base = jax.random.fold_in(run_key, 777_000_001)
    kwargs = {}
    blk = block
    if protected:
        win, blk = _protected_window_block(
            sim, load, block, config, timeline
        )
        method = getattr(
            runner,
            "run_rollouts_ensemble" if roll
            else "run_policies_ensemble",
        )
        kwargs["window_s"] = win
    else:
        method = runner.run_ensemble
    if jitter is not None:
        reps = topo.compiled.services.replicas_by_name()
        from isotope_tpu.resilience import faults as faults_mod

    def evaluate(chaos_seeds, work_seeds):
        n_m = len(work_seeds)
        espec = EnsembleSpec.of(n_m)
        mkeys = [
            jax.random.fold_in(base, int(w)) for w in work_seeds
        ]
        mc = None
        if jitter is not None:
            mc = [
                faults_mod.jitter_chaos_events(chaos, jitter, row,
                                               reps)
                for row in np.asarray(chaos_seeds)
            ]
        out = method(
            load, n_short, base, espec, block_size=blk, trim=False,
            member_keys=mkeys, member_chaos=mc, **kwargs,
        )
        return split_mod.severity_scores(
            split, out.summaries, out.timelines
        )

    try:
        with telemetry.phase("splitting.pass"):
            doc = split_mod.subset_estimate(
                evaluate, split,
                chaos_components=max(len(chaos), 1),
            )
        telemetry.counter_inc("splitting_passes")
        return doc
    except Exception as e:  # pragma: no cover - best-effort surface
        telemetry.counter_inc("splitting_pass_failures")
        print(f"warning: splitting pass failed: {e}", file=sys.stderr)
        return None


def _record_vet_memory_ratio() -> None:
    """Measured/estimated device-peak-bytes ratio gauge: pairs the
    VET-M cost-model estimate with the run's real high-water so
    ``CAPACITY_FILL`` can be calibrated from production telemetry."""
    est = telemetry.gauge_get("vet_peak_bytes_estimate")
    measured = telemetry.gauge_get("device_memory_peak_bytes_max")
    if est and measured:
        telemetry.gauge_set(
            "vet_peak_bytes_measured_ratio", measured / est
        )


def run_experiment(
    config: ExperimentConfig,
    out_dir: Optional[str] = None,
    progress=None,
    resume: bool = True,
    profile_dir: Optional[str] = None,
    export: Sequence[str] = (),
    policy: Optional[ResiliencePolicy] = None,
    vet: Optional[str] = None,
    attribution: Optional[str] = None,
    timeline: Optional[float] = None,
) -> List[RunResult]:
    """``profile_dir`` captures a ``jax.profiler`` trace per executed run
    into ``<profile_dir>/<label>/`` — the analogue of the reference's
    per-run ``perf record`` flame capture (runner.py:405-417), readable
    in TensorBoard/XProf.  ``export`` lists exporter specs (e.g.
    ``bigquery:proj.ds.table``) run over the collected results after the
    CSV is written — the collector's upload hook (fortio.py:235-242).

    Every device-touching phase runs under the resilience supervisor
    (``policy``; default from ``ISOTOPE_MAX_RETRIES`` /
    ``ISOTOPE_NO_DEGRADE``): transients retry with backoff, OOM walks
    the degradation ladder, and an unrecoverable case is recorded as
    FAILED in the checkpoint while the sweep continues — resume retries
    failed cases and never re-runs completed ones.

    ``vet`` arms the static pre-flight gate (``"on"`` / ``"strict"``;
    ``None`` reads ``$ISOTOPE_VET``): before each case executes, the
    topology is linted, the traced program audited, and the pre-flight
    cost model compared against device capacity.  Blocking findings
    fail the case (recorded like any deterministic failure); a memory
    verdict instead pre-selects the degradation-ladder rung the case
    STARTS on — when the ladder is armed, a predictable OOM is a rung
    choice, not a crash.  With ``vet`` off, none of this code runs.

    ``attribution`` (``"on"`` / ``"tail"``; requires
    ``config.attribution``) runs a critical-path blame pass per case
    after its metrics land: the blame tables ride ``RunResult.blame``
    and, with an output directory, ``<label>.blame.json`` +
    ``<label>.flame.txt`` artifacts the ``report`` command renders.

    ``timeline`` (a window width in seconds; requires
    ``config.timeline``) runs a flight-recorder pass per case: the
    windowed series ride ``RunResult.timeline`` and, with an output
    directory, a ``<label>.timeline.json`` artifact the ``report``
    command renders as per-run sparklines.

    Fleet-served cases (the ensemble axis armed) thread BOTH passes
    through the fleet dispatch itself (PR 17): blame and window
    series accumulate per member inside the fleet program, the worst
    member's become the case's blame/timeline docs (stamped with
    member + seed), and the cross-member divergence explanation lands
    in ``<label>.fleet-blame.json``
    (``isotope-fleet-blame/v1`` — the ``explain`` subcommand's
    input)."""
    from isotope_tpu.analysis.vet import vet_mode

    vet = vet_mode(vet)
    # resolve exporter specs up front: a typo'd --export must fail
    # before hours of simulation, not after
    exporters = []
    if export:
        if out_dir is None:
            # exporters write datafiles under the output directory;
            # without one they'd be silently dropped at the end
            raise ValueError(
                "export specs require out_dir (exporters write their "
                "datafiles under the run's output directory)"
            )
        from isotope_tpu.metrics.export import resolve_exporter

        exporters = [resolve_exporter(s) for s in export]

    if policy is None:
        policy = ResiliencePolicy.from_env()
    results: List[RunResult] = []
    key = jax.random.PRNGKey(config.seed)
    # "auto" | MeshSpec | None — parse/env errors surface here, before
    # anything simulates; "auto" resolves per topology (the layout
    # search needs the compiled service count)
    mesh_req = resolve_mesh_request(config)
    # scenario ensembles ([sim] ensemble / --ensemble): spec errors
    # surface here, before anything simulates
    ens_spec = config.ensemble_spec()
    # config-search brackets ([search]): likewise fail-fast on a bad
    # spec before any case compiles
    search_spec_cfg = config.search_spec()

    # Labels are the identity of a run everywhere downstream — the
    # artifact filenames, the checkpoint restore key, the CSV rows.  A
    # colliding grid (two topology files with the same stem, or a
    # duplicated load row) would silently clobber artifacts and restore
    # the wrong record, so it must fail loudly up front.
    grid_cells = [
        {"topo": topo_path, "env": env.name, "load": load,
         "label": _label(topo_path, env.name, load, config.labels),
         "idx": i}
        for i, (topo_path, env, load) in enumerate(
            (t, e, ld)
            for t in config.topology_paths
            for e in config.environments
            for ld in config.load_models()
        )
    ]
    grid_labels = [c["label"] for c in grid_cells]
    dupes = {lb for lb in grid_labels if grid_labels.count(lb) > 1}
    if dupes:
        raise ValueError(
            f"duplicate run label(s) in the sweep grid: "
            f"{sorted(dupes)} — disambiguate the topology filenames "
            "(labels use the file stem) or the load grid"
        )

    out = ckpt_path = ckpt_file = None
    done_records: List[dict] = []
    fingerprint = _config_fingerprint(config)
    # label-keyed restore (latest record wins): completed cases are
    # never re-run, FAILED and quarantined-corrupt cases are
    done: dict = {}
    if out_dir is not None:
        out = pathlib.Path(out_dir)
        out.mkdir(parents=True, exist_ok=True)
        ckpt_path = out / "checkpoint.jsonl"
        if resume:
            done_records = _load_checkpoint(ckpt_path, fingerprint)
        # rewrite via temp + atomic rename: drops any truncated tail a
        # kill left behind, guarantees appends start on a fresh line,
        # and a kill during the rewrite itself cannot lose the old file
        tmp_path = out / "checkpoint.jsonl.tmp"
        with open(tmp_path, "w") as tmp:
            tmp.write(json.dumps({"config": fingerprint}) + "\n")
            for rec in done_records:
                tmp.write(json.dumps(rec) + "\n")
            tmp.flush()
            os.fsync(tmp.fileno())
        os.replace(tmp_path, ckpt_path)
        ckpt_file = open(ckpt_path, "a")
        for rec in done_records:
            done[rec["label"]] = rec

    ens_groups = None
    if ens_spec is not None:
        completed = {
            lb for lb, rec in done.items() if not rec.get("failed")
        }
        ens_groups = _EnsembleGroups(
            config, ens_spec, key, grid_cells, completed
        )

    try:
        run_index = 0
        for topo_path in config.topology_paths:
            topo = _LazyTopology(topo_path, config, mesh_req)
            for env in config.environments:
                for load in config.load_models():
                    label = _label(topo_path, env.name, load, config.labels)
                    rec = done.get(label)
                    if rec is not None and not rec.get("failed"):
                        results.append(_restore_result(rec, out))
                        run_index += 1
                        continue
                    if progress:
                        progress(label)
                    if telemetry.emitting():
                        # per-run records: each telemetry.jsonl line
                        # covers exactly ONE run (the README reading
                        # guide depends on it) — reset before this
                        # run's simulators build/compile/execute
                        telemetry.reset()
                    run_key = jax.random.fold_in(key, run_index)
                    if profile_dir is not None:
                        prof_ctx = jax.profiler.trace(
                            str(pathlib.Path(profile_dir) / label)
                        )
                    else:
                        prof_ctx = contextlib.nullcontext()
                    try:
                        with prof_ctx:
                            # engine build (device-constant upload, first
                            # compile triggers inside the run) is itself
                            # a supervised phase
                            sim, sharded = call_with_retries(
                                lambda: topo.sims(env),
                                site="engine.build", policy=policy,
                            )
                            n = _num_requests(
                                load, sim.capacity_qps(),
                                config.num_requests,
                            )
                            # the scan path is the product path: requests
                            # stream through HBM-bounded blocks, metrics
                            # and the trim window accumulate on device
                            block = sim.default_block_size()
                            use_sharded = sharded is not None and (
                                load.kind == OPEN_LOOP
                                or load.connections % sharded.n_shards
                                == 0
                            )
                            rungs = execution_rungs(
                                sim, sharded, use_sharded, load, n,
                                run_key, block,
                                collector=topo.collector, trim=True,
                            )
                            protected = (
                                topo.policy_tables is not None
                                or topo.rollout_tables is not None
                            )
                            start_rung = 0
                            if vet is not None:
                                start_rung = _vet_gate(
                                    vet, sim, topo, config, load,
                                    block, rungs, policy,
                                    # fleet verdicts for every case a
                                    # fleet serves — protected fleets
                                    # get the carry-aware VET-T025
                                    # variant
                                    ensemble=ens_spec,
                                    protected=protected,
                                    split_spec=config.ensemble_split,
                                    search_spec=search_spec_cfg,
                                )
                            tl_main = pol_main = roll_main = None
                            pol_blame = pol_attr = None
                            ens_summary = None
                            prot_fleet = False
                            prot_worst = None
                            if ens_groups is not None \
                                    and not protected \
                                    and start_rung == 0:
                                # Monte Carlo fleet: the case's N seed
                                # members run as ONE vmapped dispatch
                                # (same-shape grid cells collapse into
                                # it); the reported summary pools the
                                # members and the distributional view
                                # lands in <label>.ensemble.json.  A
                                # fleet failure falls back to the solo
                                # ladder below — never fails the case.
                                # Memory-degraded cases (the vet
                                # verdict pre-selected a ladder rung)
                                # skip the fleet outright: even a
                                # one-member chunk runs the full
                                # block, and a TPU HBM overflow is
                                # not reliably a catchable exception.
                                try:
                                    with telemetry.phase(
                                        "ensemble.run"
                                    ):
                                        ens_summary = ens_groups.run(
                                            label, topo_path,
                                            env.name, load, sim,
                                            sharded, use_sharded, n,
                                            block,
                                            attribution=attribution,
                                            timeline=timeline,
                                        )
                                    telemetry.counter_inc(
                                        "ensemble_cases"
                                    )
                                    telemetry.set_meta(
                                        "ensemble",
                                        str(ens_summary.members),
                                    )
                                except Exception as e:
                                    telemetry.counter_inc(
                                        "ensemble_fallbacks"
                                    )
                                    # the solo fallback serves this
                                    # cell: keep later groups from
                                    # re-dispatching its members
                                    ens_groups.completed.add(label)
                                    print(
                                        f"warning: ensemble dispatch "
                                        f"for {label} failed "
                                        f"({type(e).__name__}: {e}); "
                                        "falling back to the solo "
                                        "run",
                                        file=sys.stderr,
                                    )
                            if protected:
                                # policy/rollout co-sim: the PROTECTED
                                # run IS the measurement.  With the
                                # ensemble axis armed it dispatches as
                                # a FLEET (PR 15 — the pre-fleet
                                # protected-solo fallback is deleted):
                                # member 0 rides the run key, so it is
                                # bit-equal to the solo protected run,
                                # and the worst member's artifacts
                                # become the postmortem.  Attributed
                                # cases thread the blame pass through
                                # the SAME fleet dispatch (PR 17 —
                                # the solo-path detour is deleted);
                                # memory-degraded cases keep the solo
                                # path.
                                degraded_to = None
                                if ens_spec is not None \
                                        and start_rung == 0:
                                    try:
                                        # the same-shape collapse
                                        # serves protected cases too
                                        # (PR 18): grid cells sharing
                                        # a fleet shape ride one
                                        # protected dispatch
                                        ens_summary = \
                                            ens_groups.run_protected(
                                                label, topo_path,
                                                env.name, load, sim,
                                                sharded, use_sharded,
                                                n, block,
                                                topo.rollout_tables,
                                                config
                                                .chaos_jitter_spec(),
                                                attribution=(
                                                    attribution
                                                ),
                                                timeline=timeline,
                                            )
                                        prot_fleet = True
                                        summary = \
                                            ens_summary.pooled()
                                        prot_worst = (
                                            ens_summary
                                            .worst_member()
                                        )
                                        tl_main = (
                                            ens_summary
                                            .member_timeline(
                                                prot_worst
                                            )
                                        )
                                        if ens_summary.policies \
                                                is not None:
                                            pol_main = (
                                                ens_summary
                                                .member_policies(
                                                    prot_worst
                                                )
                                            )
                                        if ens_summary.rollouts \
                                                is not None:
                                            roll_main = (
                                                ens_summary
                                                .member_rollouts(
                                                    prot_worst
                                                )
                                            )
                                        if ens_summary.attributions \
                                                is not None:
                                            # the worst member's
                                            # blame IS the postmortem
                                            # blame doc (stamped with
                                            # member/seed below)
                                            from isotope_tpu.metrics \
                                                import attribution \
                                                as attr_mod

                                            pol_attr = (
                                                ens_summary
                                                .member_attribution(
                                                    prot_worst
                                                )
                                            )
                                            pol_blame = (
                                                attr_mod.to_doc(
                                                    topo.compiled,
                                                    pol_attr,
                                                )
                                            )
                                        telemetry.counter_inc(
                                            "ensemble_cases"
                                        )
                                        telemetry.set_meta(
                                            "ensemble",
                                            str(ens_summary.members),
                                        )
                                    except Exception as e:
                                        telemetry.counter_inc(
                                            "ensemble_fallbacks"
                                        )
                                        # the solo fallback serves
                                        # this cell: keep later
                                        # groups from re-dispatching
                                        # its members
                                        ens_groups.completed.add(
                                            label
                                        )
                                        print(
                                            f"warning: protected "
                                            f"fleet dispatch for "
                                            f"{label} failed "
                                            f"({type(e).__name__}: "
                                            f"{e}); falling back to "
                                            "the solo protected run",
                                            file=sys.stderr,
                                        )
                                if not prot_fleet:
                                    (summary, tl_main, roll_main,
                                     pol_main, pol_blame, pol_attr,
                                     degraded_to) = _protected_run(
                                        sim, sharded, use_sharded,
                                        load, n, run_key, block,
                                        config, topo.collector,
                                        policy, timeline,
                                        topo.policy_tables,
                                        topo.rollout_tables,
                                        attribution=attribution,
                                    )
                            elif ens_summary is not None:
                                summary = ens_summary.pooled()
                                degraded_to = None
                            else:
                                summary, degraded_to = run_ladder(
                                    rungs[start_rung:], policy,
                                    site_prefix="engine",
                                )
                            if start_rung and degraded_to is None \
                                    and not protected \
                                    and ens_summary is None:
                                # the pre-selected rung IS a
                                # degradation: record it exactly as a
                                # ladder descent would have (bench
                                # gates key on degraded_to presence)
                                degraded_to = rungs[start_rung][0]
                                telemetry.set_meta(
                                    "degraded_to", degraded_to
                                )
                    except Exception as e:
                        # unrecoverable for THIS case (deterministic
                        # error, retries/ladder exhausted): record it,
                        # keep the sweep alive — the reference's sweeps
                        # survive one broken deployment the same way
                        err_class = classify(e)
                        err_text = f"{type(e).__name__}: {e}"
                        telemetry.counter_inc("run_failures")
                        print(
                            f"error: run {label} failed "
                            f"({err_class}): {err_text}",
                            file=sys.stderr,
                        )
                        failed = RunResult(
                            label=label,
                            topology=topo_path,
                            environment=env.name,
                            flat={"Labels": label, "failed": True,
                                  "error": err_text},
                            window=_failed_window(err_text),
                            fortio_json={},
                            prometheus_text="",
                            failed=True,
                            error=err_text,
                        )
                        results.append(failed)
                        if ckpt_file is not None:
                            ckpt_file.write(
                                json.dumps(
                                    {
                                        "label": label,
                                        "topology": topo_path,
                                        "environment": env.name,
                                        "failed": True,
                                        "error": err_text[:1000],
                                        "error_class": err_class,
                                    }
                                )
                                + "\n"
                            )
                            ckpt_file.flush()
                        run_index += 1
                        continue
                    blame_doc = attr_summary = None
                    if protected:
                        # the protected attributed pass (if requested)
                        # already ran inside _protected_run with the
                        # same streams/trajectory as the measurement
                        blame_doc, attr_summary = pol_blame, pol_attr
                    elif attribution is not None:
                        if ens_summary is not None and \
                                ens_summary.attributions is not None:
                            # the fleet already carried the blame
                            # pass per member (PR 17): the worst
                            # member's blame is the case's blame doc,
                            # stamped so the bad day replays solo
                            from isotope_tpu.metrics import (
                                attribution as attr_mod,
                            )

                            worst = ens_summary.worst_member()
                            attr_summary = (
                                ens_summary.member_attribution(worst)
                            )
                            blame_doc = attr_mod.to_doc(
                                topo.compiled, attr_summary,
                            )
                            blame_doc.update({
                                "member": int(worst),
                                "member_seed": int(
                                    ens_summary.spec.seeds[worst]
                                ),
                                "fleet_members": (
                                    ens_summary.members
                                ),
                                "worst_member": True,
                            })
                        else:
                            # identical executor/key/blocking to the
                            # main run, so the attributed pass replays
                            # the same request streams the reported
                            # metrics came from
                            blame_doc, attr_summary = (
                                _attribution_pass(
                                    sim, sharded, use_sharded, topo,
                                    load, n, run_key, block,
                                    tail=attribution == "tail",
                                )
                            )
                    tl_doc = tl_summary = None
                    pol_doc = pol_summary_out = None
                    roll_doc = roll_summary_out = None
                    lb_doc = None
                    if protected:
                        # the protected run already reduced the
                        # timeline next to the control series — no
                        # separate recorder pass needed.  Fleet-served
                        # cases report the MOST-SEVERE member's
                        # artifacts, stamped with its member index and
                        # seed, so a rare failure the fleet found is
                        # immediately replayable solo.
                        from isotope_tpu.metrics import (
                            timeline as timeline_mod,
                        )

                        tl_summary = tl_main
                        tl_doc = timeline_mod.to_doc(
                            topo.compiled, tl_main
                        )
                        if pol_main is not None:
                            from isotope_tpu.sim import (
                                policies as policies_mod,
                            )

                            pol_summary_out = pol_main
                            pol_doc = policies_mod.to_doc(
                                topo.compiled, pol_main,
                                topo.policy_tables,
                            )
                        if roll_main is not None:
                            from isotope_tpu.sim import (
                                rollout as rollout_mod,
                            )

                            roll_summary_out = roll_main
                            roll_doc = rollout_mod.to_doc(
                                topo.compiled, roll_main,
                                topo.rollout_tables,
                            )
                        if prot_fleet:
                            stamp = {
                                "member": int(prot_worst),
                                # member 0 is the CONTROL member: it
                                # rides the RUN key itself, so the
                                # replay recipe is the solo run, not
                                # a folded seed
                                "member_seed": (
                                    None if prot_worst == 0 else int(
                                        ens_spec.seeds[prot_worst]
                                    )
                                ),
                                "member_key": (
                                    "run_key" if prot_worst == 0
                                    else "fold_in(run_key, "
                                         "member_seed)"
                                ),
                                "fleet_members": (
                                    ens_summary.members
                                ),
                                "worst_member": True,
                            }
                            if ens_summary.member_chaos is not None:
                                stamp["member_chaos"] = [
                                    {
                                        "service": ev.service,
                                        "start_s": float(ev.start_s),
                                        "end_s": float(ev.end_s),
                                        "replicas_down": (
                                            ev.replicas_down
                                        ),
                                        "drain": ev.drain,
                                    }
                                    for ev in ens_summary
                                    .member_chaos[prot_worst]
                                ]
                            for d in (tl_doc, pol_doc, roll_doc,
                                      blame_doc):
                                if d is not None:
                                    d.update(stamp)
                    elif timeline is not None:
                        if ens_summary is not None and \
                                ens_summary.timelines is not None:
                            # the fleet already carried the recorder
                            # per member: the worst member's window
                            # series is the case's timeline doc
                            from isotope_tpu.metrics import (
                                timeline as timeline_mod,
                            )

                            worst = ens_summary.worst_member()
                            tl_summary = (
                                ens_summary.member_timeline(worst)
                            )
                            tl_doc = timeline_mod.to_doc(
                                topo.compiled, tl_summary,
                            )
                            tl_doc.update({
                                "member": int(worst),
                                "member_seed": int(
                                    ens_summary.spec.seeds[worst]
                                ),
                                "fleet_members": (
                                    ens_summary.members
                                ),
                                "worst_member": True,
                            })
                        else:
                            tl_doc, tl_summary = _timeline_pass(
                                sim, sharded, use_sharded, topo,
                                load, n, run_key, block,
                                window_s=timeline,
                            )
                    if (
                        topo.lb_tables is not None
                        and topo.lb_tables.active
                    ):
                        # ACTIVE laws only: an all-fifo/no-panic block
                        # is the pinned neutral path — marking it _lb
                        # would mislabel a plain-M/M/k measurement.
                        # Static law/split always; the per-window
                        # per-backend census when a recorder ran (and
                        # the actuated pool sizes when PR 9 loops did)
                        from isotope_tpu.sim import lb as lb_mod

                        lb_doc = lb_mod.to_doc(
                            topo.lb_tables,
                            tl=tl_summary, pol=pol_summary_out,
                        )
                    doc = fortio_result_from_summary(
                        summary, load, labels=label,
                        response_size_bytes=topo.entry_response_size,
                    )
                    if ens_summary is not None:
                        # the pooled count spans N member WORLDS of
                        # one wall-clock each: normalize the rate to
                        # per-member so ActualQPS stays comparable to
                        # RequestedQPS (and to pre-ensemble rows in
                        # report.py's label-joined regression view);
                        # counts/histograms stay pooled — they are
                        # sample sizes, and errorPercent is a ratio
                        doc["ActualQPS"] /= ens_summary.members
                    flat = convert_data(doc)
                    window = window_summary_from_summary(
                        summary,
                        service_names=topo.compiled.services.names,
                        replicas=topo.compiled.services.replicas,
                    )
                    if ens_summary is not None:
                        window = dataclasses.replace(
                            window,
                            qps=window.qps / ens_summary.members,
                        )
                    flat["windowDiscarded"] = window.discarded
                    if use_sharded and topo.mesh_layout:
                        # the factorization that served the case is run
                        # METADATA (like degraded_to): a record produced
                        # by a different mesh layout is a different
                        # measurement, and bench gates key on it
                        flat["_mesh_layout"] = topo.mesh_layout
                        telemetry.set_meta(
                            "mesh_layout", topo.mesh_layout
                        )
                    if degraded_to is not None:
                        # degradation is run METADATA: a sweep row that
                        # came off a fallback rung must say so (and
                        # bench_regress fails a capture that degrades a
                        # previously-clean case)
                        flat["degraded_to"] = degraded_to
                    if pol_doc is not None:
                        # the row came from PROTECTED physics — a
                        # different measurement than an unprotected
                        # run of the same grid cell
                        flat["_policies"] = True
                        telemetry.set_meta("policies", "on")
                    if roll_doc is not None:
                        # likewise for the rollout controller: bench
                        # and bench_regress key on the marker so a
                        # rollout-enabled case is never compared
                        # against an open-loop twin
                        flat["_rollout"] = True
                        telemetry.set_meta("rollouts", "on")
                    if lb_doc is not None:
                        # lb laws change the wait physics of every run
                        # kind — the marker keeps bench_regress from
                        # comparing an lb row against a fifo twin
                        flat["_lb"] = True
                        telemetry.set_meta("lb", "on")
                    if config.ingest:
                        # the row replays FITTED telemetry, not a
                        # hand-written topology — different
                        # provenance; bench_regress keys on the
                        # marker so an ingested replay is never
                        # compared against a hand-written twin
                        flat["_ingest"] = str(
                            config.ingest.get("label", "ingested")
                        )
                        telemetry.set_meta("ingest", flat["_ingest"])
                    ens_doc = None
                    fb_doc = None
                    if ens_summary is not None:
                        # the row POOLS N seed members — a tighter
                        # estimate than a solo run of the same cell,
                        # but a different measurement; the marker
                        # keeps comparisons honest and the artifact
                        # carries the distributional view
                        split_doc = None
                        if config.ensemble_split:
                            # importance splitting (sim/splitting.py):
                            # resolve the rare-outage tail the fleet's
                            # Wilson interval cannot, one short-
                            # horizon fleet dispatch per level
                            split_doc = _splitting_pass(
                                sim, sharded, use_sharded, topo,
                                load, n, run_key, block, config,
                                timeline, protected,
                                topo.rollout_tables,
                                config.split_spec(),
                                config.chaos_jitter_spec(),
                            )
                        ens_doc = ens_summary.to_doc(
                            label=label,
                            slo_s=config.ensemble_slo_s,
                            splitting=split_doc,
                        )
                        flat["_ensemble"] = ens_summary.members
                        if prot_fleet:
                            flat["_protected_fleet"] = True
                            if ens_doc.get("worst_member") == 0:
                                # the control member rides the RUN
                                # key, not a folded seed — the
                                # replay recipe is the solo run
                                ens_doc["worst_member_seed"] = None
                        if ens_summary.attributions is not None:
                            # fleet divergence explainer (PR 17):
                            # band the per-hop blame shares across
                            # members, rank who diverged and why,
                            # localize the window of onset — one
                            # device reduce, one readback.  Best
                            # effort: an explainer failure never
                            # fails a case whose metrics landed.
                            import numpy as _np

                            from isotope_tpu.metrics import (
                                fleetblame,
                            )

                            try:
                                win_arr = None
                                if ens_summary.timelines is not None:
                                    win_arr = float(
                                        _np.asarray(
                                            ens_summary.timelines
                                            .window_s
                                        ).reshape(-1)[0]
                                    )
                                fb_doc = fleetblame.to_doc(
                                    topo.compiled,
                                    ens_summary.attributions,
                                    ens_summary.timelines,
                                    label=label,
                                    severity=(
                                        ens_summary.severity()
                                    ),
                                    seeds=ens_summary.spec.seeds,
                                    window_s=win_arr,
                                )
                                flat["_fleet_blame"] = True
                                telemetry.counter_inc(
                                    "fleet_blame_docs"
                                )
                            except Exception as e:
                                telemetry.counter_inc(
                                    "fleet_blame_failures"
                                )
                                print(
                                    f"warning: fleet-blame "
                                    f"explainer for {label} failed "
                                    f"({type(e).__name__}: {e})",
                                    file=sys.stderr,
                                )
                    search_doc = None
                    if search_spec_cfg is not None \
                            and not protected \
                            and start_rung == 0:
                        # successive-halving config search
                        # (sim/search.py): the bracket screens N
                        # traced perturbations of THIS case and
                        # rides its own key lane, so the reported
                        # measurement above is untouched.  Best
                        # effort like the ensemble axis: a bracket
                        # failure never fails the case.  Memory-
                        # degraded cases skip it outright (the
                        # widest rung is the ensemble problem VET-M
                        # pre-selected a rung for).
                        try:
                            with telemetry.phase("search.run"):
                                srch = (
                                    sharded.run_search
                                    if use_sharded
                                    else sim.run_search
                                )(
                                    load, n,
                                    jax.random.fold_in(
                                        run_key, 911
                                    ),
                                    search_spec_cfg,
                                    block_size=block,
                                )
                            search_doc = srch.to_doc(label)
                            # the marker keeps bench_regress from
                            # comparing a search-carrying row
                            # against a plain twin
                            flat["_search"] = (
                                search_spec_cfg.members
                            )
                            telemetry.counter_inc("search_cases")
                            telemetry.set_meta(
                                "search",
                                str(search_spec_cfg.members),
                            )
                        except Exception as e:
                            telemetry.counter_inc(
                                "search_fallbacks"
                            )
                            print(
                                f"warning: config-search bracket "
                                f"for {label} failed "
                                f"({type(e).__name__}: {e}); the "
                                "case keeps its solo measurement",
                                file=sys.stderr,
                            )
                    flat.update(
                        {
                            "cpu_cores_" + name: round(v, 4)
                            for name, v in window.cpu_cores.items()
                        }
                    )
                    # full exposition: the five service series plus the
                    # sim-side resource series the alarm queries read
                    prom_text = topo.collector.full_text(summary)
                    run_telem = None
                    if telemetry.emitting():
                        # one scrape sees workload AND engine: append
                        # the isotope_engine_* series to the exposition
                        telemetry.record_device_memory()
                        _record_vet_memory_ratio()
                        run_telem = telemetry.snapshot(label=label)
                        prom_text += run_telem.prometheus_text()
                    result = RunResult(
                        label=label,
                        topology=topo_path,
                        environment=env.name,
                        flat=flat,
                        window=window,
                        fortio_json=doc,
                        prometheus_text=prom_text,
                        telemetry=(
                            run_telem.to_dict() if run_telem else None
                        ),
                        degraded_to=degraded_to,
                        blame=blame_doc,
                        attribution=attr_summary,
                        compiled=(
                            topo.compiled
                            if attr_summary is not None
                            or tl_summary is not None
                            else None
                        ),
                        timeline=tl_doc,
                        timeline_summary=tl_summary,
                        policies=pol_doc,
                        policies_summary=pol_summary_out,
                        rollouts=roll_doc,
                        rollouts_summary=roll_summary_out,
                        lb=lb_doc,
                        ensemble=ens_doc,
                        ensemble_summary=ens_summary,
                        fleet_blame=fb_doc,
                        search=search_doc,
                    )
                    results.append(result)
                    if out is not None:
                        # per-run artifacts + checkpoint line land NOW,
                        # so a kill loses at most the in-flight run
                        with open(out / f"{label}.json", "w") as f:
                            json.dump(doc, f, indent=2)
                        (out / f"{label}.prom").write_text(prom_text)
                        if blame_doc is not None:
                            with open(
                                out / f"{label}.blame.json", "w"
                            ) as f:
                                json.dump(blame_doc, f, indent=2)
                        if tl_doc is not None:
                            with open(
                                out / f"{label}.timeline.json", "w"
                            ) as f:
                                json.dump(tl_doc, f, indent=2)
                        if pol_doc is not None:
                            with open(
                                out / f"{label}.policies.json", "w"
                            ) as f:
                                json.dump(pol_doc, f, indent=2)
                        if roll_doc is not None:
                            with open(
                                out / f"{label}.rollout.json", "w"
                            ) as f:
                                json.dump(roll_doc, f, indent=2)
                        if lb_doc is not None:
                            with open(
                                out / f"{label}.lb.json", "w"
                            ) as f:
                                json.dump(lb_doc, f, indent=2)
                        if ens_doc is not None:
                            with open(
                                out / f"{label}.ensemble.json", "w"
                            ) as f:
                                json.dump(ens_doc, f, indent=2)
                        if fb_doc is not None:
                            with open(
                                out / f"{label}.fleet-blame.json",
                                "w",
                            ) as f:
                                json.dump(fb_doc, f, indent=2)
                        if search_doc is not None:
                            with open(
                                out / f"{label}.search.json", "w"
                            ) as f:
                                json.dump(search_doc, f, indent=2)
                        if attr_summary is not None:
                            from isotope_tpu.metrics.export import (
                                write_flamegraph,
                            )

                            write_flamegraph(
                                out / f"{label}.flame.txt",
                                topo.compiled, attr_summary,
                            )
                        if run_telem is not None:
                            run_telem.append_jsonl(out / "telemetry.jsonl")
                        rec_out = {
                            "label": label,
                            "topology": topo_path,
                            "environment": env.name,
                            "flat": flat,
                            "window": dataclasses.asdict(window),
                            "fortio_json": doc,
                        }
                        if degraded_to is not None:
                            rec_out["degraded_to"] = degraded_to
                        ckpt_file.write(json.dumps(rec_out) + "\n")
                        ckpt_file.flush()
                    run_index += 1
    finally:
        if ckpt_file is not None:
            ckpt_file.close()

    ok = [r for r in results if not r.failed]
    if out is not None:
        with open(out / "results.jsonl", "w") as f:
            for r in results:
                f.write(json.dumps(r.flat) + "\n")
        # the per-service cpu_cores_<svc> columns are record-dependent;
        # append them so `plot --metrics cpu_cores_<svc>` works off this CSV
        extra_keys = sorted(
            {k for r in ok for k in r.flat if k.startswith("cpu_cores_")}
        )
        keys = DEFAULT_CSV_KEYS
        if extra_keys:
            keys = keys + "," + ",".join(extra_keys)
        write_csv(
            keys,
            [r.flat for r in ok],
            out / "benchmark.csv",
        )
        for exporter in exporters:
            print(exporter(results, out), file=sys.stderr)
    n_failed = len(results) - len(ok)
    if n_failed:
        print(
            f"warning: {n_failed} run(s) failed and were recorded in "
            "the checkpoint; re-invoke with the same config to retry "
            "them",
            file=sys.stderr,
        )
    return results
