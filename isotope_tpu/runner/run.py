"""The sweep driver: topology x environment x connections x qps.

Mirrors the shape of the reference's drivers (run_tests.py:35-44 outer
product; runner.py:522-525 conn x qps grid; fortio.py artifact formats)
with compilation replacing deployment and simulation replacing ``kubectl
exec fortio load``.
"""
from __future__ import annotations

import dataclasses
import json
import pathlib
from typing import List, Optional

import jax

from isotope_tpu.compiler import compile_graph
from isotope_tpu.metrics.fortio import (
    DEFAULT_CSV_KEYS,
    WindowSummary,
    convert_data,
    fortio_result_from_summary,
    window_summary_from_summary,
    write_csv,
)
from isotope_tpu.metrics.prometheus import MetricsCollector
from isotope_tpu.models.graph import ServiceGraph
from isotope_tpu.parallel import ShardedSimulator, make_mesh
from isotope_tpu.runner.config import ExperimentConfig
from isotope_tpu.sim.config import OPEN_LOOP, LoadModel
from isotope_tpu.sim.engine import Simulator


@dataclasses.dataclass
class RunResult:
    label: str
    topology: str
    environment: str
    flat: dict                    # the reference's single-line schema
    window: WindowSummary
    fortio_json: dict
    prometheus_text: str


def _label(topo_path: str, env: str, load: LoadModel, extra: str) -> str:
    stem = pathlib.Path(topo_path).stem
    qps = "max" if load.qps is None else f"{load.qps:g}"
    base = f"{stem}_{env.lower()}_{qps}qps_{load.connections}c"
    return f"{base}_{extra}" if extra else base


def _num_requests(load: LoadModel, capacity: float, cap: int) -> int:
    """Size the batch so the simulated run spans ``load.duration_s``."""
    rate = capacity if load.qps is None else min(load.qps, capacity)
    return max(1, min(int(rate * load.duration_s), cap))


def run_experiment(
    config: ExperimentConfig,
    out_dir: Optional[str] = None,
    progress=None,
) -> List[RunResult]:
    results: List[RunResult] = []
    key = jax.random.PRNGKey(config.seed)
    mesh_svc = max(config.mesh_svc, 1)
    mesh_data = (
        config.mesh_data
        if config.mesh_data > 0
        else max(jax.device_count() // mesh_svc, 1)
    )
    use_mesh = mesh_data * mesh_svc > 1

    for topo_path in config.topology_paths:
        graph = ServiceGraph.from_yaml_file(topo_path)
        topo_yaml_entry = graph.entrypoints()
        entry_resp = (
            float(int(topo_yaml_entry[0].response_size))
            if topo_yaml_entry
            else 0.0
        )
        compiled = compile_graph(graph)
        collector = MetricsCollector(compiled)
        for env in config.environments:
            params = env.apply(config.sim_params())
            sim = Simulator(compiled, params, config.chaos)
            sharded = (
                ShardedSimulator(
                    compiled,
                    make_mesh(mesh_data, mesh_svc),
                    params,
                    config.chaos,
                )
                if use_mesh
                else None
            )
            for i, load in enumerate(config.load_models()):
                label = _label(topo_path, env.name, load, config.labels)
                if progress:
                    progress(label)
                run_key = jax.random.fold_in(key, len(results))
                n = _num_requests(
                    load, sim.capacity_qps(), config.num_requests
                )
                # the scan path is the product path: requests stream
                # through HBM-bounded blocks, metrics and the trim window
                # accumulate on device — 1M-request runs fit on one chip
                block = sim.default_block_size()
                use_sharded = sharded is not None and (
                    load.kind == OPEN_LOOP
                    or load.connections % sharded.n_shards == 0
                )
                if use_sharded:
                    summary = sharded.run(
                        load, n, run_key, block_size=block, trim=True
                    )
                else:
                    summary = sim.run_summary(
                        load, n, run_key, block_size=block,
                        collector=collector, trim=True,
                    )
                doc = fortio_result_from_summary(
                    summary, load, labels=label,
                    response_size_bytes=entry_resp,
                )
                flat = convert_data(doc)
                window = window_summary_from_summary(
                    summary,
                    service_names=compiled.services.names,
                    replicas=compiled.services.replicas,
                )
                flat["windowDiscarded"] = window.discarded
                flat.update(
                    {
                        "cpu_cores_" + name: round(v, 4)
                        for name, v in window.cpu_cores.items()
                    }
                )
                prom_text = collector.to_text(summary.metrics)
                results.append(
                    RunResult(
                        label=label,
                        topology=topo_path,
                        environment=env.name,
                        flat=flat,
                        window=window,
                        fortio_json=doc,
                        prometheus_text=prom_text,
                    )
                )

    if out_dir is not None:
        out = pathlib.Path(out_dir)
        out.mkdir(parents=True, exist_ok=True)
        with open(out / "results.jsonl", "w") as f:
            for r in results:
                f.write(json.dumps(r.flat) + "\n")
        for r in results:
            with open(out / f"{r.label}.json", "w") as f:
                json.dump(r.fortio_json, f, indent=2)
            (out / f"{r.label}.prom").write_text(r.prometheus_text)
        # the per-service cpu_cores_<svc> columns are record-dependent;
        # append them so `plot --metrics cpu_cores_<svc>` works off this CSV
        extra_keys = sorted(
            {k for r in results for k in r.flat if k.startswith("cpu_cores_")}
        )
        keys = DEFAULT_CSV_KEYS
        if extra_keys:
            keys = keys + "," + ",".join(extra_keys)
        write_csv(
            keys,
            [r.flat for r in results],
            out / "benchmark.csv",
        )
    return results
