"""isotope-tpu command-line interface.

The TPU-native counterpart of the reference's ``service-grapher`` cobra CLI
(isotope/convert/cmd/root.go:25-28) plus the benchmark runner entry points.
Subcommands are registered as they are built; ``kubernetes`` and ``graphviz``
mirror the converter, ``generate`` the topology generators, ``simulate`` /
``sweep`` the load-test drivers, and ``ingest`` the reverse path —
observed telemetry (Prometheus, Envoy stats, CSV traces) fitted back
into a runnable topology + schedule with an isotope-ingest/v1
fidelity report.
"""
from __future__ import annotations

import argparse
import sys


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="isotope-tpu",
        description="TPU-native isotope: service-graph traffic simulation",
    )
    sub = parser.add_subparsers(dest="command")
    from isotope_tpu.commands import register_all

    register_all(sub)
    return parser


def main(argv=None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    if not getattr(args, "func", None):
        parser.print_help()
        return 2
    try:
        return args.func(args) or 0
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
