"""Go-compatible duration parsing and formatting.

Sleep commands in topology YAML use Go ``time.ParseDuration`` strings
("100ms", "1.5s", "1h2m3s"); the reference stores them as ``time.Duration``
(isotope/convert/pkg/graph/script/sleep_command.go:23-38). We parse the same
grammar and format with the same rules as Go's ``Duration.String()`` so
round-tripped YAML matches the reference's output.
"""
from __future__ import annotations

import re

# Unit -> nanoseconds, per Go time.ParseDuration.
_UNITS = {
    "ns": 1,
    "us": 1_000,
    "µs": 1_000,
    "μs": 1_000,
    "ms": 1_000_000,
    "s": 1_000_000_000,
    "m": 60 * 1_000_000_000,
    "h": 3600 * 1_000_000_000,
}

_TOKEN = re.compile(r"(\d+(?:\.\d*)?|\.\d+)(ns|us|µs|μs|ms|s|m|h)")


class InvalidDurationError(ValueError):
    def __init__(self, s: str):
        super().__init__(f"time: invalid duration {s!r}")


def parse_duration_ns(s: str) -> int:
    """Parse a Go duration string to integer nanoseconds.

    Accepts a sign, then one or more (number, unit) tokens; "0" is allowed
    without a unit. Mirrors Go time.ParseDuration's grammar.
    """
    if not isinstance(s, str) or not s:
        raise InvalidDurationError(s)
    orig = s
    sign = 1
    if s[0] in "+-":
        sign = -1 if s[0] == "-" else 1
        s = s[1:]
    if s == "0":
        return 0
    pos = 0
    total = 0.0
    found = False
    while pos < len(s):
        m = _TOKEN.match(s, pos)
        if m is None:
            raise InvalidDurationError(orig)
        total += float(m.group(1)) * _UNITS[m.group(2)]
        pos = m.end()
        found = True
    if not found:
        raise InvalidDurationError(orig)
    return sign * int(round(total))


def parse_duration_seconds(s: str) -> float:
    return parse_duration_ns(s) / 1e9


def format_duration_ns(ns: int) -> str:
    """Format nanoseconds the way Go's ``Duration.String()`` does.

    < 1s uses ns/us/ms with fractional digits; >= 1s uses h/m/s. Trailing
    zero fractions are trimmed. Examples: 0 -> "0s", 10ms -> "10ms",
    90s -> "1m30s", 1.5s -> "1.5s".
    """
    if ns == 0:
        return "0s"
    sign = "-" if ns < 0 else ""
    ns = abs(ns)
    if ns < 1_000:
        return f"{sign}{ns}ns"
    if ns < 1_000_000:
        return sign + _trim(ns / 1_000) + "µs"
    if ns < 1_000_000_000:
        return sign + _trim(ns / 1_000_000) + "ms"
    secs = ns / 1e9
    h = int(secs // 3600)
    rem = secs - h * 3600
    m = int(rem // 60)
    s_part = rem - m * 60
    out = ""
    if h:
        out += f"{h}h"
    if m or h:
        out += f"{m}m"
    out += _trim(s_part) + "s"
    return sign + out


def _trim(x: float) -> str:
    out = f"{x:.9f}".rstrip("0").rstrip(".")
    return out if out else "0"


def format_duration_seconds(seconds: float) -> str:
    return format_duration_ns(int(round(seconds * 1e9)))
