"""isotope-tpu: TPU-native service-mesh traffic laboratory.

Re-implements the capabilities of istio-isotope (istio/tools) — declarative
service-graph topologies, mock-service execution semantics, load generation,
and Fortio/Prometheus-compatible metrics — as a vectorized discrete-event
simulation compiled with JAX for TPU meshes.

Layer map (mirrors SURVEY.md §1):
  models/    L0 graph IR: Service/Script/Command types, YAML codec, validation,
             topology generators.
  ops/       graph -> tensor-plan compiler + the jitted event-step engine
             (the TPU-native analogue of isotope/service's script executor).
  parallel/  mesh construction and sharded execution (pjit/shard_map).
  metrics/   Fortio-style percentile summaries and isotope's five Prometheus
             series, drop-in compatible layouts.
  convert/   parity exporters: Kubernetes manifests and Graphviz DOT.
  utils/     Go-compatible duration parsing, config loading.
"""

__version__ = "0.1.0"

from isotope_tpu.models.graph import ServiceGraph  # noqa: F401
