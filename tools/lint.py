"""Repo lint driver (``make lint``).

Runs the configured linters when they are installed, and a dependable
built-in floor everywhere else — the container CI image does not ship
ruff/mypy, and a lint target that silently no-ops teaches nothing:

1. **ruff** (``[tool.ruff]`` in pyproject.toml): lint + format check —
   used when importable/installed;
2. **mypy** (``[tool.mypy]``, permissive baseline) — used when
   installed;
3. **built-in fallback** (always available): per-file syntax check via
   ``compile()`` plus an AST pass for unused imports (ruff's F401) —
   the highest-signal subset of the configured ruleset, implemented
   against the same conventions (``# noqa`` respected, ``__init__.py``
   re-exports exempt, ``__all__`` counts as a use);
4. **vet rule-table drift check** (always available): every ``VET-*``
   id README.md cites must exist in ``analysis/findings.RULES`` and
   every registered rule must appear in README.md (range citations
   like ``VET-T001..T008`` expand) — the README tables are
   hand-maintained and this class of drift has already happened once
   (T010-T022/T026/M005-M006 shipped unregistered, breaking their
   suppression).  RULES is read by AST, not import, so the check
   never pays (or depends on) a jax import.

Exit status is nonzero on any finding, so the target composes into CI
recipes exactly like ``make resilience-smoke``.
"""
from __future__ import annotations

import ast
import pathlib
import re
import shutil
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent

#: directories scanned by the fallback linter (and passed to ruff)
TARGETS = ("isotope_tpu", "tests", "tools", "bench.py",
           "__graft_entry__.py")


def _files():
    for t in TARGETS:
        p = REPO / t
        if p.is_file():
            yield p
        else:
            yield from sorted(p.rglob("*.py"))


def _noqa_lines(src: str) -> set:
    return {
        i
        for i, line in enumerate(src.splitlines(), 1)
        if "# noqa" in line
    }


class _ImportUseScan(ast.NodeVisitor):
    """Collect module-level import bindings and every name usage."""

    def __init__(self) -> None:
        self.imports = {}  # name -> lineno (module level only)
        self.used = set()
        self._depth = 0

    def visit_Import(self, node: ast.Import) -> None:
        if self._depth == 0:
            for a in node.names:
                name = (a.asname or a.name).split(".")[0]
                self.imports[name] = node.lineno

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module == "__future__":
            return  # compiler directives, not bindings
        if self._depth == 0:
            for a in node.names:
                if a.name == "*":
                    continue
                self.imports[a.asname or a.name] = node.lineno

    def _scope(self, node) -> None:
        self._depth += 1
        self.generic_visit(node)
        self._depth -= 1

    visit_FunctionDef = _scope
    visit_AsyncFunctionDef = _scope
    visit_ClassDef = _scope

    def visit_Name(self, node: ast.Name) -> None:
        self.used.add(node.id)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        self.generic_visit(node)


def _string_uses(tree: ast.Module) -> set:
    """Names referenced via ``__all__`` or doctest-free string exports."""
    out = set()
    for node in tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AugAssign):
            targets = [node.target]
        for t in targets:
            if isinstance(t, ast.Name) and t.id == "__all__":
                for c in ast.walk(node):
                    if isinstance(c, ast.Constant) and isinstance(
                        c.value, str
                    ):
                        out.add(c.value)
    return out


def fallback_lint() -> int:
    """Syntax + unused-module-level-import check; returns #findings."""
    findings = 0
    for path in _files():
        rel = path.relative_to(REPO)
        try:
            src = path.read_text()
        except OSError as e:
            print(f"{rel}: unreadable: {e}")
            findings += 1
            continue
        try:
            tree = ast.parse(src, filename=str(rel))
        except SyntaxError as e:
            print(f"{rel}:{e.lineno}: syntax error: {e.msg}")
            findings += 1
            continue
        if path.name == "__init__.py":
            continue  # re-export modules import for the namespace
        scan = _ImportUseScan()
        scan.visit(tree)
        used = scan.used | _string_uses(tree)
        noqa = _noqa_lines(src)
        for name, lineno in sorted(
            scan.imports.items(), key=lambda kv: kv[1]
        ):
            if name in used or name == "_" or lineno in noqa:
                continue
            # conventional re-export / side-effect import aliases
            if name.startswith("_"):
                continue
            print(f"{rel}:{lineno}: F401 unused import: {name}")
            findings += 1
    return findings


#: a lone rule id, or a range over a shared letter (VET-T001..T008,
#: also tolerating a repeated letter on the right: VET-C001..C005)
_RULE_RE = re.compile(
    r"VET-([A-Z])(\d{3})(?:\.\.(?:[A-Z])?(\d{3}))?"
)


def registered_rules() -> set:
    """The rule ids in ``analysis/findings.RULES`` — by AST, so the
    drift check works without importing the package (or jax)."""
    src = (REPO / "isotope_tpu" / "analysis" / "findings.py").read_text()
    tree = ast.parse(src)
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign):  # RULES: Dict[...] = {..}
            targets = [node.target]
        else:
            continue
        for t in targets:
            if isinstance(t, ast.Name) and t.id == "RULES" and isinstance(
                node.value, ast.Dict
            ):
                return {
                    k.value for k in node.value.keys
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, str)
                }
    return set()


def readme_rules() -> set:
    """Every rule id README.md cites, with ranges expanded."""
    text = (REPO / "README.md").read_text()
    out = set()
    for m in _RULE_RE.finditer(text):
        letter, lo, hi = m.group(1), int(m.group(2)), m.group(3)
        hi = int(hi) if hi else lo
        for n in range(lo, hi + 1):
            out.add(f"VET-{letter}{n:03d}")
    return out


def rule_table_check() -> int:
    """README <-> findings.RULES drift; returns #findings."""
    registered = registered_rules()
    documented = readme_rules()
    findings = 0
    if not registered:
        print("tools/lint.py: could not parse RULES from "
              "isotope_tpu/analysis/findings.py")
        return 1
    for rule in sorted(documented - registered):
        print(f"README.md cites {rule} but analysis/findings.RULES "
              "does not register it (suppression of it would raise)")
        findings += 1
    for rule in sorted(registered - documented):
        print(f"analysis/findings.RULES registers {rule} but "
              "README.md never documents it (add it to a rule table, "
              "ranges like VET-T001..T008 count)")
        findings += 1
    return findings


def _run(cmd) -> int:
    print("+", " ".join(cmd))
    return subprocess.call(cmd, cwd=str(REPO))


def main() -> int:
    rc = 0
    ran_external = False
    if shutil.which("ruff"):
        ran_external = True
        rc |= _run(["ruff", "check", *TARGETS])
        rc |= _run(["ruff", "format", "--check", *TARGETS])
    if shutil.which("mypy"):
        ran_external = True
        rc |= _run(["mypy", "isotope_tpu"])
    n = fallback_lint() + rule_table_check()
    if n:
        print(f"lint: {n} finding(s)")
        rc |= 1
    if rc == 0:
        how = "ruff/mypy + builtin" if ran_external else (
            "builtin (ruff/mypy not installed)"
        )
        print(f"lint: clean ({how})")
    return rc


if __name__ == "__main__":
    sys.exit(main())
