"""search-smoke: the config-search acceptance story end-to-end.

One svc-scale successive-halving bracket (the vendored 1000-service
fan-out with a 2% entry error rate injected so ``error_scale``
bites) over 16 candidates on CPU, checked four ways (sim/search.py):

1. **The planted best wins**: candidate 5 carries a near-zero
   ``error_scale`` while every rival's is >= 0.8, so the err_share
   ranking must advance it through every rung and crown it — and
   ``winner_config()`` must hand back exactly that candidate's
   scales (the ``optimize`` warm start).

2. **A bracket costs at most one compile per rung**: the telemetry
   trace counter across the whole bracket must record <= rungs
   engine traces (one per rung width), and a second bracket of the
   same shape must add ZERO — every rung rides the executable cache.

3. **Rung 0 is the plain fleet, bit for bit**: each screening row
   must equal the matching member of ``run_ensemble`` at the same
   horizon on every exact field — ranking gathers candidates, it
   never perturbs their physics.

4. **The winner's carry-continued trajectory replays solo**: the
   per-rung segments merged by ``winner_summary()`` must match the
   winner's row of an UNBROKEN full-horizon fleet exactly on counts,
   extrema, and the latency histogram (float-summed leaves agree to
   reduction order).

``make search-smoke`` wires it into CI-style checks next to the
other smokes.
"""
from __future__ import annotations

import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

import numpy as np

EXACT_FIELDS = (
    "count", "error_count", "hop_events",
    "latency_min", "latency_max", "latency_hist", "end_max",
)


def main() -> int:
    import jax
    import yaml

    from isotope_tpu import telemetry
    from isotope_tpu.compiler import compile_graph
    from isotope_tpu.models.graph import ServiceGraph
    from isotope_tpu.sim import LoadModel
    from isotope_tpu.sim.engine import Simulator
    from isotope_tpu.sim.ensemble import EnsembleSpec
    from isotope_tpu.sim.search import SearchSpec

    telemetry.reset()
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(
        root, "examples/topologies/1000-svc_2000-end.yaml"
    )) as f:
        doc = yaml.safe_load(f)
    # the vendored fan-out ships error-free; give the entrypoint a
    # base error rate so the candidates' error_scale has a signal
    doc["services"][0]["errorRate"] = "2%"
    sim = Simulator(compile_graph(ServiceGraph.decode(doc)))
    load = LoadModel(kind="open", qps=10_000.0)
    key = jax.random.PRNGKey(42)
    cands, best, n, block = 16, 5, 256, 64

    # a planted winner: near-zero error scaling for candidate 5,
    # every rival >= 0.8 (distinct, so no rank ties)
    err = 0.8 + 0.08 * np.arange(cands, dtype=np.float64)
    err[best] = 1e-3
    pop = EnsembleSpec(seeds=tuple(range(cands)), error_scale=err)
    spec = SearchSpec(candidates=pop, eta=4, rungs=2)

    # -- 1+2. the bracket: planted winner, <= rungs compiles ----------
    traces0 = telemetry.counter_get("engine_traces")
    srch = sim.run_search(load, n, key, spec, block_size=block)
    traces = int(telemetry.counter_get("engine_traces") - traces0)
    for r in srch.rungs:
        print(
            f"search-smoke: rung {r.rung}: width {r.width} @ "
            f"{r.cum_requests} reqs -> survivors "
            f"{[int(x) for x in r.survivors]}"
        )
        assert best in set(int(x) for x in r.survivors), (
            f"planted best {best} eliminated at rung {r.rung}"
        )
    print(
        f"search-smoke: winner {srch.winner} (severity "
        f"{srch.winner_severity:.5f}) in {traces} engine trace(s) "
        f"for {spec.rungs} rungs"
    )
    assert srch.winner == best, (
        f"planted best {best} must win, got {srch.winner}"
    )
    assert srch.traces <= spec.rungs and traces <= spec.rungs, (
        f"a bracket compiles at most once per rung "
        f"(recorded {traces}, reported {srch.traces})"
    )
    cfg = srch.winner_config()
    assert cfg["candidate"] == best
    assert abs(cfg["error_scale"] - float(err[best])) < 1e-12, (
        "winner_config must replay the planted candidate's scales"
    )

    traces1 = telemetry.counter_get("engine_traces")
    sim.run_search(
        load, n, jax.random.fold_in(key, 1), spec, block_size=block
    )
    re_traces = int(telemetry.counter_get("engine_traces") - traces1)
    assert re_traces == 0, (
        f"the second bracket must reuse every rung's compile "
        f"(got {re_traces} new traces)"
    )
    print("search-smoke: second bracket: 0 new traces "
          "(the cache serves every rung shape)")

    # -- 3. rung 0 == the plain screening fleet, bit for bit ----------
    rung0 = srch.rungs[0]
    ens = sim.run_ensemble(
        load, rung0.cum_requests, key, pop, block_size=block
    )
    for row, cand in enumerate(int(x) for x in rung0.candidates):
        for f in EXACT_FIELDS:
            a = np.asarray(getattr(rung0.summaries, f)[row])
            b = np.asarray(getattr(ens.summaries, f)[cand])
            assert np.array_equal(a, b), (
                f"rung 0 row {row} (candidate {cand}) diverged from "
                f"the plain fleet on {f}"
            )
    print("search-smoke: rung 0 bit-equals the plain "
          f"{cands}-member fleet on {len(EXACT_FIELDS)} exact fields")

    # -- 4. the winner's carried segments replay the unbroken run -----
    full = sim.run_ensemble(load, n, key, pop, block_size=block)
    won = srch.winner_summary()
    for f in EXACT_FIELDS:
        a = np.asarray(getattr(won, f))
        b = np.asarray(getattr(full.summaries, f)[best])
        assert np.array_equal(a, b), (
            f"winner's carry-continued {f} diverged from the "
            "unbroken member"
        )
    a = float(np.asarray(won.latency_sum))
    b = float(np.asarray(full.summaries.latency_sum)[best])
    assert abs(a - b) <= 1e-5 * max(abs(b), 1.0), (
        "winner's latency_sum drifted beyond reduction-order noise"
    )
    print("search-smoke: winner's carry-continued trajectory "
          "replays the unbroken member bit-for-bit")
    print("search-smoke: PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
