"""sparse-smoke: force the non-dense step encodings on a small graph
and diff the executors' summaries.

Drives the same topology through THREE engines — the dense grid
(default thresholds), the dense-blocked TILED encoding, and the pure
SPARSE call-slot encoding (``sparse_level_elems`` lowered to 1 flips
the threshold; ``sparse_tiling`` selects tiled vs sparse) — plus the
tiled engine with the Pallas census kernel in interpreter mode, then
diffs the RunSummary fields.  Exit nonzero on any disagreement beyond
f32 reduction noise.  ``make sparse-smoke`` wires it into CI-style
checks next to the other smokes.
"""
from __future__ import annotations

import sys

import numpy as np


def main() -> int:
    import jax

    from isotope_tpu.compiler import compile_graph
    from isotope_tpu.models.generators import realistic_topology
    from isotope_tpu.models.graph import ServiceGraph
    from isotope_tpu.sim import LoadModel, SimParams, Simulator

    graph = ServiceGraph.decode(
        realistic_topology(60, archetype="star", seed=0)
    )
    compiled = compile_graph(graph)
    load = LoadModel(kind="open", qps=500.0)
    key = jax.random.PRNGKey(0)
    n, block = 4096, 1024

    engines = {
        "dense": SimParams(),
        "tiled": SimParams(sparse_level_elems=1),
        "sparse": SimParams(sparse_level_elems=1, sparse_tiling=False),
        "tiled+pallas": SimParams(
            sparse_level_elems=1, pallas_census=True
        ),
    }
    sums = {}
    for name, params in engines.items():
        sim = Simulator(compiled, params)
        if name.startswith("tiled"):
            assert any(
                lvl.tiled is not None for lvl in sim._levels
            ), f"{name}: tiled encoding did not engage"
        if name == "sparse":
            assert any(
                lvl.sparse is not None for lvl in sim._levels
            ), "sparse encoding did not engage"
        s = sim.run_summary(load, n, key, block_size=block)
        jax.block_until_ready(s.count)
        sums[name] = s

    ref = sums["dense"]
    rc = 0
    for name, s in sums.items():
        if name == "dense":
            continue
        exact = (
            float(s.count) == float(ref.count)
            and float(s.hop_events) == float(ref.hop_events)
            and float(s.error_count) == float(ref.error_count)
            and np.array_equal(
                np.asarray(s.latency_hist), np.asarray(ref.latency_hist)
            )
        )
        lat_rel = abs(
            float(s.latency_sum) - float(ref.latency_sum)
        ) / max(abs(float(ref.latency_sum)), 1e-30)
        ok = exact and lat_rel < 1e-5
        print(
            f"sparse-smoke: dense vs {name}: counts "
            f"{'EQUAL' if exact else 'DIFFER'}, latency_sum rel delta "
            f"{lat_rel:.2e} -> {'OK' if ok else 'FAIL'}"
        )
        if not ok:
            rc = 1
    if rc == 0:
        print(
            "sparse-smoke: all executors agree "
            f"(hop_events {float(ref.hop_events):.0f}, "
            f"p99 {ref.quantiles_s([0.99])[0] * 1e3:.3f} ms)"
        )
    return rc


if __name__ == "__main__":
    sys.exit(main())
