"""ingest-smoke: the trace-driven ingest self-closure loop end-to-end.

PR 20's ground-truth pin: the ingest path is only trustworthy on real
telemetry if it reconstructs telemetry whose generator we KNOW.  This
smoke drives the full loop on the power-law fixture:

1. **simulate** examples/topologies/realistic-powerlaw-100.yaml (Zipf
   fan-out skew, heterogeneous per-service sleeps and error rates)
   with the timeline recorder armed;
2. **export** the two expositions a real scrape would see — the full
   collector text (service_* families) and the timestamped timeline
   text (timeline_* families);
3. **ingest** both through the CLI path (readers -> fitters ->
   artifacts), writing <label>.yaml / .toml / .ingest.json;
4. **pin closure**: reconstructed per-service error share, mean
   self-time, fan-out degree sequence, and windowed qps schedule
   match the source within report.CLOSURE_TOLERANCES; coverage
   counters partition every input line; the emitted TOML decodes
   through runner.config.load_toml;
5. **re-simulate** the fitted topology and check the replayed client
   error share lands near the source run's, and that vet (lint_graph
   + lint_ingest) reports no errors on the reconstruction.

``make ingest-smoke`` wires it in next to the other smokes.
"""
from __future__ import annotations

import json
import os
import pathlib
import sys
import tempfile

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

QPS = 50.0
DURATION_S = 30.0
SEED = 0


def main() -> int:
    import jax

    from isotope_tpu.analysis.findings import SEV_ERROR
    from isotope_tpu.analysis.topo_lint import lint_graph, lint_ingest
    from isotope_tpu.compiler import compile_graph
    from isotope_tpu.ingest import fitters, readers, report
    from isotope_tpu.metrics import timeline as timeline_mod
    from isotope_tpu.metrics.prometheus import MetricsCollector
    from isotope_tpu.models.graph import ServiceGraph
    from isotope_tpu.runner.config import load_toml
    from isotope_tpu.sim import LoadModel, SimParams, Simulator

    rc = 0

    def check(name: str, ok: bool, detail: str) -> None:
        nonlocal rc
        status = "ok" if ok else "FAIL"
        print(f"  {status:<5} {name}: {detail}")
        if not ok:
            rc = 1

    root = pathlib.Path(__file__).parent.parent
    fixture = root / "examples/topologies/realistic-powerlaw-100.yaml"
    print(f"ingest-smoke: source {fixture.name}, {QPS:g} qps x "
          f"{DURATION_S:g}s")

    graph = ServiceGraph.from_yaml_file(fixture)
    compiled = compile_graph(graph)
    params = SimParams(timeline=True, timeline_window_s=1.0)
    sim = Simulator(compiled, params)
    collector = MetricsCollector(compiled)
    load = LoadModel(kind="open", qps=QPS)
    n = int(QPS * DURATION_S)
    summary, tl = sim.run_timeline(
        load, n, jax.random.PRNGKey(SEED),
        collector=collector, window_s=1.0,
    )
    src_err_share = float(summary.error_count) / max(
        float(summary.count), 1.0
    )
    full_text = collector.full_text(summary)
    tl_text = timeline_mod.prometheus_text(compiled, tl)

    with tempfile.TemporaryDirectory(prefix="ingest_smoke_") as td:
        tdp = pathlib.Path(td)
        (tdp / "full.prom").write_text(full_text)
        (tdp / "timeline.prom").write_text(tl_text)

        obs = readers.read_path(str(tdp / "full.prom"))
        obs = readers.read_path(str(tdp / "timeline.prom"), obs=obs)
        for cov in obs.inputs:
            parts = (
                cov.lines_blank + cov.lines_comment + cov.lines_parsed
                + cov.lines_malformed
            )
            check(
                f"coverage partition {pathlib.Path(cov.path).name}",
                cov.lines_total == parts
                and cov.samples_used + cov.samples_ignored
                == cov.lines_parsed,
                f"{cov.lines_total} lines = {cov.lines_blank} blank + "
                f"{cov.lines_comment} comment + {cov.lines_parsed} "
                f"parsed + {cov.lines_malformed} malformed",
            )

        fr = fitters.fit(obs, fitters.FitOptions(label="closure"))
        doc = report.to_doc(fr, obs)
        closure = report.closure_check(
            graph, params.cpu_time_s, [QPS], fr
        )
        doc["closure"] = closure
        for c in closure["checks"]:
            detail = {
                "error_share":
                    f"worst |fit-src| {c.get('worst_abs_error', 0)}",
                "self_time":
                    f"mean rel {c.get('mean_rel_error', 0):.3f}, "
                    f"{c.get('services_in_band_share', 0):.0%} of "
                    f"{c.get('services_eligible', 0)} services in band",
                "degree_sequence":
                    f"{sum(c.get('fitted', []))} edges, "
                    f"top degree {max(c.get('fitted') or [0])}",
                "qps_schedule":
                    f"mean rel {c.get('mean_rel_error', 0):.3f}, "
                    f"{c.get('windows_in_band_share', 0):.0%} windows "
                    "in band",
            }.get(c["check"], "")
            check(f"closure {c['check']}", bool(c["ok"]), detail)

        # nothing silently dropped: the fixture is fully reachable
        cov_block = doc["coverage"]
        check(
            "no unexplained drops",
            not cov_block["services_dropped"]
            and not cov_block["edges_dropped"],
            f"{len(cov_block['services_dropped'])} services / "
            f"{len(cov_block['edges_dropped'])} edges dropped",
        )

        # artifacts: YAML validates, TOML decodes, report round-trips
        out_dir = tdp / "out"
        out_dir.mkdir()
        (out_dir / "closure.yaml").write_text(fr.graph.to_yaml())
        (out_dir / "closure.toml").write_text(fr.toml_text)
        cfg = load_toml(out_dir / "closure.toml")
        check(
            "emitted TOML decodes",
            cfg.ingest is not None
            and abs(cfg.qps[0] - fr.qps_mean) < 1e-6,
            f"[client] qps {cfg.qps[0]:g}, [ingest] label "
            f"{cfg.ingest and cfg.ingest.get('label')!r}",
        )
        report.save_doc(doc, str(out_dir / "closure.ingest.json"))
        loaded = report.load_doc(str(out_dir / "closure.ingest.json"))
        check(
            "isotope-ingest/v1 round-trip",
            loaded["fit"]["degree_sequence"]
            == doc["fit"]["degree_sequence"],
            f"{len(json.dumps(loaded))} bytes",
        )

        # vet: the reconstruction must lint clean (no errors, and the
        # well-sampled fixture must not trip the ingest rules)
        findings = lint_graph(fr.graph, entry=fr.entry)
        findings += lint_ingest(fr.graph, loaded)
        errors = [f for f in findings if f.severity == SEV_ERROR]
        ingest_rules = [
            f for f in findings if f.rule in ("VET-T027", "VET-T028")
        ]
        check(
            "vet clean",
            not errors and not ingest_rules,
            f"{len(findings)} findings, {len(errors)} errors, "
            f"{len(ingest_rules)} ingest-rule warnings",
        )

        # re-simulate the reconstruction: the replay must run and land
        # near the source's client error share (self-closure, not just
        # syntax)
        re_compiled = compile_graph(fr.graph)
        re_sim = Simulator(re_compiled, cfg.sim_params())
        re_load = LoadModel(kind="open", qps=float(cfg.qps[0]))
        re_summary, _ = re_sim.run_timeline(
            re_load, n, jax.random.PRNGKey(SEED),
            window_s=cfg.timeline_window_s,
        )
        re_err_share = float(re_summary.error_count) / max(
            float(re_summary.count), 1.0
        )
        check(
            "re-simulated error share",
            abs(re_err_share - src_err_share) <= 0.03,
            f"source {src_err_share:.4f} vs replay {re_err_share:.4f}",
        )

    print("ingest-smoke:", "PASS" if rc == 0 else "FAIL")
    return rc


if __name__ == "__main__":
    sys.exit(main())
