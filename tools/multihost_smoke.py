"""Multi-host end-to-end smoke (``make multihost-smoke``).

Drives the whole PR-8 scale-out surface on one machine:

1. **emulated multi-host twin** — a 2 hosts x 8 devices mesh
   (slice=2, data=4, svc=2 => 16 shards) replayed shard-by-shard on a
   single device via :class:`EmulatedMesh`; counts must reconcile and
   the run must be deterministic;
2. **shard_map == twin** — the same (2, 2, 2) multislice program on
   the 8-device virtual CPU mesh vs its emulated replay, every summary
   field within 1 f32 ULP (measured bit-equal on CPU);
3. **overlap == off** — collective/compute overlap
   (``SimParams.overlap``) must match the single post-scan merge
   exactly on integer-valued fields and to f32 reduction order on
   float sums;
4. **layout search** — ``--mesh auto`` (parallel/layout.py) must score
   no worse than the hand-picked ``{'slice': 2, 'data': 2, 'svc': 2}``;
5. **DCN chaos** — a transient injected at the
   ``sharded.dcn_collective`` site must classify transient and be
   retried by the supervisor to a bit-identical result.
"""
from __future__ import annotations

import sys


def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", 8)
    except AttributeError:  # jax < 0.5
        import os

        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        )
    import numpy as np

    from isotope_tpu import telemetry
    from isotope_tpu.compiler import compile_graph
    from isotope_tpu.models.graph import ServiceGraph
    from isotope_tpu.parallel import (
        EmulatedMesh,
        MeshSpec,
        ShardedSimulator,
        build_mesh,
        layout,
    )
    from isotope_tpu.resilience import execution_rungs, faults, run_ladder
    from isotope_tpu.resilience.supervisor import ResiliencePolicy
    from isotope_tpu.sim import LoadModel, SimParams

    yaml = """
services:
- name: entry
  isEntrypoint: true
  script:
  - - call: x
    - call: y
  - call: z
- name: x
- name: y
  script:
  - call: z
- name: z
"""
    compiled = compile_graph(ServiceGraph.from_yaml(yaml))
    load = LoadModel(kind="open", qps=2000.0)
    key = jax.random.PRNGKey(7)
    n = 8192

    def ulp(a, b):
        a, b = np.asarray(a), np.asarray(b)
        if a.dtype == bool:
            return 0.0 if (a == b).all() else np.inf
        a64, b64 = a.astype(np.float64), b.astype(np.float64)
        same = (a64 == b64) | (
            np.isinf(a64) & np.isinf(b64) & (np.sign(a64) == np.sign(b64))
        )
        sp = np.spacing(
            np.maximum(np.abs(a), np.abs(b)).astype(np.float32)
        ).astype(np.float64)
        with np.errstate(invalid="ignore"):
            diff = np.abs(a64 - b64) / np.where(sp > 0, sp, 1.0)
        return float(np.max(np.where(same, 0.0, diff)))

    # 1. emulated 2 hosts x 8 devices = 16 shards on ONE device
    twin16 = ShardedSimulator(
        compiled, EmulatedMesh(MeshSpec(data=4, svc=2, slices=2))
    )
    assert twin16.n_shards == 16
    s16 = twin16.run_emulated(load, n, key, block_size=1024)
    assert int(s16.count) == n, int(s16.count)
    s16b = twin16.run_emulated(load, n, key, block_size=1024)
    assert ulp(s16.latency_hist, s16b.latency_hist) == 0.0

    # 2. shard_map (2, 2, 2) vs its emulated twin
    spec222 = MeshSpec(data=2, svc=2, slices=2)
    sharded = ShardedSimulator(compiled, build_mesh(spec222))
    dev = sharded.run(load, n, key, block_size=1024)
    jax.block_until_ready(dev.count)
    tw = sharded.run_emulated(load, n, key, block_size=1024)
    worst = max(
        ulp(a, b)
        for a, b in zip(jax.tree.leaves(dev), jax.tree.leaves(tw))
    )
    assert worst <= 1.0, worst

    # 3. overlap on == off
    on = ShardedSimulator(
        compiled, build_mesh(spec222), params=SimParams(overlap=True)
    ).run(load, n, key, block_size=1024)
    for f in ("count", "error_count", "hop_events", "win_count"):
        assert float(getattr(on, f)) == float(getattr(dev, f)), f
    np.testing.assert_array_equal(
        np.asarray(on.latency_hist), np.asarray(dev.latency_hist)
    )
    np.testing.assert_allclose(
        float(on.latency_sum), float(dev.latency_sum), rtol=1e-6
    )

    # 4. layout search beats (or ties) the hand-picked mesh
    auto = layout.choose_layout(8, compiled.num_services, max_slices=2)
    hand = layout.score_layout(spec222, compiled.num_services)
    assert auto.score_s <= hand.score_s, (auto.score_s, hand.score_s)

    # 5. injected DCN-collective transient retries to identical results
    telemetry.reset()
    faults.install("transient:sharded.dcn_collective:1")
    try:
        rungs = execution_rungs(
            sharded.sim, sharded, True, load, n, key, 1024, trim=False
        )
        summary, degraded = run_ladder(
            rungs,
            ResiliencePolicy(sleep=lambda s: None),
        )
    finally:
        faults.clear()
    assert degraded is None, degraded
    assert telemetry.counter_get("retries_total") >= 1.0
    assert float(summary.count) == float(dev.count)

    print(
        "multihost-smoke: 16-shard emulated twin reconciles "
        f"({int(s16.count)} reqs), shard_map==twin within "
        f"{worst:.1f} ULP, overlap==off, auto mesh "
        f"{auto.spec.describe()} ({auto.score_s:.3g}s) <= hand "
        f"{hand.score_s:.3g}s, DCN transient retried "
        f"({int(telemetry.counter_get('retries_total'))}x)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
