"""chaosfleet-smoke: the chaos-fleet acceptance story end-to-end.

A retry-storm topology (entry -> worker with timeouts + retries, a
breaker / retry-budget / HPA policy block) under a worker-kill chaos
schedule, dispatched as a PROTECTED Monte Carlo fleet with PER-MEMBER
kill timing/magnitude (PR 15), checked four ways:

1. **Protected fleet == solo protected runs**: member k of the
   seeds-only policy fleet must be BIT-IDENTICAL to the solo
   ``run_policies`` with ``fold_in(key, k)`` — summary, recorder
   windows, and policy actuation series alike.

2. **Every member survives a different bad day**: under a
   ``ChaosJitterSpec`` the members' kill windows differ (asserted on
   the jittered schedules) and the severity statistic spreads across
   members.

3. **Splitting resolves a forced-rare outage**: a severity threshold
   is placed so deep that the brute-force fleet sees ~no hits, then
   the multilevel-splitting estimator (sim/splitting.py) must return
   a NONZERO probability using <= 10% of the member budget an
   oversampled brute-force reference needs for a stable estimate —
   and on a COMMON event the splitting CI must overlap the
   brute-force Wilson CI.

4. **Worst-member replay**: the most-severe member's jittered
   schedule, replayed through a solo Simulator, reproduces that
   member's run bit-for-bit — the postmortem artifact contract.

``make chaosfleet-smoke`` wires it in next to the other smokes.
"""
from __future__ import annotations

import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

import numpy as np

TOPOLOGY = """
services:
- name: entry
  isEntrypoint: true
  numReplicas: 4
  script:
  - call: {service: worker, timeout: 850us, retries: 2}
- name: worker
  numReplicas: 4
  errorRate: 0.5%
policies:
  defaults:
    retry_budget: {budget_percent: 25%}
  worker:
    breaker: {max_pending: 6, max_connections: 64,
              consecutive_errors: 5, base_ejection: 2s}
    autoscaler: {min_replicas: 2, max_replicas: 8,
                 target_utilization: 60%, sync_period: 1s,
                 stabilization_window: 3s}
"""


def main() -> int:
    import jax

    from isotope_tpu.compiler import compile_graph, compile_policies
    from isotope_tpu.models.graph import ServiceGraph
    from isotope_tpu.resilience import faults
    from isotope_tpu.sim import splitting as split_mod
    from isotope_tpu.sim.config import ChaosEvent, LoadModel, SimParams
    from isotope_tpu.sim.engine import Simulator
    from isotope_tpu.sim.ensemble import EnsembleSpec, wilson_interval

    g = ServiceGraph.from_yaml(TOPOLOGY)
    compiled = compile_graph(g)
    pol = compile_policies(g, compiled)
    chaos = (ChaosEvent("worker", 0.5, 1.5, replicas_down=3),)
    sim = Simulator(
        compiled, SimParams(timeline=True), chaos=chaos, policies=pol
    )
    load = LoadModel(kind="open", qps=4_000.0)
    key = jax.random.PRNGKey(0)
    n, block, win = 8_192, 2_048, 0.25
    members = 8
    spec = EnsembleSpec.of(members)
    reps = compiled.services.replicas_by_name()

    # -- 1. protected fleet == solo protected runs ---------------------
    fleet = sim.run_policies_ensemble(
        load, n, key, spec, block_size=block, trim=True, window_s=win
    )
    k = 3
    solo = sim.run_policies(
        load, n, jax.random.fold_in(key, k), block_size=block,
        trim=True, window_s=win,
    )
    assert np.array_equal(
        np.asarray(fleet.member(k).latency_hist),
        np.asarray(solo[0].latency_hist),
    ), "fleet member summary != solo run_policies"
    assert np.array_equal(
        np.asarray(fleet.member_timeline(k).errors),
        np.asarray(solo[1].errors),
    ), "fleet member timeline != solo"
    assert np.array_equal(
        np.asarray(fleet.member_policies(k).replicas),
        np.asarray(solo[2].replicas),
    ), "fleet member policy series != solo"
    print(
        f"protected fleet: {members} members, member {k} bit-equal "
        "to solo run_policies (summary + timeline + policy series)"
    )

    # -- 2. per-member bad days ----------------------------------------
    jitter = faults.ChaosJitterSpec(time=0.3, magnitude=0.6, seed=7)
    jfleet = sim.run_policies_ensemble(
        load, n, key, spec, block_size=block, trim=True,
        window_s=win, member_chaos=jitter,
    )
    starts = [evs[0].start_s for evs in jfleet.member_chaos]
    downs = [evs[0].replicas_down for evs in jfleet.member_chaos]
    assert len(set(round(s, 6) for s in starts)) > 1, \
        "kill timing did not vary across members"
    sev = jfleet.severity()
    print(
        f"per-member chaos: kill starts "
        f"{min(starts):.2f}..{max(starts):.2f}s, replicas_down "
        f"{min(downs)}..{max(downs)}, severity "
        f"{sev.min():.4f}..{sev.max():.4f} (worst member "
        f"{jfleet.worst_member()})"
    )

    # -- 3. splitting vs brute force -----------------------------------
    # severity here is the RUN-LONG client error share: continuous in
    # the jittered kill timing/magnitude, so quantile thresholds from
    # an oversampled reference define events of known rarity
    n_short = 2_048
    base = jax.random.fold_in(key, 777)
    sev_spec = split_mod.SplitSpec(severity="err_share")

    def evaluate(chaos_seeds, work_seeds):
        mkeys = [
            jax.random.fold_in(base, int(w)) for w in work_seeds
        ]
        mc = [
            faults.jitter_chaos_events(chaos, jitter, row, reps)
            for row in np.asarray(chaos_seeds)
        ]
        ens = sim.run_policies_ensemble(
            load, n_short, base, EnsembleSpec.of(len(mkeys)),
            block_size=block, window_s=win, member_keys=mkeys,
            member_chaos=mc,
        )
        return split_mod.severity_scores(
            sev_spec, ens.summaries, ens.timelines,
        )

    # the oversampled brute-force reference: B batches place the
    # common (p ~ 0.3) and forced-rare (p ~ 1/100) thresholds
    rng = np.random.default_rng(99)
    ref = np.concatenate([
        evaluate(
            rng.integers(1, 2**31 - 1, size=(24, 1)),
            rng.integers(1, 2**31 - 1, size=24),
        )
        for _ in range(10)
    ])
    t_common = float(np.quantile(ref, 0.7))
    t_rare = float(np.quantile(ref, 1.0 - 2.5 / len(ref)))

    # common event: splitting CI must overlap a fresh brute-force
    # fleet's Wilson interval
    brute = np.concatenate([
        evaluate(
            rng.integers(1, 2**31 - 1, size=(24, 1)),
            rng.integers(1, 2**31 - 1, size=24),
        )
        for _ in range(2)
    ])
    k_hits = int((brute >= t_common).sum())
    b_lo, b_hi = wilson_interval(k_hits, len(brute))
    sdoc = split_mod.subset_estimate(
        evaluate,
        split_mod.SplitSpec(
            levels=3, members=24, keep=0.5, threshold=t_common,
            severity="err_share", seed=1,
        ),
        chaos_components=1,
    )
    overlap = sdoc["ci_hi"] >= b_lo and b_hi >= sdoc["ci_lo"]
    print(
        f"common event (share >= {t_common:.4f}): brute "
        f"{k_hits}/{len(brute)} -> [{b_lo:.3f}, {b_hi:.3f}], "
        f"splitting p={sdoc['p']:.3f} [{sdoc['ci_lo']:.3f}, "
        f"{sdoc['ci_hi']:.3f}] ({sdoc['evaluations']} member runs)"
    )
    assert overlap, "splitting CI does not overlap brute-force CI"

    # forced-rare outage: the reference's extreme quantile — a
    # 48-member brute-force fleet typically sees NOTHING past it;
    # splitting must climb to a nonzero estimate on <= 10% of the
    # budget a stable brute-force estimate needs (~10/p members)
    rdoc = split_mod.subset_estimate(
        evaluate,
        split_mod.SplitSpec(
            levels=4, members=24, keep=0.3, threshold=t_rare,
            severity="err_share", seed=2, chaos_prob=0.6,
        ),
        chaos_components=1,
    )
    brute_budget_needed = (
        10.0 / max(rdoc["p"], 1e-12) if rdoc["p"] > 0 else np.inf
    )
    print(
        f"rare outage (share >= {t_rare:.4f}): splitting "
        f"p={rdoc['p']:.2e} [{rdoc['ci_lo']:.2e}, "
        f"{rdoc['ci_hi']:.2e}] in {rdoc['evaluations']} member runs "
        f"(brute force would need ~{brute_budget_needed:.0f})"
    )
    assert rdoc["p"] > 0.0, "splitting failed to resolve the outage"
    assert rdoc["evaluations"] <= 0.1 * brute_budget_needed, (
        "splitting spent more than 10% of the brute-force budget"
    )

    # -- 4. worst-member replay ----------------------------------------
    worst = jfleet.worst_member()
    replay_sim = Simulator(
        compiled, SimParams(timeline=True),
        chaos=jfleet.member_chaos[worst], policies=pol,
    )
    replay = replay_sim.run_policies(
        load, n, jax.random.fold_in(key, worst), block_size=block,
        trim=True, window_s=win,
    )
    assert np.array_equal(
        np.asarray(jfleet.member(worst).latency_hist),
        np.asarray(replay[0].latency_hist),
    ), "worst-member replay diverged"
    print(
        f"worst member {worst} replayed solo from its jittered "
        "schedule: BIT-EQUAL — the postmortem artifact is executable"
    )
    print("chaosfleet-smoke OK")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
