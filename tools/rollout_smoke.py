"""rollout-smoke: the progressive-delivery acceptance scenario
end-to-end.

An entry -> worker chain pushes a BAD canary (``canary:
{error_rate: 30%}``) through a 5% -> 25% -> 100% step schedule twice:

- CLOSED-LOOP (the rollout controller): the 5% step's bake window
  accumulates ``min_samples`` canary hops, the error-share gate trips
  on the canary's ~30% 500-rate, and the controller ROLLS BACK (weight
  -> 0, retries exhausted -> FAILED) before the bad push ever sees
  real traffic;
- OPEN-LOOP twin (the pre-rollout ``churn`` idiom: traffic-shift
  weights as pure clocks): the SAME schedule with its gates disabled
  promotes on every bake boundary and marches the bad canary to 100%
  of traffic, burning error budget for the rest of the run.

Asserts the acceptance criteria: the bad canary is detected and
reverted within its first bake window, the canary's traffic exposure
stays pinned low (a few percent of hops), the gate demonstrably SAW
the bad arm (observed canary error share ~30%), the closed-loop run's
total client-error share is STRICTLY below the open-loop twin's, and
the 4-shard sharded trajectory is bit-equal to the emulated twin.
``make rollout-smoke`` wires it into CI-style checks next to the
other smokes.
"""
from __future__ import annotations

import sys


TOPOLOGY = {
    "services": [
        {
            "name": "entry",
            "isEntrypoint": True,
            "numReplicas": 4,
            "script": [{"call": "worker"}],
        },
        {"name": "worker", "numReplicas": 4},
    ],
}

STEPS = ["5%", "25%", "100%"]
BAKE_S = 2.0

# the closed-loop controller: min-sample-guarded error-share gate,
# no retry budget — a trip parks the rollout FAILED at weight 0
GATED = {
    "worker": {
        "steps": STEPS,
        "bake": BAKE_S,
        "gates": {"min_samples": 100, "max_error_share": "10%"},
        "rollback": {"cooldown": 30.0, "max_retries": 0},
        "canary": {"error_rate": "30%"},
    }
}

# the open-loop twin: identical schedule and canary physics, gates
# disabled (inf thresholds, min_samples 1) — promotion becomes a pure
# bake clock, exactly the `churn` traffic-shift idiom this controller
# replaces
CLOCKED = {
    "worker": {
        **GATED["worker"],
        "gates": {
            "min_samples": 1,
            "max_error_ratio": float("inf"),
            "max_latency_ratio": float("inf"),
        },
    }
}


def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", 4)
    except AttributeError:  # jax < 0.5
        import os

        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=4"
        )
    import numpy as np

    from isotope_tpu.compiler import compile_graph, compile_rollouts
    from isotope_tpu.models.graph import ServiceGraph
    from isotope_tpu.sim import LoadModel, SimParams, Simulator
    from isotope_tpu.sim import rollout as roll_mod

    def build(rollouts_block):
        doc = dict(TOPOLOGY, rollouts=rollouts_block)
        g = ServiceGraph.decode(doc)
        compiled = compile_graph(g)
        return compiled, compile_rollouts(g, compiled)

    params = SimParams(timeline=True, timeline_window_s=0.5)
    load = LoadModel(kind="open", qps=2_000.0)
    n, block = 30_000, 2_000
    key = jax.random.PRNGKey(11)
    args = dict(block_size=block, window_s=0.5)

    compiled_g, tables_g = build(GATED)
    closed = Simulator(compiled_g, params, rollouts=tables_g)
    s_c, tl_c, roll_c = closed.run_rollouts(load, n, key, **args)

    compiled_o, tables_o = build(CLOCKED)
    open_sim = Simulator(compiled_o, params, rollouts=tables_o)
    s_o, tl_o, roll_o = open_sim.run_rollouts(load, n, key, **args)

    rc = 0

    def check(name, ok, detail):
        nonlocal rc
        status = "ok" if ok else "FAIL"
        print(f"  {status:<5} {name}: {detail}")
        if not ok:
            rc = 1

    doc_c = roll_mod.to_doc(compiled_g, roll_c, tables_g)
    doc_o = roll_mod.to_doc(compiled_o, roll_o, tables_o)
    w_c, w_o = doc_c["services"]["worker"], doc_o["services"]["worker"]

    onsets = w_c["rollback_onsets_s"]
    check(
        "rollback within the bake window",
        w_c["rollbacks"] == 1.0 and onsets
        and 0.0 < onsets[0] <= BAKE_S,
        f"rolled back at t={onsets[0] if onsets else None}s "
        f"(bake {BAKE_S:g}s)",
    )
    check(
        "retries exhausted -> FAILED at weight 0",
        w_c["state"] == "failed" and w_c["final_weight"] == 0.0,
        f"state={w_c['state']!r} final_weight={w_c['final_weight']}",
    )
    share_seen = max(w_c["canary_error_share"], default=0.0)
    check(
        "gate saw the bad arm",
        share_seen >= 0.2,
        f"observed canary error share {share_seen:.1%} "
        "(configured 30%)",
    )
    arr = np.asarray(roll_c.ver_arrivals, np.float64)
    widx = list(tables_g.names).index("worker")
    exposure = arr[widx, 1].sum() / max(arr[widx].sum(), 1.0)
    check(
        "canary exposure pinned low",
        exposure < 0.05,
        f"canary served {exposure:.2%} of worker hops "
        "(weight capped at the 5% step)",
    )
    # total error share is HOP-level (the 500s the worker's callers
    # observe): per executable.go:132-143 semantics a callee 500 does
    # not fail the caller, so client_error would hide the burn
    arr_o = np.asarray(roll_o.ver_arrivals, np.float64)
    err_o = np.asarray(roll_o.ver_errors, np.float64)
    err_c_tot = np.asarray(roll_c.ver_errors, np.float64)
    share_closed = err_c_tot[widx].sum() / max(arr[widx].sum(), 1.0)
    share_open = err_o[widx].sum() / max(arr_o[widx].sum(), 1.0)
    check(
        "closed-loop beats the open-loop twin",
        share_closed < share_open and share_closed < 0.05,
        f"worker error share {share_closed:.2%} < open-loop "
        f"{share_open:.2%}",
    )
    check(
        "open-loop twin marched to 100%",
        w_o["final_weight"] == 1.0 and w_o["rollbacks"] == 0.0,
        f"twin final weight {w_o['final_weight']:.0%} "
        f"({w_o['promotions']:.0f} clock promotes)",
    )

    # 4-shard mesh trajectory == emulated twin, bit for bit
    from isotope_tpu.parallel import MeshSpec, ShardedSimulator, build_mesh

    sh = ShardedSimulator(
        compiled_g, build_mesh(MeshSpec(data=4, svc=1)), params,
        rollouts=tables_g,
    )
    dev = sh.run_rollouts(load, 8_000, key, **args)
    emu = sh.run_rollouts_emulated(load, 8_000, key, **args)
    leaves_d, leaves_e = jax.tree.leaves(dev), jax.tree.leaves(emu)
    bit_equal = len(leaves_d) == len(leaves_e) and all(
        np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(leaves_d, leaves_e)
    )
    check(
        "sharded == emulated twin",
        bit_equal and np.asarray(dev[2].rollbacks).sum() >= 1.0,
        f"{len(leaves_d)} leaves bit-equal across 4 shards, "
        "trip on the merged trajectory",
    )

    print()
    print(roll_mod.format_table(doc_c))
    print(
        "rollout-smoke:"
        + (" all checks passed" if rc == 0 else " FAILURES above")
    )
    return rc


if __name__ == "__main__":
    sys.exit(main())
