"""Regenerate examples/topologies/ (run via `make examples`).

Mirrors the coverage of the reference's isotope/example-topologies/ — a
1-service baseline, short chains, the canonical graph (checked in by
hand), replica-heavy fan-out trees at increasing endpoint counts, and the
two tree sizes — using this package's generators.
"""
from __future__ import annotations

import pathlib

import yaml

from isotope_tpu.models import generators
from isotope_tpu.models.graph import ServiceGraph

OUT = pathlib.Path(__file__).parent.parent / "examples" / "topologies"


def dump(name: str, doc: dict) -> None:
    ServiceGraph.decode(doc)  # must validate
    (OUT / name).write_text(
        yaml.safe_dump(doc, default_flow_style=False, sort_keys=False)
    )
    print(f"wrote {OUT / name}")


def chain(n: int) -> dict:
    services = []
    for i in range(n):
        svc: dict = {"name": f"svc-{i}"}
        if i == 0:
            svc["isEntrypoint"] = True
        if i + 1 < n:
            svc["script"] = [{"call": f"svc-{i + 1}"}]
        services.append(svc)
    return {"defaults": {"requestSize": 128, "responseSize": 128},
            "services": services}


def main() -> None:
    OUT.mkdir(parents=True, exist_ok=True)
    dump("1-service.yaml", {
        "services": [{"name": "svc-0", "isEntrypoint": True,
                      "responseSize": 1024}],
    })
    dump("chain-2-services.yaml", chain(2))
    dump("chain-3-services.yaml", chain(3))

    # replica-heavy fan-out trees: 10 services x k replicas = N endpoints
    for reps in (1, 10, 100, 1000):
        dump(
            f"10-svc_{10 * reps}-end.yaml",
            generators.tree_topology(
                num_levels=3, num_branches=9,
                num_services=10, num_replicas=reps,
            ),
        )
    dump(
        "1000-svc_2000-end.yaml",
        generators.tree_topology(
            num_levels=5, num_branches=6, num_services=1000, num_replicas=2
        ),
    )

    dump("tree-13-services.yaml",
         generators.tree_topology(num_levels=3, num_branches=3,
                                  num_replicas=6))
    dump("tree-111-services.yaml",
         generators.tree_topology(num_levels=3, num_branches=10))

    # the four realistic archetypes (create_realistic_topology.py:55-99)
    for archetype in sorted(generators.ARCHETYPES):
        dump(
            f"realistic-{archetype}-50.yaml",
            generators.realistic_topology(
                num_services=50, archetype=archetype, seed=0
            ),
        )

    # Zipf out-degree skew with heterogeneous sleeps/error rates: the
    # ingest self-closure fixture (tools/ingest_smoke.py simulates it,
    # exports the exposition, and re-fits it back)
    dump(
        "realistic-powerlaw-100.yaml",
        generators.powerlaw_topology(
            num_services=100, exponent=2.0, seed=7,
            sleep_choices=["0", "1ms", "2ms", "4ms", "8ms"],
            error_rate_choices=["0%", "0%", "1%", "2%", "5%"],
        ),
    )


if __name__ == "__main__":
    main()
