"""Engine-vs-oracle fidelity sweep (generates the ORACLE.md table).

Runs the analytic TPU engine and the exact DES oracle on the same
topologies and loads, and prints the relative error of the engine's
p50/p99 against the oracle's ground truth.

Usage: JAX_PLATFORMS=cpu python tools/fidelity_check.py
"""
from __future__ import annotations

import argparse

import jax
import numpy as np

from isotope_tpu.compiler import compile_graph
from isotope_tpu.models.graph import ServiceGraph
from isotope_tpu.sim.config import LoadModel, SimParams
from isotope_tpu.sim.engine import Simulator
from isotope_tpu.sim.oracle import OracleSimulator

CHAIN3 = """
services:
- name: a
  isEntrypoint: true
  script: [{call: b}]
- name: b
  script: [{call: c}]
- name: c
"""

TREE13 = """
defaults: {responseSize: 1 KiB, requestSize: 1 KiB}
services:
- name: entry
  isEntrypoint: true
  script:
  - [{call: c0}, {call: c1}, {call: c2}]
- name: c0
  script: [[{call: l00}, {call: l01}, {call: l02}]]
- name: c1
  script: [[{call: l10}, {call: l11}, {call: l12}]]
- name: c2
  script: [[{call: l20}, {call: l21}, {call: l22}]]
- name: l00
- name: l01
- name: l02
- name: l10
- name: l11
- name: l12
- name: l20
- name: l21
- name: l22
"""

STAR9 = """
services:
- name: entry
  isEntrypoint: true
  script:
  - [{call: s0}, {call: s1}, {call: s2}, {call: s3},
     {call: s4}, {call: s5}, {call: s6}, {call: s7}]
- name: s0
- name: s1
- name: s2
- name: s3
- name: s4
- name: s5
- name: s6
- name: s7
"""


def compare(
    name: str,
    yaml_text: str,
    load: LoadModel,
    n_engine: int,
    n_oracle: int,
    params: SimParams = SimParams(),
    warmup_s: float = 0.5,
    seed: int = 0,
):
    graph = ServiceGraph.from_yaml(yaml_text)
    engine = Simulator(compile_graph(graph), params)
    res_e = engine.run(load, n_engine, jax.random.PRNGKey(seed))
    lat_e = np.asarray(res_e.client_latency, np.float64)

    oracle = OracleSimulator(graph, params)
    res_o = oracle.run(load, n_oracle, seed=seed)
    mask = res_o.client_start >= warmup_s
    lat_o = res_o.client_latency[mask]

    qs = (0.5, 0.99)
    qe = np.quantile(lat_e, qs)
    qo = np.quantile(lat_o, qs)
    rows = []
    for q, e, o in zip(qs, qe, qo):
        rows.append((name, q, e, o, e / o - 1.0))
    # throughput check for closed loop
    thr_e = float(res_e.offered_qps)
    dur_o = float(res_o.client_end.max())
    thr_o = len(res_o.client_latency) / dur_o if dur_o > 0 else 0.0
    return rows, (thr_e, thr_o)


def print_rows(rows, te=None, to=None):
    for r in rows:
        print(f"{r[0]:<28}{r[1]:>6}{r[2]*1e3:>10.4f}ms"
              f"{r[3]*1e3:>10.4f}ms{r[4]*100:>8.2f}%")
    if te is not None:
        print(f"{'  throughput':<28}{'':>6}{te:>10.0f}/s"
              f"{to:>10.0f}/s{(te/to-1)*100:>8.2f}%")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n-engine", type=int, default=400_000)
    ap.add_argument("--n-oracle", type=int, default=2_000_000)
    args = ap.parse_args()

    params = SimParams()
    mu = 1.0 / params.cpu_time_s
    print(f"{'case':<28}{'q':>6}{'engine':>12}{'oracle':>12}{'rel_err':>9}")
    for name, yaml_text in (
        ("chain3", CHAIN3), ("tree13", TREE13), ("star9", STAR9)
    ):
        for rho in (0.3, 0.7):
            load = LoadModel(kind="open", qps=rho * mu)
            rows, _ = compare(
                f"{name}/open rho={rho}", yaml_text, load,
                args.n_engine, args.n_oracle,
            )
            print_rows(rows)
    # closed loop: 64 connections, qps None (max) and paced
    for name, yaml_text in (("chain3", CHAIN3),):
        for qps, tag in ((None, "max"), (0.5 * mu, "half")):
            load = LoadModel(kind="closed", qps=qps, connections=64)
            rows, (te, to) = compare(
                f"{name}/closed64 {tag}", yaml_text, load,
                256_000, 1_024_000,
            )
            print_rows(rows, te, to)
    # mixed replica counts: the 1-replica bottleneck regression case
    mixed = """
services:
- name: a
  isEntrypoint: true
  numReplicas: 2
  script: [{call: b}]
- name: b
  numReplicas: 1
  script: [{call: c}]
- name: c
  numReplicas: 2
"""
    load = LoadModel(kind="closed", qps=None, connections=64)
    rows, (te, to) = compare(
        "mixed-k/closed64 max", mixed, load, 64_000, 256_000
    )
    print_rows(rows, te, to)


if __name__ == "__main__":
    main()
