"""Capture a jax.profiler trace of the bench step and dump HLO op stats."""
import glob
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from __graft_entry__ import _flagship
from isotope_tpu.metrics.histogram import latency_histogram
from isotope_tpu.sim.config import OPEN_LOOP
from isotope_tpu.sim.engine import Simulator

OUT = "/tmp/jaxprof"


def main():
    compiled = _flagship()
    sim = Simulator(compiled)
    n = 65_536
    qps = jnp.float32(100_000.0)

    @jax.jit
    def step(key):
        res = sim._simulate(n, OPEN_LOOP, 0, False, key, qps,
                            jnp.float32(0.0), qps)
        return res.hop_events, latency_histogram(res.client_latency)

    key = jax.random.PRNGKey(0)
    jax.block_until_ready(step(key))

    with jax.profiler.trace(OUT):
        out = None
        for i in range(3):
            out = step(jax.random.fold_in(key, i))
        jax.block_until_ready(out)

    xplanes = glob.glob(os.path.join(OUT, "**", "*.xplane.pb"),
                        recursive=True)
    print("xplane files:", xplanes)


if __name__ == "__main__":
    main()
