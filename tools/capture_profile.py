"""Thin shim over isotope_tpu.telemetry.profile (the promoted backend).

Kept so existing ``python tools/capture_profile.py`` invocations keep
working; the real capture path now lives in the package and also backs
``isotope-tpu telemetry --xla-trace``.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

OUT = "/tmp/jaxprof"


def main():
    from isotope_tpu.telemetry.profile import capture_xla_trace

    out = sys.argv[1] if len(sys.argv) > 1 else OUT
    xplanes = capture_xla_trace(out)
    print("xplane files:", xplanes)


if __name__ == "__main__":
    main()
