"""chaosgrid-smoke: the universal-member composition grid end-to-end.

PR 18 collapsed the ensemble member programs into ONE scan body whose
chaos tables, policy state, rollout state, and LB tables are optional
pytree leaves — and promoted the four host/trace constants that used
to make whole compositions impossible (canary-first kill splits,
ungraceful-kill resets, LB panic pools, saturated finite-population
tables) to stacked traced per-member arguments.  This smoke drives
the composition grid the old member REJECTED, then the all-on case:

1. **Grid**: each formerly-rejected composition (chaos x ungraceful,
   chaos x LB panic, chaos x saturated ``-qps max``, chaos x rollout)
   runs as a member-jittered fleet, and the jittered member is
   BIT-IDENTICAL to the solo Simulator built with its schedule.

2. **All-on fleet**: policies + LB panic + rollout kill split +
   UNGRACEFUL member-jittered chaos in ONE jitted program.  The kill
   windows differ across members and the severity statistic spreads.

3. **Worst-member postmortem**: the most-severe all-on member's
   jittered schedule, replayed through a solo ``run_rollouts``,
   reproduces the member bit-for-bit — summary histogram AND rollout
   controller weight series — so the postmortem artifact stays
   executable even at full composition depth.

``make chaosgrid-smoke`` wires it in next to chaosfleet-smoke.
"""
from __future__ import annotations

import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

import numpy as np

BASE = """
services:
- name: entry
  isEntrypoint: true
  numReplicas: 4
  script:
  - call: {service: worker, timeout: 850us, retries: 2}
- name: worker
  numReplicas: 4
  errorRate: 0.5%
"""

STORM = BASE + """
policies:
  defaults:
    retry_budget: {budget_percent: 25%}
  worker:
    breaker: {max_pending: 6, max_connections: 64,
              consecutive_errors: 5, base_ejection: 2s}
    autoscaler: {min_replicas: 2, max_replicas: 8,
                 target_utilization: 60%, sync_period: 1s,
                 stabilization_window: 3s}
"""

LB_YAML = """
policies:
  worker:
    lb: {policy: least_request, panic_threshold: 50%}
"""

ROLLOUT_YAML = """
rollouts:
  defaults:
    gates: {min_samples: 20}
  worker:
    steps: [10%, 50%, 100%]
    bake: 2s
    rollback: {cooldown: 4s, max_retries: 1}
    canary: {error_rate: 30%}
"""


def main() -> int:
    import jax

    from isotope_tpu.compiler import (
        compile_graph,
        compile_lb,
        compile_policies,
        compile_rollouts,
    )
    from isotope_tpu.models.graph import ServiceGraph
    from isotope_tpu.resilience import faults
    from isotope_tpu.sim.config import ChaosEvent, LoadModel, SimParams
    from isotope_tpu.sim.engine import Simulator
    from isotope_tpu.sim.ensemble import EnsembleSpec

    key = jax.random.PRNGKey(0)
    open_load = LoadModel(kind="open", qps=4_000.0)
    sat_load = LoadModel(kind="closed", qps=None, connections=8)
    n, block, win = 4_096, 1_024, 0.25
    chaos = (ChaosEvent("worker", 0.1, 0.3, replicas_down=3),)
    ungraceful = (ChaosEvent("worker", 0.1, 0.3, replicas_down=3,
                             drain=False),)
    jitter = faults.ChaosJitterSpec(time=0.3, magnitude=0.5, seed=11)
    reps = {"entry": 4, "worker": 4}

    def jittered(events, k):
        return faults.jitter_chaos_events(
            events, jitter,
            faults.member_event_seeds(jitter, k, len(events)), reps,
        )

    # -- 1. the formerly-rejected grid ---------------------------------
    # each cell: a 2-member fleet ([base schedule, jittered member 1])
    # whose jittered member must bit-equal the solo Simulator built
    # with that schedule — count and latency histogram alike
    def pin(stacked, solo, label):
        for name in ("count", "latency_hist"):
            assert np.array_equal(
                np.asarray(getattr(stacked, name))[1],
                np.asarray(getattr(solo, name)),
            ), f"{label}: member 1 != solo ({name})"

    grid = []

    c_plain = compile_graph(ServiceGraph.from_yaml(BASE))
    jit_u = jittered(ungraceful, 1)
    ens = Simulator(c_plain, chaos=ungraceful).run_ensemble(
        open_load, n, key, EnsembleSpec.of(2, mode="map"),
        block_size=block, member_chaos=[ungraceful, jit_u],
    )
    solo = Simulator(c_plain, chaos=jit_u).run_summary(
        open_load, n, jax.random.fold_in(key, 1), block_size=block
    )
    pin(ens.summaries, solo, "chaos x ungraceful")
    grid.append("ungraceful-kill resets")

    g_lb = ServiceGraph.from_yaml(BASE + LB_YAML)
    c_lb = compile_graph(g_lb)
    lbt = compile_lb(g_lb, c_lb)
    jit_c = jittered(chaos, 1)
    ens = Simulator(c_lb, chaos=chaos, lb=lbt).run_ensemble(
        open_load, n, key, EnsembleSpec.of(2, mode="map"),
        block_size=block, member_chaos=[chaos, jit_c],
    )
    solo = Simulator(c_lb, chaos=jit_c, lb=lbt).run_summary(
        open_load, n, jax.random.fold_in(key, 1), block_size=block
    )
    pin(ens.summaries, solo, "chaos x lb-panic")
    grid.append("LB panic pools")

    ens = Simulator(c_plain, chaos=chaos).run_ensemble(
        sat_load, n, key, EnsembleSpec.of(2, mode="map"),
        block_size=block, member_chaos=[chaos, jit_c],
    )
    solo = Simulator(c_plain, chaos=jit_c).run_summary(
        sat_load, n, jax.random.fold_in(key, 1), block_size=block
    )
    pin(ens.summaries, solo, "chaos x saturated")
    grid.append("saturated -qps max")

    g_r = ServiceGraph.from_yaml(STORM + ROLLOUT_YAML)
    c_r = compile_graph(g_r)
    pol_r = compile_policies(g_r, c_r)
    rt_r = compile_rollouts(g_r, c_r)
    sim_r = Simulator(c_r, SimParams(timeline=True), chaos=chaos,
                      policies=pol_r, rollouts=rt_r)
    ens = sim_r.run_rollouts_ensemble(
        open_load, n, key, EnsembleSpec.of(2, mode="map"),
        block_size=block, trim=True, window_s=win,
        member_chaos=[chaos, jit_c],
    )
    solo = Simulator(
        c_r, SimParams(timeline=True), chaos=jit_c,
        policies=pol_r, rollouts=rt_r,
    ).run_rollouts(
        open_load, n, jax.random.fold_in(key, 1), block_size=block,
        trim=True, window_s=win,
    )
    pin(ens.summaries, solo[0], "chaos x rollout")
    assert np.array_equal(
        np.asarray(ens.rollouts.weight)[1],
        np.asarray(solo[2].weight),
    ), "chaos x rollout: controller weight series diverged"
    grid.append("canary-first kill splits")

    print(
        "composition grid: "
        + ", ".join(grid)
        + " — each jittered member BIT-EQUAL to its solo twin"
    )

    # -- 2. the all-on fleet -------------------------------------------
    all_on = STORM.replace(
        "  worker:\n    breaker:",
        "  worker:\n    lb: {policy: least_request, "
        "panic_threshold: 50%}\n    breaker:",
    ) + ROLLOUT_YAML
    g = ServiceGraph.from_yaml(all_on)
    c = compile_graph(g)
    pol = compile_policies(g, c)
    rt = compile_rollouts(g, c)
    lbt = compile_lb(g, c)
    sim = Simulator(c, SimParams(timeline=True), chaos=ungraceful,
                    policies=pol, rollouts=rt, lb=lbt)
    members = 8
    fleet = sim.run_rollouts_ensemble(
        open_load, n, key, EnsembleSpec.of(members, mode="map"),
        block_size=block, trim=True, window_s=win,
        member_chaos=jitter,
    )
    starts = [evs[0].start_s for evs in fleet.member_chaos]
    downs = [evs[0].replicas_down for evs in fleet.member_chaos]
    assert len(set(round(s, 6) for s in starts)) > 1, \
        "kill timing did not vary across members"
    sev = fleet.severity()
    print(
        f"all-on fleet ({members} members): policies + LB panic + "
        f"rollout + ungraceful kills in one program; kill starts "
        f"{min(starts):.2f}..{max(starts):.2f}s, replicas_down "
        f"{min(downs)}..{max(downs)}, severity "
        f"{sev.min():.4f}..{sev.max():.4f}"
    )

    # -- 3. worst-member postmortem ------------------------------------
    worst = fleet.worst_member()
    replay_sim = Simulator(
        c, SimParams(timeline=True), chaos=fleet.member_chaos[worst],
        policies=pol, rollouts=rt, lb=lbt,
    )
    replay = replay_sim.run_rollouts(
        open_load, n, jax.random.fold_in(key, worst),
        block_size=block, trim=True, window_s=win,
    )
    assert np.array_equal(
        np.asarray(fleet.member(worst).latency_hist),
        np.asarray(replay[0].latency_hist),
    ), "worst-member replay diverged (summary)"
    assert np.array_equal(
        np.asarray(fleet.rollouts.weight)[worst],
        np.asarray(replay[2].weight),
    ), "worst-member replay diverged (rollout weight)"
    print(
        f"worst member {worst} replayed solo from its jittered "
        "schedule: BIT-EQUAL (summary + rollout controller) — the "
        "postmortem artifact survives full composition"
    )
    print("chaosgrid-smoke OK")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
