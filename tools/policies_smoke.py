"""policies-smoke: the mesh-policy acceptance scenario end-to-end.

A retry chain (entry -> worker, 850us timeout, 2 retries) takes a
chaos kill phase (3 of 4 worker replicas down for 5 s) twice:

- UNPROTECTED: the retry storm amplifies load and every request in the
  window (and the drain tail after it) transport-fails;
- PROTECTED (``policies:`` block): the circuit breaker trips at the
  kill onset and sheds the queue overflow, the retry budget truncates
  the attempt fan, and the HPA autoscaler recovers capacity — the
  cascade the reference system existed to benchmark.

Asserts the acceptance criteria: the protected run's retry-amplified
hop-event count and client-error share are STRICTLY lower, the breaker
trip and recovery appear as sim-time onsets on the timeline window
axis, the budget visibly caps retries, the autoscaler's replica series
rises in response, and the tail-attribution BLAME SHIFT is visible —
the worker's timeout blame collapses once the breaker sheds instead of
queueing.  ``make policies-smoke`` wires it into CI-style checks next
to the other smokes.
"""
from __future__ import annotations

import sys

import numpy as np

TOPOLOGY = """
services:
- name: entry
  isEntrypoint: true
  numReplicas: 4
  script:
  - call: {service: worker, timeout: 850us, retries: 2}
- name: worker
  numReplicas: 4
policies:
  worker:
    breaker: {max_pending: 6, max_connections: 48}
    retry_budget: {budget_percent: 20%, min_retries_concurrent: 2}
    autoscaler:
      min_replicas: 4
      max_replicas: 12
      target_utilization: 50%
      sync_period: 1s
      stabilization_window: 10s
      scale_up_step: 2
"""

MU = 13_000.0  # 1 / DEFAULT_CPU_TIME_S


def main() -> int:
    import jax

    from isotope_tpu.compiler import compile_graph, compile_policies
    from isotope_tpu.models.graph import ServiceGraph
    from isotope_tpu.sim import LoadModel, SimParams, Simulator
    from isotope_tpu.sim import policies as policies_mod
    from isotope_tpu.sim.config import ChaosEvent

    graph = ServiceGraph.from_yaml(TOPOLOGY)
    compiled = compile_graph(graph)
    tables = compile_policies(graph, compiled)
    assert tables is not None and tables.any_breaker
    worker = list(compiled.services.names).index("worker")

    params = SimParams(
        timeline=True, timeline_window_s=1.0, attribution=True
    )
    chaos = (ChaosEvent(service="worker", start_s=3.0, end_s=8.0,
                        replicas_down=3),)
    qps = 0.325 * 4 * MU
    load = LoadModel(kind="open", qps=qps)
    n, block = 270_000, 8192
    key = jax.random.PRNGKey(7)

    protected = Simulator(compiled, params, chaos, policies=tables)
    s_p, tl_p, pol, attr_p = protected.run_policies(
        load, n, key, block_size=block, window_s=1.0,
        attribution=True, tail=True,
    )
    unprotected = Simulator(compiled, params, chaos)
    s_u, tl_u = unprotected.run_timeline(
        load, n, key, block_size=block, window_s=1.0
    )
    _, attr_u = unprotected.run_attributed(
        load, n, key, block_size=block, tail=True
    )

    rc = 0

    def check(name, ok, detail):
        nonlocal rc
        status = "ok" if ok else "FAIL"
        print(f"  {status:<5} {name}: {detail}")
        if not ok:
            rc = 1

    hop_p, hop_u = float(s_p.hop_events), float(s_u.hop_events)
    err_p, err_u = float(s_p.error_count), float(s_u.error_count)
    share_p = err_p / max(float(s_p.count), 1.0)
    share_u = err_u / max(float(s_u.count), 1.0)
    check(
        "retry amplification", hop_p < hop_u,
        f"protected {hop_p:.0f} hop events < unprotected {hop_u:.0f}",
    )
    check(
        "error share", share_p < share_u,
        f"protected {share_p:.2%} < unprotected {share_u:.2%}",
    )

    doc = policies_mod.to_doc(compiled, pol, tables)
    w = doc["services"]["worker"]
    trip = w["breaker_trip_onset_s"]
    recover = w["breaker_recovery_s"]
    check(
        "breaker trip onset",
        trip is not None and 3.0 <= trip <= 6.0,
        f"tripped at t={trip}s (kill at 3s)",
    )
    check(
        "breaker recovery",
        recover is not None,
        f"shed back to 0 at t={recover}s",
    )
    allow = np.asarray(pol.retry_allow, np.float64)[worker]
    done = np.asarray(pol.windows_done, np.float64) > 0
    check(
        "retry budget caps the fan",
        bool((allow[done] < 1.0).any()),
        f"min retry_allow {allow[done].min():.3f}",
    )
    reps = np.asarray(pol.replicas, np.float64)[worker]
    check(
        "autoscaler recovery",
        float(reps[done].max()) > float(reps[done][0])
        and w["scale_events"] >= 1,
        f"replicas {reps[done][0]:.0f} -> peak {reps[done].max():.0f} "
        f"({w['scale_events']:.0f} scale event(s))",
    )
    # the blame SHIFT in tail attribution: unprotected, the storm's
    # timeouts own the worker's tail blame; protected, the breaker
    # sheds instead of queueing, so the worker's timeout blame and its
    # overall blame share both collapse
    from isotope_tpu.metrics import attribution as attr_mod

    def worker_row(attr, field):
        doc = attr_mod.to_doc(compiled, attr)
        rows = {r["service"]: r for r in doc[field]}
        return rows["worker"]

    to_p = worker_row(attr_p, "services")["timeout_s"]
    to_u = worker_row(attr_u, "services")["timeout_s"]
    check(
        "blame shift (timeout)", to_p < to_u,
        f"worker timeout blame {to_p:.1f}s < unprotected "
        f"{to_u:.1f}s",
    )
    sh_p = worker_row(attr_p, "tail_services")["share"]
    sh_u = worker_row(attr_u, "tail_services")["share"]
    check(
        "blame shift (tail share)", sh_p < sh_u,
        f"worker tail blame share {sh_p:.2%} < unprotected "
        f"{sh_u:.2%}",
    )

    # after the breaker closes, the protected error stream is quiet
    # while the unprotected run is still draining its storm backlog
    err_w_p = np.asarray(tl_p.errors, np.float64)
    err_w_u = np.asarray(tl_u.errors, np.float64)
    tail = slice(11, 14)
    check(
        "post-recovery quiet",
        err_w_p[tail].sum() < err_w_u[tail].sum(),
        f"windows 11-13 errors: protected {err_w_p[tail].sum():.0f} "
        f"vs unprotected {err_w_u[tail].sum():.0f}",
    )

    print(
        "policies-smoke:"
        + (" all checks passed" if rc == 0 else " FAILURES above")
    )
    return rc


if __name__ == "__main__":
    sys.exit(main())
