"""Per-case bench regression gate.

VERDICT r4 found svc1000 sliding 2.50B -> 2.05B -> 1.50B across rounds
with nothing noticing: ``bench.py`` reported best-of-3 and no check
compared against the previous round's driver capture.  This tool diffs
a fresh bench capture against the newest ``BENCH_r*.json`` in the repo
root and fails on any per-case regression beyond the threshold.

Usage:
    python bench.py | tee /tmp/bench.json
    python tools/bench_regress.py /tmp/bench.json

The driver's BENCH files wrap the parsed line under ``"parsed"``; a raw
``bench.py`` line is accepted too.  Only numeric, per-case rate keys
present in both captures are compared (evidence keys like
``*_inflight`` and spread keys are skipped); the headline ``value`` is
compared as case ``tree121``.

Optional telemetry gates — each armed by setting its env var to a
threshold (unset = not gated), compared per case over the
``<case>_telemetry`` blocks bench.py embeds:

- ``BENCH_REGRESS_COMPILE_THRESHOLD``: relative increase allowed on
  first-call compile seconds (``<case>_compile_s``, falling back to
  the telemetry block's ``compile_s``), e.g. ``0.5`` = +50%;
- ``BENCH_REGRESS_MEM_THRESHOLD``: relative increase allowed on
  ``peak_device_bytes``;
- ``BENCH_REGRESS_WASTE_THRESHOLD``: ABSOLUTE increase allowed on
  ``padding_waste_fraction`` (it is already a ratio);
- ``BENCH_REGRESS_VET_GATE=1``: fail a capture whose static-analysis
  pass (``vet_errors`` in the telemetry block — bench runs the
  no-trace vet per case) reports MORE errors than the previous
  capture's; captures without vet data on either side are skipped.
- ``BENCH_REGRESS_SPREAD_THRESHOLD``: relative spread bound on
  ``<case>_spread`` — a case past it that also got noisier than the
  previous capture fails (keeps bench.py's steady-state warmup
  discipline from silently regressing);
- ``BENCH_REGRESS_BLAME_THRESHOLD``: ABSOLUTE per-service drift
  allowed on the critical-path blame shares (``<case>_blame`` blocks
  from bench's attributed probe), e.g. ``0.1`` = 10 share points; a
  case's throughput can hold while its critical path migrates, which
  only this gate sees.
- ``BENCH_REGRESS_TIMELINE_THRESHOLD``: ABSOLUTE bound on the
  flight-recorder overhead (``<case>_timeline_overhead`` — bench's
  timeline-on vs timeline-off steady-state delta), e.g. ``0.05`` =
  the 5% svc1000 acceptance bar.
- ``BENCH_REGRESS_LAYOUT_GATE=1``: fail a capture whose automatic
  mesh-layout search picked a WORSE-scoring factorization than the
  baseline's (``_mesh_layout`` / ``_mesh_layout_score`` — bench
  embeds the ``--mesh auto`` choice and its comm-cost-model score).

Always armed (no env var): a case whose telemetry block carries
``degraded_to`` — the resilience supervisor served it from a
degradation-ladder rung — fails the gate if the previous round's
capture ran that case clean (a degraded number is not comparable).
"""
from __future__ import annotations

import glob
import json
import os
import re
import sys

# Fail when new < (1 - THRESHOLD) * old.  NOTE the instrument: the
# tunneled chip drifts by up to ~2x across sessions (interleaved
# A/B of r4-vs-r5 binaries measured both orderings within minutes),
# so the default gate is meaningful for SAME-SESSION comparisons
# (pre/post an optimization); across rounds, expect noise-fired
# alarms and read them against the per-case ``_spread`` evidence.
THRESHOLD = float(os.environ.get("BENCH_REGRESS_THRESHOLD", "0.15"))
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_capture(path: str) -> dict:
    with open(path) as f:
        text = f.read()
    # accept either a driver BENCH_r*.json wrapper or a raw bench line
    # (possibly preceded by jax warnings on stderr-merged logs)
    try:
        doc = json.loads(text)
    except json.JSONDecodeError:
        doc = None
        for line in text.splitlines():
            line = line.strip()
            if line.startswith("{"):
                try:
                    doc = json.loads(line)
                except json.JSONDecodeError:
                    continue
        if doc is None:
            raise
    if "parsed" in doc:
        doc = doc["parsed"]
    return doc


def _cases(doc: dict, prefer_best: bool = False) -> dict:
    """Per-case rates from a capture.

    ``prefer_best=True`` (applied to the NEW capture) compares the
    best-window statistic against older rounds: captures before r5
    reported best-of-3, so the r5 median would read as a spurious
    across-methodology "regression" otherwise.
    """
    extra = doc.get("extra", {})
    cases = {"tree121": float(doc["value"])}
    for k, v in extra.items():
        if not isinstance(v, (int, float)):
            continue
        if k.endswith(("_inflight", "_spread", "_census", "_best",
                       "_compile_s", "_warmup_windows",
                       "_timeline_overhead", "_blame_overhead",
                       "_mesh_layout_score",
                       "_rollout", "_lb", "_ensemble_members",
                       "_ensemble_traces", "_ensemble_solo_rate",
                       "_ensemble_speedup",
                       "_chaosfleet_members", "_chaosfleet_traces",
                       "_chaosfleet_worst_severity",
                       "_chaosfleet_split_p",
                       "_chaosfleet_split_evals",
                       "_composed", "_composed_members",
                       "_composed_traces",
                       "_composed_worst_severity",
                       "_search_candidates", "_search_rungs",
                       "_search_traces", "_search_sequential_rate",
                       "_search_speedup",
                       "_ingest_fit_s", "_ingest_services",
                       "_ingest_edges", "_ingest_lines",
                       "_ingest_qps")):
            # evidence / variance keys, not rates — "_composed" also
            # drops the svc1000_composed COVERAGE case's rate (its
            # telemetry degraded_to gate still applies)
            continue
        cases[k] = float(v)
    if prefer_best:
        for k in list(cases):
            b = extra.get(f"{k}_best")
            if isinstance(b, (int, float)):
                cases[k] = float(b)
    return cases


def _telemetry_value(extra: dict, case: str, field: str):
    """A case's telemetry field: the legacy flat ``<case>_compile_s``
    key wins for compile seconds (it predates the telemetry block),
    then the ``<case>_telemetry`` dict."""
    if field == "compile_s":
        flat = extra.get(f"{case}_compile_s")
        if isinstance(flat, (int, float)) and flat > 0:
            return float(flat)
    blk = extra.get(f"{case}_telemetry")
    if isinstance(blk, dict):
        v = blk.get(field)
        if isinstance(v, (int, float)):
            return float(v)
    return None


def telemetry_failures(prev_doc: dict, new_doc: dict) -> list:
    """Env-armed per-case gates on the embedded telemetry fields.

    Reads the thresholds at call time (not import) so one process can
    evaluate several configurations; an unset env var disarms its gate.
    """
    gates = (
        # (field, env var, relative?)
        ("compile_s", "BENCH_REGRESS_COMPILE_THRESHOLD", True),
        ("peak_device_bytes", "BENCH_REGRESS_MEM_THRESHOLD", True),
        ("padding_waste_fraction", "BENCH_REGRESS_WASTE_THRESHOLD",
         False),
    )
    prev_extra = prev_doc.get("extra", {})
    new_extra = new_doc.get("extra", {})
    cases = sorted(
        {k[: -len("_telemetry")] for k in prev_extra if
         k.endswith("_telemetry")}
        | {k[: -len("_compile_s")] for k in prev_extra if
           k.endswith("_compile_s")}
    )
    failures = []
    for field, env, relative in gates:
        raw = os.environ.get(env)
        if raw is None or raw == "":
            continue
        thr = float(raw)
        for case in cases:
            old = _telemetry_value(prev_extra, case, field)
            new = _telemetry_value(new_extra, case, field)
            if old is None or new is None:
                continue
            if relative:
                if old <= 0:
                    continue
                bad = new > old * (1.0 + thr)
                delta = f"{(new / old - 1) * 100:+.1f}%"
            else:
                bad = new > old + thr
                delta = f"{new - old:+.4f}"
            verdict = "REGRESSION" if bad else "OK"
            print(f"bench_regress: {case}.{field}: {old:.4g} -> "
                  f"{new:.4g} ({delta}) {verdict}")
            if bad:
                failures.append(f"{case}.{field}")
    return failures


def vet_failures(prev_doc: dict, new_doc: dict) -> list:
    """Opt-in gate (``BENCH_REGRESS_VET_GATE=1``): a case whose vet run
    reports MORE errors than the previous capture's vet run regressed.

    Both captures must carry vet data (``vet_errors`` in the
    ``<case>_telemetry`` block — present only when the capture actually
    vetted, telemetry/core.py summary_block): a baseline from before
    vet existed is skipped, never read as "zero errors".
    """
    if os.environ.get("BENCH_REGRESS_VET_GATE", "") not in (
        "1", "true", "on", "yes",
    ):
        return []
    prev_extra = prev_doc.get("extra", {})
    new_extra = new_doc.get("extra", {})
    failures = []
    for k, blk in sorted(new_extra.items()):
        if not k.endswith("_telemetry") or not isinstance(blk, dict):
            continue
        new_errs = blk.get("vet_errors")
        prev_blk = prev_extra.get(k)
        old_errs = (
            prev_blk.get("vet_errors")
            if isinstance(prev_blk, dict)
            else None
        )
        if new_errs is None or old_errs is None:
            continue  # one side never vetted: nothing comparable
        case = k[: -len("_telemetry")]
        bad = int(new_errs) > int(old_errs)
        verdict = "REGRESSION" if bad else "OK"
        print(f"bench_regress: {case}.vet_errors: {int(old_errs)} -> "
              f"{int(new_errs)} {verdict}")
        if bad:
            failures.append(f"{case}.vet_errors")
    return failures


def blame_failures(prev_doc: dict, new_doc: dict) -> list:
    """Opt-in gate (``BENCH_REGRESS_BLAME_THRESHOLD=<abs drift>``): a
    case whose per-service critical-path blame SHARE moved by more
    than the threshold (absolute, shares are in [0, 1]) vs the
    previous capture regressed.

    Blame shares localize *where* latency comes from — a case can hold
    its throughput while its critical path silently migrates (e.g. a
    queueing change moving p99 blame from a leaf to the entry), which
    the rate gates cannot see.  Both captures must carry the case's
    ``<case>_blame`` block (bench embeds it via a small attributed
    run); baselines from before attribution existed are skipped.
    """
    raw = os.environ.get("BENCH_REGRESS_BLAME_THRESHOLD")
    if raw is None or raw == "":
        return []
    thr = float(raw)
    prev_extra = prev_doc.get("extra", {})
    new_extra = new_doc.get("extra", {})
    failures = []
    for k, blk in sorted(new_extra.items()):
        if not k.endswith("_blame") or not isinstance(blk, dict):
            continue
        prev_blk = prev_extra.get(k)
        if not isinstance(prev_blk, dict):
            continue  # baseline never carried blame: nothing comparable
        case = k[: -len("_blame")]
        new_sv = blk.get("services") or {}
        old_sv = prev_blk.get("services") or {}
        worst_svc, worst = None, 0.0
        for svc in set(new_sv) | set(old_sv):
            drift = abs(
                float(new_sv.get(svc, 0.0)) - float(old_sv.get(svc, 0.0))
            )
            if drift > worst:
                worst_svc, worst = svc, drift
        bad = worst > thr
        verdict = "REGRESSION" if bad else "OK"
        print(
            f"bench_regress: {case}.blame: max share drift "
            f"{worst:+.4f}"
            + (f" ({worst_svc})" if worst_svc else "")
            + f" {verdict}"
        )
        if bad:
            failures.append(f"{case}.blame")
    return failures


def timeline_failures(new_doc: dict) -> list:
    """Opt-in gate (``BENCH_REGRESS_TIMELINE_THRESHOLD=<max overhead>``):
    a case whose measured flight-recorder overhead
    (``<case>_timeline_overhead``, the timeline-on vs timeline-off
    steady-state delta bench.py embeds) exceeds the threshold fails.

    An ABSOLUTE bound, not a vs-baseline diff: the acceptance bar is
    "timeline-on costs <= X of timeline-off" (5% on svc1000), which
    holds or it doesn't — comparing drifting overheads against each
    other would let the bound creep."""
    raw = os.environ.get("BENCH_REGRESS_TIMELINE_THRESHOLD")
    if raw is None or raw == "":
        return []
    thr = float(raw)
    failures = []
    for k, v in sorted(new_doc.get("extra", {}).items()):
        if not k.endswith("_timeline_overhead") or not isinstance(
            v, (int, float)
        ):
            continue
        case = k[: -len("_timeline_overhead")]
        bad = float(v) > thr
        verdict = "REGRESSION" if bad else "OK"
        print(f"bench_regress: {case}.timeline_overhead: "
              f"{float(v):+.3f} (threshold {thr:.3f}) {verdict}")
        if bad:
            failures.append(f"{case}.timeline_overhead")
    return failures


def fleetblame_failures(new_doc: dict) -> list:
    """Opt-in gate (``BENCH_REGRESS_FLEETBLAME_THRESHOLD=<max
    overhead>``): a fleet case whose measured blame-pass overhead
    (``<case>_blame_overhead``, the attribution-on vs attribution-off
    fleet steady-state delta bench.py embeds) exceeds the threshold
    fails.

    Same discipline as :func:`timeline_failures` — an ABSOLUTE bound:
    "blame-on costs <= X of blame-off" holds or it doesn't; diffing
    drifting overheads against each other would let the bound creep.
    """
    raw = os.environ.get("BENCH_REGRESS_FLEETBLAME_THRESHOLD")
    if raw is None or raw == "":
        return []
    thr = float(raw)
    failures = []
    for k, v in sorted(new_doc.get("extra", {}).items()):
        if not k.endswith("_blame_overhead") or not isinstance(
            v, (int, float)
        ):
            continue
        case = k[: -len("_blame_overhead")]
        bad = float(v) > thr
        verdict = "REGRESSION" if bad else "OK"
        print(f"bench_regress: {case}.blame_overhead: "
              f"{float(v):+.3f} (threshold {thr:.3f}) {verdict}")
        if bad:
            failures.append(f"{case}.blame_overhead")
    return failures


def ensemble_failures(prev_doc: dict, new_doc: dict) -> list:
    """Opt-in gate (``BENCH_REGRESS_ENSEMBLE_THRESHOLD=<ratio>``): a
    fleet case whose PER-MEMBER throughput (case rate divided by its
    ``<case>_ensemble_members``) regressed beyond the threshold vs the
    previous capture fails.

    The aggregate rate alone can hide a per-member regression behind a
    member-count change (double the members, tank each member 40%, still
    "faster") — normalizing by the fleet width keeps the comparison
    per-scenario-honest.  Captures without the members key on either
    side are skipped (pre-ensemble baselines).
    """
    raw = os.environ.get("BENCH_REGRESS_ENSEMBLE_THRESHOLD")
    if raw is None or raw == "":
        return []
    thr = float(raw)
    prev_extra = prev_doc.get("extra", {})
    new_extra = new_doc.get("extra", {})
    prev_rates = _cases(prev_doc)
    new_rates = _cases(new_doc)
    failures = []
    for k, new_m in sorted(new_extra.items()):
        if not k.endswith("_ensemble_members") or not isinstance(
            new_m, (int, float)
        ):
            continue
        case = k[: -len("_ensemble_members")]
        old_m = prev_extra.get(k)
        if not isinstance(old_m, (int, float)) or old_m <= 0 \
                or new_m <= 0:
            continue
        if case not in prev_rates or case not in new_rates:
            continue
        old_pm = prev_rates[case] / float(old_m)
        new_pm = new_rates[case] / float(new_m)
        bad = old_pm > 0 and new_pm < old_pm * (1.0 - thr)
        verdict = "REGRESSION" if bad else "OK"
        print(f"bench_regress: {case}.per_member: {old_pm:.4g} -> "
              f"{new_pm:.4g} "
              f"({(new_pm / old_pm - 1) * 100:+.1f}%) {verdict}")
        if bad:
            failures.append(f"{case}.per_member")
    return failures


def search_failures(new_doc: dict) -> list:
    """Opt-in gate (``BENCH_REGRESS_SEARCH_THRESHOLD=<ratio>``): a
    config-search bracket case whose measured speedup over the
    sequential sweep (``<case>_search_speedup``) fell under the
    threshold fails the round.

    Like the timeline-overhead gate this is an absolute bound on the
    NEW capture, not a ratio against the previous one — the bracket's
    perf claim (the ISSUE's >= 3x bar) either holds or it doesn't;
    comparing drifting speedups would let the bound creep.  The trace
    bound rides along: a bracket that compiled more executables than
    rungs (``_search_traces`` > ``_search_rungs``) lost the
    one-compile-per-rung-shape property the speedup rests on.
    """
    raw = os.environ.get("BENCH_REGRESS_SEARCH_THRESHOLD")
    if raw is None or raw == "":
        return []
    thr = float(raw)
    failures = []
    new_extra = new_doc.get("extra", {})
    for k, v in sorted(new_extra.items()):
        if not k.endswith("_search_speedup") or not isinstance(
            v, (int, float)
        ):
            continue
        case = k[: -len("_search_speedup")]
        bad = float(v) < thr
        verdict = "REGRESSION" if bad else "OK"
        print(f"bench_regress: {case}.search_speedup: {float(v):.3f} "
              f"(threshold {thr:.3f}) {verdict}")
        if bad:
            failures.append(f"{case}.search_speedup")
        traces = new_extra.get(f"{case}_search_traces")
        rungs = new_extra.get(f"{case}_search_rungs")
        if isinstance(traces, (int, float)) and isinstance(
            rungs, (int, float)
        ) and traces > rungs:
            print(f"bench_regress: {case}.search_traces: "
                  f"{int(traces)} > {int(rungs)} rung shapes "
                  "REGRESSION")
            failures.append(f"{case}.search_traces")
    return failures


def layout_failures(prev_doc: dict, new_doc: dict) -> list:
    """Opt-in gate (``BENCH_REGRESS_LAYOUT_GATE=1``): the automatic
    mesh-layout search (parallel/layout.py — bench embeds the chosen
    factorization and its cost-model score as ``_mesh_layout`` /
    ``_mesh_layout_score``) must never pick a WORSE-scoring mesh than
    the recorded baseline's.  A higher score means a search or
    cost-model change regressed the chosen layout — visible here
    before any multi-host run pays for it.  Captures without layout
    data on either side are skipped (pre-gate baselines)."""
    if os.environ.get("BENCH_REGRESS_LAYOUT_GATE", "") not in (
        "1", "true", "on", "yes",
    ):
        return []
    prev_extra = prev_doc.get("extra", {})
    new_extra = new_doc.get("extra", {})
    old = prev_extra.get("_mesh_layout_score")
    new = new_extra.get("_mesh_layout_score")
    if not isinstance(old, (int, float)) or not isinstance(
        new, (int, float)
    ):
        print("bench_regress: layout gate: no _mesh_layout_score on "
              "one side — skipped")
        return []
    bad = float(new) > float(old) * (1.0 + 1e-9)
    verdict = "REGRESSION" if bad else "OK"
    print(f"bench_regress: _mesh_layout: "
          f"{prev_extra.get('_mesh_layout')!r} ({float(old):.3g}s) -> "
          f"{new_extra.get('_mesh_layout')!r} ({float(new):.3g}s) "
          f"{verdict}")
    return ["_mesh_layout"] if bad else []


def spread_failures(prev_doc: dict, new_doc: dict) -> list:
    """Opt-in gate (``BENCH_REGRESS_SPREAD_THRESHOLD=<ratio>``): a case
    whose window-to-window relative spread (``<case>_spread``) exceeds
    the threshold AND got noisier than the previous capture regressed.

    This keeps noise fixes fixed: once a case's steady-state discipline
    (bench.py warmup windows) brings its spread under the threshold, a
    later change that re-noises it fails the round — deltas measured
    through a 25% spread cannot clear the 15% rate gate honestly.  A
    case already past the threshold in the baseline only fails when it
    gets WORSE (no permanent alarm on known-noisy cases).
    """
    raw = os.environ.get("BENCH_REGRESS_SPREAD_THRESHOLD")
    if raw is None or raw == "":
        return []
    thr = float(raw)
    prev_extra = prev_doc.get("extra", {})
    new_extra = new_doc.get("extra", {})
    failures = []
    for k, v in sorted(new_extra.items()):
        if not k.endswith("_spread") or not isinstance(v, (int, float)):
            continue
        case = k[: -len("_spread")]
        old = prev_extra.get(k)
        old_ok = isinstance(old, (int, float))
        bad = float(v) > thr and (not old_ok or float(v) > float(old))
        verdict = "REGRESSION" if bad else "OK"
        prev_txt = f"{float(old):.3f}" if old_ok else "n/a"
        print(f"bench_regress: {case}.spread: {prev_txt} -> "
              f"{float(v):.3f} (threshold {thr:.3f}) {verdict}")
        if bad:
            failures.append(f"{case}.spread")
    return failures


def degradation_failures(prev_doc: dict, new_doc: dict) -> list:
    """Always-armed gate: a case that DEGRADED in the new capture but
    ran clean in the previous round is a regression.

    The resilience supervisor (isotope_tpu/resilience/) lets an OOM'd
    case complete on a fallback rung instead of crashing — which must
    never silently normalize: a benchmark number produced by the
    half-block or single-device rung is not comparable to the mesh
    path's, so bench gates on the ``degraded_to`` key the telemetry
    block carries only when a degradation happened.
    """
    prev_extra = prev_doc.get("extra", {})
    new_extra = new_doc.get("extra", {})
    failures = []
    for k, blk in sorted(new_extra.items()):
        if not k.endswith("_telemetry") or not isinstance(blk, dict):
            continue
        degraded = blk.get("degraded_to")
        if not degraded:
            continue
        case = k[: -len("_telemetry")]
        prev_blk = prev_extra.get(k)
        prev_degraded = (
            prev_blk.get("degraded_to")
            if isinstance(prev_blk, dict)
            else None
        )
        if prev_degraded:
            print(f"bench_regress: {case}: degraded to {degraded!r} "
                  f"(previously {prev_degraded!r}) OK")
            continue
        print(f"bench_regress: {case}: DEGRADED to {degraded!r} on a "
              "previously clean case REGRESSION")
        failures.append(f"{case}.degraded_to")
    return failures


def previous_capture() -> tuple:
    """(path, parsed_doc) of the newest BENCH_r*.json, or (None, None)."""
    files = sorted(
        glob.glob(os.path.join(REPO_ROOT, "BENCH_r*.json")),
        # match against the BASENAME only: a checkout path containing
        # "r<digit>" (e.g. /home/r2/repo) must not key the ordering
        key=lambda p: int(
            re.search(r"r(\d+)", os.path.basename(p)).group(1)
        ),
    )
    if not files:
        return None, None
    path = files[-1]
    return path, _load_capture(path)


def main() -> int:
    if len(sys.argv) != 2:
        print(__doc__)
        return 2
    new_doc = _load_capture(sys.argv[1])
    prev_path, prev_doc = previous_capture()
    if prev_doc is None:
        print("bench_regress: no BENCH_r*.json baseline found — skipping")
        return 0
    prev = _cases(prev_doc)
    # like-for-like statistics: an r5+ baseline carries medians (and
    # *_best evidence keys) — compare median vs median; a pre-r5
    # baseline reported best-of-window, so compare the NEW capture's
    # best against it (new-best-vs-old-median would mask a real median
    # regression behind the +-40% window spread)
    baseline_has_best = any(
        k.endswith("_best") for k in prev_doc.get("extra", {})
    )
    new = _cases(new_doc, prefer_best=not baseline_has_best)
    new_extra = new_doc.get("extra", {})
    failures = []
    for case, old_rate in sorted(prev.items()):
        if case in new_extra and new_extra[case] is None:
            # the case crashed or timed out inside bench.py — a
            # vanished case must fail the gate, not be skipped
            print(f"bench_regress: {case}: FAILED in the new capture "
                  f"(was {old_rate:.3g})")
            failures.append(case)
            continue
        if case not in new:
            print(f"bench_regress: {case}: dropped from capture "
                  f"(was {old_rate:.3g}) — not compared")
            continue
        ratio = new[case] / old_rate if old_rate > 0 else float("inf")
        verdict = "OK"
        if ratio < 1.0 - THRESHOLD:
            verdict = "REGRESSION"
            failures.append(case)
        print(f"bench_regress: {case}: {old_rate:.4g} -> "
              f"{new[case]:.4g} ({(ratio - 1) * 100:+.1f}%) {verdict}")
    failures.extend(telemetry_failures(prev_doc, new_doc))
    failures.extend(degradation_failures(prev_doc, new_doc))
    failures.extend(vet_failures(prev_doc, new_doc))
    failures.extend(blame_failures(prev_doc, new_doc))
    failures.extend(spread_failures(prev_doc, new_doc))
    failures.extend(timeline_failures(new_doc))
    failures.extend(fleetblame_failures(new_doc))
    failures.extend(ensemble_failures(prev_doc, new_doc))
    failures.extend(search_failures(new_doc))
    failures.extend(layout_failures(prev_doc, new_doc))
    if failures:
        print(f"bench_regress: FAIL vs {prev_path}: "
              f"{', '.join(failures)} regressed >"
              f"{THRESHOLD:.0%}")
        return 1
    print(f"bench_regress: PASS vs {prev_path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
