"""ensemble-smoke: the scenario-ensemble acceptance story end-to-end.

One svc-scale fleet (the vendored 1000-service fan-out) of 32 seed
members on CPU, checked three ways (sim/ensemble.py):

1. **One compile serves the fleet**: the telemetry trace counters must
   record exactly ONE engine trace (and one executable-cache build)
   for the whole 32-member dispatch — the executable cache keys on the
   ensemble dim, so every member (and every later fleet of the same
   width) rides that single compile.

2. **Distributional answers match brute force**: the fleet's
   P(p99 > SLO) estimate (Wilson CI) must agree EXACTLY with the
   brute-force per-seed Python loop over solo runs — member k of the
   fleet is bit-identical to the solo run with ``fold_in(key, k)``,
   so the two estimators see the same 32 p99 samples.

3. **Aggregate beats sequential**: fleet wall-clock vs the 32
   sequential solo dispatches (one host sync each — the Python case
   loop the ensemble axis replaces).  The asserted bar here is >= 1.2x
   (CI boxes down to ONE core must pass; the bench.py ``ensembleN``
   case carries the >= 2x screening-regime evidence with medians and
   spreads).

``make ensemble-smoke`` wires it into CI-style checks next to the
other smokes.
"""
from __future__ import annotations

import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

import numpy as np


def main() -> int:
    import jax
    import yaml

    from isotope_tpu import telemetry
    from isotope_tpu.compiler import compile_graph
    from isotope_tpu.metrics.histogram import quantile_from_histogram
    from isotope_tpu.models.graph import ServiceGraph
    from isotope_tpu.sim import LoadModel
    from isotope_tpu.sim.engine import Simulator
    from isotope_tpu.sim.ensemble import EnsembleSpec, wilson_interval

    telemetry.reset()
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(
        root, "examples/topologies/1000-svc_2000-end.yaml"
    )) as f:
        doc = yaml.safe_load(f)
    sim = Simulator(compile_graph(ServiceGraph.decode(doc)))
    load = LoadModel(kind="open", qps=10_000.0)
    key = jax.random.PRNGKey(42)
    members, n, block = 32, 64, 64
    spec = EnsembleSpec.of(members)

    # -- 1. one compile serves the fleet --------------------------------
    traces0 = telemetry.counter_get("engine_traces")
    misses0 = telemetry.counter_get("executable_cache_misses")
    ens = sim.run_ensemble(load, n, key, spec, block_size=block)
    traces = int(telemetry.counter_get("engine_traces") - traces0)
    builds = int(
        telemetry.counter_get("executable_cache_misses") - misses0
    )
    print(
        f"ensemble-smoke: {members}-member fleet: {traces} engine "
        f"trace(s), {builds} executable build(s)"
    )
    assert traces == 1, (
        f"the fleet must compile ONCE, recorded {traces} traces"
    )

    # a second fleet of the same width must re-use the compiled
    # program: zero new traces, zero new executable builds
    traces1 = telemetry.counter_get("engine_traces")
    misses1 = telemetry.counter_get("executable_cache_misses")
    sim.run_ensemble(
        load, n, jax.random.fold_in(key, 1), spec, block_size=block
    )
    re_traces = int(telemetry.counter_get("engine_traces") - traces1)
    re_builds = int(
        telemetry.counter_get("executable_cache_misses") - misses1
    )
    assert re_traces == 0 and re_builds == 0, (
        f"the second fleet must reuse the compile (got {re_traces} "
        f"traces, {re_builds} builds)"
    )
    print("ensemble-smoke: second fleet: 0 new traces, 0 new builds "
          "(cache serves the whole width)")

    # -- 2. P(SLO violation) vs the brute-force per-seed loop ----------
    q = 0.99
    p99s = ens.member_quantiles((q,))[:, 0]
    slo_s = float(np.median(p99s))  # a bar some members straddle
    est = ens.slo_violation(slo_s, quantile=q)
    # warm the solo program first: the sequential baseline must pay
    # per-dispatch overhead only, not the one-time compile
    solo_warm = sim.run_summary(load, n, key, block_size=block)
    jax.block_until_ready(solo_warm.count)
    t0 = time.perf_counter()
    brute = []
    for s_i in spec.seeds:
        solo = sim.run_summary(
            load, n, jax.random.fold_in(key, s_i), block_size=block
        )
        brute.append(float(quantile_from_histogram(
            np.asarray(solo.latency_hist), (q,)
        )[0]))
    seq_dt = time.perf_counter() - t0
    k_brute = int(np.sum(np.asarray(brute) > slo_s))
    lo, hi = wilson_interval(k_brute, members)
    print(
        f"ensemble-smoke: P(p99 > {slo_s * 1e3:.2f}ms) = "
        f"{est['p_violation']:.3f} "
        f"[{est['ci_lo']:.3f}, {est['ci_hi']:.3f}] @95% "
        f"(fleet) vs {k_brute / members:.3f} [{lo:.3f}, {hi:.3f}] "
        "(brute-force per-seed loop)"
    )
    assert est["violations"] == k_brute, (
        "fleet members must be bit-identical to the solo loop: "
        f"violation counts differ ({est['violations']} vs {k_brute})"
    )
    assert (est["ci_lo"], est["ci_hi"]) == (lo, hi), "Wilson CI drifted"

    # -- 3. aggregate vs sequential wall-clock --------------------------
    t0 = time.perf_counter()
    ens2 = sim.run_ensemble(
        load, n, jax.random.fold_in(key, 2), spec, block_size=block
    )
    jax.block_until_ready(ens2.summaries.count)
    fleet_dt = time.perf_counter() - t0
    speedup = seq_dt / max(fleet_dt, 1e-9)
    print(
        f"ensemble-smoke: fleet {fleet_dt * 1e3:.0f}ms vs "
        f"{members} sequential dispatches {seq_dt * 1e3:.0f}ms "
        f"-> {speedup:.2f}x aggregate"
    )
    assert speedup >= 1.2, (
        f"the fleet must beat the sequential loop (got {speedup:.2f}x;"
        " bench.py ensembleN carries the >= 2x screening-regime"
        " evidence)"
    )
    print("ensemble-smoke: PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
