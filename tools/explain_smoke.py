"""explain-smoke: the fleet-observability acceptance story end-to-end.

A fan-out topology runs as a 4-member Monte Carlo fleet with the
attribution pass AND the flight recorder threaded through the member
axis (PR 17), with a PLANTED bad member: member 2's chaos schedule
kills 3 of 4 ``worker`` replicas at t=0.3s while every other member
loses one.  The check:

1. **One fleet dispatch carries all evidence**: blame vectors,
   per-hop histograms, and window series for every member come off the
   same ``run_ensemble(attribution=True, timeline=True)`` program —
   no per-member re-runs.

2. **The explainer localizes the plant from artifacts alone**: the
   ``isotope-fleet-blame/v1`` doc is written to disk, then
   ``isotope-tpu explain`` (the same code path as the CLI) must rank
   member 2 worst, blame the ``worker`` hop, place the onset window at
   the kill time (~0.3s with 0.1s windows), and report the band
   departure — WITHOUT touching the simulator again.

3. **The postmortem replay recipe is honest**: the worst member's
   stacked blame is bit-identical to a solo ``run_attributed`` with
   its folded member key.

``make explain-smoke`` wires it in next to the other smokes.
"""
from __future__ import annotations

import json
import os
import sys
import tempfile

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

import numpy as np

TOPOLOGY = """
services:
- name: entry
  isEntrypoint: true
  script:
  - call: worker
- name: worker
  numReplicas: 4
- name: cold
  numReplicas: 2
"""


def main() -> int:
    import jax

    from isotope_tpu.commands.explain_cmd import run_explain_cmd
    from isotope_tpu.compiler import compile_graph
    from isotope_tpu.metrics import fleetblame
    from isotope_tpu.models.graph import ServiceGraph
    from isotope_tpu.sim.config import ChaosEvent, LoadModel, SimParams
    from isotope_tpu.sim.engine import Simulator
    from isotope_tpu.sim.ensemble import EnsembleSpec

    compiled = compile_graph(ServiceGraph.from_yaml(TOPOLOGY))
    mild = (ChaosEvent("worker", 0.3, 1.0, replicas_down=1),)
    sim = Simulator(
        compiled,
        SimParams(attribution=True, timeline=True),
        chaos=mild,
    )
    load = LoadModel(kind="open", qps=4_000.0)
    key = jax.random.PRNGKey(3)
    spec = EnsembleSpec.of(4)
    # the plant: member 2's bad day is categorically worse
    events = [mild, mild,
              (ChaosEvent("worker", 0.3, 1.0, replicas_down=3),),
              mild]

    # 1. one observed fleet dispatch
    obs = sim.run_ensemble(
        load, 4_096, key, spec, block_size=1_024,
        attribution=True, timeline=True, window_s=0.1,
        member_chaos=events,
    )
    assert obs.attributions is not None and obs.timelines is not None
    print("smoke: observed fleet ran "
          f"({obs.members} members, one dispatch)")

    # 2. artifact -> explain, no simulator in the loop
    # no severity channel: members rank by positive blame excess vs
    # the control member (this topology is error-free, so err_peak
    # would tie every member)
    doc = fleetblame.to_doc(
        compiled, obs.attributions, obs.timelines,
        label="explain-smoke", seeds=spec.seeds,
        window_s=float(
            np.asarray(obs.timelines.window_s).reshape(-1)[0]
        ),
    )
    worst = doc["ranking"][0]
    assert worst == 2, f"explainer ranked member {worst}, wanted 2"
    entry = [m for m in doc["member_blame"] if m["member"] == 2][0]
    hop = entry["gap_ranking"][0]["service"]
    assert hop == "worker", f"blamed hop {hop!r}, wanted 'worker'"
    onset = entry["onset"]
    assert onset is not None and onset["service"] == "worker"
    assert 2 <= onset["window"] <= 5, onset
    print(f"smoke: plant localized — member 2, hop {hop!r}, onset "
          f"window {onset['window']} (~{onset['time_s']:.1f}s, "
          f"{onset['depth']:.1f} sigmas out of band)")

    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "smoke.fleet-blame.json")
        with open(path, "w") as f:
            json.dump(doc, f)

        class Args:
            label = None
            top = 3
            hops = 3
            json = False

        Args.path = td
        rc = run_explain_cmd(Args())
        assert rc == 0, f"explain exited {rc}"
    report = fleetblame.format_report(doc)
    assert "member 2" in report and "worker" in report
    assert "onset" in report and "band" in report
    print("smoke: explain renders the why-report from the artifact "
          "alone")

    # 3. the replay recipe
    mkey = jax.random.fold_in(key, spec.seeds[2])
    solo_sim = Simulator(
        compiled,
        SimParams(attribution=True, timeline=True),
        chaos=events[2],
    )
    _, solo = solo_sim.run_attributed(load, 4_096, mkey,
                                      block_size=1_024)
    fleet_blame = obs.member_attribution(2)
    # event counts and histograms replay BIT-equal; the blame-seconds
    # floats match to accumulation epsilon — the solo replay bakes the
    # chaos schedule in as compile-time constants while the fleet
    # threads it as traced member rows, so XLA folds the float
    # reductions differently (seeds-only fleets, where the programs
    # are identical, pin the floats bit-equal in
    # tests/test_fleetblame.py)
    for name in ("count", "crit_count", "hist", "error_count"):
        a = np.asarray(getattr(solo, name))
        b = np.asarray(getattr(fleet_blame, name))
        assert np.array_equal(a, b), f"replay leaf {name} diverged"
    for name in ("wait_blame", "self_blame", "net_blame"):
        a = np.asarray(getattr(solo, name))
        b = np.asarray(getattr(fleet_blame, name))
        assert np.allclose(a, b, rtol=0, atol=1e-6), (
            f"replay leaf {name} diverged"
        )
    print("smoke: worst-member blame replays solo (counts bit-equal, "
          "blame seconds to accumulation epsilon)")
    print("explain-smoke PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
