"""lb-smoke: the load-balancing-law acceptance scenarios end-to-end.

Two scenarios over an entry -> worker chain (sim/lb.py):

1. **Heterogeneous backends, hot pool** (worker rho ~ 0.9): the same
   traffic under three balancing laws —

   - ``wrr`` with weights ``[3, 1, 1, 1]``: the classic one-slow-pod
     pool (a mis-weighted endpoint attracting 3x its fair share is
     indistinguishable, census-wise, from a pod serving at 1/3 speed).
     Its hot backend saturates and the tail explodes;
   - ``fifo``: the legacy shared-queue M/M/k idealization — blind to
     backends, and at high utilization its Erlang-C tail decays at
     only ``k mu (1 - rho)``;
   - ``least_request`` (power-of-2-choices): samples the per-backend
     census and joins the least loaded — queue tails decay doubly
     exponentially, so at rho ~0.9 it beats BOTH.

   Asserts ``p99(least_request) < p99(fifo) < p99(wrr-hot)``, and
   prints the per-window per-backend load split of the skewed pool
   (the lb.json census surface).

2. **Panic routing through an ejection storm**: a chaos phase kills
   3 of 4 worker replicas mid-run.  Without panic every arrival piles
   onto the lone survivor (rho >> 1, second-scale waits); with
   ``panic_threshold: 50%`` the mesh routes to ALL backends — the
   dead-backend share fast-fails, the survivor keeps its undegraded
   load, and goodput stays nonzero through every storm window.

   Asserts nonzero worker goodput (ok hops per window) through the
   storm AND a strictly lower p99 than the unprotected twin.

``make lb-smoke`` wires it into CI-style checks next to the other
smokes.
"""
from __future__ import annotations

import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

import numpy as np

BASE = """
services:
- name: entry
  isEntrypoint: true
  numReplicas: 8
  script:
  - call: worker
- name: worker
  numReplicas: 4
"""

LAWS = {
    "fifo": "policies:\n  worker:\n    lb: fifo\n",
    "least_request": (
        "policies:\n  worker:\n"
        "    lb: {policy: least_request, choices_d: 2}\n"
    ),
    "wrr_hot": (
        "policies:\n  worker:\n"
        "    lb: {policy: wrr, weights: [3, 1, 1, 1]}\n"
    ),
    "panic": (
        "policies:\n  worker:\n"
        "    lb: {policy: least_request, choices_d: 2, "
        "panic_threshold: 50%}\n"
    ),
}


def main() -> int:
    import jax

    from isotope_tpu.compiler import compile_graph, compile_lb
    from isotope_tpu.metrics.histogram import quantile_from_histogram
    from isotope_tpu.models.graph import ServiceGraph
    from isotope_tpu.sim import LoadModel, SimParams, Simulator
    from isotope_tpu.sim import lb as lb_mod
    from isotope_tpu.sim.config import ChaosEvent

    key = jax.random.PRNGKey(7)
    n, block = 32_768, 4_096

    def build(law: str, chaos=()):
        g = ServiceGraph.from_yaml(BASE + LAWS.get(law, ""))
        c = compile_graph(g)
        t = compile_lb(g, c)
        sim = Simulator(c, SimParams(timeline=True), chaos=chaos, lb=t)
        return sim, c, t

    def p99(summary) -> float:
        return float(
            quantile_from_histogram(
                np.asarray(summary.latency_hist), [0.99]
            )[0]
        )

    # -- scenario 1: heterogeneous backends at rho ~ 0.9 ---------------
    load = LoadModel(kind="open", qps=47_000.0)  # worker rho ~ 0.904
    tails = {}
    for law in ("fifo", "least_request", "wrr_hot"):
        sim, c, t = build(law)
        s, tl = sim.run_timeline(load, n, key, block_size=block,
                                 window_s=0.1)
        tails[law] = p99(s)
        if law == "wrr_hot":
            doc = lb_mod.to_doc(t, tl=tl)
            print(lb_mod.format_table(doc))
            print("per-window per-backend load split (worker):")
            for wi, row in enumerate(
                doc["services"]["worker"]["window_split"]
            ):
                print(
                    f"  w{wi:02d} "
                    + " ".join(f"{v:8.1f}" for v in row)
                )
    print(
        "p99: least_request %.3fms  fifo %.3fms  wrr-hot %.3fms"
        % tuple(tails[k] * 1e3
                for k in ("least_request", "fifo", "wrr_hot"))
    )
    assert tails["least_request"] < tails["fifo"], (
        "least-request must beat the shared-queue fifo tail at high "
        f"utilization: {tails}"
    )
    assert tails["fifo"] < tails["wrr_hot"], (
        f"the mis-weighted hot pool must have the worst tail: {tails}"
    )

    # -- scenario 2: panic routing through a 3/4-replica storm ---------
    storm = (ChaosEvent(service="worker", start_s=0.2, end_s=0.8,
                        replicas_down=3),)
    load2 = LoadModel(kind="open", qps=30_000.0)
    sim_p, c_p, t_p = build("panic", chaos=storm)
    s_p, tl_p = sim_p.run_timeline(load2, n, key, block_size=block,
                                   window_s=0.1)
    sim_0, _, _ = build("least_request", chaos=storm)
    s_0, tl_0 = sim_0.run_timeline(load2, n, key, block_size=block,
                                   window_s=0.1)
    w_idx = list(c_p.services.names).index("worker")

    def storm_goodput(tl):
        dt = float(tl.window_s)
        arr = np.asarray(tl.svc_arrivals, np.float64)[w_idx]
        err = np.asarray(tl.svc_errors, np.float64)[w_idx]
        w = np.arange(arr.shape[0]) * dt
        in_storm = (w >= 0.2) & (w < 0.7) & (arr > 0)
        return (arr - err)[in_storm]

    good_p = storm_goodput(tl_p)
    p99_p, p99_0 = p99(s_p), p99(s_0)
    print(
        "panic storm: goodput/window min %.0f hops, p99 %.2fms vs "
        "unprotected %.2fms" % (good_p.min(initial=np.inf), p99_p * 1e3,
                                p99_0 * 1e3)
    )
    assert len(good_p) > 0 and (good_p > 0).all(), (
        "panic routing must keep worker goodput nonzero through every "
        "storm window"
    )
    assert p99_p < p99_0, (
        f"panic p99 {p99_p} must beat the survivor-collapse p99 {p99_0}"
    )

    print("lb-smoke: least-request beats fifo beats the hot pool, "
          "panic routing holds goodput through the ejection storm")
    return 0


if __name__ == "__main__":
    sys.exit(main())
