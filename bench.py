"""Benchmark: simulated hop-events per second on one chip.

Four workloads, all through the microbatched (lax.scan) summary path —
HBM holds one request block, counters/histograms accumulate on device:

- ``tree121``   (headline): the ~120-service complete tree
  (BASELINE.json configs[1]) under open-loop load — every request
  executes all 121 hops.
- ``svc1000``: the vendored 1000-svc_2000-end.yaml fan-out
  (BASELINE.json configs[2]) — 1000 hops per request.
- ``realistic50``: a skewed Barabasi-Albert multitier topology with
  sequential calls — the unfavorable shape (long scripts, sparse hop
  execution).
- ``closed64``: the tree under 64-connection closed-loop load (Fortio's
  default mode) including the fixed-point rate solve.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "extra"}.
``value`` is the headline tree121 rate; vs_baseline measures it against
the north-star per-chip rate from BASELINE.json (1e9 hop-events/s on a
v5e-8 => 1.25e8 per chip).
"""
from __future__ import annotations

import json
import time

import jax

NORTH_STAR_PER_CHIP = 1e9 / 8.0


def _rate(sim, load, num_requests, block_size, *, warm=10, iters=5,
          trials=3):
    """Steady-state hop-events/s of run_summary on the current device.

    Best of ``trials`` timed windows: the tunneled chip's first window
    after a compile can run 3-4x below steady state, so a single window
    under-reports by whatever warm-up it caught.
    """
    key = jax.random.PRNGKey(0)

    def once(k):
        return sim.run_summary(load, num_requests, k, block_size=block_size)

    s = once(key)
    jax.block_until_ready(s.count)
    hops = float(s.hop_events)
    for i in range(warm):
        s = once(jax.random.fold_in(key, 1000 + i))
    jax.block_until_ready(s.count)
    best = 0.0
    for trial in range(trials):
        t0 = time.perf_counter()
        for i in range(iters):
            s = once(jax.random.fold_in(key, trial * iters + i))
        jax.block_until_ready(s.count)
        dt = time.perf_counter() - t0
        best = max(best, hops * iters / dt)
    return best


def main() -> None:
    import yaml

    from __graft_entry__ import _flagship
    from isotope_tpu.compiler import compile_graph
    from isotope_tpu.models.generators import realistic_topology
    from isotope_tpu.models.graph import ServiceGraph
    from isotope_tpu.sim.config import LoadModel
    from isotope_tpu.sim.engine import Simulator

    on_tpu = jax.devices()[0].platform != "cpu"
    # Measured per-topology sweet spots (r4 block sweep): per-dispatch
    # overhead through the tunneled chip dominates small blocks, so each
    # workload runs at ~2*16M elements / H per (block, H) tensor.
    blk = 262_144 if on_tpu else 4_096
    blocks = 4 if on_tpu else 2
    open_load = LoadModel(kind="open", qps=100_000.0)

    tree = Simulator(_flagship())
    tree121 = _rate(tree, open_load, blk * blocks, blk)

    extra = {}
    if on_tpu:
        with open("examples/topologies/1000-svc_2000-end.yaml") as f:
            doc = yaml.safe_load(f)
        svc1000 = Simulator(compile_graph(ServiceGraph.decode(doc)))
        extra["svc1000"] = _rate(
            svc1000, LoadModel(kind="open", qps=10_000.0), 65_536, 16_384
        )

        real = Simulator(
            compile_graph(
                ServiceGraph.decode(
                    realistic_topology(50, archetype="multitier", seed=0)
                )
            )
        )
        blk_real = real.default_block_size()
        extra["realistic50"] = _rate(real, open_load, blk_real * 4, blk_real)

        # BASELINE configs[3]: 10k services, realistic shape (deep
        # sequential scripts — the unfavorable geometry)
        svc10k = Simulator(
            compile_graph(
                ServiceGraph.decode(
                    realistic_topology(
                        10_000, archetype="multitier", seed=0
                    )
                )
            )
        )
        blk10k = svc10k.default_block_size()
        extra["svc10k"] = _rate(
            svc10k, LoadModel(kind="open", qps=1000.0),
            blk10k * 4, blk10k, warm=3, iters=3,
        )

        # the star archetype's skewed hub level (one ~2,000-step
        # service among thousands of leaves) runs via the sparse
        # call-slot encoding — dense grids made it block-starved
        star10k = Simulator(
            compile_graph(
                ServiceGraph.decode(
                    realistic_topology(10_000, archetype="star", seed=0)
                )
            )
        )
        blk_star = star10k.default_block_size()
        extra["star10k"] = _rate(
            star10k, LoadModel(kind="open", qps=1000.0),
            blk_star * 4, blk_star, warm=3, iters=3,
        )

        closed = LoadModel(kind="closed", qps=None, connections=64)
        extra["closed64"] = _rate(tree, closed, blk * blocks, blk)

    print(
        json.dumps(
            {
                "metric": "simulated hop-events/sec/chip",
                "value": tree121,
                "unit": "hop-events/s",
                "vs_baseline": tree121 / NORTH_STAR_PER_CHIP,
                "extra": {k: round(v) for k, v in extra.items()},
            }
        )
    )


if __name__ == "__main__":
    main()
