"""Benchmark: simulated hop-events per second on one chip.

Workloads, all through the microbatched (lax.scan) summary path — HBM
holds one request block, counters/histograms accumulate on device:

- ``tree121``   (headline): the ~120-service complete tree
  (BASELINE.json configs[1]) under open-loop load — every request
  executes all 121 hops.
- ``svc1000``: the vendored 1000-svc_2000-end.yaml fan-out
  (BASELINE.json configs[2]) — 1000 hops per request.
- ``realistic50``: a skewed Barabasi-Albert multitier topology with
  sequential calls — the unfavorable shape (long scripts, sparse hop
  execution).
- ``svc10k`` / ``star10k``: the 10k-service realistic shapes.
- ``svc10k_cfg3_10M``: BASELINE configs[3] AND the north-star census —
  the 10k multitier graph with per-call ``timeout: 30s, retries: 2``
  (models/generators.py with_call_policy) at an offered load whose
  Little-law census lambda x E[W] exceeds 10M concurrent in-flight
  requests (numReplicas 192 keeps every station stable at rho ~ 0.69).
  The census evidence is reported as ``svc10k_cfg3_inflight``.
- ``closed64``: the tree under 64-connection closed-loop load (Fortio's
  default mode) including the fixed-point rate solve.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "extra"}.
``value`` is the headline tree121 rate; vs_baseline measures it against
the north-star per-chip rate from BASELINE.json (1e9 hop-events/s on a
v5e-8 => 1.25e8 per chip).

Methodology (r5): each case reports the MEDIAN over >= 5 timed windows,
with the relative spread (max - min) / median of the windows recorded
as ``<case>_spread`` in extras.  r4's best-of-3 hid both the
window-to-window variance of the tunneled chip (measured +-40% on
svc1000) and a round-over-round doc drift; medians + spreads +
tools/bench_regress.py (>15% per-case gate vs the previous round's
driver capture) replace it.
"""
from __future__ import annotations

import json
import statistics
import time

import jax

NORTH_STAR_PER_CHIP = 1e9 / 8.0


def _rate(sim, load, num_requests, block_size, *, warm=3, iters=3,
          trials=5):
    """Steady-state hop-events/s of run_summary on the current device.

    Returns (median, rel_spread) over ``trials`` timed windows of
    ``iters`` runs each.  The tunneled chip's window-to-window variance
    is large (+-40% observed on svc1000), so the median over >= 5
    windows is the reported statistic and the spread is kept as
    evidence instead of silently picking the best window.
    """
    key = jax.random.PRNGKey(0)

    def once(k):
        return sim.run_summary(load, num_requests, k, block_size=block_size)

    s = once(key)
    jax.block_until_ready(s.count)
    hops = float(s.hop_events)
    for i in range(warm):
        s = once(jax.random.fold_in(key, 1000 + i))
    jax.block_until_ready(s.count)
    rates = []
    for trial in range(trials):
        t0 = time.perf_counter()
        for i in range(iters):
            s = once(jax.random.fold_in(key, trial * iters + i))
        jax.block_until_ready(s.count)
        dt = time.perf_counter() - t0
        rates.append(hops * iters / dt)
    med = statistics.median(rates)
    spread = (max(rates) - min(rates)) / med if med > 0 else 0.0
    return med, spread


def main() -> None:
    import yaml

    from __graft_entry__ import _flagship
    from isotope_tpu.compiler import compile_graph
    from isotope_tpu.models.generators import (
        realistic_topology,
        with_call_policy,
    )
    from isotope_tpu.models.graph import ServiceGraph
    from isotope_tpu.sim.config import LoadModel
    from isotope_tpu.sim.engine import Simulator

    on_tpu = jax.devices()[0].platform != "cpu"
    # Measured per-topology sweet spots (r4 block sweep): per-dispatch
    # overhead through the tunneled chip dominates small blocks, so each
    # workload runs at ~2*16M elements / H per (block, H) tensor.
    blk = 262_144 if on_tpu else 4_096
    blocks = 4 if on_tpu else 2
    open_load = LoadModel(kind="open", qps=100_000.0)

    extra = {}
    spreads = {}

    def case(name, sim, load, n, bs, **kw):
        med, spread = _rate(sim, load, n, bs, **kw)
        extra[name] = med
        spreads[name] = spread
        return med

    tree = Simulator(_flagship())
    tree121 = case("tree121", tree, open_load, blk * blocks, blk,
                   trials=5)

    if on_tpu:
        with open("examples/topologies/1000-svc_2000-end.yaml") as f:
            doc = yaml.safe_load(f)
        svc1000 = Simulator(compile_graph(ServiceGraph.decode(doc)))
        # r4 ran 65_536 requests; the r5 block sweep showed per-window
        # rates 2x noisier at that size — 262_144 requests amortize the
        # tunnel's dispatch overhead (r2-code-vs-r5-code probes under
        # one harness agree within noise, so the r2->r4 "slide" was
        # this measurement, not the engine)
        case("svc1000", svc1000, LoadModel(kind="open", qps=10_000.0),
             262_144, 32_768)

        real = Simulator(
            compile_graph(
                ServiceGraph.decode(
                    realistic_topology(50, archetype="multitier", seed=0)
                )
            )
        )
        blk_real = real.default_block_size()
        case("realistic50", real, open_load, blk_real * 4, blk_real)

        # BASELINE configs[3]: 10k services, realistic shape (deep
        # sequential scripts — the unfavorable geometry)
        svc10k = Simulator(
            compile_graph(
                ServiceGraph.decode(
                    realistic_topology(
                        10_000, archetype="multitier", seed=0
                    )
                )
            )
        )
        blk10k = svc10k.default_block_size()
        case("svc10k", svc10k, LoadModel(kind="open", qps=1000.0),
             blk10k * 4, blk10k)

        # the star archetype's skewed hub level (one ~2,000-step
        # service among thousands of leaves) runs via the sparse
        # call-slot encoding — dense grids made it block-starved
        star10k = Simulator(
            compile_graph(
                ServiceGraph.decode(
                    realistic_topology(10_000, archetype="star", seed=0)
                )
            )
        )
        blk_star = star10k.default_block_size()
        case("star10k", star10k, LoadModel(kind="open", qps=1000.0),
             blk_star * 4, blk_star)

        # BASELINE configs[4]: 100k services + fault injection + heavy
        # tails.  24 unrolled levels, block 335 (the hop axis dominates
        # the element budget); a mid-run total outage exercises the
        # phase tables and Pareto(2.5) the heavy-tail sampler.  r4's
        # "~80M/chip" README figure was the old best-effort probe; with
        # warm-up + medians this captures ~140M/chip (>= the 125M
        # per-chip pro-rata bar).
        from isotope_tpu.sim.config import ChaosEvent, SimParams

        big = Simulator(
            compile_graph(
                ServiceGraph.decode(
                    realistic_topology(
                        100_000, archetype="multitier", seed=0
                    )
                )
            ),
            SimParams(service_time="pareto", service_time_param=2.5),
            (ChaosEvent(service="mock-7", start_s=5.0, end_s=15.0,
                        replicas_down=None),),
        )
        blk_big = big.default_block_size()
        case("svc100k_chaos", big, LoadModel(kind="open", qps=100.0),
             blk_big * 2, blk_big)

        # north-star census (BASELINE.json): configs[3] WITH the
        # retries/timeouts policy, at an offered load holding >= 10M
        # requests in flight (Little: lambda x E[W]).  1.78M qps over a
        # ~5.8s critical path (probed: W=5.77s at 1.73M => 9.98M; the
        # bump clears 1e7 with margin at rho ~ 0.71); numReplicas 192 keeps
        # rho ~ 0.69 everywhere so the census is a stable steady state.
        # Timeouts go on EVERY call; retries go on the entry's direct
        # calls — each retry attempt unrolls its whole subtree, so
        # tree-wide retries would explode the static hop budget
        # (3^depth copies); entry-level retries triple the graph to
        # ~30k hops while still exercising the retry-feedback path.
        doc3 = with_call_policy(
            realistic_topology(
                10_000, archetype="multitier", seed=0,
                num_replicas=192,
            ),
            timeout="30s",
        )
        for cmd in doc3["services"][0].get("script", []):
            if isinstance(cmd, dict) and "call" in cmd:
                cmd["call"]["retries"] = 2
        cfg3 = Simulator(compile_graph(ServiceGraph.decode(doc3)))
        blk_cfg3 = cfg3.default_block_size()
        load_cfg3 = LoadModel(kind="open", qps=1_780_000.0)
        case("svc10k_cfg3_10M", cfg3, load_cfg3,
             blk_cfg3 * 4, blk_cfg3)
        s = cfg3.run_summary(
            load_cfg3, blk_cfg3 * 4, jax.random.PRNGKey(42),
            block_size=blk_cfg3,
        )
        jax.block_until_ready(s.count)
        extra["svc10k_cfg3_inflight"] = load_cfg3.qps * s.mean_latency_s

        closed = LoadModel(kind="closed", qps=None, connections=64)
        case("closed64", tree, closed, blk * blocks, blk)

    extra_out = {k: round(v) for k, v in extra.items()}
    for k, v in spreads.items():
        extra_out[f"{k}_spread"] = round(v, 3)
    print(
        json.dumps(
            {
                "metric": "simulated hop-events/sec/chip",
                "value": tree121,
                "unit": "hop-events/s",
                "vs_baseline": tree121 / NORTH_STAR_PER_CHIP,
                "extra": extra_out,
            }
        )
    )


if __name__ == "__main__":
    main()
