"""Benchmark: simulated hop-events per second on one chip.

Workload: the ~120-service complete tree (BASELINE.json configs[1]) under
open-loop load — every request executes all 121 hops, so one batch of N
requests is N x 121 hop-events.  The timed step is the full jitted
simulation (RNG, queue sampling, both tree sweeps, arrival stream) plus
the fine latency-histogram reduction; only scalars/histograms leave the
device.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline is measured against the north-star per-chip rate of the
BASELINE.json target (1e9 hop-events/s on a v5e-8 => 1.25e8 per chip).
"""
from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp

NORTH_STAR_PER_CHIP = 1e9 / 8.0


def main() -> None:
    from __graft_entry__ import _flagship
    from isotope_tpu.metrics.histogram import latency_histogram
    from isotope_tpu.sim.config import OPEN_LOOP
    from isotope_tpu.sim.engine import Simulator

    compiled = _flagship()  # 121 services / 121 hops per request
    sim = Simulator(compiled)
    platform = jax.devices()[0].platform
    n = 65_536 if platform != "cpu" else 4_096
    qps = jnp.float32(100_000.0)

    @jax.jit
    def step(key):
        res = sim._simulate(n, OPEN_LOOP, 0, key, qps, jnp.float32(0.0), qps)
        return res.hop_events, latency_histogram(res.client_latency)

    key = jax.random.PRNGKey(0)
    hops, hist = step(key)  # compile + warmup
    jax.block_until_ready((hops, hist))
    hops_per_batch = float(hops)

    # The remote-TPU tunnel lazily uploads program state: the first ~10
    # executions after compile run an order of magnitude slower than steady
    # state.  Run a full untimed round first so the timed round measures
    # the device, not the tunnel warm-up.
    warm = 10 if platform != "cpu" else 1
    out = None
    for i in range(warm):
        out = step(jax.random.fold_in(key, 1000 + i))
    jax.block_until_ready(out)

    iters = 30 if platform != "cpu" else 3
    t0 = time.perf_counter()
    out = None
    for i in range(iters):
        out = step(jax.random.fold_in(key, i))
    jax.block_until_ready(out)
    dt = time.perf_counter() - t0

    rate = hops_per_batch * iters / dt
    print(
        json.dumps(
            {
                "metric": "simulated hop-events/sec/chip",
                "value": rate,
                "unit": "hop-events/s",
                "vs_baseline": rate / NORTH_STAR_PER_CHIP,
            }
        )
    )


if __name__ == "__main__":
    main()
