"""Benchmark: simulated hop-events per second on one chip.

Workloads, all through the microbatched (lax.scan) summary path — HBM
holds one request block, counters/histograms accumulate on device:

- ``tree121``   (headline): the ~120-service complete tree
  (BASELINE.json configs[1]) under open-loop load — every request
  executes all 121 hops.
- ``closed64``: the tree under 64-connection closed-loop load (Fortio's
  default mode) including the fixed-point rate solve.
- ``svc1000``: the vendored 1000-svc_2000-end.yaml fan-out
  (BASELINE.json configs[2]) — 1000 hops per request.
- ``realistic50``: a skewed Barabasi-Albert multitier topology with
  sequential calls — the unfavorable shape (long scripts, sparse hop
  execution).
- ``svc10k`` / ``star10k``: the 10k-service realistic shapes.
- ``svc10k_ingested``: trace-driven replay at scale (ingest/) — the
  svc10k shape simulated once with the recorder armed, its Prometheus
  expositions fitted back into a topology, and the FITTED graph's
  replay measured.  The rate shares the svc10k family (a fit that
  distorts the topology shows up as a rate break); the host-side fit
  lands as ``<case>_ingest_*`` evidence keys, which
  tools/bench_regress.py excludes from the rate gate.
- ``svc100k_chaos``: BASELINE configs[4] — 100k services + a mid-run
  total outage + Pareto(2.5) heavy tails.
- ``svc10k_cfg3_10M``: BASELINE configs[3] AND the north-star census —
  the 10k multitier graph with per-call ``timeout: 30s`` everywhere
  and ``retries: 2`` on the entry's two smallest call subtrees (each
  retry attempt unrolls its subtree, and wider retry fans push the
  XLA compile past the tunnel's request deadline), at an offered load whose
  Little-law census lambda x E[W] exceeds 10M concurrent in-flight
  requests (numReplicas 192 keeps every station stable at rho ~ 0.71).
  The census evidence is reported as ``svc10k_cfg3_inflight``.

The capture also embeds the ``--mesh auto`` layout verdict for this
host (``_mesh_layout`` / ``_mesh_layout_score``, parallel/layout.py)
so ``tools/bench_regress.py`` can gate the search
(``BENCH_REGRESS_LAYOUT_GATE=1``) — bench cases themselves measure the
single-chip path, so the mesh choice is evidence, not a knob.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "extra"}.
``value`` is the headline tree121 rate; vs_baseline measures it against
the north-star per-chip rate from BASELINE.json (1e9 hop-events/s on a
v5e-8 => 1.25e8 per chip).

Methodology (r5):

- Each case reports the MEDIAN over >= 5 timed windows, with the
  relative spread (max - min)/median recorded as ``<case>_spread`` in
  extras.  r4's best-of-3 hid both the tunneled chip's +-40%
  window-to-window variance and a round-over-round doc drift; medians
  + spreads + tools/bench_regress.py (>15% per-case gate vs the
  previous round's driver capture) replace it.
- Each case runs in its OWN SUBPROCESS.  One process accumulating
  every case's executables and device constants exhausted HBM by the
  late cases (jax.clear_caches() does not reliably release axon
  device buffers), wedging the tunnel; per-case processes guarantee
  release, and one failing case degrades to a null instead of killing
  the whole capture.
"""
from __future__ import annotations

import dataclasses
import json
import math
import os
import statistics
import subprocess
import sys
import time

NORTH_STAR_PER_CHIP = 1e9 / 8.0

CASE_ORDER = [
    "tree121",
    "closed64",
    "svc1000",
    "ensembleN",
    "search64",
    "svc1000_chaosfleet",
    "svc1000_composed",
    "realistic50",
    "rollout50",
    "svc10k",
    "svc10k_protected",
    "svc10k_ingested",
    "star10k",
    "svc100k_chaos",
    "svc10k_cfg3_10M",
]

# per-case subprocess budget, seconds (compile + warm + timed
# windows).  cfg3's 30k-hop compile alone is ~200s on a healthy
# tunneled chip and stretches well past that when the tunnel is busy,
# so it gets a larger budget.
CASE_TIMEOUT_S = 1200
# svc10k_ingested compiles TWO 10k-service programs (the recorder-armed
# source and the fitted replay) on top of the host-side fit
CASE_TIMEOUT_OVERRIDES = {"svc10k_cfg3_10M": 3000,
                          "svc10k_ingested": 2400}


def _rate(sim, load, num_requests, block_size, *, warm=3, iters=3,
          trials=5, runner=None, case=None):
    """Steady-state hop-events/s of run_summary on the current device.

    Returns (median, rel_spread, best, first_s, warmup_windows) over
    the last ``trials`` timed windows of ``iters`` runs each.  The
    tunneled chip's window-to-window variance is large (+-40% observed
    on svc1000), so the median over >= 5 windows is the reported
    statistic and the spread is kept as evidence instead of silently
    picking the best window.

    Steady-state discipline (r6): beyond the fixed ``warm`` untimed
    runs, EARLY TIMED WINDOWS ARE DISCARDED until the rolling spread of
    the last ``trials`` windows drops under ``$BENCH_STEADY_SPREAD``
    (default 0.15 — the bench_regress gate's threshold) or
    ``$BENCH_WARMUP_CAP`` (default 5) extra windows have been burned.
    The discard count is returned as ``warmup_windows`` and lands in
    the capture as ``<case>_warmup_windows`` — a case that never
    settles is visible evidence, not silent noise (r5 spreads of
    22-27% on tree121/closed64/realistic50 made tentpole deltas
    unclaimable against the 15% gate).

    ``first_s`` is the first-call wall time — trace + XLA compile
    (+ the closed-loop rate solve where applicable) — the compile-wall
    evidence the level-scan executor and the persistent compilation
    cache exist to shrink.  It is sourced from the engine telemetry
    phase timers (telemetry/core.py), which also split it into
    trace/lower/backend in the case's telemetry block.

    The first call runs under the resilience supervisor's OOM ladder
    (resilience/supervisor.py): a case that exhausts HBM serves its
    windows from a fallback rung — recorded as ``degraded_to`` in the
    case's telemetry block — instead of hard-crashing the capture, and
    ``tools/bench_regress.py`` fails the round if a previously-clean
    case degrades.  The surviving rung serves every subsequent window,
    so the measured rate and its label agree.
    """
    import contextlib

    import jax

    from isotope_tpu import telemetry
    from isotope_tpu.resilience import ResiliencePolicy, run_ladder

    # static vet pass, no jaxpr trace (the audit trace would perturb
    # the compile-wall measurement below): rule counters land in the
    # case's telemetry block (`vet_errors`/`vet_warnings`) so
    # tools/bench_regress.py can gate on NEW vet errors vs the previous
    # capture.  Best-effort — a vet crash must never kill a capture.
    try:
        from isotope_tpu.analysis import vet_simulator

        vet_simulator(sim, load, block_requests=block_size, trace=False)
    except Exception:  # pragma: no cover - capture survival
        pass

    key = jax.random.PRNGKey(0)
    serving = {"block": block_size, "eager": False}

    def once(k):
        ctx = (
            jax.disable_jit() if serving["eager"]
            else contextlib.nullcontext()
        )
        with ctx:
            if runner is not None:
                # protected co-sim cases (e.g. run_rollouts) time the
                # control loop's program, not the plain summary path
                return runner(sim, load, num_requests, k,
                              serving["block"])
            return sim.run_summary(
                load, num_requests, k, block_size=serving["block"]
            )

    def rung(block, eager):
        def thunk():
            serving.update(block=block, eager=eager)
            s = once(key)
            jax.block_until_ready(s.count)
            return s
        return thunk

    half = max(256, block_size // 2)
    before = telemetry.phase_seconds("bench.first_call")
    with telemetry.phase("bench.first_call"):
        s, _degraded = run_ladder(
            [
                ("scan", rung(block_size, False)),
                ("half-block", rung(half, False)),
                ("cpu-eager", rung(half, True)),
            ],
            ResiliencePolicy.from_env(),
            site_prefix="bench",
        )
    first_s = telemetry.phase_seconds("bench.first_call") - before
    hops = float(s.hop_events)
    for i in range(warm):
        s = once(jax.random.fold_in(key, 1000 + i))
    jax.block_until_ready(s.count)

    def window_spread(window):
        m = statistics.median(window)
        return (max(window) - min(window)) / m if m > 0 else 0.0

    # per-case steady-state threshold (r7): $BENCH_STEADY_SPREAD_<CASE>
    # overrides the global default — the tunneled chip's fast cases
    # (tree121/closed64/realistic50 at 22-27% r6 spread) need a looser
    # settle bar than the long-window ones, and a single global knob
    # either burns the fast cases' budget or lets the slow ones drift
    default_thr = os.environ.get("BENCH_STEADY_SPREAD", "0.15")
    steady_thr = float(
        os.environ.get(f"BENCH_STEADY_SPREAD_{case.upper()}",
                       default_thr)
        if case else default_thr
    )
    warmup_cap = int(os.environ.get("BENCH_WARMUP_CAP", "5"))
    # window floor (r7): sub-millisecond timed windows measure the
    # host timer + dispatch jitter, not the engine — scale ``iters``
    # until one window spans at least $BENCH_WINDOW_FLOOR seconds
    # (probed with one untimed-for-stats window; rates normalize by
    # iters so the statistic is unchanged)
    floor_s = float(os.environ.get("BENCH_WINDOW_FLOOR", "0.2"))
    if floor_s > 0:
        t0 = time.perf_counter()
        s = once(jax.random.fold_in(key, 777))
        jax.block_until_ready(s.count)
        probe_dt = time.perf_counter() - t0
        if probe_dt * iters < floor_s:
            iters = min(
                512, max(iters, int(floor_s / max(probe_dt, 1e-6)) + 1)
            )
    rates = []
    warmup_windows = 0
    trial = 0
    while True:
        t0 = time.perf_counter()
        for i in range(iters):
            s = once(jax.random.fold_in(key, trial * iters + i))
        jax.block_until_ready(s.count)
        dt = time.perf_counter() - t0
        rates.append(hops * iters / dt)
        trial += 1
        if len(rates) < trials:
            continue
        if window_spread(rates[-trials:]) <= steady_thr:
            break
        if warmup_windows >= warmup_cap:
            break
        # the oldest window is pre-steady-state: discard and extend
        warmup_windows += 1
    window = rates[-trials:]
    med = statistics.median(window)
    spread = window_spread(window)
    return med, spread, max(window), first_s, warmup_windows


def _case_blame(sim, load, n: int = 2_048, top: int = 8) -> dict:
    """Per-service blame shares from a small attributed run.

    Rebuilds the case's Simulator with ``attribution=True`` (chaos /
    churn schedules are run-time state and stay off — the probe gates
    structural blame drift, not chaos behavior).
    """
    import dataclasses

    import jax

    from isotope_tpu.metrics import attribution as attr_mod
    from isotope_tpu.sim.engine import Simulator

    asim = Simulator(
        sim.compiled,
        dataclasses.replace(sim.params, attribution=True),
    )
    block = min(1_024, max(256, asim.default_block_size()))
    _, attr = asim.run_attributed(
        load, n, jax.random.PRNGKey(7), block_size=block
    )
    rows = attr_mod.service_blame(sim.compiled, attr)[:top]
    count = max(float(attr.count), 1.0)
    return {
        "services": {
            r["service"]: round(r["share"], 4) for r in rows
        },
        "residual_abs_us_per_req": round(
            float(attr.residual_abs) / count * 1e6, 4
        ),
    }


def _case_timeline_overhead(sim, load, n, block, iters=2) -> float:
    """Steady-state overhead of the flight recorder: timed windows of
    ``run_timeline`` vs ``run_summary`` on the same sim/load shape.

    BOTH sides run on freshly rebuilt Simulators from the case's
    compiled graph and params — chaos/churn/mtls constructor state is
    dropped symmetrically, so the delta isolates recorder cost (an
    asymmetric rebuild would diff a chaos-phased baseline against a
    chaos-free timeline run).  Reports ``(t_on - t_off) / t_off``;
    lands in the capture as ``<case>_timeline_overhead`` so
    ``tools/bench_regress.py`` can gate it (opt-in
    ``BENCH_REGRESS_TIMELINE_THRESHOLD``).
    """
    import dataclasses

    import jax

    from isotope_tpu.sim.engine import Simulator

    osim = Simulator(sim.compiled, sim.params)
    tsim = Simulator(
        sim.compiled, dataclasses.replace(sim.params, timeline=True)
    )
    key = jax.random.PRNGKey(13)

    def timed(fn, windows=3):
        # two warm calls (compile + any lazy host-side table builds),
        # then the best of a few timed windows — the single-window
        # form read one-time lazy costs as "overhead" (measured: the
        # first post-warm run_summary window ~20x its steady state)
        for i in range(2):
            s = fn(jax.random.fold_in(key, 900 + i))
        jax.block_until_ready(s.count)
        best = float("inf")
        for w in range(windows):
            t0 = time.perf_counter()
            for i in range(iters):
                s = fn(jax.random.fold_in(key, w * iters + i))
            jax.block_until_ready(s.count)
            best = min(best, time.perf_counter() - t0)
        return best

    t_off = timed(
        lambda k: osim.run_summary(load, n, k, block_size=block)
    )
    t_on = timed(
        lambda k: tsim.run_timeline(load, n, k, block_size=block)[0]
    )
    return (t_on - t_off) / max(t_off, 1e-9)


def _case_fleet_blame_overhead(sim, spec, load, n, block,
                               iters=2) -> float:
    """Steady-state overhead of the FLEET attribution pass (PR 17):
    timed windows of ``run_ensemble(attribution=True)`` vs the plain
    fleet on the same sim/load/population shape.

    Symmetric double-warm probe (the ``_case_timeline_overhead``
    discipline): BOTH sides run on freshly rebuilt Simulators — each
    side pays its own compile in the warm calls, each side times the
    same member count — so the delta isolates the stacked blame
    carry + readback cost, not a cold-vs-warm artifact.  Lands in the
    capture as ``ensembleN_blame_overhead``; ``tools/bench_regress.py``
    gates it opt-in (``BENCH_REGRESS_FLEETBLAME_THRESHOLD``) and
    excludes it from the plain rate comparison.
    """
    import dataclasses

    import jax

    from isotope_tpu.sim.engine import Simulator

    osim = Simulator(sim.compiled, sim.params)
    asim = Simulator(
        sim.compiled,
        dataclasses.replace(sim.params, attribution=True),
    )
    key = jax.random.PRNGKey(17)

    def timed(fn, windows=3):
        for i in range(2):
            s = fn(jax.random.fold_in(key, 900 + i))
        jax.block_until_ready(s.summaries.count)
        best = float("inf")
        for w in range(windows):
            t0 = time.perf_counter()
            for i in range(iters):
                s = fn(jax.random.fold_in(key, w * iters + i))
            jax.block_until_ready(s.summaries.count)
            best = min(best, time.perf_counter() - t0)
        return best

    t_off = timed(
        lambda k: osim.run_ensemble(load, n, k, spec,
                                    block_size=block)
    )
    t_on = timed(
        lambda k: asim.run_ensemble(load, n, k, spec,
                                    block_size=block,
                                    attribution=True)
    )
    return (t_on - t_off) / max(t_off, 1e-9)


def run_case(name: str) -> dict:
    """Build and measure ONE case; returns {"median", "spread", ...}.

    Executed inside the per-case subprocess.
    """
    import jax
    import yaml

    from __graft_entry__ import _flagship
    from isotope_tpu import telemetry
    from isotope_tpu.compiler import compile_graph
    from isotope_tpu.compiler.cache import enable_persistent_cache

    # fresh per-case registry (each case runs in its own subprocess
    # anyway — this guards direct run_case() callers like tests)
    telemetry.reset()
    telemetry.install_jax_hooks()

    # persistent XLA cache across the per-case subprocesses (and across
    # whole bench runs): repeated topology families skip the backend
    # compile entirely.  Default on, repo-local; $ISOTOPE_COMPILE_CACHE
    # overrides the directory (or disables with "off").
    cache_dir = enable_persistent_cache(
        os.environ.get("ISOTOPE_COMPILE_CACHE", ".xla-cache")
    )
    from isotope_tpu.models.generators import (
        realistic_topology,
        with_call_policy,
    )
    from isotope_tpu.models.graph import ServiceGraph
    from isotope_tpu.sim.config import ChaosEvent, LoadModel, SimParams
    from isotope_tpu.sim.engine import Simulator

    on_tpu = jax.devices()[0].platform != "cpu"
    blk = 262_144 if on_tpu else 4_096
    blocks = 4 if on_tpu else 2
    open_load = LoadModel(kind="open", qps=100_000.0)
    out: dict = {}

    # remember what each case measured so the post-measurement blame
    # probe (metrics/attribution.py) runs the same sim + load shape
    case_ctx: dict = {}

    def measure(sim, load, *args, **kw):
        case_ctx["sim"], case_ctx["load"] = sim, load
        med, spread, best, first_s, warmup = _rate(
            sim, load, *args, case=name, **kw
        )
        case_ctx["warmup_windows"] = warmup
        return med, spread, best, first_s

    if name == "tree121":
        sim = Simulator(_flagship())
        med, spread, best, first_s = measure(sim, open_load, blk * blocks, blk)
        # auto-layout evidence: the factorization `--mesh auto` picks on
        # THIS host plus its cost-model score, so bench_regress's
        # opt-in BENCH_REGRESS_LAYOUT_GATE can fail a round whose
        # search regressed to a worse-scoring mesh (a model-constant or
        # search bug shows up here before any pod run does)
        try:
            from isotope_tpu.parallel import layout

            chosen = layout.choose_layout(
                jax.device_count(), sim.compiled.num_services
            )
            out["_mesh_layout"] = chosen.spec.describe()
            out["_mesh_layout_score"] = float(chosen.score_s)
        except Exception:  # pragma: no cover - capture survival
            pass
    elif name == "closed64":
        sim = Simulator(_flagship())
        med, spread, best, first_s = measure(
            sim, LoadModel(kind="closed", qps=None, connections=64),
            blk * blocks, blk,
        )
    elif name == "svc1000":
        with open("examples/topologies/1000-svc_2000-end.yaml") as f:
            doc = yaml.safe_load(f)
        sim = Simulator(compile_graph(ServiceGraph.decode(doc)))
        # 262_144 requests: the r5 block sweep showed 65_536-request
        # windows 2x noisier (r2-code-vs-r5-code probes under one
        # harness agree within noise, so the r2->r4 "slide" was this
        # measurement, not the engine)
        med, spread, best, first_s = measure(
            sim, LoadModel(kind="open", qps=10_000.0), 262_144, 32_768
        )
    elif name == "ensembleN":
        # scenario ensembles (sim/ensemble.py): svc1000 x N seed
        # members behind ONE jitted program (run_ensemble).  The case
        # rate is the fleet's AGGREGATE hop-events/s; the embedded
        # evidence carries the member count, the fleet's engine-trace
        # delta (exactly ONE compile serves every member), the
        # N-sequential-solo-dispatch rate of the SAME member keys
        # (the Python case loop the fleet replaces, host sync per
        # member like runner/run.py), and the aggregate speedup.
        # tools/bench_regress.py gates the per-member throughput
        # (opt-in BENCH_REGRESS_ENSEMBLE_THRESHOLD) and excludes the
        # evidence keys from the plain rate comparison.
        from isotope_tpu.sim.ensemble import EnsembleSpec

        with open("examples/topologies/1000-svc_2000-end.yaml") as f:
            doc = yaml.safe_load(f)
        sim = Simulator(compile_graph(ServiceGraph.decode(doc)))
        # screening-fleet shape: MANY members, SHORT horizons — the
        # successive-halving / what-if-triage regime where the Python
        # case loop's per-dispatch overhead dominates and the fleet's
        # one-dispatch amortization pays even on a 1-core CPU (the
        # >= 2x acceptance bar).  Longer-horizon fleets converge to
        # compute parity per member on CPU; on TPU the vmap batch dim
        # feeds the MXU, so the TPU case runs wider blocks.
        members = int(os.environ.get(
            "BENCH_ENSEMBLE_MEMBERS", "32" if on_tpu else "128"
        ))
        spec = EnsembleSpec.of(members)
        load_e = LoadModel(kind="open", qps=10_000.0)
        n_e = int(os.environ.get(
            "BENCH_ENSEMBLE_REQUESTS", "8192" if on_tpu else "16"
        ))
        b_e = min(n_e, 8_192 if on_tpu else 1_024)
        traces0 = telemetry.counter_get("engine_traces")

        def ens_runner(s_, l_, n_, k_, b_):
            return s_.run_ensemble(
                l_, n_, k_, spec, block_size=b_
            ).pooled()

        med, spread, best, first_s = measure(
            sim, load_e, n_e, b_e, warm=2, iters=2,
            runner=ens_runner,
        )
        out[f"{name}_ensemble_members"] = members
        out[f"{name}_ensemble_traces"] = int(
            telemetry.counter_get("engine_traces") - traces0
        )

        # the sequential baseline: N solo dispatches of the SAME
        # member keys, one host sync each (the case-loop pattern)
        key_e = jax.random.PRNGKey(0)

        def solo_loop(k):
            tot = 0.0
            for s_i in spec.seeds:
                s = sim.run_summary(
                    load_e, n_e, jax.random.fold_in(k, s_i),
                    block_size=b_e,
                )
                tot += float(s.hop_events)
            return tot

        hops_total = solo_loop(key_e)  # warm: compiles the solo path
        solo_best = 0.0
        for w in range(3):
            t0 = time.perf_counter()
            hops_total = solo_loop(jax.random.fold_in(key_e, 900 + w))
            dt = time.perf_counter() - t0
            solo_best = max(solo_best, hops_total / dt)
        out[f"{name}_ensemble_solo_rate"] = solo_best
        out[f"{name}_ensemble_speedup"] = round(
            med / max(solo_best, 1e-9), 3
        )

        # fleet blame-pass overhead probe (PR 17): attribution ON vs
        # OFF over the same fleet shape, bounded to a small member
        # count so the probe's extra compiles stay cheap relative to
        # the case.  BENCH_FLEETBLAME=0 disables.
        if os.environ.get("BENCH_FLEETBLAME", "1") not in ("0", "off"):
            try:
                probe_spec = EnsembleSpec.of(min(members, 32))
                out[f"{name}_blame_overhead"] = round(
                    _case_fleet_blame_overhead(
                        sim, probe_spec, load_e, n_e, b_e
                    ),
                    4,
                )
            except Exception:  # pragma: no cover - capture survival
                pass
    elif name == "search64":
        # on-device config search (sim/search.py): a 64-candidate
        # successive-halving bracket over svc1000 — eta=4, 3 rungs
        # (64 -> 16 -> 4 -> winner), growth=2 so the screening
        # horizons double per rung (1/2/4 blocks).  The
        # case rate is the bracket's POOLED hop-events/s (every
        # simulated row across all rungs over its wall-clock); the
        # evidence carries the candidate/rung counts, the engine-
        # trace delta (one compile per rung shape — <= 3 for the
        # whole bracket), and the rate of the SEQUENTIAL sweep that
        # replays the same per-rung per-candidate budgets as solo
        # run_summary dispatches (64 + 16 + 4 = 84 host round-trips,
        # the Python screening loop the bracket replaces).  The
        # `<case>_search_*` keys are EXCLUDED from bench_regress's
        # rate comparison; the speedup has its own opt-in gate
        # (BENCH_REGRESS_SEARCH_THRESHOLD).
        from isotope_tpu.sim.ensemble import EnsembleSpec
        from isotope_tpu.sim.search import SearchSpec, plan_bracket

        with open("examples/topologies/1000-svc_2000-end.yaml") as f:
            doc = yaml.safe_load(f)
        sim = Simulator(compile_graph(ServiceGraph.decode(doc)))
        cands = int(os.environ.get("BENCH_SEARCH_CANDIDATES", "64"))
        spec = SearchSpec(
            candidates=EnsembleSpec.from_jitter(
                cands, qps_jitter=0.2, cpu_jitter=0.1,
                error_jitter=0.3,
            ),
            eta=4, rungs=3, growth=2,
        )
        load_s = LoadModel(kind="open", qps=10_000.0)
        # 4 blocks total => cumulative rung horizons 1/2/4 at
        # growth=2; short blocks on CPU — the screening regime where
        # dispatch overhead dominates — wider on TPU where the
        # member axis feeds the MXU
        b_s = 4_096 if on_tpu else 4
        n_s = b_s * 4
        traces0 = telemetry.counter_get("engine_traces")
        last_srch = {}

        def search_runner(s_, l_, n_, k_, b_):
            srch = s_.run_search(l_, n_, k_, spec, block_size=b_)
            last_srch["srch"] = srch
            return srch.pooled()

        med, spread, best, first_s = measure(
            sim, load_s, n_s, b_s, warm=2, iters=2,
            runner=search_runner,
        )
        out[f"{name}_search_candidates"] = cands
        out[f"{name}_search_rungs"] = spec.rungs
        out[f"{name}_search_traces"] = int(
            telemetry.counter_get("engine_traces") - traces0
        )

        # the sequential sweep: the SAME successive-halving screen
        # run the only way it could be before the bracket — a Python
        # loop of solo run_summary dispatches, each candidate at its
        # OWN jittered qps, each rung's cumulative horizon
        # resimulated from scratch (solo runs have no carry
        # machinery; extending a candidate means rerunning it), the
        # rung ranked HOST-side from each candidate's summary (the
        # severity reads are the per-candidate syncs a screening
        # loop pays) and the top 1/eta advanced.  That is the loop
        # the bracket replaces, and what the screen costs without it.
        plan = plan_bracket(spec, n_s, b_s)
        key_s = jax.random.PRNGKey(0)
        scales = spec.candidates.qps_scale

        def solo_sweep(k):
            live = list(range(cands))
            tot = 0.0
            for rp in plan:
                sev = []
                for m in live:
                    sc = 1.0 if scales is None else float(scales[m])
                    load_m = dataclasses.replace(
                        load_s, qps=load_s.qps * sc
                    )
                    s = sim.run_summary(
                        load_m, rp.num_blocks * b_s,
                        jax.random.fold_in(k, rp.rung * 1_000 + m),
                        block_size=b_s,
                    )
                    tot += float(s.hop_events)
                    sev.append((
                        float(s.error_count)
                        / max(float(s.count), 1.0),
                        m,
                    ))
                sev.sort()
                keep = (
                    plan[rp.rung + 1].width
                    if rp.rung + 1 < len(plan) else 1
                )
                live = [m for _, m in sev[:keep]]
            return tot

        hops_total = solo_sweep(key_s)  # warm: compiles the solo shapes
        solo_dt = math.inf
        for w in range(5):
            t0 = time.perf_counter()
            hops_total = solo_sweep(jax.random.fold_in(key_s, 900 + w))
            solo_dt = min(solo_dt, time.perf_counter() - t0)
        out[f"{name}_search_sequential_rate"] = hops_total / solo_dt

        # speedup: wall-clock to complete the same screen (find the
        # winner over the same per-rung candidate budgets), best-of-N
        # on both sides so a noisy box compares floors with floors
        br_dt = math.inf
        for w in range(8):
            t0 = time.perf_counter()
            sim.run_search(
                load_s, n_s, jax.random.fold_in(key_s, 700 + w),
                spec, block_size=b_s,
            )
            br_dt = min(br_dt, time.perf_counter() - t0)
        out[f"{name}_search_speedup"] = round(
            solo_dt / max(br_dt, 1e-9), 3
        )
    elif name == "svc1000_chaosfleet":
        # chaos fleets (PR 15): svc1000 under a retry-storm policy
        # block, dispatched as a PROTECTED Monte Carlo fleet with
        # per-member kill timing/magnitude (run_policies_ensemble +
        # ChaosJitterSpec) — every member survives a DIFFERENT bad
        # day behind one jitted program.  Evidence: member count,
        # engine-trace delta (one compile serves the fleet), the
        # worst member's severity, and a short importance-splitting
        # estimate of a forced-rare outage (severity threshold well
        # past the typical member).  The `<case>_chaosfleet_*` keys
        # are EXCLUDED from bench_regress's rate comparison (like the
        # ensembleN evidence) and covered by the clean-case gate.
        from isotope_tpu.compiler import compile_policies
        from isotope_tpu.resilience.faults import ChaosJitterSpec
        from isotope_tpu.sim import splitting as split_mod
        from isotope_tpu.sim.config import ChaosEvent, SimParams
        from isotope_tpu.sim.ensemble import EnsembleSpec

        with open("examples/topologies/1000-svc_2000-end.yaml") as f:
            doc = yaml.safe_load(f)
        doc.setdefault("policies", {})["defaults"] = {
            "retry_budget": {"budget_percent": "25%"},
        }
        g = ServiceGraph.decode(doc)
        compiled_g = compile_graph(g)
        svc_name = compiled_g.services.names[1]
        chaos = (ChaosEvent(svc_name, 0.05, 0.25, replicas_down=1),)
        sim = Simulator(
            compiled_g, SimParams(timeline=True), chaos=chaos,
            policies=compile_policies(g, compiled_g),
        )
        jitter = ChaosJitterSpec(time=0.3, magnitude=0.5, seed=0)
        members = int(os.environ.get("BENCH_CHAOSFLEET_MEMBERS", "8"))
        spec = EnsembleSpec.of(members)
        load_e = LoadModel(kind="open", qps=10_000.0)
        n_e = int(os.environ.get(
            "BENCH_CHAOSFLEET_REQUESTS", "8192" if on_tpu else "512"
        ))
        b_e = min(n_e, 4_096 if on_tpu else 512)
        traces0 = telemetry.counter_get("engine_traces")
        last_fleet = {}

        def fleet_runner(s_, l_, n_, k_, b_):
            ens = s_.run_policies_ensemble(
                l_, n_, k_, spec, block_size=b_, window_s=0.05,
                member_chaos=jitter,
            )
            last_fleet["ens"] = ens
            return ens.pooled()

        med, spread, best, first_s = measure(
            sim, load_e, n_e, b_e, warm=2, iters=2,
            runner=fleet_runner,
        )
        out[f"{name}_chaosfleet_members"] = members
        out[f"{name}_chaosfleet_traces"] = int(
            telemetry.counter_get("engine_traces") - traces0
        )
        sev = last_fleet["ens"].severity()
        out[f"{name}_chaosfleet_worst_severity"] = round(
            float(sev.max()), 6
        )
        # forced-rare outage estimate: peak error share past a
        # threshold the typical member never reaches
        sspec = split_mod.SplitSpec(
            levels=3, members=members, keep=0.25,
            threshold=max(float(sev.max()) * 2.0, 0.2),
            severity="err_peak", seed=0,
        )
        reps = compiled_g.services.replicas_by_name()
        from isotope_tpu.resilience.faults import jitter_chaos_events

        def evaluate(chaos_seeds, work_seeds):
            import numpy as _np

            mkeys = [
                jax.random.fold_in(jax.random.PRNGKey(9), int(w))
                for w in work_seeds
            ]
            mc = [
                jitter_chaos_events(chaos, jitter, row, reps)
                for row in _np.asarray(chaos_seeds)
            ]
            ens = sim.run_policies_ensemble(
                load_e, n_e, jax.random.PRNGKey(9),
                EnsembleSpec.of(len(mkeys)), block_size=b_e,
                window_s=0.05, member_keys=mkeys, member_chaos=mc,
            )
            return split_mod.severity_scores(
                sspec, ens.summaries, ens.timelines
            )

        try:
            sdoc = split_mod.subset_estimate(
                evaluate, sspec, chaos_components=len(chaos)
            )
            out[f"{name}_chaosfleet_split_p"] = sdoc["p"]
            out[f"{name}_chaosfleet_split_evals"] = sdoc[
                "evaluations"
            ]
        except Exception as e:  # pragma: no cover - capture survival
            out[f"{name}_chaosfleet_split_error"] = str(e)[:200]
    elif name == "svc1000_composed":
        # universal member (PR 18): svc1000 with EVERY layer composed
        # in one fleet program — retry-budget policies, an LB panic
        # pool on a mid-graph service, a canary rollout on another,
        # and member-jittered UNGRACEFUL (drain: false) kills.  The
        # pre-universal member rejected all four of those tables as
        # host/trace constants; this case exists for GATE COVERAGE of
        # the full composition at svc scale.  The `<case>_composed_*`
        # evidence keys and the case rate are EXCLUDED from
        # bench_regress's rate comparison (coverage, not headline);
        # its telemetry block carries degraded_to like every case, so
        # the previously-clean-case gate must see the composed fleet
        # complete undegraded.
        from isotope_tpu.compiler import (
            compile_lb,
            compile_policies,
            compile_rollouts,
        )
        from isotope_tpu.resilience.faults import ChaosJitterSpec
        from isotope_tpu.sim.ensemble import EnsembleSpec

        with open("examples/topologies/1000-svc_2000-end.yaml") as f:
            doc = yaml.safe_load(f)
        lb_svc = doc["services"][1]["name"]
        roll_svc = doc["services"][2]["name"]
        doc["policies"] = {
            "defaults": {"retry_budget": {"budget_percent": "25%"}},
            lb_svc: {"lb": {"policy": "least_request",
                            "panic_threshold": "50%"}},
        }
        doc["rollouts"] = {
            "defaults": {"gates": {"min_samples": 20}},
            roll_svc: {
                "steps": ["10%", "50%", "100%"],
                "bake": "2s",
                "rollback": {"cooldown": "4s", "max_retries": 1},
                "canary": {"error_rate": "30%"},
            },
        }
        g = ServiceGraph.decode(doc)
        compiled_g = compile_graph(g)
        chaos = (ChaosEvent(lb_svc, 0.05, 0.25, replicas_down=1,
                            drain=False),)
        sim = Simulator(
            compiled_g, SimParams(timeline=True), chaos=chaos,
            policies=compile_policies(g, compiled_g),
            rollouts=compile_rollouts(g, compiled_g),
            lb=compile_lb(g, compiled_g),
        )
        jitter = ChaosJitterSpec(time=0.3, magnitude=0.5, seed=0)
        members = int(os.environ.get("BENCH_COMPOSED_MEMBERS", "8"))
        spec = EnsembleSpec.of(members)
        load_e = LoadModel(kind="open", qps=10_000.0)
        n_e = int(os.environ.get(
            "BENCH_COMPOSED_REQUESTS", "8192" if on_tpu else "512"
        ))
        b_e = min(n_e, 4_096 if on_tpu else 512)
        traces0 = telemetry.counter_get("engine_traces")
        last_fleet = {}

        def composed_runner(s_, l_, n_, k_, b_):
            ens = s_.run_rollouts_ensemble(
                l_, n_, k_, spec, block_size=b_, window_s=0.05,
                member_chaos=jitter,
            )
            last_fleet["ens"] = ens
            return ens.pooled()

        med, spread, best, first_s = measure(
            sim, load_e, n_e, b_e, warm=2, iters=2,
            runner=composed_runner,
        )
        out[f"{name}_composed_members"] = members
        out[f"{name}_composed_traces"] = int(
            telemetry.counter_get("engine_traces") - traces0
        )
        sev = last_fleet["ens"].severity()
        out[f"{name}_composed_worst_severity"] = round(
            float(sev.max()), 6
        )
    elif name == "realistic50":
        sim = Simulator(
            compile_graph(
                ServiceGraph.decode(
                    realistic_topology(50, archetype="multitier", seed=0)
                )
            )
        )
        b = sim.default_block_size()
        med, spread, best, first_s = measure(sim, open_load, b * 4, b)
    elif name == "rollout50":
        # reactive canary co-sim (sim/rollout.py): realistic50 with a
        # mid-graph service on a step schedule, windows served by
        # run_rollouts — the case exists for GATE COVERAGE of the
        # rollout-enabled program: its telemetry block carries
        # degraded_to like every other case (bench_regress's
        # previously-clean-case gate), and the `<case>_rollout` marker
        # records that the rollout controller, not the plain summary
        # path, produced the number
        doc = realistic_topology(50, archetype="multitier", seed=0)
        canary_svc = doc["services"][1]["name"]
        doc["rollouts"] = {
            canary_svc: {
                "steps": ["5%", "25%", "100%"],
                "bake": "2s",
                "gates": {"min_samples": 50},
            }
        }
        g = ServiceGraph.decode(doc)
        compiled = compile_graph(g)
        from isotope_tpu.compiler import compile_rollouts

        rtables = compile_rollouts(g, compiled)
        sim = Simulator(compiled, SimParams(timeline=True),
                        rollouts=rtables)

        def roll_runner(s_, l_, n_, k_, b_):
            return s_.run_rollouts(
                l_, n_, k_, block_size=b_, window_s=1.0
            )[0]

        # half the plain-case request budget: the protected program
        # sweeps two M/M/k stations per service and carries the
        # controller state, so its windows cost ~2x run_summary's —
        # the case exists for coverage, not the headline
        b = sim.default_block_size()
        med, spread, best, first_s = measure(
            sim, open_load, b * 2, b, warm=2, iters=2,
            runner=roll_runner,
        )
        out[f"{name}_rollout"] = 1
    elif name == "svc10k":
        sim = Simulator(
            compile_graph(
                ServiceGraph.decode(
                    realistic_topology(10_000, archetype="multitier",
                                       seed=0)
                )
            )
        )
        b = sim.default_block_size()
        med, spread, best, first_s = measure(
            sim, LoadModel(kind="open", qps=1000.0), b * 4, b
        )
    elif name == "svc10k_protected":
        # protected svc10k through the DEFAULT scan-bucket plan: the
        # retry-budget gate reached the bucket attempt loop
        # (sim/levelscan.py), so Simulator(policies=...) no longer
        # forces the unrolled trace — this case exists for GATE
        # COVERAGE of that path at scale (cfg3-style timeouts +
        # entry-subtree retries, a retry-budget default, and a
        # least-request lb law on a mid-tier service).  Its telemetry
        # block carries degraded_to like every case (the
        # previously-clean-case gate must see the protected program
        # complete through scan buckets undegraded), and the
        # `<case>_lb` marker records that the lb-law wait physics, not
        # the plain M/M/k path, produced the number.
        from isotope_tpu.compiler import compile_lb, compile_policies
        from isotope_tpu.compiler.buckets import ScanBucketPlan

        doc = with_call_policy(
            realistic_topology(10_000, archetype="multitier", seed=0),
            timeout="30s",
        )
        kids: dict = {}
        for svc in doc["services"]:
            kids[svc["name"]] = [
                c["call"]["service"] for c in svc.get("script", [])
                if isinstance(c, dict) and "call" in c
            ]

        def psub(name, _memo={}):
            if name not in _memo:
                _memo[name] = 1 + sum(psub(c) for c in kids[name])
            return _memo[name]

        pcalls = [
            c for c in doc["services"][0].get("script", [])
            if isinstance(c, dict) and "call" in c
        ]
        for cmd in sorted(
            pcalls, key=lambda c: psub(c["call"]["service"])
        )[:2]:
            cmd["call"]["retries"] = 2
        mid = doc["services"][1]["name"]
        doc["policies"] = {
            "defaults": {"retry_budget": {"budget_percent": "20%"}},
            mid: {"lb": {"policy": "least_request", "choices_d": 2,
                         "panic_threshold": "30%"}},
        }
        g = ServiceGraph.decode(doc)
        compiled = compile_graph(g)
        sim = Simulator(
            compiled, SimParams(timeline=True),
            policies=compile_policies(g, compiled),
            lb=compile_lb(g, compiled),
        )
        if not any(isinstance(p, ScanBucketPlan) for p in sim._plan):
            raise RuntimeError(
                "svc10k_protected must plan scan buckets (the lifted "
                "restriction is the thing under test)"
            )

        def prot_runner(s_, l_, n_, k_, b_):
            return s_.run_policies(
                l_, n_, k_, block_size=b_, window_s=1.0
            )[0]

        b = sim.default_block_size()
        med, spread, best, first_s = measure(
            sim, LoadModel(kind="open", qps=1000.0), b * 2, b,
            warm=2, iters=2, runner=prot_runner,
        )
        out[f"{name}_lb"] = 1
    elif name == "svc10k_ingested":
        # trace-driven replay at scale (PR 20, ingest/): simulate the
        # svc10k multitier shape ONCE with the flight recorder armed,
        # export the two Prometheus expositions a real scrape would
        # see, fit them back into a topology (pure host code), and
        # measure the FITTED graph's replay throughput.  The case rate
        # is the replay's hop-events/s — same family as svc10k, so a
        # fit that loses edges or inflates sleeps breaks the rate; the
        # `<case>_ingest_*` keys carry the host-side fit evidence
        # (bench_regress excludes them from the rate comparison).
        import tempfile as _tempfile

        from isotope_tpu.ingest import fitters, readers
        from isotope_tpu.metrics import timeline as timeline_mod
        from isotope_tpu.metrics.prometheus import MetricsCollector

        src_sim = Simulator(
            compile_graph(
                ServiceGraph.decode(
                    realistic_topology(10_000, archetype="multitier",
                                       seed=0)
                )
            ),
            SimParams(timeline=True, timeline_window_s=1.0),
        )
        coll = MetricsCollector(src_sim.compiled)
        load_i = LoadModel(kind="open", qps=1000.0)
        n_i = min(blk, 8_192)
        summary, tl = src_sim.run_timeline(
            load_i, n_i, jax.random.PRNGKey(0), collector=coll,
            window_s=1.0,
        )
        jax.block_until_ready(summary.count)
        t0 = time.perf_counter()
        with _tempfile.TemporaryDirectory() as td:
            p_full = os.path.join(td, "full.prom")
            p_tl = os.path.join(td, "timeline.prom")
            with open(p_full, "w") as f:
                f.write(coll.full_text(summary))
            with open(p_tl, "w") as f:
                f.write(timeline_mod.prometheus_text(
                    src_sim.compiled, tl
                ))
            obs = readers.read_path(p_full)
            obs = readers.read_path(p_tl, obs=obs)
        fr = fitters.fit(obs, fitters.FitOptions(label="svc10k"))
        out[f"{name}_ingest_fit_s"] = round(
            time.perf_counter() - t0, 3
        )
        out[f"{name}_ingest_services"] = len(fr.services)
        out[f"{name}_ingest_edges"] = len(fr.edges)
        out[f"{name}_ingest_lines"] = sum(
            c.lines_parsed for c in obs.inputs
        )
        out[f"{name}_ingest_qps"] = round(float(fr.qps_mean or 0), 3)

        sim = Simulator(compile_graph(fr.graph))
        b = sim.default_block_size()
        med, spread, best, first_s = measure(
            sim, LoadModel(kind="open", qps=float(fr.qps_mean or 1000)),
            b * 2, b, warm=2, iters=2,
        )
    elif name == "star10k":
        # the star archetype's skewed hub level runs via the sparse
        # call-slot encoding — dense grids made it block-starved
        sim = Simulator(
            compile_graph(
                ServiceGraph.decode(
                    realistic_topology(10_000, archetype="star", seed=0)
                )
            )
        )
        b = sim.default_block_size()
        med, spread, best, first_s = measure(
            sim, LoadModel(kind="open", qps=1000.0), b * 4, b
        )
    elif name == "svc100k_chaos":
        # BASELINE configs[4]: 24 unrolled levels, block ~335; a
        # mid-run total outage exercises the phase tables and
        # Pareto(2.5) the heavy-tail sampler
        sim = Simulator(
            compile_graph(
                ServiceGraph.decode(
                    realistic_topology(100_000, archetype="multitier",
                                       seed=0)
                )
            ),
            SimParams(service_time="pareto", service_time_param=2.5),
            (ChaosEvent(service="mock-7", start_s=5.0, end_s=15.0,
                        replicas_down=None),),
        )
        b = sim.default_block_size()
        med, spread, best, first_s = measure(
            sim, LoadModel(kind="open", qps=100.0), b * 2, b
        )
    elif name == "svc10k_cfg3_10M":
        # north-star census: timeouts on EVERY call, retries on the
        # entry's two SMALLEST call subtrees (each retry attempt
        # unrolls its whole subtree: tree-wide retries explode
        # 3^depth, and even entry-wide retries tripled the graph to
        # 30k hops, pushing the XLA compile past the tunnel's remote
        # request deadline).  The retry-feedback machinery engages the
        # same either way.  1.78M qps over the probed 5.77s critical
        # path => lambda*W > 1e7 resident requests at rho ~ 0.71.
        doc3 = with_call_policy(
            realistic_topology(10_000, archetype="multitier", seed=0,
                               num_replicas=192),
            timeout="30s",
        )
        kids: dict = {}
        for svc in doc3["services"]:
            kids[svc["name"]] = [
                c["call"]["service"] for c in svc.get("script", [])
                if isinstance(c, dict) and "call" in c
            ]

        def subtree(name, _memo={}):
            if name not in _memo:
                _memo[name] = 1 + sum(subtree(c) for c in kids[name])
            return _memo[name]

        entry_calls = [
            c for c in doc3["services"][0].get("script", [])
            if isinstance(c, dict) and "call" in c
        ]
        for cmd in sorted(
            entry_calls, key=lambda c: subtree(c["call"]["service"])
        )[:2]:
            cmd["call"]["retries"] = 2
        sim = Simulator(compile_graph(ServiceGraph.decode(doc3)))
        b = sim.default_block_size()
        load3 = LoadModel(kind="open", qps=1_780_000.0)
        # fewer windows: the ~200s compile dominates this case's
        # budget and its measured spread is small
        med, spread, best, first_s = measure(sim, load3, b * 4, b, warm=2,
                                  iters=2, trials=5)
        s = sim.run_summary(
            load3, b * 4, jax.random.PRNGKey(42), block_size=b
        )
        jax.block_until_ready(s.count)
        out["svc10k_cfg3_inflight"] = load3.qps * s.mean_latency_s
    else:
        raise ValueError(f"unknown case {name!r}")

    # critical-path blame probe (metrics/attribution.py): a SMALL
    # attributed run on the same sim/load shape embeds per-service
    # blame shares so tools/bench_regress.py can gate on blame drift
    # (opt-in BENCH_REGRESS_BLAME_THRESHOLD).  Best-effort and cheap
    # (one extra block); BENCH_BLAME=0 disables.
    if os.environ.get("BENCH_BLAME", "1") not in ("0", "off"):
        try:
            out["blame"] = _case_blame(
                case_ctx["sim"], case_ctx["load"]
            )
        except Exception:  # pragma: no cover - capture survival
            pass

    # flight-recorder overhead probe (metrics/timeline.py): the
    # acceptance bar is <= 5% steady-state on svc1000; embed the
    # measured delta so the bench gate can hold the line.  Cheap (a
    # few timed windows); BENCH_TIMELINE=0 disables.
    if os.environ.get("BENCH_TIMELINE", "1") not in ("0", "off"):
        try:
            out["timeline_overhead"] = round(
                _case_timeline_overhead(
                    case_ctx["sim"], case_ctx["load"],
                    min(4_096, blk), min(1_024, blk),
                ),
                4,
            )
        except Exception:  # pragma: no cover - capture survival
            pass

    out["median"] = med
    out["spread"] = spread
    out["best"] = best
    # timed windows discarded by the steady-state detector before the
    # reported window (see _rate) — noise-discipline evidence
    out["warmup_windows"] = case_ctx.get("warmup_windows", 0)
    # first-call wall time (trace + XLA compile): the compile-wall
    # evidence for the bucketed level-scan executor / compile cache —
    # sourced from the telemetry phase timer (see _rate)
    out["compile_s"] = first_s
    # the engine telemetry block: compile-phase split, cache hit
    # ratios, padding waste, device-memory high-water — lands in the
    # BENCH json per case so tools/bench_regress.py can gate on
    # compile-time / memory regressions, not just throughput
    telemetry.record_device_memory()
    out["telemetry"] = telemetry.summary_block()
    if cache_dir:
        out["compile_cache"] = cache_dir
    return out


def main() -> None:
    if len(sys.argv) == 3 and sys.argv[1] == "--case":
        print(json.dumps(run_case(sys.argv[2])))
        return

    # platform detection runs in a THROWAWAY subprocess: holding a live
    # jax client in the parent would keep one device context resident
    # (and on exclusive-ownership runtimes would lock every child out)
    probe = subprocess.run(
        [sys.executable, "-c",
         "import jax; print(jax.devices()[0].platform)"],
        capture_output=True, text=True, timeout=300,
    )
    platform = probe.stdout.strip().splitlines()[-1] if probe.stdout.strip() \
        else ""
    if probe.returncode != 0 or not platform:
        # a broken environment must fail fast, not masquerade as TPU
        # and run 8 cases to their timeouts (ADVICE r5)
        print(f"bench: platform probe failed (rc={probe.returncode}); "
              "aborting", file=sys.stderr)
        for tail_line in (probe.stderr or "").strip().splitlines()[-6:]:
            print(f"bench:   probe| {tail_line}", file=sys.stderr)
        sys.exit(1)
    on_tpu = platform != "cpu"
    # CPU keeps the cheap cases: the headline tree plus the ensemble
    # fleet (its acceptance bar — >= 2x aggregate vs N sequential solo
    # dispatches with ONE compile — is a CPU-checkable claim)
    names = CASE_ORDER if on_tpu else ["tree121", "ensembleN",
                                       "search64"]

    extra: dict = {}
    for name in names:
        proc = None
        try:
            proc = subprocess.run(
                [sys.executable, __file__, "--case", name],
                capture_output=True, text=True,
                timeout=CASE_TIMEOUT_OVERRIDES.get(name, CASE_TIMEOUT_S),
                cwd=os.path.dirname(os.path.abspath(__file__)),
            )
            line = proc.stdout.strip().splitlines()[-1]
            res = json.loads(line)
        except Exception as e:  # timeout, crash, bad output
            print(f"bench: case {name} FAILED: {e}", file=sys.stderr)
            # surface the child's actual error (the traceback / OOM
            # message lives in ITS stderr, not the parent exception)
            err = getattr(e, "stderr", None) or (
                proc.stderr if proc is not None else None
            )
            for tail_line in (err or "").strip().splitlines()[-6:]:
                print(f"bench:   {name}| {tail_line}", file=sys.stderr)
            extra[name] = None
            continue
        extra[name] = res["median"]
        extra[f"{name}_spread"] = round(res["spread"], 3)
        extra[f"{name}_warmup_windows"] = res.get("warmup_windows", 0)
        # best window: the statistic r4-and-earlier captures reported
        # (best-of-3); kept for cross-round comparability next to the
        # honest median
        extra[f"{name}_best"] = round(res["best"])
        extra[f"{name}_compile_s"] = round(res.get("compile_s", 0.0), 2)
        if res.get("telemetry"):
            extra[f"{name}_telemetry"] = res["telemetry"]
        if res.get("blame"):
            extra[f"{name}_blame"] = res["blame"]
        if res.get("timeline_overhead") is not None:
            extra[f"{name}_timeline_overhead"] = res[
                "timeline_overhead"
            ]
        for k, v in res.items():
            if k not in ("median", "spread", "best", "compile_s",
                         "telemetry", "blame", "warmup_windows",
                         "timeline_overhead"):
                extra[k] = v
        print(f"bench: {name}: {res['median'] / 1e9:.3f}B "
              f"(spread {res['spread']:.0%}, first-call "
              f"{res.get('compile_s', 0.0):.1f}s)", file=sys.stderr)

    tree121 = extra.get("tree121") or 0.0
    extra_out = {
        k: (round(v) if isinstance(v, float)
            and not k.endswith(("_spread", "_timeline_overhead",
                                "_blame_overhead",
                                "_mesh_layout_score"))
            else v)
        for k, v in extra.items()
    }
    print(
        json.dumps(
            {
                "metric": "simulated hop-events/sec/chip",
                "value": tree121,
                "unit": "hop-events/s",
                "vs_baseline": tree121 / NORTH_STAR_PER_CHIP,
                "extra": extra_out,
            }
        )
    )


if __name__ == "__main__":
    main()
