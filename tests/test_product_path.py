"""Summary-path formatters vs the dense per-request formatters.

The product path (runner + CLI) now flows through ``run_summary`` —
O(buckets) on-device accumulation — so the Fortio JSON / trim-window /
CSV artifacts are derived from a RunSummary instead of per-request
tensors.  These tests pin the two derivations against each other on the
SAME SimResults, so any drift is formatter error, not RNG noise.
"""
import jax
import numpy as np
import pytest
import yaml

from isotope_tpu.compiler import compile_graph
from isotope_tpu.metrics.fortio import (
    fortio_result,
    fortio_result_from_summary,
    trim_window_bounds,
    trim_window_summary,
    window_summary_from_summary,
)
from isotope_tpu.models.graph import ServiceGraph
from isotope_tpu.sim.config import LoadModel
from isotope_tpu.sim.engine import Simulator
from isotope_tpu.sim.summary import summarize

CHAIN = """
services:
- name: entry
  isEntrypoint: true
  errorRate: 2%
  script:
  - call: leaf
- name: leaf
  script:
  - sleep: 1ms
"""


def _run(load, n, seed=0):
    sim = Simulator(compile_graph(ServiceGraph.decode(yaml.safe_load(CHAIN))))
    res = sim.run(load, n, jax.random.PRNGKey(seed))
    return sim, res


def test_fortio_result_from_summary_matches_dense():
    load = LoadModel(kind="open", qps=500.0, duration_s=10.0)
    _, res = _run(load, 5000)
    summary = summarize(res)

    dense = fortio_result(res, load, labels="x", response_size_bytes=1024)
    via_summary = fortio_result_from_summary(
        summary, load, labels="x", response_size_bytes=1024
    )

    for key in ("RunType", "Labels", "RequestedQPS", "RequestedDuration",
                "NumThreads", "RetCodes", "Sizes"):
        assert via_summary[key] == dense[key]
    assert via_summary["ActualQPS"] == pytest.approx(
        dense["ActualQPS"], rel=1e-4
    )
    dh, sh = dense["DurationHistogram"], via_summary["DurationHistogram"]
    assert sh["Count"] == dh["Count"]
    assert sh["Min"] == pytest.approx(dh["Min"], rel=1e-5)
    assert sh["Max"] == pytest.approx(dh["Max"], rel=1e-5)
    # f32 accumulation on device vs f64 on host
    assert sh["Avg"] == pytest.approx(dh["Avg"], rel=1e-3)
    assert sh["StdDev"] == pytest.approx(dh["StdDev"], rel=2e-2)
    # percentiles recovered from the fine log histogram (~0.6% buckets)
    for pd, ps in zip(dh["Percentiles"], sh["Percentiles"]):
        assert ps["Percentile"] == pd["Percentile"]
        assert ps["Value"] == pytest.approx(pd["Value"], rel=0.02)
    # re-bucketed rows partition the same population
    assert sum(r["Count"] for r in sh["Data"]) == sh["Count"]
    assert sum(r["Count"] for r in dh["Data"]) == dh["Count"]


def test_window_summary_from_summary_matches_dense():
    # 6000 req at 50 qps ~ 120s: window = [62, 62+28)
    load = LoadModel(kind="open", qps=50.0, duration_s=120.0)
    sim, res = _run(load, 6000)
    names = sim.compiled.services.names
    reps = sim.compiled.services.replicas

    dense = trim_window_summary(res, load, service_names=names,
                                replicas=reps)
    lo, hi = trim_window_bounds(6000, 50.0)
    summary = summarize(res, window=(lo, hi))
    via = window_summary_from_summary(summary, service_names=names,
                                      replicas=reps)

    assert via.start_s == dense.start_s
    # The summary window is placed from the EXPECTED duration, the
    # dense one from the ACTUAL duration; they differ by the arrival
    # process's ~1/sqrt(n) noise AMPLIFIED ~4x through the fixed 92 s
    # skip subtraction (a 2.6% duration deficit at this seed becomes
    # an 11% window-length delta: (120-92) vs (116.8-92)).  Bound the
    # placement gap on the run-duration scale, where the noise lives,
    # not on the subtracted window length.
    assert abs(via.duration_s - dense.duration_s) <= 0.05 * 120.0
    assert abs(via.count - dense.count) <= 0.05 * 120.0 * dense.qps
    assert via.qps == pytest.approx(dense.qps, rel=0.1)
    assert via.discarded == dense.discarded is False
    assert via.error_percent == pytest.approx(dense.error_percent, abs=1.0)
    # percentile fidelity is a SAME-POPULATION check: the two
    # derivations window different request sets (expected- vs
    # actual-duration placement), so compare the summary path against
    # dense quantiles over ITS OWN accumulated window — any gap left
    # is formatter error (histogram quantization), not placement noise
    starts = np.asarray(res.client_start, np.float64)
    lat = np.asarray(res.client_latency, np.float64)
    mask = (starts >= lo) & (starts < hi)
    from isotope_tpu.metrics.fortio import PERCENTILES

    qs = np.quantile(lat[mask], [p / 100.0 for p in PERCENTILES])
    for p, v in zip(PERCENTILES, qs):
        k = "p" + str(p).replace(".", "")
        assert via.percentiles_us[k] == pytest.approx(
            v * 1e6, rel=0.03, abs=30
        ), k
    assert via.cpu_cores == pytest.approx(dense.cpu_cores, rel=1e-5)


def test_short_run_discarded_same_as_dense():
    load = LoadModel(kind="open", qps=500.0, duration_s=4.0)
    sim, res = _run(load, 2000)
    dense = trim_window_summary(res, load)
    summary = summarize(res, window=trim_window_bounds(2000, 500.0))
    via = window_summary_from_summary(summary)
    assert dense.discarded and via.discarded
    assert "less than minimum" in via.discard_reason
    # fallback: window empty => overall error percent
    assert via.error_percent == pytest.approx(dense.error_percent, abs=0.5)


def test_run_summary_trim_populates_window_fields():
    sim = Simulator(
        compile_graph(ServiceGraph.decode(yaml.safe_load(CHAIN)))
    )
    load = LoadModel(kind="open", qps=50.0)
    s = sim.run_summary(load, 6000, jax.random.PRNGKey(1),
                        block_size=2048, trim=True)
    assert 0 < float(s.win_count) < float(s.count)
    assert float(np.asarray(s.win_latency_hist).sum()) == float(s.win_count)
    # untrimmed: the window covers everything
    s2 = sim.run_summary(load, 6000, jax.random.PRNGKey(1),
                         block_size=2048)
    assert float(s2.win_count) == float(s2.count)


def test_closed_loop_summary_window_spans_blocks():
    sim = Simulator(
        compile_graph(ServiceGraph.decode(yaml.safe_load(CHAIN)))
    )
    load = LoadModel(kind="closed", qps=100.0, connections=8)
    s = sim.run_summary(load, 12000, jax.random.PRNGKey(2),
                        block_size=1024, trim=True)
    # ~120s run: window [62, 90) holds ~100qps * 28s requests
    expect = 100.0 * (120.0 - 92.0)
    assert float(s.win_count) == pytest.approx(expect, rel=0.15)
