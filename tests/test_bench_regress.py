"""The per-case bench regression gate (tools/bench_regress.py).

Locks the behaviors the r5 measurement-honesty work depends on:
like-for-like statistic selection across methodology generations
(pre-r5 captures reported best-of-window; r5+ report medians with
``*_best`` evidence keys), failure on vanished (null) cases, and the
pass/fail threshold itself.
"""
import importlib.util
import json
import pathlib
import sys


TOOL = pathlib.Path(__file__).parent.parent / "tools" / "bench_regress.py"
spec = importlib.util.spec_from_file_location("bench_regress", TOOL)
br = importlib.util.module_from_spec(spec)
spec.loader.exec_module(br)


def capture(value, extra):
    return {"metric": "m", "value": value, "unit": "u",
            "vs_baseline": 1.0, "extra": extra}


def run_gate(tmp_path, monkeypatch, new_doc, baseline_doc, r=4):
    (tmp_path / f"BENCH_r{r:02d}.json").write_text(
        json.dumps({"parsed": baseline_doc})
    )
    new_path = tmp_path / "new.json"
    new_path.write_text(json.dumps(new_doc))
    monkeypatch.setattr(br, "REPO_ROOT", str(tmp_path))
    # pin the threshold: br.THRESHOLD is baked from the ambient
    # BENCH_REGRESS_THRESHOLD env var at import, and these tests'
    # numeric expectations assume the 15% default
    monkeypatch.setattr(br, "THRESHOLD", 0.15)
    monkeypatch.setattr(sys, "argv", ["bench_regress", str(new_path)])
    return br.main()


def test_pass_within_threshold(tmp_path, monkeypatch, capsys):
    base = capture(2.0e9, {"svc1000": 1.5e9})
    new = capture(1.9e9, {"svc1000": 1.45e9})
    assert run_gate(tmp_path, monkeypatch, new, base) == 0
    assert "PASS" in capsys.readouterr().out


def test_fail_beyond_threshold(tmp_path, monkeypatch, capsys):
    base = capture(2.0e9, {"svc1000": 1.5e9})
    new = capture(2.0e9, {"svc1000": 1.0e9})
    assert run_gate(tmp_path, monkeypatch, new, base) == 1
    assert "svc1000" in capsys.readouterr().out


def test_best_vs_pre_r5_baseline(tmp_path, monkeypatch, capsys):
    # pre-r5 baseline (no *_best keys) reported best-of-window: the
    # new capture's BEST must be compared, not its median (a median
    # 25% below an old best is methodology, not regression)
    base = capture(2.0e9, {"svc1000": 2.0e9})
    new = capture(
        1.5e9,
        {"svc1000": 1.5e9, "svc1000_spread": 0.4,
         "svc1000_best": 1.9e9, "tree121_best": 1.9e9},
    )
    assert run_gate(tmp_path, monkeypatch, new, base) == 0
    out = capsys.readouterr().out
    assert "1.9e+09" in out  # compared the best, not the median


def test_median_vs_r5_baseline(tmp_path, monkeypatch, capsys):
    # an r5-style baseline (has *_best keys) stores medians: compare
    # median vs median — new-best-vs-old-median would mask a real
    # median regression behind the window spread
    base = capture(
        2.0e9, {"svc1000": 2.0e9, "svc1000_best": 2.6e9}
    )
    new = capture(
        1.5e9,
        {"svc1000": 1.5e9, "svc1000_best": 2.5e9,
         "tree121_best": 2.5e9},
    )
    assert run_gate(tmp_path, monkeypatch, new, base) == 1
    assert "svc1000" in capsys.readouterr().out


def test_null_case_fails(tmp_path, monkeypatch, capsys):
    # a case that crashed/timed out inside bench.py becomes null in
    # the capture — the gate must FAIL, not skip it
    base = capture(2.0e9, {"svc1000": 1.5e9})
    new = capture(2.0e9, {"svc1000": None})
    assert run_gate(tmp_path, monkeypatch, new, base) == 1
    assert "FAILED in the new capture" in capsys.readouterr().out


def test_evidence_keys_not_compared(tmp_path, monkeypatch, capsys):
    base = capture(
        2.0e9,
        {"svc10k_cfg3_inflight": 1.0e7, "svc1000_spread": 0.3,
         "svc1000": 2.0e9, "svc1000_best": 2.2e9},
    )
    new = capture(
        2.0e9,
        {"svc10k_cfg3_inflight": 5.0e6, "svc1000_spread": 0.9,
         "svc1000": 2.0e9, "svc1000_best": 2.2e9},
    )
    # halved census / tripled spread are evidence, not rate cases
    assert run_gate(tmp_path, monkeypatch, new, base) == 0


def test_telemetry_gates_off_by_default(tmp_path, monkeypatch, capsys):
    # a 10x compile-time and memory blowup passes when no telemetry
    # threshold env var is armed — the gates are strictly opt-in
    for var in ("BENCH_REGRESS_COMPILE_THRESHOLD",
                "BENCH_REGRESS_MEM_THRESHOLD",
                "BENCH_REGRESS_WASTE_THRESHOLD"):
        monkeypatch.delenv(var, raising=False)
    base = capture(2.0e9, {
        "svc1000": 2.0e9, "svc1000_best": 2.1e9, "svc1000_compile_s": 5.0,
        "svc1000_telemetry": {"compile_s": 5.0, "peak_device_bytes": 1e8,
                              "padding_waste_fraction": 0.1},
    })
    new = capture(2.0e9, {
        "svc1000": 2.0e9, "svc1000_best": 2.1e9, "svc1000_compile_s": 50.0,
        "svc1000_telemetry": {"compile_s": 50.0, "peak_device_bytes": 1e9,
                              "padding_waste_fraction": 0.9},
    })
    assert run_gate(tmp_path, monkeypatch, new, base) == 0


def test_telemetry_compile_gate(tmp_path, monkeypatch, capsys):
    monkeypatch.setenv("BENCH_REGRESS_COMPILE_THRESHOLD", "0.5")
    base = capture(2.0e9, {
        "svc1000": 2.0e9, "svc1000_best": 2.1e9, "svc1000_compile_s": 10.0,
    })
    new = capture(2.0e9, {
        "svc1000": 2.0e9, "svc1000_best": 2.1e9, "svc1000_compile_s": 16.0,
    })
    assert run_gate(tmp_path, monkeypatch, new, base) == 1
    assert "svc1000.compile_s" in capsys.readouterr().out
    # within threshold passes
    ok = capture(2.0e9, {
        "svc1000": 2.0e9, "svc1000_best": 2.1e9, "svc1000_compile_s": 14.0,
    })
    assert run_gate(tmp_path, monkeypatch, ok, base) == 0


def test_telemetry_memory_gate_from_block(tmp_path, monkeypatch, capsys):
    monkeypatch.setenv("BENCH_REGRESS_MEM_THRESHOLD", "0.2")
    base = capture(2.0e9, {
        "svc1000": 2.0e9, "svc1000_best": 2.1e9,
        "svc1000_telemetry": {"peak_device_bytes": 1.0e8},
    })
    new = capture(2.0e9, {
        "svc1000": 2.0e9, "svc1000_best": 2.1e9,
        "svc1000_telemetry": {"peak_device_bytes": 1.5e8},
    })
    assert run_gate(tmp_path, monkeypatch, new, base) == 1
    assert "svc1000.peak_device_bytes" in capsys.readouterr().out


def test_telemetry_waste_gate_is_absolute(tmp_path, monkeypatch, capsys):
    monkeypatch.setenv("BENCH_REGRESS_WASTE_THRESHOLD", "0.05")
    base = capture(2.0e9, {
        "svc1000": 2.0e9, "svc1000_best": 2.1e9,
        "svc1000_telemetry": {"padding_waste_fraction": 0.0},
    })
    # +0.04 absolute passes even though it is an infinite relative jump
    ok = capture(2.0e9, {
        "svc1000": 2.0e9, "svc1000_best": 2.1e9,
        "svc1000_telemetry": {"padding_waste_fraction": 0.04},
    })
    assert run_gate(tmp_path, monkeypatch, ok, base) == 0
    bad = capture(2.0e9, {
        "svc1000": 2.0e9, "svc1000_best": 2.1e9,
        "svc1000_telemetry": {"padding_waste_fraction": 0.12},
    })
    assert run_gate(tmp_path, monkeypatch, bad, base) == 1
    assert "padding_waste_fraction" in capsys.readouterr().out


def test_telemetry_block_not_compared_as_rate(tmp_path, monkeypatch):
    # the embedded dict must never be treated as a per-case rate
    base = capture(2.0e9, {"svc1000": 2.0e9, "svc1000_best": 2.1e9,
                           "svc1000_telemetry": {"compile_s": 5.0}})
    new = capture(2.0e9, {"svc1000": 2.0e9, "svc1000_best": 2.1e9})
    assert run_gate(tmp_path, monkeypatch, new, base) == 0


def test_no_baseline_skips(tmp_path, monkeypatch, capsys):
    new_path = tmp_path / "new.json"
    new_path.write_text(json.dumps(capture(1.0e9, {})))
    monkeypatch.setattr(br, "REPO_ROOT", str(tmp_path / "empty"))
    (tmp_path / "empty").mkdir()
    monkeypatch.setattr(sys, "argv", ["bench_regress", str(new_path)])
    assert br.main() == 0
    assert "skipping" in capsys.readouterr().out


def test_degraded_on_previously_clean_case_fails(tmp_path, monkeypatch,
                                                 capsys):
    # always-armed gate: a case the resilience supervisor served from a
    # degradation-ladder rung is not comparable to its clean baseline
    base = capture(2.0e9, {
        "svc1000": 2.0e9, "svc1000_best": 2.1e9,
        "svc1000_telemetry": {"compile_s": 5.0},
    })
    bad = capture(2.0e9, {
        "svc1000": 2.0e9, "svc1000_best": 2.1e9,
        "svc1000_telemetry": {"compile_s": 5.0,
                              "degraded_to": "single-device"},
    })
    assert run_gate(tmp_path, monkeypatch, bad, base) == 1
    assert "svc1000.degraded_to" in capsys.readouterr().out


def test_degraded_both_rounds_passes(tmp_path, monkeypatch, capsys):
    # a case that ALREADY ran degraded in the baseline stays comparable
    base = capture(2.0e9, {
        "svc1000": 2.0e9, "svc1000_best": 2.1e9,
        "svc1000_telemetry": {"degraded_to": "half-block"},
    })
    new = capture(2.0e9, {
        "svc1000": 2.0e9, "svc1000_best": 2.1e9,
        "svc1000_telemetry": {"degraded_to": "half-block"},
    })
    assert run_gate(tmp_path, monkeypatch, new, base) == 0
    assert "OK" in capsys.readouterr().out


def test_blame_gate_off_by_default(tmp_path, monkeypatch):
    base = capture(2.0e9, {
        "svc1000": 2.0e9, "svc1000_best": 2.1e9,
        "svc1000_blame": {"services": {"a": 0.8, "b": 0.2}},
    })
    new = capture(2.0e9, {
        "svc1000": 2.0e9, "svc1000_best": 2.1e9,
        "svc1000_blame": {"services": {"a": 0.2, "b": 0.8}},
    })
    monkeypatch.delenv("BENCH_REGRESS_BLAME_THRESHOLD", raising=False)
    assert run_gate(tmp_path, monkeypatch, new, base) == 0


def test_blame_gate_fails_on_share_drift(tmp_path, monkeypatch, capsys):
    base = capture(2.0e9, {
        "svc1000": 2.0e9, "svc1000_best": 2.1e9,
        "svc1000_blame": {"services": {"a": 0.8, "b": 0.2}},
    })
    bad = capture(2.0e9, {
        "svc1000": 2.0e9, "svc1000_best": 2.1e9,
        "svc1000_blame": {"services": {"a": 0.55, "b": 0.45}},
    })
    monkeypatch.setenv("BENCH_REGRESS_BLAME_THRESHOLD", "0.1")
    assert run_gate(tmp_path, monkeypatch, bad, base) == 1
    out = capsys.readouterr().out
    assert "svc1000.blame" in out and "REGRESSION" in out


def test_blame_gate_within_threshold_and_new_service(tmp_path,
                                                     monkeypatch, capsys):
    # a service present on only one side compares against a 0.0 share
    base = capture(2.0e9, {
        "svc1000": 2.0e9, "svc1000_best": 2.1e9,
        "svc1000_blame": {"services": {"a": 0.85, "b": 0.15}},
    })
    ok = capture(2.0e9, {
        "svc1000": 2.0e9, "svc1000_best": 2.1e9,
        "svc1000_blame": {"services": {"a": 0.82, "b": 0.13,
                                       "c": 0.05}},
    })
    monkeypatch.setenv("BENCH_REGRESS_BLAME_THRESHOLD", "0.1")
    assert run_gate(tmp_path, monkeypatch, ok, base) == 0
    assert "OK" in capsys.readouterr().out


def test_blame_gate_skips_pre_attribution_baseline(tmp_path,
                                                   monkeypatch):
    # the baseline predates blame blocks: nothing comparable, no gate
    base = capture(2.0e9, {"svc1000": 2.0e9, "svc1000_best": 2.1e9})
    new = capture(2.0e9, {
        "svc1000": 2.0e9, "svc1000_best": 2.1e9,
        "svc1000_blame": {"services": {"a": 1.0}},
    })
    monkeypatch.setenv("BENCH_REGRESS_BLAME_THRESHOLD", "0.01")
    assert run_gate(tmp_path, monkeypatch, new, base) == 0


def test_spread_gate_off_by_default(tmp_path, monkeypatch):
    base = capture(2.0e9, {"svc1000": 2.0e9, "svc1000_best": 2.1e9,
                           "svc1000_spread": 0.05})
    new = capture(2.0e9, {"svc1000": 2.0e9, "svc1000_best": 2.1e9,
                          "svc1000_spread": 0.40})
    monkeypatch.delenv("BENCH_REGRESS_SPREAD_THRESHOLD", raising=False)
    assert run_gate(tmp_path, monkeypatch, new, base) == 0


def test_spread_gate_fails_on_noise_regression(tmp_path, monkeypatch,
                                               capsys):
    base = capture(2.0e9, {"svc1000": 2.0e9, "svc1000_best": 2.1e9,
                           "svc1000_spread": 0.05})
    noisy = capture(2.0e9, {"svc1000": 2.0e9, "svc1000_best": 2.1e9,
                            "svc1000_spread": 0.30})
    monkeypatch.setenv("BENCH_REGRESS_SPREAD_THRESHOLD", "0.15")
    assert run_gate(tmp_path, monkeypatch, noisy, base) == 1
    out = capsys.readouterr().out
    assert "svc1000.spread" in out and "REGRESSION" in out


def test_spread_gate_tolerates_known_noisy_case(tmp_path, monkeypatch,
                                                capsys):
    # already past the threshold in the baseline AND no worse: no alarm
    base = capture(2.0e9, {"svc1000": 2.0e9, "svc1000_best": 2.1e9,
                           "svc1000_spread": 0.30})
    same = capture(2.0e9, {"svc1000": 2.0e9, "svc1000_best": 2.1e9,
                           "svc1000_spread": 0.28})
    monkeypatch.setenv("BENCH_REGRESS_SPREAD_THRESHOLD", "0.15")
    assert run_gate(tmp_path, monkeypatch, same, base) == 0
    assert "OK" in capsys.readouterr().out


def test_spread_under_threshold_passes(tmp_path, monkeypatch):
    base = capture(2.0e9, {"svc1000": 2.0e9, "svc1000_best": 2.1e9,
                           "svc1000_spread": 0.05})
    new = capture(2.0e9, {"svc1000": 2.0e9, "svc1000_best": 2.1e9,
                          "svc1000_spread": 0.10})
    monkeypatch.setenv("BENCH_REGRESS_SPREAD_THRESHOLD", "0.15")
    assert run_gate(tmp_path, monkeypatch, new, base) == 0


def test_warmup_windows_not_compared_as_rate(tmp_path, monkeypatch,
                                             capsys):
    # the steady-state evidence key must never read as a rate drop
    base = capture(2.0e9, {"svc1000": 2.0e9, "svc1000_best": 2.1e9,
                           "svc1000_warmup_windows": 5})
    new = capture(2.0e9, {"svc1000": 2.0e9, "svc1000_best": 2.1e9,
                          "svc1000_warmup_windows": 0})
    assert run_gate(tmp_path, monkeypatch, new, base) == 0
    # never printed as a rate row (the tmp dir name may contain the
    # phrase — check the case-qualified key)
    assert "svc1000_warmup_windows" not in capsys.readouterr().out


def _load_bench():
    import importlib.util as _ilu
    import pathlib as _pl

    bench_path = _pl.Path(__file__).parent.parent / "bench.py"
    spec = _ilu.spec_from_file_location("bench_mod", bench_path)
    bench = _ilu.module_from_spec(spec)
    spec.loader.exec_module(bench)
    return bench


def test_bench_rate_steady_state_detector(monkeypatch):
    """bench._rate discards pre-steady windows, reports the discard
    count, and the reported stats come from the settled window."""
    bench = _load_bench()

    from isotope_tpu.compiler import compile_graph
    from isotope_tpu.models.graph import ServiceGraph
    from isotope_tpu.sim import LoadModel, Simulator

    chain = (
        "services:\n- name: a\n  isEntrypoint: true\n"
        "  script:\n  - call: b\n- name: b\n"
    )
    sim = Simulator(compile_graph(ServiceGraph.from_yaml(chain)))
    load = LoadModel(kind="open", qps=200.0)

    monkeypatch.setenv("BENCH_STEADY_SPREAD", "0.5")
    monkeypatch.setenv("BENCH_WARMUP_CAP", "3")
    med, spread, best, first_s, warmup = bench._rate(
        sim, load, 256, 128, warm=1, iters=1, trials=3
    )
    assert 0 <= warmup <= 3
    assert med > 0 and spread >= 0.0 and best >= med

    # an impossible steady-state bar burns exactly the warmup cap
    monkeypatch.setenv("BENCH_STEADY_SPREAD", "-1")
    monkeypatch.setenv("BENCH_WARMUP_CAP", "2")
    *_stats, warmup_capped = bench._rate(
        sim, load, 256, 128, warm=0, iters=1, trials=2
    )
    assert warmup_capped == 2


# -- timeline-overhead gate (metrics/timeline.py) ---------------------------


def test_timeline_gate_off_by_default(tmp_path, monkeypatch):
    base = capture(2.0e9, {"svc1000": 2.0e9, "svc1000_best": 2.1e9})
    new = capture(2.0e9, {"svc1000": 2.0e9, "svc1000_best": 2.1e9,
                          "svc1000_timeline_overhead": 0.40})
    monkeypatch.delenv("BENCH_REGRESS_TIMELINE_THRESHOLD",
                       raising=False)
    assert run_gate(tmp_path, monkeypatch, new, base) == 0


def test_timeline_gate_fails_past_bound(tmp_path, monkeypatch, capsys):
    base = capture(2.0e9, {"svc1000": 2.0e9, "svc1000_best": 2.1e9})
    new = capture(2.0e9, {"svc1000": 2.0e9, "svc1000_best": 2.1e9,
                          "svc1000_timeline_overhead": 0.12})
    monkeypatch.setenv("BENCH_REGRESS_TIMELINE_THRESHOLD", "0.05")
    assert run_gate(tmp_path, monkeypatch, new, base) == 1
    out = capsys.readouterr().out
    assert "svc1000.timeline_overhead" in out and "REGRESSION" in out


def test_timeline_gate_absolute_bound_passes_under(tmp_path,
                                                   monkeypatch):
    # absolute bound, not vs-baseline: a baseline with a worse
    # overhead does NOT excuse the new capture, and under-threshold
    # passes regardless of history
    base = capture(2.0e9, {"svc1000": 2.0e9, "svc1000_best": 2.1e9,
                           "svc1000_timeline_overhead": 0.50})
    new = capture(2.0e9, {"svc1000": 2.0e9, "svc1000_best": 2.1e9,
                          "svc1000_timeline_overhead": 0.03})
    monkeypatch.setenv("BENCH_REGRESS_TIMELINE_THRESHOLD", "0.05")
    assert run_gate(tmp_path, monkeypatch, new, base) == 0


def test_timeline_overhead_not_a_rate_key(tmp_path, monkeypatch):
    # the evidence key must not be compared as a hop-rate (a drop in
    # measured overhead would otherwise read as a "regression")
    base = capture(2.0e9, {"svc1000": 2.0e9, "svc1000_best": 2.1e9,
                           "svc1000_timeline_overhead": 0.50})
    new = capture(2.0e9, {"svc1000": 2.0e9, "svc1000_best": 2.1e9,
                          "svc1000_timeline_overhead": 0.01})
    monkeypatch.delenv("BENCH_REGRESS_TIMELINE_THRESHOLD",
                       raising=False)
    assert run_gate(tmp_path, monkeypatch, new, base) == 0


def test_rollout_marker_not_a_rate_key(tmp_path, monkeypatch):
    # the `<case>_rollout` marker (bench.py rollout50: the rollout
    # co-sim served the windows) is evidence, not a rate — and the
    # clean-case degradation gate covers the rollout-enabled case
    # through its telemetry block like any other
    base = capture(2.0e9, {"rollout50": 2.0e9, "rollout50_best": 2.1e9,
                           "rollout50_rollout": 1,
                           "rollout50_telemetry": {}})
    new = capture(2.0e9, {"rollout50": 2.0e9, "rollout50_best": 2.1e9,
                          "rollout50_rollout": 1,
                          "rollout50_telemetry": {}})
    assert run_gate(tmp_path, monkeypatch, new, base) == 0


def test_rollout_case_degradation_gates(tmp_path, monkeypatch):
    base = capture(2.0e9, {"rollout50": 2.0e9, "rollout50_best": 2.1e9,
                           "rollout50_rollout": 1,
                           "rollout50_telemetry": {}})
    new = capture(2.0e9, {"rollout50": 2.0e9, "rollout50_best": 2.1e9,
                          "rollout50_rollout": 1,
                          "rollout50_telemetry": {
                              "degraded_to": "half-block"}})
    assert run_gate(tmp_path, monkeypatch, new, base) == 1


def test_layout_gate_off_by_default(tmp_path, monkeypatch):
    base = capture(2.0e9, {"svc1000": 2.0e9, "svc1000_best": 2.1e9,
                           "_mesh_layout": "data=2,svc=4",
                           "_mesh_layout_score": 1.0e-5})
    new = capture(2.0e9, {"svc1000": 2.0e9, "svc1000_best": 2.1e9,
                          "_mesh_layout": "data=8,svc=1",
                          "_mesh_layout_score": 5.0e-5})
    monkeypatch.delenv("BENCH_REGRESS_LAYOUT_GATE", raising=False)
    assert run_gate(tmp_path, monkeypatch, new, base) == 0


def test_layout_gate_fails_on_worse_score(tmp_path, monkeypatch,
                                          capsys):
    base = capture(2.0e9, {"svc1000": 2.0e9, "svc1000_best": 2.1e9,
                           "_mesh_layout": "data=2,svc=4",
                           "_mesh_layout_score": 1.0e-5})
    new = capture(2.0e9, {"svc1000": 2.0e9, "svc1000_best": 2.1e9,
                          "_mesh_layout": "data=8,svc=1",
                          "_mesh_layout_score": 5.0e-5})
    monkeypatch.setenv("BENCH_REGRESS_LAYOUT_GATE", "1")
    assert run_gate(tmp_path, monkeypatch, new, base) == 1
    assert "_mesh_layout" in capsys.readouterr().out


def test_layout_gate_passes_on_equal_or_better(tmp_path, monkeypatch):
    base = capture(2.0e9, {"svc1000": 2.0e9, "svc1000_best": 2.1e9,
                           "_mesh_layout": "data=2,svc=4",
                           "_mesh_layout_score": 1.0e-5})
    new = capture(2.0e9, {"svc1000": 2.0e9, "svc1000_best": 2.1e9,
                          "_mesh_layout": "data=2,svc=4",
                          "_mesh_layout_score": 1.0e-5})
    monkeypatch.setenv("BENCH_REGRESS_LAYOUT_GATE", "1")
    assert run_gate(tmp_path, monkeypatch, new, base) == 0


def test_layout_gate_skips_pre_layout_baseline(tmp_path, monkeypatch):
    base = capture(2.0e9, {"svc1000": 2.0e9, "svc1000_best": 2.1e9})
    new = capture(2.0e9, {"svc1000": 2.0e9, "svc1000_best": 2.1e9,
                          "_mesh_layout": "data=2,svc=4",
                          "_mesh_layout_score": 1.0e-5})
    monkeypatch.setenv("BENCH_REGRESS_LAYOUT_GATE", "1")
    assert run_gate(tmp_path, monkeypatch, new, base) == 0


def test_layout_score_not_a_rate_key(tmp_path, monkeypatch):
    # a score IMPROVEMENT (smaller) must not read as a rate regression
    base = capture(2.0e9, {"svc1000": 2.0e9, "svc1000_best": 2.1e9,
                           "_mesh_layout_score": 1.0e-5})
    new = capture(2.0e9, {"svc1000": 2.0e9, "svc1000_best": 2.1e9,
                          "_mesh_layout_score": 1.0e-7})
    monkeypatch.delenv("BENCH_REGRESS_LAYOUT_GATE", raising=False)
    assert run_gate(tmp_path, monkeypatch, new, base) == 0


def _ens_extra(rate, members, **kw):
    d = {"ensembleN": rate, "ensembleN_best": rate,
         "ensembleN_ensemble_members": members,
         "ensembleN_ensemble_traces": 1,
         "ensembleN_ensemble_solo_rate": rate / 2.0,
         "ensembleN_ensemble_speedup": 2.0}
    d.update(kw)
    return d


def test_ensemble_gate_off_by_default(tmp_path, monkeypatch):
    base = capture(2.0e9, _ens_extra(3.2e7, 8))
    # per-member rate halves via a member-count doubling at flat
    # aggregate — invisible without the gate
    new = capture(2.0e9, _ens_extra(3.2e7, 16))
    monkeypatch.delenv("BENCH_REGRESS_ENSEMBLE_THRESHOLD",
                       raising=False)
    assert run_gate(tmp_path, monkeypatch, new, base) == 0


def test_ensemble_gate_fails_on_per_member_regression(tmp_path,
                                                      monkeypatch,
                                                      capsys):
    base = capture(2.0e9, _ens_extra(3.2e7, 8))
    new = capture(2.0e9, _ens_extra(3.2e7, 16))
    monkeypatch.setenv("BENCH_REGRESS_ENSEMBLE_THRESHOLD", "0.15")
    assert run_gate(tmp_path, monkeypatch, new, base) == 1
    assert "ensembleN.per_member" in capsys.readouterr().out


def test_ensemble_gate_passes_within_threshold(tmp_path, monkeypatch):
    base = capture(2.0e9, _ens_extra(3.2e7, 8))
    new = capture(2.0e9, _ens_extra(3.1e7, 8))
    monkeypatch.setenv("BENCH_REGRESS_ENSEMBLE_THRESHOLD", "0.15")
    assert run_gate(tmp_path, monkeypatch, new, base) == 0


def test_ensemble_gate_skips_pre_ensemble_baseline(tmp_path,
                                                   monkeypatch):
    base = capture(2.0e9, {"svc1000": 2.0e9, "svc1000_best": 2.1e9})
    new = capture(2.0e9, _ens_extra(3.2e7, 128,
                                    svc1000=2.0e9,
                                    svc1000_best=2.1e9))
    monkeypatch.setenv("BENCH_REGRESS_ENSEMBLE_THRESHOLD", "0.15")
    assert run_gate(tmp_path, monkeypatch, new, base) == 0


def test_ensemble_evidence_keys_not_compared_as_rates(tmp_path,
                                                      monkeypatch):
    # a speedup/solo-rate/member-count drop must never read as a rate
    # regression (they are evidence keys, like *_spread)
    base = capture(2.0e9, _ens_extra(3.2e7, 128))
    new = capture(2.0e9, {"ensembleN": 3.2e7, "ensembleN_best": 3.2e7,
                          "ensembleN_ensemble_members": 128,
                          "ensembleN_ensemble_traces": 1,
                          "ensembleN_ensemble_solo_rate": 1.0e6,
                          "ensembleN_ensemble_speedup": 0.5})
    monkeypatch.delenv("BENCH_REGRESS_ENSEMBLE_THRESHOLD",
                       raising=False)
    assert run_gate(tmp_path, monkeypatch, new, base) == 0


# -- config-search speedup gate (BENCH_REGRESS_SEARCH_THRESHOLD) -------


def _search_extra(rate, speedup, traces=3, **kw):
    d = {"search64": rate, "search64_best": rate,
         "search64_search_candidates": 64,
         "search64_search_rungs": 3,
         "search64_search_traces": traces,
         "search64_search_sequential_rate": rate / max(speedup, 1e-9),
         "search64_search_speedup": speedup}
    d.update(kw)
    return d


def test_search_gate_off_by_default(tmp_path, monkeypatch):
    base = capture(2.0e9, _search_extra(1.3e7, 3.5))
    new = capture(2.0e9, _search_extra(1.3e7, 1.2))
    monkeypatch.delenv("BENCH_REGRESS_SEARCH_THRESHOLD",
                       raising=False)
    assert run_gate(tmp_path, monkeypatch, new, base) == 0


def test_search_gate_fails_below_threshold(tmp_path, monkeypatch,
                                           capsys):
    base = capture(2.0e9, _search_extra(1.3e7, 3.5))
    new = capture(2.0e9, _search_extra(1.3e7, 2.4))
    monkeypatch.setenv("BENCH_REGRESS_SEARCH_THRESHOLD", "3.0")
    assert run_gate(tmp_path, monkeypatch, new, base) == 1
    assert "search64.search_speedup" in capsys.readouterr().out


def test_search_gate_passes_at_threshold(tmp_path, monkeypatch):
    base = capture(2.0e9, _search_extra(1.3e7, 3.5))
    new = capture(2.0e9, _search_extra(1.3e7, 3.1))
    monkeypatch.setenv("BENCH_REGRESS_SEARCH_THRESHOLD", "3.0")
    assert run_gate(tmp_path, monkeypatch, new, base) == 0


def test_search_gate_trace_bound_rides_along(tmp_path, monkeypatch,
                                             capsys):
    # a bracket that compiled more executables than rungs lost the
    # one-compile-per-rung-shape property, whatever the speedup says
    base = capture(2.0e9, _search_extra(1.3e7, 3.5))
    new = capture(2.0e9, _search_extra(1.3e7, 3.5, traces=5))
    monkeypatch.setenv("BENCH_REGRESS_SEARCH_THRESHOLD", "3.0")
    assert run_gate(tmp_path, monkeypatch, new, base) == 1
    assert "search64.search_traces" in capsys.readouterr().out


def test_search_evidence_keys_not_compared_as_rates(tmp_path,
                                                    monkeypatch):
    # a sequential-rate / speedup drop must never read as a case-rate
    # regression: the *_search_* keys are evidence, like *_spread
    base = capture(2.0e9, _search_extra(1.3e7, 4.0))
    new = capture(2.0e9, _search_extra(1.3e7, 1.1))
    monkeypatch.delenv("BENCH_REGRESS_SEARCH_THRESHOLD",
                       raising=False)
    assert run_gate(tmp_path, monkeypatch, new, base) == 0
