"""Resilient execution supervisor (isotope_tpu/resilience/).

Pins the tentpole's contracts: the error taxonomy classifies real and
injected failures, transient retries back off deterministically, the
OOM degradation ladder completes a sharded run with results identical
(<= 1 f32 ULP — measured bit-exact on CPU) to a clean run, corrupted
persistent-cache entries quarantine instead of crashing, numeric
sentinels catch NaN/negative outputs (and localize the segment in
detail mode), and the no-fault default path gains zero sync points.
"""
import json
import pathlib

import jax
import jax.tree_util as jtu
import numpy as np
import pytest

from isotope_tpu import telemetry
from isotope_tpu.compiler import cache as compile_cache, compile_graph
from isotope_tpu.models.graph import ServiceGraph
from isotope_tpu.parallel import ShardedSimulator, make_mesh
from isotope_tpu.resilience import (
    DETERMINISTIC,
    RESOURCE_EXHAUSTED,
    TRANSIENT,
    InjectedFault,
    NumericSentinelError,
    ResiliencePolicy,
    backoff_seconds,
    call_with_retries,
    classify,
    execution_rungs,
    faults,
    run_ladder,
)
from isotope_tpu.resilience import sentinels
from isotope_tpu.sim import LoadModel, Simulator

CHAIN = """
services:
- name: a
  isEntrypoint: true
  script: [{call: b}]
- name: b
  script: [{call: c}]
- name: c
"""

FORK = """
services:
- name: entry
  isEntrypoint: true
  script:
  - - call: x
    - call: y
- name: x
- name: y
  script: [{call: z}]
- name: z
"""

OPEN = LoadModel(kind="open", qps=2000.0)
KEY = jax.random.PRNGKey(11)
NOSLEEP = ResiliencePolicy(sleep=lambda s: None)


@pytest.fixture(autouse=True)
def clean_state():
    faults.clear()
    telemetry.reset()
    yield
    faults.clear()
    telemetry.reset()
    telemetry.disable()


# -- taxonomy --------------------------------------------------------------


@pytest.mark.parametrize(
    "exc,want",
    [
        (RuntimeError("RESOURCE_EXHAUSTED: Out of memory allocating "
                      "268435456 bytes"), RESOURCE_EXHAUSTED),
        (RuntimeError("Failed to allocate request for 2.0GiB"),
         RESOURCE_EXHAUSTED),
        (MemoryError(), RESOURCE_EXHAUSTED),
        (RuntimeError("UNAVAILABLE: Socket closed"), TRANSIENT),
        (RuntimeError("DEADLINE_EXCEEDED: RPC timed out"), TRANSIENT),
        (ConnectionResetError("peer reset"), TRANSIENT),
        (TimeoutError(), TRANSIENT),
        (ValueError("shapes (3,) and (4,) not aligned"), DETERMINISTIC),
        (RuntimeError("INVALID_ARGUMENT: bad operand"), DETERMINISTIC),
        (NumericSentinelError("NaN"), DETERMINISTIC),
    ],
)
def test_classify(exc, want):
    assert classify(exc) == want


def test_injected_faults_classify_like_their_shape():
    faults.install("oom:sharded.compute:1,transient:cache.load:1")
    with pytest.raises(InjectedFault) as oom:
        faults.check("sharded.compute")
    with pytest.raises(InjectedFault) as tr:
        faults.check("cache.load")
    assert classify(oom.value) == RESOURCE_EXHAUSTED
    assert classify(tr.value) == TRANSIENT
    # budgets are consumed: the sites pass afterwards
    faults.check("sharded.compute")
    faults.check("cache.load")
    assert telemetry.counter_get("faults_injected") == 2.0


def test_fault_spec_validation():
    with pytest.raises(ValueError, match="unknown fault kind"):
        faults.install("explode:engine.run:1")
    with pytest.raises(ValueError, match="nan faults target segments"):
        faults.install("nan:engine.run:1")
    with pytest.raises(ValueError, match="bad fault spec"):
        faults.install("oom")
    # a typo'd site must raise AT PARSE TIME with the valid-site list,
    # not parse fine and silently never fire
    with pytest.raises(ValueError, match="sharded.gather"):
        faults.install("oom:sharded.gater:1")
    for site in faults.VALID_SITES:
        faults.install(f"oom:{site}:1")  # every documented site parses
    faults.clear()


# -- retry / backoff --------------------------------------------------------


def test_backoff_deterministic_and_bounded():
    p = ResiliencePolicy()
    seq = [backoff_seconds("engine.run", a, p) for a in range(8)]
    assert seq == [backoff_seconds("engine.run", a, p) for a in range(8)]
    assert all(0 < s <= p.backoff_cap_s for s in seq)
    assert seq[1] > seq[0]  # exponential growth under the cap
    # jitter decorrelates sites
    assert backoff_seconds("sharded.gather", 0, p) != seq[0]


def test_transient_retries_then_succeeds():
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise ConnectionResetError("blip")
        return "ok"

    slept = []
    p = ResiliencePolicy(max_retries=3, sleep=slept.append)
    assert call_with_retries(flaky, "t.site", p) == "ok"
    assert calls["n"] == 3
    assert len(slept) == 2
    assert telemetry.counter_get("retries_total") == 2.0


def test_retry_budget_exhausts():
    def always():
        raise TimeoutError("never")

    with pytest.raises(TimeoutError):
        call_with_retries(
            always, "t.site", ResiliencePolicy(max_retries=2,
                                               sleep=lambda s: None)
        )
    assert telemetry.counter_get("retries_total") == 2.0


def test_deterministic_error_not_retried():
    calls = {"n": 0}

    def boom():
        calls["n"] += 1
        raise ValueError("bad shape")

    with pytest.raises(ValueError):
        call_with_retries(boom, "t.site", NOSLEEP)
    assert calls["n"] == 1
    assert telemetry.counter_get("retries_total") == 0.0


# -- the ladder ------------------------------------------------------------


def test_ladder_descends_on_oom_only():
    seen = []

    def rung(name, fail):
        def thunk():
            seen.append(name)
            if fail:
                raise RuntimeError("RESOURCE_EXHAUSTED: injected")
            return name
        return (name, thunk)

    out, degraded = run_ladder(
        [rung("a", True), rung("b", True), rung("c", False)], NOSLEEP
    )
    assert (out, degraded) == ("c", "c")
    assert seen == ["a", "b", "c"]
    assert telemetry.counter_get("degradations_total") == 2.0
    assert telemetry.get_meta("degraded_to") == "c"
    # Prometheus: first-class series, not an events_total label
    assert "isotope_engine_degradations_total 2" in (
        telemetry.prometheus_text()
    )


def test_ladder_respects_no_degrade():
    def oom():
        raise RuntimeError("RESOURCE_EXHAUSTED: no")

    with pytest.raises(RuntimeError):
        run_ladder(
            [("a", oom), ("b", lambda: "b")],
            ResiliencePolicy(degrade=False, sleep=lambda s: None),
        )


def test_ladder_undegraded_run_sets_no_meta():
    out, degraded = run_ladder([("a", lambda: 1)], NOSLEEP)
    assert (out, degraded) == (1, None)
    assert telemetry.get_meta("degraded_to") is None
    assert telemetry.counter_get("degradations_total") == 0.0


# -- acceptance: injected sharded OOM completes bit-identically ------------


def _ulp_diff(a, b) -> float:
    a, b = np.asarray(a), np.asarray(b)
    if a.dtype == bool:
        return 0.0 if (a == b).all() else np.inf
    a64, b64 = a.astype(np.float64), b.astype(np.float64)
    same = (a64 == b64) | (np.isinf(a64) & np.isinf(b64)
                           & (np.sign(a64) == np.sign(b64)))
    sp = np.spacing(
        np.maximum(np.abs(a), np.abs(b)).astype(np.float32)
    ).astype(np.float64)
    with np.errstate(invalid="ignore"):  # inf - inf on the `same` mask
        diff = np.abs(a64 - b64) / np.where(sp > 0, sp, 1.0)
    return float(np.max(np.where(same, 0.0, diff)))


@pytest.mark.slow
def test_sharded_gather_oom_degrades_to_identical_results():
    """ISSUE acceptance: OOM injected at sharded.gather -> the ladder
    completes the run on the single-device rung with every summary
    field within 1 f32 ULP of the clean sharded run, and the
    degradation is counted in the Prometheus exposition."""
    compiled = compile_graph(ServiceGraph.from_yaml(FORK))
    sharded = ShardedSimulator(compiled, make_mesh(4, 2))
    n = 8192
    clean = sharded.run(OPEN, n, KEY, block_size=1024, trim=True)
    jax.block_until_ready(clean.count)

    telemetry.reset()
    faults.install("oom:sharded.gather:2")  # rung 0 AND half-block fail
    rungs = execution_rungs(
        sharded.sim, sharded, True, OPEN, n, KEY, 1024, trim=True
    )
    summary, degraded = run_ladder(rungs, NOSLEEP, site_prefix="engine")
    assert degraded == "single-device"
    assert telemetry.counter_get("degradations_total") >= 1.0
    prom = telemetry.prometheus_text()
    line = next(
        ln for ln in prom.splitlines()
        if ln.startswith("isotope_engine_degradations_total")
    )
    assert float(line.split()[-1]) >= 1.0

    clean_leaves = jtu.tree_flatten_with_path(clean)[0]
    got_leaves = jtu.tree_flatten_with_path(summary)[0]
    assert len(clean_leaves) == len(got_leaves)
    for (path, want), (_, got) in zip(clean_leaves, got_leaves):
        assert _ulp_diff(want, got) <= 1.0, jtu.keystr(path)


def test_transient_compute_fault_retries_to_identical_results():
    compiled = compile_graph(ServiceGraph.from_yaml(CHAIN))
    sharded = ShardedSimulator(compiled, make_mesh(4, 2))
    n = 4096
    clean = sharded.run(OPEN, n, KEY, block_size=1024)
    jax.block_until_ready(clean.count)
    faults.install("transient:sharded.compute:1")
    rungs = execution_rungs(
        sharded.sim, sharded, True, OPEN, n, KEY, 1024, trim=False
    )
    summary, degraded = run_ladder(rungs, NOSLEEP)
    assert degraded is None
    assert telemetry.counter_get("retries_total") == 1.0
    for (path, want), (_, got) in zip(
        jtu.tree_flatten_with_path(clean)[0],
        jtu.tree_flatten_with_path(summary)[0],
    ):
        assert _ulp_diff(want, got) == 0.0, jtu.keystr(path)


def test_single_device_ladder_halves_block():
    sim = Simulator(compile_graph(ServiceGraph.from_yaml(CHAIN)))
    faults.install("oom:engine.run:1")
    rungs = execution_rungs(sim, None, False, OPEN, 2048, KEY, 1024)
    summary, degraded = run_ladder(rungs, NOSLEEP)
    assert degraded == "half-block"
    assert float(summary.count) >= 2048


# -- zero added sync points on the default path ----------------------------


def test_no_fault_path_adds_zero_sync_points(monkeypatch):
    """The fault hooks and supervisor plumbing must not fence the
    engine's default dispatch (the PR-2 contract extends to PR 3)."""
    sim = Simulator(compile_graph(ServiceGraph.from_yaml(CHAIN)))
    calls = {"n": 0}
    orig = jax.block_until_ready

    def counting(x):
        calls["n"] += 1
        return orig(x)

    monkeypatch.setattr(jax, "block_until_ready", counting)
    res = sim.run(OPEN, 64, KEY)
    assert calls["n"] == 0, "default path must not fence"
    monkeypatch.undo()
    assert int(res.hop_events) == 64 * 3


# -- numeric sentinels -----------------------------------------------------


def test_nan_injection_trips_summary_sentinel():
    faults.install("nan:segment:0")
    sim = Simulator(compile_graph(ServiceGraph.from_yaml(CHAIN)))
    summary = sim.run_summary(OPEN, 512, KEY, block_size=256)
    with pytest.raises(NumericSentinelError, match="NaN"):
        sentinels.check_summary(summary)
    assert telemetry.counter_get("numeric_sentinel_violations") >= 1.0


def test_nan_localized_per_segment_in_detail_mode():
    faults.install("nan:segment:0")
    telemetry.enable(detail=True)
    sim = Simulator(compile_graph(ServiceGraph.from_yaml(CHAIN)))
    sim.run(OPEN, 64, KEY)
    snap = telemetry.snapshot()
    hits = [
        k for k in snap.gauges
        if k.startswith("numeric_sentinel{") and "segment=" in k
    ]
    assert hits, "detail mode must pin the offending segment"


def test_clean_run_passes_sentinels():
    sim = Simulator(compile_graph(ServiceGraph.from_yaml(CHAIN)))
    sentinels.check_summary(sim.run_summary(OPEN, 512, KEY,
                                            block_size=256))
    sentinels.check_results(sim.run(OPEN, 64, KEY))
    assert telemetry.counter_get("numeric_sentinel_violations") == 0.0


def test_nan_poisoned_trace_never_shares_executables():
    """The fault plan participates in the engine signature: a poisoned
    program must not be served from (or pollute) the clean cache."""
    sim_clean = Simulator(compile_graph(ServiceGraph.from_yaml(CHAIN)))
    faults.install("nan:segment:0")
    sim_bad = Simulator(compile_graph(ServiceGraph.from_yaml(CHAIN)))
    assert sim_clean.signature != sim_bad.signature
    faults.clear()
    res = sim_clean.run(OPEN, 64, KEY)
    assert not np.isnan(np.asarray(res.client_latency)).any()


# -- compile-cache quarantine ----------------------------------------------


def test_scan_quarantines_corrupted_entries(tmp_path):
    d = tmp_path / "cache"
    d.mkdir()
    (d / "jit_good").write_bytes(b"compiled-bytes-1")
    (d / "jit_bad").write_bytes(b"compiled-bytes-2")
    (d / "jit_empty").write_bytes(b"")
    # first scan: the empty entry quarantines, digests recorded
    stats = compile_cache.scan_cache_dir(str(d))
    assert stats["quarantined"] == ["jit_empty"]
    assert stats["recorded"] == 2
    # corrupt one entry between runs (bit rot / torn write)
    (d / "jit_bad").write_bytes(b"compiled-bytes-CORRUPTED")
    stats = compile_cache.scan_cache_dir(str(d))
    assert stats["quarantined"] == ["jit_bad"]
    assert (d / "quarantine" / "jit_bad").exists()
    assert not (d / "jit_bad").exists()
    # the intact entry survives both scans
    assert (d / "jit_good").read_bytes() == b"compiled-bytes-1"
    assert telemetry.counter_get("compile_cache_quarantined") == 2.0
    sidecar = json.loads(
        (d / compile_cache.DIGEST_SIDECAR).read_text()
    )
    assert set(sidecar) == {"jit_good"}


def test_scan_tolerates_corrupt_sidecar(tmp_path):
    d = tmp_path / "cache"
    d.mkdir()
    (d / "jit_x").write_bytes(b"abc")
    (d / compile_cache.DIGEST_SIDECAR).write_text("{not json")
    stats = compile_cache.scan_cache_dir(str(d))
    assert stats["quarantined"] == []
    assert stats["recorded"] == 1


def test_corrupt_cache_load_evicts_and_retraces():
    faults.install("corrupt:cache.load:1")
    built = {"n": 0}

    def build():
        built["n"] += 1
        return "executable"

    out = compile_cache.executable_cache.get_or_build(
        ("resilience-corrupt-probe", KEY.tolist()[0]), build
    )
    assert out == "executable"
    assert built["n"] == 1  # the injected corruption fired pre-build
    assert telemetry.counter_get(
        "compile_cache_quarantine_retries"
    ) == 1.0


def test_non_corruption_build_errors_propagate():
    def build():
        raise ValueError("real bug")

    with pytest.raises(ValueError, match="real bug"):
        compile_cache.executable_cache.get_or_build(
            ("resilience-bug-probe",), build
        )


# -- runner integration: failed case recorded, sweep continues -------------

TOPO = (
    pathlib.Path(__file__).parent.parent
    / "examples/topologies/canonical.yaml"
)


def _config(tmp_path):
    from isotope_tpu.runner import load_toml

    cfg = tmp_path / "exp.toml"
    cfg.write_text(
        f"""
topology_paths = ["{TOPO}"]
environments = ["NONE"]

[client]
qps = [200, 400]
num_concurrent_connections = [8]
duration = "30s"
load_kind = "open"

[sim]
num_requests = 1500
seed = 7
"""
    )
    return load_toml(cfg)


def test_numeric_failure_fails_case_but_not_sweep(tmp_path):
    from isotope_tpu.runner.run import run_experiment

    faults.install("nan:segment:0")
    results = run_experiment(
        _config(tmp_path), out_dir=str(tmp_path / "out"),
        policy=NOSLEEP,
    )
    faults.clear()
    assert len(results) == 2
    assert all(r.failed for r in results)
    assert all("sentinel" in (r.error or "") for r in results)
    ckpt = (tmp_path / "out" / "checkpoint.jsonl").read_text()
    recs = [json.loads(ln) for ln in ckpt.splitlines()[1:]]
    assert all(r["failed"] for r in recs)
    assert all(r["error_class"] == DETERMINISTIC for r in recs)
    # the failed sweep's CSV has no data rows (header only)
    csv = (tmp_path / "out" / "benchmark.csv").read_text().splitlines()
    assert len(csv) == 1

    # resume with the fault gone: both cases retry and complete
    ran = []
    results = run_experiment(
        _config(tmp_path), out_dir=str(tmp_path / "out"),
        progress=ran.append, policy=NOSLEEP,
    )
    assert len(ran) == 2
    assert not any(r.failed for r in results)
