"""Fleet observability (ISSUE 17): blame + timelines over the member axis.

The pins the feature's contract rests on:

- member k of an ATTRIBUTED fleet carries the bit-identical
  ``AttributionSummary`` (and ``TimelineSummary``) of the solo
  ``run_attributed`` / ``run_timeline`` with ``fold_in(key, seeds[k])``
  — open and closed loop, plain and protected fleets;
- ``attribution``/``timeline`` off leaves the fleet byte-identical to
  the pre-observability program (no silent cost on the default path);
- member-chunked observed dispatches == the unchunked fleet;
- the sharded observed fleet == its emulated host-loop twin == the
  single-device engine, bit-for-bit;
- the divergence explainer (metrics/fleetblame.py) names a PLANTED bad
  member's service and onset window from the stacked evidence alone;
- VET-M006 prices the stacked blame/timeline carry into the chunk
  plan before dispatch;
- the runner writes ``<label>.fleet-blame.json`` + stamped
  worst-member postmortems, and ``isotope-tpu explain`` renders them
  without re-running anything.
"""
import json

import jax
import jax.tree_util as jtu
import numpy as np
import pytest

from isotope_tpu.compiler import compile_graph, compile_policies
from isotope_tpu.metrics import fleetblame
from isotope_tpu.models.graph import ServiceGraph
from isotope_tpu.sim import LoadModel, SimParams
from isotope_tpu.sim.config import ChaosEvent
from isotope_tpu.sim.engine import Simulator
from isotope_tpu.sim.ensemble import EnsembleSpec

YAML = """
defaults:
  responseSize: 1 KiB
services:
- name: entry
  isEntrypoint: true
  errorRate: 1%
  script:
  - - call: x
    - call: y
  - call: z
- name: x
  numReplicas: 2
- name: y
  script:
  - call: z
- name: z
"""

OPEN = LoadModel(kind="open", qps=2000.0)
CLOSED = LoadModel(kind="closed", qps=None, connections=8)
KEY = jax.random.PRNGKey(7)
N, BLOCK = 512, 256  # two blocks: the scan carry is exercised
WIN = 0.05


def _leaves_equal(a, b):
    la, lb = jtu.tree_leaves(a), jtu.tree_leaves(b)
    assert len(la) == len(lb)
    return all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(la, lb)
    )


@pytest.fixture(scope="module")
def compiled():
    return compile_graph(ServiceGraph.from_yaml(YAML))


@pytest.fixture(scope="module")
def asim(compiled):
    """Simulator with both observers armed (params gate the carry)."""
    return Simulator(
        compiled,
        SimParams(attribution=True, attribution_top_k=4,
                  timeline=True),
    )


@pytest.fixture(scope="module")
def obs4(asim):
    """The canonical observed fleet: 4 members, blame + recorder."""
    return asim.run_ensemble(
        OPEN, N, KEY, EnsembleSpec.of(4), block_size=BLOCK,
        attribution=True, timeline=True, window_s=WIN,
    )


# -- off == byte-identical ---------------------------------------------


def test_observability_off_is_byte_identical(asim, obs4):
    base = asim.run_ensemble(
        OPEN, N, KEY, EnsembleSpec.of(4), block_size=BLOCK
    )
    assert base.attributions is None and base.timelines is None
    assert _leaves_equal(base.summaries, obs4.summaries)


def test_attribution_needs_armed_params(compiled):
    plain = Simulator(compiled)
    with pytest.raises(ValueError, match="attribution"):
        plain.run_ensemble(
            OPEN, N, KEY, EnsembleSpec.of(2), block_size=BLOCK,
            attribution=True,
        )


# -- member k == solo, bit for bit -------------------------------------


def test_member_k_blame_bit_equals_solo_open(asim, obs4):
    k = 2
    mkey = jax.random.fold_in(KEY, EnsembleSpec.of(4).seeds[k])
    _, solo = asim.run_attributed(OPEN, N, mkey, block_size=BLOCK)
    assert _leaves_equal(solo, obs4.member_attribution(k))
    _, solo_tl = asim.run_timeline(
        OPEN, N, mkey, block_size=BLOCK, window_s=WIN
    )
    assert _leaves_equal(solo_tl, obs4.member_timeline(k))


@pytest.mark.slow
@pytest.mark.slow
def test_member_k_blame_bit_equals_solo_closed(asim):
    fleet = asim.run_ensemble(
        CLOSED, N, KEY, EnsembleSpec.of(3), block_size=BLOCK,
        attribution=True,
    )
    k = 1
    mkey = jax.random.fold_in(KEY, 1)
    _, solo = asim.run_attributed(CLOSED, N, mkey, block_size=BLOCK)
    assert _leaves_equal(solo, fleet.member_attribution(k))


def test_chunked_observed_equals_unchunked(asim, obs4):
    chunked = asim.run_ensemble(
        OPEN, N, KEY, EnsembleSpec.of(4), block_size=BLOCK,
        attribution=True, timeline=True, window_s=WIN, chunk=3,
    )
    assert chunked.chunk == 3
    assert _leaves_equal(obs4.attributions, chunked.attributions)
    assert _leaves_equal(obs4.timelines, chunked.timelines)


@pytest.mark.slow
@pytest.mark.slow
def test_tail_mode_fleet_equals_solo(asim):
    cut = 0.012
    fleet = asim.run_ensemble(
        OPEN, N, KEY, EnsembleSpec.of(3), block_size=BLOCK,
        attribution=True, tail=True, tail_cut=cut,
    )
    k = 0
    mkey = jax.random.fold_in(KEY, 0)
    _, solo = asim.run_attributed(
        OPEN, N, mkey, block_size=BLOCK, tail=True, tail_cut=cut
    )
    assert _leaves_equal(solo, fleet.member_attribution(k))


# -- sharded == emulated twin == engine --------------------------------


@pytest.mark.slow
def test_sharded_observed_fleet_bit_equal(compiled, asim, obs4):
    from isotope_tpu.parallel import (
        MeshSpec,
        ShardedSimulator,
        build_mesh,
    )

    sh = ShardedSimulator(
        compiled, build_mesh(MeshSpec(data=2, svc=2)), asim.params
    )
    kw = dict(block_size=BLOCK, attribution=True, timeline=True,
              window_s=WIN)
    mesh_out = sh.run_ensemble(OPEN, N, KEY, EnsembleSpec.of(4), **kw)
    emu = sh.run_ensemble_emulated(
        OPEN, N, KEY, EnsembleSpec.of(4), **kw
    )
    assert _leaves_equal(mesh_out.summaries, emu.summaries)
    assert _leaves_equal(mesh_out.attributions, emu.attributions)
    assert _leaves_equal(mesh_out.timelines, emu.timelines)
    # and both == the single-device engine fleet
    assert _leaves_equal(mesh_out.summaries, obs4.summaries)
    assert _leaves_equal(mesh_out.attributions, obs4.attributions)
    assert _leaves_equal(mesh_out.timelines, obs4.timelines)


# -- protected fleets ---------------------------------------------------


STORM = """
services:
- name: entry
  isEntrypoint: true
  numReplicas: 4
  script:
  - call: {service: worker, timeout: 850us, retries: 2}
- name: worker
  numReplicas: 4
  errorRate: 0.5%
policies:
  defaults:
    retry_budget: {budget_percent: 25%}
  worker:
    breaker: {max_pending: 6, max_connections: 64,
              consecutive_errors: 5, base_ejection: 2s}
    autoscaler: {min_replicas: 2, max_replicas: 8,
                 target_utilization: 60%, sync_period: 1s,
                 stabilization_window: 3s}
"""


@pytest.mark.slow
@pytest.mark.slow
def test_protected_fleet_blame_bit_equals_solo():
    g = ServiceGraph.from_yaml(STORM)
    compiled = compile_graph(g)
    pol = compile_policies(g, compiled)
    chaos = (ChaosEvent("worker", 0.1, 0.3, replicas_down=3),)
    psim = Simulator(
        compiled,
        SimParams(timeline=True, attribution=True),
        chaos=chaos, policies=pol,
    )
    kw = dict(block_size=1_024, trim=True, window_s=0.25)
    spec = EnsembleSpec.of(3, mode="map")
    base = psim.run_policies_ensemble(OPEN, 2_048, KEY, spec, **kw)
    obs = psim.run_policies_ensemble(
        OPEN, 2_048, KEY, spec, attribution=True, **kw
    )
    # arming blame leaves the protected fleet's physics untouched
    assert base.attributions is None
    assert _leaves_equal(base.summaries, obs.summaries)
    assert _leaves_equal(base.policies, obs.policies)
    # member k == the solo attributed protected run
    k = 1
    mkey = jax.random.fold_in(KEY, spec.seeds[k])
    _, solo_tl, _, solo_attr = psim.run_policies(
        OPEN, 2_048, mkey, attribution=True, **kw
    )
    assert _leaves_equal(solo_attr, obs.member_attribution(k))
    assert _leaves_equal(solo_tl, obs.member_timeline(k))


# -- the divergence explainer ------------------------------------------


BLAME_YAML = """
services:
- name: entry
  isEntrypoint: true
  script:
  - call: worker
- name: worker
  numReplicas: 4
- name: cold
  numReplicas: 2
"""


@pytest.fixture(scope="module")
def planted():
    """A fleet with a PLANTED bad member: member 2 loses 3/4 worker
    replicas from t=0.3s while everyone else loses 1 — the divergence
    the explainer must localize (service AND onset window)."""
    compiled = compile_graph(ServiceGraph.from_yaml(BLAME_YAML))
    mild = (ChaosEvent("worker", 0.3, 1.0, replicas_down=1),)
    sim = Simulator(
        compiled,
        SimParams(attribution=True, timeline=True),
        chaos=mild,
    )
    events = [mild, mild,
              (ChaosEvent("worker", 0.3, 1.0, replicas_down=3),),
              mild]
    spec = EnsembleSpec.of(4)
    obs = sim.run_ensemble(
        LoadModel(kind="open", qps=4000.0), 4_096, KEY, spec,
        block_size=1_024, attribution=True, timeline=True,
        window_s=0.1, member_chaos=events,
    )
    doc = fleetblame.to_doc(
        compiled, obs.attributions, obs.timelines, label="planted",
        seeds=spec.seeds,
        window_s=float(np.asarray(obs.timelines.window_s).reshape(-1)[0]),
    )
    return obs, doc


def test_explainer_names_planted_member_hop_and_onset(planted):
    _, doc = planted
    assert doc["schema"] == "isotope-fleet-blame/v1"
    worst = doc["ranking"][0]
    assert worst == 2
    m = [e for e in doc["member_blame"] if e["member"] == worst][0]
    # the hop: worker queueing is where the lost capacity bites
    assert m["gap_ranking"][0]["service"] == "worker"
    # the onset: the kill lands at 0.3s; 0.1s windows -> window ~3
    assert m["onset"] is not None
    assert m["onset"]["service"] == "worker"
    assert 2 <= m["onset"]["window"] <= 5
    assert m["onset"]["time_s"] == pytest.approx(
        m["onset"]["window"] * 0.1
    )
    # doc is a JSON artifact
    json.dumps(doc)


def test_explainer_report_and_worst_members(planted):
    _, doc = planted
    worst = fleetblame.worst_members(doc, top=2)
    assert worst[0]["member"] == 2
    assert all(not m["control"] for m in worst)
    report = fleetblame.format_report(doc)
    assert "member 2" in report
    assert "worker" in report
    assert "onset" in report
    # bands cover every surfaced hop
    hops = {b["hop"] for b in doc["hop_bands"]}
    for m in doc["member_blame"]:
        for r in m["top_hops"] + m["gap_ranking"]:
            assert r["hop"] in hops


def test_explain_fleet_single_readback(planted):
    obs, _ = planted
    host = fleetblame.explain_fleet(obs.attributions, obs.timelines)
    assert isinstance(host["share"], np.ndarray)
    assert host["share"].shape[0] == 4
    # share rows are distributions over hops
    np.testing.assert_allclose(host["share"].sum(axis=1), 1.0,
                               atol=1e-5)
    assert host["onset_errors"].shape == host["onset_inflight"].shape


# -- VET-M006: the observed-fleet carry is priced before dispatch -------


def test_vet_m006_observed_carry_findings():
    from isotope_tpu.analysis import costmodel

    est = costmodel.CostEstimate(
        block_requests=256, trace_requests=8, jaxpr=None,
        peak_bytes_at_block=1e6, flops_at_block=1.0, critical_path=1,
        segments=[], capacity_bytes=4e6,
    )
    # a fat observability carry forces a tighter chunk than the plain
    # fleet would need -> WARN with the carry-aware chunk
    findings = costmodel.observed_ensemble_findings(
        est, members=64, obs_carry_bytes=200_000.0
    )
    assert [f.rule for f in findings] == ["VET-M006"]
    assert "chunk" in findings[0].message
    # no observability carry -> silent
    assert costmodel.observed_ensemble_findings(
        est, members=64, obs_carry_bytes=0.0
    ) == []


def test_vet_m006_fires_on_over_capacity_observed_fleet(monkeypatch):
    from isotope_tpu.analysis import costmodel, vet_simulator

    monkeypatch.setenv(costmodel.ENV_DEVICE_BYTES, "200000")
    compiled = compile_graph(ServiceGraph.from_yaml(YAML))
    sim = Simulator(
        compiled,
        SimParams(attribution=True, attribution_top_k=4,
                  timeline=True),
    )
    report = vet_simulator(
        sim, OPEN, block_requests=256, trace=False,
        ensemble=EnsembleSpec.of(64),
    )
    rules = {f.rule for f in report.findings}
    assert "VET-M006" in rules
    # the chunk plan accounts the stacked observer carry
    plain = Simulator(compiled)
    base = vet_simulator(
        plain, OPEN, block_requests=256, trace=False,
        ensemble=EnsembleSpec.of(64),
    )
    assert "VET-M006" not in {f.rule for f in base.findings}
    assert (report.meta["ensemble"]["chunk"]
            <= base.meta["ensemble"]["chunk"])


# -- runner + explain subcommand ---------------------------------------


@pytest.mark.slow
def test_runner_fleet_blame_artifacts_and_explain(tmp_path):
    from isotope_tpu.commands.explain_cmd import run_explain_cmd
    from isotope_tpu.runner.config import (
        DEFAULT_ENVIRONMENTS,
        ExperimentConfig,
    )
    from isotope_tpu.runner.run import run_experiment

    topo = tmp_path / "t.yaml"
    topo.write_text(YAML)
    cfg = ExperimentConfig(
        topology_paths=(str(topo),),
        environments=(DEFAULT_ENVIRONMENTS["NONE"],),
        qps=(500.0,), connections=(8,), duration_s=2.0,
        load_kind="open", num_requests=256,
        ensemble=3, attribution=True, timeline=True,
    )
    out = tmp_path / "out"
    (res,) = run_experiment(
        cfg, out_dir=str(out), attribution="on", timeline=0.25
    )
    assert not res.failed, res.error
    assert res.flat.get("_fleet_blame") is True
    fb = json.loads(
        (out / f"{res.label}.fleet-blame.json").read_text()
    )
    assert fb["schema"] == "isotope-fleet-blame/v1"
    assert fb["members"] == 3
    assert res.fleet_blame["members"] == 3
    # worst-member postmortems carry the replay stamp
    blame = json.loads((out / f"{res.label}.blame.json").read_text())
    assert blame["worst_member"] is True
    assert blame["fleet_members"] == 3
    worst = int(blame["member"])
    assert blame["member_seed"] == int(
        res.ensemble_summary.spec.seeds[worst]
    )
    tl = json.loads((out / f"{res.label}.timeline.json").read_text())
    assert tl["worst_member"] is True and tl["member"] == worst
    # the worst member's fleet blame replays bit-equal solo
    seed_key = jax.random.PRNGKey(cfg.seed)
    mkey = jax.random.fold_in(
        jax.random.fold_in(seed_key, 0),
        int(res.ensemble_summary.spec.seeds[worst]),
    )
    sim = Simulator(
        compile_graph(ServiceGraph.from_yaml(YAML)),
        cfg.sim_params(),
    )
    load = LoadModel(kind="open", qps=500.0, connections=8,
                     duration_s=2.0)
    _, solo = sim.run_attributed(
        load, 256, mkey, block_size=sim.default_block_size(),
        trim=True,
    )
    assert _leaves_equal(
        solo, res.ensemble_summary.member_attribution(worst)
    )

    # explain renders the why-report from the artifacts alone
    class Args:
        path = str(out)
        label = None
        top = 3
        hops = 3
        json = False

    assert run_explain_cmd(Args()) == 0


def test_explain_cmd_narrates_search_doc(tmp_path, capsys):
    from isotope_tpu.commands.explain_cmd import run_explain_cmd

    doc = {
        "schema": "isotope-search/v1",
        "label": "t", "rank": "err_peak",
        "rank_effective": "err_share", "eta": 4, "growth": 2,
        "candidates": 4, "block": 256, "traces": 2, "mode": "map",
        "winner": {"candidate": 3, "severity": 0.01},
        "lineage": [
            {
                "rung": 0, "width": 4, "chunk": 4, "start_block": 0,
                "num_blocks": 1, "cum_requests": 1024,
                "candidates": [0, 1, 2, 3],
                "severity": [0.4, 0.3, 0.2, 0.1],
                "survivors": [3],
                "cut": {
                    "kept": 1,
                    "last_kept": {"candidate": 3, "severity": 0.1},
                    "first_cut": {"candidate": 2, "severity": 0.2},
                    "margin": 0.1,
                },
                "evidence": {"traces": 1, "compile_s": 0.5,
                             "rank_order": [3, 2, 1, 0]},
            },
        ],
        "spec": {},
    }
    p = tmp_path / "t.search.json"
    p.write_text(json.dumps(doc))

    class Args:
        path = str(p)
        label = None
        top = 3
        hops = 3
        json = False

    assert run_explain_cmd(Args()) == 0
    text = capsys.readouterr().out
    assert "winner 3" in text
    assert "beat runner-up 2" in text
    assert "margin 0.1" in text
    assert "compile 0.50s" in text


def test_search_lineage_carries_rung_evidence(compiled):
    from isotope_tpu.sim.search import SearchSpec

    sim = Simulator(compiled)
    spec = SearchSpec(
        candidates=EnsembleSpec.from_jitter(8, qps_jitter=0.2),
        eta=4, rungs=2,
    )
    summ = sim.run_search(OPEN, N, KEY, spec, block_size=BLOCK)
    doc = summ.to_doc("evidence")
    assert sum(
        r["evidence"]["traces"] for r in doc["lineage"]
    ) == doc["traces"]
    for r in doc["lineage"]:
        assert r["evidence"]["compile_s"] >= 0.0
        assert len(r["evidence"]["rank_order"]) == r["width"]
        cut = r["cut"]
        assert cut["last_kept"]["candidate"] in r["survivors"]
        if "first_cut" in cut:
            assert cut["first_cut"]["candidate"] not in r["survivors"]
            assert cut["margin"] >= 0.0
    json.dumps(doc)
