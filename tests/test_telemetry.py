"""Engine self-telemetry (isotope_tpu/telemetry/).

Pins the contracts the tentpole depends on: phase timers nest and sum,
counters are recorded host-side (once per TRACE, surviving the jit
boundary), cache hit/miss counts mirror the executable cache, the
Prometheus exposition parses, telemetry.jsonl round-trips, and —
critically — telemetry-off mode adds ZERO sync points to the engine's
default path (asserted via a fence-counter monkeypatch), while detail
mode fences at segment granularity.
"""
import json
import re
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from isotope_tpu import telemetry
from isotope_tpu.compiler import buckets, compile_graph
from isotope_tpu.compiler.cache import cache_stats, executable_cache
from isotope_tpu.models.graph import ServiceGraph
from isotope_tpu.sim import LoadModel, SimParams, Simulator

CHAIN = """
services:
- name: a
  isEntrypoint: true
  script:
  - call: b
- name: b
  script:
  - call: c
- name: c
"""

OPEN = LoadModel(kind="open", qps=100.0)
KEY = jax.random.PRNGKey(0)


@pytest.fixture(autouse=True)
def clean_registry():
    """Fresh registry per test; restore the off/off default after."""
    telemetry.reset()
    telemetry.disable()
    yield
    telemetry.reset()
    telemetry.disable()


def _sim(params=SimParams()):
    return Simulator(compile_graph(ServiceGraph.from_yaml(CHAIN)), params)


# -- phase timers ----------------------------------------------------------

def test_phase_timers_nest_and_sum():
    with telemetry.phase("outer"):
        with telemetry.phase("inner"):
            time.sleep(0.02)
        with telemetry.phase("inner"):  # re-entry accumulates
            time.sleep(0.02)
    assert telemetry.phase_seconds("inner") >= 0.04
    # the enclosing phase's clock includes its children's
    assert telemetry.phase_seconds("outer") >= telemetry.phase_seconds(
        "inner"
    )
    # phases are independent accumulators, not a consuming hierarchy
    with telemetry.phase("outer"):
        pass
    assert telemetry.phase_seconds("outer") >= 0.04


def test_phase_records_on_exception():
    with pytest.raises(RuntimeError):
        with telemetry.phase("boom"):
            time.sleep(0.01)
            raise RuntimeError()
    assert telemetry.phase_seconds("boom") >= 0.01


# -- counters across the jit boundary --------------------------------------

def test_counters_recorded_host_side_not_traced():
    """A counter bumped inside a jitted body counts TRACES, not calls."""

    @jax.jit
    def f(x):
        telemetry.counter_inc("traced_bodies")
        return x * 2.0

    for i in range(3):
        f(jnp.float32(i)).block_until_ready()
    assert telemetry.counter_get("traced_bodies") == 1.0


def test_engine_trace_and_retrace_detection():
    telemetry.record_trace(("sig", 1), tracing=True, requests=64, hops=3)
    telemetry.record_trace(("sig", 2), tracing=True, requests=64, hops=3)
    assert telemetry.counter_get("engine_traces") == 2.0
    assert telemetry.counter_get("engine_retraces") == 0.0
    telemetry.record_trace(("sig", 1), tracing=True, requests=64, hops=3)
    assert telemetry.counter_get("engine_retraces") == 1.0
    # eager (detail-mode) executions count separately, never as retraces
    telemetry.record_trace(("sig", 1), tracing=False, requests=64, hops=3)
    assert telemetry.counter_get("engine_retraces") == 1.0
    assert telemetry.counter_get("engine_eager_calls") == 1.0
    assert telemetry.gauge_get("engine_last_requests") == 64.0


# -- cache hit/miss parity with the executable cache -----------------------

def test_cache_counters_match_executable_cache():
    """The telemetry counters move in lockstep with the cache's own
    hit/miss counts under the test_compile_cache.py sharing scenario:
    two identical Simulators share one executable (1 hit), a different
    request shape misses."""
    h0 = telemetry.counter_get("executable_cache_hits")
    m0 = telemetry.counter_get("executable_cache_misses")
    ch0, cm0 = executable_cache.hits, executable_cache.misses
    s1, s2 = _sim(), _sim()
    assert s1._get(48, "open") is s2._get(48, "open")   # miss then hit
    s2._get(96, "open")                                 # second miss
    dh = telemetry.counter_get("executable_cache_hits") - h0
    dm = telemetry.counter_get("executable_cache_misses") - m0
    assert dh == executable_cache.hits - ch0 == 1
    assert dm == executable_cache.misses - cm0 == 2


def test_cache_stats_introspection():
    st0 = cache_stats()
    _sim()._get(52, "open")
    st = cache_stats()
    assert st["misses"] == st0["misses"] + 1
    assert st["entries"] == len(executable_cache)
    assert len(st["keys"]) == st["entries"]
    assert all(re.fullmatch(r"[0-9a-f]{12}", k) for k in st["keys"])
    # reset hook zeroes counters without dropping entries
    executable_cache.reset_stats()
    st2 = cache_stats()
    assert st2["hits"] == st2["misses"] == st2["evictions"] == 0
    assert st2["entries"] == st["entries"]


def test_cache_miss_logs_debug_summary(caplog):
    import logging

    with caplog.at_level(logging.DEBUG, logger="isotope_tpu.compiler.cache"):
        executable_cache.get_or_build(
            ("telemetry-log-probe", time.time()), lambda: object()
        )
    assert any("executable-cache miss" in r.message for r in caplog.records)


# -- bucket-plan accounting ------------------------------------------------

def test_bucket_plan_stats_recorded():
    shapes = [
        buckets.LevelShape(size=4, pmax=2, children=4, calls=4,
                           attempts=1, sparse=False, offset=0),
        buckets.LevelShape(size=2, pmax=2, children=2, calls=2,
                           attempts=1, sparse=False, offset=4),
        buckets.LevelShape(size=2, pmax=1, children=0, calls=0,
                           attempts=1, sparse=False, offset=6),
    ]
    segs = buckets.plan_segments(shapes, waste=4.0)
    st = buckets.plan_stats(shapes, segs)
    assert st["num_buckets"] == 1 and st["levels_bucketed"] == 2
    assert st["padded_elems"] > st["real_elems"] > 0
    assert 0.0 < st["padding_waste_fraction"] < 1.0
    assert telemetry.counter_get("buckets_formed") >= 1.0
    assert telemetry.counter_get("bucket_padded_elems") >= st[
        "padded_elems"
    ]
    assert telemetry.gauge_get("bucket_padding_waste_fraction") == (
        pytest.approx(st["padding_waste_fraction"])
    )


# -- zero sync points with telemetry off -----------------------------------

def test_off_mode_adds_zero_sync_points(monkeypatch):
    sim = _sim()
    calls = {"n": 0}
    orig = jax.block_until_ready

    def counting(x):
        calls["n"] += 1
        return orig(x)

    monkeypatch.setattr(jax, "block_until_ready", counting)
    res = sim.run(OPEN, 64, KEY)
    assert calls["n"] == 0, "default path must not fence"
    assert telemetry.counter_get("engine_fences") == 0.0
    monkeypatch.undo()
    assert int(res.hop_events) == 64 * 3


def test_detail_mode_fences_per_segment():
    sim = _sim()
    telemetry.enable(detail=True)
    res = sim.run(OPEN, 64, KEY)
    assert telemetry.counter_get("engine_fences") > 0.0
    seg_phases = [
        k for k in telemetry.snapshot().phases if k.startswith("segment.")
    ]
    assert seg_phases, "detail mode must record per-segment phases"
    # eager execution, exact same results contract
    assert int(res.hop_events) == 64 * 3


# -- first-call compile timing ---------------------------------------------

def test_first_call_phase_timer():
    before = telemetry.counter_get("jit_first_calls")
    sim = Simulator(
        compile_graph(ServiceGraph.from_yaml(CHAIN)),
        SimParams(cpu_time_s=1.0 / 7_777.0),  # fresh program
    )
    sim.run(OPEN, 40, KEY)
    assert telemetry.counter_get("jit_first_calls") == before + 1
    assert telemetry.phase_seconds("compile.jit_first_call") > 0.0


# -- exposition ------------------------------------------------------------

PROM_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? [0-9.eE+-]+(\s|$)"
)


def test_prometheus_exposition_parses():
    telemetry.counter_inc("probe_events", 3)
    telemetry.gauge_set("probe_gauge", 1.5)
    telemetry.gauge_set("probe_labeled", 2.0, device="0")
    with telemetry.phase("probe.phase"):
        pass
    text = telemetry.prometheus_text()
    assert text.endswith("\n")
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        assert PROM_LINE.match(line), f"unparseable line: {line!r}"
    assert 'isotope_engine_events_total{event="probe_events"} 3' in text
    assert "isotope_engine_probe_gauge 1.5" in text
    assert 'isotope_engine_probe_labeled{device="0"} 2' in text
    assert (
        'isotope_engine_phase_seconds_total{phase="probe.phase"}' in text
    )


# -- JSONL round trip ------------------------------------------------------

def test_run_telemetry_jsonl_round_trip(tmp_path):
    telemetry.counter_inc("x", 2)
    telemetry.gauge_set("g", 0.5, device="1")
    telemetry.phase_add("p", 1.25)
    rec = telemetry.snapshot(label="roundtrip")
    line = rec.to_json_line()
    back = telemetry.RunTelemetry.from_dict(json.loads(line))
    assert back.to_dict() == rec.to_dict()
    path = tmp_path / "telemetry.jsonl"
    rec.append_jsonl(path)
    rec.append_jsonl(path)
    assert telemetry.validate_jsonl(path) == 2


def test_jsonl_tolerates_crash_torn_final_line(tmp_path):
    # a SIGKILL mid-append leaves half a record with no newline: both
    # readers must skip-and-count it, keeping the killed run's
    # telemetry readable
    p = tmp_path / "telemetry.jsonl"
    telemetry.counter_inc("x", 1)
    rec = telemetry.snapshot(label="kept")
    rec.append_jsonl(p)
    rec.append_jsonl(p)
    with open(p, "a") as f:
        f.write(rec.to_json_line()[: 40])  # torn tail, no newline
    assert telemetry.validate_jsonl(p) == 2
    records = list(telemetry.iter_jsonl(p))
    assert [r.label for r in records] == ["kept", "kept"]
    assert telemetry.counter_get("telemetry_torn_lines") >= 1.0


def test_jsonl_quarantines_mid_file_corruption(tmp_path):
    # one bad line (e.g. a healed torn fragment) costs one record,
    # never the file — same policy as the sweep checkpoint loader
    p = tmp_path / "telemetry.jsonl"
    line = telemetry.snapshot(label="ok").to_json_line()
    p.write_text(line[:30] + "\n" + line + "\n")
    assert telemetry.validate_jsonl(p) == 1
    assert [r.label for r in telemetry.iter_jsonl(p)] == ["ok"]
    assert telemetry.counter_get("telemetry_torn_lines") >= 1.0


def test_append_jsonl_heals_torn_tail(tmp_path):
    # a record appended AFTER a kill must not concatenate onto the
    # torn fragment: append starts a fresh line, and readers then see
    # every intact record
    p = tmp_path / "telemetry.jsonl"
    rec = telemetry.snapshot(label="ok")
    rec.append_jsonl(p)
    with open(p, "a") as f:
        f.write(rec.to_json_line()[:25])  # SIGKILL mid-append
    rec.append_jsonl(p)
    assert telemetry.validate_jsonl(p) == 2
    assert [r.label for r in telemetry.iter_jsonl(p)] == ["ok", "ok"]


def test_degraded_to_meta_lands_in_snapshot_and_summary():
    telemetry.set_meta("degraded_to", "single-device")
    telemetry.counter_inc("degradations_total")
    telemetry.counter_inc("retries_total", 2)
    snap = telemetry.snapshot()
    assert snap.meta["degraded_to"] == "single-device"
    blk = telemetry.summary_block()
    assert blk["degraded_to"] == "single-device"
    assert blk["degradations_total"] == 1
    assert blk["retries_total"] == 2
    # clean runs carry NO degraded_to key (bench_regress keys on it)
    telemetry.reset()
    assert "degraded_to" not in telemetry.summary_block()


def test_total_counters_render_as_first_class_series():
    telemetry.counter_inc("retries_total", 3)
    telemetry.counter_inc("engine_traces", 2)
    text = telemetry.prometheus_text()
    assert "isotope_engine_retries_total 3" in text
    assert 'events_total{event="retries_total"}' not in text
    assert 'isotope_engine_events_total{event="engine_traces"} 2' in text


def test_validate_jsonl_rejects_bad_schema(tmp_path):
    p = tmp_path / "bad.jsonl"
    p.write_text('{"schema": "nope", "phases": {}}\n')
    with pytest.raises(ValueError, match="schema"):
        telemetry.validate_jsonl(p)
    p.write_text("")
    with pytest.raises(ValueError, match="no telemetry records"):
        telemetry.validate_jsonl(p)
    rec = telemetry.snapshot()
    doc = rec.to_dict()
    doc["counters"] = {"k": "not-a-number"}
    p.write_text(json.dumps(doc) + "\n")
    with pytest.raises(ValueError, match="not numeric"):
        telemetry.validate_jsonl(p)


# -- summary block ---------------------------------------------------------

def test_summary_block_derivations():
    telemetry.counter_inc("executable_cache_hits", 3)
    telemetry.counter_inc("executable_cache_misses", 1)
    telemetry.counter_inc("bucket_padded_elems", 200)
    telemetry.counter_inc("bucket_real_elems", 150)
    telemetry.phase_add("compile.trace", 1.0)
    telemetry.phase_add("compile.backend", 2.0)
    blk = telemetry.summary_block()
    assert blk["cache_hit_ratio"] == pytest.approx(0.75)
    assert blk["padding_waste_fraction"] == pytest.approx(0.25)
    assert blk["compile_s"] == pytest.approx(3.0)
    assert blk["peak_device_bytes"] is None  # CPU: no memory_stats


# -- runner integration ----------------------------------------------------

def test_runner_emits_telemetry_artifacts(tmp_path):
    import pathlib

    from isotope_tpu.runner.config import DEFAULT_ENVIRONMENTS, ExperimentConfig
    from isotope_tpu.runner.run import run_experiment

    topo = (
        pathlib.Path(__file__).parent.parent
        / "examples/topologies/canonical.yaml"
    )
    telemetry.enable()
    config = ExperimentConfig(
        topology_paths=(str(topo),),
        environments=(DEFAULT_ENVIRONMENTS["NONE"],),
        qps=(200.0,),
        connections=(4,),
        duration_s=1.0,
        load_kind="open",
        num_requests=200,
        seed=3,
    )
    (result,) = run_experiment(config, out_dir=str(tmp_path / "out"))
    assert result.telemetry is not None
    assert result.telemetry["schema"] == telemetry.SCHEMA
    assert result.telemetry["phases"].get("engine.build", 0) > 0
    assert "isotope_engine_events_total" in result.prometheus_text
    jsonl = tmp_path / "out" / "telemetry.jsonl"
    assert telemetry.validate_jsonl(jsonl) == 1
    # the workload series are still there alongside the engine series
    assert "service_incoming_requests_total" in result.prometheus_text


def test_runner_skips_telemetry_when_off(tmp_path):
    import pathlib

    from isotope_tpu.runner.config import DEFAULT_ENVIRONMENTS, ExperimentConfig
    from isotope_tpu.runner.run import run_experiment

    topo = (
        pathlib.Path(__file__).parent.parent
        / "examples/topologies/chain-2-services.yaml"
    )
    config = ExperimentConfig(
        topology_paths=(str(topo),),
        environments=(DEFAULT_ENVIRONMENTS["NONE"],),
        qps=(200.0,),
        connections=(4,),
        duration_s=1.0,
        load_kind="open",
        num_requests=100,
        seed=3,
    )
    (result,) = run_experiment(config, out_dir=str(tmp_path / "out"))
    assert result.telemetry is None
    assert "isotope_engine_" not in result.prometheus_text
    assert not (tmp_path / "out" / "telemetry.jsonl").exists()


# -- jax monitoring hooks --------------------------------------------------

def test_jax_hooks_split_compile_phases():
    telemetry.install_jax_hooks()
    t0 = telemetry.phase_seconds("compile.trace")
    b0 = telemetry.phase_seconds("compile.backend")

    @jax.jit
    def f(x):
        return jnp.sin(x) * np.float32(2.0)

    f(jnp.arange(8, dtype=jnp.float32)).block_until_ready()
    assert telemetry.phase_seconds("compile.trace") > t0
    assert telemetry.phase_seconds("compile.backend") > b0
