"""On-device config search (sim/search.py): successive-halving
brackets as a few jitted dispatches.

The pins the feature's contract rests on:

- rung 0 of a bracket is BIT-IDENTICAL to the plain ``run_ensemble``
  fleet at the screening horizon (same fold_in layout, same stacked
  tables);
- a survivor's carry-continued trajectory equals the unbroken solo
  member at the combined horizon on every exact field (counts, hist,
  min/max, end_max); the float-summed ``latency_sum``/``latency_m2``
  may differ by reduction order only;
- the zero-carry export path leaves the plain fleet byte-identical
  (search off = nothing changed);
- ranking is deterministic under ties: the fold_in-derived tie-break
  draws order all-tied candidates the same way on every run key;
- the sharded bracket == its emulated twin == the solo bracket,
  winner and full lineage;
- member-chunked rung dispatches == the unchunked bracket;
- the isotope-search/v1 artifact round-trips; the ``[search]`` TOML
  block decodes to the same spec; VET-T026/VET-M005 lint the
  degenerate cases the run entry raises on.
"""
import dataclasses
import json

import jax
import jax.tree_util as jtu
import numpy as np
import pytest

from isotope_tpu.compiler import compile_graph
from isotope_tpu.models.graph import ServiceGraph
from isotope_tpu.sim import LoadModel
from isotope_tpu.sim.engine import Simulator
from isotope_tpu.sim.ensemble import EnsembleSpec
from isotope_tpu.sim.search import (
    DOC_SCHEMA,
    SearchSpec,
    check_doc,
    load_doc,
    plan_bracket,
    tiebreak_draws,
)

YAML = """
defaults:
  responseSize: 1 KiB
services:
- name: entry
  isEntrypoint: true
  errorRate: 1%
  script:
  - - call: x
    - call: y
  - call: z
- name: x
  numReplicas: 2
- name: y
  script:
  - call: z
- name: z
"""

# the tie graph: no errorRate anywhere, so err_share severity is 0.0
# for EVERY candidate and ranking falls through to the tie-break draws
YAML_NOERR = """
defaults:
  responseSize: 1 KiB
services:
- name: entry
  isEntrypoint: true
  script:
  - call: z
- name: z
"""

OPEN = LoadModel(kind="open", qps=2000.0)
KEY = jax.random.PRNGKey(7)
N, BLOCK = 512, 128  # 4 blocks: rungs screen at 1 then continue to 4


@pytest.fixture(scope="module")
def compiled():
    return compile_graph(ServiceGraph.from_yaml(YAML))


@pytest.fixture(scope="module")
def sim(compiled):
    return Simulator(compiled)


@pytest.fixture(scope="module")
def pop16():
    """The module's canonical candidate population: every perturbation
    axis jittered, so per-candidate offered rates and physics differ."""
    return EnsembleSpec.from_jitter(
        16, qps_jitter=0.2, cpu_jitter=0.1, error_jitter=0.3
    )


@pytest.fixture(scope="module")
def spec16(pop16):
    return SearchSpec(candidates=pop16, eta=4, rungs=2)


@pytest.fixture(scope="module")
def srch16(sim, spec16):
    """The canonical bracket: 16 -> 4 -> winner over 1 then 4 blocks."""
    return sim.run_search(OPEN, N, KEY, spec16, block_size=BLOCK)


def _leaves_equal(a, b):
    la, lb = jtu.tree_leaves(a), jtu.tree_leaves(b)
    assert len(la) == len(lb)
    return all(
        np.array_equal(np.asarray(x), np.asarray(y), equal_nan=True)
        for x, y in zip(la, lb)
    )

# fields a segmented (carry-continued) run reproduces EXACTLY; the
# float-summed leaves (latency_sum/latency_m2) may differ by reduction
# order, like summary_accumulate
EXACT_FIELDS = ("count", "error_count", "hop_events", "latency_min",
                "latency_max", "latency_hist", "end_max")


# -- plan law ----------------------------------------------------------


def test_plan_bracket_widths_horizons(spec16):
    plan = plan_bracket(spec16, N, BLOCK)
    assert [rp.width for rp in plan] == [16, 4]
    assert [rp.bucket for rp in plan] == [16, 4]
    assert [rp.start_block for rp in plan] == [0, 1]
    assert [rp.num_blocks for rp in plan] == [1, 3]
    assert [rp.cum_requests for rp in plan] == [BLOCK, 4 * BLOCK]


def test_plan_bracket_rejects_flat_horizon(spec16):
    # 1 total block cannot grow between 2 rungs
    with pytest.raises(ValueError, match="VET-T026"):
        plan_bracket(spec16, BLOCK, BLOCK)


def test_spec_validation():
    pop = EnsembleSpec.of(8)
    with pytest.raises(ValueError, match="eta"):
        SearchSpec(candidates=pop, eta=1)
    with pytest.raises(ValueError, match="rungs"):
        SearchSpec(candidates=pop, rungs=0)
    with pytest.raises(ValueError, match="growth"):
        SearchSpec(candidates=pop, growth=1)
    with pytest.raises(ValueError, match="rank"):
        SearchSpec(candidates=pop, rank="latency_hist")
    with pytest.raises(ValueError, match="slo_s"):
        SearchSpec(candidates=pop, rank="p99")
    # population too small for the rung count: widths stop shrinking
    with pytest.raises(ValueError, match="VET-T026"):
        SearchSpec(candidates=EnsembleSpec.of(4), eta=4,
                   rungs=3).check()


# -- rung 0 == the plain fleet at the screening horizon ----------------


def test_rung0_bit_equals_run_ensemble(sim, pop16, srch16):
    ens = sim.run_ensemble(OPEN, BLOCK, KEY, pop16, block_size=BLOCK)
    r0 = srch16.rungs[0]
    assert list(r0.candidates) == list(range(16))
    assert _leaves_equal(ens.summaries, r0.summaries)


def test_search_off_byte_identity(sim, pop16):
    """The carry export with zero carry and zero offset IS the plain
    fleet — arming the machinery without using it changes nothing."""
    plain = sim.run_ensemble(OPEN, BLOCK, KEY, pop16, block_size=BLOCK)
    carried, carry_out = sim.run_ensemble(
        OPEN, BLOCK, KEY, pop16, block_size=BLOCK, return_carry=True,
    )
    assert _leaves_equal(plain.summaries, carried.summaries)
    t0, conn_t0, req_off = carry_out
    assert np.asarray(t0).shape == (16,)
    assert np.asarray(req_off).shape == (16,)


# -- survivor continuation == the unbroken solo member -----------------


def test_winner_continuation_equals_unbroken_member(sim, pop16,
                                                    srch16):
    full = sim.run_ensemble(OPEN, N, KEY, pop16, block_size=BLOCK)
    combined = srch16.winner_summary()
    unbroken = full.member(srch16.winner)
    for f in EXACT_FIELDS:
        assert np.array_equal(
            np.asarray(getattr(combined, f)),
            np.asarray(getattr(unbroken, f)),
        ), f
    np.testing.assert_allclose(
        np.asarray(combined.latency_sum),
        np.asarray(unbroken.latency_sum), rtol=1e-5,
    )


def test_every_survivor_continuation_matches(sim, pop16, srch16):
    """Not just the winner: each rung-1 row is candidate c's blocks
    [1, 4) continuation — accumulated with its rung-0 segment it
    matches c's unbroken full-horizon member."""
    full = sim.run_ensemble(OPEN, N, KEY, pop16, block_size=BLOCK)
    r0, r1 = srch16.rungs
    for row, c in enumerate(r1.candidates):
        seg0 = jtu.tree_map(
            lambda x: np.asarray(x)[int(c)], r0.summaries
        )
        seg1 = jtu.tree_map(
            lambda x: np.asarray(x)[row], r1.summaries
        )
        unbroken = full.member(int(c))
        for f in ("count", "error_count", "hop_events"):
            assert (
                np.asarray(getattr(seg0, f))
                + np.asarray(getattr(seg1, f))
                == np.asarray(getattr(unbroken, f))
            ), (c, f)
        assert np.array_equal(
            np.asarray(seg0.latency_hist)
            + np.asarray(seg1.latency_hist),
            np.asarray(unbroken.latency_hist),
        ), c
        assert np.asarray(seg1.end_max) == np.asarray(
            unbroken.end_max
        ), c


# -- deterministic ranking under ties ----------------------------------


def test_rank_ties_resolve_by_fold_in_draws():
    sim_t = Simulator(compile_graph(ServiceGraph.from_yaml(YAML_NOERR)))
    spec = SearchSpec(
        candidates=EnsembleSpec.of(8), eta=2, rungs=2, seed=3,
    )
    a = sim_t.run_search(OPEN, 256, KEY, spec, block_size=128)
    assert np.all(a.rungs[0].severity == 0.0)  # everything tied
    # the tie order is the spec's fold_in draws, not timing or memory
    tb = np.asarray(tiebreak_draws(spec))
    expected = np.argsort(tb, kind="stable")
    assert list(a.rungs[0].survivors) == list(expected[:4])
    assert a.winner == int(expected[0])
    # ...and independent of the run key: a different key re-draws the
    # simulation, but all-tied severities rank identically
    b = sim_t.run_search(
        OPEN, 256, jax.random.fold_in(KEY, 99), spec, block_size=128
    )
    assert b.winner == a.winner
    assert list(b.rungs[1].candidates) == list(a.rungs[1].candidates)


# -- chunked == unchunked ----------------------------------------------


def test_chunked_bracket_matches_unchunked(sim, spec16, srch16):
    chunked = sim.run_search(
        OPEN, N, KEY, spec16, block_size=BLOCK, chunk=4
    )
    assert chunked.rungs[0].chunk == 4
    assert chunked.winner == srch16.winner
    for ra, rb in zip(chunked.rungs, srch16.rungs):
        assert list(ra.candidates) == list(rb.candidates)
        assert list(ra.survivors) == list(rb.survivors)
        assert _leaves_equal(ra.summaries, rb.summaries)


def test_search_auto_chunk_unknown_capacity_is_whole_rung(sim):
    from isotope_tpu.analysis import costmodel
    from isotope_tpu.sim.search import search_auto_chunk

    if costmodel.device_capacity_bytes() is None:
        assert search_auto_chunk(sim, 16, BLOCK, 0) == 16


# -- sharded == emulated == solo ---------------------------------------


def test_sharded_bracket_bit_equals_emulated_twin(compiled, spec16,
                                                  srch16):
    from isotope_tpu.parallel import (
        EmulatedMesh,
        MeshSpec,
        ShardedSimulator,
        build_mesh,
    )

    sh = ShardedSimulator(compiled, build_mesh(MeshSpec(data=4, svc=2)))
    dev = sh.run_search(OPEN, N, KEY, spec16, block_size=BLOCK)
    esh = ShardedSimulator(
        compiled, EmulatedMesh(MeshSpec(data=4, svc=2))
    )
    emu = esh.run_search_emulated(OPEN, N, KEY, spec16,
                                  block_size=BLOCK)
    for twin in (emu, dev):
        assert twin.winner == srch16.winner
        for ra, rb in zip(twin.rungs, srch16.rungs):
            assert list(ra.candidates) == list(rb.candidates)
            assert list(ra.survivors) == list(rb.survivors)
            assert np.array_equal(ra.severity, rb.severity)
            assert _leaves_equal(ra.summaries, rb.summaries)
    with pytest.raises(ValueError, match="emulated"):
        esh.run_search(OPEN, N, KEY, spec16, block_size=BLOCK)


# -- trace discipline --------------------------------------------------


def test_bracket_traces_bounded_by_rungs(sim, spec16, srch16):
    from isotope_tpu import telemetry

    assert srch16.traces <= spec16.rungs
    # a repeat bracket re-dispatches the SAME executables: 0 traces
    t0 = telemetry.counter_get("engine_traces")
    sim.run_search(
        OPEN, N, jax.random.fold_in(KEY, 5), spec16, block_size=BLOCK
    )
    assert telemetry.counter_get("engine_traces") == t0


# -- artifact ----------------------------------------------------------


def test_artifact_round_trip(tmp_path, spec16, srch16):
    doc = srch16.to_doc("svc.search")
    doc = json.loads(json.dumps(doc))  # through the wire
    assert check_doc(doc) is doc
    assert doc["schema"] == DOC_SCHEMA
    assert doc["label"] == "svc.search"
    assert doc["candidates"] == 16
    assert doc["winner"]["candidate"] == srch16.winner
    assert [r["width"] for r in doc["lineage"]] == [16, 4]
    spec_rt = SearchSpec.from_dict(doc["spec"])
    assert spec_rt.eta == spec16.eta
    assert spec_rt.rungs == spec16.rungs
    assert spec_rt.members == spec16.members
    np.testing.assert_allclose(
        spec_rt.candidates.qps_scale, spec16.candidates.qps_scale
    )
    p = tmp_path / "x.search.json"
    p.write_text(json.dumps(doc))
    assert load_doc(str(p))["winner"]["candidate"] == srch16.winner
    with pytest.raises(ValueError, match="isotope-search"):
        check_doc({"schema": "isotope-ensemble/v1"})


def test_winner_config_is_the_warm_start(pop16, srch16):
    w = srch16.winner_config()
    k = srch16.winner
    assert w["seed"] == pop16.seeds[k]
    assert w["qps_scale"] == pytest.approx(float(pop16.qps_scale[k]))
    assert w["offered_qps"] == pytest.approx(
        float(srch16.offered_qps[k])
    )
    assert w["rank"] == "err_share"


# -- [search] TOML block -----------------------------------------------


def test_toml_search_block_decodes(tmp_path):
    topo = tmp_path / "t.yaml"
    topo.write_text(YAML)
    cfg = tmp_path / "exp.toml"
    cfg.write_text(f"""
topology_paths = ["{topo}"]
environments = ["NONE"]

[client]
qps = [500]
num_concurrent_connections = [8]
duration = "60s"
load_kind = "open"

[sim]
num_requests = 512
seed = 7

[search]
candidates = 16
eta = 4
rungs = 2
rank = "p99"
slo = "250ms"
jitter = "qps=0.2,cpu=0.1,error=0.3"
seed = 3
""")
    from isotope_tpu.runner import load_toml

    spec = load_toml(cfg).search_spec()
    assert spec is not None
    assert (spec.members, spec.eta, spec.rungs) == (16, 4, 2)
    assert spec.rank == "p99"
    assert spec.slo_s == pytest.approx(0.25)
    assert spec.seed == 3
    assert spec.candidates.qps_scale is not None


def test_toml_search_block_rejects_typos(tmp_path):
    topo = tmp_path / "t.yaml"
    topo.write_text(YAML)
    cfg = tmp_path / "exp.toml"
    cfg.write_text(f"""
topology_paths = ["{topo}"]
environments = ["NONE"]

[client]
qps = [500]
num_concurrent_connections = [8]
duration = "60s"
load_kind = "open"

[search]
candidats = 16
""")
    from isotope_tpu.runner import load_toml

    with pytest.raises(ValueError, match="candidats"):
        load_toml(cfg)


# -- vet rules ---------------------------------------------------------


def test_lint_search_rules():
    from isotope_tpu.analysis.topo_lint import lint_search

    assert lint_search(None) == []
    ok = SearchSpec(candidates=EnsembleSpec.of(16), eta=4, rungs=2)
    assert lint_search(ok, num_requests=N, block=BLOCK) == []
    # undecodable raw [search] table
    bad = lint_search({"eta": "wide"})
    assert bad and bad[0].rule == "VET-T026"
    assert bad[0].severity == "error"
    # population too small: widths stop shrinking
    small = lint_search(
        {"candidates": {"seeds": [0, 1, 2, 3]}, "eta": 4, "rungs": 3}
    )
    assert any(
        f.rule == "VET-T026" and f.severity == "error" for f in small
    )
    # flat horizon schedule (1 total block over 2 rungs)
    flat = lint_search(ok, num_requests=BLOCK, block=BLOCK)
    assert any(
        f.rule == "VET-T026" and f.severity == "error" for f in flat
    )
    # warn-grade: non-power-of-eta population, recorderless err_peak
    ragged = lint_search(
        SearchSpec(candidates=EnsembleSpec.of(10), eta=4, rungs=2)
    )
    assert any(f.severity == "warn" for f in ragged)
    peak = lint_search(
        SearchSpec(candidates=EnsembleSpec.of(16), eta=4, rungs=2,
                   rank="err_peak")
    )
    assert any("err_share" in f.message for f in peak)


def test_vet_m005_widest_rung_capacity(sim, monkeypatch):
    from isotope_tpu.analysis import costmodel

    est = costmodel.estimate_run(sim, BLOCK)
    # no capacity signal (CPU): the vet gate invents no OOMs
    if est.capacity_bytes is None:
        assert costmodel.search_findings(est, 64, 0) == []
    # force a tiny budget: the widest rung must report its auto-chunk
    tiny = dataclasses.replace(
        est, capacity_bytes=2.0 * est.peak_bytes_at_block
    )
    out = costmodel.search_findings(tiny, 64, 8)
    assert out and out[0].rule == "VET-M005"
    assert out[0].severity == "warn"
    assert "member chunks" in out[0].message
    # a rung that fits reports nothing
    assert costmodel.search_findings(
        dataclasses.replace(
            est, capacity_bytes=1e6 * est.peak_bytes_at_block
        ),
        64, 8,
    ) == []


# -- protected brackets (PR 18) ----------------------------------------------
#
# Successive halving over a PROTECTED population: every candidate is a
# full run_policies member whose breakers / budgets / HPA ride the
# carry between rungs via the run_policies_ensemble carry-I/O
# contract.  The pins: rung 0 bit-equal to the protected fleet at the
# screening horizon; the winner's carry-continued trajectory equal to
# the unbroken fleet's member row on every exact field; the "trips"
# severity channel ranks by breaker trips + budget ejections.

STORM_YAML = """
services:
- name: entry
  isEntrypoint: true
  numReplicas: 4
  script:
  - call: {service: worker, timeout: 850us, retries: 2}
- name: worker
  numReplicas: 4
  errorRate: 0.5%
policies:
  defaults:
    retry_budget: {budget_percent: 25%}
  worker:
    breaker: {max_pending: 6, max_connections: 64,
              consecutive_errors: 5, base_ejection: 2s}
    autoscaler: {min_replicas: 2, max_replicas: 8,
                 target_utilization: 60%, sync_period: 1s,
                 stabilization_window: 3s}
"""

P_OPEN = LoadModel(kind="open", qps=4_000.0)
P_N, P_BLOCK, P_WIN = 2_048, 1_024, 0.25


@pytest.fixture(scope="module")
def psim():
    from isotope_tpu.compiler import compile_policies
    from isotope_tpu.sim.config import SimParams

    g = ServiceGraph.from_yaml(STORM_YAML)
    c = compile_graph(g)
    return Simulator(c, SimParams(timeline=True),
                     policies=compile_policies(g, c))


@pytest.fixture(scope="module")
def ppop():
    return EnsembleSpec.from_jitter(
        8, qps_jitter=0.2, cpu_jitter=0.1, error_jitter=0.3
    )


@pytest.fixture(scope="module")
def psrch(psim, ppop):
    return psim.run_search_protected(
        P_OPEN, P_N, KEY, SearchSpec(candidates=ppop, eta=4, rungs=2),
        block_size=P_BLOCK, window_s=P_WIN,
    )


def test_protected_bracket_rung0_bit_equal_protected_fleet(
    psim, ppop, psrch
):
    r0 = psrch.rungs[0]
    ens = psim.run_policies_ensemble(
        P_OPEN, r0.cum_requests, KEY, ppop,
        block_size=P_BLOCK, window_s=P_WIN,
    )
    for a, b in zip(jtu.tree_leaves(r0.summaries),
                    jtu.tree_leaves(ens.summaries)):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_protected_bracket_winner_resume_bit_equal_fleet(
    psim, ppop, psrch
):
    """The carry contract's acceptance pin: the winner's rung-0 +
    rung-1 segments (control state carried between rungs) accumulate
    to the UNBROKEN full-horizon fleet's member row on every exact
    field — the survivor kept its breakers and budgets."""
    full = psim.run_policies_ensemble(
        P_OPEN, P_N, KEY, ppop, block_size=P_BLOCK, window_s=P_WIN,
    )
    k = psrch.winner
    win = psrch.winner_summary()
    for name in ("count", "error_count", "latency_hist"):
        assert np.array_equal(
            np.asarray(getattr(win, name)),
            np.asarray(getattr(full.summaries, name))[k],
        ), name


def test_protected_bracket_trips_rank_and_doc(psim, ppop):
    srch = psim.run_search_protected(
        P_OPEN, P_N, KEY,
        SearchSpec(candidates=ppop, eta=4, rungs=2, rank="trips"),
        block_size=P_BLOCK, window_s=P_WIN,
    )
    assert srch.rungs[0].severity.shape == (8,)
    assert np.all(srch.rungs[0].severity >= 0.0)
    doc = srch.to_doc()
    assert doc["rank_effective"] == "trips"
    check_doc(doc)


def test_protected_bracket_rejections(sim, psim, ppop):
    spec = SearchSpec(candidates=ppop, eta=4, rungs=2)
    # no policy tables compiled
    with pytest.raises(ValueError, match="polic"):
        sim.run_search_protected(OPEN, N, KEY, spec,
                                 block_size=BLOCK)
    # saturated -qps max load
    with pytest.raises(ValueError, match="saturated"):
        psim.run_search_protected(
            LoadModel(kind="closed", qps=None, connections=8),
            P_N, KEY, spec, block_size=P_BLOCK,
        )
